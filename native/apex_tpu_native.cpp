// apex_tpu native runtime — host-side C++ hot paths.
//
// Reference: csrc/flatten_unflatten.cpp (apex_C.flatten/unflatten, the
// bucket marshalling layer under apex DDP and fp16_utils' flat master
// params).  On TPU the device-side bucketing disappeared into XLA, but
// the HOST-side equivalents remain hot: checkpoint serialization
// (gather a whole param pytree into one contiguous blob) and input-batch
// assembly (gather sample rows into a batch buffer).  Those are
// multithreaded memcpy problems, which is exactly what this library
// provides via a tiny C ABI loaded with ctypes (no pybind11 in the
// image).
//
// Build: see apex_tpu/io/native.py (g++ -O3 -shared -fPIC -pthread).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) over a small thread pool.
template <typename F>
void parallel_for(int64_t n, int threads, F fn) {
  if (n <= 0) return;
  if (threads <= 1 || n == 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    while (true) {
      int64_t i = next.fetch_add(1);
      if (i >= n) break;
      fn(i);
    }
  };
  std::vector<std::thread> pool;
  int nt = threads < n ? threads : static_cast<int>(n);
  pool.reserve(nt);
  for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Gather n buffers (byte sizes in `sizes`) into contiguous `dst`.
// Offsets are the exclusive prefix sum of sizes.  apex_C.flatten.
void apex_tpu_flatten(const void** srcs, const int64_t* sizes, int64_t n,
                      void* dst, int threads) {
  std::vector<int64_t> offsets(n);
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = off;
    off += sizes[i];
  }
  char* out = static_cast<char*>(dst);
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(out + offsets[i], srcs[i], static_cast<size_t>(sizes[i]));
  });
}

// Scatter contiguous `src` back into n buffers.  apex_C.unflatten.
void apex_tpu_unflatten(const void* src, void** dsts, const int64_t* sizes,
                        int64_t n, int threads) {
  std::vector<int64_t> offsets(n);
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = off;
    off += sizes[i];
  }
  const char* in = static_cast<const char*>(src);
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(dsts[i], in + offsets[i], static_cast<size_t>(sizes[i]));
  });
}

// Gather `n` rows of `row_bytes` each from `src` at `indices` into `dst`
// (input-batch assembly: dst[i] = src[indices[i]]).
void apex_tpu_gather_rows(const void* src, const int64_t* indices, int64_t n,
                          int64_t row_bytes, void* dst, int threads) {
  const char* in = static_cast<const char*>(src);
  char* out = static_cast<char*>(dst);
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(out + i * row_bytes, in + indices[i] * row_bytes,
                static_cast<size_t>(row_bytes));
  });
}

int apex_tpu_native_abi_version() { return 1; }

}  // extern "C"
