"""Minimal data-parallel training example.

Reference: ``examples/simple/distributed/distributed_data_parallel.py``
— the smallest apex DDP script.  TPU version: one process, a ``dp``
mesh over local devices, `shard_map` + psum gradient sync.

    python examples/simple/distributed/distributed_data_parallel.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[3]))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import allreduce_gradients


def main():
    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("dp",))
    print(f"{len(devs)} devices, dp mesh")

    rng = np.random.RandomState(0)
    w_true = rng.randn(16, 1).astype(np.float32)
    X = rng.randn(64 * len(devs), 16).astype(np.float32)
    Y = X @ w_true + 0.01 * rng.randn(64 * len(devs), 1).astype(np.float32)

    params = {"w": jnp.zeros((16, 1))}
    opt = FusedSGD(lr=0.02, momentum=0.9)
    state = opt.init(params)

    def local_step(params, state, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = allreduce_gradients(grads, axis_name="dp")  # the DDP sync
        loss = jax.lax.pmean(loss, "dp")
        params, state = opt.update(grads, state, params)
        return params, state, loss

    step = jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )

    # loss printing rides the async fetch seam (the APX108-clean
    # spelling: no blocking device read inside the step loop)
    from apex_tpu.observability.stepstats import AsyncFetcher

    fetcher = AsyncFetcher()
    for i in range(60):
        params, state, loss = step(params, state, jnp.asarray(X), jnp.asarray(Y))
        if i % 15 == 0:
            fetcher.put("loss", i, {"loss": loss})
        for _, s, tree in fetcher.ready():
            print(f"step {s}: loss {float(tree['loss']):.6f}")
    for _, s, tree in fetcher.flush():
        print(f"step {s}: loss {float(tree['loss']):.6f}")
    err = float(jnp.max(jnp.abs(params["w"] - w_true)))
    print(f"max |w - w_true| = {err:.4f}")
    assert err < 0.1


if __name__ == "__main__":
    main()
