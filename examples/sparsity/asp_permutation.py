"""ASP 2:4 sparsity with channel-permutation search — small-model demo.

Mirrors the reference recipe (apex/contrib/sparsity/README.md): train
dense, prune with 2:4 masks, finetune masked.  The permutation search
(permutation_lib.py) picks masks that retain more magnitude, so the
pruned model starts closer to the dense one and finetunes back faster.

Run (CPU is fine):
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python examples/sparsity/asp_permutation.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.sparsity import ASP, compute_sparse_masks
from apex_tpu.optimizers import FusedAdam


def make_data(rng, n=512, d_in=32):
    x = rng.randn(n, d_in).astype(np.float32)
    w_true = rng.randn(d_in, 1).astype(np.float32)
    y = x @ w_true + 0.01 * rng.randn(n, 1).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def loss_fn(params, x, y):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return jnp.mean((h @ params["w2"] + params["b2"] - y) ** 2)


def train(params, x, y, steps, masks=None):
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, g = jax.value_and_grad(loss_fn)(params, x, y)
        params, state = opt.update(g, state, params)
        if masks is not None:
            params = ASP.apply_masks(params, masks)
        return params, state, loss

    for _ in range(steps):
        params, state, loss = step(params, state)
    return params, float(loss)


def main():
    rng = np.random.RandomState(0)
    x, y = make_data(rng)
    params = {
        "w1": jnp.asarray(rng.randn(32, 64).astype(np.float32) * 0.3),
        "b1": jnp.zeros((64,)),
        "w2": jnp.asarray(rng.randn(64, 1).astype(np.float32) * 0.3),
        "b2": jnp.zeros((1,)),
    }

    params, dense_loss = train(params, x, y, 300)
    print(f"dense loss             {dense_loss:.5f}")

    for label, kw in (("naive 2:4", {}), ("permutation-searched", {"permutation_search": True})):
        masks = compute_sparse_masks(params, **kw)
        pruned, masks = ASP.prune_trained_model(params, masks)
        pruned_loss = float(loss_fn(pruned, x, y))
        finetuned, ft_loss = train(pruned, x, y, 100, masks=masks)
        print(f"{label:22s} pruned {pruned_loss:.5f}  finetuned {ft_loss:.5f}")


if __name__ == "__main__":
    main()
