"""Serve GPT with continuous batching — the one-command decode driver.

A load generator over :class:`apex_tpu.inference
.ContinuousBatchingScheduler`: N concurrent request streams with
Poisson arrivals, each a random prompt + generation budget, served by
the paged-KV decode engine.  Reports aggregate decode throughput
(tokens/sec) and per-token latency percentiles (p50/p99), plus
time-to-first-token — the serving numbers the north star is measured
by.

    python examples/gpt/serve_gpt.py --streams 8 --requests 32
    python examples/gpt/serve_gpt.py --smoke     # tiny CPU acceptance
    # serving v2: speculative decode + shared system prompt + chunked
    # prefill + a preemptible best-effort lane, one command
    python examples/gpt/serve_gpt.py --draft-len 4 --prefix-sharing \\
        --system-prompt-len 128 --prefill-chunk 64 --best-effort-frac 0.5

``--smoke`` runs a tiny greedy config end-to-end on CPU and ASSERTS
the engine's contracts: continuous batching admitted/evicted >= 3
generations through recycled pages, every generated token equals the
training forward's greedy continuation (decode↔training parity at the
decision level; the fp32 logits band lives in
tests/test_inference.py), and the decode step compiled exactly once
across all cache lengths and occupancies.

Weights are randomly initialized — this is a load/latency driver, not
a quality demo.  Kernel impls thread through flags (never env vars);
a kernel that dies at build time degrades once through
``resilience.fallback`` and the server keeps serving.
"""

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp

from apex_tpu.inference import (
    ContinuousBatchingScheduler, DecodeConfig, KVCacheConfig, Request,
)
from apex_tpu.models.gpt import GPTConfig, gpt_forward, init_params


def build_args():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--smoke", action="store_true",
                   help="tiny deterministic CPU run asserting the "
                        "engine contracts (admit/evict, greedy parity, "
                        "compile-once)")
    p.add_argument("--streams", type=int, default=8,
                   help="decode slots (max concurrent sequences)")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--arrival-rate", type=float, default=0.0,
                   help="Poisson arrivals per second (0 = all queued "
                        "up front)")
    p.add_argument("--prompt-len", type=int, default=64,
                   help="max prompt length (per-request lengths are "
                        "uniform in [4, prompt-len])")
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--hidden", type=int, default=768)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--kv-groups", type=int, default=None,
                   help="GQA query groups (None = MHA)")
    p.add_argument("--vocab", type=int, default=50304)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--num-pages", type=int, default=None,
                   help="pool pages (default: sized for streams x "
                        "worst-case request + 1 garbage page)")
    p.add_argument("--kv-dtype", default="bfloat16",
                   choices=["bfloat16", "float32", "float16"])
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--attn-impl", default="auto",
                   choices=["auto", "pallas", "interpret", "xla"])
    p.add_argument("--sample-impl", default="auto",
                   choices=["auto", "pallas", "interpret", "xla"])
    p.add_argument("--seed", type=int, default=0)
    # ---- serving v2 (all default OFF: the plain PR 9 engine) ----
    p.add_argument("--draft-len", type=int, default=0,
                   help="speculative decode: n-gram drafts of up to k "
                        "tokens verified per step in ONE batched pass "
                        "(0 disables; the emitted stream is bitwise the "
                        "non-speculative stream)")
    p.add_argument("--ngram-max", type=int, default=3,
                   help="longest prompt-lookup n-gram the drafter sweeps")
    p.add_argument("--ngram-min", type=int, default=1)
    p.add_argument("--prefill-chunk", type=int, default=None,
                   help="chunked prefill: admit prompts as C-token "
                        "chunks interleaved with decode steps (any "
                        "prompt length; None = one padded prefill "
                        "shape, prompts capped at --prompt-len)")
    p.add_argument("--prefix-sharing", action="store_true",
                   help="dedupe identical prompt-prefix pages through "
                        "the refcounted trie, copy-on-write on first "
                        "divergence")
    p.add_argument("--system-prompt-len", type=int, default=0,
                   help="prepend one shared system prompt of this many "
                        "tokens to every request (the prefix-sharing "
                        "workload; 0 = fully random prompts)")
    p.add_argument("--best-effort-frac", type=float, default=0.0,
                   help="fraction of requests submitted on the "
                        "preemptible best_effort lane (the rest are "
                        "interactive); the report splits TTFT by lane")
    p.add_argument("--metrics-dir", default=None,
                   help="observability sink dir: serving metrics (queue "
                        "depth, slot/page occupancy, admission wait, "
                        "TTFT, inter-token latency histograms) land in "
                        "metrics.jsonl plus a final Prometheus snapshot "
                        "metrics.prom (apex_tpu.observability)")
    p.add_argument("--run-id", default="serve",
                   help="correlation id on metrics points and trace spans")
    p.add_argument("--replica-id", default=None,
                   help="this process's fleet replica id (the frontend's "
                        "roster name, e.g. r0).  Suffixes every "
                        "observability artifact — metrics_<id>.jsonl/"
                        ".prom, and <id> folded into --run-id for trace/"
                        "flight-recorder file names — so N replica "
                        "processes can share one --metrics-dir/"
                        "--trace-dir without clobbering each other "
                        "(the per-rank suffix convention, serving-side)")
    p.add_argument("--trace-dir", default=None,
                   help="host-side request tracing + crash forensics: "
                        "per-request spans (admission wait -> prefill "
                        "chunks -> decode/verify steps, spec accept "
                        "counts, split by lane; every span carries the "
                        "request's trace_id — the same id the TTFT/"
                        "inter-token histogram exemplars carry, so a "
                        "p99 outlier joins to its spans) exported as a "
                        "Perfetto-loadable trace_<run-id>_<pid>.json; "
                        "a flight recorder ring dumps here on a wedged "
                        "decode step")
    p.add_argument("--watchdog-secs", type=float, default=None,
                   help="serving step watchdog: a decode step exceeding "
                        "this many seconds (dead tunnel, wedged "
                        "collective) logs every queued/in-flight request "
                        "id (the requeue manifest), records "
                        "apex_serve_wedges_total, and exits 75 so a "
                        "supervisor restarts the engine")
    p.add_argument("--watchdog-compile-grace", type=float, default=600.0,
                   help="the FIRST step's watchdog allowance (the "
                        "prefill/decode jit compiles make it slow)")
    p.add_argument("--chaos-wedge-decode-step", type=int, default=None,
                   help="chaos: wedge this decode step's dispatch for "
                        "--chaos-wedge-secs (pair with --watchdog-secs)")
    p.add_argument("--chaos-wedge-secs", type=float, default=120.0)
    from apex_tpu.resilience.supervisor import add_supervisor_args

    add_supervisor_args(p)
    return p


def make_requests(args, rng):
    reqs, arrivals = [], []
    t = 0.0
    sysp = (rng.randint(0, args.vocab,
                        size=args.system_prompt_len).tolist()
            if args.system_prompt_len > 0 else [])
    for rid in range(args.requests):
        lo = min(4, args.prompt_len)
        plen = int(rng.randint(lo, args.prompt_len + 1))
        prompt = sysp + rng.randint(0, args.vocab, size=plen).tolist()
        lane = ("best_effort"
                if rng.uniform() < args.best_effort_frac else "interactive")
        reqs.append(Request(rid=rid, prompt=prompt,
                            max_new_tokens=args.max_new, lane=lane))
        if args.arrival_rate > 0:
            t += float(rng.exponential(1.0 / args.arrival_rate))
        arrivals.append(t)
    return reqs, arrivals


def serve(sched, reqs, arrivals):
    """Submit on (wall-clock) arrival, step until drained."""
    t0 = time.monotonic()
    pending = list(zip(arrivals, reqs))
    while pending or not sched.idle():
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            sched.submit(pending[0][1])
            pending.pop(0)
        if not sched.step() and pending:
            # nothing resident and the next arrival is in the future
            time.sleep(min(0.01, max(0.0, pending[0][0] - now)))
    return sched.completed


def report(completions, wall_secs):
    per_token, ttft = [], []
    lane_ttft = {}
    n_tokens = 0
    for c in completions:
        n_tokens += len(c.tokens)
        t = c.token_times[0] - c.submit_time
        ttft.append(t)
        lane_ttft.setdefault(c.lane, []).append(t)
        per_token.extend(np.diff(c.token_times))
    out = {
        "requests": len(completions),
        "generated_tokens": n_tokens,
        "wall_secs": round(wall_secs, 3),
        "tokens_per_sec": round(n_tokens / max(wall_secs, 1e-9), 2),
        "ttft_p50_ms": round(1e3 * float(np.percentile(ttft, 50)), 2),
        "ttft_p99_ms": round(1e3 * float(np.percentile(ttft, 99)), 2),
    }
    if len(lane_ttft) > 1:  # mixed lanes: the per-lane SLO evidence
        out["lanes"] = {
            lane: {"requests": len(ts),
                   "ttft_p50_ms": round(
                       1e3 * float(np.percentile(ts, 50)), 2),
                   "ttft_p99_ms": round(
                       1e3 * float(np.percentile(ts, 99)), 2)}
            for lane, ts in sorted(lane_ttft.items())}
    if per_token:
        out["per_token_p50_ms"] = round(
            1e3 * float(np.percentile(per_token, 50)), 2)
        out["per_token_p99_ms"] = round(
            1e3 * float(np.percentile(per_token, 99)), 2)
    return out


def check_greedy_parity(params, config, completions, max_check=3):
    """Every generated token must be the training forward's argmax
    continuation — the decision-level decode↔training parity the smoke
    contract promises."""
    for c in completions[:max_check]:
        seq = list(c.prompt)
        for tok in c.tokens:
            logits = gpt_forward(params, jnp.asarray([seq]), config)
            pred = int(jnp.argmax(logits[len(seq) - 1, 0]))
            assert pred == tok, (
                f"rid={c.rid}: decode produced {tok} where the training "
                f"forward's greedy continuation is {pred} at position "
                f"{len(seq)} — decode/training parity broke")
            seq.append(tok)


def main(argv=None):
    args = build_args().parse_args(argv)
    if args.supervise:
        # same self-healing outer loop as the trainer (no checkpoint
        # dir: a serving restart is stateless — the wedge manifest in
        # the logs is what a frontend replays)
        from apex_tpu.resilience.supervisor import run_supervised_cli

        return run_supervised_cli(args, argv=(None if argv is None
                                              else [sys.argv[0], *argv]),
                                  checkpoint_dir=None)
    if args.smoke:
        # tiny, deterministic, greedy: the CPU acceptance contract
        args.layers, args.hidden, args.heads, args.vocab = 2, 64, 4, 128
        args.streams, args.requests, args.arrival_rate = 3, 7, 0.0
        args.prompt_len, args.max_new = 8, 4
        args.page_size, args.kv_dtype = 4, "float32"
        args.temperature, args.top_k = 0.0, 0
        if args.attn_impl == "pallas":
            args.attn_impl = "interpret"
        if args.sample_impl == "pallas":
            args.sample_impl = "interpret"

    total_prompt = args.system_prompt_len + args.prompt_len
    config = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        num_query_groups=args.kv_groups,
        max_seq_len=max(total_prompt + args.max_new + args.draft_len + 1,
                        64),
        position_embedding_type="rope",
        compute_dtype=jnp.float32 if args.smoke else jnp.bfloat16,
        checkpoint_layers=False,
    )
    rng = np.random.RandomState(args.seed)
    params = init_params(config, jax.random.PRNGKey(args.seed))

    # worst-case footprint: full prompt + generation budget + the
    # speculative write window (draft k/v land past the accepted stream)
    pages_per_seq = -(-(total_prompt + args.max_new + args.draft_len)
                      // args.page_size)
    num_pages = args.num_pages
    if num_pages is None:
        # pool sized so ~streams worst-case sequences fit (+ garbage
        # page); smaller pools exercise queueing, larger ones admission
        num_pages = 1 + args.streams * pages_per_seq
    dcfg = DecodeConfig(
        cache=KVCacheConfig(
            num_pages=num_pages, page_size=args.page_size,
            pages_per_seq=pages_per_seq,
            dtype=jnp.dtype(args.kv_dtype)),
        max_batch=args.streams, max_prompt_len=total_prompt,
        temperature=args.temperature, top_k=args.top_k,
        attn_impl=args.attn_impl, sample_impl=args.sample_impl,
        sample_dot_dtype=jnp.float32 if args.smoke else None,
        base_seed=args.seed,
        draft_len=args.draft_len, ngram_max=args.ngram_max,
        ngram_min=args.ngram_min, prefill_chunk=args.prefill_chunk,
        prefix_sharing=args.prefix_sharing,
    )
    from apex_tpu.observability import (
        AnomalyMonitor, get_metrics, set_step_context,
    )
    from apex_tpu.observability import flightrec, tracing
    from apex_tpu.resilience import ChaosMonkey, ChaosPlan, StepWatchdog

    # fleet-replica suffixing: N replica processes share one sink dir;
    # each writes metrics_<replica>.jsonl/.prom and folds the replica
    # id into the run id (trace + flight-recorder file names derive
    # from it) — same convention as pretrain's per-rank `_rank{p}`
    rep_sfx = f"_{args.replica_id}" if args.replica_id else ""
    if args.replica_id:
        args.run_id = f"{args.run_id}_{args.replica_id}"

    set_step_context(run_id=args.run_id, step=0)
    registry = get_metrics()  # the scheduler's gauges/histograms land here
    tracer = None
    if args.trace_dir:
        Path(args.trace_dir).mkdir(parents=True, exist_ok=True)
        tracer = tracing.configure()
    flight_dir = flightrec.default_dir(metrics_dir=args.metrics_dir,
                                       trace_dir=args.trace_dir)
    if flight_dir is not None:
        rec = flightrec.install(
            flightrec.FlightRecorder(flight_dir, run_id=args.run_id))
        if tracer is not None:
            rec.attach(tracer)
    # per-lane SLO burn: the scheduler scores every TTFT/inter-token
    # sample; alert counts ride the report so a lane claim carries its
    # alert evidence
    anomaly = (AnomalyMonitor()
               if (args.metrics_dir or args.trace_dir) else None)

    # wedged-decode-step watchdog: heartbeats ride scheduler.step(); a
    # wedge logs the queued/in-flight request ids and exits 75 for the
    # supervisor (no checkpointer to drain — serving state is the logs)
    watchdog = None
    if args.watchdog_secs is not None:
        watchdog = StepWatchdog(
            args.watchdog_secs,
            first_deadline_sec=args.watchdog_compile_grace)
        watchdog.start()
    monkey = None
    if args.chaos_wedge_decode_step is not None:
        monkey = ChaosMonkey(ChaosPlan.make(
            wedge_step_at=args.chaos_wedge_decode_step,
            wedge_step_seconds=args.chaos_wedge_secs))

    sched = ContinuousBatchingScheduler(params, config, dcfg,
                                        watchdog=watchdog,
                                        anomaly=anomaly)
    reqs, arrivals = make_requests(args, rng)

    t0 = time.monotonic()
    if monkey is not None:
        with monkey.active():
            completions = serve(sched, reqs, arrivals)
    else:
        completions = serve(sched, reqs, arrivals)
    wall = time.monotonic() - t0
    if watchdog is not None:
        watchdog.stop()

    out = report(completions, wall)
    out["stats"] = dict(sched.stats)
    out["decode_compiles"] = sched.decode_cache_size()
    if args.draft_len > 0:
        out["accepted_tokens_per_step"] = round(
            sched.stats["spec_emitted"]
            / max(sched.stats["spec_steps"], 1), 3)
    if args.prefix_sharing:
        full_per = args.system_prompt_len // args.page_size
        out["page_dedupe_ratio"] = round(
            sched.stats["shared_full_pages"]
            / max(len(completions) * full_per, 1), 3)
    if args.metrics_dir:
        mdir = Path(args.metrics_dir)
        mdir.mkdir(parents=True, exist_ok=True)
        registry.snapshot_jsonl(mdir / f"metrics{rep_sfx}.jsonl")
        (mdir / f"metrics{rep_sfx}.prom").write_text(
            registry.prometheus_text())
        out["metrics_dir"] = str(mdir)
    if anomaly is not None:
        anomaly.persist(args.metrics_dir or args.trace_dir)
        # per-lane alert counts: the SLO-lane evidence column
        out["anomaly"] = {"counts": anomaly.counts(),
                          "by_lane": anomaly.counts_by("lane")}
    if tracer is not None:
        out["trace_file"] = tracing.export_run(
            args.trace_dir, args.run_id, tracer)["chrome"]

    if args.smoke:
        assert len(completions) == args.requests, (
            f"served {len(completions)}/{args.requests}")
        assert sched.stats["evicted"] >= 3, (
            "smoke must admit/evict >= 3 generations through the pool")
        assert sched.stats["admitted"] > args.streams, (
            "smoke must recycle pages: more admissions than slots")
        assert out["decode_compiles"] == 1, (
            f"decode step compiled {out['decode_compiles']} times — "
            "the compile-once contract broke")
        check_greedy_parity(params, config, completions)
        out["smoke"] = True
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
