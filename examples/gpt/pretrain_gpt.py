"""GPT pretraining — the flagship end-to-end composition.

The reference has no trainer of its own (SURVEY §1 L7: entry points are
users' scripts); this example is the script a Megatron/apex user would
write, re-based on apex_tpu: 3D parallelism (tp × pp × dp) over a
device mesh, optional fp16 dynamic loss scaling (the amp × parallel
flagship stack, reference ``apex/amp/handle.py:16`` +
``apex/transformer/amp/grad_scaler.py``), optional ZeRO-2 optimizer
state sharding (``DistributedFusedAdam``), Megatron batch sampling, and
async checkpoint/resume through ``apex_tpu.io``.

Runs out of the box on the virtual CPU mesh (synthetic data):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python examples/gpt/pretrain_gpt.py --tp 2 --pp 2 --steps 4
    ... --tp 2 --fp16                  # fp16 + dynamic loss scaling
    ... --tp 2 --zero                  # ZeRO-2 state sharding over dp
    ... --tp 2 --zero --grad-sync-dtype int8   # quantized grad sync
    #   (int8/fp8 wire + error-feedback residuals in the sharded state)
    ... --checkpoint /tmp/gpt_ck --steps 4   # then: --resume /tmp/gpt_ck
    ... --checkpoint /tmp/gpt_ck --auto-resume   # preemption-safe: SIGTERM
    #   saves+flushes and exits; rerunning the same line resumes from the
    #   newest valid checkpoint (torn files skipped) — apex_tpu.resilience
    ... --tp 2 --zero --checkpoint /tmp/gpt_ck --auto-resume   # ELASTIC:
    #   --zero checkpoints are per-dp-rank step_* dirs; the same command
    #   at a DIFFERENT device count (dp=4 -> dp=2) reshards the full
    #   sharded state on resume (resilience.elastic)
    ... --watchdog-secs 60   # wedged-step watchdog: drain + exit 75
    #   (EX_TEMPFAIL) for supervisor restart-with-backoff
    ... --chaos-kill-at-step 3   # pod chaos: die hard (exit 137, no save)
    ... --supervise --zero --checkpoint /tmp/gpt_ck --auto-resume   # SELF-
    #   HEALING: an outer supervisor relaunches this same command on
    #   75/137/crash with full-jitter backoff, quarantines a corrupt
    #   newest checkpoint (resume falls back one step), trips a circuit
    #   breaker (exit 76) after K no-progress failures, and prints the
    #   whole-job goodput report (apex_tpu.resilience.supervisor)
"""

import argparse
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp
import numpy as np


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--micro-batches", type=int, default=2)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--fp16", action="store_true",
                   help="float16 compute + dynamic loss scaling")
    p.add_argument("--rope", action="store_true",
                   help="rotary position embeddings (no learned table)")
    p.add_argument("--num-query-groups", type=int, default=None,
                   help="grouped-query attention: kv-head groups (1 = MQA)")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO-2: shard optimizer state over dp")
    p.add_argument("--grad-sync-dtype", default=None,
                   choices=["int8", "float8_e4m3fn", "float8_e5m2"],
                   help="quantized ZeRO gradient sync (needs --zero): the "
                        "per-bucket reduce-scatter carries int8/fp8 "
                        "payloads with per-block fp32 scales, and the "
                        "quantization error rides the optimizer state as "
                        "an error-feedback residual (checkpointed; resume "
                        "must pass the same flag)")
    p.add_argument("--sequence-parallel", action="store_true")
    p.add_argument("--remat-policy", default="full", choices=["full", "dots"],
                   help="layer remat: 'full' saves only layer inputs, "
                        "'dots' keeps matmul outputs (cheaper backward)")
    p.add_argument("--fused-ce", action="store_true",
                   help="chunked fused LM-head+CE: never materializes "
                        "the fp32 (S,B,V) logits (ops/fused_ce.py)")
    p.add_argument("--checkpoint", default=None, help="save dir (async)")
    p.add_argument("--save-every", type=int, default=4)
    p.add_argument("--keep", type=int, default=3,
                   help="multi-process: retain this many step_* dirs "
                        "(min 3 — younger dirs may still be writing)")
    p.add_argument("--data", default=None,
                   help="memmapped token file (flat binary of ids); "
                        "consumed as non-overlapping seq+1 windows. "
                        "Default: a synthetic random corpus")
    p.add_argument("--data-dtype", default="uint16",
                   choices=["uint16", "int32"],
                   help="token id dtype of --data")
    p.add_argument("--resume", default=None, help="checkpoint dir to resume")
    p.add_argument("--watchdog-secs", type=float, default=None,
                   help="step watchdog: a step exceeding this many "
                        "seconds (wedged collective, hung compile) is "
                        "declared dead — the async checkpoint queue is "
                        "drained and the process exits with the distinct "
                        "code 75 (EX_TEMPFAIL) so a supervisor restarts "
                        "with backoff; the run then resumes elastically")
    p.add_argument("--watchdog-compile-grace", type=float, default=600.0,
                   help="the FIRST step's watchdog allowance (jit "
                        "compile makes it legitimately slow)")
    p.add_argument("--chaos-kill-at-step", type=int, default=None,
                   help="chaos: die HARD (exit 137, no save, no drain) "
                        "at this loop step — the kill-one-host fault; "
                        "rerunning the same command resumes elastically")
    p.add_argument("--chaos-wedge-step", type=int, default=None,
                   help="chaos: wedge this loop step's dispatch for "
                        "--chaos-wedge-secs (pair with --watchdog-secs "
                        "to demonstrate the drain-and-exit path)")
    p.add_argument("--chaos-wedge-secs", type=float, default=120.0)
    p.add_argument("--metrics-dir", default=None,
                   help="observability sink dir (apex_tpu.observability): "
                        "device-side StepStats telemetry rides the jitted "
                        "step and is fetched ASYNCHRONOUSLY (no per-step "
                        "host sync), windows land in metrics.jsonl, a "
                        "final Prometheus snapshot in metrics.prom, and "
                        "goodput accounting (productive vs checkpoint/"
                        "restore/restart/wedge wall time, surviving "
                        "elastic restarts) in goodput_*.json + "
                        "goodput_report.json")
    p.add_argument("--telemetry-every", type=int, default=8,
                   help="StepStats fetch cadence (steps per window): the "
                        "accumulated window is handed to the async "
                        "fetcher and a fresh one swapped in — lower = "
                        "finer time series, higher = less host work")
    p.add_argument("--run-id", default="gpt",
                   help="correlation id stamped on structured logs, "
                        "metrics points, and xprof trace spans (join key "
                        "is (run_id, step))")
    p.add_argument("--trace-dir", default=None,
                   help="host-side distributed tracing + crash forensics "
                        "(apex_tpu.observability.tracing/flightrec): "
                        "spans wrap the loop's host phases (data wait, "
                        "step dispatch, telemetry fetch, checkpoint "
                        "save/restore) — never the compiled step itself "
                        "(tracing on/off is pinned to identical "
                        "lowerings and bitwise loss) — and export as "
                        "trace_<run-id>_<pid>.json (Perfetto/"
                        "chrome://tracing loadable) plus spans JSONL; a "
                        "flight recorder ring of recent spans/events/"
                        "telemetry windows dumps atomically here on "
                        "watchdog wedge, StepGuard abort, and preemption")
    p.add_argument("--trace-capacity", type=int, default=4096,
                   help="finished-span ring size (oldest dropped)")
    p.add_argument("--auto-resume", action="store_true",
                   help="preemption-safe mode (needs --checkpoint): resume "
                        "from the newest VALID checkpoint in the dir if one "
                        "exists (torn files from a killed writer are "
                        "skipped), install a SIGTERM hook that saves and "
                        "flushes before exiting, and degrade kernel compile "
                        "failures to the XLA fallback instead of dying — "
                        "the same command line works for the first launch "
                        "and every restart")
    from apex_tpu.resilience.supervisor import add_supervisor_args

    add_supervisor_args(p)
    return p.parse_args()


def main():
    args = parse_args()

    if args.supervise:
        # the self-healing outer loop: relaunch THIS command (minus the
        # supervisor flags) as a child and run the restart state
        # machine — exit-code table, crash-loop breaker, checkpoint
        # quarantine, goodput summary.  Runs before any jax backend
        # init: the parent must never hold the devices the child needs.
        from apex_tpu.resilience.supervisor import run_supervised_cli

        if not args.auto_resume and args.checkpoint:
            raise SystemExit("--supervise needs --auto-resume with "
                             "--checkpoint: a restarted child that does "
                             "not resume would retrain from step 0")
        raise SystemExit(run_supervised_cli(args))

    from apex_tpu import io, resilience
    from apex_tpu.amp import DynamicLossScaler
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.models.gpt import (
        GPTConfig, init_params, make_pp_train_step, make_train_step,
        param_specs,
    )
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import parallel_state as ps
    from apex_tpu.transformer._data import MegatronPretrainingSampler

    if args.auto_resume and not args.checkpoint:
        raise SystemExit("--auto-resume needs --checkpoint (the dir it "
                         "both resumes from and saves into)")
    if args.telemetry_every < 1:
        raise SystemExit("--telemetry-every must be >= 1 (steps per "
                         "StepStats fetch window)")

    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size_=args.tp,
        pipeline_model_parallel_size_=args.pp,
    )
    dp = mesh.shape["dp"]
    print(f"mesh: dp={dp} pp={args.pp} tp={args.tp} "
          f"({len(jax.devices())} devices)")

    config = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        max_seq_len=args.seq,
        compute_dtype=jnp.float16 if args.fp16 else jnp.bfloat16,
        checkpoint_layers=True,
        remat_policy=args.remat_policy,
        sequence_parallel=args.sequence_parallel,
        position_embedding_type="rope" if args.rope else "learned",
        num_query_groups=args.num_query_groups,
        fused_ce=args.fused_ce,
        # largest divisor of seq <= 128, so the flag always engages
        # (the gpt_loss guard silently falls back on indivisibility)
        fused_ce_chunk=next(c for c in range(min(128, args.seq), 0, -1)
                            if args.seq % c == 0),
    )
    params = init_params(config, jax.random.PRNGKey(0))

    def train_param_specs():
        """PartitionSpec tree for the params as the train step shards
        them — with pp, stacked layers shard over the pp mesh axis.
        The ONE place this rule lives: ZeRO init and checkpoint specs
        both consume it."""
        from jax.sharding import PartitionSpec as P

        specs = dict(param_specs(config))
        if args.pp > 1:
            specs["layers"] = {
                k: P("pp", *s[1:]) for k, s in specs["layers"].items()
            }
        return specs

    if args.grad_sync_dtype and not args.zero:
        raise SystemExit("--grad-sync-dtype needs --zero: the quantized "
                         "wire's error-feedback residual lives in the "
                         "ZeRO optimizer's sharded state")
    # the model layout an elastic checkpoint must match (only dp is
    # elastic: tp/pp reshape is a state-layout change)
    mesh_meta = {"tp": args.tp, "pp": args.pp}

    if args.zero:
        optimizer = DistributedFusedAdam(lr=args.lr, weight_decay=0.01,
                                         axis_name="dp",
                                         grad_sync_dtype=args.grad_sync_dtype)
        # the specs handed to init must include every model axis the
        # params shard over
        zspecs = train_param_specs()
        axis_sizes = {"tp": args.tp}
        if args.pp > 1:
            axis_sizes["pp"] = args.pp
        state = optimizer.init(params, world_size=dp, param_specs=zspecs,
                               axis_sizes=axis_sizes)
    else:
        optimizer = FusedAdam(lr=args.lr, weight_decay=0.01)
        state = optimizer.init(params)

    scaler = DynamicLossScaler(init_scale=2.0 ** 12) if args.fp16 else None
    scaler_state = scaler.init() if scaler else None

    # Observability (apex_tpu.observability): the StepStats window rides
    # the jitted step (device-side accumulation, donated buffers) and is
    # fetched asynchronously — the loop below has ZERO blocking device
    # reads (`float(loss)` per step is the spelling analyzer rule APX108
    # flags); even without --metrics-dir the loss print itself goes
    # through the async fetcher.  With --metrics-dir the windows feed
    # the metrics registry (JSONL time series + final Prometheus
    # snapshot) and a goodput accountant attributes checkpoint/restore/
    # restart/wedge wall time across elastic restarts.
    from apex_tpu import observability as obs
    from apex_tpu.observability import flightrec, stepstats, tracing
    from apex_tpu.observability.tracing import span

    obs.set_step_context(run_id=args.run_id, step=0)
    fetcher = stepstats.AsyncFetcher()
    # telemetry windows drive MORE than the metrics files: the harvest
    # cadence is also the flight recorder's rolling republish (the
    # hard-kill dump) and the step-time/throughput anomaly detectors —
    # so a --trace-dir-only run builds StepStats too
    telemetry = (stepstats.StepTelemetry()
                 if (args.metrics_dir or args.trace_dir) else None)
    registry = obs.get_metrics()
    # Tracing + crash forensics (--trace-dir): a host-side span per loop
    # phase, exported Perfetto-loadable at exit; the flight recorder
    # subscribes to the tracer and to every log_structured event, and
    # dumps on wedge/abort/preemption (the watchdog and StepGuard call
    # flightrec.dump_active themselves — installing the recorder is the
    # only wiring the driver owes).  The anomaly monitor watches step
    # time and window throughput whenever any observability sink is on.
    tracer = None
    if args.trace_dir:
        Path(args.trace_dir).mkdir(parents=True, exist_ok=True)
        tracer = tracing.configure(capacity=args.trace_capacity)
    flight_dir = flightrec.default_dir(metrics_dir=args.metrics_dir,
                                       trace_dir=args.trace_dir)
    recorder = None
    if flight_dir is not None:
        recorder = flightrec.install(
            flightrec.FlightRecorder(flight_dir, run_id=args.run_id))
        if tracer is not None:
            recorder.attach(tracer)
    anomaly = (obs.AnomalyMonitor()
               if (args.metrics_dir or args.trace_dir) else None)
    # multi-process: metrics files are per-rank (rank labels alone can't
    # save a last-writer-wins file clobber on a shared FS), and the
    # goodput accountant runs on process 0 ONLY — every rank shares one
    # wall clock, so folding N concurrent session records as if they
    # were sequential restarts would double-count attributed time and
    # break the fractions-sum-to-1 closure
    proc = jax.process_index()
    rank_sfx = f"_rank{proc}" if jax.process_count() > 1 else ""
    if args.metrics_dir:
        # every rank writes its own metrics files — the dir must exist
        # on every rank, not just the accountant-owning process 0
        Path(args.metrics_dir).mkdir(parents=True, exist_ok=True)
    acct = (obs.GoodputAccountant(args.metrics_dir, run_id=args.run_id)
            if args.metrics_dir and proc == 0 else None)
    metrics_jsonl = (Path(args.metrics_dir) / f"metrics{rank_sfx}.jsonl"
                     if args.metrics_dir else None)

    #: wall time of the previous StepStats harvest — the window-level
    #: step-time series the anomaly detector watches when tracing is
    #: off (the harvest follows the ASYNC fetch's completed copy, the
    #: allowed timing seam; per-dispatch host timing would be the
    #: APX112 lie)
    last_window_wall = [time.time(), 0]
    #: checkpoint-save seconds since the last drain — deducted from the
    #: window samples below (a 30s save is not step time: scoring it
    #: would fire a false step-time alert and double the supervisor's
    #: next backoff on a perfectly healthy run)
    excluded_wall = [0.0]

    def observe_window_span(at_step):
        """One anomaly sample per DRAIN batch, not per window: wall
        time since the previous drain over the steps it covered.  When
        the host runs ahead, two windows can materialize in a single
        ``fetcher.ready()`` batch sharing one arrival time — per-window
        dts would read 2x-actual for the first and ~0 for the second,
        firing false step-time/throughput alerts."""
        now, prev_step = time.time(), last_window_wall[1]
        dt = now - last_window_wall[0] - excluded_wall[0]
        excluded_wall[0] = 0.0
        if anomaly is not None and at_step > prev_step > 0 and dt > 0:
            w_steps = at_step - prev_step
            anomaly.observe("step_time", dt / w_steps)
            anomaly.observe(
                "tokens_per_sec",
                w_steps * args.global_batch * args.seq / max(dt, 1e-9))
        last_window_wall[0], last_window_wall[1] = now, at_step

    def emit_harvested(kind, at_step, tree):
        """Print/record one harvested async fetch (host numpy values —
        the loop never touches device scalars)."""
        if kind == "loss":
            extra = (f" scale={float(tree['scale']):.0f}"
                     if "scale" in tree else "")
            print(f"step {at_step}: loss={float(tree['loss']):.4f}{extra}",
                  flush=True)
        else:  # a StepStats window
            s = stepstats.StepTelemetry.emit(registry, tree)
            if recorder is not None:
                recorder.record_stats(at_step, s)
                recorder.checkpoint()  # republish the rolling recording
            if metrics_jsonl is not None:
                registry.snapshot_jsonl(metrics_jsonl,
                                        window_end_step=at_step)
            if acct is not None:
                acct.heartbeat()
            print(f"telemetry[{at_step}]: loss_mean={s['loss_mean']:.4f} "
                  f"grad_norm={s['grad_norm_last']:.3g} "
                  f"bad={s['bad_steps']}", flush=True)

    def build_step():
        # donate_state: the loop rebinds params/state every step and the
        # async checkpointer host-snapshots at save() time, so donation
        # is safe — and saves ~3x param bytes of transient HBM.  A
        # builder (not a one-shot) so a kernel compile failure can
        # rebuild the step against the tripped fallback registry.
        if args.pp > 1:
            built = make_pp_train_step(config, optimizer, mesh,
                                       num_microbatches=args.micro_batches,
                                       loss_scaler=scaler,
                                       donate_state=True,
                                       telemetry=telemetry)
        else:
            built = make_train_step(config, optimizer, mesh,
                                    loss_scaler=scaler,
                                    donate_state=True, telemetry=telemetry)
        # dispatch-span wrapper: lives entirely OUTSIDE jit (delegates
        # lower/attrs), so the compiled program and loss/params are
        # byte/bitwise identical with tracing on or off — the
        # TestTracingTrainStep lowered pin + test_tracing parity band
        return tracing.TracedStep(built, name="train.step.dispatch")

    step = build_step()
    # one marker per (bucket, hop) of the ZeRO sync plan: the trace's
    # wire-plan track (dispatch-span duration ÷ hop bytes bounds the
    # achieved per-hop bandwidth); no-op when tracing is off or the
    # optimizer has no bucket plan
    tracing.emit_sync_plan(optimizer)

    # Corpus: a memmapped token file (--data, the real-pretraining path:
    # the OS pages in only the rows each batch touches) or a synthetic
    # random corpus.  Either way batches assemble through the native
    # multithreaded gather_rows on a background prefetch thread.
    if args.data:
        raw = np.memmap(args.data, dtype=args.data_dtype, mode="r")
        n = len(raw) // (args.seq + 1)
        if n < args.global_batch:
            raise ValueError(
                f"--data holds {n} samples of seq+1={args.seq + 1} tokens; "
                f"need at least one global batch ({args.global_batch})")
        corpus = raw[: n * (args.seq + 1)].reshape(n, args.seq + 1)
    else:
        corpus = np.random.RandomState(0).randint(
            0, args.vocab, size=(4096, args.seq + 1))
    start_step = 0

    multiproc = jax.process_count() > 1

    def ckpt_tree(params, state, step, scaler_state):
        return {
            "params": params,
            "state": state,
            "step": np.int64(step),
            "scaler": scaler.state_dict(scaler_state) if scaler else None,
        }

    def ckpt_specs():
        """The training-time PartitionSpec tree for everything saved —
        the same specs the train step shards with, NOT inferred from
        array shardings (freshly-initialized params are unsharded, so
        introspection would silently restore everything replicated)."""
        from jax.sharding import PartitionSpec as P

        pspecs = train_param_specs()
        if args.zero:
            sspec = optimizer.state_partition_spec()
        else:
            sspec = type(state)(
                step=P(), exp_avg=pspecs, exp_avg_sq=pspecs,
                master=pspecs if state.master is not None else None,
            )
        scaler_spec = (
            jax.tree.map(lambda _: P(), scaler.state_dict(scaler_state))
            if scaler else None
        )
        return {"params": pspecs, "state": sspec, "step": P(),
                "scaler": scaler_spec}

    ckpt = io.AsyncCheckpointer() if args.checkpoint else None
    # ONE run controller for every mode: it owns the per-step protocol
    # (watchdog heartbeat + chaos delivery — wired further down once
    # those are built) and, for --zero single-process runs, the elastic
    # checkpointing (save + bounded-disk prune, restore-or-fresh with
    # cross-world resharding).  Multiproc keeps the per-process
    # distributed save path below.
    run_ctl = resilience.ElasticRunController(
        args.checkpoint, optimizer, world_size=dp, mesh_axes=mesh_meta,
        checkpointer=ckpt, keep=args.keep)

    # --resume points at a dir and fails loudly if nothing valid is
    # there; --auto-resume resumes from --checkpoint when it holds a
    # valid checkpoint and silently starts fresh otherwise (first
    # launch and post-preemption restart share one command line).
    t_restore = time.time()
    resume_dir = args.resume or (args.checkpoint if args.auto_resume
                                 else None)
    ck = None
    if resume_dir:
        if multiproc:
            # pod-scale restore: every process reads only the pieces its
            # own devices need (lazy shard files, no host materializes
            # the full state).  Per-step directories: an interrupted
            # save can only leave an INCOMPLETE newest dir, never a torn
            # mix of steps.  Process 0 picks the newest complete dir and
            # broadcasts it so the whole pod resumes the same step even
            # if a shared FS shows processes different file listings;
            # load errors (template/shape mismatch) propagate loudly.
            from jax.experimental import multihost_utils

            def newest_complete():
                try:
                    return io.latest_distributed_step(resume_dir)
                except io.AllCheckpointsTornError:
                    # encode over the broadcast so every process raises
                    # together instead of peers hanging in the collective
                    return -2

            chosen = newest_complete() if jax.process_index() == 0 else 0
            chosen = int(multihost_utils.broadcast_one_to_all(
                np.int64(chosen)))
            if chosen == -2 or (chosen < 0 and args.resume):
                # -2: step_* dirs EXIST but none is fully published —
                # prior progress would be silently discarded, so loud
                # even under --auto-resume (the single-process
                # AllCheckpointsTornError invariant, pod-scale)
                raise FileNotFoundError(
                    f"no complete checkpoint under {resume_dir}" +
                    (": step_* dirs exist but none is fully published; "
                     "refusing to silently restart from step 0"
                     if chosen == -2 else ""))
            if chosen >= 0:
                ck = io.load_distributed_checkpoint(
                    Path(resume_dir) / f"step_{chosen:08d}",
                    ckpt_tree(params, state, 0, scaler_state),
                    mesh=mesh, spec_tree=ckpt_specs())
        elif args.zero:
            # ELASTIC resume (apex_tpu.resilience.elastic): --zero runs
            # checkpoint as per-dp-rank step_* dirs whose index.json
            # records the saved world layout.  A dp=4 checkpoint resumes
            # at dp=2 (or dp=8) in this same command line: the sharded
            # optimizer state — m/v, masters/remainders, error-feedback
            # residuals — reshards through the bucket plan's one
            # padded_total formula; params/scaler ride rank 0's shard.
            # AllCheckpointsTornError (dirs exist, none complete) stays
            # loud even under --auto-resume.
            restored = resilience.restore_elastic_checkpoint(
                resume_dir, optimizer=optimizer, world_size=dp,
                mesh_axes=mesh_meta)
            if restored is None and args.resume:
                raise FileNotFoundError(
                    f"no elastic checkpoint under {resume_dir}")
            if restored is not None:
                params = restored.params
                state = restored.opt_state
                start_step = restored.step
                if scaler is not None:
                    if restored.scaler is None:
                        raise ValueError(
                            f"checkpoint in {resume_dir} has no "
                            "loss-scaler state (saved by a run without "
                            "--fp16); resume without --fp16 or point at "
                            "a matching run's dir")
                    scaler_state = scaler.load_state_dict(restored.scaler)
                msg = f"resumed at step {start_step}"
                if restored.resharded:
                    msg += (f" (elastic reshard: dp={restored.saved_world}"
                            f" -> dp={dp})")
                print(msg, flush=True)
        else:
            # torn-file-safe discovery: a file the preempted writer was
            # killed inside (bad header, short blob) is skipped with a
            # warning; only a VALID checkpoint is ever loaded
            try:
                path = io.latest_checkpoint(resume_dir)
            except io.AllCheckpointsTornError:
                # candidates EXISTED but every one failed validation:
                # prior progress would be silently discarded by a fresh
                # start — loud even under --auto-resume
                raise
            except FileNotFoundError:
                if args.resume:
                    raise  # explicit --resume with nothing valid: loud
                if any(Path(resume_dir).glob("step_*/index.json")):
                    # the dir holds ELASTIC step dirs (a --zero run's
                    # layout): silently starting fresh would discard
                    # that progress — name the flag mismatch instead
                    raise ValueError(
                        f"{resume_dir} holds elastic step_* checkpoints "
                        "(saved by a --zero run); resume with --zero or "
                        "point at a matching run's dir")
                path = None  # --auto-resume first launch: fresh start
            if path is not None:
                ck = io.load_checkpoint(path)
                ck = jax.tree.map(jnp.asarray, ck)
    if ck is not None:
        params = ck["params"]
        # the checkpoint restores the saved pytree structure, so a
        # checkpoint from a different optimizer fails loudly in update()
        state = ck["state"]
        start_step = int(ck["step"])
        if scaler is not None:
            if ck.get("scaler") is None:
                # checkpoints from a non---fp16 run carry no scaler
                # state (one dir mixing runs with different precision
                # flags hits this); fail with the mismatch, not a
                # NoneType subscript deep inside load_state_dict
                raise ValueError(
                    f"checkpoint in {resume_dir} has no loss-scaler "
                    "state (saved by a run without --fp16); resume "
                    "without --fp16 or point at a matching run's dir")
            scaler_state = scaler.load_state_dict(ck["scaler"])
        print(f"resumed at step {start_step}")
    if acct is not None and start_step:
        # goodput: restore (incl. any elastic reshard) is attributable
        # downtime, not productive time
        acct.add_segment("restore", time.time() - t_restore)
    if tracer is not None and resume_dir and start_step:
        # retro-emit (both endpoints known): the restore/reshard phase
        # as its own track in the trace
        tracer.emit("train.checkpoint_restore", t_restore,
                    time.time() - t_restore, resumed_step=start_step)

    mb_size = args.global_batch  # sampler yields global batches here

    def epoch_cycling_batches(consumed):
        """Megatron sampling with epoch wrap: the sampler is
        single-epoch by design (reference _batchsampler.py), so restart
        it from zero each time the corpus is exhausted."""
        consumed %= (len(corpus) // mb_size) * mb_size
        while True:
            it = MegatronPretrainingSampler(
                total_samples=len(corpus), consumed_samples=consumed,
                micro_batch_size=mb_size,
                data_parallel_rank=0, data_parallel_size=1,
            )
            yield from it
            consumed = 0

    sampler = epoch_cycling_batches(start_step * args.global_batch)

    # batch assembly off the training thread: the native multithreaded
    # gather_rows pulls the sampled rows (reference's DataLoader-worker
    # role; on a memmap corpus only the touched rows page in), a
    # depth-2 prefetch queue keeps it a step ahead of the device.
    # Token ids validate per batch — exactly the rows about to train —
    # so a bad id anywhere in --data fails loudly instead of wrapping
    # through the embedding lookup (the prefetch worker's exception
    # re-raises on the training thread).
    def assemble(idx):
        batch = io.native.gather_rows(corpus, np.asarray(idx))
        if args.data:
            lo, hi = int(batch.min()), int(batch.max())
            if lo < 0 or hi >= args.vocab:
                raise ValueError(
                    f"--data batch has token id "
                    f"{lo if lo < 0 else hi} outside [0, vocab={args.vocab})")
        return batch.astype(np.int32)

    prefetch = io.PrefetchIterator(sampler, size=2, transform=assemble)

    # SIGTERM (Cloud TPU preemption notice) -> finish the current step,
    # save, flush the async queue, exit 0; the same command resumes.
    pre = resilience.PreemptionHandler().install() if args.auto_resume \
        else None

    # chaos faults armed from the CLI (the one-command reproduction of
    # the pod-scale scenarios: kill-one-host, wedged step)
    chaos_monkey = None
    if args.chaos_kill_at_step is not None or args.chaos_wedge_step is not None:
        chaos_monkey = resilience.ChaosMonkey(resilience.ChaosPlan.make(
            kill_at=({0: args.chaos_kill_at_step}
                     if args.chaos_kill_at_step is not None else None),
            wedge_step_at=args.chaos_wedge_step,
            wedge_step_seconds=args.chaos_wedge_secs,
        ))

    # step watchdog: a wedged step (hung collective, dead tunnel) gets
    # one structured log, a bounded drain of the async queue, and the
    # distinct exit 75 so a supervisor restarts with backoff
    def on_wedge(info):
        """Watchdog pre-exit hook (best-effort, each piece its own
        job): force the step-time anomaly alert (the wedged dispatch
        never returns, so no ordinary observation will ever see it),
        persist the anomaly record + a final metrics snapshot so the
        counter increment survives the os._exit, and stamp the goodput
        session wedged.  The watchdog itself dumps the flight recorder
        right AFTER this hook — so the alert is IN the dump."""
        for piece in (
            (lambda: (anomaly.wedge(info.get("elapsed_s"),
                                    step=info.get("step")),
                      anomaly.persist(args.metrics_dir or args.trace_dir)))
                if anomaly is not None else None,
            (lambda: registry.snapshot_jsonl(metrics_jsonl, wedged=True))
                if metrics_jsonl is not None else None,
            (lambda: tracing.export_run(args.trace_dir, args.run_id,
                                        tracer))
                if tracer is not None else None,
            # goodput: stamp the session wedged BEFORE os._exit so the
            # report can attribute the lost tail per cause
            (lambda: acct.finalize("wedge")) if acct is not None else None,
        ):
            if piece is None:
                continue
            try:
                piece()
            except Exception:  # noqa: BLE001 — one broken sink must not
                pass           # rob the others (the watchdog still exits)

    watchdog = None
    if args.watchdog_secs is not None:
        watchdog = resilience.StepWatchdog(
            args.watchdog_secs, checkpointer=ckpt, preemption=pre,
            first_deadline_sec=args.watchdog_compile_grace,
            on_wedge=on_wedge)
        watchdog.start()
    # the controller's on_step drives both from here on
    run_ctl.watchdog = watchdog
    run_ctl.chaos = chaos_monkey

    def preempt_agreed():
        """Every process must take the same break-or-continue decision:
        one host seeing SIGTERM while another enters the next step would
        deadlock that step's cross-host collectives (and produce a
        partial step_* dir only some processes wrote).  A host-side
        allgather of the local flag per step is cheap next to a train
        step; single-process runs skip it."""
        if not multiproc:
            return pre.preempted
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.int8(pre.preempted))
        return bool(np.max(flags))

    def save_at(tree, step_no):
        t0 = time.time()
        try:
            with span("train.checkpoint_save", save_step=step_no):
                if acct is None:
                    return _save_at(tree, step_no)
                with acct.attribute("checkpoint"):
                    return _save_at(tree, step_no)
        finally:
            excluded_wall[0] += time.time() - t0

    def _save_at(tree, step_no):
        if multiproc:
            # each process snapshots + writes only its addressable
            # shards (non-addressable global arrays never hit host);
            # one directory per step keeps every published
            # checkpoint internally consistent
            ckpt.save_distributed(
                Path(args.checkpoint) / f"step_{step_no:08d}", tree)
            if jax.process_index() == 0:
                # bounded disk: drop dirs older than the newest
                # --keep.  The async queue holds ≤2 pending saves
                # per process, so anything older than the 3 newest
                # is fully published on every process — with the
                # default keep=3 a prune can never race a write.
                import shutil

                old = sorted(Path(args.checkpoint).glob("step_*"))
                for d in old[:-max(args.keep, 3)]:
                    shutil.rmtree(d, ignore_errors=True)
        elif args.zero:
            # elastic per-dp-rank step dir via the run controller:
            # index first (an interrupted save leaves an incomplete dir
            # the resume side skips as torn), shard snapshots on the
            # async queue, bounded disk via the controller's prune;
            # resume at a DIFFERENT dp reshards
            run_ctl.save(step_no, tree["params"], tree["state"],
                         scaler_state=tree["scaler"])
        else:
            # step-named files (atomic publish) so a preempted restart
            # picks the newest VALID one; same bounded-disk pruning
            ckpt.save(Path(args.checkpoint) / f"step_{step_no:08d}.ckpt",
                      tree)
            old = sorted(Path(args.checkpoint).glob("step_*.ckpt"))
            for f in old[:-max(args.keep, 3)]:
                try:
                    f.unlink()
                except OSError:
                    pass

    stats = telemetry.init() if telemetry is not None else None
    window_steps = 0  # host-side: steps accumulated since the last fetch

    def run_step(tokens, targets):
        nonlocal step
        step_args = [params, state]
        if scaler is not None:
            step_args.append(scaler_state)
        if stats is not None:
            step_args.append(stats)
        step_args = (*step_args, tokens, targets)
        if not args.auto_resume or multiproc:
            # fail-fast: without --auto-resume, kernel compile errors
            # surface to the operator (the degrade-and-rebuild retry
            # below is part of the --auto-resume contract, see --help).
            # Multi-process ALWAYS fails fast: a kernel error on ONE
            # host (flaky chip) tripping only that host's registry would
            # rebuild it on the scan fallback — whose collective count
            # differs per chunk from the kernel's — deadlocking every
            # peer inside the step's collectives.  The peers are stuck
            # device-side, so no host-level agreement (the
            # preempt_agreed pattern) can run here; a clean job-level
            # crash + --auto-resume restart is the recoverable path.
            return step(*step_args)
        # one rebuild per registered kernel: each retry's fresh trace can
        # surface the NEXT kernel's deferred compile error (the kernels
        # have never been proven on real chips — several failing at once
        # is the expected first-contact mode, and each has a fallback)
        from apex_tpu.resilience.fallback import KERNELS

        for _ in range(len(KERNELS) + 1):
            try:
                return step(*step_args)
            except Exception as e:  # noqa: BLE001 — kernel failures only
                # a Mosaic/Pallas failure is DEFERRED to the first call
                # of the jitted step: attribute it, trip the fallback
                # registry, rebuild — the fresh trace lowers the XLA
                # reference impl
                tripped = resilience.trip_from_exception(e)
                if not tripped:
                    raise
                if any(getattr(x, "is_deleted", lambda: False)()
                       for tree in step_args
                       for x in jax.tree.leaves(tree)):
                    # the failure surfaced AFTER execution started: the
                    # donated params/state buffers are gone, so a retry
                    # would read deleted arrays — restart from the
                    # checkpoint instead of a confusing secondary crash
                    raise RuntimeError(
                        "kernel failure after the step consumed its "
                        "donated inputs; rerun to resume from the last "
                        "checkpoint (the fallback registry is tripped "
                        f"for: {', '.join(tripped)})") from e
                print(f"kernel failure ({', '.join(tripped)}); rebuilt "
                      f"the step on the XLA fallback impl", flush=True)
                step = build_step()
        return step(*step_args)

    t0 = time.time()
    last_saved = None
    done = 0
    for i in range(start_step, start_step + args.steps):
        done = i - start_step + 1
        # heartbeat + chaos delivery (wedge: the watchdog fires
        # mid-sleep; kill: hard exit 137, no drain); the first
        # iteration's allowance covers the jit compile
        run_ctl.on_step(i, deadline=(args.watchdog_compile_grace
                                     if i == start_step else None))
        obs.set_step_context(step=i)
        with span("train.data_wait"):
            batch = next(prefetch)
        tokens = jnp.asarray(batch[:, :-1])
        targets = jnp.asarray(batch[:, 1:])
        out = run_step(tokens, targets)
        params, state = out[0], out[1]
        k = 2
        if scaler is not None:
            scaler_state = out[k]
            k += 1
        if stats is not None:
            stats = out[k]
            k += 1
            window_steps += 1
        loss = out[-1]
        # the ASYNC telemetry seam: hand the device scalars to the
        # fetcher (starts a non-blocking copy) and print whatever
        # earlier steps have materialized — zero blocking host reads in
        # this loop (analyzer rule APX108 pins the spelling)
        push = {"loss": loss}
        if scaler is not None:
            push["scale"] = scaler_state.loss_scale
        fetcher.put("loss", i, push)
        if acct is not None:
            acct.step_done(tokens=args.global_batch * args.seq)
        if telemetry is not None \
                and (i + 1 - start_step) % args.telemetry_every == 0:
            # fetch the accumulated window, swap in a fresh one placed
            # like the old (the stats buffers are donated AND the jit
            # cache keys on shardings)
            fetcher.put("stats", i + 1, stats._asdict())
            stats = telemetry.init_like(stats)
            window_steps = 0
        harvested = fetcher.ready()
        if harvested:
            with span("train.telemetry_fetch", harvested=len(harvested)):
                for kind, at_step, tree in harvested:
                    emit_harvested(kind, at_step, tree)
                batch_stats = [s for k, s, _ in harvested if k == "stats"]
                if batch_stats:
                    observe_window_span(batch_stats[-1])
        if ckpt and (i + 1) % args.save_every == 0:
            save_at(ckpt_tree(params, state, i + 1, scaler_state), i + 1)
            last_saved = i + 1
        if pre is not None and preempt_agreed():
            if ckpt and last_saved != i + 1:
                save_at(ckpt_tree(params, state, i + 1, scaler_state),
                        i + 1)
            if ckpt:
                pre.drain(ckpt)  # every accepted save is durable
            print(f"preempted ({pre.reason or 'peer process'}) after "
                  f"step {i}; rerun the same command to resume",
                  flush=True)
            break
    if watchdog is not None:
        watchdog.stop()  # the loop is done; the queue flush below may
        # legitimately outlast a step deadline
    # final async harvest: the tail window plus any loss lines still in
    # flight (blocking is correct here — the run is over)
    if telemetry is not None and stats is not None and window_steps > 0:
        fetcher.put("stats", start_step + done, stats._asdict())
    flushed = fetcher.flush()
    for kind, at_step, tree in flushed:
        emit_harvested(kind, at_step, tree)
    tail_stats = [s for k, s, _ in flushed if k == "stats"]
    if tail_stats:
        observe_window_span(tail_stats[-1])
    if ckpt:
        t_close = time.time()
        ckpt.close()
        if acct is not None:
            acct.add_segment("checkpoint", time.time() - t_close)
        print(f"checkpoint: {args.checkpoint}")
    if args.metrics_dir:
        (Path(args.metrics_dir) / f"metrics{rank_sfx}.prom").write_text(
            registry.prometheus_text())
    if acct is not None:  # process 0 owns the goodput record
        import json

        from apex_tpu.observability import goodput as gp

        acct.finalize("preempted" if (pre is not None and pre.preempted)
                      else "clean")
        n_params = gp.param_count(params)
        report = gp.goodput_report(
            args.metrics_dir,
            flops_per_token=gp.model_flops_per_token(
                n_params, args.layers, args.seq, args.hidden))
        (Path(args.metrics_dir) / "goodput_report.json").write_text(
            json.dumps(report, indent=1))
        print("goodput: " + " ".join(
            f"{k}={v:.1%}" for k, v in sorted(report["fractions"].items())),
            flush=True)
    if anomaly is not None:
        anomaly.persist(args.metrics_dir or args.trace_dir)
        counts = anomaly.counts()
        if counts:
            print("anomalies: " + " ".join(
                f"{k}={v}" for k, v in sorted(counts.items())), flush=True)
    if tracer is not None:
        exp = tracing.export_run(args.trace_dir, args.run_id, tracer)
        print(f"trace: {args.trace_dir} ({exp['events']} events, "
              f"{exp['dropped']} dropped)", flush=True)
    dt = time.time() - t0
    print(f"{done} steps in {dt:.1f}s "
          f"({args.global_batch * args.seq * done / dt:.0f} tokens/s)")


if __name__ == "__main__":
    main()
