"""T5 (encoder-decoder) pretraining example — span-corruption-style
seq2seq on synthetic data over a tp × pp × dp mesh.

The enc-dec counterpart of ``examples/gpt/pretrain_gpt.py``
(reference role: the Megatron T5 path,
``apex/transformer/pipeline_parallel/schedules/common.py:30-120``'s
``ModelType.encoder_and_decoder`` routing): the pipeline carries TWO
activation streams — encoder stages before the split rank, decoder
stages (+ the forwarded encoder output) at and after it — via the
dual-stream 1F1B tick schedule.

Synthetic task: the decoder must reproduce the source sequence
shifted by one (a copy task — loss visibly falls within a few steps,
so the example doubles as an end-to-end smoke check).

    # 8 virtual CPU devices:
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/t5/pretrain_t5.py --pp 4 --split 2 --steps 4
    # flags compose: --tp 2, --fp16 (dynamic loss scaling through the
    # dual-stream pipeline), --fused-ce (chunked fused LM-head+CE)
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from apex_tpu.models.t5 import (
    T5Config,
    init_params,
    make_pp_train_step,
    make_train_step,
    params_to_pp_layout,
)
from apex_tpu.optimizers import FusedAdam


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--split", type=int, default=None,
                   help="pipeline rank where encoder hands to decoder "
                        "(default pp//2)")
    p.add_argument("--micro-batches", type=int, default=2)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--enc-layers", type=int, default=2)
    p.add_argument("--dec-layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--fp16", action="store_true",
                   help="dynamic loss scaling through the pipeline")
    p.add_argument("--fused-ce", action="store_true",
                   help="chunked fused LM-head+CE (ops/fused_ce.py)")
    return p.parse_args()


def make_batch(rng, batch, seq, vocab):
    """Copy task: decoder input is <bos>+src[:-1], target is src."""
    src = rng.randint(2, vocab, size=(batch, seq))
    dec_in = np.concatenate([np.ones((batch, 1), src.dtype), src[:, :-1]], 1)
    return jnp.asarray(src), jnp.asarray(dec_in), jnp.asarray(src)


def main():
    args = parse_args()
    n_dev = len(jax.devices())
    dp = n_dev // (args.tp * args.pp)
    assert dp >= 1 and dp * args.tp * args.pp == n_dev, (
        f"tp({args.tp}) x pp({args.pp}) must divide device count {n_dev}")
    split = args.split if args.split is not None else max(args.pp // 2, 1)

    config = T5Config(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_encoder_layers=args.enc_layers, num_decoder_layers=args.dec_layers,
        num_attention_heads=args.heads,
        max_src_len=args.seq, max_tgt_len=args.seq,
        compute_dtype=jnp.float16 if args.fp16 else jnp.bfloat16,
        checkpoint_layers=True,
        fused_ce=args.fused_ce,
        fused_ce_chunk=next(c for c in range(min(128, args.seq), 0, -1)
                            if args.seq % c == 0),
    )
    params = init_params(config, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=args.lr)

    scaler = sstate = None
    if args.fp16:
        from apex_tpu.amp import DynamicLossScaler

        scaler = DynamicLossScaler(init_scale=2.0 ** 15)
        sstate = scaler.init()

    if args.pp > 1:
        mesh = Mesh(np.array(jax.devices()).reshape(dp, args.pp, args.tp),
                    ("dp", "pp", "tp"))
        params = params_to_pp_layout(params, pp=args.pp, split=split)
        state = opt.init(params)
        step = make_pp_train_step(config, opt, mesh,
                                  num_microbatches=args.micro_batches,
                                  split=split, dp_axis="dp",
                                  loss_scaler=scaler, donate_state=True)
    else:
        mesh = Mesh(np.array(jax.devices()).reshape(dp, args.tp),
                    ("dp", "tp"))
        state = opt.init(params)
        step = make_train_step(config, opt, mesh, dp_axis="dp",
                               donate_state=True)
        assert scaler is None, "--fp16 demo path requires --pp > 1"

    # a small fixed pool of batches: a fresh random batch per step keeps
    # the copy task at uniform-entropy loss for tens of steps (nothing
    # generalizes that fast at this size); cycling a pool makes the
    # loss fall visibly within one epoch, which is what a smoke example
    # is for
    rng = np.random.RandomState(0)
    pool = [make_batch(rng, args.global_batch, args.seq, args.vocab)
            for _ in range(4)]
    # loss printing rides the async telemetry seam (the APX108-clean
    # spelling): the loop never blocks on a device array — completed
    # copies print a step or two later, the flush drains the rest
    from apex_tpu.observability.stepstats import AsyncFetcher

    fetcher = AsyncFetcher()

    def emit(harvested):
        for _, s, tree in harvested:
            print(f"step {s}: loss={float(tree['loss']):.4f}", flush=True)

    t0 = time.time()
    for i in range(args.steps):
        src, dec_in, tgt = pool[i % len(pool)]
        if scaler is not None:
            params, state, sstate, loss = step(params, state, sstate,
                                               src, dec_in, tgt)
        else:
            params, state, loss = step(params, state, src, dec_in, tgt)
        fetcher.put("loss", i, {"loss": loss})
        emit(fetcher.ready())
    emit(fetcher.flush())
    dt = time.time() - t0
    tok = args.steps * args.global_batch * args.seq
    print(f"{args.steps} steps in {dt:.1f}s ({tok / dt:.0f} tokens/s)")


if __name__ == "__main__":
    main()
