"""ImageNet trainer — the TPU re-design of the reference's canonical
end-to-end example (``examples/imagenet/main_amp.py``, 543 LoC):
ResNet-50, amp O2 (bf16 compute + fp32 BN + fp32 master weights), fused
optimizer, DDP over the ``dp`` mesh axis, checkpoint save/resume.

Synthetic data by default (no dataset in the image); plug a real input
pipeline into ``data_iter``.

Usage:
    python examples/imagenet/main_amp.py --steps 20 --batch-size 64
    python examples/imagenet/main_amp.py --dp 8  # 8-way data parallel
"""

import argparse
import pickle
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.resnet import ResNet50, ResNet18ish
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import allreduce_gradients


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=32, help="global batch")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--dp", type=int, default=1, help="data-parallel ways")
    p.add_argument("--small", action="store_true", help="tiny model (CI)")
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--resume", default=None)
    return p.parse_args()


def synthetic_batch(rng, batch, size, num_classes=1000):
    x = rng.standard_normal((batch, size, size, 3), dtype=np.float32)
    y = rng.integers(0, num_classes, size=(batch,))
    return jnp.asarray(x), jnp.asarray(y)


def main():
    args = parse_args()
    cls = ResNet18ish if args.small else ResNet50
    model = cls(sync_bn_axis="dp" if args.dp > 1 else None, num_classes=1000)
    # init outside the mesh with an axis-free twin (same param shapes)
    init_model = cls(sync_bn_axis=None, num_classes=1000)

    rng = np.random.default_rng(0)
    x0, y0 = synthetic_batch(rng, args.batch_size, args.image_size)

    variables = init_model.init(jax.random.PRNGKey(0), x0[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # amp O2: bf16 params (except norms), no loss scaler needed for bf16
    params, amp_state = amp.initialize(params, opt_level=args.opt_level)
    opt = FusedSGD(
        lr=args.lr,
        momentum=args.momentum,
        weight_decay=args.weight_decay,
        master_weights=True,  # O2 fp32 master weights
    )
    opt_state = opt.init(params)
    scaler_state = amp_state.init_state()

    start_step = 0
    if args.resume:
        with open(args.resume, "rb") as f:
            ck = pickle.load(f)
        params = jax.tree.map(jnp.asarray, ck["params"])
        opt_state = jax.tree.map(
            lambda x: jnp.asarray(x) if x is not None else None, ck["opt_state"]
        )
        batch_stats = jax.tree.map(jnp.asarray, ck["batch_stats"])
        start_step = ck["step"]
        if ck.get("amp") and amp_state.scaler:
            scaler_state = amp_state.load_state_dict(ck["amp"])

    def loss_fn(params, batch_stats, x, y):
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats}, x, train=True,
            mutable=["batch_stats"],
        )
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
        return loss, updates["batch_stats"]

    def local_step(params, opt_state, batch_stats, x, y, dp: bool):
        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch_stats, x, y
        )
        if dp:
            grads = allreduce_gradients(grads, axis_name="dp")
            loss = jax.lax.pmean(loss, "dp")
        new_params, new_opt = opt.update(grads, opt_state, params)
        return new_params, new_opt, new_bs, loss

    if args.dp > 1:
        devs = jax.devices()[: args.dp]
        mesh = Mesh(np.array(devs), ("dp",))
        step_fn = jax.jit(
            jax.shard_map(
                lambda p, o, b, x, y: local_step(p, o, b, x, y, True),
                mesh=mesh,
                in_specs=(P(), P(), P(), P("dp"), P("dp")),
                out_specs=(P(), P(), P(), P()),
                check_vma=False,
            )
        )
    else:
        step_fn = jax.jit(lambda p, o, b, x, y: local_step(p, o, b, x, y, False))

    print(f"training {'ResNet-small' if args.small else 'ResNet-50'}: "
          f"{args.steps} steps, global batch {args.batch_size}, dp={args.dp}, "
          f"opt_level={args.opt_level}")

    # loss printing rides the async telemetry seam (the APX108-clean
    # spelling): the loop never blocks on a device scalar — completed
    # copies print a step or two late, the flush drains the rest
    from apex_tpu.observability.stepstats import AsyncFetcher

    fetcher = AsyncFetcher()

    def emit(harvested):
        for _, s, tree in harvested:
            print(f"step {s}: loss {float(tree['loss']):.4f}")

    t_start = None
    for step in range(start_step, start_step + args.steps):
        x, y = synthetic_batch(rng, args.batch_size, args.image_size)
        params, opt_state, batch_stats, loss = step_fn(params, opt_state, batch_stats, x, y)
        if step == start_step:
            jax.block_until_ready(loss)
            t_start = time.perf_counter()  # exclude compile
        fetcher.put("loss", step, {"loss": loss})
        emit(fetcher.ready())
    emit(fetcher.flush())
    jax.block_until_ready(params)
    if t_start and args.steps > 1:
        dt = time.perf_counter() - t_start
        ips = args.batch_size * (args.steps - 1) / dt
        print(f"throughput: {ips:.1f} images/sec")

    if args.checkpoint:
        ck = {
            "params": jax.tree.map(np.asarray, params),
            "opt_state": jax.tree.map(
                lambda x: np.asarray(x) if x is not None else None, opt_state
            ),
            "batch_stats": jax.tree.map(np.asarray, batch_stats),
            "step": start_step + args.steps,
            "amp": amp_state.state_dict(scaler_state) if amp_state.scaler else None,
        }
        Path(args.checkpoint).parent.mkdir(parents=True, exist_ok=True)
        # atomic publish (APX104): a run killed mid-save must not leave
        # a torn pickle under the final name
        from apex_tpu.io import native

        with native.atomic_output(args.checkpoint) as f:
            pickle.dump(ck, f)
        print(f"checkpoint saved to {args.checkpoint}")


if __name__ == "__main__":
    main()
