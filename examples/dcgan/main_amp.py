"""DCGAN with mixed precision — example parity slot.

Reference: ``examples/dcgan`` ships only a README describing how apex
amp *would* wire into a DCGAN (two models, two optimizers, two loss
scalers); this version actually runs: a small conv GAN on synthetic
64×64 images, bf16 compute with fp32 master weights (amp O2
semantics), one FusedAdam per network, and per-network dynamic loss
scaling — the ``amp.initialize(num_losses=2)`` scenario from the
reference README.

    python examples/dcgan/main_amp.py [--steps 20] [--batch-size 32]
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp.scaler import DynamicLossScaler
from apex_tpu.optimizers import FusedAdam

LATENT = 64


def _conv(x, w, stride=2):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _deconv(x, w, stride=2):
    return jax.lax.conv_transpose(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def init_params(key):
    ks = jax.random.split(key, 8)
    he = lambda k, *s: jax.random.normal(k, s, jnp.float32) * np.sqrt(2.0 / np.prod(s[:-1]))
    gen = {
        "fc": he(ks[0], LATENT, 4 * 4 * 256),
        "d1": he(ks[1], 4, 4, 256, 128),
        "d2": he(ks[2], 4, 4, 128, 64),
        "d3": he(ks[3], 4, 4, 64, 3),
    }
    disc = {
        "c1": he(ks[4], 4, 4, 3, 64),
        "c2": he(ks[5], 4, 4, 64, 128),
        "c3": he(ks[6], 4, 4, 128, 256),
        "fc": he(ks[7], 8 * 8 * 256, 1),
    }
    return gen, disc


def generator(z, p):
    x = (z @ p["fc"].astype(z.dtype)).reshape(-1, 4, 4, 256)
    x = jax.nn.relu(_deconv(x, p["d1"]))   # 8×8
    x = jax.nn.relu(_deconv(x, p["d2"]))   # 16×16
    return jnp.tanh(_deconv(x, p["d3"], stride=4))  # 64×64


def discriminator(img, p):
    x = jax.nn.leaky_relu(_conv(img, p["c1"]), 0.2)   # 32×32
    x = jax.nn.leaky_relu(_conv(x, p["c2"]), 0.2)     # 16×16
    x = jax.nn.leaky_relu(_conv(x, p["c3"]), 0.2)     # 8×8
    return x.reshape(x.shape[0], -1) @ p["fc"].astype(x.dtype)


def bce(logits, label):
    # label 1 = real; stable sigmoid cross entropy in f32
    logits = logits.astype(jnp.float32)
    return jnp.mean(jnp.maximum(logits, 0) - logits * label +
                    jnp.log1p(jnp.exp(-jnp.abs(logits))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--compute-dtype", default="bfloat16")
    args = ap.parse_args()
    cd = jnp.bfloat16 if args.compute_dtype == "bfloat16" else jnp.float32

    gen, disc = init_params(jax.random.PRNGKey(0))
    g_opt, d_opt = FusedAdam(lr=2e-4, betas=(0.5, 0.999)), FusedAdam(lr=2e-4, betas=(0.5, 0.999))
    g_state, d_state = g_opt.init(gen), d_opt.init(disc)
    # one dynamic scaler per loss — the reference README's num_losses=2
    scaler = DynamicLossScaler()
    g_ss, d_ss = scaler.init(), scaler.init()

    @jax.jit
    def d_step(disc, d_state, d_ss, gen, real, z):
        def loss_fn(disc):
            fake = generator(z.astype(cd), gen)
            l = bce(discriminator(real.astype(cd), disc), 1.0) + bce(
                discriminator(fake, disc), 0.0
            )
            return scaler.scale(d_ss, l)

        scaled_loss, grads = jax.value_and_grad(loss_fn)(disc)
        grads, finite = scaler.unscale(d_ss, grads)
        disc, d_state = d_opt.update(grads, d_state, disc, grads_finite=finite)
        return disc, d_state, scaler.update(d_ss, finite), scaled_loss / d_ss.loss_scale

    @jax.jit
    def g_step(gen, g_state, g_ss, disc, z):
        def loss_fn(gen):
            fake = generator(z.astype(cd), gen)
            return scaler.scale(g_ss, bce(discriminator(fake, disc), 1.0))

        scaled_loss, grads = jax.value_and_grad(loss_fn)(gen)
        grads, finite = scaler.unscale(g_ss, grads)
        gen, g_state = g_opt.update(grads, g_state, gen, grads_finite=finite)
        return gen, g_state, scaler.update(g_ss, finite), scaled_loss / g_ss.loss_scale

    rng = np.random.RandomState(0)
    t0 = time.time()
    for step in range(args.steps):
        real = jnp.asarray(rng.rand(args.batch_size, 64, 64, 3).astype(np.float32) * 2 - 1)
        z = jnp.asarray(rng.randn(args.batch_size, LATENT).astype(np.float32))
        disc, d_state, d_ss, d_loss = d_step(disc, d_state, d_ss, gen, real, z)
        gen, g_state, g_ss, g_loss = g_step(gen, g_state, g_ss, disc, z)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: d_loss={float(d_loss):.4f} g_loss={float(g_loss):.4f}")
    print(f"done: {args.steps} steps in {time.time() - t0:.1f}s")


if __name__ == "__main__":
    main()
