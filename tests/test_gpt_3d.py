"""3D-parallel GPT: tp×pp×dp pipelined training must match the
single-device model exactly (the reference's
test_pipeline_parallel_fwd_bwd.py parity standard, applied to the full
flagship stack)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from apex_tpu.models.gpt import (
    GPTConfig,
    gpt_loss,
    init_params,
    make_pp_train_step,
)
from apex_tpu.optimizers import FusedAdam

# whole-file e2e/parity workloads: >20 s compiled (quick tier skips)
pytestmark = pytest.mark.slow

CFG = GPTConfig(
    vocab_size=64,
    hidden_size=32,
    num_layers=4,
    num_attention_heads=4,
    max_seq_len=16,
    compute_dtype=jnp.float32,
    checkpoint_layers=False,
)


@pytest.mark.parametrize("sp", [False, True])
def test_tp_pp_dp_matches_single_device(devices8, sp):
    cfg = GPTConfig(**{**CFG.__dict__, "sequence_parallel": sp})
    mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "pp", "tp"))

    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(8, 16)))
    targets = jnp.roll(tokens, -1, axis=1)

    step = make_pp_train_step(cfg, opt, mesh, num_microbatches=2)
    new_params, new_state, loss = step(params, state, tokens, targets)

    # single-device oracle: same global batch (dp shards see tokens[i::2]?
    # data_spec P("dp", None) splits the batch over dp; total loss is the
    # dp-mean of per-shard means == global mean over the batch)
    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, tokens, targets, CFG)
    ref_params, _ = opt.update(ref_grads, opt.init(params), params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(new_params),
        jax.tree_util.tree_leaves_with_path(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-5,
            err_msg=jax.tree_util.keystr(ka),
        )


def test_pp_training_reduces_loss(devices8):
    mesh = Mesh(np.array(devices8).reshape(1, 4, 2), ("dp", "pp", "tp"))
    params = init_params(CFG, jax.random.PRNGKey(1))
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(4, 16)))
    targets = jnp.roll(tokens, -1, axis=1)
    step = make_pp_train_step(CFG, opt, mesh, num_microbatches=4)
    losses = []
    for _ in range(6):
        params, state, loss = step(params, state, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_vpp_interleaved_matches_single_device(devices8):
    """make_pp_train_step(virtual_pipeline_size=2) == single-device step
    (the interleaved schedule driven end-to-end through the flagship)."""
    from apex_tpu.models.gpt import params_from_vpp_layout, params_to_vpp_layout

    mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "pp", "tp"))
    params = init_params(CFG, jax.random.PRNGKey(3))
    opt = FusedAdam(lr=1e-2)

    vparams = params_to_vpp_layout(params, pp=2, vpp=2)
    vstate = opt.init(vparams)
    step = make_pp_train_step(CFG, opt, mesh, num_microbatches=4, virtual_pipeline_size=2)

    rng = np.random.RandomState(3)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(8, 16)))
    targets = jnp.roll(tokens, -1, axis=1)
    new_vparams, _, loss = step(vparams, vstate, tokens, targets)
    new_params = params_from_vpp_layout(new_vparams, pp=2, vpp=2)

    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, tokens, targets, CFG)
    ref_params, _ = opt.update(ref_grads, opt.init(params), params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(new_params),
        jax.tree_util.tree_leaves_with_path(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-5,
            err_msg=jax.tree_util.keystr(ka),
        )


@pytest.mark.xfail(
    strict=False,
    reason="known 1-in-16384-elements mismatch at |g|≈eps on this CPU "
           "box (fails identically on the pre-PR-4 tree — an XLA:CPU "
           "accumulation-order artifact, not a ZeRO regression; see "
           "the PR 4 'Known pre-existing' note in CHANGES.md)")
def test_zero2_composed_with_pp_tp_matches_fused_adam(devices8):
    """Full-stack ZeRO: pp=2 x tp=2 x dp=2 pipeline step with
    DistributedFusedAdam (state sharded over (pp, tp, dp), grads synced
    by the optimizer's reduce-scatter) must match the single-device
    FusedAdam oracle.

    xfail-gated, not skipped: the 5e-5 atol holds for 16383 of 16384
    elements and the outlier is a single |grad|≈eps element whose
    reduction order differs between the sharded and oracle paths on
    XLA:CPU — strict=False so a box where it passes doesn't fail the
    gate."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.models.gpt import param_specs
    from apex_tpu.optimizers import FusedAdam

    mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "pp", "tp"))
    params = init_params(CFG, jax.random.PRNGKey(0))

    base = param_specs(CFG)
    specs = dict(base)
    specs["layers"] = {k: P("pp", *s[1:]) for k, s in base["layers"].items()}

    opt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
    state = opt.init(params, world_size=2, param_specs=specs,
                     axis_sizes={"pp": 2, "tp": 2})

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(8, 16)))
    targets = jnp.roll(tokens, -1, axis=1)

    step = make_pp_train_step(CFG, opt, mesh, num_microbatches=2)
    new_params, new_state, loss = step(params, state, tokens, targets)

    ref = FusedAdam(lr=1e-2, adam_w_mode=True, weight_decay=0.0)
    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, tokens, targets, CFG)
    ref_params, _ = ref.update(ref_grads, ref.init(params), params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(new_params),
        jax.tree_util.tree_leaves_with_path(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-5,
            err_msg=jax.tree_util.keystr(ka),
        )
