"""fp16 dynamic loss scaling through the flagship parallel train steps.

The reference's flagship call stack is amp × DDP × Megatron: loss
scaling runs INSIDE distributed training, with found_inf agreed across
the model-parallel group (``apex/amp/handle.py:16``,
``apex/transformer/amp/grad_scaler.py:21-126``).  These tests prove the
TPU analog end to end: ``make_train_step``/``make_pp_train_step`` with a
``DynamicLossScaler`` must track a single-device scaled-fp16-style
oracle step for step — including an overflow step (scaled loss
saturates fp32 → every rank skips, scale backs off, the Adam step
counter holds) and subsequent growth.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from apex_tpu.amp import DynamicLossScaler
from apex_tpu.models.gpt import (
    GPTConfig,
    gpt_loss,
    init_params,
    make_pp_train_step,
    make_train_step,
)
from apex_tpu.optimizers import FusedAdam

pytestmark = pytest.mark.slow

STEPS = 6


def tiny_config(dtype=jnp.float32, **kw):
    return GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=4, num_attention_heads=4,
        max_seq_len=16, compute_dtype=dtype, checkpoint_layers=True, **kw
    )


def make_scaler():
    """init_scale 2**127 makes the FIRST scaled loss overflow fp32 on
    every path identically (the loss scalar itself saturates — immune to
    reduction-order noise); backoff 2**-4 lands the next step at a
    comfortably finite scale; growth_interval 3 exercises a growth
    (clamped to max_scale) inside a 6-step run."""
    return DynamicLossScaler(
        init_scale=2.0 ** 127, backoff_factor=2.0 ** -4,
        growth_factor=2.0, growth_interval=3, hysteresis=1,
    )


def data(batch, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    tok = jnp.asarray(rng.randint(0, 64, size=(batch, seq)))
    return tok, jnp.roll(tok, -1, axis=1)


def oracle_trajectory(config, scaler, tokens, targets, nsteps=STEPS):
    """Single-device scaled train loop: the fp16 oracle of reference
    §3.2 (scale → backward → unscale+found_inf → predicated step →
    scale update), one jit program."""
    params = init_params(config, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    sstate = scaler.init()

    @jax.jit
    def step(params, state, sstate, tok, tgt):
        def f(p):
            return gpt_loss(p, tok, tgt, config) * sstate.loss_scale

        sloss, grads = jax.value_and_grad(f)(params)
        loss = sloss / sstate.loss_scale
        grads, finite = scaler.unscale(sstate, grads)
        params, state = opt.update(grads, state, params, grads_finite=finite)
        sstate = scaler.update(sstate, finite)
        return params, state, sstate, loss

    losses, scales = [], []
    for _ in range(nsteps):
        params, state, sstate, loss = step(params, state, sstate, tokens, targets)
        losses.append(float(loss))
        scales.append(float(sstate.loss_scale))
    return params, state, sstate, np.asarray(losses), np.asarray(scales)


def assert_trajectory_matches(params, state, sstate, losses, scales, oracle):
    o_params, o_state, o_sstate, o_losses, o_scales = oracle
    # scaler decisions must be IDENTICAL (they're discrete)
    np.testing.assert_array_equal(scales, o_scales)
    assert int(sstate.growth_tracker) == int(o_sstate.growth_tracker)
    assert int(sstate.hysteresis) == int(o_sstate.hysteresis)
    # the overflow step must not have advanced Adam's step counter
    assert int(state.step) == int(o_state.step)
    # losses: inf on the overflow step on BOTH, close elsewhere
    assert np.isinf(losses[0]) and np.isinf(o_losses[0])
    np.testing.assert_allclose(losses[1:], o_losses[1:], rtol=1e-4)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(o_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-5)


def test_scaled_tp_dp_matches_oracle(devices8):
    """make_train_step(loss_scaler=...) at tp=2 × dp=4 vs the oracle."""
    config = tiny_config(sequence_parallel=True)
    scaler = make_scaler()
    mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
    params = init_params(config, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    sstate = scaler.init()
    step = make_train_step(config, opt, mesh, loss_scaler=scaler)
    tok, tgt = data(batch=8)

    losses, scales = [], []
    for _ in range(STEPS):
        params, state, sstate, loss = step(params, state, sstate, tok, tgt)
        losses.append(float(loss))
        scales.append(float(sstate.loss_scale))

    oracle = oracle_trajectory(tiny_config(), scaler, tok, tgt)
    assert_trajectory_matches(params, state, sstate,
                              np.asarray(losses), np.asarray(scales), oracle)
    # sanity: it actually trained after the overflow step
    assert losses[-1] < losses[1]


def test_scaled_tp_dp_fused_ce_matches_oracle(devices8):
    """The chunked fused LM-head+CE (ops/fused_ce.py) under dynamic loss
    scaling at tp=2 × dp=4: the custom_vjp must carry the scaled
    cotangent (incl. the saturating overflow step) identically to the
    dense head — discrete scaler decisions AND the post-recovery
    trajectory match the dense-head oracle."""
    config = tiny_config(sequence_parallel=True, fused_ce=True,
                         fused_ce_chunk=8)
    scaler = make_scaler()
    mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
    params = init_params(config, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    sstate = scaler.init()
    step = make_train_step(config, opt, mesh, loss_scaler=scaler)
    tok, tgt = data(batch=8)

    losses, scales = [], []
    for _ in range(STEPS):
        params, state, sstate, loss = step(params, state, sstate, tok, tgt)
        losses.append(float(loss))
        scales.append(float(sstate.loss_scale))

    oracle = oracle_trajectory(tiny_config(), scaler, tok, tgt)
    assert_trajectory_matches(params, state, sstate,
                              np.asarray(losses), np.asarray(scales), oracle)
    assert losses[-1] < losses[1]


def test_scaled_pp_tp_dp_matches_oracle(devices8):
    """make_pp_train_step(loss_scaler=...) at tp=2 × pp=2 × dp=2 vs the
    oracle — found_inf agreed across stages, skip in lockstep."""
    config = tiny_config()
    scaler = make_scaler()
    mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "pp", "tp"))
    params = init_params(config, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    sstate = scaler.init()
    step = make_pp_train_step(config, opt, mesh, num_microbatches=2,
                              loss_scaler=scaler)
    tok, tgt = data(batch=8)

    losses, scales = [], []
    for _ in range(STEPS):
        params, state, sstate, loss = step(params, state, sstate, tok, tgt)
        losses.append(float(loss))
        scales.append(float(sstate.loss_scale))

    oracle = oracle_trajectory(tiny_config(), scaler, tok, tgt)
    assert_trajectory_matches(params, state, sstate,
                              np.asarray(losses), np.asarray(scales), oracle)


def test_scaled_moe_trains_with_dp_vote(devices8):
    """MoE expert grads are dp-sharded, so make_train_step adds dp to
    the found_inf vote axes; the scaled MoE step must compile with that
    extra collective and train."""
    config = tiny_config(moe_num_experts=4, moe_top_k=2)
    scaler = DynamicLossScaler(init_scale=2.0 ** 16)
    mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
    params = init_params(config, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    sstate = scaler.init()
    step = make_train_step(config, opt, mesh, loss_scaler=scaler)
    tok, tgt = data(batch=8)

    losses = []
    for _ in range(5):
        params, state, sstate, loss = step(params, state, sstate, tok, tgt)
        losses.append(float(loss))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses


def test_found_inf_vote_spans_given_axes(devices8):
    """One rank's overflow must veto the step on EVERY rank of every
    sync axis (the dp-sharded-expert-grads / ZeRO-local-grads case)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.amp.grad_scaler import sync_found_inf

    mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
    # finite everywhere except dp rank 2 (all tp ranks of it)
    flags = jnp.asarray([True, True, False, True])

    def f(flag):
        return sync_found_inf(flag[0], ("dp", "tp")).astype(jnp.int32)

    out = shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P())(flags)
    assert int(out) == 0  # every rank agreed: not finite


def test_scaled_vpp_interleaved_matches_oracle(devices8):
    """The interleaved (vpp=2) schedule composes with the loss scaler:
    scaled steps at tp2×pp2×dp2 vpp2 match the single-device scaled
    oracle (scaled backward seed through the ring, unscale, finite vote
    over tp+pp, growth on schedule).  Overflow/backoff semantics are
    covered by the 1F1B test — forcing an overflow via a saturating
    scale is knife-edge-dependent on microbatch count (cotangents scale
    with 1/M), so this variant pins the finite path."""
    from apex_tpu.models.gpt import params_from_vpp_layout, params_to_vpp_layout

    config = tiny_config()
    scaler = DynamicLossScaler(init_scale=2.0 ** 10, growth_factor=2.0,
                               growth_interval=2, hysteresis=1)
    mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "pp", "tp"))
    params = init_params(config, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    vparams = params_to_vpp_layout(params, pp=2, vpp=2)
    vstate = opt.init(vparams)
    sstate = scaler.init()
    step = make_pp_train_step(config, opt, mesh, num_microbatches=4,
                              virtual_pipeline_size=2, loss_scaler=scaler)
    tok, tgt = data(batch=8)

    losses, scales = [], []
    for _ in range(3):
        vparams, vstate, sstate, loss = step(vparams, vstate, sstate, tok, tgt)
        losses.append(float(loss))
        scales.append(float(sstate.loss_scale))

    o_scaler = DynamicLossScaler(init_scale=2.0 ** 10, growth_factor=2.0,
                                 growth_interval=2, hysteresis=1)
    o_params, o_state, o_sstate, o_losses, o_scales = oracle_trajectory(
        tiny_config(), o_scaler, tok, tgt, nsteps=3)
    np.testing.assert_array_equal(np.asarray(scales), o_scales)
    assert scales[-1] == 2.0 ** 11  # growth fired at the interval
    np.testing.assert_allclose(np.asarray(losses), o_losses, rtol=1e-4)
    new_params = params_from_vpp_layout(vparams, pp=2, vpp=2)
    for a, b in zip(jax.tree.leaves(new_params), jax.tree.leaves(o_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-5)


def test_fp16_compute_trains_through_pipeline(devices8):
    """True float16 compute through tp×pp×dp with a standard dynamic
    scaler: finite losses, decreasing trend, params stay finite."""
    config = tiny_config(dtype=jnp.float16)
    scaler = DynamicLossScaler(init_scale=2.0 ** 16)
    mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "pp", "tp"))
    params = init_params(config, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    sstate = scaler.init()
    step = make_pp_train_step(config, opt, mesh, num_microbatches=2,
                              loss_scaler=scaler)
    tok, tgt = data(batch=8)

    losses = []
    for _ in range(STEPS):
        params, state, sstate, loss = step(params, state, sstate, tok, tgt)
        losses.append(float(loss))
    finite_losses = [l for l in losses if np.isfinite(l)]
    assert len(finite_losses) >= 4, losses
    assert finite_losses[-1] < finite_losses[0], losses
    for leaf in jax.tree.leaves(params):
        assert np.all(np.isfinite(np.asarray(leaf, np.float32)))
