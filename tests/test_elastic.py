"""Elastic fault-tolerant training (`apex_tpu.resilience.elastic`).

The executable spec of the TorchTitan-class scenarios on the virtual
8-device CPU mesh:

- the SCENARIO MATRIX: gpt × {replicated, ZeRO, ZeRO+int8 sync} ×
  {same-world, shrink, grow} resume, each asserting loss-trajectory
  continuation against the uninterrupted run (and bitwise state at the
  saved world);
- pod-scale chaos: kill-one-host-of-N → elastic resume at the smaller
  world; a wedged collective (ONE rank stalled inside the compiled
  step) → the step watchdog notices, drains, and reports;
- the step watchdog's heartbeat/deadline/drain contract and the
  supervisor restart-backoff schedule.

Everything here rides the quick tier: tiny model, per-(mode, world)
step compiles shared across the matrix via a module-scoped cache.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from apex_tpu import io, resilience
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.models.gpt import (
    GPTConfig, init_params, make_train_step, param_specs,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import (
    ChaosHostKilled,
    ChaosMonkey,
    ChaosPlan,
    ElasticRunController,
    StepGuard,
    StepWatchdog,
    restart_backoff,
    restore_elastic_checkpoint,
    save_elastic_checkpoint,
)

CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                num_attention_heads=2, max_seq_len=16,
                compute_dtype=jnp.float32)
BATCH, SEQ = 8, 16

MODES = ("replicated", "zero", "zero_int8")
#: transition -> (save world, resume world)
TRANSITIONS = {"same": (2, 2), "shrink": (4, 2), "grow": (2, 4)}


def batch(i):
    """Step ``i``'s global batch — a function of the step index alone,
    so runs at different dp worlds consume identical data."""
    rng = np.random.RandomState(1000 + i)
    d = rng.randint(0, CFG.vocab_size, size=(BATCH, SEQ + 1))
    return jnp.asarray(d[:, :-1]), jnp.asarray(d[:, 1:])


@pytest.fixture(scope="module")
def rig(devices8):
    """(optimizer, fresh state, compiled step, fresh params) per
    (mode, world) — cached so the 9 matrix cells share 6 compiles."""
    cache = {}

    def get(mode, world):
        key = (mode, world)
        if key not in cache:
            mesh = Mesh(np.array(devices8[:world]).reshape(world, 1),
                        ("dp", "tp"))
            params0 = init_params(CFG, jax.random.PRNGKey(0))
            if mode == "replicated":
                opt = FusedAdam(lr=1e-2, weight_decay=0.01)
                state0 = opt.init(params0)
            else:
                opt = DistributedFusedAdam(
                    lr=1e-2, weight_decay=0.01, axis_name="dp",
                    grad_sync_dtype="int8" if mode == "zero_int8" else None)
                state0 = opt.init(params0, world_size=world,
                                  param_specs=param_specs(CFG),
                                  axis_sizes={"tp": 1})
            step = make_train_step(CFG, opt, mesh)
            cache[key] = (opt, state0, step, params0)
        return cache[key]

    return get


_ORACLES = {}


def oracle(rig, mode, world, steps=6):
    """The uninterrupted ``steps``-step run at ``world`` — the
    continuation reference; cached per (mode, world)."""
    key = (mode, world)
    if key not in _ORACLES:
        opt, state, step, params = rig(mode, world)
        losses = []
        for i in range(steps):
            params, state, loss = step(params, state, *batch(i))
            losses.append(float(loss))
        _ORACLES[key] = (params, losses)
    return _ORACLES[key]


def tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------- scenario matrix
@pytest.mark.parametrize("transition", sorted(TRANSITIONS))
@pytest.mark.parametrize("mode", MODES)
class TestScenarioMatrix:
    def test_resume_continues_loss_trajectory(self, rig, tmp_path, mode,
                                              transition):
        """Train 3 steps at world A, elastic-save, restore (resharding
        when A != B) at world B, train 3 more on the same data schedule:
        the resumed trajectory must continue the uninterrupted run's —
        within reduction-order ulps for the fp32 modes, a quantization
        band for the int8 wire — and a same-world resume is BITWISE."""
        w0, w1 = TRANSITIONS[transition]
        opt0, state, step0, params = rig(mode, w0)
        for i in range(3):
            params, state, _ = step0(params, state, *batch(i))
        save_elastic_checkpoint(
            tmp_path, 3, params=params, opt_state=state, optimizer=opt0,
            world_size=w0, mesh_axes={"tp": 1})

        opt1, _, step1, _ = rig(mode, w1)
        r = restore_elastic_checkpoint(
            tmp_path, optimizer=opt1, world_size=w1, mesh_axes={"tp": 1})
        assert r is not None and r.step == 3
        if mode == "replicated":
            # replicated state is dp-invariant: saved as world 1,
            # elastic by construction
            assert r.saved_world == 1 and not r.resharded
        else:
            assert r.saved_world == w0
            assert r.resharded == (w0 != w1)
        tree_equal(r.params, params)  # params dp-replicated: bitwise

        p_r, s_r = r.params, r.opt_state
        resumed = []
        for i in range(3, 6):
            p_r, s_r, loss = step1(p_r, s_r, *batch(i))
            resumed.append(float(loss))

        _, ref = oracle(rig, mode, w0)
        band = 0.05 if mode == "zero_int8" else 5e-3
        np.testing.assert_allclose(resumed, ref[3:], rtol=band)
        if transition == "same":
            ref_params, _ = oracle(rig, mode, w1)
            tree_equal(p_r, ref_params)


# ------------------------------------------- hierarchical layout elasticity
class TestHierarchicalElasticChain:
    """ISSUE 12's elastic coverage: the dp LAYOUT (flat vs the
    hierarchical (outer, inner) split) is as elastic as the dp world
    size — shard ownership keeps the flat chunk-per-rank layout and the
    one ``padded_total`` formula, so checkpoints cross flat <->
    hierarchical with no special case in the elastic machinery."""

    def _hier_rig(self, devices8):
        mesh = Mesh(np.array(devices8[:4]).reshape(2, 2, 1),
                    ("dp_out", "dp_in", "tp"))
        params0 = init_params(CFG, jax.random.PRNGKey(0))
        opt = DistributedFusedAdam(
            lr=1e-2, weight_decay=0.01, dp_axes=("dp_out", "dp_in"),
            grad_sync_dtype="int8")
        opt.init(params0, world_size=4, param_specs=param_specs(CFG),
                 axis_sizes={"tp": 1, "dp_out": 2, "dp_in": 2})
        step = make_train_step(CFG, opt, mesh,
                               dp_axis=("dp_out", "dp_in"))
        return opt, step

    @staticmethod
    def _residual_sum(state):
        return sum(float(np.asarray(r, np.float64).sum())
                   for r in state.residual)

    def test_flat4_to_hier22_to_flat2_resume_chain(self, rig, tmp_path,
                                                   devices8):
        """The three-layout chain on the int8 wire: train flat dp=4,
        resume on the hierarchical (2, 2) mesh (same world — BITWISE
        state restore, no reshard), train two more steps through the
        two-hop sync, then resume flat at dp=2 (world change — the
        error-feedback residuals sum-collapse onto new rank 0, sum
        preserved exactly) — with every loss inside the quantized
        continuation band of the uninterrupted flat run."""
        opt4, state, step4, params = rig("zero_int8", 4)
        for i in range(2):
            params, state, _ = step4(params, state, *batch(i))
        dir_a = tmp_path / "a"
        save_elastic_checkpoint(
            dir_a, 2, params=params, opt_state=state, optimizer=opt4,
            world_size=4, mesh_axes={"tp": 1})

        # hop 1 of the chain: flat save → HIERARCHICAL restore.  Same
        # world (2·2 = 4), so nothing reshards and the state is bitwise
        # — the layout change is invisible to the checkpoint.
        opt_h, step_h = self._hier_rig(devices8)
        r = restore_elastic_checkpoint(
            dir_a, optimizer=opt_h, world_size=4, mesh_axes={"tp": 1})
        assert r is not None and r.step == 2
        assert r.saved_world == 4 and not r.resharded
        tree_equal(r.params, params)
        for a, b in zip(jax.tree.leaves(state),
                        jax.tree.leaves(r.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        p_h, s_h = r.params, r.opt_state
        hier_losses = []
        for i in range(2, 4):
            p_h, s_h, loss = step_h(p_h, s_h, *batch(i))
            hier_losses.append(float(loss))
        res_sum_h = self._residual_sum(s_h)
        dir_b = tmp_path / "b"
        save_elastic_checkpoint(
            dir_b, 4, params=p_h, opt_state=s_h, optimizer=opt_h,
            world_size=4, mesh_axes={"tp": 1})

        # hop 2: hierarchical save → FLAT dp=2 restore.  The world
        # changes (4 → 2), so the full state reshards through the one
        # padded_total formula and the per-rank residuals collapse
        # onto new rank 0 — error SUM preserved exactly.
        opt2, _, step2, _ = rig("zero_int8", 2)
        r2 = restore_elastic_checkpoint(
            dir_b, optimizer=opt2, world_size=2, mesh_axes={"tp": 1})
        assert r2 is not None and r2.step == 4
        assert r2.saved_world == 4 and r2.resharded
        tree_equal(r2.params, p_h)
        np.testing.assert_allclose(self._residual_sum(r2.opt_state),
                                   res_sum_h, rtol=1e-6)

        p_f, s_f = r2.params, r2.opt_state
        flat_losses = []
        for i in range(4, 6):
            p_f, s_f, loss = step2(p_f, s_f, *batch(i))
            flat_losses.append(float(loss))

        # the whole chain continues the uninterrupted flat-dp=4
        # trajectory inside the int8 band — layout changes cost only
        # quantization-order noise, never a restart from scratch
        _, ref = oracle(rig, "zero_int8", 4)
        np.testing.assert_allclose(hier_losses + flat_losses, ref[2:6],
                                   rtol=0.05)

    def test_three_level_checkpoint_restores_flat_and_two_level(
            self, rig, tmp_path, devices8):
        """A checkpoint saved on the (dcn, dp_out, dp_in) = (2, 2, 2)
        mesh restores into a flat dp=8 optimizer AND a two-level
        (2, 4) one bitwise, with no special case: shard ownership is
        the flat chunk-per-rank layout under ONE ``padded_total``
        formula at every hop depth, and the index records only the dp
        world."""
        mesh3 = Mesh(np.array(devices8).reshape(2, 2, 2, 1),
                     ("dcn", "dp_out", "dp_in", "tp"))
        axes3 = ("dcn", "dp_out", "dp_in")
        sizes3 = {"tp": 1, "dcn": 2, "dp_out": 2, "dp_in": 2}
        params = init_params(CFG, jax.random.PRNGKey(0))
        opt3 = DistributedFusedAdam(
            lr=1e-2, weight_decay=0.01, dp_axes=axes3,
            grad_sync_dtype="int8")
        state = opt3.init(params, world_size=8,
                          param_specs=param_specs(CFG), axis_sizes=sizes3)
        step3 = make_train_step(CFG, opt3, mesh3, dp_axis=axes3)
        params, state, _ = step3(params, state, *batch(0))
        save_elastic_checkpoint(
            tmp_path, 1, params=params, opt_state=state, optimizer=opt3,
            world_size=8, mesh_axes={"tp": 1})

        # flat dp=8 restore: same world, bitwise, no reshard
        opt_f, _, step_f, _ = rig("zero_int8", 8)
        r = restore_elastic_checkpoint(
            tmp_path, optimizer=opt_f, world_size=8, mesh_axes={"tp": 1})
        assert r is not None and r.saved_world == 8 and not r.resharded
        for a, b in zip(jax.tree.leaves(state),
                        jax.tree.leaves(r.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        _, _, loss = step_f(r.params, r.opt_state, *batch(1))
        assert np.isfinite(float(loss))

        # two-level (2, 4) restore: same world, bitwise, no reshard
        mesh2 = Mesh(np.array(devices8).reshape(2, 4, 1),
                     ("dp_out", "dp_in", "tp"))
        opt2 = DistributedFusedAdam(
            lr=1e-2, weight_decay=0.01, dp_axes=("dp_out", "dp_in"),
            grad_sync_dtype="int8")
        opt2.init(params, world_size=8, param_specs=param_specs(CFG),
                  axis_sizes={"tp": 1, "dp_out": 2, "dp_in": 4})
        r2 = restore_elastic_checkpoint(
            tmp_path, optimizer=opt2, world_size=8, mesh_axes={"tp": 1})
        assert r2 is not None and not r2.resharded
        for a, b in zip(jax.tree.leaves(state),
                        jax.tree.leaves(r2.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        step2 = make_train_step(CFG, opt2, mesh2,
                                dp_axis=("dp_out", "dp_in"))
        _, _, loss2 = step2(r2.params, r2.opt_state, *batch(1))
        assert np.isfinite(float(loss2))

    def test_hier_checkpoint_restores_flat_without_special_case(
            self, rig, tmp_path, devices8):
        """A checkpoint SAVED on the hierarchical mesh restores into a
        flat same-world optimizer bitwise: the index records only the
        dp world and model axes — the (outer, inner) split never leaks
        into the format."""
        opt_h, step_h = self._hier_rig(devices8)
        params = init_params(CFG, jax.random.PRNGKey(0))
        state = opt_h.init(params, world_size=4,
                           param_specs=param_specs(CFG),
                           axis_sizes={"tp": 1, "dp_out": 2, "dp_in": 2})
        params, state, _ = step_h(params, state, *batch(0))
        save_elastic_checkpoint(
            tmp_path, 1, params=params, opt_state=state, optimizer=opt_h,
            world_size=4, mesh_axes={"tp": 1})
        opt4, _, step4, _ = rig("zero_int8", 4)
        r = restore_elastic_checkpoint(
            tmp_path, optimizer=opt4, world_size=4, mesh_axes={"tp": 1})
        assert r is not None and not r.resharded
        for a, b in zip(jax.tree.leaves(state),
                        jax.tree.leaves(r.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        p, s, loss = step4(r.params, r.opt_state, *batch(1))
        assert np.isfinite(float(loss))


# ------------------------------------------------------------- pod chaos
class TestPodChaos:
    def test_kill_one_host_of_n_then_elastic_resume(self, rig, tmp_path):
        """Host 2 of 4 dies HARD at step 2 (no save, no drain); the
        supervisor reschedules the survivors at dp=2 and the run
        resumes from the last COMPLETE step dir, resharded."""
        opt4, state, step4, params = rig("zero", 4)
        monkey = ChaosMonkey(ChaosPlan.make(kill_at={2: 2}))
        ctl = ElasticRunController(tmp_path, opt4, world_size=4,
                                   mesh_axes={"tp": 1}, chaos=monkey,
                                   rank=2)
        with pytest.raises(ChaosHostKilled) as ei:
            for i in range(4):
                ctl.on_step(i)
                params, state, _ = step4(params, state, *batch(i))
                ctl.save(i + 1, params, state)
        assert ei.value.code == resilience.EXIT_KILLED
        assert monkey.injected.get("kill:2") == 1

        opt2, _, step2, _ = rig("zero", 2)
        r = restore_elastic_checkpoint(
            tmp_path, optimizer=opt2, world_size=2, mesh_axes={"tp": 1})
        assert r.step == 2 and r.resharded and r.saved_world == 4
        p, s, loss = step2(r.params, r.opt_state, *batch(2))
        assert np.isfinite(float(loss))

    def test_kill_plan_is_per_rank(self, rig, tmp_path):
        """Only the planned host dies: rank 0's controller sails past
        the step that kills rank 2."""
        opt4, state, step4, params = rig("zero", 4)
        monkey = ChaosMonkey(ChaosPlan.make(kill_at={2: 1}))
        ctl = ElasticRunController(tmp_path, opt4, world_size=4,
                                   mesh_axes={"tp": 1}, chaos=monkey,
                                   rank=0)
        for i in range(3):
            ctl.on_step(i)  # never raises: this "host" is rank 0
        assert not monkey.injected

    def test_wedged_collective_rank_trips_watchdog(self, devices8):
        """The wedge-a-collective-site fault: rank 1 stalls INSIDE the
        compiled step (io_callback before the grad/loss sync), so rank
        0 blocks device-side in the collective.  Only the host-side
        watchdog can see it — and does, while the step is still hung."""
        mesh = Mesh(np.array(devices8[:2]).reshape(2, 1), ("dp", "tp"))
        guard = StepGuard()
        monkey = ChaosMonkey(ChaosPlan.make(
            wedge_collective_rank=1, wedge_collective_at_step=1,
            wedge_collective_seconds=1.5))
        opt = FusedAdam(lr=1e-2)
        params = init_params(CFG, jax.random.PRNGKey(0))
        state = opt.init(params)
        step = make_train_step(CFG, opt, mesh, step_guard=guard,
                               chaos=monkey)
        gs = guard.init()
        # step 0: off-plan — compiles, runs fast
        params, state, gs, loss = step(params, state, gs, *batch(0))
        assert np.isfinite(float(loss))

        fired = []
        wd = StepWatchdog(0.4, poll_sec=0.05, on_fire=fired.append)
        with wd:
            wd.beat(1)
            t0 = time.monotonic()
            params, state, gs, loss = step(params, state, gs, *batch(1))
            assert np.isfinite(float(loss))
            dt_hung = time.monotonic() - t0
        assert monkey.injected.get("wedge_collective") == 1
        assert dt_hung >= 1.0, "the wedged rank did not hold the step"
        assert fired and fired[0]["step"] == 1
        assert fired[0]["exit_code"] == resilience.EXIT_WEDGED

    def test_host_side_step_wedge(self):
        """The whole-step dispatch wedge (dead tunnel shape): the plan
        sleeps at exactly the armed step."""
        monkey = ChaosMonkey(ChaosPlan.make(wedge_step_at=2,
                                            wedge_step_seconds=0.2))
        assert monkey.maybe_wedge_step(1) == 0.0
        t0 = time.monotonic()
        assert monkey.maybe_wedge_step(2) == 0.2
        assert time.monotonic() - t0 >= 0.2
        assert monkey.injected.get("wedge_step") == 1


# ---------------------------------------------------------- step watchdog
class _StubCheckpointer:
    def __init__(self, gate=None):
        self.calls = 0
        self._gate = gate

    def wait_until_finished(self):
        self.calls += 1
        if self._gate is not None:
            self._gate.wait()


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


class TestStepWatchdog:
    def test_fires_after_deadline_and_drains(self):
        ck = _StubCheckpointer()
        fired = []
        with StepWatchdog(0.2, checkpointer=ck, poll_sec=0.05,
                          on_fire=fired.append) as wd:
            wd.beat(5)
            assert _wait_for(lambda: wd.fired)
        assert fired[0]["step"] == 5
        assert fired[0]["drain"] == "drained" and ck.calls == 1
        assert fired[0]["exit_code"] == resilience.EXIT_WEDGED

    def test_heartbeat_staves_off_firing(self):
        with StepWatchdog(0.5, poll_sec=0.05, on_fire=lambda i: None) as wd:
            for i in range(8):
                wd.beat(i)
                time.sleep(0.1)
            assert not wd.fired

    def test_first_interval_gets_compile_grace(self):
        """Unbeaten, the FIRST deadline applies (jit compile); the
        steady-state deadline takes over after the first beat."""
        with StepWatchdog(0.15, first_deadline_sec=1.0, poll_sec=0.05,
                          on_fire=lambda i: None) as wd:
            time.sleep(0.4)
            assert not wd.fired  # 0.4 < the 1.0 first allowance
            assert _wait_for(lambda: wd.fired, timeout=2.0)

    def test_per_beat_deadline_override(self):
        """``beat(step, deadline=...)`` loosens ONE interval (the
        loop's first-step compile grace) without touching the rest."""
        with StepWatchdog(0.15, first_deadline_sec=10.0, poll_sec=0.05,
                          on_fire=lambda i: None) as wd:
            wd.beat(0, deadline=1.0)
            time.sleep(0.4)
            assert not wd.fired  # inside the per-beat override
            wd.beat(1)
            assert _wait_for(lambda: wd.fired, timeout=2.0)
            assert wd.fire_info["step"] == 1

    def test_drain_is_bounded(self):
        """A wedged filesystem must not wedge the watchdog's own exit:
        the drain runs on a helper thread with a timeout."""
        gate = threading.Event()  # never set: the flush hangs forever
        ck = _StubCheckpointer(gate=gate)
        fired = []
        with StepWatchdog(0.1, checkpointer=ck, poll_sec=0.05,
                          drain_timeout_sec=0.2,
                          on_fire=fired.append) as wd:
            assert _wait_for(lambda: wd.fired)
        gate.set()
        assert fired[0]["drain"] == "drain_timeout"

    def test_drain_routes_through_preemption_guard(self):
        """With a PreemptionHandler the watchdog's drain takes the
        re-entrancy-guarded path."""
        ck = _StubCheckpointer()
        pre = resilience.PreemptionHandler()
        fired = []
        with StepWatchdog(0.1, checkpointer=ck, preemption=pre,
                          poll_sec=0.05, on_fire=fired.append) as wd:
            assert _wait_for(lambda: wd.fired)
        assert fired[0]["drain"] == "drained" and ck.calls == 1

    def test_deadline_validation(self):
        with pytest.raises(ValueError):
            StepWatchdog(0.0)

    def test_restart_backoff_contract(self):
        """Deterministic per (seed, attempt), full-jitter exponential,
        capped."""
        a = [restart_backoff(k, base=2.0, cap=30.0, seed=7)
             for k in range(6)]
        b = [restart_backoff(k, base=2.0, cap=30.0, seed=7)
             for k in range(6)]
        assert a == b  # deterministic schedule
        for k, v in enumerate(a):
            assert 0.0 <= v <= min(30.0, 2.0 * 2 ** k)
        assert restart_backoff(3, seed=1) != restart_backoff(3, seed=2)
        with pytest.raises(ValueError):
            restart_backoff(-1)


# ------------------------------------------------- restore validation
class TestElasticValidation:
    def test_empty_dir_is_fresh_start(self, rig, tmp_path):
        opt, _, _, _ = rig("zero", 2)
        assert restore_elastic_checkpoint(
            tmp_path, optimizer=opt, world_size=2,
            mesh_axes={"tp": 1}) is None

    def test_kind_mismatch_refused(self, rig, tmp_path):
        """A ZeRO checkpoint cannot restore into a replicated optimizer
        (and vice versa): the --zero flag must agree."""
        opt, state, _, params = rig("zero", 2)
        save_elastic_checkpoint(tmp_path, 1, params=params,
                                opt_state=state, optimizer=opt,
                                world_size=2, mesh_axes={"tp": 1})
        with pytest.raises(ValueError, match="kind"):
            restore_elastic_checkpoint(
                tmp_path, optimizer=FusedAdam(lr=1e-2), world_size=2,
                mesh_axes={"tp": 1})

    def test_model_axes_mismatch_refused(self, rig, tmp_path):
        """Only dp is elastic: a tp change between save and resume is a
        state-layout change and fails loudly."""
        opt, state, _, params = rig("zero", 2)
        save_elastic_checkpoint(tmp_path, 1, params=params,
                                opt_state=state, optimizer=opt,
                                world_size=2, mesh_axes={"tp": 1})
        with pytest.raises(ValueError, match="data-parallel-only"):
            restore_elastic_checkpoint(tmp_path, optimizer=opt,
                                       world_size=2, mesh_axes={"tp": 2})

    def test_non_elastic_dir_refused(self, tmp_path):
        io.save_sharded_checkpoint(tmp_path / "step_00000001",
                                   {"x": np.zeros(3)}, 0, 1)
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        opt.init({"w": jnp.zeros(8)}, world_size=2)
        with pytest.raises(ValueError, match="elastic"):
            restore_elastic_checkpoint(tmp_path, optimizer=opt,
                                       world_size=2)

    def test_optimizer_world_mismatch_refused(self, rig, tmp_path):
        """restore() refuses an optimizer init'd for a different world
        than the live one — the bucket plan would disagree with the
        resharded state at first trace."""
        opt4, state, _, params = rig("zero", 4)
        save_elastic_checkpoint(tmp_path, 1, params=params,
                                opt_state=state, optimizer=opt4,
                                world_size=4, mesh_axes={"tp": 1})
        with pytest.raises(ValueError, match="init"):
            restore_elastic_checkpoint(tmp_path, optimizer=opt4,
                                       world_size=2, mesh_axes={"tp": 1})

    def test_scaler_guard_rng_ride_rank0(self, rig, tmp_path):
        """The dp-replicated pieces of the FULL train state — scaler,
        StepGuard counts, RNG tracker — round-trip through the elastic
        dir (and survive a reshard, which never touches rank 0's
        payload)."""
        opt4, state, _, params = rig("zero", 4)
        guard = StepGuard(max_consecutive_bad=5)
        gs = guard.update(guard.init(), jnp.bool_(False))
        rng_sd = {"states": {"dropout": np.arange(4, dtype=np.uint32)},
                  "counts": {"dropout": 3}}
        scaler_sd = {"loss_scale": np.float32(1024.0), "growth": 7}
        save_elastic_checkpoint(
            tmp_path, 2, params=params, opt_state=state, optimizer=opt4,
            world_size=4, mesh_axes={"tp": 1},
            scaler_state=scaler_sd, guard_state=guard.state_dict(gs),
            rng_state=rng_sd)
        opt2, _, _, _ = rig("zero", 2)
        r = restore_elastic_checkpoint(tmp_path, optimizer=opt2,
                                       world_size=2, mesh_axes={"tp": 1})
        assert r.resharded
        back = guard.load_state_dict(
            {k: int(np.asarray(v)) for k, v in r.guard.items()})
        assert guard.state_dict(back) == guard.state_dict(gs)
        assert float(np.asarray(r.scaler["loss_scale"])) == 1024.0
        np.testing.assert_array_equal(
            np.asarray(r.rng["states"]["dropout"]),
            rng_sd["states"]["dropout"])
        assert int(np.asarray(r.rng["counts"]["dropout"])) == 3

    def test_controller_prunes_bounded_disk(self, rig, tmp_path):
        opt, state, step, params = rig("zero", 2)
        ctl = ElasticRunController(tmp_path, opt, world_size=2,
                                   mesh_axes={"tp": 1}, keep=2)
        for i in range(5):
            ctl.save(i + 1, params, state)
        left = sorted(p.name for p in tmp_path.glob("step_*"))
        assert left == ["step_00000004", "step_00000005"]
        r = ctl.restore()
        assert r.step == 5
