"""Test configuration: run everything on a virtual 8-device CPU platform.

Mirrors the reference's test strategy (SURVEY.md §4): distributed
correctness is established by comparing a parallel run against a
single-device oracle.  Multi-chip hardware isn't needed —
``xla_force_host_platform_device_count=8`` gives 8 CPU devices for
``jax.sharding.Mesh`` tests.

Tiers (the reference's L0/L1 split):

- quick: ``pytest -m "not slow" tests/`` — unit + small parity tests,
  ~2:30 on this (1-core) box.  Run on every change.
- full:  ``pytest tests/`` — adds the compiled e2e/model-level parity
  workloads (GPT 3D/MoE/ResNet trainers, ZeRO resharding + tp
  composition, HLO memory regressions, 2-process jax.distributed
  tests) and every per-test ``slow`` mark; 456 tests, ~20 min on this
  box.  CI / pre-commit.

Anything >~15 s compiled carries ``@pytest.mark.slow`` (file-level
``pytestmark`` for whole-file e2e suites).
"""

import os

# Must be set before the first JAX backend call.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# Neutralize the axon TPU-tunnel sitecustomize for tests.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

# Persistent compilation cache: the suite's wall time is dominated by
# XLA:CPU compiles (this box has one core), and the same programs
# recompile on every run.  First run pays; re-runs hit the cache.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test; `-m 'not slow'` gives the quick tier"
    )


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("need 8 virtual devices")
    return devs[:8]
