"""Contrib op tests — mirrors apex/contrib/test/<feature>/ parity-vs-
unfused pattern, with torch CPU as the oracle where applicable."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.group_norm import GroupNorm, cuda_group_norm_nhwc_forward
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.fmha import fmha
from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn
from apex_tpu.contrib.xentropy import softmax_xentropy


class TestXentropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_matches_torch(self, smoothing):
        rng = np.random.RandomState(0)
        logits = rng.randn(16, 10).astype(np.float32)
        labels = rng.randint(0, 10, size=(16,))
        out = softmax_xentropy(jnp.asarray(logits), jnp.asarray(labels), smoothing)
        ref = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels), label_smoothing=smoothing, reduction="none"
        )
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_grad_matches_torch(self, smoothing):
        rng = np.random.RandomState(1)
        logits = rng.randn(8, 6).astype(np.float32)
        labels = rng.randint(0, 6, size=(8,))

        g = jax.grad(lambda l: jnp.mean(softmax_xentropy(l, jnp.asarray(labels), smoothing)))(
            jnp.asarray(logits)
        )
        t = torch.tensor(logits, requires_grad=True)
        torch.nn.functional.cross_entropy(
            t, torch.tensor(labels), label_smoothing=smoothing
        ).backward()
        np.testing.assert_allclose(np.asarray(g), t.grad.numpy(), rtol=1e-4, atol=1e-6)


class TestGroupNorm:
    def test_matches_torch_group_norm(self):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 4, 4, 8).astype(np.float32)  # NHWC
        w = rng.rand(8).astype(np.float32) + 0.5
        b = rng.randn(8).astype(np.float32)
        out = cuda_group_norm_nhwc_forward(jnp.asarray(x), 4, jnp.asarray(w), jnp.asarray(b))
        ref = torch.nn.functional.group_norm(
            torch.tensor(x).permute(0, 3, 1, 2), 4, torch.tensor(w), torch.tensor(b)
        ).permute(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4, atol=1e-5)

    def test_silu_fusion(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(1, 2, 2, 4).astype(np.float32))
        base = cuda_group_norm_nhwc_forward(x, 2)
        silu = cuda_group_norm_nhwc_forward(x, 2, act="silu")
        np.testing.assert_allclose(
            np.asarray(silu), np.asarray(base) * (1 / (1 + np.exp(-np.asarray(base)))), rtol=1e-5
        )

    def test_module(self):
        m = GroupNorm(num_groups=2, num_channels=8)
        x = jnp.ones((1, 3, 3, 8))
        p = m.init(jax.random.PRNGKey(0), x)
        assert m.apply(p, x).shape == x.shape


class TestFocalLoss:
    def test_reduces_and_is_finite(self):
        rng = np.random.RandomState(4)
        logits = jnp.asarray(rng.randn(32, 10).astype(np.float32))
        targets = jnp.asarray(rng.randint(-1, 11, size=(32,)))
        loss = focal_loss(logits, targets, jnp.float32(5.0), 10)
        assert np.isfinite(float(loss))

    def test_matches_manual_sigmoid_focal(self):
        # single positive example, compare vs hand formula
        logits = jnp.asarray([[2.0, -1.0]])
        targets = jnp.asarray([1])  # class id 1 → one-hot index 0
        loss = focal_loss(logits, targets, jnp.float32(1.0), 2, alpha=0.25, gamma=2.0)
        x = np.array([2.0, -1.0])
        onehot = np.array([1.0, 0.0])
        p = 1 / (1 + np.exp(-x))
        ce = np.logaddexp(0, x) - x * onehot
        pt = p * onehot + (1 - p) * (1 - onehot)
        at = 0.25 * onehot + 0.75 * (1 - onehot)
        ref = (at * (1 - pt) ** 2 * ce).sum()
        np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


class TestIndexMul2d:
    def test_matches_reference(self):
        rng = np.random.RandomState(5)
        in1 = rng.randn(10, 4).astype(np.float32)
        idx = rng.randint(0, 10, size=(6,))
        in2 = rng.randn(6, 4).astype(np.float32)
        out = index_mul_2d(jnp.asarray(in1), jnp.asarray(in2), jnp.asarray(idx))
        np.testing.assert_allclose(np.asarray(out), in1[idx] * in2, rtol=1e-6)

    def test_grad(self):
        in1 = jnp.ones((5, 3))
        idx = jnp.asarray([0, 0, 2])
        in2 = jnp.full((3, 3), 2.0)
        g = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
        expected = np.zeros((5, 3))
        expected[0] = 4.0  # two uses
        expected[2] = 2.0
        np.testing.assert_allclose(np.asarray(g), expected)


class TestFMHA:
    def test_padding_mask(self):
        rng = np.random.RandomState(6)
        B, S, H, D = 2, 8, 2, 4
        qkv = jnp.asarray(rng.randn(B, S, 3, H, D).astype(np.float32))
        mask = jnp.asarray(np.array([[True] * 6 + [False] * 2, [True] * 8]))
        out = fmha(qkv, key_padding_mask=mask)
        assert out.shape == (B, S, H, D)
        # masked keys must not influence rows: perturb masked positions
        qkv2 = qkv.at[0, 6:].set(99.0)
        out2 = fmha(qkv2, key_padding_mask=mask)
        np.testing.assert_allclose(
            np.asarray(out[0, :6]), np.asarray(out2[0, :6]), rtol=1e-4, atol=1e-5
        )

    def test_no_mask_uses_flash(self):
        rng = np.random.RandomState(7)
        qkv = jnp.asarray(rng.randn(1, 16, 3, 2, 4).astype(np.float32))
        out = fmha(qkv, causal=True)
        assert out.shape == (1, 16, 2, 4)

    @pytest.mark.parametrize("causal", [True, False])
    def test_padded_parity_vs_dense_oracle(self, causal):
        from apex_tpu.ops.attention import mha_reference

        rng = np.random.RandomState(8)
        B, S, H, D = 2, 32, 2, 8
        qkv = jnp.asarray(rng.randn(B, S, 3, H, D).astype(np.float32))
        mask = jnp.asarray(np.array([[True] * 32, [True] * 19 + [False] * 13]))
        out = fmha(qkv, key_padding_mask=mask, causal=causal)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        ref = mha_reference(q, k, v, causal=causal, kv_mask=mask).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref[0]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out[1, :19]), np.asarray(ref[1, :19]),
                                   rtol=1e-4, atol=1e-5)
        # padded query rows are zeroed (packed-varlen semantics)
        np.testing.assert_allclose(np.asarray(out[1, 19:]), 0.0, atol=1e-6)

    def test_padded_grads_flow(self):
        rng = np.random.RandomState(9)
        qkv = jnp.asarray(rng.randn(1, 16, 3, 2, 4).astype(np.float32))
        mask = jnp.asarray(np.array([[True] * 10 + [False] * 6]))
        g = jax.grad(lambda x: jnp.sum(fmha(x, key_padding_mask=mask) ** 2))(qkv)
        assert bool(jnp.all(jnp.isfinite(g)))
        # padded positions get zero gradient through q (their rows are zeroed)
        np.testing.assert_allclose(np.asarray(g[0, 10:, 0]), 0.0, atol=1e-6)


class TestMultiheadAttn:
    @pytest.mark.slow
    def test_self_attn_shapes_and_norm_add(self):
        m = SelfMultiheadAttn(hidden_size=16, num_heads=4, include_norm_add=True, dropout=0.0)
        x = jnp.ones((8, 2, 16))
        p = m.init(jax.random.PRNGKey(0), x, train=False)
        out = m.apply(p, x, train=False)
        assert out.shape == x.shape

    def test_encdec_shapes(self):
        m = EncdecMultiheadAttn(hidden_size=16, num_heads=4, dropout=0.0)
        q = jnp.ones((6, 2, 16))
        k = jnp.ones((10, 2, 16))
        p = m.init(jax.random.PRNGKey(0), q, k, train=False)
        out = m.apply(p, q, k, train=False)
        assert out.shape == q.shape

    @pytest.mark.slow
    def test_self_attn_matches_torch_mha(self):
        """Parity vs torch.nn.MultiheadAttention (the reference's own test
        pattern in contrib/test/multihead_attn)."""
        H, nh, S, B = 8, 2, 5, 3
        rng = np.random.RandomState(8)
        x = rng.randn(S, B, H).astype(np.float32)

        m = SelfMultiheadAttn(hidden_size=H, num_heads=nh, dropout=0.0)
        p = m.init(jax.random.PRNGKey(0), jnp.asarray(x), train=False)

        tm = torch.nn.MultiheadAttention(H, nh, bias=True)
        sd = tm.state_dict()
        sd["in_proj_weight"] = torch.tensor(np.asarray(p["params"]["input_weights"]))
        sd["in_proj_bias"] = torch.tensor(np.asarray(p["params"]["input_biases"]))
        sd["out_proj.weight"] = torch.tensor(np.asarray(p["params"]["output_weights"]))
        sd["out_proj.bias"] = torch.tensor(np.asarray(p["params"]["output_biases"]))
        tm.load_state_dict(sd)
        ref, _ = tm(torch.tensor(x), torch.tensor(x), torch.tensor(x))

        out = m.apply(p, jnp.asarray(x), train=False)
        np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(), rtol=1e-3, atol=1e-4)

    def test_self_attn_key_padding_mask_matches_torch(self):
        """key_padding_mask (True = PAD, torch polarity) actually masks."""
        H, nh, S, B = 8, 2, 6, 2
        rng = np.random.RandomState(9)
        x = rng.randn(S, B, H).astype(np.float32)
        pad = np.zeros((B, S), bool)
        pad[1, 4:] = True  # last two positions of batch 1 are padding

        m = SelfMultiheadAttn(hidden_size=H, num_heads=nh, dropout=0.0)
        p = m.init(jax.random.PRNGKey(0), jnp.asarray(x), train=False)

        tm = torch.nn.MultiheadAttention(H, nh, bias=True)
        sd = tm.state_dict()
        sd["in_proj_weight"] = torch.tensor(np.asarray(p["params"]["input_weights"]))
        sd["in_proj_bias"] = torch.tensor(np.asarray(p["params"]["input_biases"]))
        sd["out_proj.weight"] = torch.tensor(np.asarray(p["params"]["output_weights"]))
        sd["out_proj.bias"] = torch.tensor(np.asarray(p["params"]["output_biases"]))
        tm.load_state_dict(sd)
        ref, _ = tm(torch.tensor(x), torch.tensor(x), torch.tensor(x),
                    key_padding_mask=torch.tensor(pad))

        out = m.apply(p, jnp.asarray(x), jnp.asarray(pad), train=False)
        # valid rows only (torch zeroes nothing; padded query rows attend
        # to valid keys in both implementations)
        np.testing.assert_allclose(np.asarray(out[:4, 1]), ref.detach().numpy()[:4, 1],
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(out[:, 0]), ref.detach().numpy()[:, 0],
                                   rtol=1e-3, atol=1e-4)

    def test_encdec_norm_add_and_bias(self):
        """include_norm_add (pre-LN + residual) and bias parity with the
        reference encdec module options (encdec_multihead_attn.py:27-63)."""
        m = EncdecMultiheadAttn(hidden_size=16, num_heads=4, dropout=0.0,
                                use_bias=True, include_norm_add=True)
        rng = np.random.RandomState(12)
        q = jnp.asarray(rng.randn(6, 2, 16).astype(np.float32))
        k = jnp.asarray(rng.randn(10, 2, 16).astype(np.float32))
        p = m.init(jax.random.PRNGKey(0), q, k, train=False)
        names = set(p["params"].keys())
        assert {"lyr_nrm_gamma_weights", "lyr_nrm_beta_weights",
                "q_biases", "kv_biases", "output_biases"} <= names
        out = m.apply(p, q, k, train=False)
        assert out.shape == q.shape
        # with zero-init biases and unit LN the residual shows up: output
        # minus residual equals the plain (norm-applied) attention output
        m0 = EncdecMultiheadAttn(hidden_size=16, num_heads=4, dropout=0.0)
        p0 = {"params": {n: p["params"][n] for n in
                         ("q_weights", "kv_weights", "output_weights")}}
        from apex_tpu.normalization import fused_layer_norm_affine
        qn = fused_layer_norm_affine(
            q, p["params"]["lyr_nrm_gamma_weights"],
            p["params"]["lyr_nrm_beta_weights"], (16,), 1e-5)
        base = m0.apply(p0, qn, k, train=False)
        np.testing.assert_allclose(np.asarray(out - q), np.asarray(base),
                                   rtol=1e-4, atol=1e-5)

    def test_mask_softmax_dropout_func(self):
        """fast_mask_softmax_dropout_func parity vs plain softmax oracle,
        byte-mask and additive-mask modes (reference
        mask_softmax_dropout_func.py)."""
        from apex_tpu.contrib.multihead_attn import fast_mask_softmax_dropout_func

        B, nh, Sq, Sk = 2, 3, 4, 5
        rng = np.random.RandomState(13)
        scores = jnp.asarray(rng.randn(B * nh, Sq, Sk).astype(np.float32))
        pad = np.zeros((B, Sk), np.uint8)
        pad[1, 3:] = 1

        out = fast_mask_softmax_dropout_func(False, nh, scores, jnp.asarray(pad), False, 0.3)
        ref = np.asarray(scores, np.float64).copy().reshape(B, nh, Sq, Sk)
        ref[1, :, :, 3:] = -1e9
        ref = torch.softmax(torch.tensor(ref), dim=-1).numpy().reshape(B * nh, Sq, Sk)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-6)

        add = np.where(pad, -30000.0, 0.0).astype(np.float32)
        out2 = fast_mask_softmax_dropout_func(False, nh, scores, jnp.asarray(add), True, 0.0)
        np.testing.assert_allclose(np.asarray(out2), ref, rtol=1e-4, atol=1e-6)

        # training dropout: rows still sum to ~1/keep in expectation, and
        # an explicit key is required
        out3 = fast_mask_softmax_dropout_func(
            True, nh, scores, None, False, 0.5, key=jax.random.PRNGKey(0))
        assert out3.shape == scores.shape
        with pytest.raises(ValueError):
            fast_mask_softmax_dropout_func(True, nh, scores, None, False, 0.5)

    def test_legacy_contrib_optimizer_exports(self):
        """Reference contrib/optimizers re-exports deprecated
        FP16_Optimizer/FusedAdam/FusedLAMB; ours alias the maintained
        implementations."""
        import apex_tpu.contrib.optimizers as co
        from apex_tpu.fp16_utils import FP16_Optimizer as RealFP16
        from apex_tpu.optimizers import FusedAdam as RealAdam
        from apex_tpu.optimizers import FusedLAMB as RealLamb

        assert co.FusedAdam is RealAdam
        assert co.FusedLAMB is RealLamb
        assert co.FP16_Optimizer is RealFP16

    def test_encdec_key_padding_mask_blocks_keys(self):
        m = EncdecMultiheadAttn(hidden_size=16, num_heads=4, dropout=0.0)
        rng = np.random.RandomState(10)
        q = jnp.asarray(rng.randn(6, 2, 16).astype(np.float32))
        k = jnp.asarray(rng.randn(10, 2, 16).astype(np.float32))
        pad = np.zeros((2, 10), bool)
        pad[0, 7:] = True
        p = m.init(jax.random.PRNGKey(0), q, k, train=False)
        out = m.apply(p, q, k, jnp.asarray(pad), train=False)
        # perturbing padded encoder keys must not change the output
        k2 = k.at[7:, 0].set(55.0)
        out2 = m.apply(p, q, k2, jnp.asarray(pad), train=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)


class TestFMHAVarlen:
    """Packed cu_seqlens interface (reference FMHAFun call shape)."""

    @pytest.mark.slow
    def test_matches_per_sequence_oracle(self):
        from apex_tpu.contrib.fmha import fmha_varlen
        from apex_tpu.ops.attention import mha_reference

        rng = np.random.RandomState(11)
        lens = [7, 12, 3]
        H, D, max_s = 2, 8, 16
        total = sum(lens)
        qkv = jnp.asarray(rng.randn(total, 3, H, D).astype(np.float32))
        cu = jnp.asarray(np.cumsum([0] + lens).astype(np.int32))

        out = fmha_varlen(qkv, cu, max_s)
        assert out.shape == (total, H, D)

        off = 0
        for L in lens:
            sl = qkv[off:off + L]
            q, k, v = (sl[:, i].transpose(1, 0, 2)[None] for i in range(3))
            ref = mha_reference(q, k, v, causal=False)[0].transpose(1, 0, 2)
            np.testing.assert_allclose(np.asarray(out[off:off + L]), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)
            off += L

    @pytest.mark.slow
    def test_causal_and_grads(self):
        from apex_tpu.contrib.fmha import fmha_varlen

        rng = np.random.RandomState(12)
        lens = [5, 9]
        qkv = jnp.asarray(rng.randn(sum(lens), 3, 2, 4).astype(np.float32))
        cu = jnp.asarray(np.cumsum([0] + lens).astype(np.int32))
        out = fmha_varlen(qkv, cu, 16, causal=True)
        assert out.shape == (sum(lens), 2, 4)
        g = jax.grad(lambda x: jnp.sum(fmha_varlen(x, cu, 16) ** 2))(qkv)
        assert bool(jnp.all(jnp.isfinite(g)))
        # tokens of sequence 0 must not receive grads from sequence 1's loss
        g0 = jax.grad(lambda x: jnp.sum(fmha_varlen(x, cu, 16)[5:] ** 2))(qkv)
        np.testing.assert_allclose(np.asarray(g0[:5]), 0.0, atol=1e-6)
