"""BERT tests — mirrors test_bert_minimal.py: TP parity + training
smoke with FusedLAMB (the reference's BERT pretraining pairing)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.bert import (
    BertConfig,
    bert_forward,
    bert_mlm_loss,
    init_params,
    param_specs,
)
from apex_tpu.optimizers import FusedLAMB

CFG = BertConfig(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_seq_len=16,
    compute_dtype=jnp.float32,
    checkpoint_layers=False,
)


@pytest.fixture
def batch():
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(2, 16)))
    pad = jnp.asarray(np.array([[True] * 16, [True] * 12 + [False] * 4]))
    return tokens, pad


@pytest.mark.slow
def test_forward_shapes(batch):
    tokens, pad = batch
    params = init_params(CFG, jax.random.PRNGKey(0))
    logits = bert_forward(params, tokens, pad_mask=pad, config=CFG)
    assert logits.shape == (16, 2, CFG.vocab_size)


def test_padding_mask_blocks_attention(batch):
    tokens, pad = batch
    params = init_params(CFG, jax.random.PRNGKey(0))
    base = bert_forward(params, tokens, pad_mask=pad, config=CFG)
    # perturb a padded position's token: valid positions must not change
    tokens2 = tokens.at[1, 14].set((int(tokens[1, 14]) + 5) % CFG.vocab_size)
    out2 = bert_forward(params, tokens2, pad_mask=pad, config=CFG)
    np.testing.assert_allclose(
        np.asarray(base[:12, 1]), np.asarray(out2[:12, 1]), rtol=1e-4, atol=1e-5
    )


@pytest.mark.slow
def test_tp_matches_single_device(batch, devices8):
    tokens, pad = batch
    params = init_params(CFG, jax.random.PRNGKey(0))
    ref = bert_forward(params, tokens, pad_mask=pad, config=CFG)

    mesh = Mesh(np.array(devices8[:4]), ("tp",))
    specs = param_specs(CFG)
    f = jax.shard_map(
        lambda p, t, m: bert_forward(p, t, pad_mask=m, config=CFG, axis_name="tp"),
        mesh=mesh,
        in_specs=(specs, P(), P()),
        out_specs=P(None, None, "tp"),
        check_vma=False,
    )
    out = f(params, tokens, pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_mlm_training_with_lamb_reduces_loss(batch):
    tokens, pad = batch
    params = init_params(CFG, jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    loss_mask = jnp.asarray((rng.rand(2, 16) < 0.3).astype(np.float32))
    targets = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(2, 16)))

    opt = FusedLAMB(lr=1e-2, weight_decay=0.01)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(bert_mlm_loss)(
            params, tokens, targets, loss_mask, CFG, pad_mask=pad
        )
        params, state = opt.update(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(12):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
