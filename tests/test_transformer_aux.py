"""Aux transformer subsystems: TP-aware GradScaler, microbatch
calculators (incl. rampup — mirrors test_microbatches.py), batch
samplers (test_batch_sampler.py), pipeline utils, fp16_utils."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    master_params_to_model_params,
    network_to_half,
    prep_param_lists,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer._data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)
from apex_tpu.transformer.amp import GradScaler
from apex_tpu.transformer.microbatches import build_num_microbatches_calculator
from apex_tpu.transformer.pipeline_parallel.utils import get_ltor_masks_and_position_ids


class TestGradScaler:
    def test_found_inf_syncs_across_tp(self, devices8):
        mesh = Mesh(np.array(devices8[:4]), ("tp",))
        scaler = GradScaler(init_scale=4.0, model_parallel_axes=("tp",))
        state = scaler.init()

        def f(g):
            # only rank 0's grads overflow; all ranks must agree
            out, finite = scaler.unscale(state, {"w": g})
            return jnp.asarray(finite, jnp.int32).reshape(1)

        g = jnp.asarray([np.inf, 1.0, 1.0, 1.0])  # rank 0 gets inf
        finite = jax.shard_map(
            f, mesh=mesh, in_specs=P("tp"), out_specs=P("tp"), check_vma=False
        )(g)
        assert np.asarray(finite).sum() == 0  # all ranks saw not-finite


class TestMicrobatches:
    def test_constant(self):
        c = build_num_microbatches_calculator(0, None, 64, 4, 2)
        assert c.get() == 8
        assert c.get_current_global_batch_size() == 64

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            build_num_microbatches_calculator(0, None, 30, 4, 2)

    def test_rampup(self):
        # start 16, +16 per increment, over 64 samples, target 64
        c = build_num_microbatches_calculator(0, [16, 16, 64], 64, 4, 1)
        assert c.get_current_global_batch_size() == 16
        assert c.get() == 4
        # num_increments = 3, samples_per_increment = 64/3 ≈ 21.33
        c.update(45, True)  # int(45/21.33) = 2 increments
        assert c.get_current_global_batch_size() == 48
        c.update(100, True)
        assert c.get_current_global_batch_size() == 64
        assert c.get() == 16


class TestBatchSamplers:
    def test_sequential_shards_by_rank(self):
        s0 = MegatronPretrainingSampler(20, 0, 2, data_parallel_rank=0, data_parallel_size=2)
        s1 = MegatronPretrainingSampler(20, 0, 2, data_parallel_rank=1, data_parallel_size=2)
        b0 = next(iter(s0))
        b1 = next(iter(s1))
        assert b0 == [0, 1]
        assert b1 == [2, 3]

    def test_sequential_resume(self):
        s = MegatronPretrainingSampler(20, 8, 2, 0, 2)
        assert next(iter(s)) == [8, 9]

    def test_random_sampler_deterministic_epoch(self):
        a = list(MegatronPretrainingRandomSampler(32, 0, 2, 0, 2))
        b = list(MegatronPretrainingRandomSampler(32, 0, 2, 0, 2))
        assert a == b
        assert all(len(x) == 2 for x in a)

    def test_random_sampler_rank_disjoint(self):
        a = {i for batch in MegatronPretrainingRandomSampler(32, 0, 2, 0, 2) for i in batch}
        b = {i for batch in MegatronPretrainingRandomSampler(32, 0, 2, 1, 2) for i in batch}
        assert not (a & b)


class TestLtorMasks:
    def test_basic_causal(self):
        data = jnp.asarray([[1, 2, 3, 0]])
        att, loss_mask, pos = get_ltor_masks_and_position_ids(data, eod_token=0, eod_mask_loss=True)
        assert att.shape == (1, 1, 4, 4)
        assert bool(att[0, 0, 0, 1])  # future masked
        assert not bool(att[0, 0, 1, 0])  # past visible
        np.testing.assert_allclose(np.asarray(loss_mask), [[1, 1, 1, 0]])
        np.testing.assert_allclose(np.asarray(pos), [[0, 1, 2, 3]])

    def test_reset_attention_mask(self):
        data = jnp.asarray([[5, 0, 6, 7]])  # EOD at position 1
        att, _, pos = get_ltor_masks_and_position_ids(
            data, eod_token=0, reset_attention_mask=True, reset_position_ids=True
        )
        # token 2 (new doc) must not attend to token 0 (previous doc)
        assert bool(att[0, 0, 2, 0])


class TestFp16Utils:
    def test_network_to_half_keeps_norms(self):
        params = {"dense": jnp.ones((2, 2)), "bn_scale": jnp.ones((2,))}
        half = network_to_half(params)
        assert half["dense"].dtype == jnp.bfloat16
        assert half["bn_scale"].dtype == jnp.float32

    def test_prep_param_lists_flat(self):
        params = {"a": jnp.ones((2, 2)), "b": jnp.ones((3,))}
        model, master = prep_param_lists(params, flat_master=True)
        assert master.shape == (7,)

    def test_master_to_model_roundtrip(self):
        model = {"w": jnp.ones((2,), jnp.bfloat16)}
        master = {"w": jnp.asarray([1.5, 2.5], jnp.float32)}
        out = master_params_to_model_params(model, master)
        assert out["w"].dtype == jnp.bfloat16

    def test_fp16_optimizer_end_to_end(self):
        params = {"w": jnp.asarray([1.0, 2.0], jnp.bfloat16)}
        opt = FP16_Optimizer(FusedAdam(lr=0.1), dynamic_loss_scale=True)
        state = opt.init(params)
        grads = {"w": jnp.asarray([0.1, 0.1], jnp.bfloat16)}
        scaled = opt.scale_loss(state, jnp.float32(1.0))
        assert float(scaled) == 2.0 ** 16
        # pretend grads are scaled
        sg = jax.tree.map(lambda g: g * state.scaler.loss_scale.astype(g.dtype), grads)
        new_params, state, finite = opt.step(sg, state, params)
        assert bool(finite)
        assert new_params["w"].dtype == jnp.bfloat16
        # overflow path: params unchanged
        bad = {"w": jnp.asarray([jnp.inf, 0.0], jnp.bfloat16)}
        p2, state, finite = opt.step(bad, state, new_params)
        assert not bool(finite)
        np.testing.assert_array_equal(
            np.asarray(p2["w"], np.float32), np.asarray(new_params["w"], np.float32)
        )

    def test_fp16_optimizer_state_dict_roundtrip(self):
        params = {"w": jnp.ones((3,), jnp.bfloat16)}
        opt = FP16_Optimizer(FusedAdam(lr=0.1), dynamic_loss_scale=True)
        state = opt.init(params)
        sd = opt.state_dict(state)
        state2 = opt.load_state_dict(sd)
        assert float(state2.scaler.loss_scale) == float(state.scaler.loss_scale)


class TestTransformerUtils:
    """Reference apex/transformer/utils.py surface."""

    def test_top_level_exports(self):
        import apex_tpu.transformer as t

        assert t.LayerType.encoder.value == 1
        assert t.AttnType.cross_attn.value == 2
        assert t.AttnMaskType.causal.value == 2
        assert t.ModelType.encoder_and_decoder.value == 2
        assert hasattr(t.utils, "divide")

    def test_split_gather_roundtrip(self, devices8):
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as P

        shard_map = jax.shard_map

        from apex_tpu.transformer.utils import (
            gather_split_1d_tensor,
            split_tensor_into_1d_equal_chunks,
        )

        x = jnp.arange(32.0).reshape(4, 8)
        mesh = Mesh(np.array(devices8[:4]), ("tp",))

        def body(full):
            r = jax.lax.axis_index("tp")
            chunk = split_tensor_into_1d_equal_chunks(full, rank=r, world_size=4)
            return gather_split_1d_tensor(chunk, axis_name="tp")

        out = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                        check_vma=False)(x)
        np.testing.assert_array_equal(np.asarray(out), np.arange(32.0))

    def test_split_explicit_args_outside_jit(self):
        from apex_tpu.transformer.utils import split_tensor_into_1d_equal_chunks

        c = split_tensor_into_1d_equal_chunks(
            jnp.arange(12.0).reshape(3, 4), rank=2, world_size=3)
        np.testing.assert_array_equal(np.asarray(c), np.arange(8.0, 12.0))
        # parity with the reference: uninitialized parallel state raises
        with pytest.raises(RuntimeError):
            split_tensor_into_1d_equal_chunks(jnp.arange(6.0))


class TestProfiler:
    """NVTX-range and trace-capture analogs (reference DDP prof flag +
    torch.cuda.nvtx)."""

    def test_range_push_pop(self):
        from apex_tpu.utils import nvtx_range, nvtx_range_pop, nvtx_range_push

        nvtx_range_push("outer")
        with nvtx_range("inner"):
            pass
        nvtx_range_pop()
        with pytest.raises(RuntimeError):
            nvtx_range_pop()

    def test_named_scope_inside_jit(self):
        from apex_tpu.utils import nvtx_range

        @jax.jit
        def f(x):
            with nvtx_range("scaled_add"):
                return x * 2 + 1

        assert float(f(jnp.float32(3.0))) == 7.0
        # the scope name survives into the HLO metadata
        hlo = jax.jit(f).lower(jnp.float32(3.0)).as_text(debug_info=True)
        assert "scaled_add" in hlo

    def test_profile_capture(self, tmp_path):
        from apex_tpu.utils import profile, start_profile, stop_profile

        d = str(tmp_path / "trace")
        with profile(d):
            float(jnp.sum(jnp.ones((8, 8))))
        import os

        assert any("plugins" in r and f for r, _, f in os.walk(d))
        with pytest.raises(RuntimeError):
            stop_profile()

    def test_ddp_prof_flag(self, devices8):
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel import DistributedDataParallel

        ddp = DistributedDataParallel(prof=True, axis_name="dp")
        mesh = Mesh(np.array(devices8), ("dp",))
        out = jax.shard_map(
            lambda g: ddp.sync(g), mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,
        )(jnp.arange(8.0))
        np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))
