"""API-surface parity additions: amp module-level functions, disable_casts,
MemoryBuffer, syncbn subgroup helper, pipeline next/prev rank, bottleneck
blocks, Megatron-style arguments/global_vars, DistributedTestBase."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu.amp as amp
from apex_tpu.contrib.bottleneck import (
    Bottleneck,
    HaloExchangerPeer,
    SpatialBottleneck,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import create_syncbn_process_group, SYNCBN_AXIS
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    MemoryBuffer,
    RingMemBuffer,
    get_cuda_rng_tracker,
    get_rng_state_tracker,
)
from apex_tpu.transformer.tensor_parallel import memory as tp_memory
from apex_tpu.transformer.testing import global_vars
from apex_tpu.transformer.testing.arguments import parse_args
from apex_tpu.transformer.testing.distributed_test_base import DistributedTestBase


class TestAmpModuleSurface:
    def test_scale_loss_and_state_dict_roundtrip(self):
        params = {"w": jnp.ones((4,), jnp.float32)}
        cast, a = amp.initialize(params, opt_level="O2", half_dtype=jnp.float16)
        state = a.init_state()
        loss = jnp.float32(2.0)
        scaled = amp.scale_loss(loss, a, state)
        assert float(scaled) == float(loss) * float(state.loss_scale)
        d = amp.state_dict(state)
        restored = amp.load_state_dict(d)
        assert float(restored.loss_scale) == float(state.loss_scale)

    def test_master_params_iterates_fp32(self):
        params = {"w": jnp.ones((4,), jnp.bfloat16)}
        opt = FusedAdam(lr=1e-3, master_weights=True)
        st = opt.init(params)
        masters = list(amp.master_params(st))
        assert masters and all(m.dtype == jnp.float32 for m in masters)

    def test_disable_casts(self):
        @amp.half_function
        def f(x):
            return x.dtype

        x = jnp.ones((2,), jnp.float32)
        assert f(x) == jnp.bfloat16
        with amp.disable_casts():
            assert f(x) == jnp.float32
        assert f(x) == jnp.bfloat16

    def test_legacy_init(self):
        handle = amp.init(enabled=True)
        st = handle.init_state()
        assert st is not None
        noop = amp.init(enabled=False)
        assert noop.scaler is None
        # legacy kwargs are accepted and ignored
        amp.init(enabled=True, verbose=False, enable_caching=True)

    def test_set_half_dtype_affects_existing_decorations(self):
        @amp.half_function
        def f(x):
            return x.dtype

        x = jnp.ones((2,), jnp.float32)
        assert f(x) == jnp.bfloat16
        try:
            amp.set_half_dtype(jnp.float16)
            assert f(x) == jnp.float16
        finally:
            amp.set_half_dtype(jnp.bfloat16)

    def test_promote_function_casts_kwargs(self):
        @amp.promote_function
        def f(x, y=None):
            return x.dtype, y.dtype

        dx, dy = f(jnp.ones(2, jnp.bfloat16), y=jnp.ones(2, jnp.float32))
        assert dx == jnp.float32 and dy == jnp.float32

    def test_adam_swa_skips_overflow_steps(self):
        from apex_tpu.contrib.openfold_triton import FusedAdamSWA

        params = {"w": jnp.ones((4,), jnp.float32)}
        opt = FusedAdamSWA(lr=0.1)
        st = opt.init(params)
        grads = {"w": jnp.full((4,), 0.5)}
        p1, st = opt.update(grads, st, params, grads_finite=jnp.bool_(False))
        np.testing.assert_array_equal(np.asarray(p1["w"]), np.asarray(params["w"]))
        assert int(st.n_averaged) == 0
        np.testing.assert_array_equal(
            np.asarray(st.swa_params["w"]), np.asarray(params["w"])
        )
        p2, st = opt.update(grads, st, p1, grads_finite=jnp.bool_(True))
        assert int(st.n_averaged) == 1
        np.testing.assert_allclose(
            np.asarray(st.swa_params["w"]), np.asarray(p2["w"]), rtol=1e-6
        )


class TestMemoryBuffer:
    def setup_method(self, method):
        tp_memory.reset_mem_buffs()

    def test_add_get_reset(self):
        buf = MemoryBuffer("act", 64, jnp.float32, track_usage=True)
        a = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
        view = buf.add(a)
        np.testing.assert_array_equal(np.asarray(view), np.asarray(a))
        assert buf.numel_in_use() == 12
        b = jnp.ones((8,), jnp.float32)
        buf.add(b)
        assert buf.numel_in_use() == 20
        np.testing.assert_array_equal(
            np.asarray(buf.get_data()[:12]), np.asarray(a).ravel()
        )
        buf.reset()
        assert not buf.is_in_use()

    def test_overflow_and_dtype_checks(self):
        buf = MemoryBuffer("small", 4, jnp.float32)
        with pytest.raises(AssertionError):
            buf.add(jnp.ones((8,), jnp.float32))
        with pytest.raises(AssertionError):
            buf.add(jnp.ones((2,), jnp.bfloat16))

    def test_ring(self):
        ring = RingMemBuffer("ring", 2, 16, jnp.float32)
        b0 = ring.get_next_buffer()
        b0.add(jnp.ones((4,), jnp.float32))
        b1 = ring.get_next_buffer()
        assert b1 is not b0
        b0_again = ring.get_next_buffer()
        assert b0_again is b0 and not b0.is_in_use()  # reset on rotation

    def test_named_registry(self):
        buf = tp_memory.allocate_mem_buff("x", 8, jnp.float32)
        assert tp_memory.get_mem_buff("x") is buf
        with pytest.raises(AssertionError):
            tp_memory.allocate_mem_buff("x", 8, jnp.float32)


class TestSyncbnGroups:
    def test_split(self):
        axis, (outer, inner) = create_syncbn_process_group(2, world_size=8)
        assert axis == SYNCBN_AXIS and (outer, inner) == (4, 2)
        with pytest.raises(ValueError):
            create_syncbn_process_group(3, world_size=8)

    def test_subgroup_stats_differ_across_groups(self, devices8):
        # Two groups of 4: stats must sync within, not across.
        from apex_tpu.parallel.sync_batchnorm import sync_batch_norm_stats

        axis, (outer, inner) = create_syncbn_process_group(4, world_size=8)
        mesh = Mesh(np.array(devices8).reshape(outer, inner), ("dp", axis))
        x = jnp.concatenate(
            [jnp.zeros((4, 2, 2, 3)), jnp.ones((4, 2, 2, 3))]
        )  # group 0 all-zero, group 1 all-one

        def f(xs):
            mean, var, n = sync_batch_norm_stats(xs, (0, 1, 2), axis)
            return mean

        means = jax.shard_map(
            f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False
        )(x)
        np.testing.assert_allclose(np.asarray(means[0]), 0.0)
        np.testing.assert_allclose(np.asarray(means[-1]), 1.0)


class TestPipelineRankGetters:
    def test_next_prev(self, devices8):
        with parallel_state_ctx(pp=4):
            mesh = parallel_state.get_mesh()

            def f():
                nxt = parallel_state.get_pipeline_model_parallel_next_rank()
                prv = parallel_state.get_pipeline_model_parallel_prev_rank()
                return jnp.reshape(nxt, (1,)), jnp.reshape(prv, (1,))

            nxt, prv = jax.shard_map(
                f, mesh=mesh, in_specs=(), out_specs=P(parallel_state.PIPELINE_AXIS),
                check_vma=False,
            )()
            np.testing.assert_array_equal(np.asarray(nxt), [1, 2, 3, 0])
            np.testing.assert_array_equal(np.asarray(prv), [3, 0, 1, 2])


def parallel_state_ctx(**kw):
    from apex_tpu.transformer.testing.commons import DistributedTestContext

    return DistributedTestContext(**kw)


class TestRngTrackerAlias:
    def test_alias(self):
        assert get_cuda_rng_tracker is get_rng_state_tracker


class TestBottleneck:
    @pytest.mark.slow
    def test_forward_shapes(self):
        m = Bottleneck(in_channels=8, bottleneck_channels=4, out_channels=16, stride=2)
        x = jnp.ones((2, 8, 8, 8), jnp.bfloat16)
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        assert y.shape == (2, 4, 4, 16)

    @pytest.mark.slow
    def test_spatial_matches_single_device(self, devices8):
        # H split over 4 devices + halo exchange == unsharded block.
        mesh = Mesh(np.array(devices8[:4]), ("spatial",))
        m = SpatialBottleneck(
            in_channels=6, bottleneck_channels=4, out_channels=6, axis_name="spatial",
            dtype=jnp.float32,
        )
        x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 8, 6), jnp.float32)
        # Oracle params from the unsharded block (identical param structure:
        # Conv_0..2 + FrozenScaleBias_0..2 in the same order).
        ref_m = Bottleneck(
            in_channels=6, bottleneck_channels=4, out_channels=6, stride=1,
            dtype=jnp.float32,
        )
        params = ref_m.init(jax.random.PRNGKey(0), x)
        y_ref = ref_m.apply(params, x)

        def shard_fn(xs):
            return m.apply(params, xs)

        y_sharded = jax.shard_map(
            shard_fn, mesh=mesh, in_specs=P(None, "spatial"),
            out_specs=P(None, "spatial"), check_vma=False,
        )(x)
        np.testing.assert_allclose(
            np.asarray(y_sharded), np.asarray(y_ref), rtol=1e-5, atol=1e-5
        )

    def test_halo_peer_alias(self):
        ex = HaloExchangerPeer("spatial", halo=1, peer_pool=object())
        assert ex.halo == 1


class TestArguments:
    def test_derived_values(self):
        args = parse_args(args=[
            "--num-layers", "4", "--hidden-size", "64",
            "--num-attention-heads", "4", "--micro-batch-size", "2",
            "--tensor-model-parallel-size", "2", "--world-size", "8", "--bf16",
        ])
        assert args.ffn_hidden_size == 256
        assert args.kv_channels == 16
        assert args.data_parallel_size == 4
        assert args.global_batch_size == 8
        assert args.params_dtype == "bfloat16"

    def test_consistency_errors(self):
        with pytest.raises(ValueError):
            parse_args(args=["--tensor-model-parallel-size", "3", "--world-size", "8"])
        with pytest.raises(ValueError):
            parse_args(args=["--fp16", "--bf16", "--world-size", "1"])

    def test_extra_args_provider_and_overrides(self):
        def extra(parser):
            parser.add_argument("--my-flag", type=int, default=1)
            return parser

        args = parse_args(
            extra_args_provider=extra,
            defaults={"hidden_size": 32},
            override_args={"seq_length": 128},
            args=["--world-size", "1"],
        )
        assert args.my_flag == 1 and args.hidden_size == 32 and args.seq_length == 128


class TestGlobalVars:
    def teardown_method(self, method):
        global_vars.destroy_global_vars()
        from apex_tpu.transformer.pipeline_parallel import utils as ppu
        ppu.destroy_num_microbatches_calculator()

    def test_set_and_get(self):
        global_vars.destroy_global_vars()
        args = global_vars.set_global_variables(args=[
            "--micro-batch-size", "2", "--global-batch-size", "8",
            "--world-size", "1",
        ])
        assert global_vars.get_args() is args
        assert global_vars.get_num_microbatches() == 4
        assert global_vars.get_current_global_batch_size() == 8
        assert global_vars.get_timers() is not None
        assert global_vars.get_adlr_autoresume() is None
        with pytest.raises(AssertionError):
            global_vars.set_global_variables(args=["--world-size", "1"])


class TestDistributedTestBase(DistributedTestBase):
    TP = 2

    def test_mesh_built(self):
        assert self.mesh is not None
        assert parallel_state.get_tensor_model_parallel_world_size() == 2
        assert self.world_size == 8


class TestGroupGetters:
    """Group handles are mesh-axis names usable directly as axis_name."""

    def test_groups_are_axis_names(self):
        with parallel_state_ctx(tp=2, pp=2):
            tp_g = parallel_state.get_tensor_model_parallel_group()
            pp_g = parallel_state.get_pipeline_model_parallel_group()
            dp_g = parallel_state.get_data_parallel_group()
            assert tp_g == parallel_state.TENSOR_AXIS and tp_g.size() == 2
            assert pp_g == parallel_state.PIPELINE_AXIS and pp_g.size() == 2
            assert dp_g == parallel_state.DATA_AXIS and dp_g.size() == 2
            emb = parallel_state.get_embedding_group()
            assert emb.members == (0, 1)
            assert parallel_state.get_position_embedding_group().members == (0,)
            assert parallel_state.get_amax_reduction_group() == parallel_state.TENSOR_AXIS

    def test_group_usable_in_collective(self):
        from jax.experimental.shard_map import shard_map

        with parallel_state_ctx(tp=4):
            mesh = parallel_state.get_mesh()
            g = parallel_state.get_tensor_model_parallel_group()

            def f(x):
                return jax.lax.psum(x, g)

            x = jnp.arange(4, dtype=jnp.float32)
            out = shard_map(
                f, mesh=mesh,
                in_specs=P(parallel_state.TENSOR_AXIS),
                out_specs=P(parallel_state.TENSOR_AXIS),
            )(x)
            np.testing.assert_array_equal(np.asarray(out), [6.0, 6.0, 6.0, 6.0])

    def test_multislice_mesh_and_hierarchical_dp_group(self):
        """num_distributed_slices splits dp into (dcn, dp); the dp group
        spans both axes so one psum is the hierarchical reduction."""
        from jax.experimental.shard_map import shard_map

        with parallel_state_ctx(tp=2, slices=2):
            mesh = parallel_state.get_mesh()
            assert mesh.axis_names == ("dcn", "dp", "pp", "cp", "tp")
            assert mesh.devices.shape == (2, 2, 1, 1, 2)
            assert parallel_state.get_num_distributed_slices() == 2
            assert parallel_state.get_data_parallel_world_size() == 2  # per slice
            g = parallel_state.get_data_parallel_group()
            assert tuple(g) == ("dcn", "dp") and g.size() == 4

            x = jnp.arange(8, dtype=jnp.float32)
            out = shard_map(
                lambda x: jax.lax.psum(x, g), mesh=mesh,
                in_specs=P(("dcn", "dp", "pp", "cp", "tp")),
                out_specs=P(("dcn", "dp", "pp", "cp", "tp")),
            )(x)
            # per tp-coordinate: tp=0 holds {0,2,4,6} → 12, tp=1 {1,3,5,7} → 16
            np.testing.assert_array_equal(np.asarray(out), [12, 16] * 4)

    def test_multislice_requires_divisible_dp(self):
        with pytest.raises(RuntimeError, match="slices"):
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size_=2, num_distributed_slices_=3,
                devices=jax.devices()[:8],
            )
        parallel_state.destroy_model_parallel()

    def test_masked_psum_sums_members_only(self):
        from jax.experimental.shard_map import shard_map

        with parallel_state_ctx(pp=4):
            mesh = parallel_state.get_mesh()
            g = parallel_state.get_embedding_group()  # members (0, 3)

            def f(x):
                return g.masked_psum(x)

            x = jnp.arange(4, dtype=jnp.float32) + 1.0  # stage s holds s+1
            out = shard_map(
                f, mesh=mesh,
                in_specs=P(parallel_state.PIPELINE_AXIS),
                out_specs=P(parallel_state.PIPELINE_AXIS),
            )(x)
            # only stages 0 and 3 contribute: 1 + 4 = 5
            np.testing.assert_array_equal(np.asarray(out), [5.0] * 4)
            # full-membership group degrades to a plain psum
            tp_like = parallel_state.get_pipeline_model_parallel_group()
            out2 = shard_map(
                lambda x: tp_like.masked_psum(x), mesh=mesh,
                in_specs=P(parallel_state.PIPELINE_AXIS),
                out_specs=P(parallel_state.PIPELINE_AXIS),
            )(x)
            np.testing.assert_array_equal(np.asarray(out2), [10.0] * 4)

    def test_model_parallel_group_is_axis_tuple(self):
        from jax.experimental.shard_map import shard_map

        with parallel_state_ctx(tp=2, pp=2):
            mesh = parallel_state.get_mesh()
            g = parallel_state.get_model_parallel_group()
            assert tuple(g) == (parallel_state.PIPELINE_AXIS, parallel_state.TENSOR_AXIS)
            assert g.size() == 4

            def f(x):
                return jax.lax.psum(x, g)

            x = jnp.arange(4, dtype=jnp.float32)
            out = shard_map(
                f, mesh=mesh,
                in_specs=P(None, (parallel_state.PIPELINE_AXIS, parallel_state.TENSOR_AXIS)),
                out_specs=P(None, (parallel_state.PIPELINE_AXIS, parallel_state.TENSOR_AXIS)),
            )(x.reshape(1, 4))
            np.testing.assert_array_equal(np.asarray(out), [[6.0, 6.0, 6.0, 6.0]])

    def test_embedding_group_pp1_dedup(self):
        with parallel_state_ctx(tp=2):
            assert parallel_state.get_embedding_group().members == (0,)

    def test_usage_tracked_at_get_data(self):
        # sampling happens at get_data, as in the reference (memory.py:115)
        buf = MemoryBuffer("cyc", 100, jnp.float32, track_usage=True)
        for _ in range(10):
            buf.add(jnp.ones((10,), jnp.float32))
        assert buf.in_use_value == 0.0  # not sampled yet
        buf.get_data()
        assert buf.in_use_value == 100.0 and buf.total_value == 100.0
        buf.reset()
        assert buf.in_use_value == 100.0  # reset does not sample

    def test_add_rejects_tracers(self):
        buf = MemoryBuffer("tr", 16, jnp.float32)
        with pytest.raises(TypeError, match="jit"):
            jax.jit(lambda t: buf.add(t))(jnp.ones((4,), jnp.float32))


class TestGlobalVarsCalculatorWiring:
    def test_set_global_variables_installs_pp_calculator(self):
        from apex_tpu.transformer.pipeline_parallel import utils as ppu

        global_vars.destroy_global_vars()
        try:
            global_vars.set_global_variables(args=[
                "--world-size", "8", "--tensor-model-parallel-size", "2",
                "--micro-batch-size", "2",
            ])
            # the pipeline schedules read this module-global; it must be set
            assert ppu.get_num_microbatches() == global_vars.get_num_microbatches()
        finally:
            global_vars.destroy_global_vars()

    def test_validate_args_accounts_for_cp(self):
        from apex_tpu.transformer.testing.arguments import parse_args

        a = parse_args(args=[
            "--world-size", "8", "--tensor-model-parallel-size", "2",
            "--context-parallel-size", "2", "--micro-batch-size", "2",
        ])
        assert a.data_parallel_size == 2
        with pytest.raises(ValueError):
            parse_args(args=[
                "--world-size", "4", "--tensor-model-parallel-size", "2",
                "--context-parallel-size", "4", "--micro-batch-size", "1",
            ])


class TestPublicSurfaceInventory:
    """Every name the docs/migration guide promises must import — the
    one-stop check that the reference's component inventory is reachable."""

    def test_inventory_imports(self):
        from apex_tpu.amp import DynamicLossScaler, StaticLossScaler, initialize, value_and_grad  # noqa: F401
        from apex_tpu.contrib.bottleneck import halo_exchange_1d  # noqa: F401
        from apex_tpu.contrib.conv_bias_relu import (  # noqa: F401
            ConvBias, ConvBiasMaskReLU, ConvBiasReLU, ConvFrozenScaleBiasReLU,
        )
        from apex_tpu.contrib.fmha import fmha, fmha_varlen  # noqa: F401
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC, GroupBatchNorm2d  # noqa: F401
        from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn  # noqa: F401
        from apex_tpu.contrib.openfold_triton import (  # noqa: F401
            CanSchTriMHA, FusedAdamSWA, attention_core,
        )
        from apex_tpu.contrib.optimizers import DistributedFusedAdam, DistributedFusedLAMB  # noqa: F401
        from apex_tpu.contrib.sparsity import ASP, compute_sparse_masks  # noqa: F401
        from apex_tpu.contrib.sparsity.permutation_lib import search_channel_permutation  # noqa: F401
        from apex_tpu.contrib.transducer import TransducerJoint, transducer_loss  # noqa: F401
        from apex_tpu.contrib.xentropy import softmax_xentropy  # noqa: F401
        from apex_tpu.fp16_utils import FP16_Optimizer, network_to_half  # noqa: F401
        from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense  # noqa: F401
        from apex_tpu.io import (  # noqa: F401
            load_checkpoint, load_sharded_checkpoint, save_checkpoint,
            save_sharded_checkpoint,
        )
        from apex_tpu.mlp import MLP  # noqa: F401
        from apex_tpu.models.bert import bert_forward, bert_mlm_loss  # noqa: F401
        from apex_tpu.models.gpt import gpt_forward, make_pp_train_step, make_train_step  # noqa: F401
        from apex_tpu.normalization import (  # noqa: F401
            FusedLayerNorm, FusedRMSNorm, MixedFusedLayerNorm, MixedFusedRMSNorm,
        )
        from apex_tpu.ops.attention import flash_attention, mha_reference  # noqa: F401
        from apex_tpu.optimizers import (  # noqa: F401
            FusedAdagrad, FusedAdam, FusedLAMB, FusedMixedPrecisionLamb,
            FusedNovoGrad, FusedSGD,
        )
        from apex_tpu.parallel import LARC, SyncBatchNorm, allreduce_gradients  # noqa: F401
        from apex_tpu.RNN import GRU, LSTM, ReLU, Tanh, mLSTM  # noqa: F401
        from apex_tpu.transformer.context_parallel import ring_attention  # noqa: F401
        from apex_tpu.transformer.expert_parallel import moe_ffn  # noqa: F401
        from apex_tpu.transformer.functional import FusedScaleMaskSoftmax, scaled_masked_softmax  # noqa: F401
        from apex_tpu.transformer.pipeline_parallel import p2p_communication  # noqa: F401
        from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
            forward_backward_no_pipelining,
            forward_backward_pipelining_with_interleaving,
            forward_backward_pipelining_without_interleaving,
            get_forward_backward_func,
        )
        from apex_tpu.transformer.tensor_parallel import (  # noqa: F401
            ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
            accumulate_gradients, vocab_parallel_cross_entropy,
        )
        from apex_tpu.transformer.microbatches import build_num_microbatches_calculator  # noqa: F401
        from apex_tpu.transformer._data._batchsampler import (  # noqa: F401
            MegatronPretrainingRandomSampler, MegatronPretrainingSampler,
        )


class TestSplitRankMachinery:
    """Encoder/decoder split predicates, membership checks, src/first/
    last rank getters, and test-support setters (reference
    parallel_state.py:504-759)."""

    def test_split_predicates(self):
        with parallel_state_ctx(pp=4, split_rank=2):
            ps = parallel_state
            assert ps.get_pipeline_model_parallel_split_rank() == 2
            assert [ps.is_pipeline_stage_before_split(s) for s in range(4)] == [True, True, False, False]
            assert [ps.is_pipeline_stage_after_split(s) for s in range(4)] == [False, False, True, True]
            assert [ps.is_pipeline_stage_at_split(s) for s in range(4)] == [False, True, False, False]

    def test_split_predicates_no_split(self):
        with parallel_state_ctx(pp=4):
            ps = parallel_state
            assert ps.is_pipeline_stage_before_split(3)
            assert ps.is_pipeline_stage_after_split(0)
            assert not ps.is_pipeline_stage_at_split(1)

    def test_membership_and_ranks(self):
        with parallel_state_ctx(pp=4, split_rank=2):
            ps = parallel_state
            assert ps.get_pipeline_model_parallel_first_rank() == 0
            assert ps.get_pipeline_model_parallel_last_rank() == 3
            assert ps.get_tensor_model_parallel_src_rank() == 0
            assert ps.get_data_parallel_src_rank() == 0
            # with split=2 the embedding group is {0, 2, 3} (the first
            # decoder stage owns the decoder's tied embedding) and the
            # position group {0, 2} — reference :352-372
            assert ps.is_rank_in_embedding_group(stage=0)
            assert ps.is_rank_in_embedding_group(stage=2)
            assert ps.is_rank_in_embedding_group(stage=3)
            assert not ps.is_rank_in_embedding_group(stage=1)
            assert ps.get_embedding_group().members == (0, 2, 3)
            assert ps.is_rank_in_position_embedding_group(stage=0)
            assert ps.is_rank_in_position_embedding_group(stage=2)
            assert not ps.is_rank_in_position_embedding_group(stage=1)
            assert ps.get_position_embedding_group().members == (0, 2)
            # encoder stages {0,1}; decoder stages {2,3}
            assert ps.is_rank_in_encoder_relative_position_embedding_group(stage=1)
            assert not ps.is_rank_in_encoder_relative_position_embedding_group(stage=2)
            assert ps.is_rank_in_decoder_relative_position_embedding_group(stage=2)
            enc = ps.get_encoder_relative_position_embedding_group()
            dec = ps.get_decoder_relative_position_embedding_group()
            assert enc.members == (0, 1) and dec.members == (2, 3)
            assert enc == parallel_state.PIPELINE_AXIS  # usable as axis_name

    def test_setters_and_uninitialized(self):
        assert parallel_state.is_unitialized()
        with parallel_state_ctx(tp=2, pp=2):
            ps = parallel_state
            assert not ps.is_unitialized()
            ps.set_pipeline_model_parallel_split_rank(1)
            assert ps.get_pipeline_model_parallel_split_rank() == 1
            ps.set_tensor_model_parallel_world_size(1)
            assert ps.get_tensor_model_parallel_world_size() == 1
            ps.set_tensor_model_parallel_rank(1)
            assert ps.get_tensor_model_parallel_rank() == 1  # static override
            ps.set_tensor_model_parallel_rank(None)
            ps.set_pipeline_model_parallel_rank(0)
            assert ps.get_pipeline_model_parallel_rank() == 0

    def test_nccl_plumbing_shims(self):
        parallel_state.init_nccl_net()
        parallel_state.set_nccl_ib_envs()
        parallel_state.set_nccl_socket_envs()
        for fn in (parallel_state.new_process_group,
                   parallel_state.new_nccl_ib_group,
                   parallel_state.new_nccl_socket_group):
            with pytest.raises(RuntimeError, match="mesh axes"):
                fn([0, 1])
