"""Pallas fused softmax kernels vs the jnp composite (interpret mode).

Reference parity model: tests/L0/run_transformer/test_fused_softmax.py
compares each CUDA kernel against a torch composite."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.softmax_pallas import (
    scaled_masked_softmax_pallas,
    scaled_softmax_pallas,
)
from apex_tpu.transformer.functional.fused_softmax import (
    MASK_FILL_VALUE,
    _softmax,
)


def _x(shape=(2, 4, 64, 128), seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype(np.float32), dtype)


class TestScaledSoftmaxPallas:
    @pytest.mark.parametrize("scale", [1.0, 0.5])
    def test_plain_matches_composite(self, scale):
        x = _x()
        y = scaled_softmax_pallas(x, scale, interpret=True)
        ref = _softmax(x * scale)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)

    def test_causal_matches_composite(self):
        x = _x()
        y = scaled_softmax_pallas(x, 0.7, causal=True, interpret=True)
        sq, sk = x.shape[-2], x.shape[-1]
        scores = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), x * 0.7, MASK_FILL_VALUE)
        np.testing.assert_allclose(np.asarray(y), np.asarray(_softmax(scores)), atol=1e-6)

    def test_masked_matches_composite(self):
        x = _x()
        rng = np.random.RandomState(1)
        mask = jnp.asarray(rng.rand(2, 1, 64, 128) > 0.7)
        y = scaled_masked_softmax_pallas(x, mask, 0.5, interpret=True)
        ref = _softmax(jnp.where(mask, MASK_FILL_VALUE, x * 0.5))
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)

    def test_grads_match_composite(self):
        x = _x(shape=(2, 2, 32, 128))

        def loss_pallas(x):
            return jnp.sum(scaled_softmax_pallas(x, 0.6, causal=True, interpret=True) ** 2)

        def loss_ref(x):
            sq, sk = x.shape[-2], x.shape[-1]
            s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), x * 0.6, MASK_FILL_VALUE)
            return jnp.sum(_softmax(s) ** 2)

        gp = jax.grad(loss_pallas)(x)
        gr = jax.grad(loss_ref)(x)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr), atol=1e-5)

    def test_masked_grads_match_composite(self):
        x = _x(shape=(2, 2, 32, 128))
        mask = jnp.asarray(np.random.RandomState(2).rand(2, 1, 32, 128) > 0.6)

        gp = jax.grad(lambda x: jnp.sum(
            scaled_masked_softmax_pallas(x, mask, 0.5, interpret=True) ** 2))(x)
        gr = jax.grad(lambda x: jnp.sum(
            _softmax(jnp.where(mask, MASK_FILL_VALUE, x * 0.5)) ** 2))(x)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gr), atol=1e-5)

    def test_bf16(self):
        x = _x(dtype=jnp.bfloat16)
        y = scaled_softmax_pallas(x, 1.0, causal=True, interpret=True)
        sq, sk = x.shape[-2], x.shape[-1]
        ref = _softmax(jnp.where(jnp.tril(jnp.ones((sq, sk), bool)),
                                 x.astype(jnp.float32), MASK_FILL_VALUE)).astype(jnp.bfloat16)
        np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(ref, np.float32),
                                   atol=1e-2)
