"""ZeRO optimizer tests — mirrors apex/contrib/test/optimizers/
test_dist_adam.py: the sharded optimizer must match the non-sharded
fused optimizer exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.optimizers import DistributedFusedAdam, DistributedFusedLAMB
from apex_tpu.optimizers import FusedAdam, FusedLAMB

DP = 8


def make_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "a": jnp.asarray(rng.randn(13, 5).astype(np.float32)),
        "b": {"w": jnp.asarray(rng.randn(31).astype(np.float32))},
    }


def run_sharded(opt_cls, ref_opt, devices8, nsteps=4, seed=0, **kw):
    params = make_tree(seed)
    mesh = Mesh(np.array(devices8), ("dp",))

    dist = opt_cls(lr=1e-2, weight_decay=kw.pop("weight_decay", 0.01), axis_name="dp", **kw)
    state = dist.init(params, world_size=DP)

    ref_state = ref_opt.init(params)
    ref_params = params

    rng = np.random.RandomState(seed + 50)
    for _ in range(nsteps):
        g = jax.tree.map(lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)), params)

        def stepper(params, state, grads):
            return dist.update(grads, state, params)

        sspec = dist.state_partition_spec()
        params, state = jax.shard_map(
            stepper,
            mesh=mesh,
            in_specs=(P(), sspec, P()),
            out_specs=(P(), sspec),
            check_vma=False,
        )(params, state, g)

        # reference: the same grads, averaged identically (each dp rank got
        # identical grads here, so psum/world == grads)
        ref_params, ref_state = ref_opt.update(g, ref_state, ref_params)
    return params, ref_params


class TestDistributedFusedAdam:
    def test_matches_fused_adam(self, devices8):
        ref = FusedAdam(lr=1e-2, weight_decay=0.01, adam_w_mode=True)
        params, ref_params = run_sharded(DistributedFusedAdam, ref, devices8)
        for a, r in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-5, atol=1e-6)

    def test_state_is_sharded(self, devices8):
        params = make_tree()
        total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = dist.init(params, world_size=DP)
        # global flat state padded to a dp multiple; sharded via the spec
        padded = ((total + DP - 1) // DP) * DP
        assert state.exp_avg.shape[0] == padded
        spec = dist.state_partition_spec()
        assert spec.exp_avg == P("dp")

    def test_overflow_skip(self, devices8):
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = dist.init(params, world_size=DP)
        g = jax.tree.map(lambda x: jnp.full(x.shape, jnp.inf), params)

        def stepper(params, state, grads):
            return dist.update(grads, state, params, grads_finite=jnp.bool_(False))

        sspec = dist.state_partition_spec()
        new_params, new_state = jax.shard_map(
            stepper, mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec), check_vma=False
        )(params, state, g)
        for a, r in zip(jax.tree.leaves(new_params), jax.tree.leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(r))
        assert int(new_state.step) == 0


class TestDistributedFusedLAMB:
    def test_matches_fused_lamb(self, devices8):
        ref = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        params, ref_params = run_sharded(
            DistributedFusedLAMB, ref, devices8, weight_decay=0.01, max_grad_norm=1.0
        )
        for a, r in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-5)
