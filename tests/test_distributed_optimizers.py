"""ZeRO optimizer tests — the dp-sharded parity band.

Mirrors apex/contrib/test/optimizers/test_dist_adam.py with a stricter
standard: the per-leaf fused optimizers are the NUMERICS ORACLE, and on
fp32 trees with exactly-representable grads the resident-sharded bucket
engine must match them **bit for bit** (elementwise expression trees are
shared; the dp reduce adds no rounding when every addend is exactly
representable).  LAMB (reduction-fed trust ratios) gets a tight
allclose, same convention as ``tests/test_bucketed_engine.py``.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.analysis import lowered as lw
from apex_tpu.contrib.optimizers import DistributedFusedAdam, DistributedFusedLAMB
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.optimizers import bucketing

DP = 8


def make_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "a": jnp.asarray(rng.randn(13, 5).astype(np.float32)),
        "b": {"w": jnp.asarray(rng.randn(31).astype(np.float32))},
    }


def make_mixed_tree(seed=0):
    """fp32 + bf16 leaves: two dtype buckets."""
    t = make_tree(seed)
    rng = np.random.RandomState(seed + 1)
    t["h"] = jnp.asarray(rng.randn(24, 8).astype(np.float32)).astype(
        jnp.bfloat16)
    return t


def exact_grads(rng, tree):
    """Grads whose dp sum and mean are EXACT in fp32/bf16: small
    integers × 2⁻³ (sums ≤ 64 stay integral ×2⁻³; /8 is a power of
    two) — the construction that makes end-to-end bit-exactness a fair
    assertion rather than a rounding lottery."""
    return jax.tree.map(
        lambda x: jnp.asarray(
            (rng.randint(-8, 9, size=x.shape) * 0.125).astype(np.float32)
        ).astype(x.dtype),
        tree)


def assert_bitwise(tree_a, tree_b, err=""):
    for (ka, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(tree_a),
        jax.tree_util.tree_leaves_with_path(tree_b),
    ):
        a, b = np.asarray(a), np.asarray(b)
        view = np.uint16 if a.dtype == jnp.bfloat16 else None
        av = a.view(view) if view else a
        bv = b.view(view) if view else b
        np.testing.assert_array_equal(
            av, bv, err_msg=f"{err}{jax.tree_util.keystr(ka)}")


def zero_step(dist, mesh, params, state, g, **kw):
    sspec = dist.state_partition_spec()
    return jax.shard_map(
        lambda p, s, gg: dist.update(gg, s, p, **kw),
        mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec),
        check_vma=False,
    )(params, state, g)


# --------------------------------------------------------------- Adam parity
class TestDistributedFusedAdam:
    def test_matches_fused_adam_bit_exact(self, devices8):
        """fp32+bf16 tree, 4 steps: the sharded trajectory must equal
        the per-leaf oracle's BITWISE.  Oracle is
        ``FusedAdam(master_weights=True)`` — ZeRO's resident fp32
        master integrates half-precision params in fp32 exactly like
        the oracle's master copy (an oracle without masters would
        re-round to bf16 every step, a semantic ZeRO exists to avoid)."""
        params = make_mixed_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        state = dist.init(params, world_size=DP)

        ref = FusedAdam(lr=1e-2, weight_decay=0.01, master_weights=True,
                        use_buckets=False)
        ref_state = ref.init(params)
        ref_params = params
        rng = np.random.RandomState(50)
        for _ in range(4):
            g = exact_grads(rng, params)
            params, state = zero_step(dist, mesh, params, state, g)
            ref_params, ref_state = ref.update(g, ref_state, ref_params)
        assert_bitwise(params, ref_params)

    def test_update_collective_structure(self, devices8):
        """The acceptance contract of the bucketed design, read off the
        lowering: a 2-dtype tree emits (at least) one reduce-scatter
        and one all-gather PER BUCKET — the bf16 bucket's in bf16
        element type (half the wire bytes) — no grad all-reduce, and no
        whole-tree fp32 concatenate anywhere in the step (the
        ``_flatten`` stub this engine replaced).  Asserted on the
        StableHLO lowering via ``analysis.lowered`` (the reusable
        second-tier checkers): the CPU backend's compile upcasts bf16
        collectives, a TPU-irrelevant detail."""
        params = make_mixed_tree()
        total_f32 = sum(int(np.prod(x.shape))
                        for x in jax.tree.leaves(params))
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = dist.init(params, world_size=DP)
        sspec = dist.state_partition_spec()
        g = jax.tree.map(jnp.ones_like, params)

        f = jax.jit(jax.shard_map(
            lambda p, s, gg: dist.update(gg, s, p),
            mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec),
            check_vma=False,
        ))
        txt = f.lower(params, state, g).as_text()
        lw.count_collectives(txt, "reduce_scatter", minimum=2)
        lw.assert_collective_dtype(txt, "reduce_scatter", "bf16")
        lw.assert_collective_dtype(txt, "reduce_scatter", "f32")
        lw.count_collectives(txt, "all_gather", minimum=2)
        lw.assert_collective_dtype(txt, "all_gather", "bf16")
        lw.count_collectives(txt, "all_reduce", maximum=0)
        lw.assert_no_whole_tree_concat(txt, total_f32)

    def test_state_is_sharded_per_bucket(self, devices8):
        params = make_mixed_tree()
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = dist.init(params, world_size=DP)
        plan = dist._plan
        assert len(plan.buckets) == 2  # fp32 + bf16
        for arr, b in zip(state.exp_avg, plan.buckets):
            assert arr.shape == (b.total,)
            assert b.total % DP == 0  # shards split evenly
        spec = dist.state_partition_spec()
        assert spec.exp_avg == tuple(P("dp") for _ in plan.buckets)
        assert spec.step == P()

    def test_bucket_cap_splits_collectives(self, devices8):
        """bucket_cap_mb actually splits: a tiny cap turns the fp32
        bucket into several, each with its own reduce-scatter — the
        overlap granularity knob doing its job.  (The cap clamps at one
        dtype tile — 1024 fp32 elements — so the leaves here exceed
        that.)"""
        rng = np.random.RandomState(2)
        params = {
            "w1": jnp.asarray(rng.randn(40, 40).astype(np.float32)),
            "w2": jnp.asarray(rng.randn(1300).astype(np.float32)),
            "w3": jnp.asarray(rng.randn(50, 30).astype(np.float32)),
        }
        capped = DistributedFusedAdam(
            lr=1e-2, axis_name="dp", bucket_cap_mb=4096 / 2 ** 20)
        state = capped.init(params, world_size=DP)
        n_capped = len(capped._plan.buckets)
        assert n_capped >= 2, "cap should split the fp32 bucket"
        # every leaf still lands exactly once, offsets intact
        seen = sorted(bl.leaf_id for b in capped._plan.buckets
                      for bl in b.leaves)
        assert seen == list(range(capped._plan.n_leaves))

        mesh = Mesh(np.array(devices8), ("dp",))
        sspec = capped.state_partition_spec()
        g = jax.tree.map(jnp.ones_like, params)
        txt = jax.jit(jax.shard_map(
            lambda p, s, gg: capped.update(gg, s, p),
            mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec),
            check_vma=False,
        )).lower(params, state, g).as_text()
        lw.count_collectives(txt, "reduce_scatter",
                             minimum=n_capped, maximum=n_capped)

    def test_resident_shard_state_is_donated(self, devices8):
        """The resident claim at the lowering level: every per-bucket
        m/v/master shard input of a ``donate_argnums`` step is aliased
        to an output in the compiled module's ``input_output_alias``
        table — the ZeRO state updates in place.  (Under shard_map jax
        marks the inputs ``jax.buffer_donor`` and the ALIASING shows up
        at compile time, unlike the plain-jit ``tf.aliasing_output``
        path the bucketed-engine test pins.)"""
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = dist.init(params, world_size=DP)
        sspec = dist.state_partition_spec()
        g = jax.tree.map(jnp.ones_like, params)
        n_buckets = len(dist._plan.buckets)

        sharded = jax.shard_map(
            lambda p, s, gg: dist.update(gg, s, p),
            mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec),
            check_vma=False)
        step = jax.jit(lambda s, p: sharded(p, s, g)[::-1],
                       donate_argnums=(0,))
        low = step.lower(state, params)
        # step counter + m/v/master per bucket all declared donatable
        # AND actually aliased in the compiled input_output_alias table
        assert len(jax.tree_util.tree_leaves(state)) == 1 + 3 * n_buckets
        lw.assert_donation_covers(low, state)

    @pytest.mark.slow
    def test_overflow_skip(self, devices8):
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = dist.init(params, world_size=DP)
        g = jax.tree.map(lambda x: jnp.full(x.shape, jnp.inf), params)
        new_params, new_state = zero_step(
            dist, mesh, params, state, g, grads_finite=jnp.bool_(False))
        assert_bitwise(new_params, params)
        assert int(new_state.step) == 0

    @pytest.mark.slow
    def test_update_scaled_folds_unscale_vote_clip(self, devices8):
        """``update_scaled`` on the sharded read must match the oracle's
        fused amp tail: same unscale, same torch-semantics global clip
        (Σx² agreed across the dp shards), same vote, and an inf grad
        skips the step on every rank."""
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        state = dist.init(params, world_size=DP)
        sspec = dist.state_partition_spec()
        ref = FusedAdam(lr=1e-2, weight_decay=0.01, master_weights=True,
                        use_buckets=False)
        ref_state = ref.init(params)

        rng = np.random.RandomState(3)
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)) * 4.0,
            params)
        scale = jnp.float32(4.0)

        def local(p, s, gg):
            return dist.update_scaled(gg, s, p, scale=scale, clip_norm=1.0)

        p2, s2, fin = jax.shard_map(
            local, mesh=mesh, in_specs=(P(), sspec, P()),
            out_specs=(P(), sspec, P()), check_vma=False,
        )(params, state, g)
        rp, rs_, rfin = ref.update_scaled(g, ref_state, params, scale=scale,
                                          clip_norm=1.0)
        assert bool(fin) and bool(rfin)
        assert int(s2.step) == 1
        for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(rp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

        bad = jax.tree.map(
            lambda x: jnp.full(x.shape, jnp.inf, jnp.float32), params)
        p3, s3, fin3 = jax.shard_map(
            local, mesh=mesh, in_specs=(P(), sspec, P()),
            out_specs=(P(), sspec, P()), check_vma=False,
        )(params, state, bad)
        assert not bool(fin3)
        assert int(s3.step) == 0
        assert_bitwise(p3, params)

    def test_overlap_param_sync_matches(self, devices8):
        """``overlap_param_sync=True`` changes the gather/commit ORDER
        (pre-vote gather, per-leaf predicated select), never the
        values."""
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        rng = np.random.RandomState(9)
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
            params)

        def run(overlap):
            dist = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                        axis_name="dp",
                                        overlap_param_sync=overlap)
            state = dist.init(params, world_size=DP)
            sspec = dist.state_partition_spec()
            return jax.shard_map(
                lambda p, s, gg: dist.update_scaled(gg, s, p),
                mesh=mesh, in_specs=(P(), sspec, P()),
                out_specs=(P(), sspec, P()), check_vma=False,
            )(params, state, g)

        p_a, s_a, _ = run(False)
        p_b, s_b, _ = run(True)
        assert_bitwise(p_a, p_b)
        assert_bitwise(s_a.master_shard, s_b.master_shard)


# ------------------------------------------------------- sync dtype knobs
class TestSyncDtypeValidation:
    """The reference's grad_sync_dtype/param_sync_dtype were silently
    accepted-and-dropped by the old stub; now they are wired, the
    still-unsupported combinations must raise, not no-op."""

    def test_quantized_grad_sync_accepted_wide_ints_rejected(self):
        """int8 and both fp8 formats are now legal grad_sync_dtype
        values (the quantized wire); every OTHER integer keeps raising
        at construction."""
        for ok in (jnp.int8, jnp.float8_e4m3fn, jnp.float8_e5m2,
                   "int8", "float8_e5m2"):
            opt = DistributedFusedAdam(lr=1e-2, grad_sync_dtype=ok)
            assert opt._quantized
        for bad in (jnp.int32, jnp.int16, jnp.uint8, int):
            with pytest.raises(ValueError, match="grad_sync_dtype"):
                DistributedFusedAdam(lr=1e-2, grad_sync_dtype=bad)

    def test_quantized_param_sync_rejected(self):
        """param sync has no error-feedback channel — a gather is not a
        sum — so the quantized dtypes stay grad-only."""
        for bad in (jnp.int8, jnp.float8_e4m3fn):
            with pytest.raises(ValueError,
                               match="param_sync_dtype.*error-feedback"):
                DistributedFusedAdam(lr=1e-2, param_sync_dtype=bad)

    def test_remainder_mode_param_sync_must_be_bf16(self):
        with pytest.raises(ValueError, match="bfloat16"):
            DistributedFusedAdam(lr=1e-2, store_param_remainders=True,
                                 param_sync_dtype=jnp.float32)
        # None and bf16 are fine
        DistributedFusedAdam(lr=1e-2, store_param_remainders=True)
        DistributedFusedAdam(lr=1e-2, store_param_remainders=True,
                             param_sync_dtype=jnp.bfloat16)

    def test_lamb_validates_too(self):
        with pytest.raises(ValueError, match="grad_sync_dtype"):
            DistributedFusedLAMB(lr=1e-2, grad_sync_dtype=jnp.int32)
        assert DistributedFusedLAMB(lr=1e-2,
                                    grad_sync_dtype=jnp.int8)._quantized

    def test_grad_sync_dtype_override_changes_wire_type(self, devices8):
        """grad_sync_dtype=float32 forces the bf16 bucket's
        reduce-scatter up to f32 — the knob is live, not recorded."""
        params = make_mixed_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                    grad_sync_dtype=jnp.float32)
        state = dist.init(params, world_size=DP)
        sspec = dist.state_partition_spec()
        g = jax.tree.map(jnp.ones_like, params)
        txt = jax.jit(jax.shard_map(
            lambda p, s, gg: dist.update(gg, s, p),
            mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec),
            check_vma=False,
        )).lower(params, state, g).as_text()
        lw.assert_collective_dtype(txt, "reduce_scatter", "f32",
                                   mode="all")

    def test_bucket_cap_must_be_positive(self):
        with pytest.raises(ValueError, match="bucket_cap_mb"):
            DistributedFusedAdam(lr=1e-2, bucket_cap_mb=0)

    @pytest.mark.slow
    def test_fp16_grad_sync_predivides(self, devices8):
        """fp16 sync takes the predivide branch (overflow control);
        the trajectory still tracks the oracle to fp16 grad rounding."""
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                    axis_name="dp",
                                    grad_sync_dtype=jnp.float16)
        state = dist.init(params, world_size=DP)
        ref = FusedAdam(lr=1e-2, weight_decay=0.01, master_weights=True,
                        use_buckets=False)
        ref_state = ref.init(params)
        ref_params = params
        rng = np.random.RandomState(31)
        for _ in range(2):
            g = exact_grads(rng, params)  # fp16-exact too (ints * 2^-3)
            params, state = zero_step(dist, mesh, params, state, g)
            ref_params, ref_state = ref.update(g, ref_state, ref_params)
        assert_bitwise(params, ref_params)


# ------------------------------------------------------------ state dicts
class TestShardedStateDict:
    """Per-rank save + cross-world reshard (reference
    distributed_fused_adam.py:2527,2959), on the bucket layout."""

    def _grads(self, params, rng):
        return jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
            params)

    @pytest.mark.slow
    @pytest.mark.parametrize("via_disk", [False, True], ids=["memory", "disk"])
    def test_save_dp4_load_dp2_resumes_identically(self, devices8, tmp_path,
                                                   via_disk):
        """Per-rank save at dp=4, resume at dp=2, trajectory parity vs
        the uninterrupted run.  ``via_disk`` composes ZeRO with io: the
        shard dicts round-trip through per-rank files bit-exactly."""
        params0 = make_tree(3)
        rng = np.random.RandomState(7)

        mesh4 = Mesh(np.array(devices8[:4]), ("dp",))
        opt4 = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        state = opt4.init(params0, world_size=4)
        params = params0
        for _ in range(3):
            params, state = zero_step(opt4, mesh4, params, state,
                                      self._grads(params, rng))
        shards = [opt4.sharded_state_dict(state, r, 4) for r in range(4)]
        assert shards[0]["format"] == DistributedFusedAdam.SHARD_FORMAT

        if via_disk:
            from apex_tpu import io

            zdir = tmp_path / "zero"
            for r, sd in enumerate(shards):
                io.save_sharded_checkpoint(zdir, sd, r, 4)
            with io.AsyncCheckpointer() as ck:
                ck.save(tmp_path / "params.ckpt", params)
            loaded = io.load_sharded_checkpoint(zdir)
            state2 = DistributedFusedAdam.load_sharded_state_dicts(
                loaded, world_size=2)
            state2_mem = DistributedFusedAdam.load_sharded_state_dicts(
                shards, world_size=2)
            for a, b in zip(jax.tree.leaves(state2), jax.tree.leaves(state2_mem)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            params_r = jax.tree.map(jnp.asarray,
                                    io.load_checkpoint(tmp_path / "params.ckpt"))
        else:
            state2 = DistributedFusedAdam.load_sharded_state_dicts(
                shards, world_size=2)
            params_r = jax.tree.map(lambda x: jnp.asarray(np.asarray(x)), params)
        assert int(state2.step) == 3

        mesh2 = Mesh(np.array(devices8[:2]), ("dp",))
        opt2 = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        opt2.init(params0, world_size=2)  # rebuild the dp=2 plan
        for _ in range(2):
            params_r, state2 = zero_step(opt2, mesh2, params_r, state2,
                                         self._grads(params_r, rng))

        rng_o = np.random.RandomState(7)
        opt_o = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        state_o = opt_o.init(params0, world_size=4)
        params_o = params0
        for _ in range(5):
            params_o, state_o = zero_step(opt_o, mesh4, params_o, state_o,
                                          self._grads(params_o, rng_o))

        for a, r in zip(jax.tree.leaves(params_r), jax.tree.leaves(params_o)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-6, atol=1e-7)

    def test_incomplete_shard_set_rejected(self, devices8):
        params = make_tree(4)
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = opt.init(params, world_size=4)
        shards = [opt.sharded_state_dict(state, r, 4) for r in range(4)]
        with pytest.raises(ValueError, match="incomplete"):
            DistributedFusedAdam.load_sharded_state_dicts(shards[:3],
                                                          world_size=2)
        with pytest.raises(ValueError, match="format"):
            DistributedFusedAdam.load_sharded_state_dicts(
                [{**shards[0], "format": "bogus"}], world_size=2)

    def test_sharded_state_dict_requires_init(self):
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            DistributedFusedAdamState,
        )

        stub = DistributedFusedAdamState(
            step=jnp.int32(0), exp_avg=(jnp.zeros(8),),
            exp_avg_sq=(jnp.zeros(8),), master_shard=(jnp.zeros(8),))
        with pytest.raises(ValueError, match="init"):
            opt.sharded_state_dict(stub, 0, 2)

    def test_indivisible_model_shard_rejected(self):
        """A param whose sharded DIMENSION isn't divisible by its mesh
        axes must be rejected — floor division would silently misalign
        the flat ZeRO layout."""
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            local_total_and_axes,
        )

        params = {"w": jnp.zeros((13, 5))}
        with pytest.raises(ValueError, match="not divisible"):
            local_total_and_axes(params, {"w": P("tp", None)},
                                 {"tp": 2}, zero_axis="dp")
        with pytest.raises(ValueError, match="not divisible"):
            local_total_and_axes(params, {"w": P("tp", None)},
                                 {"tp": 5}, zero_axis="dp")
        total, axes, repl = local_total_and_axes(
            params, {"w": P(None, "tp")}, {"tp": 5}, zero_axis="dp")
        assert total == 13 and axes == ("tp",) and repl == [1]

    def test_master_kind_mismatch_refused(self):
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), make_tree())
        opt_rem = DistributedFusedAdam(lr=1e-2, store_param_remainders=True)
        state = opt_rem.init(params, world_size=2)
        sd = opt_rem.state_dict(state)
        assert sd["master_kind"] == "remainder_u16"
        opt_f32 = DistributedFusedAdam(lr=1e-2)
        opt_f32.init(params, world_size=2)
        with pytest.raises(ValueError, match="master_kind"):
            opt_f32.load_state_dict(sd)
        opt_rem.load_state_dict(sd)  # matching kind loads
        # a pre-bucket (v1 flat) dict has no format field: refused with
        # the format message, not a misleading bucket-layout crash
        v1 = {"step": 0, "exp_avg": np.zeros(8, np.float32),
              "exp_avg_sq": np.zeros(8, np.float32),
              "master_shard": np.zeros(8, np.float32)}
        with pytest.raises(ValueError, match="format"):
            opt_f32.load_state_dict(v1)

    def test_zero_composed_with_tp_matches_fused_adam(self, devices8):
        """dp=4 × tp=2: params sharded over tp, ZeRO state over
        (tp, dp), BIT-exact vs the per-leaf oracle on exact grads."""
        rng = np.random.RandomState(11)
        params = {
            "w": jnp.asarray(rng.randn(8, 6).astype(np.float32)),
            "b": jnp.asarray(rng.randn(12).astype(np.float32)),
        }
        pspecs = {"w": P("tp", None), "b": P(None)}
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))

        dist = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        state = dist.init(params, world_size=4, param_specs=pspecs,
                          axis_sizes={"tp": 2})
        sspec = dist.state_partition_spec()
        assert sspec.exp_avg[0] == P(("tp", "dp"))

        ref = FusedAdam(lr=1e-2, weight_decay=0.01, master_weights=True,
                        use_buckets=False)
        ref_state = ref.init(params)
        ref_params = params

        for _ in range(3):
            g = exact_grads(rng, params)
            params, state = jax.shard_map(
                lambda p, s, gg: dist.update(gg, s, p),
                mesh=mesh, in_specs=(pspecs, sspec, pspecs),
                out_specs=(pspecs, sspec), check_vma=False,
            )(params, state, g)
            ref_params, ref_state = ref.update(g, ref_state, ref_params)
        assert_bitwise(params, ref_params)


# ------------------------------------------------------------ ZeRO resume
class TestZeroAutoResume:
    """The --auto-resume protocol at pod scale: per-rank shard dicts in
    step_* directories, discovered by ``io.latest_distributed_step``
    with world_size > 1 — and the precision-mismatch failure mode."""

    def _train(self, opt, mesh, params, state, rng, steps):
        for _ in range(steps):
            g = jax.tree.map(
                lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
                params)
            params, state = zero_step(opt, mesh, params, state, g)
        return params, state

    @pytest.mark.slow
    def test_step_dir_roundtrip_world2(self, devices8, tmp_path):
        from apex_tpu import io

        params0 = make_tree(5)
        mesh = Mesh(np.array(devices8[:2]), ("dp",))
        opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        state = opt.init(params0, world_size=2)
        rng = np.random.RandomState(13)
        params, state = self._train(opt, mesh, params0, state, rng, 2)

        # each "process" saves its rank's shard dict into the step dir
        step_dir = tmp_path / f"step_{2:08d}"
        for r in range(2):
            io.save_sharded_checkpoint(
                step_dir,
                {"params": jax.tree.map(np.asarray, params),
                 "opt": opt.sharded_state_dict(state, r, 2)},
                r, 2)
        # an INCOMPLETE newer dir (kill mid-save) must be skipped
        newer = tmp_path / f"step_{3:08d}"
        io.save_sharded_checkpoint(newer, {"torn": np.zeros(3)}, 0, 2)
        (newer / "shard_00000-of-00002.ckpt").rename(newer / "gone.tmp")

        assert io.latest_distributed_step(tmp_path) == 2
        loaded = io.load_sharded_checkpoint(step_dir)
        state_r = DistributedFusedAdam.load_sharded_state_dicts(
            [d["opt"] for d in loaded], world_size=2)
        params_r = jax.tree.map(jnp.asarray, loaded[0]["params"])
        assert int(state_r.step) == 2

        # resumed continuation must equal the uninterrupted run bitwise
        p_cont, s_cont = self._train(opt, mesh, params, state,
                                     np.random.RandomState(17), 1)
        p_res, s_res = self._train(opt, mesh, params_r, state_r,
                                   np.random.RandomState(17), 1)
        assert_bitwise(p_cont, p_res)
        for a, b in zip(jax.tree.leaves(s_cont), jax.tree.leaves(s_res)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_remainder_ckpt_into_fp32_mode_fails_loudly(self, devices8):
        """A bf16 ``store_param_remainders`` state restored into an
        fp32-master optimizer must raise the precision-mismatch message
        at trace time — never a shape/NoneType crash mid-math."""
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), make_tree(6))
        mesh = Mesh(np.array(devices8[:2]), ("dp",))
        opt_rem = DistributedFusedAdam(lr=1e-2, store_param_remainders=True)
        state = opt_rem.init(params, world_size=2)

        # the raw-pytree restore path (pretrain_gpt --auto-resume saves
        # the state tree itself): the wrong-mode optimizer sees uint16
        # shards where it expects fp32 masters
        opt_f32 = DistributedFusedAdam(lr=1e-2)
        opt_f32.init(params, world_size=2)
        g = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
        with pytest.raises(ValueError, match="store_param_remainders"):
            zero_step(opt_f32, mesh, params, state, g)
        # and the reshard path refuses with the master_kind message
        shards = [opt_rem.sharded_state_dict(state, r, 2) for r in range(2)]
        with pytest.raises(ValueError, match="master_kind"):
            DistributedFusedAdam.load_sharded_state_dicts(
                shards, world_size=2, store_param_remainders=False)


# ------------------------------------------------------------------- LAMB
class TestDistributedFusedLAMB:
    @pytest.mark.slow
    def test_matches_fused_lamb(self, devices8):
        """Trust ratios are reduction-fed, so LAMB gets the tight
        allclose band (the bucket-engine convention), not bitwise."""
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                    max_grad_norm=1.0, axis_name="dp")
        state = dist.init(params, world_size=DP)
        ref = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0,
                        use_buckets=False)
        ref_state = ref.init(params)
        ref_params = params
        rng = np.random.RandomState(23)
        for _ in range(4):
            g = jax.tree.map(
                lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
                params)
            params, state = zero_step(dist, mesh, params, state, g)
            ref_params, ref_state = ref.update(g, ref_state, ref_params)
        for a, r in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    @pytest.mark.parametrize("dp_varying_grads", [False, True])
    def test_zero_lamb_composed_with_tp_matches_fused_lamb(
            self, devices8, dp_varying_grads):
        """dp=4 × tp=2: trust ratios and the clip norm must use GLOBAL
        per-tensor norms — psum over tp WITHOUT double-counting
        tp-replicated leaves, and over dp on the AVERAGED grad."""
        rng = np.random.RandomState(21)
        params = {
            "w": jnp.asarray(rng.randn(8, 6).astype(np.float32)),
            "b": jnp.asarray(rng.randn(12).astype(np.float32)),
        }
        pspecs = {"w": P("tp", None), "b": P(None)}
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))

        dist = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                    axis_name="dp", max_grad_norm=1.0)
        state = dist.init(params, world_size=4, param_specs=pspecs,
                          axis_sizes={"tp": 2})
        sspec = dist.state_partition_spec()
        assert sspec.exp_avg[0] == P(("tp", "dp"))

        ref = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0,
                        use_buckets=False)
        ref_state = ref.init(params)
        ref_params = params

        gspecs = jax.tree.map(lambda s: P("dp", *tuple(s)), pspecs)
        step = jax.shard_map(
            lambda p, s, gg: dist.update(
                jax.tree.map(lambda x: x[0], gg), s, p),
            mesh=mesh, in_specs=(pspecs, sspec, gspecs),
            out_specs=(pspecs, sspec), check_vma=False,
        )

        for _ in range(3):
            g_stack = jax.tree.map(
                lambda x: jnp.asarray(
                    rng.randn(4, *x.shape).astype(np.float32)
                    if dp_varying_grads
                    else np.broadcast_to(
                        rng.randn(*x.shape).astype(np.float32), (4, *x.shape)
                    ).copy()
                ),
                params,
            )
            params, state = step(params, state, g_stack)
            g_mean = jax.tree.map(lambda x: jnp.mean(x, axis=0), g_stack)
            ref_params, ref_state = ref.update(g_mean, ref_state, ref_params)

        for a, r in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-5, atol=1e-6)


# --------------------------------------------------- store_param_remainders
class TestStoreParamRemainders:
    """fp32 master = bf16 param bits + stored 16-bit remainder
    (reference distributed_fused_adam.py store_param_remainders)."""

    def test_split_combine_bitwise_roundtrip(self):
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _master_from_remainder,
            _split_master,
        )

        rng = np.random.RandomState(3)
        master = jnp.asarray(
            (rng.randn(257) * 10 ** rng.uniform(-3, 3, 257)).astype(np.float32))
        p_bf16, rem = _split_master(master)
        back = _master_from_remainder(p_bf16.astype(jnp.float32), rem)
        np.testing.assert_array_equal(
            np.asarray(master).view(np.uint32),
            np.asarray(back).view(np.uint32))

    def test_requires_bf16_params(self, devices8):
        opt = DistributedFusedAdam(lr=1e-2, store_param_remainders=True)
        with pytest.raises(ValueError, match="bf16"):
            opt.init(make_tree(), world_size=DP)

    @pytest.mark.slow
    def test_master_trajectory_matches_fp32_mode(self, devices8):
        """The reconstructed master must track the fp32-master mode's
        master bitwise: precision is identical, only storage differs
        (params differ by the documented <=1-ulp trunc-vs-RNE)."""
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _master_from_remainder,
        )

        params0 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), make_tree(7))
        mesh = Mesh(np.array(devices8), ("dp",))
        rng = np.random.RandomState(11)
        grads = [
            jax.tree.map(lambda x: jnp.asarray(
                rng.randn(*x.shape).astype(np.float32)), params0)
            for _ in range(4)
        ]

        def run(store_rem):
            opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                       store_param_remainders=store_rem)
            state = opt.init(params0, world_size=DP)
            pp = params0
            for g in grads:
                pp, state = zero_step(opt, mesh, pp, state, g)
            return opt, pp, state

        opt_r, p_r, s_r = run(True)
        opt_f, p_f, s_f = run(False)

        assert all(a.dtype == jnp.uint16 for a in s_r.master_shard)
        plan = opt_r._plan
        leaves_r = jax.tree.leaves(p_r)
        for bi, b in enumerate(plan.buckets):
            parts = [np.asarray(leaves_r[bl.leaf_id], np.float32).reshape(-1)
                     for bl in b.leaves]
            flat = np.pad(np.concatenate(parts), (0, b.pad))
            master_r = _master_from_remainder(jnp.asarray(flat),
                                              s_r.master_shard[bi])
            np.testing.assert_array_equal(
                np.asarray(master_r).view(np.uint32),
                np.asarray(s_f.master_shard[bi]).view(np.uint32))
        for a, b in zip(jax.tree.leaves(p_r), jax.tree.leaves(p_f)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=1e-2, atol=1e-3)

    @pytest.mark.slow
    def test_overflow_skip_keeps_params(self, devices8):
        params0 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), make_tree(9))
        mesh = Mesh(np.array(devices8), ("dp",))
        opt = DistributedFusedAdam(lr=1e-2, store_param_remainders=True)
        state = opt.init(params0, world_size=DP)
        g = jax.tree.map(
            lambda x: jnp.full(x.shape, jnp.nan, jnp.float32), params0)
        params, state = zero_step(opt, mesh, params0, state, g,
                                  grads_finite=jnp.bool_(False))
        assert int(state.step) == 0
        assert_bitwise(params, params0)


# --------------------------------------------------- quantized grad sync
class TestQuantizedGradSync:
    """int8/fp8 wire traffic with error-feedback residuals
    (``_quantized_sync`` + the engine's quantized ``_prepare_grads``
    branch): bitwise error accounting, residual state discipline, and
    the compressed checkpoint format (v3)."""

    def _qstep(self, opt, mesh, p, s, g, **kw):
        return zero_step(opt, mesh, p, s, g, **kw)

    def test_error_feedback_roundtrip_bitwise(self, devices8):
        """The telescoping identity, BITWISE on crafted inputs:
        transmitted₁ + transmitted₂ + Σ residual₂ == Σ (g₁ + g₂).
        Values are integers/half-integers with per-block amaxes pinned
        to 127·2ᵏ, so the shared scale is an exact power of two and
        every add/multiply in the chain is exact in fp32."""
        from apex_tpu.contrib.optimizers import _quantized_sync as qs

        mesh = Mesh(np.array(devices8[:2]), ("dp",))
        spec = qs.qspec_of("int8")
        N = 2 * qs.QBLOCK
        rng = np.random.RandomState(0)

        def one(h_stack):
            def f(h):
                h = h.reshape(-1)
                rank = jax.lax.axis_index("dp")
                shard, res = qs.quantized_reduce_scatter(
                    h, "dp", spec, rank, 2)
                full = jax.lax.all_gather(shard, "dp", axis=0, tiled=True)
                return full[None], res[None]

            out = jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=P("dp"),
                out_specs=(P("dp"), P("dp")), check_vma=False))(h_stack)
            return map(np.asarray, out)

        def ints(scale):
            # random ints plus a pinned ±127·scale per block per rank:
            # a_loc = 127·scale each, a_sum = 254·scale, s = 2·scale
            h = (rng.randint(-100, 101, size=(2, N)) * scale
                 ).astype(np.float32)
            h[:, 0] = 127.0 * scale
            h[:, qs.QBLOCK] = -127.0 * scale
            return h

        g1 = ints(1)
        t1, res1 = one(jnp.asarray(g1))
        h2 = ints(2)       # the step-2 PRE-quantization values...
        g2 = h2 - res1     # ...reached by grads that absorb residual₁
        t2, res2 = one(jnp.asarray(h2))
        lhs = t1[0] + t2[0] + res2.sum(axis=0)
        rhs = (g1 + g2).sum(axis=0)
        np.testing.assert_array_equal(lhs.view(np.uint32),
                                      rhs.view(np.uint32))
        assert np.abs(res1).max() > 0  # feedback actually engaged

    def test_int8_sum_cannot_overflow_the_wire(self, devices8):
        """Adversarial amaxes: every rank at the int8 clip ceiling.
        The per-rank bounds Σ⌊qmax·amax_r/Σamax⌋ ≤ 127 keep the wire
        sum in range — the dequantized result stays finite and close."""
        from apex_tpu.contrib.optimizers import _quantized_sync as qs

        mesh = Mesh(np.array(devices8), ("dp",))
        spec = qs.qspec_of("int8")
        N = qs.QBLOCK * 8
        h = np.full((8, N), 3.14159e4, np.float32)  # same sign, all big

        def f(h):
            h = h.reshape(-1)
            rank = jax.lax.axis_index("dp")
            shard, _ = qs.quantized_reduce_scatter(h, "dp", spec, rank, 8)
            return jax.lax.all_gather(shard, "dp", axis=0, tiled=True)[None]

        out = np.asarray(jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False))(jnp.asarray(h)))
        assert np.isfinite(out).all()
        # ⌊127/8⌋ per-rank levels: single-shot accuracy is ~1/15 here
        # (the error-feedback residual is what recovers it over steps)
        np.testing.assert_allclose(out[0], h.sum(axis=0), rtol=0.08)

    def test_nonfinite_grads_leave_residual_unchanged(self, devices8):
        """The guarded-step no-op contract: a non-finite grad (which
        the int8 wire itself would MASK — nan casts to a finite int)
        must fail the vote via the pre-quantization values and leave
        params, state, AND the error-feedback residuals untouched."""
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                   axis_name="dp", grad_sync_dtype="int8")
        state = opt.init(params, world_size=DP)
        sspec = opt.state_partition_spec()
        rng = np.random.RandomState(5)
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
            params)

        def scaled(p, s, gg):
            return opt.update_scaled(gg, s, p)

        step = jax.shard_map(
            scaled, mesh=mesh, in_specs=(P(), sspec, P()),
            out_specs=(P(), sspec, P()), check_vma=False)
        p1, s1, fin = step(params, state, g)
        assert bool(fin)
        assert any(float(jnp.abs(r.astype(jnp.float32)).max()) > 0
                   for r in s1.residual)

        bad = jax.tree.map(
            lambda x: x.at[(0,) * x.ndim].set(jnp.nan), g)
        p2, s2, fin2 = step(p1, s1, bad)
        assert not bool(fin2)
        assert int(s2.step) == 1
        assert_bitwise(p2, p1)
        assert_bitwise(s2.residual, s1.residual)

    @pytest.mark.slow
    @pytest.mark.parametrize("wire", ["int8", "float8_e4m3fn",
                                      "float8_e5m2"])
    def test_loss_curve_within_band_of_fp32_sync(self, devices8, wire):
        """The convergence contract (the documented tolerance band,
        docs/optimizers.md): the tiny GPT dp-sharded config trained
        with a quantized wire stays within 5% relative of the
        fp32-sync loss at EVERY step, and within 1% on the mean of the
        last 10 of 50 steps."""
        from apex_tpu.models.gpt import (
            GPTConfig, init_params, make_train_step,
        )

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_seq_len=16,
                        compute_dtype=jnp.float32, checkpoint_layers=False)
        mesh = Mesh(np.array(devices8).reshape(DP, 1), ("dp", "tp"))
        params0 = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        data = [jnp.asarray(rng.randint(0, 64, size=(DP, 16)))
                for _ in range(50)]

        def run(sync):
            opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                       axis_name="dp", grad_sync_dtype=sync)
            state = opt.init(params0, world_size=DP)
            step = make_train_step(cfg, opt, mesh, donate_state=True)
            p = jax.tree.map(lambda x: x.copy(), params0)
            losses = []
            for tok in data:
                p, state, loss = step(p, state, tok,
                                      jnp.roll(tok, -1, axis=1))
                losses.append(float(loss))
            return np.asarray(losses)

        base = run(jnp.float32)
        quant = run(wire)
        rel = np.abs(quant - base) / np.abs(base)
        assert np.isfinite(quant).all()
        assert rel.max() <= 0.05, f"per-step dev {rel.max():.4f}"
        assert rel[-10:].mean() <= 0.01, f"tail dev {rel[-10:].mean():.4f}"

    @pytest.mark.slow
    def test_lamb_quantized_trajectory_close_to_wide(self, devices8):
        """LAMB on the int8 wire: trust-ratio segment sums operate on
        the DEQUANTIZED fp32 shards, so the trajectory tracks the
        wide-wire LAMB to quantization noise."""
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        rng = np.random.RandomState(23)
        grads = [jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
            params) for _ in range(3)]

        def run(**kw):
            opt = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                       max_grad_norm=1.0, axis_name="dp",
                                       **kw)
            state = opt.init(params, world_size=DP)
            p = params
            for g in grads:
                p, state = zero_step(opt, mesh, p, state, g)
            return p, state

        p_w, _ = run()
        p_q, s_q = run(grad_sync_dtype="int8")
        assert all(r.dtype == jnp.float32 for r in s_q.residual)
        for a, b in zip(jax.tree.leaves(p_q), jax.tree.leaves(p_w)):
            # trust ratios divide by per-tensor update norms, so the
            # int8 noise floor is a touch higher than Adam's
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0.05, atol=2e-2)

    def test_quantized_composes_with_tp_and_remainder_master(self, devices8):
        """dp=4 × tp=2 with an int8 wire: residuals shard
        P(("tp","dp")) and each (tp, dp) rank quantizes its LOCAL
        bucket against dp-only shared scales.  Plus the bf16
        remainder-master mode on an fp8 wire — storage-dtype residuals
        (bf16) compose with the uint16 master."""
        rng = np.random.RandomState(11)
        params = {"w": jnp.asarray(rng.randn(8, 6).astype(np.float32)),
                  "b": jnp.asarray(rng.randn(12).astype(np.float32))}
        pspecs = {"w": P("tp", None), "b": P(None)}
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
        dist = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                    axis_name="dp", grad_sync_dtype="int8")
        state = dist.init(params, world_size=4, param_specs=pspecs,
                          axis_sizes={"tp": 2})
        sspec = dist.state_partition_spec()
        assert sspec.residual[0] == P(("tp", "dp"))
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
            params)
        p2, s2 = jax.shard_map(
            lambda p, s, gg: dist.update(gg, s, p),
            mesh=mesh, in_specs=(pspecs, sspec, pspecs),
            out_specs=(pspecs, sspec), check_vma=False,
        )(params, state, g)
        assert int(s2.step) == 1
        assert all(np.isfinite(np.asarray(x)).all()
                   for x in jax.tree.leaves(p2))

        pb = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        mesh2 = Mesh(np.array(devices8[:4]), ("dp",))
        opt = DistributedFusedAdam(lr=1e-2, store_param_remainders=True,
                                   axis_name="dp",
                                   grad_sync_dtype="float8_e5m2")
        st = opt.init(pb, world_size=4)
        assert all(r.dtype == jnp.bfloat16 for r in st.residual)
        g2 = jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
            pb)
        _, s3 = zero_step(opt, mesh2, pb, st, g2)
        assert int(s3.step) == 1

    def test_compressed_resume_bitwise(self, devices8):
        """Format v3 auto-resume: per-rank shard dicts round-trip the
        residuals bitwise at the saved world size, and the resumed
        continuation equals the uninterrupted run bit for bit."""
        params0 = make_tree(5)
        mesh = Mesh(np.array(devices8[:2]), ("dp",))
        opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                   axis_name="dp", grad_sync_dtype="int8")
        state = opt.init(params0, world_size=2)
        rng = np.random.RandomState(13)

        def train(p, s, seed, steps):
            r = np.random.RandomState(seed)
            for _ in range(steps):
                g = jax.tree.map(
                    lambda x: jnp.asarray(r.randn(*x.shape)
                                          .astype(np.float32)), p)
                p, s = zero_step(opt, mesh, p, s, g)
            return p, s

        params, state = train(params0, state, 13, 2)
        shards = [opt.sharded_state_dict(state, r, 2) for r in range(2)]
        assert shards[0]["format"] == "apex_tpu_zero2_v3"
        assert shards[0]["residual_kind"] == "ef"
        state_r = DistributedFusedAdam.load_sharded_state_dicts(
            shards, world_size=2, grad_sync_dtype="int8")
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(state_r)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        p_cont, s_cont = train(params, state, 17, 1)
        p_res, s_res = train(params, state_r, 17, 1)
        assert_bitwise(p_cont, p_res)
        for a, b in zip(jax.tree.leaves(s_cont), jax.tree.leaves(s_res)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_cross_world_reshard_preserves_residual_sum(self, devices8):
        """dp=2 save → dp=4 load: the optimizer trajectory sees only
        Σ_r (g_r + residual_r), so the reshard collapses the per-rank
        errors onto new rank 0 — sum preserved exactly, re-padded with
        the one ``padded_total`` formula."""
        params0 = make_tree(7)
        mesh = Mesh(np.array(devices8[:2]), ("dp",))
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                   grad_sync_dtype="int8")
        state = opt.init(params0, world_size=2)
        rng = np.random.RandomState(3)
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
            params0)
        _, state = zero_step(opt, mesh, params0, state, g)
        shards = [opt.sharded_state_dict(state, r, 2) for r in range(2)]
        state4 = DistributedFusedAdam.load_sharded_state_dicts(
            shards, world_size=4)
        opt4 = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                    grad_sync_dtype="int8")
        opt4.init(params0, world_size=4)
        for old, new, b in zip(state.residual, state4.residual,
                               opt4._plan.buckets):
            assert new.shape[0] == 4 * b.total
            np.testing.assert_allclose(
                np.asarray(old, np.float64).sum(),
                np.asarray(new, np.float64).sum(), rtol=1e-6)

    def test_compressed_state_mismatch_fails_loudly(self, devices8):
        """The remainder-master discipline, mirrored: compressed state
        into an uncompressed optimizer (and the reverse) is refused by
        every load path — and the raw-pytree trace path fails naming
        the residual field, never a shape crash mid-math."""
        params = make_tree(6)
        mesh = Mesh(np.array(devices8[:2]), ("dp",))
        opt_q = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                     grad_sync_dtype="int8")
        s_q = opt_q.init(params, world_size=2)
        opt_w = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        s_w = opt_w.init(params, world_size=2)
        g = jax.tree.map(jnp.zeros_like, params)

        # whole-dict load, both directions
        with pytest.raises(ValueError, match="residual_kind"):
            opt_w.load_state_dict(opt_q.state_dict(s_q))
        with pytest.raises(ValueError, match="residual_kind"):
            opt_q.load_state_dict(opt_w.state_dict(s_w))
        # reshard path with the target wire declared
        shards = [opt_q.sharded_state_dict(s_q, r, 2) for r in range(2)]
        with pytest.raises(ValueError, match="residual_kind"):
            DistributedFusedAdam.load_sharded_state_dicts(
                shards, world_size=2, grad_sync_dtype=None)
        # raw-pytree trace path: the state/spec trees disagree exactly
        # at the residual field and jax names it
        with pytest.raises(ValueError, match="residual"):
            zero_step(opt_w, mesh, params, s_q, g)
        with pytest.raises(ValueError, match="residual"):
            zero_step(opt_q, mesh, params, s_w, g)

    def test_quantized_state_spec_and_wire_accounting(self, devices8):
        """Residuals ride the state spec (donatable like m/v) at full
        local-bucket length per rank; wire accounting charges the fp32
        scale vectors to the quantized modes."""
        params = make_mixed_tree()
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                   grad_sync_dtype="float8_e5m2")
        state = opt.init(params, world_size=DP)
        plan = opt._plan
        spec = opt.state_partition_spec()
        assert spec.residual == tuple(P("dp") for _ in plan.buckets)
        for r, b in zip(state.residual, plan.buckets):
            assert r.shape == (DP * b.total,)
            assert r.dtype == jnp.dtype(b.dtype)  # storage, never wire
        wb = opt.wire_bytes_per_step()
        assert wb["grad_scales"] == sum(
            (b.total // 1024) * 4 for b in plan.buckets)
        # an uncompressed optimizer keeps the residual field EMPTY
        opt_w = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        s_w = opt_w.init(params, world_size=DP)
        assert s_w.residual == ()
        assert opt_w.state_partition_spec().residual == ()


# ------------------------------------------------------ hierarchical sync
HIER_AXES = ("dp_out", "dp_in")
HIER_SIZES = {"dp_out": 2, "dp_in": 2}


def hier_mesh(devices8):
    return Mesh(np.array(devices8[:4]).reshape(2, 2), HIER_AXES)


class TestHierarchicalGradSync:
    """The multi-hop (fast, slow) dp split (``_hierarchical_sync`` +
    the engine's ``dp_axes=`` knob): flat-parity bands, the bitwise
    requantization-error telescoping, residual/state discipline, and
    the construction-time validation."""

    def test_wide_fp32_bitwise_vs_flat_dp4(self, devices8):
        """The acceptance parity band: hierarchical fp32-wire sync on
        the (2, 2) mesh equals flat dp=4 BITWISE over 4 steps — on
        exactly-representable (dyadic) grads, where the only thing the
        two hops could change (the dp-sum association: (a+b)+(c+d) vs
        a flat reduce's order) is exact either way.  Arbitrary fp32
        grads reorder adds ACROSS hops and track to reduction ulps —
        the gpt-level band below pins that."""
        params = make_mixed_tree()
        flat = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                    axis_name="dp")
        s_f = flat.init(params, world_size=4)
        hier = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                    dp_axes=HIER_AXES)
        s_h = hier.init(params, world_size=4, axis_sizes=HIER_SIZES)
        assert hier.hier_plan.world == 4
        mesh_f = Mesh(np.array(devices8[:4]), ("dp",))
        mesh_h = hier_mesh(devices8)
        p_f = p_h = params
        rng = np.random.RandomState(50)
        for _ in range(4):
            g = exact_grads(rng, params)
            p_f, s_f = zero_step(flat, mesh_f, p_f, s_f, g)
            p_h, s_h = zero_step(hier, mesh_h, p_h, s_h, g)
        assert_bitwise(p_f, p_h)
        for a, b in zip(jax.tree.leaves(s_f), jax.tree.leaves(s_h)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_gpt_step_fp32_loss_band_vs_flat(self, devices8):
        """The full ``make_train_step`` trajectory, hierarchical (2, 2)
        vs flat dp=4 on REAL grads: fp32 adds reorder only across the
        two hops, so per-step losses agree to a 1-ulp-class band
        (measured ~6e-8 rel on this config; pinned at 1e-6) — NOT
        bitwise, which is why the bitwise acceptance rides the
        dyadic-grads engine test above."""
        from apex_tpu.models.gpt import GPTConfig, init_params, \
            make_train_step

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_seq_len=16,
                        compute_dtype=jnp.float32, checkpoint_layers=False)
        params0 = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        data = [jnp.asarray(rng.randint(0, 64, size=(4, 16)))
                for _ in range(5)]

        def run(mesh, dp_axis, **opt_kw):
            sizes = HIER_SIZES if "dp_axes" in opt_kw else None
            opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                       **opt_kw)
            state = opt.init(params0, world_size=4, axis_sizes=sizes)
            step = make_train_step(cfg, opt, mesh, dp_axis=dp_axis,
                                   donate_state=True)
            p = jax.tree.map(lambda x: x.copy(), params0)
            losses = []
            for tok in data:
                p, state, loss = step(p, state, tok,
                                      jnp.roll(tok, -1, axis=1))
                losses.append(float(loss))
            return np.asarray(losses)

        mesh_f = Mesh(np.array(devices8[:4]).reshape(4, 1), ("dp", "tp"))
        mesh_h = Mesh(np.array(devices8[:4]).reshape(2, 2, 1),
                      ("dp_out", "dp_in", "tp"))
        l_f = run(mesh_f, "dp", axis_name="dp")
        l_h = run(mesh_h, HIER_AXES, dp_axes=HIER_AXES)
        np.testing.assert_allclose(l_h, l_f, rtol=1e-6)

    def test_requantization_error_telescopes_bitwise(self, devices8):
        """The crafted dyadic-scale acceptance test: on the (2, 2)
        mesh, transmitted + Σ_r residual_r == Σ_r h_r BITWISE through
        BOTH hops.  Per-rank block amaxes are pinned (126, 128)·scale,
        so hop 1's shared scale is 2·scale exactly; the partial-sum
        block amaxes then pin to 254·scale per slice, so hop 2's
        REQUANTIZATION scale is 4·scale exactly — every divide, round,
        clip, and add in the chain is exact fp32 arithmetic, and the
        hop-2 error provably lands in the residual (the pinned entries
        have zero hop-1 error but ±2·scale hop-2 error)."""
        from apex_tpu.contrib.optimizers import _hierarchical_sync as hsync
        from apex_tpu.contrib.optimizers import _quantized_sync as qs

        spec = qs.qspec_of("int8")
        plan = hsync.hierarchical_plan(HIER_AXES, HIER_SIZES)
        mesh = hier_mesh(devices8)
        N = 4 * qs.QBLOCK  # 4 blocks/rank; chunk = 2 blocks ≥ block·outer
        rng = np.random.RandomState(0)

        def craft(scale):
            # rng ints well under the pins; per block, rank dp_in=0
            # pins ±126·scale and dp_in=1 pins ±128·scale (amax sum
            # 254·scale → s1 = 2·scale), alternating sign per block
            h = (rng.randint(-100, 101, size=(4, N)) * scale
                 ).astype(np.float32)
            for d in range(4):  # device order: d = dp_out*2 + dp_in
                pin = 126.0 if d % 2 == 0 else 128.0
                for b in range(4):
                    h[d, b * qs.QBLOCK] = pin * scale * (-1.0) ** b
            return h

        def one(h_stack):
            def f(h):
                h = h.reshape(-1)
                shard, res = hsync.quantized_two_hop_reduce_scatter(
                    h, plan, spec)
                full = hsync.two_hop_all_gather(shard, plan)
                return full[None], res[None]

            out = jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=P(HIER_AXES),
                out_specs=(P(HIER_AXES), P(HIER_AXES)),
                check_vma=False))(h_stack)
            return map(np.asarray, out)

        for scale in (1.0, 4.0):  # dyadic scales, both exact
            h = craft(scale)
            t, res = one(jnp.asarray(h))
            lhs = t[0] + res.sum(axis=0)
            rhs = h.sum(axis=0)
            np.testing.assert_array_equal(
                lhs.view(np.uint32), rhs.view(np.uint32))
            # hop-1 error engaged (odd rng ints halve inexactly)...
            assert np.abs(res).max() > 0
            # ...and the hop-2 REQUANTIZATION error telescopes too: at
            # the pinned entries hop 1 is exact (126/2, 128/2 are
            # integers) while hop 2 rounds 254/4 = 63.5 → 63 (clipped),
            # leaving exactly ±2·scale in the owning rank's chunk
            assert abs(abs(res[0, 0]) - 2.0 * scale) < 1e-6

    def test_hier_int8_nonfinite_step_leaves_residual_unchanged(
            self, devices8):
        """The guarded no-op contract survives the second hop: a nan
        grad fails the (pre-quantization) vote and leaves params AND
        the folded two-hop residuals untouched."""
        params = make_tree()
        mesh = hier_mesh(devices8)
        opt = DistributedFusedAdam(lr=1e-2, dp_axes=HIER_AXES,
                                   grad_sync_dtype="int8")
        state = opt.init(params, world_size=4, axis_sizes=HIER_SIZES)
        sspec = opt.state_partition_spec()
        rng = np.random.RandomState(5)
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
            params)
        step = jax.shard_map(
            lambda p, s, gg: opt.update_scaled(gg, s, p),
            mesh=mesh, in_specs=(P(), sspec, P()),
            out_specs=(P(), sspec, P()), check_vma=False)
        p1, s1, fin = step(params, state, g)
        assert bool(fin)
        assert any(float(jnp.abs(r.astype(jnp.float32)).max()) > 0
                   for r in s1.residual)
        bad = jax.tree.map(lambda x: x.at[(0,) * x.ndim].set(jnp.nan), g)
        p2, s2, fin2 = step(p1, s1, bad)
        assert not bool(fin2)
        assert_bitwise(p2, p1)
        assert_bitwise(s2.residual, s1.residual)

    def test_state_reshards_flat_to_hier_bitwise_same_world(self, devices8):
        """flat dp=4 state → hierarchical (2, 2) optimizer at the SAME
        world: shard ownership is unchanged by design (same chunk per
        flat rank, same padded_total), so the reshard is bitwise and
        the hierarchical continuation runs on it."""
        params = make_tree(9)
        mesh_f = Mesh(np.array(devices8[:4]), ("dp",))
        opt_f = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                     grad_sync_dtype="int8")
        s_f = opt_f.init(params, world_size=4)
        rng = np.random.RandomState(21)
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)),
            params)
        p1, s1 = zero_step(opt_f, mesh_f, params, s_f, g)
        shards = [opt_f.sharded_state_dict(s1, r, 4) for r in range(4)]
        s_h = DistributedFusedAdam.load_sharded_state_dicts(
            shards, world_size=4, grad_sync_dtype="int8")
        for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s_h)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        opt_h = DistributedFusedAdam(lr=1e-2, dp_axes=HIER_AXES,
                                     grad_sync_dtype="int8")
        opt_h.init(params, world_size=4, axis_sizes=HIER_SIZES)
        p2, s2 = zero_step(opt_h, hier_mesh(devices8), p1, s_h, g)
        assert int(s2.step) == 2
        assert all(np.isfinite(np.asarray(x)).all()
                   for x in jax.tree.leaves(p2))

    def test_hier_validation(self, devices8):
        """Construction-time discipline: malformed splits, missing
        axis sizes, world mismatches, and step/optimizer axis-layout
        disagreement all fail loudly with the knob named."""
        from apex_tpu.models.gpt import (
            GPTConfig, make_pp_train_step, make_train_step,
        )

        params = make_tree()
        with pytest.raises(ValueError, match="distinct"):
            DistributedFusedAdam(lr=1e-3, dp_axes=("dp", "dp"))
        with pytest.raises(ValueError, match="two"):
            DistributedFusedAdam(lr=1e-3, dp_axes=("dp",))
        opt = DistributedFusedAdam(lr=1e-3, dp_axes=HIER_AXES)
        with pytest.raises(ValueError, match="axis_sizes"):
            opt.init(params, world_size=4)
        with pytest.raises(ValueError, match="world_size"):
            DistributedFusedAdam(lr=1e-3, dp_axes=HIER_AXES).init(
                params, world_size=8, axis_sizes=HIER_SIZES)

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_attention_heads=2, max_seq_len=16,
                        compute_dtype=jnp.float32)
        mesh_h = Mesh(np.array(devices8[:4]).reshape(2, 2, 1),
                      ("dp_out", "dp_in", "tp"))
        mesh_f = Mesh(np.array(devices8[:4]).reshape(4, 1), ("dp", "tp"))
        flat_opt = DistributedFusedAdam(lr=1e-3, axis_name="dp")
        flat_opt.init(params, world_size=4)
        # hier step needs a hier optimizer with the SAME split
        with pytest.raises(ValueError, match="dp_axes"):
            make_train_step(cfg, flat_opt, mesh_h, dp_axis=HIER_AXES)
        # hier optimizer refuses a flat step
        hier_opt = DistributedFusedAdam(lr=1e-3, dp_axes=HIER_AXES)
        hier_opt.init(params, world_size=4, axis_sizes=HIER_SIZES)
        with pytest.raises(ValueError, match="hierarchical"):
            make_train_step(cfg, hier_opt, mesh_f, dp_axis="dp")
        # the pipeline step's dp sync is flat-only, loudly
        with pytest.raises(NotImplementedError, match="hierarchical"):
            make_pp_train_step(cfg, hier_opt, mesh_h, num_microbatches=2,
                               dp_axis=HIER_AXES)

    @pytest.mark.slow
    @pytest.mark.parametrize("wire", ["int8", "float8_e4m3fn",
                                      "float8_e5m2"])
    def test_hier_loss_curve_within_band_of_fp32_sync(self, devices8,
                                                      wire):
        """The PR 6 convergence contract on the hierarchical wire:
        tiny-GPT on the (2, 2) mesh, 50 steps — every quantized-wire
        loss ≤5% rel of the fp32-wire sync, last-10 mean ≤1%, with the
        requantized slow hop and the folded residuals in the loop."""
        from apex_tpu.models.gpt import (
            GPTConfig, init_params, make_train_step,
        )

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_seq_len=16,
                        compute_dtype=jnp.float32, checkpoint_layers=False)
        mesh = Mesh(np.array(devices8[:4]).reshape(2, 2, 1),
                    ("dp_out", "dp_in", "tp"))
        params0 = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        data = [jnp.asarray(rng.randint(0, 64, size=(4, 16)))
                for _ in range(50)]

        def run(sync):
            opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                       dp_axes=HIER_AXES,
                                       grad_sync_dtype=sync)
            state = opt.init(params0, world_size=4,
                             axis_sizes=HIER_SIZES)
            step = make_train_step(cfg, opt, mesh, dp_axis=HIER_AXES,
                                   donate_state=True)
            p = jax.tree.map(lambda x: x.copy(), params0)
            losses = []
            for tok in data:
                p, state, loss = step(p, state, tok,
                                      jnp.roll(tok, -1, axis=1))
                losses.append(float(loss))
            return np.asarray(losses)

        base = run(jnp.float32)
        quant = run(wire)
        rel = np.abs(quant - base) / np.abs(base)
        assert np.isfinite(quant).all()
        assert rel.max() <= 0.05, f"per-step dev {rel.max():.4f}"
        assert rel[-10:].mean() <= 0.01, f"tail dev {rel[-10:].mean():.4f}"


# -------------------------------------------------------- step-builder seam
class TestStepBuilderSeam:
    def test_zero_axis_mismatch_raises(self, devices8):
        from apex_tpu.models.gpt import GPTConfig, make_train_step

        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_seq_len=16,
                        compute_dtype=jnp.float32)
        opt = DistributedFusedAdam(lr=1e-3, axis_name="data")  # wrong axis
        with pytest.raises(ValueError, match="dp"):
            make_train_step(cfg, opt, mesh)


# ------------------------------------------------- 3-level (dcn) sync
DCN_AXES = ("dcn", "dp_out", "dp_in")
DCN_SIZES = {"dcn": 2, "dp_out": 2, "dp_in": 2}


def dcn_mesh(devices8):
    return Mesh(np.array(devices8).reshape(2, 2, 2), DCN_AXES)


class TestThreeLevelGradSync:
    """The (dcn, dp_out, dp_in) three-hop split: flat-parity bitwise on
    dyadic grads, the three-hop residual telescoping with the dcn hop's
    requantization error provably in the residual, the exact
    ``1/(dp_in·dp_out)`` cross-DCN wire fraction, and validation."""

    def test_wide_fp32_bitwise_vs_flat_dp8(self, devices8):
        """Three hops reassociate the dp sum as ((a+b)+(c+d))+… — on
        exactly-representable grads that is exact either way, so the
        (2, 2, 2) engine equals flat dp=8 BITWISE over 4 steps, the
        same acceptance the two-level split carries at dp=4."""
        params = make_mixed_tree()
        flat = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                    axis_name="dp")
        s_f = flat.init(params, world_size=DP)
        hier = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                    dp_axes=DCN_AXES)
        s_h = hier.init(params, world_size=DP, axis_sizes=DCN_SIZES)
        assert hier.hier_plan.world == DP
        mesh_f = Mesh(np.array(devices8), ("dp",))
        mesh_h = dcn_mesh(devices8)

        # one jitted step per engine, reused across the loop — the
        # shared zero_step retraces per call, which dominates this
        # test's wall time; both sides run the SAME jitted pipeline so
        # the bitwise comparison stays apples-to-apples
        def stepper(dist, mesh):
            sspec = dist.state_partition_spec()
            return jax.jit(jax.shard_map(
                lambda p, s, gg: dist.update(gg, s, p),
                mesh=mesh, in_specs=(P(), sspec, P()),
                out_specs=(P(), sspec), check_vma=False))

        step_f, step_h = stepper(flat, mesh_f), stepper(hier, mesh_h)
        p_f = p_h = params
        rng = np.random.RandomState(51)
        for _ in range(4):
            g = exact_grads(rng, params)
            p_f, s_f = step_f(p_f, s_f, g)
            p_h, s_h = step_h(p_h, s_h, g)
        assert_bitwise(p_f, p_h)
        for a, b in zip(jax.tree.leaves(s_f), jax.tree.leaves(s_h)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_three_hop_requantization_telescopes_bitwise(self, devices8):
        """The crafted dyadic-scale identity at THREE hops:
        transmitted + Σ_r residual_r == Σ_r h_r bitwise on the
        (2, 2, 2) mesh.  Per dcn group, dp_out slice 0 carries the
        (126, 128)·scale dp_in pins and dp_out slice 1 is all zeros,
        which pins every hop's shared scale dyadic: hop 1 gets
        s₁ = 2·scale (254/127); hop 2 sees per-block amaxes 254·scale
        from slice 0 and 0 from slice 1, so s₂ = 2·scale and the
        requantization 254/2 = 127 ≤ bound 127 is EXACT; hop 3 (dcn)
        sums 254 + 254 → s₃ = 4·scale, and its requantization rounds
        the pinned 254/4 = 63.5 up then clips to the 63 bound — leaving
        exactly ±2·scale per dcn rank, the cross-DCN hop's error landing
        in the residual."""
        from apex_tpu.contrib.optimizers import _hierarchical_sync as hsync
        from apex_tpu.contrib.optimizers import _quantized_sync as qs

        spec = qs.qspec_of("int8")
        plan = hsync.hierarchical_plan(DCN_AXES, DCN_SIZES)
        mesh = dcn_mesh(devices8)
        N = 8 * qs.QBLOCK  # 8 blocks/rank; dcn chunk = 1 block
        rng = np.random.RandomState(0)

        def craft(scale):
            h = (rng.randint(-100, 101, size=(8, N)) * scale
                 ).astype(np.float32)
            for d in range(8):  # d = dcn*4 + dp_out*2 + dp_in
                if (d // 2) % 2 == 1:  # dp_out slice 1: silent
                    h[d] = 0.0
                    continue
                pin = 126.0 if d % 2 == 0 else 128.0
                for b in range(N // qs.QBLOCK):
                    h[d, b * qs.QBLOCK] = pin * scale * (-1.0) ** b
            return h

        def one(h_stack):
            def f(h):
                h = h.reshape(-1)
                shard, res = hsync.quantized_multi_hop_reduce_scatter(
                    h, plan, spec)
                full = hsync.multi_hop_all_gather(shard, plan)
                return full[None], res[None]

            out = jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=P(DCN_AXES),
                out_specs=(P(DCN_AXES), P(DCN_AXES)),
                check_vma=False))(h_stack)
            return map(np.asarray, out)

        for scale in (1.0, 4.0):
            h = craft(scale)
            t, res = one(jnp.asarray(h))
            lhs = t[0] + res.sum(axis=0)
            rhs = h.sum(axis=0)
            np.testing.assert_array_equal(
                lhs.view(np.uint32), rhs.view(np.uint32))
            # hop-1 error engaged (odd rng ints halve inexactly)...
            assert np.abs(res).max() > 0
            # ...hop 2 is exact by construction, and the hop-3 (dcn)
            # requantization error telescopes: rank (0,0,0) owns block
            # 0, where hop 1 is exact (126/2, 128/2 integral), hop 2 is
            # exact (254/2 = 127 at the 127 bound), and hop 3 clips
            # 63.5 → 63 — exactly +2·scale in its residual
            assert abs(res[0, 0] - 2.0 * scale) < 1e-6

    def test_cross_dcn_wire_bytes_exact_fraction(self):
        """The acceptance Fraction: the slowest (dcn) hop carries
        EXACTLY 1/(dp_in·dp_out) of the flat plan's grad-sync bytes at
        the same wire dtype — scales included, as exact rationals, not
        a float ratio."""
        from fractions import Fraction

        params = {"w": jnp.zeros((8 * 1024,), jnp.float32)}
        flat = DistributedFusedAdam(lr=1e-3, axis_name="dp",
                                    grad_sync_dtype="int8")
        flat.init(params, world_size=DP)
        h3 = DistributedFusedAdam(lr=1e-3, dp_axes=DCN_AXES,
                                  grad_sync_dtype="int8")
        h3.init(params, world_size=DP, axis_sizes=DCN_SIZES)
        wf = flat.wire_bytes_per_step()
        w3 = h3.wire_bytes_per_step()
        assert set(w3["hops"]) == set(DCN_AXES)
        base = wf["hops"]["dp"]
        cut = Fraction(1, DCN_SIZES["dp_in"] * DCN_SIZES["dp_out"])
        for key in ("grad_payload", "grad_scales", "grad_sync",
                    "param_sync"):
            assert Fraction(w3["hops"]["dcn"][key], base[key]) == cut
            assert Fraction(w3["hops"]["dp_out"][key], base[key]) \
                == Fraction(1, DCN_SIZES["dp_in"])
            assert w3["hops"]["dp_in"][key] == base[key]

    def test_three_level_validation(self, devices8):
        params = make_tree()
        with pytest.raises(ValueError, match="two or three"):
            DistributedFusedAdam(lr=1e-3, dp_axes=("a", "b", "c", "d"))
        with pytest.raises(ValueError, match="distinct"):
            DistributedFusedAdam(lr=1e-3, dp_axes=("dcn", "dp", "dp"))
        opt = DistributedFusedAdam(lr=1e-3, dp_axes=DCN_AXES)
        with pytest.raises(ValueError, match="axis_sizes"):
            opt.init(params, world_size=8,
                     axis_sizes={"dcn": 2, "dp_out": 2})
        with pytest.raises(ValueError, match="world_size"):
            DistributedFusedAdam(lr=1e-3, dp_axes=DCN_AXES).init(
                params, world_size=4, axis_sizes=DCN_SIZES)


# --------------------------------------------- backward-overlapped sync
class TestOverlappedGradSync:
    """``make_train_step(overlap_grad_sync=True)``: each bucket's sync
    collective is traced inside the backward, between the segment vjps
    — the SAME per-bucket ops on the SAME values as the unoverlapped
    build, merely reordered in the trace.  So fp32 losses and params
    are pinned BITWISE against ``overlap_grad_sync=False`` (Adam and
    LAMB, flat and hierarchical), and the quantized wires too (the
    error-feedback chain sees identical inputs).  The interleaved
    lowering itself is pinned in tests/test_lowered_invariants.py."""

    CFG = dict(vocab_size=64, hidden_size=32, num_layers=2,
               num_attention_heads=4, max_seq_len=16,
               compute_dtype=jnp.float32, checkpoint_layers=False)

    def _pair(self, devices8, make_opt, topo, scaler=None,
              grad_sync_dtype=None, steps=5):
        """Run overlap on/off through the real step builder; assert
        loss lists equal and params bitwise."""
        from apex_tpu.models.gpt import (
            GPTConfig, init_params, make_train_step,
        )

        cfg = GPTConfig(**self.CFG)
        params0 = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        data = [jnp.asarray(rng.randint(0, 64, size=(8, 16)))
                for _ in range(steps)]
        devs = np.array(devices8)
        if topo == "flat":
            mesh = Mesh(devs.reshape(8, 1), ("dp", "tp"))
            dp_axis, sizes = "dp", None
        elif topo == "hier":
            mesh = Mesh(devs.reshape(2, 4, 1), ("dp_out", "dp_in", "tp"))
            dp_axis, sizes = HIER_AXES, {"dp_out": 2, "dp_in": 4}
        else:  # "dcn"
            mesh = Mesh(devs.reshape(2, 2, 2, 1),
                        ("dcn", "dp_out", "dp_in", "tp"))
            dp_axis, sizes = DCN_AXES, dict(DCN_SIZES)

        def run(overlap):
            opt = make_opt(dp_axis)
            if hasattr(opt, "state_partition_spec"):
                state = opt.init(params0, world_size=DP,
                                 axis_sizes=sizes)
            else:
                state = opt.init(params0)
            kw = {"loss_scaler": scaler} if scaler else {}
            step = make_train_step(cfg, opt, mesh, dp_axis=dp_axis,
                                   overlap_grad_sync=overlap,
                                   grad_sync_dtype=grad_sync_dtype,
                                   donate_state=True, **kw)
            p = jax.tree.map(lambda x: x.copy(), params0)
            sc = scaler.init() if scaler else None
            losses = []
            for tok in data:
                tgt = jnp.roll(tok, -1, axis=1)
                if scaler:
                    p, state, sc, loss = step(p, state, sc, tok, tgt)
                else:
                    p, state, loss = step(p, state, tok, tgt)
                losses.append(float(loss))
            return losses, p

        base, ovl = run(False), run(True)
        assert ovl[0] == base[0], \
            f"{topo}: losses diverged {base[0]} vs {ovl[0]}"
        assert_bitwise(ovl[1], base[1], err=f"{topo}: ")

    @pytest.mark.parametrize("topo", ["flat", "hier"])
    @pytest.mark.parametrize("opt_cls", [DistributedFusedAdam,
                                         DistributedFusedLAMB])
    def test_fp32_bitwise_vs_unoverlapped(self, devices8, topo, opt_cls):
        """The headline acceptance: 5 fp32 steps, flat dp=8 and the
        (2, 4) hierarchical split, Adam and LAMB — losses equal,
        params bitwise."""
        def mk(dp_axis):
            kw = ({"dp_axes": dp_axis} if isinstance(dp_axis, tuple)
                  else {"axis_name": dp_axis})
            return opt_cls(lr=1e-3, weight_decay=0.01,
                           bucket_cap_mb=0.02, **kw)

        self._pair(devices8, mk, topo)

    def test_fp32_bitwise_three_level(self, devices8):
        """The (dcn, dp_out, dp_in) pipeline: per-hop wires issued
        inside the backward, still bitwise vs the unoverlapped trace."""
        self._pair(devices8,
                   lambda ax: DistributedFusedAdam(
                       lr=1e-3, bucket_cap_mb=0.02, dp_axes=ax),
                   "dcn")

    @pytest.mark.parametrize("topo,wire", [
        ("flat", "int8"),
        # the dcn leg re-proves what flat-int8 + the fp32 three-level
        # pair already pin — extra assurance, slow tier
        pytest.param("dcn", "int8", marks=pytest.mark.slow),
        ("flat", "float8_e5m2")])
    def test_quantized_wire_bitwise(self, devices8, topo, wire):
        """The compressed wires: identical per-bucket quantize →
        scatter → dequantize chains on identical cotangents, so the
        overlap build is bitwise too — stronger than the PR 6
        convergence band the wire itself is held to."""
        def mk(dp_axis):
            kw = ({"dp_axes": dp_axis} if isinstance(dp_axis, tuple)
                  else {"axis_name": dp_axis})
            return DistributedFusedAdam(lr=1e-3, bucket_cap_mb=0.02,
                                        grad_sync_dtype=wire, **kw)

        self._pair(devices8, mk, topo)

    @pytest.mark.slow
    @pytest.mark.parametrize("topo", ["flat", "dcn"])
    def test_replicated_quantized_overlap_bitwise(self, devices8, topo):
        """The non-ZeRO per-bucket path (``grad_sync_dtype=`` on a
        replicated optimizer): quantized pmean per bucket inside the
        backward, bitwise vs the post-backward sweep."""
        self._pair(devices8, lambda ax: FusedAdam(lr=1e-3), topo,
                   grad_sync_dtype="int8")

    @pytest.mark.slow
    def test_scaled_lamb_overlap_bitwise(self, devices8):
        """Loss scaling composes: the wires carry SCALED cotangents
        (unscale folds into the update tail), so the scaler variant is
        bitwise too — hierarchical LAMB, the hardest composition."""
        from apex_tpu.amp import DynamicLossScaler

        self._pair(devices8,
                   lambda ax: DistributedFusedLAMB(
                       lr=1e-3, bucket_cap_mb=0.02, dp_axes=ax),
                   "hier", scaler=DynamicLossScaler(init_scale=2.0 ** 10))

    def test_overlap_validation(self, devices8):
        """The knob fails loudly where there is nothing to overlap:
        GSPMD (no explicit collectives), dp_axis=None (no dp sync),
        and a replicated optimizer without a per-bucket wire."""
        from apex_tpu.models.gpt import GPTConfig, make_train_step

        cfg = GPTConfig(**self.CFG)
        devs = np.array(devices8)
        mesh = Mesh(devs.reshape(8, 1), ("dp", "tp"))
        with pytest.raises(NotImplementedError, match="GSPMD"):
            make_train_step(cfg, FusedAdam(lr=1e-3), mesh,
                            spmd="auto", overlap_grad_sync=True)
        with pytest.raises(ValueError, match="dp_axis=None"):
            make_train_step(cfg, FusedAdam(lr=1e-3),
                            Mesh(devs.reshape(8, 1), ("x", "tp")),
                            dp_axis=None, overlap_grad_sync=True)
        with pytest.raises(ValueError, match="per-bucket dp grad sync"):
            make_train_step(cfg, FusedAdam(lr=1e-3), mesh,
                            overlap_grad_sync=True)
