"""ZeRO optimizer tests — mirrors apex/contrib/test/optimizers/
test_dist_adam.py: the sharded optimizer must match the non-sharded
fused optimizer exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.optimizers import DistributedFusedAdam, DistributedFusedLAMB
from apex_tpu.optimizers import FusedAdam, FusedLAMB

DP = 8


def make_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "a": jnp.asarray(rng.randn(13, 5).astype(np.float32)),
        "b": {"w": jnp.asarray(rng.randn(31).astype(np.float32))},
    }


def run_sharded(opt_cls, ref_opt, devices8, nsteps=4, seed=0, **kw):
    params = make_tree(seed)
    mesh = Mesh(np.array(devices8), ("dp",))

    dist = opt_cls(lr=1e-2, weight_decay=kw.pop("weight_decay", 0.01), axis_name="dp", **kw)
    state = dist.init(params, world_size=DP)

    ref_state = ref_opt.init(params)
    ref_params = params

    rng = np.random.RandomState(seed + 50)
    for _ in range(nsteps):
        g = jax.tree.map(lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)), params)

        def stepper(params, state, grads):
            return dist.update(grads, state, params)

        sspec = dist.state_partition_spec()
        params, state = jax.shard_map(
            stepper,
            mesh=mesh,
            in_specs=(P(), sspec, P()),
            out_specs=(P(), sspec),
            check_vma=False,
        )(params, state, g)

        # reference: the same grads, averaged identically (each dp rank got
        # identical grads here, so psum/world == grads)
        ref_params, ref_state = ref_opt.update(g, ref_state, ref_params)
    return params, ref_params


class TestDistributedFusedAdam:
    @pytest.mark.slow
    def test_matches_fused_adam(self, devices8):
        ref = FusedAdam(lr=1e-2, weight_decay=0.01, adam_w_mode=True)
        params, ref_params = run_sharded(DistributedFusedAdam, ref, devices8)
        for a, r in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-5, atol=1e-6)

    def test_update_collective_structure(self, devices8):
        """The flat-shard design's communication is exactly ONE
        reduce-scatter (grads -> this rank's shard, fused with the dp
        mean) and ONE all-gather (updated shard -> full params) per
        update — the structure the overlap claim
        (distributed_fused_adam.py:12-18) rests on.  Extra collectives
        (e.g. a separate grad allreduce) would serialize and double the
        traffic; count them in the compiled HLO on the virtual mesh."""
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = dist.init(params, world_size=DP)
        sspec = dist.state_partition_spec()
        g = jax.tree.map(jnp.ones_like, params)

        f = jax.jit(jax.shard_map(
            lambda p, s, gg: dist.update(gg, s, p),
            mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec),
            check_vma=False,
        ))
        txt = f.lower(params, state, g).compile().as_text()
        n_rs = txt.count(" reduce-scatter(")
        n_ag = txt.count(" all-gather(")
        n_ar = txt.count(" all-reduce(")
        assert n_rs == 1, f"expected 1 reduce-scatter, HLO has {n_rs}"
        assert n_ag == 1, f"expected 1 all-gather, HLO has {n_ag}"
        assert n_ar == 0, f"expected no all-reduce, HLO has {n_ar}"

    def test_state_is_sharded(self, devices8):
        params = make_tree()
        total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = dist.init(params, world_size=DP)
        # global flat state padded to a dp multiple; sharded via the spec
        padded = ((total + DP - 1) // DP) * DP
        assert state.exp_avg.shape[0] == padded
        spec = dist.state_partition_spec()
        assert spec.exp_avg == P("dp")

    @pytest.mark.slow
    def test_overflow_skip(self, devices8):
        params = make_tree()
        mesh = Mesh(np.array(devices8), ("dp",))
        dist = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = dist.init(params, world_size=DP)
        g = jax.tree.map(lambda x: jnp.full(x.shape, jnp.inf), params)

        def stepper(params, state, grads):
            return dist.update(grads, state, params, grads_finite=jnp.bool_(False))

        sspec = dist.state_partition_spec()
        new_params, new_state = jax.shard_map(
            stepper, mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec), check_vma=False
        )(params, state, g)
        for a, r in zip(jax.tree.leaves(new_params), jax.tree.leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(r))
        assert int(new_state.step) == 0


def _zero_step(dist, mesh, params, state, g):
    sspec = dist.state_partition_spec()
    return jax.shard_map(
        lambda p, s, gg: dist.update(gg, s, p),
        mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec),
        check_vma=False,
    )(params, state, g)


class TestShardedStateDict:
    """Per-rank save + cross-world reshard (reference
    distributed_fused_adam.py:2527,2959)."""

    def _grads(self, params, rng):
        return jax.tree.map(
            lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)), params
        )

    @pytest.mark.slow
    @pytest.mark.parametrize("via_disk", [False, True], ids=["memory", "disk"])
    def test_save_dp4_load_dp2_resumes_identically(self, devices8, tmp_path, via_disk):
        """Per-rank save at dp=4, resume at dp=2, trajectory parity vs
        the uninterrupted run.  ``via_disk`` composes ZeRO with io: the
        state shards go through per-rank files (io.save_sharded_
        checkpoint) and the params through the async checkpointer, and
        the disk round trip must be bit-exact vs the in-memory dicts
        (reference distributed_fused_adam.py:2527, :2959)."""
        params0 = make_tree(3)
        rng = np.random.RandomState(7)

        # --- run 3 steps at dp=4, checkpoint per rank
        mesh4 = Mesh(np.array(devices8[:4]), ("dp",))
        opt4 = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        state = opt4.init(params0, world_size=4)
        params = params0
        for _ in range(3):
            params, state = _zero_step(opt4, mesh4, params, state, self._grads(params, rng))
        shards = [opt4.sharded_state_dict(state, r, 4) for r in range(4)]
        assert shards[0]["format"] == DistributedFusedAdam.SHARD_FORMAT
        assert shards[0]["shard_numel"] * 4 == shards[0]["padded_total"]

        # --- resume at dp=2, continuing the same grad stream
        if via_disk:
            from apex_tpu import io

            zdir = tmp_path / "zero"
            for r, sd in enumerate(shards):
                io.save_sharded_checkpoint(zdir, sd, r, 4)
            with io.AsyncCheckpointer() as ck:
                ck.save(tmp_path / "params.ckpt", params)
            loaded = io.load_sharded_checkpoint(zdir)
            state2 = DistributedFusedAdam.load_sharded_state_dicts(loaded, world_size=2)
            state2_mem = DistributedFusedAdam.load_sharded_state_dicts(shards, world_size=2)
            for a, b in zip(jax.tree.leaves(state2), jax.tree.leaves(state2_mem)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            params_r = jax.tree.map(jnp.asarray, io.load_checkpoint(tmp_path / "params.ckpt"))
        else:
            state2 = DistributedFusedAdam.load_sharded_state_dicts(shards, world_size=2)
            # a real resume re-reads params from the checkpoint: drop the
            # old mesh's device placement
            params_r = jax.tree.map(lambda x: jnp.asarray(np.asarray(x)), params)
        assert int(state2.step) == 3
        total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params0))
        assert state2.exp_avg.shape[0] == ((total + 1) // 2) * 2
        mesh2 = Mesh(np.array(devices8[:2]), ("dp",))
        opt2 = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        for _ in range(2):
            params_r, state2 = _zero_step(opt2, mesh2, params_r, state2, self._grads(params_r, rng))

        # --- oracle: uninterrupted dp=4 run over the identical grad stream
        rng_o = np.random.RandomState(7)
        opt_o = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        state_o = opt_o.init(params0, world_size=4)
        params_o = params0
        for _ in range(5):
            params_o, state_o = _zero_step(opt_o, mesh4, params_o, state_o, self._grads(params_o, rng_o))

        for a, r in zip(jax.tree.leaves(params_r), jax.tree.leaves(params_o)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-6, atol=1e-7)

    def test_incomplete_shard_set_rejected(self, devices8):
        params = make_tree(4)
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = opt.init(params, world_size=4)
        shards = [opt.sharded_state_dict(state, r, 4) for r in range(4)]
        with pytest.raises(ValueError, match="incomplete"):
            DistributedFusedAdam.load_sharded_state_dicts(shards[:3], world_size=2)
        with pytest.raises(ValueError, match="format"):
            DistributedFusedAdam.load_sharded_state_dicts(
                [{**shards[0], "format": "bogus"}], world_size=2
            )

    @pytest.mark.slow
    def test_zero_composed_with_tp_matches_fused_adam(self, devices8):
        """dp=4 x tp=2: params sharded over tp, ZeRO state over (tp, dp)."""
        rng = np.random.RandomState(11)
        params = {
            "w": jnp.asarray(rng.randn(8, 6).astype(np.float32)),
            "b": jnp.asarray(rng.randn(12).astype(np.float32)),
        }
        pspecs = {"w": P("tp", None), "b": P(None)}
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))

        dist = DistributedFusedAdam(lr=1e-2, weight_decay=0.01, axis_name="dp")
        state = dist.init(params, world_size=4, param_specs=pspecs,
                          axis_sizes={"tp": 2})
        sspec = dist.state_partition_spec()
        assert sspec.exp_avg == P(("tp", "dp"))

        ref = FusedAdam(lr=1e-2, weight_decay=0.01, adam_w_mode=True)
        ref_state = ref.init(params)
        ref_params = params

        for _ in range(3):
            g = jax.tree.map(lambda x: jnp.asarray(rng.randn(*x.shape).astype(np.float32)), params)
            params, state = jax.shard_map(
                lambda p, s, gg: dist.update(gg, s, p),
                mesh=mesh, in_specs=(pspecs, sspec, pspecs),
                out_specs=(pspecs, sspec), check_vma=False,
            )(params, state, g)
            ref_params, ref_state = ref.update(g, ref_state, ref_params)

        for a, r in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-5, atol=1e-6)

    def test_requires_total_numel(self):
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = DistributedFusedAdamStateStub()
        with pytest.raises(ValueError, match="total_numel"):
            opt.sharded_state_dict(state, 0, 2)

    def test_indivisible_model_shard_rejected(self):
        """A param whose numel isn't divisible by its mesh-axis sizes
        must be rejected — floor division would silently misalign the
        flat ZeRO layout."""
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            local_total_and_axes,
        )

        params = {"w": jnp.zeros((13, 5))}  # dim 0 (13) not divisible by tp=2
        with pytest.raises(ValueError, match="not divisible"):
            local_total_and_axes(params, {"w": P("tp", None)},
                                 {"tp": 2}, zero_axis="dp")
        # the check is per-dimension: total 65 IS divisible by 5, but
        # dim 0 (13) split 5 ways still misaligns — must raise
        with pytest.raises(ValueError, match="not divisible"):
            local_total_and_axes(params, {"w": P("tp", None)},
                                 {"tp": 5}, zero_axis="dp")
        # dim 1 (5) split 5 ways is fine
        total, axes, repl = local_total_and_axes(
            params, {"w": P(None, "tp")}, {"tp": 5}, zero_axis="dp")
        assert total == 13 and axes == ("tp",) and repl == [1]


class DistributedFusedAdamStateStub:
    exp_avg = jnp.zeros((8,), jnp.float32)
    exp_avg_sq = jnp.zeros((8,), jnp.float32)
    master_shard = jnp.zeros((8,), jnp.float32)
    step = jnp.int32(0)


class TestDistributedFusedLAMB:
    @pytest.mark.slow
    def test_matches_fused_lamb(self, devices8):
        ref = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        params, ref_params = run_sharded(
            DistributedFusedLAMB, ref, devices8, weight_decay=0.01, max_grad_norm=1.0
        )
        for a, r in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-5)


class TestStoreParamRemainders:
    """fp32 master = bf16 param bits + stored 16-bit remainder
    (reference distributed_fused_adam.py store_param_remainders)."""

    def test_split_combine_bitwise_roundtrip(self):
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _master_from_remainder,
            _split_master,
        )

        rng = np.random.RandomState(3)
        master = jnp.asarray((rng.randn(257) * 10 ** rng.uniform(-3, 3, 257)).astype(np.float32))
        p_bf16, rem = _split_master(master)
        back = _master_from_remainder(p_bf16.astype(jnp.float32), rem)
        np.testing.assert_array_equal(
            np.asarray(master).view(np.uint32), np.asarray(back).view(np.uint32))

    def test_requires_bf16_params(self, devices8):
        opt = DistributedFusedAdam(lr=1e-2, store_param_remainders=True)
        with pytest.raises(ValueError, match="bf16"):
            opt.init(make_tree(), world_size=DP)

    @pytest.mark.slow
    def test_master_trajectory_matches_fp32_mode(self, devices8):
        """The reconstructed master must track the fp32-master mode's
        master bitwise: precision is identical, only storage differs."""
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            _master_from_remainder,
        )

        params0 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), make_tree(7))
        mesh = Mesh(np.array(devices8), ("dp",))
        rng = np.random.RandomState(11)
        grads = [
            jax.tree.map(lambda x: jnp.asarray(
                rng.randn(*x.shape).astype(np.float32)), params0)
            for _ in range(4)
        ]

        def run(store_rem):
            opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                       store_param_remainders=store_rem)
            state = opt.init(params0, world_size=DP)
            sspec = opt.state_partition_spec()
            params = params0
            for g in grads:
                params, state = jax.shard_map(
                    lambda p, s, g: opt.update(g, s, p),
                    mesh=mesh, in_specs=(P(), sspec, P()),
                    out_specs=(P(), sspec), check_vma=False,
                )(params, state, g)
            return opt, params, state

        opt_r, p_r, s_r = run(True)
        opt_f, p_f, s_f = run(False)

        assert s_r.master_shard.dtype == jnp.uint16  # half the memory
        # reconstruct the remainder-mode master from (params, remainder)
        leaves = [np.asarray(x, np.float32).reshape(-1) for x in jax.tree.leaves(p_r)]
        flat_p = np.concatenate(leaves)
        padded = s_r.master_shard.shape[0]
        flat_p = np.pad(flat_p, (0, padded - flat_p.size))
        master_r = _master_from_remainder(
            jnp.asarray(flat_p), s_r.master_shard)
        np.testing.assert_array_equal(
            np.asarray(master_r).view(np.uint32),
            np.asarray(s_f.master_shard).view(np.uint32))
        # params agree to bf16 rounding-mode differences (trunc vs RNE)
        for a, b in zip(jax.tree.leaves(p_r), jax.tree.leaves(p_f)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=1e-2, atol=1e-3)

    @pytest.mark.slow
    def test_overflow_skip_keeps_params(self, devices8):
        params0 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), make_tree(9))
        mesh = Mesh(np.array(devices8), ("dp",))
        opt = DistributedFusedAdam(lr=1e-2, store_param_remainders=True)
        state = opt.init(params0, world_size=DP)
        sspec = opt.state_partition_spec()
        g = jax.tree.map(lambda x: jnp.full(x.shape, jnp.nan, jnp.float32), params0)
        params, state = jax.shard_map(
            lambda p, s, g: opt.update(g, s, p, grads_finite=jnp.bool_(False)),
            mesh=mesh, in_specs=(P(), sspec, P()),
            out_specs=(P(), sspec), check_vma=False,
        )(params0, state, g)
        assert int(state.step) == 0
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params0)):
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32))


    def test_master_kind_mismatch_refused(self):
        opt_rem = DistributedFusedAdam(lr=1e-2, store_param_remainders=True)
        opt_f32 = DistributedFusedAdam(lr=1e-2)
        sd = {"step": 0, "master_kind": "remainder_u16",
              "exp_avg": np.zeros(8, np.float32),
              "exp_avg_sq": np.zeros(8, np.float32),
              "master_shard": np.zeros(8, np.uint16)}
        with pytest.raises(ValueError, match="master_kind"):
            opt_f32.load_state_dict(sd)
        sd["master_kind"] = "fp32"
        sd["master_shard"] = np.zeros(8, np.float32)
        opt_f32.load_state_dict(sd)  # ok
        with pytest.raises(ValueError, match="master_kind"):
            opt_rem.load_state_dict(sd)
        # pre-remainder checkpoints (no field) load as fp32
        del sd["master_kind"]
        opt_f32.load_state_dict(sd)


class TestDistributedLAMBWithTP:
    @pytest.mark.slow
    @pytest.mark.parametrize("dp_varying_grads", [False, True])
    def test_zero_lamb_composed_with_tp_matches_fused_lamb(self, devices8, dp_varying_grads):
        """dp=4 x tp=2: trust ratios and the clip norm must use GLOBAL
        per-tensor norms — psum over tp WITHOUT double-counting
        tp-replicated leaves, and over dp on the AVERAGED grad (the
        dp_varying_grads case feeds each dp rank a different
        microbatch gradient, the reference sees their mean)."""
        rng = np.random.RandomState(21)
        params = {
            "w": jnp.asarray(rng.randn(8, 6).astype(np.float32)),
            "b": jnp.asarray(rng.randn(12).astype(np.float32)),
        }
        pspecs = {"w": P("tp", None), "b": P(None)}
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))

        dist = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01, axis_name="dp",
                                    max_grad_norm=1.0)
        state = dist.init(params, world_size=4, param_specs=pspecs,
                          axis_sizes={"tp": 2})
        sspec = dist.state_partition_spec()
        assert sspec.exp_avg == P(("tp", "dp"))

        ref = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        ref_state = ref.init(params)
        ref_params = params

        gspecs = jax.tree.map(lambda s: P("dp", *tuple(s)), pspecs)
        step = jax.shard_map(
            lambda p, s, gg: dist.update(
                jax.tree.map(lambda x: x[0], gg), s, p),
            mesh=mesh, in_specs=(pspecs, sspec, gspecs),
            out_specs=(pspecs, sspec), check_vma=False,
        )

        for _ in range(3):
            # per-dp-rank grads stacked on a leading dp axis; identical
            # across ranks unless dp_varying_grads
            g_stack = jax.tree.map(
                lambda x: jnp.asarray(
                    rng.randn(4, *x.shape).astype(np.float32)
                    if dp_varying_grads
                    else np.broadcast_to(
                        rng.randn(*x.shape).astype(np.float32), (4, *x.shape)
                    ).copy()
                ),
                params,
            )
            params, state = step(params, state, g_stack)
            # ZeRO grad sync averages over dp — the oracle sees the mean
            g_mean = jax.tree.map(lambda x: jnp.mean(x, axis=0), g_stack)
            ref_params, ref_state = ref.update(g_mean, ref_state, ref_params)

        for a, r in zip(jax.tree.leaves(params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-5, atol=1e-6)
