"""The runtime uniformity seam (ISSUE 16 tier 3): cross-process
divergence fails LOUDLY through ``resilience.uniformity`` with a named
tag — never the device-side wedge the APX209–211 static rules and the
``assert_same_collective_schedule`` lowering pin prove statically.

Real multi-process runs don't exist on the CPU test mesh, so the
transport is injected: a fake gather returns the per-rank views a pod
would produce, including the one-divergent-rank and the
rank-never-recorded (divergent call count) shapes.  That injection
seam — ``gather=`` / ``install_gather`` — is the same one the chaos
harness uses.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu.resilience import uniformity as U


@pytest.fixture(autouse=True)
def _isolated():
    U.reset_uniformity()
    yield
    U.reset_uniformity()


def _pod_view(n_ranks, mutate=None):
    """A gather returning ``n_ranks`` copies of the local payload,
    with ``mutate(rank_payload, rank)`` applied to each."""
    def gather(payload):
        views = [dict(payload) for _ in range(n_ranks)]
        if mutate is not None:
            for rank, view in enumerate(views):
                mutate(view, rank)
        return views
    return gather


class TestUniformDigest:
    def test_key_order_insensitive(self):
        assert U.uniform_digest({"a": 1, "b": [2, 3]}) == \
            U.uniform_digest({"b": [2, 3], "a": 1})

    def test_distinct_values_distinct_digests(self):
        assert U.uniform_digest({"cap": 1 << 20}) != \
            U.uniform_digest({"cap": 1 << 21})

    def test_sets_numpy_bytes_canonicalize(self):
        assert U.uniform_digest({1, 2, 3}) == U.uniform_digest({3, 2, 1})
        assert U.uniform_digest(np.int64(7)) == U.uniform_digest(7)
        U.uniform_digest(b"\x00\xff")            # doesn't raise
        U.uniform_digest(jnp.float32(1.5))       # jax scalars too


class TestAssertUniform:
    def test_record_only_no_transport_touched(self):
        """The contract that keeps divergent runs from wedging INSIDE
        the seam: with no gather installed, assert_uniform performs no
        communication at all — it just records."""
        d = U.assert_uniform("zero.bucket_plan", {"world": 8})
        assert U.recorded_decisions() == {"zero.bucket_plan": d}

    def test_rerecording_same_decision_is_fine(self):
        d1 = U.assert_uniform("t", [1, 2])
        d2 = U.assert_uniform("t", [1, 2])
        assert d1 == d2

    def test_eager_gather_raises_named_error(self):
        def gather(payload):
            return [dict(payload), {"t": "divergent-digest"}]
        with pytest.raises(U.UniformityError) as ei:
            U.assert_uniform("t", 5, gather=gather)
        assert ei.value.tag == "t"


class TestCheckUniform:
    def test_single_process_is_a_noop(self):
        U.assert_uniform("t", 1)
        payload = U.check_uniform()       # default gather, 1 process
        assert "t" in payload

    def test_installed_gather_is_the_transport(self):
        U.assert_uniform("t", 1)
        calls = []

        def gather(payload):
            calls.append(payload)
            return [payload]

        prev = U.install_gather(gather)
        assert prev is None
        U.check_uniform()
        assert calls and "t" in calls[0]
        U.install_gather(None)

    def test_provider_evaluated_at_check_time(self):
        state = {"plan": [4, 4]}
        U.register_uniform("zero.bucket_plan", lambda: state["plan"])
        p1 = U.check_uniform(gather=_pod_view(2))
        state["plan"] = [8, 8]
        p2 = U.check_uniform(gather=_pod_view(2))
        assert p1["zero.bucket_plan"] != p2["zero.bucket_plan"]

    def test_error_names_the_tag_and_all_views(self):
        U.assert_uniform("serve.scheduler_config", {"max_batch": 3})
        U.assert_uniform("zero.bucket_plan", {"world": 8})

        def mutate(view, rank):
            if rank == 2:
                view["zero.bucket_plan"] = "0000000000000000"

        with pytest.raises(U.UniformityError) as ei:
            U.check_uniform(gather=_pod_view(4, mutate))
        err = ei.value
        assert err.tag == "zero.bucket_plan"
        assert len(err.views) == 4
        assert "process 2" in str(err) and "wedge" in str(err)

    def test_divergent_call_count_shape_is_caught(self):
        """A rank that never REACHED the decision (the classic
        if-process_index-skips-the-call bug) shows as <never
        recorded> — the shape a per-call collective could only wedge
        on, and the reason assert_uniform is record-by-default."""
        U.assert_uniform("kernel_registry.engaged/forced=False", True)

        def mutate(view, rank):
            if rank == 1:
                view.clear()

        with pytest.raises(U.UniformityError) as ei:
            U.check_uniform(gather=_pod_view(2, mutate))
        assert "never recorded" in str(ei.value)


class TestChaosOneRankDiverges:
    """The headline chaos test: provoke exactly one divergent rank in
    each retrofitted decision and require the loud, named failure."""

    def test_one_rank_kernel_degrade_fails_loudly(self):
        from apex_tpu.resilience.fallback import registry_engaged

        engaged = registry_engaged(False)     # the real seam records
        tag = "kernel_registry.engaged/forced=False"
        assert tag in U.recorded_decisions()

        def mutate(view, rank):
            if rank == 3:                     # rank 3's kernel tripped
                view[tag] = U.uniform_digest(not engaged)

        with pytest.raises(U.UniformityError) as ei:
            U.check_uniform(gather=_pod_view(4, mutate))
        assert ei.value.tag == tag

    def test_one_rank_divergent_bucket_plan_fails_loudly(self):
        import jax

        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        params = {"w": jnp.ones((64, 8), jnp.float32),
                  "b": jnp.ones((8,), jnp.float32)}
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                   bucket_cap_mb=1.0)
        opt.init(params, world_size=2)
        tag = "zero.bucket_plan"
        local = U.recorded_decisions()[tag]
        assert local == U.uniform_digest(opt.plan_fingerprint())

        # rank 1 read a different bucket cap from its environment —
        # the exact APX210 hazard, caught at the seam instead
        divergent = U.uniform_digest(
            dict(opt.plan_fingerprint(), cap_bytes=123))

        def mutate(view, rank):
            if rank == 1:
                view[tag] = divergent

        with pytest.raises(U.UniformityError) as ei:
            U.check_uniform(gather=_pod_view(2, mutate))
        assert ei.value.tag == tag

    def test_identical_ranks_pass_the_same_check(self):
        from apex_tpu.resilience.fallback import registry_engaged

        registry_engaged(False)
        payload = U.check_uniform(gather=_pod_view(4))
        assert payload == U.recorded_decisions()

    def test_monitor_checks_on_cadence_and_records_the_step(self):
        mon = U.UniformityMonitor(every_n_steps=10,
                                  gather=_pod_view(2))
        assert mon.on_step(5) is None
        payload = mon.on_step(10)
        assert payload is not None and "uniformity.monitor_step" in payload

        # a rank that slipped a step diverges on the step tag itself
        def mutate(view, rank):
            if rank == 1:
                view["uniformity.monitor_step"] = U.uniform_digest(19)

        slipped = U.UniformityMonitor(every_n_steps=10,
                                      gather=_pod_view(2, mutate))
        with pytest.raises(U.UniformityError) as ei:
            slipped.on_step(20)
        assert ei.value.tag == "uniformity.monitor_step"


class TestSchedulerRecordsItsConfig:
    def test_scheduler_init_records_serve_config(self):
        import jax

        from apex_tpu.inference import (
            ContinuousBatchingScheduler, DecodeConfig, KVCacheConfig,
        )
        from apex_tpu.models.gpt import GPTConfig, init_params

        cfg = GPTConfig(
            vocab_size=61, hidden_size=32, num_layers=2,
            num_attention_heads=4, max_seq_len=128,
            position_embedding_type="rope",
            compute_dtype=jnp.float32, checkpoint_layers=False)
        dcfg = DecodeConfig(
            cache=KVCacheConfig(num_pages=16, page_size=4,
                                pages_per_seq=8, dtype=jnp.float32),
            max_batch=2, max_prompt_len=8, temperature=0.0,
            attn_impl="xla", sample_impl="xla")
        params = init_params(cfg, jax.random.PRNGKey(0))
        ContinuousBatchingScheduler(params, cfg, dcfg)
        assert "serve.scheduler_config" in U.recorded_decisions()
