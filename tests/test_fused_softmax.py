"""Fused scaled/masked softmax family — parity vs torch softmax
(mirrors apex tests/L0/run_transformer/test_fused_softmax.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import (
    FusedScaleMaskSoftmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)


def torch_ref(x, mask=None, scale=1.0, causal=False):
    t = torch.tensor(np.asarray(x), dtype=torch.float32) * scale
    sq, sk = t.shape[-2], t.shape[-1]
    if causal:
        tri = torch.tril(torch.ones(sq, sk, dtype=torch.bool))
        t = t.masked_fill(~tri, -10000.0)
    if mask is not None:
        t = t.masked_fill(torch.tensor(np.asarray(mask)), -10000.0)
    return torch.softmax(t, dim=-1).numpy()


class TestScaledSoftmax:
    def test_unmasked(self):
        x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 8, 16).astype(np.float32))
        out = scaled_softmax(x, 0.5)
        np.testing.assert_allclose(np.asarray(out), torch_ref(x, scale=0.5), rtol=1e-5, atol=1e-6)

    def test_causal(self):
        x = jnp.asarray(np.random.RandomState(1).randn(2, 8, 8).astype(np.float32))
        out = scaled_upper_triang_masked_softmax(x, 2.0)
        np.testing.assert_allclose(np.asarray(out), torch_ref(x, scale=2.0, causal=True),
                                   rtol=1e-5, atol=1e-6)

    def test_masked(self):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(2, 3, 4, 16).astype(np.float32))
        mask = jnp.asarray(rng.rand(2, 1, 4, 16) > 0.7)
        out = scaled_masked_softmax(x, mask, 1.5)
        np.testing.assert_allclose(np.asarray(out), torch_ref(x, mask=mask, scale=1.5),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.slow
    def test_grads_are_finite_and_masked(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(1, 1, 4, 8).astype(np.float32))
        mask = jnp.zeros((1, 1, 4, 8), bool).at[0, 0, :, 6:].set(True)
        g = jax.grad(lambda x: jnp.sum(scaled_masked_softmax(x, mask) ** 2))(x)
        assert bool(jnp.all(jnp.isfinite(g)))

    def test_bf16_in_fp32_softmax(self):
        x = jnp.asarray(np.random.RandomState(4).randn(2, 4, 8).astype(np.float32), jnp.bfloat16)
        out = scaled_softmax(x, 1.0)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   torch_ref(np.asarray(x, np.float32)), atol=1e-2)


class TestFusedScaleMaskSoftmaxModule:
    def test_causal_mode(self):
        m = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal, scale=0.7)
        x = jnp.asarray(np.random.RandomState(5).randn(2, 2, 8, 8).astype(np.float32))
        np.testing.assert_allclose(np.asarray(m(x)), torch_ref(x, scale=0.7, causal=True),
                                   rtol=1e-5, atol=1e-6)

    def test_padding_mode_and_kernel_available(self):
        m = FusedScaleMaskSoftmax()
        assert m.is_kernel_available(None, 1, 1, 8, 8)
        assert FusedScaleMaskSoftmax.get_batch_per_block(8, 8, 1, 1) == 1
        x = jnp.asarray(np.random.RandomState(6).randn(1, 2, 4, 8).astype(np.float32))
        mask = jnp.zeros((1, 1, 4, 8), bool).at[0, 0, :, 5:].set(True)
        np.testing.assert_allclose(np.asarray(m(x, mask)), torch_ref(x, mask=mask),
                                   rtol=1e-5, atol=1e-6)

    def test_rejects_fp16_and_bf16_both(self):
        with pytest.raises(RuntimeError):
            FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)
