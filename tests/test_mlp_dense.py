"""MLP / fused dense parity — mirrors tests/L0/run_mlp/test_mlp.py (MLP vs
nn.Sequential) and apex/contrib/test/fused_dense, using torch CPU as the
oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from apex_tpu.fused_dense import fused_dense_function, fused_dense_gelu_dense_function
from apex_tpu.mlp import MLP, mlp_function


def test_fused_dense_matches_torch_linear():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    w = rng.randn(24, 16).astype(np.float32)
    b = rng.randn(24).astype(np.float32)
    out = fused_dense_function(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    ref = torch.nn.functional.linear(torch.tensor(x), torch.tensor(w), torch.tensor(b))
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-5, atol=1e-5)


def test_fused_dense_gelu_dense_matches_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 16).astype(np.float32)
    w1 = rng.randn(32, 16).astype(np.float32)
    b1 = rng.randn(32).astype(np.float32)
    w2 = rng.randn(8, 32).astype(np.float32)
    b2 = rng.randn(8).astype(np.float32)
    out = fused_dense_gelu_dense_function(
        jnp.asarray(x), jnp.asarray(w1), jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2)
    )
    h = torch.nn.functional.linear(torch.tensor(x), torch.tensor(w1), torch.tensor(b1))
    h = torch.nn.functional.gelu(h)
    ref = torch.nn.functional.linear(h, torch.tensor(w2), torch.tensor(b2))
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4, atol=1e-4)


def test_mlp_matches_torch_sequential():
    sizes = [10, 20, 15, 5]
    rng = np.random.RandomState(2)
    ws = [rng.randn(sizes[i + 1], sizes[i]).astype(np.float32) for i in range(3)]
    bs = [rng.randn(sizes[i + 1]).astype(np.float32) for i in range(3)]
    x = rng.randn(6, 10).astype(np.float32)

    out = mlp_function(jnp.asarray(x), [jnp.asarray(w) for w in ws], [jnp.asarray(b) for b in bs], "relu")

    t = torch.tensor(x)
    for i in range(3):
        t = torch.nn.functional.linear(t, torch.tensor(ws[i]), torch.tensor(bs[i]))
        if i < 2:
            t = torch.relu(t)
    np.testing.assert_allclose(np.asarray(out), t.numpy(), rtol=1e-5, atol=1e-5)


def test_mlp_grad_matches_torch():
    sizes = [10, 20, 5]
    rng = np.random.RandomState(3)
    ws = [rng.randn(sizes[i + 1], sizes[i]).astype(np.float32) for i in range(2)]
    bs = [rng.randn(sizes[i + 1]).astype(np.float32) for i in range(2)]
    x = rng.randn(6, 10).astype(np.float32)

    def loss(ws_bs):
        ws_, bs_ = ws_bs
        return jnp.sum(mlp_function(jnp.asarray(x), ws_, bs_, "relu") ** 2)

    g = jax.grad(loss)(([jnp.asarray(w) for w in ws], [jnp.asarray(b) for b in bs]))

    tws = [torch.nn.Parameter(torch.tensor(w)) for w in ws]
    tbs = [torch.nn.Parameter(torch.tensor(b)) for b in bs]
    t = torch.tensor(x)
    for i in range(2):
        t = torch.nn.functional.linear(t, tws[i], tbs[i])
        if i < 1:
            t = torch.relu(t)
    (t ** 2).sum().backward()
    for a, r in zip(g[0], tws):
        np.testing.assert_allclose(np.asarray(a), r.grad.numpy(), rtol=1e-4, atol=1e-4)
    for a, r in zip(g[1], tbs):
        np.testing.assert_allclose(np.asarray(a), r.grad.numpy(), rtol=1e-4, atol=1e-4)


def test_mlp_module():
    m = MLP(mlp_sizes=[8, 16, 4])
    x = jnp.ones((2, 8))
    params = m.init(jax.random.PRNGKey(0), x)
    out = m.apply(params, x)
    assert out.shape == (2, 4)
