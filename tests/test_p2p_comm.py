"""Pipeline p2p primitives (mirrors apex tests/L0/run_transformer/
test_p2p_comm.py): ring shifts route stage data correctly, the fused
bidirectional exchange equals its two halves, and autodiff transposes a
shift into the inverse shift."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

PP = 4


def pp_mesh(devices8):
    return Mesh(np.array(devices8[:PP]), ("pp",))


def stage_data():
    # stage s holds the row [s, s, s]
    return jnp.repeat(jnp.arange(float(PP))[:, None], 3, axis=1)


class TestShifts:
    def test_send_forward_routes_to_next_stage(self, devices8):
        out = jax.shard_map(
            lambda x: p2p.send_forward(x, "pp"),
            mesh=pp_mesh(devices8), in_specs=P("pp"), out_specs=P("pp"),
            check_vma=False,
        )(stage_data())
        # stage s now holds what stage s-1 had (ring wraparound at 0)
        np.testing.assert_array_equal(np.asarray(out)[:, 0], [PP - 1, 0, 1, 2])

    def test_send_backward_routes_to_prev_stage(self, devices8):
        out = jax.shard_map(
            lambda g: p2p.send_backward(g, "pp"),
            mesh=pp_mesh(devices8), in_specs=P("pp"), out_specs=P("pp"),
            check_vma=False,
        )(stage_data())
        np.testing.assert_array_equal(np.asarray(out)[:, 0], [1, 2, 3, 0])

    def test_fused_exchange_matches_two_shifts(self, devices8):
        x = stage_data()
        g = stage_data() * 10.0

        def fused(x, g):
            return p2p.send_forward_recv_backward(x, g, "pp")

        xf, gb = jax.shard_map(
            fused, mesh=pp_mesh(devices8),
            in_specs=(P("pp"), P("pp")), out_specs=(P("pp"), P("pp")),
            check_vma=False,
        )(x, g)
        xf_ref = jax.shard_map(
            lambda x: p2p.send_forward(x, "pp"), mesh=pp_mesh(devices8),
            in_specs=P("pp"), out_specs=P("pp"), check_vma=False,
        )(x)
        gb_ref = jax.shard_map(
            lambda g: p2p.send_backward(g, "pp"), mesh=pp_mesh(devices8),
            in_specs=P("pp"), out_specs=P("pp"), check_vma=False,
        )(g)
        np.testing.assert_array_equal(np.asarray(xf), np.asarray(xf_ref))
        np.testing.assert_array_equal(np.asarray(gb), np.asarray(gb_ref))

    def test_mirror_exchange_argument_order(self, devices8):
        x = stage_data()
        g = stage_data() * 10.0
        gb, xf = jax.shard_map(
            lambda g, x: p2p.send_backward_recv_forward(g, x, "pp"),
            mesh=pp_mesh(devices8),
            in_specs=(P("pp"), P("pp")), out_specs=(P("pp"), P("pp")),
            check_vma=False,
        )(g, x)
        np.testing.assert_array_equal(np.asarray(xf)[:, 0], [PP - 1, 0, 1, 2])
        np.testing.assert_array_equal(np.asarray(gb)[:, 0], [10, 20, 30, 0])

    def test_forward_shift_transposes_to_backward_shift(self, devices8):
        """ppermute's vjp is the inverse permutation — the correct
        backward-communication pairing for pipeline autodiff."""
        x = stage_data()

        def loss(x):
            y = p2p.send_forward(x, "pp")
            # weight stage s's received value by (s+1); per-device loss —
            # the cotangent rides the inverse ppermute back to the sender
            s = jax.lax.axis_index("pp").astype(jnp.float32)
            return jnp.sum(y * (s + 1.0))

        g = jax.shard_map(
            jax.grad(loss), mesh=pp_mesh(devices8),
            in_specs=P("pp"), out_specs=P("pp"), check_vma=False,
        )(x)
        # d loss/d x[s] = weight of the stage that received x[s] = s+2 (mod ring)
        np.testing.assert_array_equal(np.asarray(g)[:, 0], [2, 3, 4, 1])
