"""Rotary position embeddings: op properties + GPT composition
(tp, pipeline, and ring-attention context parallelism — each rank
rotates its local chunk with GLOBAL positions before the ring)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.gpt import (
    GPTConfig,
    gpt_loss,
    init_params,
    make_pp_train_step,
    make_train_step,
    param_specs,
)
from apex_tpu.ops.rope import apply_rope
from apex_tpu.optimizers import FusedAdam

ROPE_CFG = GPTConfig(
    vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
    max_seq_len=32, compute_dtype=jnp.float32, checkpoint_layers=False,
    position_embedding_type="rope",
)


class TestRopeOp:
    def test_preserves_norms(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 3, 16, 8).astype(np.float32))
        pos = jnp.arange(16)
        y = apply_rope(x, pos)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)

    def test_scores_depend_only_on_relative_position(self):
        """<rope(q, p1), rope(k, p2)> must be shift-invariant — the
        property that makes RoPE length-extrapolating."""
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(8).astype(np.float32))
        k = jnp.asarray(rng.randn(8).astype(np.float32))

        def score(p1, p2):
            qr = apply_rope(q[None], jnp.asarray([p1]))[0]
            kr = apply_rope(k[None], jnp.asarray([p2]))[0]
            return float(jnp.dot(qr, kr))

        np.testing.assert_allclose(score(3, 7), score(103, 107), rtol=1e-4)
        np.testing.assert_allclose(score(10, 2), score(1010, 1002), rtol=1e-4)
        assert abs(score(3, 7) - score(3, 9)) > 1e-4  # distance matters

    def test_odd_head_dim_rejected(self):
        with pytest.raises(ValueError, match="even head_dim"):
            apply_rope(jnp.zeros((4, 7)), jnp.arange(4))

    def test_positions_beyond_any_table(self):
        """No max_seq_len cap: positions far past the config's table
        size are fine (the point of rope for long context)."""
        x = jnp.ones((4, 8))
        y = apply_rope(x, jnp.arange(4) + 10_000_000)
        assert np.all(np.isfinite(np.asarray(y)))

    def test_neighbor_resolution_past_fp32_integer_range(self):
        """Adjacent positions past 2**24 must still rotate DIFFERENTLY
        (a naive fp32 position cast rounds them to the same value); the
        hi/lo split keeps neighbor resolution through int32 range, and
        shift invariance must hold across the boundary too."""
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(8).astype(np.float32))
        base = 2 ** 25
        r0 = apply_rope(q[None], jnp.asarray([base]))[0]
        r1 = apply_rope(q[None], jnp.asarray([base + 1]))[0]
        assert float(jnp.max(jnp.abs(r0 - r1))) > 1e-3
        # relative scores survive the translation to huge offsets
        k = jnp.asarray(rng.randn(8).astype(np.float32))
        near = float(jnp.dot(apply_rope(q[None], jnp.asarray([3]))[0],
                             apply_rope(k[None], jnp.asarray([7]))[0]))
        far = float(jnp.dot(apply_rope(q[None], jnp.asarray([base + 3]))[0],
                            apply_rope(k[None], jnp.asarray([base + 7]))[0]))
        np.testing.assert_allclose(near, far, rtol=1e-3)

    def test_int64_positions_past_int32_range(self):
        """Numpy int64 positions ≥ 2**31 must not wrap (the old path
        cast to int32, turning huge positions into NEGATIVE ones):
        neighbors still rotate differently and shift invariance holds
        against small positions — exact digit split through 2**48."""
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(8).astype(np.float32))
        k = jnp.asarray(rng.randn(8).astype(np.float32))
        base = np.int64(2) ** 35
        r0 = apply_rope(q[None], np.asarray([base]))[0]
        r1 = apply_rope(q[None], np.asarray([base + 1]))[0]
        assert np.all(np.isfinite(np.asarray(r0)))
        assert float(jnp.max(jnp.abs(r0 - r1))) > 1e-3
        near = float(jnp.dot(apply_rope(q[None], np.asarray([np.int64(3)]))[0],
                             apply_rope(k[None], np.asarray([np.int64(7)]))[0]))
        far = float(jnp.dot(apply_rope(q[None], np.asarray([base + 3]))[0],
                            apply_rope(k[None], np.asarray([base + 7]))[0]))
        np.testing.assert_allclose(near, far, rtol=1e-3)


class TestGPTWithRope:
    def test_no_pos_table_in_params(self):
        params = init_params(ROPE_CFG, jax.random.PRNGKey(0))
        assert "pos_embed" not in params
        assert "pos_embed" not in param_specs(ROPE_CFG)

    def test_training_reduces_loss(self):
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "tp"))
        params = init_params(ROPE_CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        step = make_train_step(ROPE_CFG, opt, mesh)
        rng = np.random.RandomState(0)
        tok = jnp.asarray(rng.randint(0, 64, size=(2, 32)))
        tgt = jnp.roll(tok, -1, axis=1)
        losses = []
        for _ in range(5):
            params, state, loss = step(params, state, tok, tgt)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    @pytest.mark.slow
    def test_tp_matches_single_device(self, devices8):
        params = init_params(ROPE_CFG, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        tok = jnp.asarray(rng.randint(0, 64, size=(2, 32)))
        tgt = jnp.roll(tok, -1, axis=1)
        ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, tok, tgt, ROPE_CFG)

        mesh = Mesh(np.array(devices8[:4]), ("tp",))
        f = jax.shard_map(
            jax.value_and_grad(lambda p, t, y: gpt_loss(p, t, y, ROPE_CFG, axis_name="tp")),
            mesh=mesh, in_specs=(param_specs(ROPE_CFG), P(), P()),
            out_specs=(P(), param_specs(ROPE_CFG)), check_vma=False,
        )
        loss, grads = f(params, tok, tgt)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(grads),
            jax.tree_util.tree_leaves_with_path(ref_grads),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
                err_msg=jax.tree_util.keystr(ka))

    @pytest.mark.slow
    def test_cp_ring_matches_single_device(self, devices8):
        """Per-rank rotation with global positions + the ring must equal
        full attention with rope on one device — TWO steps, so the
        second loss also certifies first-step grad parity through
        Adam (rope cotangents through the ring included)."""
        cfg = dataclasses.replace(ROPE_CFG, checkpoint_layers=True)
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "cp", "tp"))
        step = make_train_step(cfg, opt, mesh, cp_axis="cp")
        rng = np.random.RandomState(0)
        tok = jnp.asarray(rng.randint(0, 64, size=(4, 32)))
        tgt = jnp.roll(tok, -1, axis=1)
        losses = []
        for _ in range(2):
            params, state, loss = step(params, state, tok, tgt)
            losses.append(float(loss))

        o_params = init_params(cfg, jax.random.PRNGKey(0))
        o_state = opt.init(o_params)
        o_losses = []
        for _ in range(2):
            loss, grads = jax.value_and_grad(gpt_loss)(o_params, tok, tgt, cfg)
            o_params, o_state = opt.update(grads, o_state, o_params)
            o_losses.append(float(loss))
        np.testing.assert_allclose(losses, o_losses, rtol=1e-4)

    @pytest.mark.slow
    def test_pp_matches_single_device(self, devices8):
        cfg = dataclasses.replace(ROPE_CFG, num_layers=4, checkpoint_layers=True)
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "pp", "tp"))
        step = make_pp_train_step(cfg, opt, mesh, num_microbatches=2)
        rng = np.random.RandomState(0)
        tok = jnp.asarray(rng.randint(0, 64, size=(4, 32)))
        tgt = jnp.roll(tok, -1, axis=1)
        _, _, loss = step(params, state, tok, tgt)
        ref_loss = gpt_loss(params, tok, tgt, cfg)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)

    def test_rope_with_gqa(self):
        cfg = dataclasses.replace(ROPE_CFG, num_query_groups=2)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        tok = jnp.asarray(rng.randint(0, 64, size=(2, 32)))
        loss = gpt_loss(params, tok, jnp.roll(tok, -1, 1), cfg)
        assert np.isfinite(float(loss))
