"""DP-layer tests — mirrors tests/distributed/ of the reference
(synced_batchnorm parity vs torch.nn.BatchNorm2d, amp_master_params,
DDP gradient averaging) on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.parallel import LARC, SyncBatchNorm, allreduce_gradients
from apex_tpu.optimizers import FusedSGD


def smap(mesh, f, in_specs, out_specs):
    return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)


class TestAllreduceGradients:
    def test_gradient_average(self, devices8):
        mesh = Mesh(np.array(devices8), ("dp",))
        # rank r holds grad value r → average = 3.5
        g = jnp.arange(8.0)

        def f(g):
            return allreduce_gradients({"w": g}, axis_name="dp")["w"]

        out = smap(mesh, f, P("dp"), P("dp"))(g)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))

    def test_no_average(self, devices8):
        mesh = Mesh(np.array(devices8), ("dp",))
        g = jnp.ones(8)

        def f(g):
            return allreduce_gradients({"w": g}, axis_name="dp", gradient_average=False)["w"]

        out = smap(mesh, f, P("dp"), P("dp"))(g)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 8.0))

    def test_predivide(self, devices8):
        mesh = Mesh(np.array(devices8), ("dp",))
        g = jnp.ones(8)

        def f(g):
            return allreduce_gradients(
                {"w": g}, axis_name="dp", gradient_predivide_factor=2.0
            )["w"]

        out = smap(mesh, f, P("dp"), P("dp"))(g)
        # sum(1/2 * 1 над 8) / (8/2) = 4 / 4 = 1 → still averages to 1
        np.testing.assert_allclose(np.asarray(out), np.full(8, 1.0))

    def test_fp32_comm_dtype(self, devices8):
        mesh = Mesh(np.array(devices8), ("dp",))
        g = jnp.ones(8, jnp.bfloat16)

        def f(g):
            return allreduce_gradients({"w": g}, axis_name="dp", allreduce_always_fp32=True)["w"]

        out = smap(mesh, f, P("dp"), P("dp"))(g)
        assert out.dtype == jnp.bfloat16  # cast back to grad dtype


class TestDDPDeterminism:
    """The TPU analog of tests/distributed/DDP/ddp_race_condition_test.py:
    the reference hammers the overlapped bucket-allreduce engine for
    stream races; under XLA the property to pin is that the compiled
    allreduce'd step is bitwise deterministic across executions and
    never partially synced."""

    def test_repeated_steps_bitwise_identical(self, devices8):
        from apex_tpu.parallel import allreduce_gradients

        mesh = Mesh(np.array(devices8), ("dp",))
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(64, 64).astype(np.float32))
        x = jnp.asarray(rng.randn(8 * 4, 64).astype(np.float32))

        def step(w, x):
            # per-shard grads of a nonlinear loss, then the DDP allreduce
            g = jax.grad(lambda w: jnp.sum(jnp.tanh(x @ w) ** 2))(w)
            return allreduce_gradients(g, axis_name="dp")

        f = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P(),
            check_vma=False,
        ))
        first = np.asarray(f(w, x))
        for _ in range(4):
            np.testing.assert_array_equal(np.asarray(f(w, x)), first)

    def test_sync_is_complete_every_param(self, devices8):
        """No parameter's gradient escapes the sync (the reference's
        'bucket never left partially reduced' assertion)."""
        from apex_tpu.parallel import allreduce_gradients

        mesh = Mesh(np.array(devices8), ("dp",))
        tree = {
            "a": jnp.ones((8, 3, 5)),
            "b": {"c": jnp.ones((8, 7)), "d": jnp.ones((8, 1))},
        }

        def f(t):
            # rank-dependent grads: rank r contributes (r+1)
            r = jax.lax.axis_index("dp").astype(jnp.float32) + 1.0
            local = jax.tree.map(lambda x: x * r, t)
            return allreduce_gradients(local, axis_name="dp")

        out = jax.shard_map(
            f, mesh=mesh, in_specs=({"a": P("dp"), "b": {"c": P("dp"), "d": P("dp")}},),
            out_specs={"a": P("dp"), "b": {"c": P("dp"), "d": P("dp")}},
            check_vma=False,
        )(tree)
        # average over ranks of (r+1) = 4.5 — for EVERY leaf and element
        for leaf in jax.tree.leaves(out):
            np.testing.assert_allclose(np.asarray(leaf), 4.5, rtol=1e-6)


class TestSyncBatchNorm:
    def _torch_bn(self, x, momentum=0.1, eps=1e-5):
        bn = torch.nn.BatchNorm2d(x.shape[1], momentum=momentum, eps=eps)
        bn.train()
        out = bn(torch.tensor(x))
        return out.detach().numpy(), bn.running_mean.numpy(), bn.running_var.numpy()

    def test_matches_torch_single_device(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 3, 5, 5).astype(np.float32)
        m = SyncBatchNorm(num_features=3, axis_name=None)
        variables = m.init(jax.random.PRNGKey(0), jnp.asarray(x))
        out, updated = m.apply(variables, jnp.asarray(x), mutable=["batch_stats"])
        ref_out, ref_mean, ref_var = self._torch_bn(x)
        np.testing.assert_allclose(np.asarray(out), ref_out, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(updated["batch_stats"]["running_mean"]), ref_mean, rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(updated["batch_stats"]["running_var"]), ref_var, rtol=1e-4, atol=1e-5
        )

    @pytest.mark.slow
    def test_sharded_matches_full_batch(self, devices8):
        """The reference's core distributed test: stats synced over dp ==
        single-process full-batch BN (two_gpu_unit_test.py)."""
        rng = np.random.RandomState(1)
        x = rng.randn(8, 3, 4, 4).astype(np.float32)
        m_sync = SyncBatchNorm(num_features=3, axis_name="dp")
        m_local = SyncBatchNorm(num_features=3, axis_name=None)
        variables = m_local.init(jax.random.PRNGKey(0), jnp.asarray(x))

        mesh = Mesh(np.array(devices8), ("dp",))

        def f(x):
            out, _ = m_sync.apply(variables, x, mutable=["batch_stats"])
            return out

        out_sharded = smap(mesh, f, P("dp"), P("dp"))(jnp.asarray(x))
        out_full, _ = m_local.apply(variables, jnp.asarray(x), mutable=["batch_stats"])
        np.testing.assert_allclose(
            np.asarray(out_sharded), np.asarray(out_full), rtol=1e-4, atol=1e-4
        )

    def test_uneven_not_degenerate_channel_last(self):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 5, 5, 3).astype(np.float32)
        m = SyncBatchNorm(num_features=3, axis_name=None, channel_last=True)
        variables = m.init(jax.random.PRNGKey(0), jnp.asarray(x))
        out, _ = m.apply(variables, jnp.asarray(x), mutable=["batch_stats"])
        assert out.shape == x.shape
        # per-channel normalized: mean≈0 std≈1
        flat = np.asarray(out).reshape(-1, 3)
        np.testing.assert_allclose(flat.mean(0), np.zeros(3), atol=1e-4)

    def test_eval_uses_running_stats(self):
        x = jnp.ones((2, 3, 4, 4))
        m = SyncBatchNorm(num_features=3, axis_name=None)
        variables = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(variables, x, use_running_average=True)
        # running mean 0, var 1 → output == input (affine identity at init)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-5)


class TestLARC:
    def test_larc_clip_matches_reference_math(self):
        rng = np.random.RandomState(3)
        p = rng.randn(10).astype(np.float32)
        g = (rng.randn(10) * 0.01).astype(np.float32)
        lr, tc, wd = 0.1, 0.02, 0.01

        opt = LARC(FusedSGD(lr=lr, weight_decay=wd), trust_coefficient=tc, clip=True)
        params = {"w": jnp.asarray(p)}
        state = opt.init(params)
        new_params, _ = opt.update({"w": jnp.asarray(g)}, state, params)

        # reference math (apex/parallel/LARC.py:78-104) + plain SGD step
        p_norm = np.linalg.norm(p)
        g_norm = np.linalg.norm(g)
        adaptive = tc * p_norm / (g_norm + p_norm * wd + 1e-8)
        adaptive = min(adaptive / lr, 1.0)
        g_adj = (g + wd * p) * adaptive
        expected = p - lr * g_adj
        np.testing.assert_allclose(np.asarray(new_params["w"]), expected, rtol=1e-5, atol=1e-6)

    def test_larc_restores_wd(self):
        inner = FusedSGD(lr=0.1, weight_decay=0.5)
        opt = LARC(inner)
        params = {"w": jnp.ones(4)}
        state = opt.init(params)
        opt.update({"w": jnp.ones(4)}, state, params)
        assert inner.weight_decay == 0.5


class TestClipGrad:
    def test_matches_torch_clip_grad_norm(self):
        rng = np.random.RandomState(4)
        gs = [rng.randn(5, 3).astype(np.float32), rng.randn(7).astype(np.float32)]
        tparams = [torch.nn.Parameter(torch.zeros(5, 3)), torch.nn.Parameter(torch.zeros(7))]
        for p, g in zip(tparams, gs):
            p.grad = torch.tensor(g)
        ref_norm = torch.nn.utils.clip_grad_norm_(tparams, max_norm=1.0)

        clipped, norm = clip_grad_norm_([jnp.asarray(g) for g in gs], max_norm=1.0)
        np.testing.assert_allclose(float(norm), float(ref_norm), rtol=1e-5)
        for c, t in zip(clipped, tparams):
            np.testing.assert_allclose(np.asarray(c), t.grad.numpy(), rtol=1e-4, atol=1e-6)

    def test_no_clip_when_under(self):
        gs = {"w": jnp.asarray(np.array([0.1, 0.2], np.float32))}
        clipped, norm = clip_grad_norm_(gs, max_norm=10.0)
        np.testing.assert_allclose(np.asarray(clipped["w"]), np.asarray(gs["w"]), rtol=1e-6)

    def test_inf_norm(self):
        gs = {"w": jnp.asarray(np.array([3.0, -4.0], np.float32))}
        clipped, norm = clip_grad_norm_(gs, max_norm=2.0, norm_type=float("inf"))
        np.testing.assert_allclose(float(norm), 4.0)
