"""Real multi-process distributed tests.

The reference's distributed test strategy is real multi-process spawn
(``apex/transformer/testing/distributed_test_base.py:22-94``,
``MultiProcessTestCase`` with file-store rendezvous; 2-proc shell tests
under ``tests/distributed/``).  The TPU-native analog: 2 OS processes ×
4 virtual CPU devices each, rendezvoused through
``jax.distributed.initialize`` — one process per host is exactly the
pod deployment shape, so this exercises mesh construction across
processes, global-array data feeding, cross-process collectives, and
multi-host checkpoint coordination that the single-process 8-device
suite cannot.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# CPU-only containers cannot run these AT ALL: jax.distributed worker
# fleets need a backend with real cross-process transport, and every
# worker dies with "Multiprocess computations aren't implemented on the
# CPU backend".  Skip LOUDLY (with that reason) instead of letting the
# fleet fail after a 600 s timeout — the suite stays honest about what
# this environment can and cannot verify.
pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        jax.default_backend() == "cpu",
        reason="Multiprocess computations aren't implemented on the "
               "CPU backend (jax.distributed needs real cross-process "
               "transport; the 8-virtual-device single-process suite "
               "covers the mesh logic)"),
]

REPO = Path(__file__).resolve().parent.parent


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def worker_run(tmp_path_factory):
    """Launch the 2-process worker fleet once; tests assert on its
    artifacts."""
    out = tmp_path_factory.mktemp("mp")
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_COMPILATION_CACHE_DIR"] = str(REPO / "tests" / ".jax_cache")
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.5"
    env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
    procs = [
        subprocess.Popen(
            [sys.executable, str(REPO / "tests" / "_mp_worker.py"),
             "--process-id", str(i), "--num-processes", "2",
             "--coordinator", f"127.0.0.1:{port}", "--out", str(out)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(stdout)
    for i, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, (
            f"worker {i} failed rc={p.returncode}:\n{text[-4000:]}"
        )
    return out, outputs


def _oracle_losses(num_layers, key, steps):
    """Single-device GPT trajectory over the worker's batch (same
    config family, PRNG key, and token stream as the worker phases)."""
    from apex_tpu.models.gpt import GPTConfig, gpt_loss, init_params
    from apex_tpu.optimizers import FusedAdam

    config = GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=num_layers,
        num_attention_heads=4, max_seq_len=16,
        compute_dtype=jnp.float32, checkpoint_layers=True,
    )
    params = init_params(config, jax.random.PRNGKey(key))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, size=(8, 16)))
    targets = jnp.roll(tokens, -1, axis=1)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(gpt_loss)(params, tokens, targets, config)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    oracle = []
    for _ in range(steps):
        params, state, loss = step(params, state)
        oracle.append(float(loss))
    return np.asarray(oracle)


def test_two_process_dp_tp_matches_single_process_oracle(worker_run):
    """The 2-process dp4×tp2 loss trajectory must match a single-device
    oracle of the same batch — the reference's dominant distributed test
    pattern (parallel run vs equivalent single-process run)."""
    out, _ = worker_run
    mp_losses = np.asarray(json.loads((out / "losses.json").read_text()))
    np.testing.assert_allclose(
        mp_losses, _oracle_losses(num_layers=2, key=0, steps=3), rtol=1e-4)


def test_two_process_pipeline_crosses_processes_matches_oracle(worker_run):
    """pp2×tp4 across 2 processes with stage 0 entirely on process 0 and
    stage 1 on process 1 (asserted in the worker) — every pipeline
    ppermute is a cross-process transfer — must match the single-device
    oracle."""
    out, _ = worker_run
    mp_losses = np.asarray(json.loads((out / "pp_losses.json").read_text()))
    np.testing.assert_allclose(
        mp_losses, _oracle_losses(num_layers=4, key=2, steps=2), rtol=1e-4)


def test_two_process_zero_checkpoint_resumes_bit_identical(worker_run):
    """Each process wrote only its addressable ZeRO shards; both
    processes verified the reassembled restart is bit-identical to the
    uninterrupted run (markers written by the workers)."""
    out, outputs = worker_run
    assert (out / "zero_ok_0").exists(), outputs[0][-2000:]
    assert (out / "zero_ok_1").exists(), outputs[1][-2000:]
