"""Flash + ring attention tests — parity vs the naive O(S²) oracle,
forward and backward (mirrors apex/contrib/test/fmha and multihead_attn
parity-vs-unfused tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.attention import flash_attention, flash_attention_with_lse, mha_reference
from apex_tpu.transformer.context_parallel import ring_attention


def qkv(seed=0, B=2, H=3, S=32, D=8):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    return mk(), mk(), mk()


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("block_k", [8, 16, 32])
    def test_forward_matches_reference(self, causal, block_k):
        q, k, v = qkv()
        out = flash_attention(q, k, v, causal=causal, block_k=block_k)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.slow
    def test_backward_matches_reference(self, causal):
        q, k, v = qkv(1)

        def f(q, k, v):
            return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=causal, block_k=8)))

        def fr(q, k, v):
            return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=causal)))

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
        for a, r in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-5)

    def test_lse_is_logsumexp(self):
        q, k, v = qkv(2, S=16)
        _, lse = flash_attention_with_lse(q, k, v, causal=False, block_k=8)
        scale = 1.0 / np.sqrt(q.shape[-1])
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        ref = jax.scipy.special.logsumexp(s, axis=-1)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref), rtol=1e-4, atol=1e-5)

    def test_non_divisible_block(self):
        q, k, v = qkv(3, S=24)
        out = flash_attention(q, k, v, causal=True, block_k=7)  # falls back to divisor
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


def padded_mask(B, S, lengths):
    m = np.zeros((B, S), bool)
    for b, n in enumerate(lengths):
        m[b, :n] = True
    return jnp.asarray(m)


class TestPaddedFlashAttention:
    """Key-padding masks through the flash path (scan composite),
    parity vs the dense oracle — the fmha varlen semantics."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("block_k", [8, 16, 32])
    def test_forward_matches_reference(self, causal, block_k):
        q, k, v = qkv(8)
        mask = padded_mask(2, 32, [32, 17])
        out = flash_attention(q, k, v, causal=causal, block_k=block_k,
                              kv_mask=mask, impl="scan")
        ref = mha_reference(q, k, v, causal=causal, kv_mask=mask)
        # compare valid query rows (padded rows see the same valid keys in
        # both paths, but have no defined semantics)
        np.testing.assert_allclose(np.asarray(out[1, :, :17]),
                                   np.asarray(ref[1, :, :17]), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref[0]),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.slow
    def test_backward_matches_reference(self, causal):
        q, k, v = qkv(9)
        mask = padded_mask(2, 32, [32, 21])
        mf = mask[:, None, :, None].astype(jnp.float32)

        def f(q, k, v):
            o = flash_attention(q, k, v, causal=causal, block_k=8,
                                kv_mask=mask, impl="scan")
            return jnp.sum(jnp.sin(o * mf))  # loss over valid rows only

        def fr(q, k, v):
            return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=causal, kv_mask=mask) * mf))

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
        for a, r in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-5)

    def test_masked_keys_have_no_influence(self):
        q, k, v = qkv(10)
        mask = padded_mask(2, 32, [32, 20])
        out = flash_attention(q, k, v, kv_mask=mask, causal=False, impl="scan")
        k2 = k.at[1, :, 20:].set(77.0)
        v2 = v.at[1, :, 20:].set(-77.0)
        out2 = flash_attention(q, k2, v2, kv_mask=mask, causal=False, impl="scan")
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)


class TestBiasedFlashAttention:
    """Additive attention bias (OpenFold pair bias) through the scan
    path — forward parity and a REAL bias cotangent."""

    def _biased_ref(self, q, k, v, bias):
        scale = 1.0 / np.sqrt(q.shape[-1])
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale + bias
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    @pytest.mark.parametrize("bias_shape", [(2, 3, 32, 32), (1, 3, 32, 32), (2, 1, 1, 32)])
    def test_forward_matches_reference(self, bias_shape):
        q, k, v = qkv(12)
        bias = jnp.asarray(np.random.RandomState(13).randn(*bias_shape).astype(np.float32))
        out = flash_attention(q, k, v, causal=False, attn_bias=bias, impl="scan",
                              block_k=8)
        ref = self._biased_ref(q, k, v, bias)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("bias_shape", [(2, 3, 32, 32), (1, 1, 32, 32), (2, 1, 1, 32)])
    def test_bias_gradient_matches_reference(self, bias_shape):
        q, k, v = qkv(14)
        bias = jnp.asarray(np.random.RandomState(15).randn(*bias_shape).astype(np.float32))

        def f(bias):
            o = flash_attention(q, k, v, causal=False, attn_bias=bias, impl="scan",
                                block_k=16)
            return jnp.sum(jnp.sin(o))

        def fr(bias):
            return jnp.sum(jnp.sin(self._biased_ref(q, k, v, bias)))

        g = jax.grad(f)(bias)
        gr = jax.grad(fr)(bias)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=2e-4, atol=2e-5)

    def test_bias_composes_with_padding_mask(self):
        q, k, v = qkv(16)
        bias = jnp.asarray(np.random.RandomState(17).randn(2, 3, 32, 32).astype(np.float32))
        mask = padded_mask(2, 32, [32, 20])
        out = flash_attention(q, k, v, causal=False, attn_bias=bias, kv_mask=mask,
                              impl="scan")
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1]) + bias
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        np.testing.assert_allclose(np.asarray(out[1, :, :20]), np.asarray(ref[1, :, :20]),
                                   rtol=1e-4, atol=1e-5)


class TestOpenFoldMHA:
    def test_attention_core_with_mask_and_bias(self):
        from apex_tpu.contrib.openfold_triton import CanSchTriMHA, attention_core

        assert CanSchTriMHA((1, 2, 16, 8))
        rng = np.random.RandomState(18)
        # OpenFold-ish leading dims: (batch, n_seq) extra axis
        q = jnp.asarray(rng.randn(2, 3, 4, 16, 8).astype(np.float32))
        k = jnp.asarray(rng.randn(2, 3, 4, 16, 8).astype(np.float32))
        v = jnp.asarray(rng.randn(2, 3, 4, 16, 8).astype(np.float32))
        mask = jnp.asarray(rng.rand(2, 3, 1, 1, 16) > 0.2)
        bias = jnp.asarray(rng.randn(2, 1, 4, 16, 16).astype(np.float32))

        out = attention_core(q, k, v, mask=mask, bias=bias)
        assert out.shape == q.shape

        s = jnp.einsum("...hqd,...hkd->...hqk", q, k) / np.sqrt(8.0) + bias
        s = jnp.where(mask, s, -1e9)
        ref = jnp.einsum("...hqk,...hkd->...hqd", jax.nn.softmax(s, axis=-1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)

    def test_pair_bias_gets_gradients(self):
        from apex_tpu.contrib.openfold_triton import attention_core

        rng = np.random.RandomState(19)
        q = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
        k, v = q + 0.1, q - 0.1
        bias = jnp.asarray(rng.randn(1, 2, 16, 16).astype(np.float32))
        g = jax.grad(lambda b: jnp.sum(attention_core(q, k, v, bias=b) ** 2))(bias)
        assert float(jnp.abs(g).max()) > 0  # trained pair bias: real cotangent
        assert bool(jnp.all(jnp.isfinite(g)))


class TestPaddedPallasFlashAttention:
    """Padding masks through the Pallas kernels (interpret mode)."""

    def _inputs(self, B=2, H=2, Sq=256, Sk=256, D=64, dtype=jnp.float32, seed=11):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(B, H, Sq, D).astype(np.float32), dtype)
        k = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32), dtype)
        v = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32), dtype)
        return q, k, v

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal):
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs()
        mask = padded_mask(2, 256, [256, 130])
        out = flash_attention_pallas(q, k, v, causal=causal, kv_mask=mask,
                                     interpret=True)
        ref = mha_reference(q, k, v, causal=causal, kv_mask=mask)
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref[0]),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(out[1, :, :130]),
                                   np.asarray(ref[1, :, :130]), atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.slow
    def test_backward_matches_reference(self, causal):
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(Sq=128, Sk=128)
        mask = padded_mask(2, 128, [128, 70])
        mf = mask[:, None, :, None].astype(jnp.float32)

        def loss_pallas(q, k, v):
            o = flash_attention_pallas(q, k, v, causal=causal, kv_mask=mask,
                                       interpret=True)
            return jnp.sum((o * mf) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum((mha_reference(q, k, v, causal=causal, kv_mask=mask) * mf) ** 2)

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)

    def test_matches_scan_path_multi_block(self):
        """Mask must land on the right k-blocks when nk > 1."""
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(Sq=256, Sk=256)
        mask = padded_mask(2, 256, [200, 64])
        out = flash_attention_pallas(q, k, v, causal=False, kv_mask=mask,
                                     block_q=128, block_k=128, interpret=True)
        ref = flash_attention(q, k, v, causal=False, kv_mask=mask, impl="scan")
        for b, n in enumerate([200, 64]):
            np.testing.assert_allclose(np.asarray(out[b, :, :n]),
                                       np.asarray(ref[b, :, :n]),
                                       atol=2e-5, rtol=2e-5)


CP = 4


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal, devices8):
        B, H, S, D = 2, 2, 32, 8
        q, k, v = qkv(4, B=B, H=H, S=S, D=D)
        ref = mha_reference(q, k, v, causal=causal)

        mesh = Mesh(np.array(devices8[:CP]), ("cp",))
        f = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp", causal=causal),
            mesh=mesh,
            in_specs=(P(None, None, "cp", None),) * 3,
            out_specs=P(None, None, "cp", None),
            check_vma=False,
        )
        out = f(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_grads_match_full_attention(self, devices8):
        B, H, S, D = 1, 2, 16, 4
        q, k, v = qkv(5, B=B, H=H, S=S, D=D)

        def fr(q, k, v):
            return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=True)))

        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)

        mesh = Mesh(np.array(devices8[:CP]), ("cp",))

        def f(q, k, v):
            out = ring_attention(q, k, v, "cp", causal=True)
            # differentiate the LOCAL loss shard: dq is local by
            # construction, and dk/dv cotangents travel the reverse ring
            # (ppermute transpose), so per-device grads sum to the
            # total-loss gradient — no psum needed (one would overcount).
            return jnp.sum(jnp.sin(out))

        g = jax.shard_map(
            jax.grad(f, argnums=(0, 1, 2)),
            mesh=mesh,
            in_specs=(P(None, None, "cp", None),) * 3,
            out_specs=(P(None, None, "cp", None),) * 3,
            check_vma=False,
        )(q, k, v)
        for a, r in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-5)


class TestRingOverlap:
    """``overlap=True`` (unrolled ring, hop r+1's ppermute issued before
    chunk r's compute) consumes the same values in the same merge order
    as the serial scan schedule, so fp32 out/dq/dk/dv are BITWISE equal
    op-by-op — pinned under ``disable_jit`` where each primitive runs
    alone and any difference is a reordering bug, never rounding.  The
    jitted pair is additionally pinned at 1-ulp scale: XLA fuses the
    while-loop body and the unrolled straight-line program differently
    (FMA contraction, iteration-0 constant folding), which no two
    differently-shaped equal-math programs escape — but that residue
    must stay at rounding scale, never a schedule-divergence scale."""

    @staticmethod
    def _fwd_bwd(kw):
        # one vjp pass: the fwd output AND all three grads from a single
        # ring traversal (the matrix runs op-by-op under disable_jit, so
        # a second fwd-only traversal would double the dominant cost);
        # the cos(out) cotangent varies per element, deterministically
        def fwd_bwd(q, k, v):
            out, vjp = jax.vjp(
                lambda q, k, v: ring_attention(q, k, v, "cp", **kw),
                q, k, v)
            return out, vjp(jnp.cos(out))

        return fwd_bwd

    def _run(self, cp, causal, impl, overlap, devices8):
        B, H, D = 1, 2, 16
        q, k, v = qkv(7, B=B, H=H, S=64 * cp, D=D)
        mesh = Mesh(np.array(devices8[:cp]), ("cp",))
        kw = dict(causal=causal, impl=impl, interpret=True, overlap=overlap)
        specs = (P(None, None, "cp", None),) * 3
        out, grads = jax.shard_map(
            self._fwd_bwd(kw), mesh=mesh, in_specs=specs,
            out_specs=(specs[0], specs), check_vma=False,
        )(q, k, v)
        return out, grads

    def _run_vmap(self, cp, causal, impl, overlap):
        # the ring emulated by vmap(axis_name="cp") over a chunk axis:
        # collectives see the same named axis, but each primitive runs
        # ONCE on batched arrays instead of per-device — the only way
        # the op-by-op matrix fits the fast tier.  Not available to the
        # pallas impl: a batched lax.switch evaluates every branch's
        # jaxpr eagerly, outside flash's disable_jit(False) window, and
        # pallas_call cannot execute eagerly.
        B, H, D = 1, 2, 16
        q, k, v = qkv(7, B=B, H=H, S=64 * cp, D=D)
        kw = dict(causal=causal, impl=impl, interpret=True, overlap=overlap)

        def split(x):  # (B, H, S, D) -> (cp, B, H, S/cp, D)
            return jnp.moveaxis(
                x.reshape(B, H, cp, x.shape[2] // cp, D), 2, 0)

        f = jax.vmap(self._fwd_bwd(kw), axis_name="cp", axis_size=cp)
        return f(split(q), split(k), split(v))

    def _assert_bitwise(self, serial, overlapped):
        out_s, g_s = serial
        out_o, g_o = overlapped
        np.testing.assert_array_equal(np.asarray(out_s), np.asarray(out_o))
        for name, a, b in zip(("dq", "dk", "dv"), g_s, g_o):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"{name} diverged between serial and overlapped")

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("cp", [2, 4])
    def test_bitwise_parity_fwd_bwd_scan(self, cp, causal):
        with jax.disable_jit():
            serial = self._run_vmap(cp, causal, "scan", False)
            overlapped = self._run_vmap(cp, causal, "scan", True)
        self._assert_bitwise(serial, overlapped)

    @pytest.mark.parametrize("causal", [
        True, pytest.param(False, marks=pytest.mark.slow)])
    @pytest.mark.parametrize("cp", [
        2, pytest.param(4, marks=pytest.mark.slow)])
    def test_bitwise_parity_fwd_bwd_pallas(self, cp, causal, devices8):
        # eager shard_map pays per-device sequential dispatch (~15-45 s
        # per combo), so tier-1 keeps only cp=2 × causal=True — whose
        # lax.switch full-block case already exercises the unmasked
        # kernel — and the rest ride the slow tier (the full cp∈{2,4} ×
        # causal matrix stays fast above via the scan vmap harness)
        with jax.disable_jit():
            serial = self._run(cp, causal, "pallas", False, devices8)
            overlapped = self._run(cp, causal, "pallas", True, devices8)
        self._assert_bitwise(serial, overlapped)

    @pytest.mark.parametrize("causal", [
        True, pytest.param(False, marks=pytest.mark.slow)])
    def test_jitted_parity_rounding_scale(self, causal, devices8):
        out_s, g_s = self._run(2, causal, "scan", False, devices8)
        out_o, g_o = self._run(2, causal, "scan", True, devices8)
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_o),
                                   rtol=1e-6, atol=1e-7)
        for a, b in zip(g_s, g_o):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


class TestPallasFlashAttention:
    """Pallas kernel parity vs the naive oracle, interpret mode on CPU."""

    def _inputs(self, B=2, H=2, Sq=256, Sk=256, D=64, dtype=jnp.float32, seed=0):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(B, H, Sq, D).astype(np.float32), dtype)
        k = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32), dtype)
        v = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32), dtype)
        return q, k, v

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal):
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs()
        out = flash_attention_pallas(q, k, v, causal=causal, interpret=True)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_tuned_block_table_consulted(self, monkeypatch):
        """Sweep-installed per-shape blocks must reach the kernel when
        the caller passes none, lose to explicit args, and miss cleanly
        for unkeyed shapes (the _pick_block fallback)."""
        from apex_tpu.ops import flash_attention_pallas as fap

        q, k, v = self._inputs()
        monkeypatch.setattr(fap, "_TUNED_BLOCKS", {})
        fap.set_tuned_blocks({(256, 64, "float32"): (128, 128)})
        assert fap.tuned_blocks(256, 64, jnp.float32) == (128, 128)
        assert fap.tuned_blocks(512, 64, jnp.float32) is None

        seen = []
        orig = fap._pick_block

        def spy(seq, target, align=fap._LANES, **kw):
            seen.append(target)
            return orig(seq, target, align, **kw)

        monkeypatch.setattr(fap, "_pick_block", spy)
        out = fap.flash_attention_pallas(q, k, v, causal=True, interpret=True)
        assert seen[:2] == [128, 128]  # table hit, not the 1024 default
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        seen.clear()
        fap.flash_attention_pallas(q, k, v, causal=True, block_q=256,
                                   block_k=256, interpret=True)
        assert seen[:2] == [256, 256]  # explicit args beat the table
        # cross-attention (Sk != Sq) must NOT pick up the self-attn entry
        seen.clear()
        q2, k2, v2 = self._inputs(Sq=256, Sk=128)
        fap.flash_attention_pallas(q2, k2, v2, causal=False, interpret=True)
        assert seen[:2] == [1024, 1024]

    def test_tuned_blocks_json_round_trip(self, monkeypatch):
        """The sweep's printed tuned_blocks_table JSON must install
        directly, and dtype keys normalize (jnp.bfloat16 == 'bfloat16')."""
        import json

        from apex_tpu.ops import flash_attention_pallas as fap

        monkeypatch.setattr(fap, "_TUNED_BLOCKS", {})
        line = json.dumps(
            {"tuned_blocks_table": [[[1024, 64, "bfloat16"], [512, 256]]]})
        fap.set_tuned_blocks(json.loads(line)["tuned_blocks_table"])
        assert fap.tuned_blocks(1024, 64, jnp.bfloat16) == (512, 256)
        fap.set_tuned_blocks({(2048, 128, jnp.float32): (256, 512)})
        assert fap.tuned_blocks(2048, 128, "float32") == (256, 512)

    def test_tuned_blocks_per_phase_lookup(self, monkeypatch):
        """Per-phase keys resolve per phase; legacy 3-tuple entries are
        fwd-only; a bad phase fails loudly at both ends."""
        from apex_tpu.ops import flash_attention_pallas as fap

        monkeypatch.setattr(fap, "_TUNED_BLOCKS", {})
        fap.set_tuned_blocks({
            (256, 64, "float32", "fwd"): (128, 128),
            (256, 64, "float32", "bwd"): (64, 64),
        })
        assert fap.tuned_blocks(256, 64, jnp.float32, phase="fwd") == (128, 128)
        assert fap.tuned_blocks(256, 64, jnp.float32, phase="bwd") == (64, 64)
        # legacy flat key: a pre-split sweep measured the forward path
        monkeypatch.setattr(fap, "_TUNED_BLOCKS", {})
        fap.set_tuned_blocks({(256, 64, "float32"): (128, 128)})
        assert fap.tuned_blocks(256, 64, jnp.float32, phase="fwd") == (128, 128)
        assert fap.tuned_blocks(256, 64, jnp.float32, phase="bwd") is None
        with pytest.raises(ValueError, match="phase"):
            fap.tuned_blocks(256, 64, jnp.float32, phase="backward")
        with pytest.raises(ValueError, match="phase"):
            fap.set_tuned_blocks({(256, 64, "float32", "backward"): (8, 8)})

    def test_bwd_consults_its_own_phase_entry(self, monkeypatch):
        """The backward kernels must key the tuned table on their OWN
        phase — a fast-forward block choice (fwd 128) must not leak into
        the backward (tuned to 64 here), and vice versa."""
        from apex_tpu.ops import flash_attention_pallas as fap

        monkeypatch.setattr(fap, "_TUNED_BLOCKS", {})
        fap.set_tuned_blocks({
            (256, 64, "float32", "fwd"): (128, 128),
            (256, 64, "float32", "bwd"): (64, 64),
        })
        resolved = []
        orig = fap._clamped_blocks

        def spy(sq, sk, d, dtype, bq, bk, phase):
            r = orig(sq, sk, d, dtype, bq, bk, phase)
            resolved.append((phase,) + r)
            return r

        monkeypatch.setattr(fap, "_clamped_blocks", spy)
        q, k, v = self._inputs()

        def loss(q):
            o = fap.flash_attention_pallas(q, k, v, causal=True,
                                           interpret=True)
            return jnp.sum(o.astype(jnp.float32))

        jax.grad(loss)(q)
        assert ("fwd", 128, 128) in resolved
        assert ("bwd", 64, 64) in resolved
        # the custom_vjp residual fwd runs too; no call may cross phases
        assert all(r in (("fwd", 128, 128), ("bwd", 64, 64))
                   for r in resolved)

    def test_clamped_blocks_respect_vmem_budget(self):
        """_pick_block must never hand Mosaic a block pair whose
        APX304-priced footprint exceeds the VMEM budget — the long-seq
        defaults (target 1024/512) clamp instead of overflowing."""
        from apex_tpu.ops import flash_attention_pallas as fap
        from apex_tpu.ops._pallas_tiling import VMEM_BUDGET, flash_vmem_bytes

        for phase, target in (("fwd", 1024), ("bwd", 512)):
            for S in (2048, 4096, 8192):
                for D in (64, 128):
                    bq, bk = fap._clamped_blocks(S, S, D, jnp.bfloat16,
                                                 target, target, phase)
                    assert S % bq == 0 and S % bk == 0
                    assert flash_vmem_bytes(bq, bk, D, phase) <= VMEM_BUDGET, \
                        (phase, S, D, bq, bk)
        # an explicitly over-budget request clamps too (2048² fwd at
        # D=64 prices ~38 MiB — more than double the 16 MiB budget)
        bq, bk = fap._clamped_blocks(2048, 2048, 64, jnp.bfloat16,
                                     2048, 2048, "fwd")
        assert flash_vmem_bytes(bq, bk, 64, "fwd") <= VMEM_BUDGET
        assert (bq, bk) != (2048, 2048)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.slow
    def test_backward_matches_reference(self, causal):
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(Sq=128, Sk=128)

        def loss_pallas(q, k, v):
            return jnp.sum(flash_attention_pallas(q, k, v, causal=causal, interpret=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)

    def test_ring_offsets_match_scan_path(self):
        """q_offset/k_offset causal masking agrees with the scan path."""
        q, k, v = self._inputs(Sq=128, Sk=256)
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        out = flash_attention_pallas(q, k, v, causal=True, q_offset=256, k_offset=64,
                                     interpret=True)
        ref = flash_attention(q, k, v, causal=True, q_offset=256, k_offset=64,
                              impl="scan")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_fully_masked_rows_zero(self):
        """Rows with no visible keys (ring warmup blocks) produce zeros."""
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(Sq=128, Sk=128)
        # every key is in the future of every query
        out = flash_attention_pallas(q, k, v, causal=True, q_offset=0, k_offset=1024,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)

    def test_bf16(self):
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(dtype=jnp.bfloat16)
        out = flash_attention_pallas(q, k, v, causal=True, interpret=True)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
        )

    @pytest.mark.slow
    def test_partially_masked_block_rows_zero(self):
        """Rows fully masked but sharing a q-block with visible rows must
        still be zero (and carry zero grads), independent of block size."""
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(Sq=128, Sk=128)
        # keys start at global position 64: query rows 0..63 see nothing
        for blocks in ((128, 128), (64, 64)):
            out = flash_attention_pallas(q, k, v, causal=True, q_offset=0,
                                         k_offset=64, block_q=blocks[0],
                                         block_k=blocks[1], interpret=True)
            np.testing.assert_allclose(np.asarray(out[:, :, :64]), 0.0, atol=1e-6)
        # scan path too
        out_s = flash_attention(q, k, v, causal=True, k_offset=64, impl="scan")
        np.testing.assert_allclose(np.asarray(out_s[:, :, :64]), 0.0, atol=1e-6)

        def loss(qq):
            o = flash_attention_pallas(qq, k, v, causal=True, q_offset=0,
                                       k_offset=64, interpret=True)
            return jnp.sum(o ** 2)

        dq = jax.grad(loss)(q)
        np.testing.assert_allclose(np.asarray(dq[:, :, :64]), 0.0, atol=1e-6)

    def test_impl_validation(self):
        q, k, v = self._inputs(Sq=128, Sk=128)
        with pytest.raises(ValueError, match="impl"):
            flash_attention(q, k, v, impl="pallaz")


class TestRingAttentionPallas:
    """Ring with per-chunk-pair Pallas kernels (interpret mode)."""

    pytestmark = pytest.mark.slow  # interpret-mode ring grads: ~10 s

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention_with_grads(self, causal, devices8):
        B, H, S, D = 1, 2, 512, 8  # S_local = 128: kernel-eligible
        q, k, v = qkv(7, B=B, H=H, S=S, D=D)
        mesh = Mesh(np.array(devices8[:4]), ("cp",))

        def fr(q, k, v):
            return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=causal)))

        ref = mha_reference(q, k, v, causal=causal)
        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)

        def f(q, k, v):
            return ring_attention(q, k, v, "cp", causal=causal,
                                  impl="pallas", interpret=True)

        out = jax.shard_map(
            f, mesh=mesh, in_specs=(P(None, None, "cp", None),) * 3,
            out_specs=P(None, None, "cp", None), check_vma=False,
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)

        g = jax.shard_map(
            jax.grad(lambda q, k, v: jnp.sum(jnp.sin(f(q, k, v))), argnums=(0, 1, 2)),
            mesh=mesh, in_specs=(P(None, None, "cp", None),) * 3,
            out_specs=(P(None, None, "cp", None),) * 3, check_vma=False,
        )(q, k, v)
        for a, r in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-5)


class TestGroupedQueryAttention:
    """GQA: k/v with fewer heads than q.  The Pallas kernels read the
    group-shared kv blocks via index maps (no HBM repeat); the scan
    path repeats heads.  Oracle = dense attention with repeated kv."""

    def _inputs(self, B=2, H=4, Hkv=2, Sq=256, Sk=256, D=64, seed=0):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(B, H, Sq, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, Hkv, Sk, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, Hkv, Sk, D).astype(np.float32))
        return q, k, v

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("hkv", [1, 2])  # MQA and 2-way groups
    def test_pallas_forward_matches_reference(self, causal, hkv):
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(Hkv=hkv)
        out = flash_attention_pallas(q, k, v, causal=causal, interpret=True)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("hkv", [1, 2])  # MQA (group=heads) and group=2
    @pytest.mark.slow
    def test_pallas_backward_matches_reference(self, causal, hkv):
        """dk/dv must be the GROUP SUM over the kv head's q heads — the
        kernel accumulates it in VMEM across the extended inner grid."""
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(Hkv=hkv, Sq=128, Sk=128)

        def loss_pallas(q, k, v):
            return jnp.sum(flash_attention_pallas(q, k, v, causal=causal, interpret=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            assert a.shape == b.shape
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)

    @pytest.mark.slow
    def test_pallas_backward_with_padding_mask(self):
        """The dkv pass's bias rows index the (B·kv_heads) grid
        (b // kv_heads); a regression to b // heads would read the
        wrong batch's mask.  B>1 with different per-batch masks makes
        that misread change the numbers."""
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(B=3, H=4, Hkv=2, Sq=128, Sk=128)
        rng = np.random.RandomState(5)
        lengths = rng.randint(32, 129, size=3)
        kv_mask = jnp.asarray(np.arange(128)[None, :] < lengths[:, None])

        def loss_pallas(q, k, v):
            return jnp.sum(flash_attention_pallas(
                q, k, v, causal=False, kv_mask=kv_mask, interpret=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=False, kv_mask=kv_mask) ** 2)

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)

    def test_scan_path_matches_reference(self):
        q, k, v = self._inputs(Sq=64, Sk=64, D=8)
        out = flash_attention(q, k, v, causal=True, impl="scan", block_k=16)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)
        # backward through the repeat sums the group
        gp = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=True, impl="scan", block_k=16) ** 2),
                      argnums=(1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(mha_reference(*a, causal=True) ** 2),
                      argnums=(1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            assert a.shape == b.shape
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)

    def test_pallas_gqa_with_padding_mask(self):
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs()
        rng = np.random.RandomState(3)
        lengths = rng.randint(128, 257, size=q.shape[0])
        kv_mask = jnp.asarray(np.arange(256)[None, :] < lengths[:, None])
        out = flash_attention_pallas(q, k, v, causal=False, kv_mask=kv_mask,
                                     interpret=True)
        ref = mha_reference(q, k, v, causal=False, kv_mask=kv_mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_indivisible_heads_rejected(self):
        from apex_tpu.ops.flash_attention_pallas import flash_attention_pallas

        q, k, v = self._inputs(H=4, Hkv=3)
        with pytest.raises(ValueError, match="not divisible"):
            flash_attention_pallas(q, k, v, interpret=True)
