"""T5 pretraining example CLI: the enc-dec counterpart of the GPT
trainer — dual-stream pipeline, fp16 scaling, fused CE, all through
the command line on the virtual mesh."""

import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[1]


def _run(args):
    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": str(REPO),
    }
    r = subprocess.run(
        [sys.executable, str(REPO / "examples/t5/pretrain_t5.py"), *args],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert r.returncode == 0, f"stderr:\n{r.stderr[-2000:]}"
    return r.stdout


def _losses(out):
    return [float(m) for m in re.findall(r"loss=([0-9.]+)", out)]


def test_pp_split_trains():
    """pp=4 split=2 x tp=2: the dual-stream pipeline runs from the CLI
    and the copy-task loss falls over the batch pool."""
    out = _run(["--pp", "4", "--split", "2", "--tp", "2", "--steps", "10",
                "--lr", "3e-3"])
    losses = _losses(out)
    assert len(losses) == 10 and losses[-1] < losses[0]


def test_fp16_fused_ce_composes():
    """--fp16 (scaler through the dual-stream schedule) x --fused-ce."""
    out = _run(["--pp", "2", "--steps", "8", "--fp16", "--fused-ce",
                "--lr", "3e-3"])
    losses = _losses(out)
    assert len(losses) == 8 and losses[-1] < losses[0]
