"""Tests for ``apex_tpu.resilience.locks`` — the runtime lock-order
sanitizer (APX115's runtime twin) and the ``assert_lock_held``
acquittal seam.

The centerpiece is the chaos pair: the SAME two-lock inversion,
provoked across the watchdog ``on_fire`` thread and the main thread,
(a) raises a structured :class:`LockOrderViolation` naming both locks
and carrying both stacks when instrumented, and (b) genuinely
deadlocks (proven under an ``acquire(timeout=)`` guard — both sides
time out, each holding the lock the other wants) when NOT
instrumented.  Together they prove the sanitizer catches a real hang,
not a false alarm.
"""

import threading

import pytest

from apex_tpu.resilience.elastic import StepWatchdog
from apex_tpu.resilience.locks import (
    LockContractError,
    LockOrderViolation,
    assert_lock_held,
    instrument_locks,
    instrumentation_enabled,
    monitored_lock,
    reset_lock_monitor,
)


@pytest.fixture(autouse=True)
def _clean_monitor():
    reset_lock_monitor()
    yield
    reset_lock_monitor()


class TestMonitoredLock:
    def test_behaves_like_a_lock_uninstrumented(self):
        lk = monitored_lock("plain")
        assert not instrumentation_enabled()
        assert lk.acquire(blocking=False)
        assert lk.locked()
        assert not lk.acquire(blocking=False)  # non-reentrant kind
        lk.release()
        assert not lk.locked()
        with lk:
            assert lk.held_by_current_thread()

    def test_rlock_kind_is_reentrant(self):
        lk = monitored_lock("re", kind="rlock")
        with lk:
            with lk:
                assert lk.held_by_current_thread()
        assert not lk.locked()

    def test_bad_kind_is_loud(self):
        with pytest.raises(ValueError, match="kind"):
            monitored_lock("x", kind="mutex")

    def test_instrument_returns_previous_state(self):
        assert instrument_locks(True) is False
        assert instrument_locks(False) is True
        assert not instrumentation_enabled()

    def test_consistent_order_never_raises(self):
        a, b = monitored_lock("a"), monitored_lock("b")
        instrument_locks(True)
        for _ in range(3):
            with a:
                with b:
                    pass
        # same order from another thread: still fine
        errors = []

        def same_order():
            try:
                with a:
                    with b:
                        pass
            except Exception as e:  # pragma: no cover - fail loudly
                errors.append(e)

        t = threading.Thread(target=same_order)
        t.start()
        t.join()
        assert not errors

    def test_inversion_raises_naming_both_locks_and_stacks(self):
        a, b = monitored_lock("ckpt.lock"), monitored_lock("stats.lock")
        instrument_locks(True)

        def establish_forward_order():
            with a:
                with b:
                    pass

        establish_forward_order()
        with pytest.raises(LockOrderViolation) as ei:
            with b:
                with a:
                    pass
        msg = str(ei.value)
        assert "ckpt.lock" in msg and "stats.lock" in msg
        assert "this acquisition" in msg and "prior acquisition" in msg
        # both stacks are carried: the historical one shows the
        # function that established the forward order
        assert "establish_forward_order" in ei.value.prior_stack
        assert ei.value.this_stack

    def test_rlock_reentry_is_not_an_inversion(self):
        r = monitored_lock("r", kind="rlock")
        instrument_locks(True)
        with r:
            with r:   # re-entry: no (r, r) edge, no violation
                pass

    def test_release_out_of_acquire_order_is_tolerated(self):
        a, b = monitored_lock("a"), monitored_lock("b")
        instrument_locks(True)
        a.acquire()
        b.acquire()
        a.release()   # release the OUTER lock first
        b.release()
        with a:       # held-stack bookkeeping survived
            pass


class TestAssertLockHeld:
    def test_monitored_lock_held_passes_not_held_raises(self):
        lk = monitored_lock("contract")
        with lk:
            assert_lock_held(lk)
        with pytest.raises(LockContractError, match="contract"):
            assert_lock_held(lk)

    def test_monitored_lock_held_by_other_thread_raises(self):
        lk = monitored_lock("other")
        lk2 = threading.Event()
        done = threading.Event()

        def holder():
            with lk:
                lk2.set()
                done.wait(5)

        t = threading.Thread(target=holder)
        t.start()
        assert lk2.wait(5)
        try:
            with pytest.raises(LockContractError):
                assert_lock_held(lk)   # held, but not by THIS thread
        finally:
            done.set()
            t.join()

    def test_plain_lock_and_rlock(self):
        pl = threading.Lock()
        with pytest.raises(LockContractError):
            assert_lock_held(pl)
        with pl:
            assert_lock_held(pl)   # locked() is the best a Lock offers
        rl = threading.RLock()
        with pytest.raises(LockContractError):
            assert_lock_held(rl)
        with rl:
            assert_lock_held(rl)


# ------------------------------------------------------------- chaos pair
class _InversionRig:
    """The two-lock inversion provoked across the watchdog ``on_fire``
    thread and the main thread: main establishes/holds ``ckpt`` then
    wants ``stats``; the watchdog's fire path takes ``stats`` then
    wants ``ckpt``.  ``make_locks`` injects monitored or plain locks so
    the instrumented and un-instrumented runs share one program."""

    def __init__(self, ckpt_lock, stats_lock):
        self.ckpt, self.stats = ckpt_lock, stats_lock
        self.main_holds_ckpt = threading.Event()
        self.fire_holds_stats = threading.Event()
        self.main_attempt_done = threading.Event()
        self.fire_error = []
        self.fire_deadlocked = []

    def on_fire(self, info):
        """Runs on the watchdog thread (the test seam replaces
        ``os._exit``): stats -> ckpt, the REVERSE of main's order.

        The cross-acquire timeouts are asymmetric (fire 0.5s, main
        2.0s) and each side keeps holding its own lock until the other
        side's attempt is acknowledged, so BOTH timeouts are provable
        — the other lock is held for the attempt's whole window — and
        the proof never races the moment a timer expires."""
        try:
            got_stats = self.stats.acquire(timeout=5)
            assert got_stats
            try:
                self.fire_holds_stats.set()
                self.main_holds_ckpt.wait(5)
                # deadlock point: main holds ckpt (and keeps holding it
                # until main_attempt_done) and wants stats
                if self.ckpt.acquire(timeout=0.5):
                    self.ckpt.release()
                else:
                    self.fire_deadlocked.append(True)
                    # keep stats held until main's (longer) attempt has
                    # definitely run its course against a held lock
                    self.main_attempt_done.wait(10)
            finally:
                self.stats.release()
        except LockOrderViolation as e:
            self.fire_error.append(e)

    def establish_forward_order(self):
        """The program's NORMAL path: ckpt -> stats, uncontended.
        Under the sanitizer this is what records the forward edge, so
        the later reversed acquisition on the watchdog thread is the
        one that closes the cycle (deterministically — not a race over
        which thread records its half first)."""
        with self.ckpt:
            with self.stats:
                pass

    def run_main_side(self):
        """ckpt -> stats on the main thread, interleaved with the
        fire path via events.  Returns True if the stats acquire
        timed out (main's half of the deadlock)."""
        self.establish_forward_order()
        got = self.ckpt.acquire(timeout=5)
        assert got
        try:
            self.main_holds_ckpt.set()
            self.fire_holds_stats.wait(5)
            if self.stats.acquire(timeout=2.0):
                self.stats.release()
                return False
            return True
        finally:
            self.main_attempt_done.set()
            self.ckpt.release()


def _fire_watchdog(rig):
    """Arm a watchdog with a tiny deadline and never beat it, so its
    monitor thread fires ``on_fire`` (the test seam) — the inversion
    really crosses the watchdog thread, not a synthetic Thread."""
    wd = StepWatchdog(deadline_sec=0.05, poll_sec=0.01,
                      on_fire=rig.on_fire)
    wd.start()
    return wd


class TestLockInversionChaos:
    def test_instrumented_inversion_raises_with_both_stacks(self):
        """Sanitizer armed: the watchdog-thread fire path's stats ->
        ckpt acquisition closes the cycle against main's ckpt -> stats
        and raises BEFORE wedging — naming both locks and carrying
        both threads' stacks."""
        instrument_locks(True)
        rig = _InversionRig(monitored_lock("ckpt.lock"),
                            monitored_lock("stats.lock"))
        wd = _fire_watchdog(rig)
        try:
            main_timed_out = rig.run_main_side()
        finally:
            wd.stop()
        assert rig.fire_error, "sanitizer did not raise on the inversion"
        err = rig.fire_error[0]
        msg = str(err)
        assert "ckpt.lock" in msg and "stats.lock" in msg
        assert "apex_tpu-step-watchdog" in msg  # the violating thread
        assert err.prior_stack and err.this_stack
        assert "run_main_side" in err.prior_stack
        assert "on_fire" in err.this_stack
        # the violation fired before the fire path ever blocked on
        # ckpt, so it never reached the deadlock point...
        assert not rig.fire_deadlocked
        # ...and main's stats acquire succeeded once the fire path
        # unwound (no hang anywhere)
        assert main_timed_out is False

    def test_uninstrumented_same_program_deadlocks(self):
        """The control: identical program, plain ``threading.Lock``s,
        no sanitizer — BOTH sides time out at the deadlock point, each
        holding the lock the other wants.  This is the real hang the
        instrumented run converted into a structured error (bounded
        here only by the acquire timeouts the rig wears)."""
        assert not instrumentation_enabled()
        rig = _InversionRig(threading.Lock(), threading.Lock())
        wd = _fire_watchdog(rig)
        try:
            main_timed_out = rig.run_main_side()
        finally:
            wd.stop()
        assert not rig.fire_error
        assert main_timed_out, "main side acquired stats — no deadlock?"
        assert rig.fire_deadlocked, \
            "fire side acquired ckpt — no deadlock?"

    def test_uninstrumented_monitored_locks_also_deadlock(self):
        """monitored_lock WITHOUT instrument_locks() must behave
        exactly like the primitive — including deadlocking — so
        production code can keep the named wrappers permanently and
        arm the sanitizer only in debug/chaos runs."""
        assert not instrumentation_enabled()
        rig = _InversionRig(monitored_lock("ckpt.lock"),
                            monitored_lock("stats.lock"))
        wd = _fire_watchdog(rig)
        try:
            main_timed_out = rig.run_main_side()
        finally:
            wd.stop()
        assert not rig.fire_error
        assert main_timed_out and rig.fire_deadlocked
