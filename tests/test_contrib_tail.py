"""Contrib tail tests: ASP 2:4 masks, transducer loss (vs brute-force
DP oracle), conv_bias_relu (vs torch), halo exchange (vs full-tensor
conv), RNN factories."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.bottleneck import halo_exchange_1d
from apex_tpu.contrib.conv_bias_relu import ConvBias, ConvBiasReLU
from apex_tpu.contrib.sparsity import ASP, compute_sparse_masks, m4n2_mask
from apex_tpu.contrib.transducer import TransducerJoint, transducer_loss


class TestASP:
    def test_m4n2_keeps_two_of_four(self):
        w = jnp.asarray(np.random.RandomState(0).randn(8, 16).astype(np.float32))
        m = m4n2_mask(w)
        groups = np.asarray(m).reshape(-1, 4)
        assert (groups.sum(1) == 2).all()

    def test_mask_keeps_largest(self):
        w = jnp.asarray([[1.0, -5.0, 0.1, 3.0]])
        m = m4n2_mask(w)
        np.testing.assert_array_equal(np.asarray(m), [[False, True, False, True]])

    def test_prune_trained_model(self):
        params = {
            "dense": jnp.asarray(np.random.RandomState(1).randn(4, 8).astype(np.float32)),
            "bias": jnp.ones((4,)),
            "layernorm": jnp.ones((4, 8)),
        }
        pruned, masks = ASP.prune_trained_model(params)
        assert masks["bias"] is None  # 1D skipped
        assert masks["layernorm"] is None  # norm skipped
        dense = np.asarray(pruned["dense"]).reshape(-1, 4)
        assert ((dense != 0).sum(1) <= 2).all()

    def test_masked_training_stays_sparse(self):
        params = {"w": jnp.asarray(np.random.RandomState(2).randn(4, 8).astype(np.float32))}
        pruned, masks = ASP.prune_trained_model(params)
        stepped = jax.tree.map(lambda p: p + 0.1, pruned)  # optimizer densifies
        remasked = ASP.apply_masks(stepped, masks)
        assert (np.asarray(remasked["w"]).reshape(-1, 4) != 0).sum() <= 2 * 8


class TestPermutationSearch:
    """Channel-permutation search (reference permutation_lib.py +
    channel_swap.py): the accuracy-preserving half of ASP."""

    def _adversarial_weight(self, R=16, C=16, seed=0):
        """Big channels clustered inside stripes: the naive 2:4 mask must
        drop large entries; a permutation spreading them out keeps them."""
        rng = np.random.RandomState(seed)
        w = rng.randn(R, C).astype(np.float32) * 0.01
        w[:, 0:4] += rng.randn(R, 4).astype(np.float32) * 10.0  # one hot stripe
        return jnp.asarray(w)

    def test_search_improves_retained_magnitude(self):
        from apex_tpu.contrib.sparsity.permutation_lib import (
            search_channel_permutation,
            sum_after_2_to_4,
        )

        w = self._adversarial_weight()
        perm, base, best = search_channel_permutation(w)
        assert best > base * 1.2, (base, best)  # the clustered case is a big win
        np.testing.assert_allclose(
            float(sum_after_2_to_4(w[:, jnp.asarray(perm)])), best, rtol=1e-6
        )

    def test_permuted_mask_is_structured_under_perm(self):
        from apex_tpu.contrib.sparsity.permutation_lib import (
            permuted_m4n2_mask,
            search_channel_permutation,
        )

        w = self._adversarial_weight(seed=1)
        perm, _, _ = search_channel_permutation(w)
        mask = permuted_m4n2_mask(w, perm)
        groups = np.asarray(mask[:, perm]).reshape(-1, 4)
        assert (groups.sum(1) == 2).all()  # 2:4 in the permuted domain

    def test_permuted_mask_beats_naive_on_model_loss(self):
        """The done-criterion: searched masks give lower masked-model
        loss than naive masks (here: output MSE of a linear layer)."""
        from apex_tpu.contrib.sparsity.permutation_lib import permuted_m4n2_mask, search_channel_permutation

        rng = np.random.RandomState(3)
        w = self._adversarial_weight(R=32, C=16, seed=3)
        x = jnp.asarray(rng.randn(64, 32).astype(np.float32))
        dense_out = x @ np.asarray(w)

        naive = m4n2_mask(w)
        perm, _, _ = search_channel_permutation(w)
        searched = permuted_m4n2_mask(w, perm)

        loss_naive = float(jnp.mean((x @ (w * naive) - dense_out) ** 2))
        loss_searched = float(jnp.mean((x @ (w * searched) - dense_out) ** 2))
        assert loss_searched < loss_naive, (loss_searched, loss_naive)

    def test_asp_integration(self):
        params = {"dense": self._adversarial_weight(seed=4), "bias": jnp.ones((4,))}
        from apex_tpu.contrib.sparsity.permutation_lib import sum_after_2_to_4

        naive = compute_sparse_masks(params)
        searched = compute_sparse_masks(params, permutation_search=True)
        assert searched["bias"] is None
        w = params["dense"]
        kept_naive = float(jnp.sum(jnp.abs(w * naive["dense"])))
        kept_searched = float(jnp.sum(jnp.abs(w * searched["dense"])))
        assert kept_searched > kept_naive


class TestTransducer:
    def test_joint_broadcast_add(self):
        f = jnp.ones((2, 3, 4))
        g = jnp.full((2, 5, 4), 2.0)
        out = TransducerJoint()(f, g)
        assert out.shape == (2, 3, 5, 4)
        np.testing.assert_allclose(np.asarray(out), 3.0)

    def test_loss_matches_bruteforce(self):
        rng = np.random.RandomState(3)
        B, T, U, V = 2, 4, 3, 5  # targets length U-1=2, vocab incl blank
        logits = rng.randn(B, T, U, V).astype(np.float32)
        targets = rng.randint(0, V - 1, size=(B, U - 1))
        loss = transducer_loss(
            jnp.asarray(logits),
            jnp.asarray(targets) ,
            jnp.full((B,), T),
            jnp.full((B,), U - 1),
            blank_idx=0,
        )
        logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
        for b in range(B):
            ref = rnnt_oracle_full(logp[b], targets[b], T, U)
            np.testing.assert_allclose(float(loss[b]), ref, rtol=1e-4)

    @pytest.mark.slow
    def test_loss_is_differentiable(self):
        rng = np.random.RandomState(4)
        logits = jnp.asarray(rng.randn(1, 3, 2, 4).astype(np.float32))
        g = jax.grad(
            lambda l: jnp.sum(
                transducer_loss(l, jnp.asarray([[1]]), jnp.asarray([3]), jnp.asarray([1]))
            )
        )(logits)
        assert np.isfinite(np.asarray(g)).all()


def rnnt_oracle_full(logp, targets, T, U):
    """Brute-force alpha DP (blank=0, labels are raw vocab ids)."""
    alpha = np.full((T, U), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U):
            if t == 0 and u == 0:
                continue
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + logp[t - 1, u, 0])
            if u > 0:
                cands.append(alpha[t, u - 1] + logp[t, u - 1, targets[u - 1]])
            alpha[t, u] = np.logaddexp.reduce(cands)
    return -(alpha[T - 1, U - 1] + logp[T - 1, U - 1, 0])


class TestConvBiasReLU:
    def test_matches_torch(self):
        rng = np.random.RandomState(5)
        x = rng.randn(1, 8, 8, 3).astype(np.float32)  # NHWC
        w = rng.randn(3, 3, 3, 6).astype(np.float32)  # HWIO
        b = rng.randn(6).astype(np.float32)
        out = ConvBiasReLU(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
        ref = torch.nn.functional.relu(
            torch.nn.functional.conv2d(
                torch.tensor(x).permute(0, 3, 1, 2),
                torch.tensor(w).permute(3, 2, 0, 1),
                torch.tensor(b),
                padding=1,
            )
        ).permute(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4, atol=1e-4)


class TestHaloExchange:
    def test_sharded_conv_matches_full(self, devices8):
        """The spatial-parallelism correctness test: conv over H-sharded
        tensor with halo exchange == conv over the full tensor."""
        rng = np.random.RandomState(6)
        N, H, W, C = 1, 16, 8, 3
        x = rng.randn(N, H, W, C).astype(np.float32)
        w = rng.randn(3, 3, C, 4).astype(np.float32)

        ref = ConvBias(jnp.asarray(x), jnp.asarray(w), jnp.zeros(4), padding="SAME")

        mesh = Mesh(np.array(devices8[:4]), ("spatial",))

        def f(x, w):
            padded = halo_exchange_1d(x, 1, "spatial", spatial_axis=1)
            out = ConvBias(padded, w, jnp.zeros(4), padding=[(0, 0), (1, 1)])
            return out  # VALID in H after halo, SAME in W

        out = jax.shard_map(
            f, mesh=mesh, in_specs=(P(None, "spatial"), P()), out_specs=P(None, "spatial"),
            check_vma=False,
        )(jnp.asarray(x), jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


class TestRNN:
    def test_factories_emit_deprecation(self):
        import apex_tpu.RNN as RNN

        with pytest.warns(DeprecationWarning):
            m = RNN.LSTM(8, 16)
        x = jnp.ones((5, 2, 8))  # (T, B, F), seq-first like the reference
        params = m.init(jax.random.PRNGKey(0))
        out, (h, c) = m.apply(params, x)
        assert out.shape == (5, 2, 16)
        assert h.shape == (1, 2, 16) and c.shape == (1, 2, 16)

    def _load_torch_lstm_weights(self, params, t_rnn, layers, dirs=1):
        for layer in range(layers):
            for d in range(dirs):
                stack = params[d][layer] if dirs == 2 else params[layer]
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                for ours, theirs in (("w_ih", "weight_ih"), ("w_hh", "weight_hh"),
                                     ("b_ih", "bias_ih"), ("b_hh", "bias_hh")):
                    getattr(t_rnn, theirs + sfx).data = torch.tensor(np.asarray(stack[ours]))

    @pytest.mark.parametrize("kind,tcls", [("lstm", torch.nn.LSTM), ("gru", torch.nn.GRU)])
    def test_matches_torch(self, kind, tcls):
        import apex_tpu.RNN as RNN

        T, B, I, H, L = 5, 3, 4, 6, 2
        with pytest.warns(DeprecationWarning):
            m = getattr(RNN, kind.upper())(I, H, num_layers=L)
        params = m.init(jax.random.PRNGKey(1))
        x = np.random.RandomState(0).randn(T, B, I).astype(np.float32)
        out, hiddens = m.apply(params, jnp.asarray(x))

        t_rnn = tcls(I, H, num_layers=L)
        self._load_torch_lstm_weights(params, t_rnn, L)
        t_out, t_hid = t_rnn(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(out), t_out.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)
        t_h = t_hid[0] if isinstance(t_hid, tuple) else t_hid
        np.testing.assert_allclose(np.asarray(hiddens[0]), t_h.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_bidirectional_matches_torch(self):
        import apex_tpu.RNN as RNN

        T, B, I, H = 4, 2, 3, 5
        with pytest.warns(DeprecationWarning):
            m = RNN.LSTM(I, H, num_layers=1, bidirectional=True)
        params = m.init(jax.random.PRNGKey(2))
        x = np.random.RandomState(1).randn(T, B, I).astype(np.float32)
        out, _ = m.apply(params, jnp.asarray(x))

        t_rnn = torch.nn.LSTM(I, H, num_layers=1, bidirectional=True)
        self._load_torch_lstm_weights(params, t_rnn, 1, dirs=2)
        t_out, _ = t_rnn(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(out), t_out.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_mlstm_formula(self):
        """mLSTM vs the reference cells.py formula, one step by hand."""
        import apex_tpu.RNN as RNN

        I, H, B = 3, 4, 2
        with pytest.warns(DeprecationWarning):
            m = RNN.mLSTM(I, H)
        params = m.init(jax.random.PRNGKey(3))
        p = params[0]
        x = np.random.RandomState(2).randn(1, B, I).astype(np.float32)
        out, (h, c) = m.apply(params, jnp.asarray(x))

        def sig(a):
            return 1.0 / (1.0 + np.exp(-a))

        mm = (x[0] @ np.asarray(p["w_mih"]).T) * (np.zeros((B, H)) @ np.asarray(p["w_mhh"]).T)
        gates = (x[0] @ np.asarray(p["w_ih"]).T + np.asarray(p["b_ih"])
                 + mm @ np.asarray(p["w_hh"]).T + np.asarray(p["b_hh"]))
        i, f, g, o = np.split(gates, 4, axis=-1)
        cy = sig(f) * 0 + sig(i) * np.tanh(g)
        hy = sig(o) * np.tanh(cy)
        np.testing.assert_allclose(np.asarray(out[0]), hy, rtol=1e-5, atol=1e-6)

    @pytest.mark.slow
    def test_grads_flow(self):
        import apex_tpu.RNN as RNN

        with pytest.warns(DeprecationWarning):
            m = RNN.GRU(4, 8, num_layers=2)
        params = m.init(jax.random.PRNGKey(4))
        x = jnp.ones((6, 2, 4))
        g = jax.grad(lambda p: jnp.sum(m.apply(p, x)[0] ** 2))(params)
        assert all(bool(jnp.all(jnp.isfinite(l))) for l in jax.tree.leaves(g))
        assert any(float(jnp.abs(l).max()) > 0 for l in jax.tree.leaves(g))


class TestGroupBN:
    """NHWC group BN + fused add/ReLU (reference apex/contrib/groupbn)."""

    def _data(self, N=4, H=3, W=3, C=8, seed=0):
        rng = np.random.RandomState(seed)
        return jnp.asarray(rng.randn(N, H, W, C).astype(np.float32))

    def test_matches_manual_bn(self):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        x = self._data()
        m = BatchNorm2d_NHWC(num_features=8, axis_name=None)
        variables = m.init(jax.random.PRNGKey(0), x)
        y, _ = m.apply(variables, x, mutable=["batch_stats"])

        xf = np.asarray(x)
        mean = xf.mean((0, 1, 2))
        var = xf.var((0, 1, 2))
        ref = (xf - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    def test_fused_add_relu(self):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        x, z = self._data(seed=1), self._data(seed=2)
        m = BatchNorm2d_NHWC(num_features=8, fuse_relu=True, axis_name=None)
        variables = m.init(jax.random.PRNGKey(0), x)
        y, _ = m.apply(variables, x, z, mutable=["batch_stats"])
        assert (np.asarray(y) >= 0).all()

        # relu backward: zero grad where the fused output was clamped
        def f(z):
            out, _ = m.apply(variables, x, z, mutable=["batch_stats"])
            return jnp.sum(out * 3.0)

        g = jax.grad(f)(z)
        np.testing.assert_allclose(
            np.asarray(g), np.where(np.asarray(y) > 0, 3.0, 0.0), atol=1e-6
        )

    def test_bn_group_partitions_stats(self, devices8):
        """dp=4, bn_group=2: stats sync within {0,1} and {2,3} only."""
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        m = BatchNorm2d_NHWC(num_features=4, bn_group=2, axis_name="dp")
        # shards 0/1 see small values, shards 2/3 see large: per-group
        # normalization differs from global
        x = jnp.concatenate([self._data(N=4, C=4, seed=3), self._data(N=4, C=4, seed=4) * 10.0])
        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        variables = m.init(jax.random.PRNGKey(0), x[:2])

        def apply(x):
            y, _ = m.apply(variables, x, mutable=["batch_stats"])
            return y

        y = jax.shard_map(
            apply, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False
        )(x)
        # oracle: group {0,1}'s output must equal unsynced BN over the
        # first half alone (its group saw exactly those samples)
        first_half = x[:4]
        m0 = BatchNorm2d_NHWC(num_features=4, axis_name=None)
        v0 = m0.init(jax.random.PRNGKey(0), first_half)
        ref, _ = m0.apply(v0, first_half, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y[:4]), np.asarray(ref), rtol=1e-4, atol=1e-5)
        # and group {2,3} equals BN over the second half alone
        second = x[4:]
        ref2, _ = m0.apply(v0, second, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y[4:]), np.asarray(ref2), rtol=1e-4, atol=2e-5)

    def test_running_stats_and_eval(self):
        from apex_tpu.contrib.groupbn import GroupBatchNorm2d

        x = self._data(seed=5)
        m = GroupBatchNorm2d(num_features=8, axis_name=None, momentum=1.0)
        variables = m.init(jax.random.PRNGKey(0), x)
        _, upd = m.apply(variables, x, mutable=["batch_stats"])
        np.testing.assert_allclose(
            np.asarray(upd["batch_stats"]["running_mean"]),
            np.asarray(x).mean((0, 1, 2)), rtol=1e-5, atol=1e-6,
        )
        y_eval = m.apply(
            {"params": variables["params"], "batch_stats": upd["batch_stats"]},
            x, use_running_average=True,
        )
        assert np.isfinite(np.asarray(y_eval)).all()


class TestConvFrozenScaleBiasReLU:
    def test_forward_and_frozen_grads(self):
        from apex_tpu.contrib.conv_bias_relu import ConvFrozenScaleBiasReLU

        rng = np.random.RandomState(20)
        x = jnp.asarray(rng.randn(1, 6, 6, 3).astype(np.float32))
        w = jnp.asarray(rng.randn(3, 3, 3, 4).astype(np.float32))
        scale = jnp.asarray(rng.rand(4).astype(np.float32) + 0.5)
        bias = jnp.asarray(rng.randn(4).astype(np.float32))

        out = ConvFrozenScaleBiasReLU(x, w, scale, bias)
        ref = torch.nn.functional.relu(
            torch.nn.functional.conv2d(
                torch.tensor(np.asarray(x)).permute(0, 3, 1, 2),
                torch.tensor(np.asarray(w)).permute(3, 2, 0, 1),
                padding=1,
            ) * torch.tensor(np.asarray(scale))[None, :, None, None]
            + torch.tensor(np.asarray(bias))[None, :, None, None]
        ).permute(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4, atol=1e-5)

        # frozen: scale/bias receive zero grads (reference returns None)
        g = jax.grad(
            lambda s, b: jnp.sum(ConvFrozenScaleBiasReLU(x, w, s, b) ** 2), argnums=(0, 1)
        )(scale, bias)
        np.testing.assert_allclose(np.asarray(g[0]), 0.0, atol=1e-7)
        np.testing.assert_allclose(np.asarray(g[1]), 0.0, atol=1e-7)
        # x and weight DO get grads
        gx = jax.grad(lambda x: jnp.sum(ConvFrozenScaleBiasReLU(x, w, scale, bias) ** 2))(x)
        assert float(jnp.abs(gx).max()) > 0


class TestTransducerJointOptions:
    @pytest.mark.slow
    def test_relu_dropout_mask(self):
        f = jnp.asarray(np.random.RandomState(21).randn(2, 3, 4).astype(np.float32))
        g = jnp.asarray(np.random.RandomState(22).randn(2, 5, 4).astype(np.float32))
        j = TransducerJoint(relu=True)
        out = j(f, g)
        assert (np.asarray(out) >= 0).all()

        jd = TransducerJoint(dropout=True, dropout_prob=0.5)
        with pytest.raises(ValueError, match="key"):
            jd(f, g)
        out_d = jd(f, g, key=jax.random.PRNGKey(0))
        zeros = float((np.asarray(out_d) == 0).mean())
        assert 0.3 < zeros < 0.7  # ~half dropped

    def test_pack_output_zeroes_dont_care(self):
        f = jnp.ones((2, 4, 3))
        g = jnp.ones((2, 3, 3))
        j = TransducerJoint(pack_output=True)
        out = j(f, g, f_len=jnp.asarray([4, 2]), g_len=jnp.asarray([3, 1]))
        np.testing.assert_allclose(np.asarray(out[0]), 2.0)  # fully valid
        assert np.asarray(out[1, 2:]).sum() == 0  # t >= f_len zeroed
        assert np.asarray(out[1, :, 1:]).sum() == 0  # u >= g_len zeroed
        np.testing.assert_allclose(np.asarray(out[1, :2, :1]), 2.0)
