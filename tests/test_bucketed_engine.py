"""Bucketed multi-tensor engine tests.

The engine (``optimizers/bucketing.py`` + the ``_bucket_update`` paths)
is the TPU form of the reference's ``multi_tensor_apply`` chunk tables:
one fused elementwise pass per dtype bucket.  Its correctness contract:

- **bit-exact vs per-leaf in fp32** — both paths evaluate the same
  elementwise expression tree per element and share one per-leaf-Σx²
  reduction shape for the clip norm, so the bucket layout may not
  change a single ulp on elementwise-only steps;
- **bit-exact vs optax.adamw in fp32** for FusedAdam (the audited
  bench baseline — the ≥1.0× claim is only meaningful if the two
  compute the same function);
- the amp path (``update_scaled``) folds unscale/clip/finite-vote into
  the same grad read with identical results to the separate sweeps;
- a non-finite step is a device-side NO-OP (params, state, step
  counter all unchanged);
- resident bucket state (``init(params, bucketed=True)``) is actually
  donated through a jitted step (the HLO aliases the buffers).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.optimizers import (
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedNovoGrad,
    FusedSGD,
)
from apex_tpu.optimizers import bucketing
from apex_tpu.ops.multi_tensor import (
    multi_tensor_l2norm,
    multi_tensor_scale,
    tree_not_finite,
)

OPTS = {
    "adam": lambda **kw: FusedAdam(lr=1e-2, weight_decay=0.01, **kw),
    "sgd": lambda **kw: FusedSGD(lr=1e-2, momentum=0.9, weight_decay=0.01,
                                 **kw),
    "lamb": lambda **kw: FusedLAMB(lr=1e-2, weight_decay=0.01, **kw),
    "novograd": lambda **kw: FusedNovoGrad(lr=1e-2, weight_decay=0.01, **kw),
    "adagrad": lambda **kw: FusedAdagrad(lr=1e-2, weight_decay=0.01, **kw),
}

#: Adam/SGD/Adagrad steps are elementwise-only, so the bucket layout
#: cannot change a single bit.  LAMB and NovoGrad reduce per-leaf norms
#: — the bucket form reduces over a 1-D slice of the concatenated
#: buffer where the leaf form reduces over the original 2-D leaf, and
#: XLA:CPU vectorizes the two reductions differently (few-ulp drift),
#: so they get a tight allclose instead.  The same applies to any path
#: with ``clip_norm`` (the clip coefficient is reduction-fed).
BITEXACT = {"adam", "sgd", "adagrad"}


def make_tree(seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(9, 17).astype(np.float32)).astype(dtype),
        "sub": {
            "b": jnp.asarray(rng.randn(33).astype(np.float32)).astype(dtype),
            # scalar leaf: exercises the shape-() packing path
            "s": jnp.asarray(np.float32(rng.randn())).astype(dtype),
        },
    }


def make_mixed_tree(seed=0):
    """fp32 and bf16 leaves interleaved → a two-bucket plan."""
    t = make_tree(seed)
    t["h"] = jnp.asarray(
        np.random.RandomState(seed + 1).randn(21).astype(np.float32)
    ).astype(jnp.bfloat16)
    t["sub"]["h2"] = jnp.asarray(
        np.random.RandomState(seed + 2).randn(5, 7).astype(np.float32)
    ).astype(jnp.bfloat16)
    return t


def grads_like(params, seed=7, dtype=None):
    rng = np.random.RandomState(seed)
    return jax.tree.map(
        lambda p: jnp.asarray(
            np.asarray(rng.randn(*p.shape), np.float32)).astype(
            dtype or p.dtype),
        params,
    )


def assert_trees(a, b, exact=True, err=""):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        xa, ya = np.asarray(x, np.float32), np.asarray(y, np.float32)
        if exact:
            np.testing.assert_array_equal(xa, ya, err_msg=err)
        else:
            np.testing.assert_allclose(xa, ya, rtol=1e-5, atol=1e-6,
                                       err_msg=err)


# --------------------------------------------------------------- the plan
class TestBucketPlan:
    def test_layout(self):
        t = make_mixed_tree()
        plan = bucketing.plan_of(t)
        assert len(plan.buckets) == 2  # one fp32 + one bf16 bucket
        assert {b.dtype for b in plan.buckets} == {"float32", "bfloat16"}
        # bucket order is the dtypes' first appearance in tree_flatten
        # order — deterministic for a fixed treedef
        first_seen = list(dict.fromkeys(plan.leaf_dtypes))
        assert [b.dtype for b in plan.buckets] == first_seen
        for b in plan.buckets:
            # leaves back-to-back, tail padded to the dtype tile
            off = 0
            for bl in b.leaves:
                assert bl.offset == off
                off += bl.size
            assert b.size == off
            assert b.total >= b.size and b.total % 128 == 0

    def test_plan_is_cached_and_hashable(self):
        t = make_mixed_tree()
        assert bucketing.plan_of(t) is bucketing.plan_of(
            jax.tree.map(lambda x: x + 1, t))
        hash(bucketing.plan_of(t))

    def test_pack_unpack_roundtrip(self):
        t = make_mixed_tree()
        plan = bucketing.plan_of(t)
        back = bucketing.unpack(plan, bucketing.pack(plan, t))
        for x, y in zip(jax.tree.leaves(t), jax.tree.leaves(back)):
            assert x.dtype == y.dtype
            np.testing.assert_array_equal(
                np.asarray(x, np.float32), np.asarray(y, np.float32))

    def test_pad_region_is_zero(self):
        t = make_tree()
        plan = bucketing.plan_of(t)
        (arr,) = bucketing.pack(plan, t)
        b = plan.buckets[0]
        if b.pad:
            assert not np.asarray(arr[b.size:]).any()


# ------------------------------------------------- bucket vs leaf parity
class TestBucketLeafParity:
    @pytest.mark.parametrize("name", sorted(OPTS))
    @pytest.mark.parametrize("mixed", [False, True])
    def test_update_parity(self, name, mixed):
        params = make_mixed_tree() if mixed else make_tree()
        grads = grads_like(params)
        ob = OPTS[name]()
        ol = OPTS[name](use_buckets=False)
        pb, pl = params, params
        sb, sl = ob.init(params), ol.init(params)
        for _ in range(3):
            pb, sb = ob.update(grads, sb, pb)
            pl, sl = ol.update(grads, sl, pl)
        assert_trees(pb, pl, exact=(name in BITEXACT and not mixed),
                     err=f"{name} bucket vs leaf params")
        # state parity: same structure (transparent mode keeps trees)
        assert jax.tree.structure(sb) == jax.tree.structure(sl)
        assert_trees(sb, sl, exact=(name in BITEXACT and not mixed),
                     err=f"{name} bucket vs leaf state")

    @pytest.mark.parametrize("name", sorted(OPTS))
    def test_clip_parity(self, name):
        params = make_tree()
        grads = grads_like(params)
        ob, ol = OPTS[name](), OPTS[name](use_buckets=False)
        pb, sb = ob.update(grads, ob.init(params), params, clip_norm=0.5)
        pl, sl = ol.update(grads, ol.init(params), params, clip_norm=0.5)
        assert_trees(pb, pl, exact=False,
                     err=f"{name} clip_norm bucket vs leaf")

    @pytest.mark.parametrize("name", sorted(OPTS))
    def test_master_weights_parity(self, name):
        params = make_tree(dtype=jnp.bfloat16)
        grads = grads_like(params)
        ob = OPTS[name](master_weights=True)
        ol = OPTS[name](master_weights=True, use_buckets=False)
        pb, sb = ob.update(grads, ob.init(params), params)
        pl, sl = ol.update(grads, ol.init(params), params)
        assert_trees(pb, pl, exact=name in BITEXACT,
                     err=f"{name} master bucket vs leaf")
        assert_trees(sb.master, sl.master, exact=name in BITEXACT)


# -------------------------------------------------------- optax parity
class TestOptaxParity:
    @pytest.mark.parametrize("wd", [0.0, 0.01])
    def test_adamw_bit_exact_fp32(self, wd):
        """The bench A/B's correctness leg: FusedAdam (bucketed, the
        default) computes bit-for-bit the same fp32 function as
        ``optax.adamw`` — so any measured speed gap is implementation,
        not numerics.  Run op-by-op (unjitted): each primitive compiles
        alone, so XLA cannot form different FMA groupings in the two
        trajectories — bit-exactness of the MATH, isolated from
        program-level codegen (the jitted comparison below)."""
        params = make_tree()
        grads = grads_like(params)
        opt = FusedAdam(lr=1e-2, weight_decay=wd)
        ox = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=wd)

        p_f, s_f = params, opt.init(params)
        p_o, s_o = params, ox.init(params)
        for _ in range(4):
            p_f, s_f = opt.update(grads, s_f, p_f)
            upd, s_o = ox.update(grads, s_o, p_o)
            p_o = optax.apply_updates(p_o, upd)
        assert_trees(p_f, p_o, exact=True, err="FusedAdam vs optax.adamw")

    @pytest.mark.parametrize("wd", [0.0, 0.01])
    def test_adamw_jitted_trajectory(self, wd):
        """Whole-step jitted, 4 steps: identical math, but two
        SEPARATELY compiled programs — XLA:CPU forms FMAs differently
        per program, so the trajectories may drift by ulps (measured
        ~3e-8 abs at step 2).  Pinned to a few-ulp band: a real
        numerics bug (wrong β association, dropped bias correction)
        shows up orders of magnitude above it."""
        params = make_tree()
        grads = grads_like(params)
        opt = FusedAdam(lr=1e-2, weight_decay=wd)
        ox = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=wd)

        step_f = jax.jit(lambda g, s, p: opt.update(g, s, p))

        def _o(g, s, p):
            upd, s = ox.update(g, s, p)
            return optax.apply_updates(p, upd), s

        step_o = jax.jit(_o)
        p_f, s_f = params, opt.init(params)
        p_o, s_o = params, ox.init(params)
        for _ in range(4):
            p_f, s_f = step_f(grads, s_f, p_f)
            p_o, s_o = step_o(grads, s_o, p_o)
        for x, y in zip(jax.tree.leaves(p_f), jax.tree.leaves(p_o)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=0, atol=5e-7)

    def test_adamw_bf16_storage_close_to_optax_fp32(self):
        """bf16 params: fp32 math inside, storage rounding outside —
        within one bf16 ulp of the fp32 optax trajectory per step."""
        params32 = make_tree()
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params32)
        grads = grads_like(params32)
        opt = FusedAdam(lr=1e-2, weight_decay=0.01)
        p_f, s_f = opt.update(grads, opt.init(params), params)
        ox = optax.adamw(1e-2, weight_decay=0.01)
        upd, _ = ox.update(grads, ox.init(params32), params32)
        p_o = optax.apply_updates(params32, upd)
        for x, y in zip(jax.tree.leaves(p_f), jax.tree.leaves(p_o)):
            np.testing.assert_allclose(
                np.asarray(x, np.float32), np.asarray(y), rtol=1e-2)


# ------------------------------------------------------ the fused amp path
class TestScaledPath:
    @pytest.mark.parametrize("name", sorted(OPTS))
    def test_update_scaled_matches_separate_sweeps(self, name):
        """unscale+clip+vote folded into the grad read ≡ the explicit
        sweep composition (scaler.unscale → clip → update)."""
        params = make_tree()
        scale = jnp.float32(1024.0)
        grads16 = jax.tree.map(
            lambda g: (g * scale).astype(jnp.float16), grads_like(params))
        opt = OPTS[name]()
        leaf = OPTS[name](use_buckets=False)
        p1, s1, fin = opt.update_scaled(
            grads16, opt.init(params), params, scale=scale, clip_norm=1.0)
        assert bool(fin)
        # reference composition on the per-leaf path
        g = jax.tree.map(lambda x: x.astype(jnp.float32) / scale, grads16)
        p2, s2, fin2 = leaf.update_scaled(
            g, leaf.init(params), params, clip_norm=1.0)
        assert_trees(p1, p2, exact=name in BITEXACT,
                     err=f"{name} fused vs composed amp tail")

    @pytest.mark.parametrize("name", sorted(OPTS))
    @pytest.mark.parametrize("resident", [False, True])
    def test_nonfinite_step_is_noop(self, name, resident):
        """grads_finite=False: params, state slots, and the step counter
        all hold (the capturable noop_flag semantics) — on both the
        transparent and the resident-bucket state."""
        params = make_tree()
        grads = grads_like(params)
        bad = jax.tree.map(lambda g: g.at[..., 0].set(jnp.inf)
                           if g.ndim else g, grads)
        opt = OPTS[name]()
        state0 = opt.init(params, bucketed=resident)
        # one clean step first so momentum buffers are nonzero
        p1, s1, fin1 = opt.update_scaled(grads, state0, params)
        assert bool(fin1)
        p2, s2, fin2 = opt.update_scaled(bad, s1, p1)
        assert not bool(fin2)
        assert_trees(p2, p1, exact=True, err=f"{name} params moved on inf")
        assert int(s2.step) == int(s1.step)
        assert_trees(jax.tree.leaves(s2), jax.tree.leaves(s1), exact=True,
                     err=f"{name} state moved on inf")

    def test_scaler_integration(self):
        """update_scaled's vote drives DynamicLossScaler.update: backoff
        on inf, growth bookkeeping on clean steps."""
        from apex_tpu.amp import DynamicLossScaler

        params = make_tree()
        scaler = DynamicLossScaler(init_scale=2.0 ** 10)
        sstate = scaler.init()
        opt = FusedAdam(lr=1e-2)
        ostate = opt.init(params)
        bad = jax.tree.map(lambda g: g * jnp.inf, grads_like(params))
        p, s, fin = opt.update_scaled(bad, ostate, params,
                                      scale=sstate.loss_scale)
        s2 = scaler.update(sstate, fin)
        assert float(s2.loss_scale) < float(sstate.loss_scale)


# ----------------------------------------------------------- residency
class TestResidentBuckets:
    def test_resident_trajectory_matches_transparent(self):
        params = make_tree()
        grads = grads_like(params)
        opt = FusedAdam(lr=1e-2, weight_decay=0.01)
        pr, sr = params, opt.init(params, bucketed=True)
        pt, st = params, opt.init(params)
        for _ in range(3):
            pr, sr = opt.update(grads, sr, pr)
            pt, st = opt.update(grads, st, pt)
        assert isinstance(sr.exp_avg, bucketing.Buckets)
        assert_trees(pr, pt, exact=True, err="resident vs transparent")
        assert_trees(sr.exp_avg.unpack(dtype=jnp.float32), st.exp_avg,
                     exact=True)

    def test_resident_buffers_are_donated(self):
        """The jaxpr-level donation assertion the engine exists for:
        every bucket buffer input of a ``donate_argnums`` step carries
        an aliased output (``tf.aliasing_output`` in the lowering) —
        m/v/master update in place instead of doubling HBM."""
        params = make_tree()
        grads = grads_like(params)
        opt = FusedAdam(lr=1e-2, master_weights=True)
        state = opt.init(params, bucketed=True)
        n_buckets = len(bucketing.plan_of(params).buckets)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, params):
            p, s = opt.update(grads, state, params)
            return s, p

        txt = step.lower(state, params).as_text()
        n_donated = txt.count("tf.aliasing_output")
        # step counter + m/v/master bucket buffers all alias
        assert n_donated >= 1 + 3 * n_buckets, txt[:2000]

    def test_resident_state_rides_tree_map(self):
        """Buckets is a pytree: the amp scaler and multi_tensor ops see
        the buffers as leaves with no special cases."""
        params = make_tree()
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params, bucketed=True)
        doubled = jax.tree.map(lambda x: x * 2, state.exp_avg)
        assert isinstance(doubled, bucketing.Buckets)
        assert not bool(tree_not_finite(state.exp_avg))


# ------------------------------------- optimizers outside the fused tail
class TestUpdateScaledRouting:
    def test_swa_routes_through_its_update_override(self):
        """``FusedAdamSWA`` overrides ``update`` with extra SWA state
        the fused tail doesn't maintain: it declares
        ``supports_update_scaled = False`` and the scaled train-step
        tail must take the explicit sweep path — calling the override,
        so the SWA average and n_averaged actually advance."""
        from apex_tpu.amp import DynamicLossScaler
        from apex_tpu.contrib.openfold_triton import FusedAdamSWA
        from apex_tpu.models.gpt import _apply_scaled_update

        opt = FusedAdamSWA(lr=1e-2)
        assert not opt.supports_update_scaled

        params = make_tree()
        scaler = DynamicLossScaler(init_scale=4.0)
        sstate = scaler.init()
        state = opt.init(params)
        grads = jax.tree.map(lambda g: g * sstate.loss_scale,
                             grads_like(params))
        new_p, new_state, new_sstate = _apply_scaled_update(
            scaler, sstate, grads, opt, state, params, sync_axes=[])
        assert int(new_state.n_averaged) == 1
        assert int(new_state.adam.step) == 1

    def test_plain_optimizers_support_the_fused_tail(self):
        for name, mk in OPTS.items():
            assert mk().supports_update_scaled, name


# ----------------------------------------------- sharded clip agreement
class TestClipSumsqReduce:
    def test_sharded_and_replicated_leaves_agree_with_oracle(self):
        """Inside a tp=2 shard_map, a tp-sharded leaf's Σx² must psum
        over tp while a replicated leaf's must NOT — the grouped
        reduction :func:`models.gpt.clip_sumsq_reduce` builds from the
        PartitionSpecs.  The oracle is the plain unsharded Σx²."""
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.models.gpt import clip_sumsq_reduce

        mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
        specs = {"w": P("tp", None), "b": P(None)}
        grads = {
            "w": jnp.arange(8.0, dtype=jnp.float32).reshape(4, 2),
            "b": jnp.asarray([3.0, -1.0], jnp.float32),
        }
        oracle = sum(float(jnp.sum(jnp.square(g)))
                     for g in jax.tree.leaves(grads))
        reduce = clip_sumsq_reduce(specs)

        def local(g):
            sq = [jnp.sum(jnp.square(x)) for x in jax.tree.leaves(g)]
            return reduce(sq)

        total = jax.shard_map(
            local, mesh=mesh, in_specs=(specs,), out_specs=P(),
            check_vma=False)(grads)
        np.testing.assert_allclose(np.asarray(total), oracle, rtol=1e-6)

    def test_engine_clip_inside_shard_map_matches_unsharded(self):
        """The whole fused pass under a tp shard_map: update with
        clip_norm + the spec-built sumsq_reduce on sharded params
        equals the unsharded update with clip_norm."""
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.models.gpt import clip_sumsq_reduce

        params = {"w": jnp.asarray(
            np.random.RandomState(0).randn(8, 6), jnp.float32),
            "b": jnp.asarray(np.random.RandomState(1).randn(6),
                             jnp.float32)}
        grads = grads_like(params, seed=3)
        specs = {"w": P("tp", None), "b": P(None)}
        opt = FusedAdam(lr=1e-2, weight_decay=0.01)
        state = opt.init(params)

        p_ref, _ = opt.update(grads, state, params, clip_norm=0.1)

        mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
        reduce = clip_sumsq_reduce(specs)
        sspec = type(state)(step=P(), exp_avg=specs, exp_avg_sq=specs,
                            master=None)

        def local(p, s, g):
            new_p, _ = opt.update(g, s, p, clip_norm=0.1,
                                  sumsq_reduce=reduce)
            return new_p

        p_sh = jax.shard_map(
            local, mesh=mesh, in_specs=(specs, sspec, specs),
            out_specs=specs, check_vma=False)(params, state, grads)
        assert_trees(jax.device_get(p_sh), jax.device_get(p_ref),
                     exact=False, err="sharded clip vs unsharded oracle")


# --------------------------------------- multi_tensor ops on bucket views
class TestMultiTensorBucketViews:
    def test_l2norm_per_leaf_matches_tree(self):
        t = make_tree()
        plan = bucketing.plan_of(t)
        b = bucketing.Buckets(plan, bucketing.pack(plan, t))
        g1, per1 = multi_tensor_l2norm(t, per_tensor=True)
        g2, per2 = multi_tensor_l2norm(b, per_tensor=True)
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        assert len(per1) == len(per2) == len(jax.tree.leaves(t))
        for a, c in zip(per1, per2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))

    def test_scale_on_buckets_returns_buckets(self):
        t = make_tree()
        plan = bucketing.plan_of(t)
        b = bucketing.Buckets(plan, bucketing.pack(plan, t))
        out, found = multi_tensor_scale(b, 2.0)
        assert isinstance(out, bucketing.Buckets)
        assert not bool(found)
        assert_trees(out.unpack(), jax.tree.map(lambda x: x * 2, t),
                     exact=True)


# ----------------------------------------------- the applier conventions
class TestMultiTensorApplier:
    """Parity with the reference calling convention
    ``multi_tensor_applier(op, noop_flag, tensor_lists, *args)``:
    the returned flag accumulates (OR) across calls exactly as the
    kernels' shared noop buffer does."""

    def test_returns_result_and_flag(self):
        t = make_tree()
        out, flag = multi_tensor_applier(multi_tensor_scale, None, [t], 2.0)
        assert flag.dtype == jnp.int32 and int(flag) == 0
        assert_trees(out, jax.tree.map(lambda x: x * 2, t), exact=True)

    def test_found_inf_sets_flag(self):
        t = {"a": jnp.asarray([1.0, jnp.nan])}
        _, flag = multi_tensor_applier(multi_tensor_scale, 0, [t], 1.0)
        assert int(flag) == 1

    def test_flag_is_sticky_across_calls(self):
        """Reference: a set noop buffer stays set — chained clean calls
        cannot clear a previous call's overflow vote."""
        t = make_tree()
        _, flag = multi_tensor_applier(
            multi_tensor_scale, jnp.int32(1), [t], 1.0)
        assert int(flag) == 1
        _, flag = multi_tensor_applier(multi_tensor_scale, flag, [t], 1.0)
        assert int(flag) == 1

    def test_voteless_op_passes_flag_through(self):
        t = make_tree()
        norm, flag = multi_tensor_applier(multi_tensor_l2norm, None, [t])
        assert norm.ndim == 0 and int(flag) == 0
        _, flag = multi_tensor_applier(multi_tensor_l2norm, 1, [t])
        assert int(flag) == 1
