"""TPU-target lowering guard for the flash attention kernels.

``jax.export`` (platforms=['tpu']) runs the full Pallas→Mosaic
lowering without a device.  The per-shape tuned-block table
(``flash_attention_pallas._TUNED_BLOCKS``) is installed from sweep
output by ``benchmarks/install_tuned_blocks.py`` — a bad entry must
fail HERE, not inside an audited bench section on the chip."""

import jax
import jax.numpy as jnp
import pytest
from jax import export as jexport

from apex_tpu.ops import flash_attention_pallas as fap


def _lower(fn, *avals):
    exp = jexport.export(jax.jit(fn), platforms=["tpu"])(*avals)
    assert len(exp.mlir_module_serialized) > 0


@pytest.mark.parametrize("shape", [
    (8, 12, 1024, 64),    # GPT-124M attention
    (2, 12, 4096, 64),    # long-context
    (8, 8, 1024, 128),    # wide head
])
def test_fwd_lowers_for_tpu(shape):
    B, H, S, D = shape
    q = jax.ShapeDtypeStruct((B * H, S, D), jnp.bfloat16)
    _lower(lambda q, k, v: fap.flash_fwd_pallas(
        q, k, v, 1.0 / D ** 0.5, True, 0, 0, heads=H), q, q, q)


def test_bwd_lowers_for_tpu():
    B, H, S, D = 8, 12, 1024, 64
    q = jax.ShapeDtypeStruct((B * H, S, D), jnp.bfloat16)
    r = jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32)
    _lower(lambda q, k, v, o, lse, do: fap.flash_bwd_pallas(
        q, k, v, o, lse, do, 1.0 / D ** 0.5, True, 0, 0, heads=H),
        q, q, q, q, r, q)


def test_tuned_blocks_lower_for_tpu():
    """Whatever the sweep installed must lower for its own shape."""
    table = dict(fap._TUNED_BLOCKS)
    if not table:
        pytest.skip("no tuned blocks installed yet")
    for (S, D, dtype), (bq, bk) in table.items():
        q = jax.ShapeDtypeStruct((4, S, D), jnp.dtype(dtype))
        _lower(lambda q, k, v: fap.flash_fwd_pallas(
            q, k, v, 1.0 / D ** 0.5, True, 0, 0,
            block_q=bq, block_k=bk, heads=4), q, q, q)
