"""TPU-target lowering guard for the flash attention kernels.

``jax.export`` (platforms=['tpu']) runs the full Pallas→Mosaic
lowering without a device.  The per-shape tuned-block table
(``flash_attention_pallas._TUNED_BLOCKS``) is installed from sweep
output by ``benchmarks/install_tuned_blocks.py`` — a bad entry must
fail HERE, not inside an audited bench section on the chip."""

import jax
import jax.numpy as jnp
import pytest
from jax import export as jexport

from apex_tpu.ops import flash_attention_pallas as fap


def _lower(fn, *avals):
    exp = jexport.export(jax.jit(fn), platforms=["tpu"])(*avals)
    assert len(exp.mlir_module_serialized) > 0


@pytest.mark.parametrize("shape", [
    (8, 12, 1024, 64),    # GPT-124M attention
    (2, 12, 4096, 64),    # long-context
    (8, 8, 1024, 128),    # wide head
])
def test_fwd_lowers_for_tpu(shape):
    B, H, S, D = shape
    q = jax.ShapeDtypeStruct((B * H, S, D), jnp.bfloat16)
    _lower(lambda q, k, v: fap.flash_fwd_pallas(
        q, k, v, 1.0 / D ** 0.5, True, 0, 0, heads=H), q, q, q)


def test_bwd_lowers_for_tpu():
    B, H, S, D = 8, 12, 1024, 64
    q = jax.ShapeDtypeStruct((B * H, S, D), jnp.bfloat16)
    r = jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32)
    _lower(lambda q, k, v, o, lse, do: fap.flash_bwd_pallas(
        q, k, v, o, lse, do, 1.0 / D ** 0.5, True, 0, 0, heads=H),
        q, q, q, q, r, q)


def test_fused_ce_small_n_bf16_lowers_for_tpu():
    """Small-N bf16 fused-CE: the row block must round up to the bf16
    (16, 128) sublane tile, not fp32's (8, 128) — ``_ceil_block(N,
    block_n, align=8)`` on bf16 inputs was exactly the dtype-dependent
    tiling class ADVICE r5 flagged (and the static analyzer's APX302
    rule now lints for)."""
    from apex_tpu.ops import fused_ce_pallas as fcp

    assert fcp._sublane(jnp.bfloat16) == 16
    assert fcp._sublane(jnp.float32) == 8
    # N below block_n forces the ceil-rounded edge block the bug lived in
    assert fcp._ceil_block(8, 256, align=fcp._sublane(jnp.bfloat16)) == 16

    N, H, V = 8, 128, 384
    x = jax.ShapeDtypeStruct((N, H), jnp.bfloat16)
    e = jax.ShapeDtypeStruct((V, H), jnp.bfloat16)
    t = jax.ShapeDtypeStruct((N,), jnp.int32)
    _lower(lambda x, e, t: fcp.fused_ce_fwd_pallas(x, e, t), x, e, t)
    lse = jax.ShapeDtypeStruct((N,), jnp.float32)
    _lower(lambda x, e, t, lse, g: fcp.fused_ce_bwd_pallas(x, e, t, lse, g),
           x, e, t, lse, lse)


def test_odd_seq_bf16_lowers_for_tpu():
    """Sq=40 bf16 has no 16-multiple divisor, so ``_pick_block`` keeps
    the misaligned whole-sequence block (bq=40) — pin that this shape
    still passes the Pallas→Mosaic lowering (``_pick_block`` is a
    preference, unlike fused-CE's padded ``_ceil_block`` which is a
    guarantee)."""
    B, H, S, D = 2, 2, 40, 64
    assert fap._pick_block(S, 1024, align=fap._sublane(jnp.bfloat16)) == 40
    q = jax.ShapeDtypeStruct((B * H, S, D), jnp.bfloat16)
    _lower(lambda q, k, v: fap.flash_fwd_pallas(
        q, k, v, 1.0 / D ** 0.5, True, 0, 0, heads=H), q, q, q)


def test_tuned_blocks_lower_for_tpu():
    """Whatever the sweep installed must lower for its own shape and
    phase (keys are per-phase ``(S, D, dtype, phase)``; legacy 3-element
    keys are forward entries)."""
    table = dict(fap._TUNED_BLOCKS)
    if not table:
        pytest.skip("no tuned blocks installed yet")
    for key, (bq, bk) in table.items():
        S, D, dtype = key[:3]
        phase = key[3] if len(key) == 4 else "fwd"
        q = jax.ShapeDtypeStruct((4, S, D), jnp.dtype(dtype))
        if phase == "fwd":
            _lower(lambda q, k, v: fap.flash_fwd_pallas(
                q, k, v, 1.0 / D ** 0.5, True, 0, 0,
                block_q=bq, block_k=bk, heads=4), q, q, q)
        else:
            r = jax.ShapeDtypeStruct((4, S, 1), jnp.float32)
            _lower(lambda q, k, v, o, lse, do: fap.flash_bwd_pallas(
                q, k, v, o, lse, do, 1.0 / D ** 0.5, True, 0, 0,
                block_q=bq, block_k=bk, heads=4), q, q, q, q, r, q)
