"""bench.py --smoke rides tier-1: every bench section's step fn must
still trace and compile on the CPU mesh, so bench bitrot (an API the
bench calls that a refactor moved, a step that no longer traces) is
caught here instead of on scarce chip time.  The smoke run executes
each section once at a tiny config — ~30-60 s total on this box, most
of it amortized by the persistent compile cache across runs."""

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def test_bench_smoke_all_sections_build():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the bench child must not inherit a test-process TPU tunnel
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke"],
        capture_output=True, text=True, timeout=560, env=env,
    )
    report = None
    for line in reversed((proc.stdout or "").splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "smoke" in rec:
            report = rec
            break
    assert report is not None, (
        f"no smoke JSON on stdout; rc={proc.returncode}\n"
        f"stderr tail: {(proc.stderr or '')[-2000:]}")
    broken = {k: v for k, v in report["sections"].items()
              if not v.get("ok")}
    assert proc.returncode == 0 and not broken, (
        f"bench sections no longer build: {json.dumps(broken, indent=2)}")


def test_elastic_resume_smoke_resharded():
    """The ``elastic_resume`` bench section under a TWO-device host
    platform, isolated via ``--smoke-only``: save at dp=2, restore
    resharded at dp=1 — the section itself asserts the banded loss
    continuation (and the bitwise branch at equal worlds), so ``ok``
    means the reshard path held."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--smoke-only", "elastic_resume"],
        capture_output=True, text=True, timeout=400, env=env,
    )
    report = None
    for line in reversed((proc.stdout or "").splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "smoke" in rec:
            report = rec
            break
    assert report is not None, (
        f"no smoke JSON; rc={proc.returncode}\n"
        f"stderr tail: {(proc.stderr or '')[-2000:]}")
    assert proc.returncode == 0 and \
        report["sections"]["elastic_resume"].get("ok"), report
    assert list(report["sections"]) == ["elastic_resume"]


def test_elastic_resume_supervised_mode_rides_smoke():
    """The section's ``supervised`` sub-mode (the save→kill→restore
    cycle driven by the REAL Supervisor over the trainer CLI) asserts
    internally — rc 0 after exactly one restart — so ``ok`` above
    already covers it; this pins that the mode actually runs by default
    (a refactor that silently drops the sub-call must fail here, not
    ship).  Source-text pin, no import: bench.py is a script with heavy
    module-level imports."""
    src = open(BENCH).read()
    assert "def bench_supervised_elastic" in src
    assert 'out["supervised"] = bench_supervised_elastic()' in src
    assert "supervised=True" in src


def test_zero_wire_bytes_accounting_ratios():
    """The ``zero_gpt124`` section's ``wire_bytes_per_step`` field,
    validated at the accounting level (pure plan arithmetic, no step
    compile): the quantized wires cut the grad-sync bytes ~2x vs the
    bf16 default and ~4x vs an fp32 wire, WITH the fp32 per-block
    scale vectors counted against them."""
    import jax.numpy as jnp

    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    params = {"w": jnp.zeros((512, 256), jnp.bfloat16),
              "b": jnp.zeros((8192,), jnp.bfloat16)}

    def wire(**kw):
        opt = DistributedFusedAdam(lr=1e-3, **kw)
        opt.init(params, world_size=4)
        return opt.wire_bytes_per_step()

    bf16 = wire()                                  # default: storage dtype
    i8 = wire(grad_sync_dtype="int8")
    f8 = wire(grad_sync_dtype=jnp.float8_e5m2)
    f32 = wire(grad_sync_dtype=jnp.float32)
    assert i8["grad_scales"] > 0 and bf16["grad_scales"] == 0
    assert round(bf16["grad_sync"] / i8["grad_sync"], 1) >= 2.0
    assert round(f32["grad_sync"] / i8["grad_sync"], 1) >= 4.0
    assert f8["grad_sync"] == i8["grad_sync"]      # both 1-byte wires
    # param gather is never quantized (no error-feedback channel)
    assert i8["param_sync"] == bf16["param_sync"]
