"""bench.py --smoke rides tier-1: every bench section's step fn must
still trace and compile on the CPU mesh, so bench bitrot (an API the
bench calls that a refactor moved, a step that no longer traces) is
caught here instead of on scarce chip time.  The smoke run executes
each section once at a tiny config — ~30-60 s total on this box, most
of it amortized by the persistent compile cache across runs."""

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def test_bench_smoke_all_sections_build():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the bench child must not inherit a test-process TPU tunnel
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke"],
        capture_output=True, text=True, timeout=560, env=env,
    )
    report = None
    for line in reversed((proc.stdout or "").splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "smoke" in rec:
            report = rec
            break
    assert report is not None, (
        f"no smoke JSON on stdout; rc={proc.returncode}\n"
        f"stderr tail: {(proc.stderr or '')[-2000:]}")
    broken = {k: v for k, v in report["sections"].items()
              if not v.get("ok")}
    assert proc.returncode == 0 and not broken, (
        f"bench sections no longer build: {json.dumps(broken, indent=2)}")


def test_elastic_resume_smoke_resharded():
    """The ``elastic_resume`` bench section under a TWO-device host
    platform, isolated via ``--smoke-only``: save at dp=2, restore
    resharded at dp=1 — the section itself asserts the banded loss
    continuation (and the bitwise branch at equal worlds), so ``ok``
    means the reshard path held."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--smoke-only", "elastic_resume"],
        capture_output=True, text=True, timeout=400, env=env,
    )
    report = None
    for line in reversed((proc.stdout or "").splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "smoke" in rec:
            report = rec
            break
    assert report is not None, (
        f"no smoke JSON; rc={proc.returncode}\n"
        f"stderr tail: {(proc.stderr or '')[-2000:]}")
    assert proc.returncode == 0 and \
        report["sections"]["elastic_resume"].get("ok"), report
    assert list(report["sections"]) == ["elastic_resume"]


def test_elastic_resume_supervised_mode_rides_smoke():
    """The section's ``supervised`` sub-mode (the save→kill→restore
    cycle driven by the REAL Supervisor over the trainer CLI) asserts
    internally — rc 0 after exactly one restart — so ``ok`` above
    already covers it; this pins that the mode actually runs by default
    (a refactor that silently drops the sub-call must fail here, not
    ship).  Source-text pin, no import: bench.py is a script with heavy
    module-level imports."""
    src = open(BENCH).read()
    assert "def bench_supervised_elastic" in src
    assert 'out["supervised"] = bench_supervised_elastic()' in src
    assert "supervised=True" in src


def test_zero_wire_bytes_accounting_ratios():
    """The ``zero_gpt124`` section's ``wire_bytes_per_step`` field,
    validated at the accounting level (pure plan arithmetic, no step
    compile) — EXACT ratios, scale-vector bytes included per hop
    (never the old payload approximation): an int8 wire carries
    ``1 + 4/QBLOCK`` bytes per element (payload + its share of the
    fp32 per-block scale psum), so the cut vs the 2-byte bf16 default
    is exactly ``2 / (1 + 4/1024) = 512/257``, and vs a 4-byte fp32
    wire exactly ``1024/257``."""
    from fractions import Fraction

    import jax.numpy as jnp

    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.contrib.optimizers._quantized_sync import QBLOCK

    params = {"w": jnp.zeros((512, 256), jnp.bfloat16),
              "b": jnp.zeros((8192,), jnp.bfloat16)}

    def wire(**kw):
        opt = DistributedFusedAdam(lr=1e-3, **kw)
        opt.init(params, world_size=4)
        return opt.wire_bytes_per_step()

    bf16 = wire()                                  # default: storage dtype
    i8 = wire(grad_sync_dtype="int8")
    f8 = wire(grad_sync_dtype=jnp.float8_e5m2)
    f32 = wire(grad_sync_dtype=jnp.float32)
    assert i8["grad_scales"] > 0 and bf16["grad_scales"] == 0
    # i8 bytes/element = 1 payload + 4/QBLOCK scales — exact, no
    # rounding: bucket totals are QBLOCK multiples by construction
    assert i8["grad_scales"] * QBLOCK == i8["grad_payload"] * 4
    per_elt_i8 = Fraction(QBLOCK + 4, QBLOCK)
    assert Fraction(bf16["grad_sync"], i8["grad_sync"]) \
        == Fraction(2, 1) / per_elt_i8             # = 512/257
    assert Fraction(f32["grad_sync"], i8["grad_sync"]) \
        == Fraction(4, 1) / per_elt_i8             # = 1024/257
    assert f8["grad_sync"] == i8["grad_sync"]      # both 1-byte wires
    # param gather is never quantized (no error-feedback channel)
    assert i8["param_sync"] == bf16["param_sync"]
    # the flat plan reports its one hop under the dp axis, and the
    # top-level fields are exactly that hop
    assert set(i8["hops"]) == {"dp"}
    assert i8["hops"]["dp"]["grad_sync"] == i8["grad_sync"]


def test_hierarchical_wire_bytes_cross_slice_cut_exact():
    """The ``hier_*_sync`` modes' per-hop accounting: the slow (outer)
    hop's bytes — payload AND scales — are exactly ``1/dp_in`` of the
    flat plan's at the same wire dtype, which is the bench's
    ``cross_slice_wire_cut`` headline; the fast (inner) hop carries the
    full bucket like the flat plan."""
    import jax.numpy as jnp

    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    params = {"w": jnp.zeros((512, 256), jnp.bfloat16),
              "b": jnp.zeros((8192,), jnp.bfloat16)}

    def wire(**kw):
        sizes = kw.pop("axis_sizes", None)
        opt = DistributedFusedAdam(lr=1e-3, **kw)
        opt.init(params, world_size=4, axis_sizes=sizes)
        return opt.wire_bytes_per_step()

    flat = wire(grad_sync_dtype="int8")
    hier = wire(grad_sync_dtype="int8", dp_axes=("dp_out", "dp_in"),
                axis_sizes={"dp_out": 2, "dp_in": 2})
    inner, outer = hier["hops"]["dp_in"], hier["hops"]["dp_out"]
    # fast hop == the flat wire (full bucket, same dtype, same scales)
    assert inner["grad_sync"] == flat["grad_sync"]
    assert inner["param_sync"] == flat["param_sync"]
    # slow hop: exactly 1/dp_in of the flat plan, scales included —
    # the cross_slice_wire_cut the bench reports is exactly dp_in
    assert outer["grad_payload"] * 2 == flat["grad_payload"]
    assert outer["grad_scales"] * 2 == flat["grad_scales"]
    assert outer["grad_sync"] * 2 == flat["grad_sync"]
    assert outer["param_sync"] * 2 == flat["param_sync"]
    # top-level fields sum the hops (total wire traffic of the step)
    assert hier["grad_sync"] == inner["grad_sync"] + outer["grad_sync"]
    # both hops stay at the compressed dtype: equal bytes/element
    # implies the slow hop never widened (3/2 = full + half buckets)
    assert hier["grad_payload"] * 2 == flat["grad_payload"] * 3
