"""bench.py --smoke rides tier-1: every bench section's step fn must
still trace and compile on the CPU mesh, so bench bitrot (an API the
bench calls that a refactor moved, a step that no longer traces) is
caught here instead of on scarce chip time.  The smoke run executes
each section once at a tiny config — ~30-60 s total on this box, most
of it amortized by the persistent compile cache across runs."""

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def test_bench_smoke_all_sections_build():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the bench child must not inherit a test-process TPU tunnel
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke"],
        capture_output=True, text=True, timeout=560, env=env,
    )
    report = None
    for line in reversed((proc.stdout or "").splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "smoke" in rec:
            report = rec
            break
    assert report is not None, (
        f"no smoke JSON on stdout; rc={proc.returncode}\n"
        f"stderr tail: {(proc.stderr or '')[-2000:]}")
    broken = {k: v for k, v in report["sections"].items()
              if not v.get("ok")}
    assert proc.returncode == 0 and not broken, (
        f"bench sections no longer build: {json.dumps(broken, indent=2)}")


def test_elastic_resume_smoke_resharded():
    """The ``elastic_resume`` bench section under a TWO-device host
    platform, isolated via ``--smoke-only``: save at dp=2, restore
    resharded at dp=1 — the section itself asserts the banded loss
    continuation (and the bitwise branch at equal worlds), so ``ok``
    means the reshard path held."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--smoke-only", "elastic_resume"],
        capture_output=True, text=True, timeout=400, env=env,
    )
    report = None
    for line in reversed((proc.stdout or "").splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "smoke" in rec:
            report = rec
            break
    assert report is not None, (
        f"no smoke JSON; rc={proc.returncode}\n"
        f"stderr tail: {(proc.stderr or '')[-2000:]}")
    assert proc.returncode == 0 and \
        report["sections"]["elastic_resume"].get("ok"), report
    assert list(report["sections"]) == ["elastic_resume"]


def test_elastic_resume_supervised_mode_rides_smoke():
    """The section's ``supervised`` sub-mode (the save→kill→restore
    cycle driven by the REAL Supervisor over the trainer CLI) asserts
    internally — rc 0 after exactly one restart — so ``ok`` above
    already covers it; this pins that the mode actually runs by default
    (a refactor that silently drops the sub-call must fail here, not
    ship).  Source-text pin, no import: bench.py is a script with heavy
    module-level imports."""
    src = open(BENCH).read()
    assert "def bench_supervised_elastic" in src
    assert 'out["supervised"] = bench_supervised_elastic()' in src
    assert "supervised=True" in src


def test_zero_wire_bytes_accounting_ratios():
    """The ``zero_gpt124`` section's ``wire_bytes_per_step`` field,
    validated at the accounting level (pure plan arithmetic, no step
    compile) — EXACT ratios, scale-vector bytes included per hop
    (never the old payload approximation): an int8 wire carries
    ``1 + 4/QBLOCK`` bytes per element (payload + its share of the
    fp32 per-block scale psum), so the cut vs the 2-byte bf16 default
    is exactly ``2 / (1 + 4/1024) = 512/257``, and vs a 4-byte fp32
    wire exactly ``1024/257``."""
    from fractions import Fraction

    import jax.numpy as jnp

    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.contrib.optimizers._quantized_sync import QBLOCK

    params = {"w": jnp.zeros((512, 256), jnp.bfloat16),
              "b": jnp.zeros((8192,), jnp.bfloat16)}

    def wire(**kw):
        opt = DistributedFusedAdam(lr=1e-3, **kw)
        opt.init(params, world_size=4)
        return opt.wire_bytes_per_step()

    bf16 = wire()                                  # default: storage dtype
    i8 = wire(grad_sync_dtype="int8")
    f8 = wire(grad_sync_dtype=jnp.float8_e5m2)
    f32 = wire(grad_sync_dtype=jnp.float32)
    assert i8["grad_scales"] > 0 and bf16["grad_scales"] == 0
    # i8 bytes/element = 1 payload + 4/QBLOCK scales — exact, no
    # rounding: bucket totals are QBLOCK multiples by construction
    assert i8["grad_scales"] * QBLOCK == i8["grad_payload"] * 4
    per_elt_i8 = Fraction(QBLOCK + 4, QBLOCK)
    assert Fraction(bf16["grad_sync"], i8["grad_sync"]) \
        == Fraction(2, 1) / per_elt_i8             # = 512/257
    assert Fraction(f32["grad_sync"], i8["grad_sync"]) \
        == Fraction(4, 1) / per_elt_i8             # = 1024/257
    assert f8["grad_sync"] == i8["grad_sync"]      # both 1-byte wires
    # param gather is never quantized (no error-feedback channel)
    assert i8["param_sync"] == bf16["param_sync"]
    # the flat plan reports its one hop under the dp axis, and the
    # top-level fields are exactly that hop
    assert set(i8["hops"]) == {"dp"}
    assert i8["hops"]["dp"]["grad_sync"] == i8["grad_sync"]


def test_hierarchical_wire_bytes_cross_slice_cut_exact():
    """The ``hier_*_sync`` modes' per-hop accounting: the slow (outer)
    hop's bytes — payload AND scales — are exactly ``1/dp_in`` of the
    flat plan's at the same wire dtype, which is the bench's
    ``cross_slice_wire_cut`` headline; the fast (inner) hop carries the
    full bucket like the flat plan."""
    import jax.numpy as jnp

    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    params = {"w": jnp.zeros((512, 256), jnp.bfloat16),
              "b": jnp.zeros((8192,), jnp.bfloat16)}

    def wire(**kw):
        sizes = kw.pop("axis_sizes", None)
        opt = DistributedFusedAdam(lr=1e-3, **kw)
        opt.init(params, world_size=4, axis_sizes=sizes)
        return opt.wire_bytes_per_step()

    flat = wire(grad_sync_dtype="int8")
    hier = wire(grad_sync_dtype="int8", dp_axes=("dp_out", "dp_in"),
                axis_sizes={"dp_out": 2, "dp_in": 2})
    inner, outer = hier["hops"]["dp_in"], hier["hops"]["dp_out"]
    # fast hop == the flat wire (full bucket, same dtype, same scales)
    assert inner["grad_sync"] == flat["grad_sync"]
    assert inner["param_sync"] == flat["param_sync"]
    # slow hop: exactly 1/dp_in of the flat plan, scales included —
    # the cross_slice_wire_cut the bench reports is exactly dp_in
    assert outer["grad_payload"] * 2 == flat["grad_payload"]
    assert outer["grad_scales"] * 2 == flat["grad_scales"]
    assert outer["grad_sync"] * 2 == flat["grad_sync"]
    assert outer["param_sync"] * 2 == flat["param_sync"]
    # top-level fields sum the hops (total wire traffic of the step)
    assert hier["grad_sync"] == inner["grad_sync"] + outer["grad_sync"]
    # both hops stay at the compressed dtype: equal bytes/element
    # implies the slow hop never widened (3/2 = full + half buckets)
    assert hier["grad_payload"] * 2 == flat["grad_payload"] * 3


# ------------------------------------------------ bench_compare CI gate
BENCH_COMPARE = os.path.join(os.path.dirname(BENCH), "benchmarks",
                             "bench_compare.py")


def _write_round(path, parsed):
    with open(path, "w") as f:
        json.dump({"n": 1, "rc": 0, "parsed": parsed}, f)


def _run_compare(*argv, cwd=None):
    return subprocess.run(
        [sys.executable, BENCH_COMPARE, *argv],
        capture_output=True, text=True, timeout=60, cwd=cwd)


_OLD_ROUND = {
    "adam": {"speedup_vs_eager": 200.0, "speedup_vs_jitted_optax": 1.2,
             "fused_ms": 2.4},
    "gpt124_s1024": {"tokens_per_sec": 90000.0,
                     "mfu_vs_measured_roofline": 0.66},
    "zero_gpt124": {"hier_int8_sync": {"cross_slice_wire_cut": 4.0,
                                       "tokens_per_sec": 40000.0}},
}


def test_bench_compare_fails_on_headline_regression():
    """>X% drop on a named headline column exits 1 and names it;
    non-headline columns (fused_ms) never participate."""
    import copy
    import tempfile

    new = copy.deepcopy(_OLD_ROUND)
    new["gpt124_s1024"]["tokens_per_sec"] = 70000.0   # -22%
    new["adam"]["fused_ms"] = 99.0                    # not a headline
    with tempfile.TemporaryDirectory() as d:
        old_p, new_p = os.path.join(d, "a.json"), os.path.join(d, "b.json")
        _write_round(old_p, _OLD_ROUND)
        _write_round(new_p, new)
        r = _run_compare(old_p, new_p, "--json")
        assert r.returncode == 1, r.stdout + r.stderr
        report = json.loads(r.stdout)
        assert [x["column"] for x in report["regressions"]] \
            == ["gpt124_s1024.tokens_per_sec"]
        assert report["regressions"][0]["change_pct"] < -20
        # within tolerance at a looser gate
        r = _run_compare(old_p, new_p, "--max-regression-pct", "30")
        assert r.returncode == 0


def test_bench_compare_tolerance_and_missing_columns():
    """Noise inside the tolerance passes; columns missing on either
    side are skipped loudly, never failed."""
    import copy
    import tempfile

    new = copy.deepcopy(_OLD_ROUND)
    new["gpt124_s1024"]["tokens_per_sec"] = 85000.0      # -5.6% noise
    del new["zero_gpt124"]                               # lost section
    new["serve_gpt124"] = {"s8": {"tokens_per_sec": 100.0}}  # new section
    with tempfile.TemporaryDirectory() as d:
        old_p, new_p = os.path.join(d, "a.json"), os.path.join(d, "b.json")
        _write_round(old_p, _OLD_ROUND)
        _write_round(new_p, new)
        r = _run_compare(old_p, new_p, "--json")
        assert r.returncode == 0, r.stdout + r.stderr
        report = json.loads(r.stdout)
        assert not report["regressions"]
        skipped = {x["column"]: x["missing_in"]
                   for x in report["skipped"]}
        assert skipped["zero_gpt124.hier_int8_sync.cross_slice_wire_cut"] \
            == "new"
        assert skipped["serve_gpt124.s8.tokens_per_sec"] == "old"
        oknames = [x["column"] for x in report["ok"]]
        assert "gpt124_s1024.tokens_per_sec" in oknames


def test_bench_compare_newest_pair_and_extra_columns():
    """No-args mode picks the two newest BENCH_r*.json by round
    number; --columns adds extra headline globs."""
    import copy
    import tempfile

    new = copy.deepcopy(_OLD_ROUND)
    new["adam"]["fused_ms"] = 5.0  # 2x slower: only --columns sees it
    with tempfile.TemporaryDirectory() as d:
        _write_round(os.path.join(d, "BENCH_r01.json"), {"adam": {}})
        _write_round(os.path.join(d, "BENCH_r02.json"), _OLD_ROUND)
        _write_round(os.path.join(d, "BENCH_r09.json"), new)
        # the repo-root discovery walks up from benchmarks/: run from a
        # fake layout instead — two files named explicitly
        r = _run_compare(os.path.join(d, "BENCH_r02.json"),
                         os.path.join(d, "BENCH_r09.json"))
        assert r.returncode == 0
        # fused_ms got 2x WORSE but is higher-is-better under the
        # default leaves — --columns opts it in, and the gate reddens
        # (direction stays higher-is-better: a perf column opted in
        # this way should be a rate, but the crafted drop proves the
        # glob matching)
        r = _run_compare(os.path.join(d, "BENCH_r02.json"),
                         os.path.join(d, "BENCH_r09.json"),
                         "--columns", "adam.fused_ms", "--json")
        assert r.returncode == 0  # 2.4 -> 5.0 is an INCREASE
        report = json.loads(r.stdout)
        assert [x["column"] for x in report["improvements"]] \
            == ["adam.fused_ms"]


def test_bench_compare_torn_input_is_a_usage_error():
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        old_p = os.path.join(d, "a.json")
        new_p = os.path.join(d, "b.json")
        _write_round(old_p, _OLD_ROUND)
        with open(new_p, "w") as f:
            f.write('{"parsed": {"adam":')
        r = _run_compare(old_p, new_p)
        assert r.returncode == 2
        assert "bench_compare" in r.stderr


def test_bench_compare_newest_pair_orders_by_round_number():
    """r10 outranks r9 even when r9's mtime is newer (post-checkout
    mtimes lie)."""
    import importlib.util
    import tempfile

    spec = importlib.util.spec_from_file_location("bench_compare",
                                                  BENCH_COMPARE)
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    with tempfile.TemporaryDirectory() as d:
        for name in ("BENCH_r02.json", "BENCH_r10.json", "BENCH_r09.json"):
            _write_round(os.path.join(d, name), {})
        now = os.path.getmtime(os.path.join(d, "BENCH_r10.json"))
        os.utime(os.path.join(d, "BENCH_r09.json"), (now + 60, now + 60))
        pair = bc.newest_pair(d)
        assert [os.path.basename(p) for p in pair] \
            == ["BENCH_r09.json", "BENCH_r10.json"]
        assert bc.newest_pair(tempfile.mkdtemp()) is None


# ------------------------------------------- flash sweep + installer
FLASH_SWEEP = os.path.join(os.path.dirname(BENCH), "benchmarks",
                           "flash_sweep.py")
INSTALL = os.path.join(os.path.dirname(BENCH), "benchmarks",
                       "install_tuned_blocks.py")


def test_flash_sweep_quick_interpret_smoke(tmp_path):
    """``flash_sweep.py --quick --interpret`` is the CPU smoke contract:
    tiny shapes through the Pallas interpreter, one JSON line per
    config, and a final per-(shape, phase) ``tuned_blocks_table`` line
    with BOTH phases that ``set_tuned_blocks`` ingests directly."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, FLASH_SWEEP, "--quick", "--interpret"],
        capture_output=True, text=True, timeout=540, env=env,
    )
    assert proc.returncode == 0, (proc.stderr or "")[-2000:]
    table = None
    for line in (proc.stdout or "").splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "tuned_blocks_table" in rec:
            table = rec["tuned_blocks_table"]
    assert table, "no tuned_blocks_table line on stdout"
    phases = {tuple(key)[3] for key, _ in table}
    assert phases == {"fwd", "bwd"}, table
    # the printed pairs install directly, per phase
    from apex_tpu.ops import flash_attention_pallas as fap

    saved = dict(fap._TUNED_BLOCKS)
    try:
        fap._TUNED_BLOCKS.clear()
        fap.set_tuned_blocks(table)
        for key, val in table:
            s, d, dtype, phase = key
            assert fap.tuned_blocks(s, d, dtype, phase=phase) == tuple(val)
    finally:
        fap._TUNED_BLOCKS.clear()
        fap._TUNED_BLOCKS.update(saved)


def _run_installer(kernel_path, sweep_path, monkeypatch):
    import importlib.util

    spec = importlib.util.spec_from_file_location("install_tuned_blocks",
                                                  INSTALL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from pathlib import Path

    mod.KERNEL = Path(kernel_path)
    monkeypatch.setattr(sys, "argv",
                        ["install_tuned_blocks.py", str(sweep_path),
                         "--provenance", "cpu-test 2026-08-07"])
    mod.main()


def test_install_tuned_blocks_round_trip(tmp_path, monkeypatch):
    """Installer contract: per-phase sweep keys land as 4-tuple entries,
    an old 3-tuple entry already in the source literal migrates to
    ``"fwd"`` (pre-split sweeps measured the forward path), and a
    second run with the same sweep output is BYTE-IDENTICAL
    (idempotent — re-running never churns the kernel source)."""
    import ast
    import re

    kernel = tmp_path / "kernel_stub.py"
    kernel.write_text(
        "# stub kernel module for the installer test\n"
        "_TUNED_BLOCKS: dict = {\n"
        "    (1024, 64, 'bfloat16'): (512, 256),\n"
        "}\n"
        "OTHER = 1\n")
    sweep = tmp_path / "sweep.jsonl"
    sweep.write_text(
        json.dumps({"roofline_tflops": 1.0}) + "\n" + json.dumps(
            {"tuned_blocks_table": [
                [[256, 64, "bfloat16", "fwd"], [128, 128]],
                [[256, 64, "bfloat16", "bwd"], [64, 64]],
                [[512, 64, "bfloat16"], [256, 256]],  # old flat key
            ]}) + "\n")
    _run_installer(kernel, sweep, monkeypatch)
    first = kernel.read_text()
    m = re.search(r"_TUNED_BLOCKS: dict = \{(.*?)\}", first, re.S)
    body = "\n".join(ln for ln in m.group(1).splitlines()
                     if not ln.strip().startswith("#"))
    entries = ast.literal_eval("{" + body + "}")
    assert entries == {
        (256, 64, "bfloat16", "fwd"): (128, 128),
        (256, 64, "bfloat16", "bwd"): (64, 64),
        (512, 64, "bfloat16", "fwd"): (256, 256),
        # the pre-existing flat entry migrated, not dropped
        (1024, 64, "bfloat16", "fwd"): (512, 256),
    }
    assert "OTHER = 1" in first  # the rest of the module is untouched
    # the installed table round-trips through the runtime setter
    from apex_tpu.ops import flash_attention_pallas as fap

    saved = dict(fap._TUNED_BLOCKS)
    try:
        fap._TUNED_BLOCKS.clear()
        fap.set_tuned_blocks(entries)
        import jax.numpy as jnp

        assert fap.tuned_blocks(256, 64, jnp.bfloat16, phase="bwd") == (64, 64)
        assert fap.tuned_blocks(1024, 64, jnp.bfloat16) == (512, 256)
    finally:
        fap._TUNED_BLOCKS.clear()
        fap._TUNED_BLOCKS.update(saved)
    # idempotency: same sweep output -> byte-identical file
    _run_installer(kernel, sweep, monkeypatch)
    assert kernel.read_text() == first


def test_install_tuned_blocks_rejects_bad_phase(tmp_path, monkeypatch):
    kernel = tmp_path / "kernel_stub.py"
    kernel.write_text("_TUNED_BLOCKS: dict = {}\n")
    sweep = tmp_path / "sweep.jsonl"
    sweep.write_text(json.dumps(
        {"tuned_blocks_table": [[[256, 64, "bfloat16", "backward"],
                                 [128, 128]]]}) + "\n")
    import pytest

    with pytest.raises(SystemExit, match="phase"):
        _run_installer(kernel, sweep, monkeypatch)
