"""bench.py --smoke rides tier-1: every bench section's step fn must
still trace and compile on the CPU mesh, so bench bitrot (an API the
bench calls that a refactor moved, a step that no longer traces) is
caught here instead of on scarce chip time.  The smoke run executes
each section once at a tiny config — ~30-60 s total on this box, most
of it amortized by the persistent compile cache across runs."""

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def test_bench_smoke_all_sections_build():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the bench child must not inherit a test-process TPU tunnel
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke"],
        capture_output=True, text=True, timeout=560, env=env,
    )
    report = None
    for line in reversed((proc.stdout or "").splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "smoke" in rec:
            report = rec
            break
    assert report is not None, (
        f"no smoke JSON on stdout; rc={proc.returncode}\n"
        f"stderr tail: {(proc.stderr or '')[-2000:]}")
    broken = {k: v for k, v in report["sections"].items()
              if not v.get("ok")}
    assert proc.returncode == 0 and not broken, (
        f"bench sections no longer build: {json.dumps(broken, indent=2)}")
