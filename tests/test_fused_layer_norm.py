"""LayerNorm/RMSNorm parity — mirrors
tests/L0/run_fused_layer_norm/test_fused_layer_norm.py:21 of the
reference: parity vs framework layer_norm / manual_rms_norm across
shapes, dtypes, affine and memory-efficient flags, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization import (
    FusedLayerNorm,
    FusedRMSNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
    manual_rms_norm,
)

SHAPES = [((4, 16), (16,)), ((2, 3, 32), (32,)), ((5, 4, 6), (4, 6))]


def ref_layer_norm(x, shape, w=None, b=None, eps=1e-5):
    dims = tuple(range(-len(shape), 0))
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=dims, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=dims, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    if w is not None:
        y = y * w
    if b is not None:
        y = y + b
    return y.astype(x.dtype)


@pytest.mark.parametrize("xshape,nshape", SHAPES)
@pytest.mark.parametrize("memory_efficient", [False, True])
class TestFusedLayerNorm:
    def test_forward_affine(self, xshape, nshape, memory_efficient):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(*xshape).astype(np.float32))
        w = jnp.asarray(rng.rand(*nshape).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(*nshape).astype(np.float32))
        out = fused_layer_norm_affine(x, w, b, nshape, 1e-5, memory_efficient)
        ref = ref_layer_norm(x, nshape, w, b)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_backward_affine(self, xshape, nshape, memory_efficient):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(*xshape).astype(np.float32))
        w = jnp.asarray(rng.rand(*nshape).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(*nshape).astype(np.float32))

        def f(x, w, b):
            return jnp.sum(jnp.sin(fused_layer_norm_affine(x, w, b, nshape, 1e-5, memory_efficient)))

        def fref(x, w, b):
            return jnp.sum(jnp.sin(ref_layer_norm(x, nshape, w, b)))

        g = jax.grad(f, argnums=(0, 1, 2))(x, w, b)
        gr = jax.grad(fref, argnums=(0, 1, 2))(x, w, b)
        for a, r in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-4)

    def test_forward_backward_nonaffine(self, xshape, nshape, memory_efficient):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(*xshape).astype(np.float32))
        out = fused_layer_norm(x, nshape, 1e-5, memory_efficient)
        ref = ref_layer_norm(x, nshape)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)
        g = jax.grad(lambda x: jnp.sum(jnp.sin(fused_layer_norm(x, nshape, 1e-5, memory_efficient))))(x)
        gr = jax.grad(lambda x: jnp.sum(jnp.sin(ref_layer_norm(x, nshape))))(x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("xshape,nshape", SHAPES)
@pytest.mark.parametrize("memory_efficient", [False, True])
class TestFusedRMSNorm:
    def test_forward_affine(self, xshape, nshape, memory_efficient):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(*xshape).astype(np.float32))
        w = jnp.asarray(rng.rand(*nshape).astype(np.float32) + 0.5)
        out = fused_rms_norm_affine(x, w, nshape, 1e-5, memory_efficient)
        ref = manual_rms_norm(x, nshape, w, 1e-5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_backward_affine(self, xshape, nshape, memory_efficient):
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(*xshape).astype(np.float32))
        w = jnp.asarray(rng.rand(*nshape).astype(np.float32) + 0.5)

        def f(x, w):
            return jnp.sum(jnp.sin(fused_rms_norm_affine(x, w, nshape, 1e-5, memory_efficient)))

        def fref(x, w):
            return jnp.sum(jnp.sin(manual_rms_norm(x, nshape, w, 1e-5)))

        g = jax.grad(f, argnums=(0, 1))(x, w)
        gr = jax.grad(fref, argnums=(0, 1))(x, w)
        for a, r in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-4)

    def test_nonaffine(self, xshape, nshape, memory_efficient):
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(*xshape).astype(np.float32))
        out = fused_rms_norm(x, nshape, 1e-5, memory_efficient)
        ref = manual_rms_norm(x, nshape, None, 1e-5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


class TestDtypes:
    def test_bf16_input_fp32_params(self):
        # MixedFused semantics: bf16 input, fp32 params, bf16 out
        rng = np.random.RandomState(6)
        x = jnp.asarray(rng.randn(4, 32).astype(np.float32)).astype(jnp.bfloat16)
        w = jnp.ones((32,), jnp.float32)
        b = jnp.zeros((32,), jnp.float32)
        out = fused_layer_norm_affine(x, w, b, (32,), 1e-5)
        assert out.dtype == jnp.bfloat16

    def test_modules(self):
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(4, 32).astype(np.float32))
        m = FusedLayerNorm(normalized_shape=(32,))
        params = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(params, x)
        ref = ref_layer_norm(x, (32,))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)
        m = FusedRMSNorm(normalized_shape=(32,))
        params = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(manual_rms_norm(x, (32,), jnp.ones((32,)), 1e-5)), rtol=1e-5, atol=1e-5
        )
