"""Tensor-parallel tests — mirrors tests/L0/run_transformer
(test_mapping.py, test_layers.py, test_cross_entropy.py) of the
reference: the parallel computation on a device mesh must match a
single-device oracle, forward and backward."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    column_parallel_linear,
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    row_parallel_linear,
    scatter_to_tensor_model_parallel_region,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)

TP = 4


@pytest.fixture
def tp_mesh(devices8):
    return Mesh(np.array(devices8[:TP]), ("tp",))


def smap(mesh, f, in_specs, out_specs):
    # check_vma=False: the custom_vjp collectives hide replication info
    # from the static checker (same pattern as Megatron-style shard_map code)
    return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)


class TestMappings:
    def test_copy_forward_identity_backward_psum(self, tp_mesh):
        x = jnp.arange(8.0)

        def f(x):
            return copy_to_tensor_model_parallel_region(x, "tp")

        out = smap(tp_mesh, f, P(), P())(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

        # backward: grad of sum over all tp ranks = psum(1) = TP
        def loss(x):
            y = copy_to_tensor_model_parallel_region(x, "tp")
            return jnp.sum(y * y)

        g = smap(tp_mesh, jax.grad(loss), P(), P())(x)
        np.testing.assert_allclose(np.asarray(g), TP * 2 * np.asarray(x))

    def test_gather_scatter_roundtrip(self, tp_mesh):
        x = jnp.arange(16.0).reshape(2, 8)  # last dim sharded 8/4=2

        def f(x):
            full = gather_from_tensor_model_parallel_region(x, "tp")
            back = scatter_to_tensor_model_parallel_region(full, "tp")
            return full, back

        full, back = smap(tp_mesh, f, P(None, "tp"), (P(None, None), P(None, "tp")))(x)
        np.testing.assert_allclose(np.asarray(full), np.asarray(x))
        np.testing.assert_allclose(np.asarray(back), np.asarray(x))

    def test_reduce(self, tp_mesh):
        x = jnp.ones((TP, 3))  # one row per rank

        def f(x):
            return reduce_from_tensor_model_parallel_region(x, "tp")

        out = smap(tp_mesh, f, P("tp", None), P(None, None))(x)
        np.testing.assert_allclose(np.asarray(out), np.full((1, 3), TP))

    def test_sequence_gather_backward_is_reduce_scatter(self, tp_mesh):
        # fwd gathers seq; bwd reduce-scatters.  With a *replicated*
        # downstream loss every rank contributes the full gradient, so the
        # reduce-scatter sums TP identical copies — grad = TP * 2x.  (In the
        # real Megatron pattern each rank's branch differs and the sum
        # accumulates partials; see test_column_row_pair_sequence_parallel.)
        x = jnp.arange(8.0).reshape(8, 1)

        def loss(x):
            full = gather_from_sequence_parallel_region(x, "tp")
            return jnp.sum(full ** 2)

        g = smap(tp_mesh, jax.grad(loss), P("tp", None), P("tp", None))(x)
        np.testing.assert_allclose(np.asarray(g), TP * 2 * np.asarray(x))

    def test_reduce_scatter_sequence(self, tp_mesh):
        x = jnp.ones((8, 2))  # every rank contributes same full-seq tensor

        def f(x):
            return reduce_scatter_to_sequence_parallel_region(x, "tp")

        # input replicated over tp; output seq-sharded
        out = smap(tp_mesh, f, P(), P("tp", None))(x)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 2), TP))


class TestParallelLinears:
    def setup_method(self, _):
        rng = np.random.RandomState(0)
        self.x = rng.randn(6, 16).astype(np.float32)
        self.w = rng.randn(24, 16).astype(np.float32)  # (out, in)
        self.b = rng.randn(24).astype(np.float32)

    def test_column_parallel_matches_dense(self, tp_mesh):
        x, w, b = map(jnp.asarray, (self.x, self.w, self.b))

        def f(x, w, b):
            return column_parallel_linear(x, w, b, gather_output=True, axis_name="tp")

        out = smap(tp_mesh, f, (P(), P("tp", None), P("tp")), P())(x, w, b)
        ref = self.x @ self.w.T + self.b
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_column_parallel_grads_match_dense(self, tp_mesh):
        x, w, b = map(jnp.asarray, (self.x, self.w, self.b))

        def loss(x, w, b):
            y = column_parallel_linear(x, w, b, gather_output=True, axis_name="tp")
            return jnp.sum(jnp.sin(y)) / 100.0

        gx, gw, gb = smap(
            tp_mesh,
            jax.grad(loss, argnums=(0, 1, 2)),
            (P(), P("tp", None), P("tp")),
            (P(), P("tp", None), P("tp")),
        )(x, w, b)

        def ref_loss(x, w, b):
            return jnp.sum(jnp.sin(x @ w.T + b)) / 100.0

        rx, rw, rb = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), rtol=1e-5, atol=1e-5)

    def test_row_parallel_matches_dense(self, tp_mesh):
        rng = np.random.RandomState(1)
        x = rng.randn(6, 16).astype(np.float32)
        w = rng.randn(10, 16).astype(np.float32)  # (out, in) — in sharded
        b = rng.randn(10).astype(np.float32)
        xj, wj, bj = map(jnp.asarray, (x, w, b))

        def f(x, w, b):
            return row_parallel_linear(x, w, b, input_is_parallel=True, axis_name="tp")

        out = smap(tp_mesh, f, (P(None, "tp"), P(None, "tp"), P()), P())(xj, wj, bj)
        ref = x @ w.T + b
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_column_row_pair_sequence_parallel(self, tp_mesh):
        # the Megatron block pattern: SP in → column (gather) → row (reduce-scatter) → SP out
        rng = np.random.RandomState(2)
        seq, hid, ffn = 8, 16, 32
        x = rng.randn(seq, hid).astype(np.float32)
        w1 = rng.randn(ffn, hid).astype(np.float32)
        w2 = rng.randn(hid, ffn).astype(np.float32)
        xj, w1j, w2j = map(jnp.asarray, (x, w1, w2))

        def f(x, w1, w2):
            h = column_parallel_linear(
                x, w1, None, gather_output=False, sequence_parallel_enabled=True, axis_name="tp"
            )
            h = jax.nn.gelu(h, approximate=False)
            return row_parallel_linear(
                h, w2, None, input_is_parallel=True, sequence_parallel_enabled=True, axis_name="tp"
            )

        out = smap(
            tp_mesh,
            f,
            (P("tp", None), P("tp", None), P(None, "tp")),
            P("tp", None),
        )(xj, w1j, w2j)
        ref = jax.nn.gelu(xj @ w1j.T, approximate=False) @ w2j.T
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


class TestVocabParallel:
    def test_embedding_matches_dense(self, tp_mesh):
        rng = np.random.RandomState(3)
        vocab, hid = 32, 8
        w = rng.randn(vocab, hid).astype(np.float32)
        ids = rng.randint(0, vocab, size=(4, 6))
        wj, idsj = jnp.asarray(w), jnp.asarray(ids)

        def f(ids, w):
            return vocab_parallel_embedding(ids, w, axis_name="tp")

        out = smap(tp_mesh, f, (P(), P("tp", None)), P())(idsj, wj)
        np.testing.assert_allclose(np.asarray(out), w[ids], rtol=1e-6)

    @pytest.mark.parametrize("smoothing", [0.0])
    def test_cross_entropy_matches_dense(self, tp_mesh, smoothing):
        rng = np.random.RandomState(4)
        batch, vocab = 10, 32
        logits = (rng.randn(batch, vocab) * 3).astype(np.float32)
        target = rng.randint(0, vocab, size=(batch,))
        lj, tj = jnp.asarray(logits), jnp.asarray(target)

        def f(logits, target):
            return vocab_parallel_cross_entropy(logits, target, smoothing, "tp")

        out = smap(tp_mesh, f, (P(None, "tp"), P()), P())(lj, tj)

        # dense oracle
        lse = jax.scipy.special.logsumexp(lj, axis=-1)
        ref = lse - jnp.take_along_axis(lj, tj[:, None], axis=1)[:, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_cross_entropy_grad_matches_dense(self, tp_mesh):
        rng = np.random.RandomState(5)
        batch, vocab = 6, 16
        logits = rng.randn(batch, vocab).astype(np.float32)
        target = rng.randint(0, vocab, size=(batch,))
        lj, tj = jnp.asarray(logits), jnp.asarray(target)

        def loss(logits, target):
            return jnp.mean(vocab_parallel_cross_entropy(logits, target, 0.0, "tp"))

        g = smap(tp_mesh, jax.grad(loss), (P(None, "tp"), P()), P(None, "tp"))(lj, tj)

        def ref_loss(logits):
            return jnp.mean(
                jax.scipy.special.logsumexp(logits, axis=-1)
                - jnp.take_along_axis(logits, tj[:, None], axis=1)[:, 0]
            )

        gr = jax.grad(ref_loss)(lj)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-5, atol=1e-5)


class TestParallelState:
    def test_initialize_and_getters(self, devices8):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2,
            pipeline_model_parallel_size_=2,
            devices=devices8,
        )
        assert parallel_state.model_parallel_is_initialized()
        assert parallel_state.get_tensor_model_parallel_world_size() == 2
        assert parallel_state.get_pipeline_model_parallel_world_size() == 2
        assert parallel_state.get_data_parallel_world_size() == 2
        assert parallel_state.get_context_parallel_world_size() == 1
        mesh = parallel_state.get_mesh()
        assert mesh.axis_names == ("dp", "pp", "cp", "tp")
        parallel_state.destroy_model_parallel()
        assert not parallel_state.model_parallel_is_initialized()

    def test_bad_sizes_raise(self, devices8):
        with pytest.raises(RuntimeError):
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size_=3, devices=devices8
            )

    def test_rank_getters_inside_shard_map(self, devices8):
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=4, devices=devices8
        )

        def f(x):
            r = parallel_state.get_tensor_model_parallel_rank()
            return x + r

        out = jax.shard_map(
            f, mesh=mesh, in_specs=P("tp"), out_specs=P("tp")
        )(jnp.zeros(4))
        np.testing.assert_allclose(np.asarray(out), [0, 1, 2, 3])
        parallel_state.destroy_model_parallel()


class TestTensorParallelAttributes:
    """Spec-tree analog of the reference's param attribute stamping
    (layers.py:70-107) and its consumer (calc_params_l2_norm dedup)."""

    def test_defaults_and_duplicate_rule(self):
        from apex_tpu.transformer.tensor_parallel import (
            TensorParallelAttributes,
            copy_tensor_model_parallel_attributes,
            param_is_not_tensor_parallel_duplicate,
            set_defaults_if_not_set_tensor_model_parallel_attributes,
            set_tensor_model_parallel_attributes,
        )

        d = set_defaults_if_not_set_tensor_model_parallel_attributes(None)
        assert d == TensorParallelAttributes(False, -1, 1)
        s = set_tensor_model_parallel_attributes(True, 0, 1)
        c = copy_tensor_model_parallel_attributes(s)
        assert c == s and c is not s
        # sharded params count on every rank; replicated only on rank 0
        assert param_is_not_tensor_parallel_duplicate(s, tp_rank=3)
        assert param_is_not_tensor_parallel_duplicate(None, tp_rank=0)
        assert not param_is_not_tensor_parallel_duplicate(None, tp_rank=1)

    def test_attributes_tree_and_l2norm_dedup(self):
        from apex_tpu.transformer.pipeline_parallel.utils import calc_params_l2_norm
        from apex_tpu.transformer.tensor_parallel import attributes_tree

        params = {"wq": jnp.full((4,), 2.0), "ln": jnp.full((9,), 2.0)}
        attrs = attributes_tree(
            params, lambda path, leaf: (0, 1) if "wq" in str(path) else None)
        assert attrs["wq"].tensor_model_parallel and not attrs["ln"].tensor_model_parallel

        # rank 0 counts both; rank 1 counts only the sharded leaf
        n0 = float(calc_params_l2_norm(params, attrs=attrs, tp_rank=0))
        n1 = float(calc_params_l2_norm(params, attrs=attrs, tp_rank=1))
        np.testing.assert_allclose(n0, np.sqrt(4 * 4 + 9 * 4), rtol=1e-6)
        np.testing.assert_allclose(n1, np.sqrt(4 * 4), rtol=1e-6)

    def test_l2norm_axis_name_psum(self, devices8):
        """With axis_name, per-rank sharded views psum norm² over the
        group (reference utils.py:234-238 all-reduces across mp)."""
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        from apex_tpu.transformer.pipeline_parallel.utils import calc_params_l2_norm

        mesh = Mesh(np.array(devices8[:4]), ("tp",))
        w = jnp.arange(16.0, dtype=jnp.float32)

        def f(w_shard):
            return calc_params_l2_norm({"w": w_shard}, axis_name="tp")

        norm = shard_map(f, mesh=mesh, in_specs=P("tp"),
                         out_specs=P())(w)
        np.testing.assert_allclose(
            float(norm), np.linalg.norm(np.arange(16.0)), rtol=1e-6)

    def test_l2norm_axis_name_with_attrs_counts_replicated_once(self, devices8):
        """attrs × axis_name: replicated leaves count once across the
        group (traced axis_index-0 weighting), sharded leaves from every
        rank — matching reference utils.py:217-238 filter-then-allreduce."""
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        from apex_tpu.transformer.pipeline_parallel.utils import calc_params_l2_norm
        from apex_tpu.transformer.tensor_parallel import attributes_tree

        mesh = Mesh(np.array(devices8[:4]), ("tp",))
        sharded = jnp.arange(16.0, dtype=jnp.float32)   # split over tp
        replicated = jnp.full((3,), 2.0)                # same on every rank
        attrs = attributes_tree(
            {"s": sharded, "r": replicated},
            lambda path, leaf: (0, 1) if "'s'" in str(path) else None)

        def f(s_shard, r):
            return calc_params_l2_norm({"s": s_shard, "r": r},
                                       attrs=attrs, axis_name="tp")

        norm = shard_map(f, mesh=mesh, in_specs=(P("tp"), P()),
                         out_specs=P())(sharded, replicated)
        expect = np.sqrt(np.sum(np.arange(16.0) ** 2) + 3 * 4.0)
        np.testing.assert_allclose(float(norm), expect, rtol=1e-6)

    def test_l2norm_tp_dedup_keeps_pp_distinct_leaves(self, devices8):
        """tp-replicated but pp-stage-sharded params (per-layer LN
        scales) are distinct per pp rank: the dedup weighting applies to
        the tp axis only, so every pp rank's slice counts (the reference
        filters TP duplicates then all-reduces over the full mp group)."""
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        from apex_tpu.transformer.pipeline_parallel.utils import calc_params_l2_norm
        from apex_tpu.transformer.tensor_parallel import attributes_tree

        mesh = Mesh(np.array(devices8[:4]).reshape(2, 2), ("tp", "pp"))
        # the flagship layout: layer-stacked params shard over pp on the
        # leading axis; weights additionally shard over tp, LN params
        # are tp-replicated
        wq = jnp.arange(16.0, dtype=jnp.float32).reshape(4, 4)  # P(pp, tp)
        ln = jnp.arange(8.0, dtype=jnp.float32)                 # P(pp)
        attrs = attributes_tree(
            {"wq": wq, "ln": ln},
            lambda path, leaf: (1, 1) if "'wq'" in str(path) else None)

        def f(wq_shard, ln_shard):
            return calc_params_l2_norm(
                {"wq": wq_shard, "ln": ln_shard}, attrs=attrs,
                axis_name=("tp", "pp"), tp_axis_name="tp")

        norm = shard_map(f, mesh=mesh, in_specs=(P("pp", "tp"), P("pp")),
                         out_specs=P())(wq, ln)
        # wq: every (pp, tp) rank owns a distinct slice -> sumsq once;
        # ln: distinct per pp rank, tp-replicated -> counted on tp rank
        # 0 of EACH pp rank -> sumsq once.  A dedup over both axes would
        # have dropped pp rank 1's ln slice.
        expect = np.sqrt(np.sum(np.arange(16.0) ** 2) + np.sum(np.arange(8.0) ** 2))
        np.testing.assert_allclose(float(norm), expect, rtol=1e-6)
