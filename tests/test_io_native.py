"""Native runtime + checkpoint I/O tests (the apex_C flatten/unflatten
parity of reference tests, host-side)."""

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.io import PrefetchIterator, load_checkpoint, native, save_checkpoint


class TestNativeLib:
    def test_builds_and_reports_abi(self):
        assert native.available(), "g++ build of the native library failed"

    def test_flatten_unflatten_roundtrip(self):
        rng = np.random.RandomState(0)
        arrays = [
            rng.randn(13, 7).astype(np.float32),
            rng.randn(5).astype(np.float64),
            rng.randint(0, 100, size=(3, 2)).astype(np.int32),
            rng.randn(2, 2).astype(np.float16),
        ]
        blob = native.flatten(arrays)
        assert blob.nbytes == sum(a.nbytes for a in arrays)
        back = native.unflatten(blob, [a.shape for a in arrays], [a.dtype for a in arrays])
        for a, b in zip(arrays, back):
            np.testing.assert_array_equal(a, b)

    def test_flatten_matches_numpy_fallback(self):
        rng = np.random.RandomState(1)
        arrays = [rng.randn(11).astype(np.float32) for _ in range(5)]
        blob = native.flatten(arrays)
        ref = np.concatenate([a.view(np.uint8) for a in arrays])
        np.testing.assert_array_equal(blob, ref)

    def test_gather_rows(self):
        rng = np.random.RandomState(2)
        src = rng.randn(20, 6).astype(np.float32)
        idx = np.array([3, 3, 0, 19, 7])
        out = native.gather_rows(src, idx)
        np.testing.assert_array_equal(out, src[idx])


class TestCheckpoint:
    def test_roundtrip_pytree(self, tmp_path):
        import jax.numpy as jnp

        tree = {
            "params": {"w": jnp.ones((4, 3)), "b": jnp.zeros((3,))},
            "step": jnp.int32(7),
            "nested": [jnp.arange(5.0), jnp.asarray([True, False])],
        }
        p = tmp_path / "ck.apex"
        save_checkpoint(p, tree)
        back = load_checkpoint(p)
        assert back["params"]["w"].shape == (4, 3)
        np.testing.assert_array_equal(np.asarray(back["step"]), 7)
        np.testing.assert_array_equal(back["nested"][0], np.arange(5.0))

    def test_bad_magic_rejected(self, tmp_path):
        p = tmp_path / "bad.bin"
        p.write_bytes(b"NOTAPEX!xxxx")
        with pytest.raises(ValueError):
            load_checkpoint(p)


class TestPrefetch:
    def test_yields_all_in_order(self):
        out = list(PrefetchIterator(iter(range(10)), size=3))
        assert out == list(range(10))

    def test_transform_applied(self):
        out = list(PrefetchIterator(iter([1, 2, 3]), transform=lambda x: x * 2))
        assert out == [2, 4, 6]

    def test_error_propagates(self):
        def gen():
            yield 1
            raise RuntimeError("boom")

        it = PrefetchIterator(gen())
        assert next(it) == 1
        with pytest.raises(RuntimeError):
            for _ in it:
                pass


class TestShardedCheckpoint:
    def test_round_trip(self, tmp_path):
        from apex_tpu.io import load_sharded_checkpoint, save_sharded_checkpoint

        d = tmp_path / "ck"
        trees = [{"rank": np.full((3,), float(r)), "x": np.arange(r + 1)} for r in range(4)]
        for r, t in enumerate(trees):
            save_sharded_checkpoint(d, t, r, 4)
        back = load_sharded_checkpoint(d)
        assert len(back) == 4
        for r in range(4):
            np.testing.assert_array_equal(back[r]["rank"], trees[r]["rank"])
        one = load_sharded_checkpoint(d, rank=2)
        np.testing.assert_array_equal(one["x"], trees[2]["x"])

    def test_missing_shard_rejected(self, tmp_path):
        from apex_tpu.io import load_sharded_checkpoint, save_sharded_checkpoint

        d = tmp_path / "ck"
        save_sharded_checkpoint(d, {"a": np.ones(2)}, 0, 3)
        with pytest.raises(FileNotFoundError, match="missing shard"):
            load_sharded_checkpoint(d)

    def test_sync_shard_write_is_atomic(self, tmp_path, monkeypatch):
        """A crash mid-save must leave NEITHER a truncated shard under
        the final name NOR a stray .tmp (the sync path shares the async
        path's tmp+fsync+rename publish)."""
        from apex_tpu.io import checkpoint as ck
        from apex_tpu.io import save_sharded_checkpoint

        d = tmp_path / "ck"

        def boom(path, tree):
            with open(path, "wb") as f:
                f.write(b"partial")  # bytes hit the tmp file...
            raise OSError("disk died mid-write")

        monkeypatch.setattr(ck, "save_checkpoint", boom)
        with pytest.raises(OSError, match="disk died"):
            save_sharded_checkpoint(d, {"a": np.ones(2)}, 1, 2)
        assert not (d / "shard_00001-of-00002.ckpt").exists()
        assert not list(d.glob("*.tmp"))
        monkeypatch.undo()
        # a retry after the crash succeeds cleanly
        save_sharded_checkpoint(d, {"a": np.ones(2)}, 1, 2)
        assert (d / "shard_00001-of-00002.ckpt").exists()

    def test_lazy_open_reads_only_requested_leaves(self, tmp_path):
        """open_checkpoint_lazy: header now, bytes on demand — and the
        bytes that do come back are the right ones, leaf by leaf."""
        from apex_tpu.io import save_checkpoint
        from apex_tpu.io.checkpoint import _LazyLeaf, open_checkpoint_lazy

        rng = np.random.RandomState(0)
        tree = {
            "big": rng.randn(64, 8).astype(np.float32),
            "small": np.arange(5, dtype=np.int64),
            "bf16": np.asarray(jnp.arange(6.0, dtype=jnp.bfloat16)),
        }
        p = tmp_path / "lazy.ckpt"
        save_checkpoint(p, tree)
        lazy = open_checkpoint_lazy(p)
        assert all(isinstance(v, _LazyLeaf) for v in lazy.values())
        # PROOF of laziness: zero out "big"'s byte region on disk AFTER
        # the open — an eager reader would have snapshotted the original
        # bytes; the lazy one must see the overwrite, and only for the
        # overwritten leaf
        big = lazy["big"]
        with open(p, "r+b") as f:
            f.seek(big.offset)
            f.write(b"\0" * tree["big"].nbytes)
        np.testing.assert_array_equal(np.asarray(lazy["small"]), tree["small"])
        np.testing.assert_array_equal(
            np.asarray(lazy["big"]), np.zeros_like(tree["big"]))
        np.testing.assert_array_equal(
            np.asarray(lazy["bf16"]).astype(np.float32),
            np.asarray(tree["bf16"]).astype(np.float32))

    def test_distributed_load_never_reads_whole_shard_files(
            self, tmp_path, devices8, monkeypatch):
        """The mesh-aware restore must go through the lazy reader (the
        pod-scale OOM fix): the eager full-file loader must never run,
        and the bytes that do come back must reassemble correctly."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from apex_tpu.io import (
            load_distributed_checkpoint, save_distributed_checkpoint,
        )
        from apex_tpu.io import checkpoint as ck

        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        x = jax.device_put(
            jnp.arange(16.0), NamedSharding(mesh, P("dp")))
        d = tmp_path / "dist"
        save_distributed_checkpoint(d, {"x": x})

        def no_eager(path):
            raise AssertionError(f"eager full-file read of {path}")

        monkeypatch.setattr(ck, "load_checkpoint", no_eager)
        out = load_distributed_checkpoint(
            d, {"x": x}, mesh=mesh, spec_tree={"x": P("dp")})
        np.testing.assert_array_equal(np.asarray(out["x"]), np.arange(16.0))

    @pytest.mark.slow
    def test_zero2_resharding_through_files(self, tmp_path, devices8):
        """End-to-end: ZeRO shard dicts through the sharded-file
        protocol, reloaded at a different dp world."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.io import load_sharded_checkpoint, save_sharded_checkpoint

        params = {"w": jnp.asarray(np.random.RandomState(0).randn(10, 3).astype(np.float32))}
        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = opt.init(params, world_size=4)
        sspec = opt.state_partition_spec()
        g = jax.tree.map(jnp.ones_like, params)
        params2, state = jax.shard_map(
            lambda p, s, gg: opt.update(gg, s, p),
            mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec),
            check_vma=False,
        )(params, state, g)

        d = tmp_path / "zero"
        for r in range(4):
            save_sharded_checkpoint(d, opt.sharded_state_dict(state, r, 4), r, 4)
        shards = load_sharded_checkpoint(d)
        state2 = DistributedFusedAdam.load_sharded_state_dicts(shards, world_size=2)
        assert int(state2.step) == 1
        np.testing.assert_allclose(
            np.asarray(state2.exp_avg[:30]), np.asarray(state.exp_avg[:30]), rtol=1e-7
        )


class TestAsyncCheckpointer:
    """Non-blocking save: snapshot-at-call-time semantics, ordered
    writes, atomic publish, error propagation."""

    def test_snapshot_semantics_and_roundtrip(self, tmp_path):
        from apex_tpu.io import AsyncCheckpointer, load_checkpoint

        p = str(tmp_path / "a.apex")
        tree = {"w": jnp.arange(4.0), "step": jnp.int32(7)}
        with AsyncCheckpointer() as ckpt:
            ckpt.save(p, tree)
            # mutate AFTER save returns: the file must hold the old values
            tree = {"w": tree["w"] * 100, "step": jnp.int32(8)}
        out = load_checkpoint(p)
        np.testing.assert_array_equal(out["w"], np.arange(4.0))
        assert int(out["step"]) == 7

    def test_many_saves_all_land_in_order(self, tmp_path):
        from apex_tpu.io import AsyncCheckpointer, load_checkpoint

        ckpt = AsyncCheckpointer()
        for i in range(5):
            ckpt.save(str(tmp_path / f"s{i}.apex"), {"i": jnp.int32(i)})
        ckpt.wait_until_finished()
        for i in range(5):
            assert int(load_checkpoint(str(tmp_path / f"s{i}.apex"))["i"]) == i
        # no stray .tmp files (atomic publish)
        assert not list(tmp_path.glob("*.tmp"))

    def test_write_error_reraises(self, tmp_path):
        from apex_tpu.io import AsyncCheckpointer

        ckpt = AsyncCheckpointer()
        bad = str(tmp_path / "no" / "\0bad")  # NUL in path: open() raises
        ckpt.save(bad, {"x": jnp.zeros(1)})
        with pytest.raises(RuntimeError, match="async checkpoint"):
            ckpt.wait_until_finished()
        # checkpointer stays usable after the failure
        ok = str(tmp_path / "ok.apex")
        ckpt.save(ok, {"x": jnp.ones(1)})
        ckpt.wait_until_finished()
        ckpt.close()
        with pytest.raises(RuntimeError, match="closed"):
            ckpt.save(ok, {"x": jnp.ones(1)})

    def test_numpy_leaves_are_copied(self, tmp_path):
        """np.ndarray leaves must be deep-copied at save() time: the
        caller may mutate them in place while the write is queued."""
        from apex_tpu.io import AsyncCheckpointer, load_checkpoint

        arr = np.arange(4.0)
        p = str(tmp_path / "c.apex")
        with AsyncCheckpointer() as ckpt:
            ckpt.save(p, {"w": arr})
            arr *= 100  # in-place mutation after save returned
        np.testing.assert_array_equal(load_checkpoint(p)["w"], np.arange(4.0))

    def test_close_joins_worker(self, tmp_path):
        import threading

        from apex_tpu.io import AsyncCheckpointer

        before = threading.active_count()
        ckpt = AsyncCheckpointer()
        ckpt.save(str(tmp_path / "d.apex"), {"x": jnp.ones(2)})
        ckpt.close()
        assert threading.active_count() == before

    def test_save_distributed_snapshot_and_roundtrip(self, tmp_path, devices8):
        """Async multi-host save: shards snapshot at call time (donation
        safe), the per-process file lands atomically, and the mesh-aware
        load reassembles the saved values."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from apex_tpu.io import AsyncCheckpointer, load_distributed_checkpoint

        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        sh = NamedSharding(mesh, P("dp"))
        x = jax.device_put(jnp.arange(16.0), sh)
        d = tmp_path / "dist"
        with AsyncCheckpointer() as ckpt:
            ckpt.save_distributed(d, {"x": x, "step": jnp.int32(3)})
            # mutate after save returns: the file must hold the old values
            x = jax.device_put(x * 100, sh)
        out = load_distributed_checkpoint(
            d, {"x": x, "step": jnp.int32(0)}, mesh=mesh,
            spec_tree={"x": P("dp"), "step": P()})
        np.testing.assert_array_equal(np.asarray(out["x"]), np.arange(16.0))
        assert int(out["step"]) == 3
        assert not list(d.glob("*.tmp"))

    def test_distributed_payload_copy_does_not_alias_device_buffers(self, devices8):
        """The async snapshot guarantee hinges on copy=True producing
        REAL copies: on the CPU backend np.asarray of a shard is a
        zero-copy view, so a donated buffer would corrupt a queued
        write.  Pin it with shares_memory (the behavior-level 'mutate
        after save' test can't catch a regression — JAX arrays are
        immutable, so rebinding keeps the old buffer alive either way)."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from apex_tpu.io.checkpoint import _distributed_payload

        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        x = jax.device_put(jnp.arange(16.0), NamedSharding(mesh, P("dp")))
        raw_views = [np.asarray(s.data) for s in x.addressable_shards]
        payload, _, _ = _distributed_payload({"x": x}, copy=True)
        for piece in payload["['x']"]:
            assert not any(np.shares_memory(piece["data"], rv) for rv in raw_views)
        # sanity: the zero-copy premise holds (the view path DOES alias),
        # so the assertion above is actually discriminating
        view_payload, _, _ = _distributed_payload({"x": x}, copy=False)
        aliases = [
            np.shares_memory(piece["data"], rv)
            for piece in view_payload["['x']"] for rv in raw_views
        ]
        assert any(aliases)
