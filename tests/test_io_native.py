"""Native runtime + checkpoint I/O tests (the apex_C flatten/unflatten
parity of reference tests, host-side)."""

from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.io import (
    PrefetchIterator,
    checkpoint_step,
    latest_checkpoint,
    load_checkpoint,
    native,
    save_checkpoint,
    validate_checkpoint,
)


class TestNativeLib:
    def test_builds_and_reports_abi(self):
        assert native.available(), "g++ build of the native library failed"

    def test_flatten_unflatten_roundtrip(self):
        rng = np.random.RandomState(0)
        arrays = [
            rng.randn(13, 7).astype(np.float32),
            rng.randn(5).astype(np.float64),
            rng.randint(0, 100, size=(3, 2)).astype(np.int32),
            rng.randn(2, 2).astype(np.float16),
        ]
        blob = native.flatten(arrays)
        assert blob.nbytes == sum(a.nbytes for a in arrays)
        back = native.unflatten(blob, [a.shape for a in arrays], [a.dtype for a in arrays])
        for a, b in zip(arrays, back):
            np.testing.assert_array_equal(a, b)

    def test_flatten_matches_numpy_fallback(self):
        rng = np.random.RandomState(1)
        arrays = [rng.randn(11).astype(np.float32) for _ in range(5)]
        blob = native.flatten(arrays)
        ref = np.concatenate([a.view(np.uint8) for a in arrays])
        np.testing.assert_array_equal(blob, ref)

    def test_gather_rows(self):
        rng = np.random.RandomState(2)
        src = rng.randn(20, 6).astype(np.float32)
        idx = np.array([3, 3, 0, 19, 7])
        out = native.gather_rows(src, idx)
        np.testing.assert_array_equal(out, src[idx])


class TestCheckpoint:
    def test_roundtrip_pytree(self, tmp_path):
        import jax.numpy as jnp

        tree = {
            "params": {"w": jnp.ones((4, 3)), "b": jnp.zeros((3,))},
            "step": jnp.int32(7),
            "nested": [jnp.arange(5.0), jnp.asarray([True, False])],
        }
        p = tmp_path / "ck.apex"
        save_checkpoint(p, tree)
        back = load_checkpoint(p)
        assert back["params"]["w"].shape == (4, 3)
        np.testing.assert_array_equal(np.asarray(back["step"]), 7)
        np.testing.assert_array_equal(back["nested"][0], np.arange(5.0))

    def test_bad_magic_rejected(self, tmp_path):
        p = tmp_path / "bad.bin"
        p.write_bytes(b"NOTAPEX!xxxx")
        with pytest.raises(ValueError):
            load_checkpoint(p)


class TestTornWriteRecovery:
    """Preemption-safe resume (apex_tpu.resilience): a writer killed
    mid-save — the exact fault a TPU reclaim produces — must cost one
    save interval, never the run.  ``latest_checkpoint`` skips torn
    files with a warning and fails LOUDLY when nothing valid remains
    (training from scratch while claiming to resume is the worst
    outcome)."""

    TREE = {"w": jnp.arange(12.0).reshape(3, 4), "step": jnp.int32(3)}

    def _save(self, path):
        save_checkpoint(path, self.TREE)
        return path

    def test_validate_accepts_good_and_reports_header(self, tmp_path):
        p = self._save(tmp_path / "step_00000003.ckpt")
        header = validate_checkpoint(p)
        assert [m["shape"] for m in header["leaves"]] == [[], [3, 4]]

    def test_truncated_blob_rejected(self, tmp_path):
        """The torn-write shape a dying writer actually produces: the
        header promises N blob bytes, the file holds fewer."""
        p = self._save(tmp_path / "step_00000003.ckpt")
        p.write_bytes(p.read_bytes()[:-7])
        with pytest.raises(ValueError, match="torn"):
            validate_checkpoint(p)

    def test_truncated_preamble_rejected(self, tmp_path):
        """Killed even earlier: mid-header.  Must be a clean rejection,
        not a struct/pickle traceback."""
        p = self._save(tmp_path / "step_00000003.ckpt")
        p.write_bytes(p.read_bytes()[:20])
        with pytest.raises(ValueError, match="torn or corrupt"):
            validate_checkpoint(p)

    def test_corrupt_header_json_wrapped_with_path(self, tmp_path):
        """Corruption in the JSON header region: json.JSONDecodeError is
        a ValueError subclass, but it must not escape context-free — the
        rejection names the file and the 'torn or corrupt' marker."""
        import json as _json

        p = self._save(tmp_path / "step_00000003.ckpt")
        raw = bytearray(p.read_bytes())
        # first header byte is '{'; flip it so the JSON no longer parses
        start = 8 + 16  # magic + (hlen, tlen)
        assert raw[start:start + 1] == b"{"
        raw[start] = ord("X")
        p.write_bytes(bytes(raw))
        with pytest.raises(ValueError, match="torn or corrupt") as ei:
            validate_checkpoint(p)
        assert p.name in str(ei.value)
        assert not isinstance(ei.value, _json.JSONDecodeError)

    def test_corrupt_header_metadata_rejected_and_skipped(self, tmp_path):
        """Corruption INSIDE a parseable header — a bit-flipped dtype
        string — is just as torn as a short preamble: validate raises
        ValueError (not an AttributeError from dtype resolution) and
        latest_checkpoint skips to the older survivor instead of
        crashing the resume."""
        self._save(tmp_path / "step_00000004.ckpt")
        newest = self._save(tmp_path / "step_00000008.ckpt")
        raw = newest.read_bytes()
        assert b"<f4" in raw  # same-length garbage keeps offsets valid
        newest.write_bytes(raw.replace(b"<f4", b"xxx", 1))
        with pytest.raises(ValueError, match="torn or corrupt"):
            validate_checkpoint(newest)
        assert latest_checkpoint(tmp_path).endswith("step_00000004.ckpt")

    def test_checkpoint_step_parses_names(self):
        assert checkpoint_step("/ck/step_00000042.ckpt") == 42
        assert checkpoint_step("/ck/latest.ckpt") == -1

    def test_latest_skips_torn_file_to_previous_step(self, tmp_path):
        self._save(tmp_path / "step_00000004.ckpt")
        newest = self._save(tmp_path / "step_00000008.ckpt")
        newest.write_bytes(newest.read_bytes()[:-5])  # torn newest
        got = latest_checkpoint(tmp_path)
        assert got.endswith("step_00000004.ckpt")
        # and the survivor actually loads
        back = load_checkpoint(got)
        np.testing.assert_array_equal(back["w"], np.arange(12.0).reshape(3, 4))

    def test_latest_ignores_tmp_leftovers(self, tmp_path):
        """A ``.tmp`` the atomic publish never renamed is not a
        candidate at all — even a VALID one (it was never published)."""
        self._save(tmp_path / "step_00000004.ckpt")
        self._save(tmp_path / "step_00000009.ckpt.tmp")
        (tmp_path / "step_00000010.ckpt.tmp").write_bytes(b"garbage")
        assert latest_checkpoint(tmp_path).endswith("step_00000004.ckpt")

    def test_latest_orders_by_step_number_not_mtime(self, tmp_path):
        import os

        self._save(tmp_path / "step_00000010.ckpt")
        older = self._save(tmp_path / "step_00000009.ckpt")
        os.utime(older, (2_000_000_000, 2_000_000_000))  # newest mtime
        assert latest_checkpoint(tmp_path).endswith("step_00000010.ckpt")

    def test_empty_dir_fails_loudly(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="empty or not a"):
            latest_checkpoint(tmp_path)

    def test_missing_dir_fails_loudly(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="does not exist"):
            latest_checkpoint(tmp_path / "nope")

    def test_all_torn_fails_loudly_with_reasons(self, tmp_path):
        """All-torn raises the DISTINCT AllCheckpointsTornError subclass:
        prior progress existed, so an auto-resuming caller must not
        treat this like an empty first-launch directory."""
        from apex_tpu.io import AllCheckpointsTornError

        p = self._save(tmp_path / "step_00000004.ckpt")
        p.write_bytes(p.read_bytes()[:-5])
        (tmp_path / "step_00000008.ckpt").write_bytes(b"NOTAPEX!xxxx")
        with pytest.raises(AllCheckpointsTornError,
                           match="torn/corrupt") as ei:
            latest_checkpoint(tmp_path)
        assert "step_00000004" in str(ei.value)
        assert "step_00000008" in str(ei.value)
        # empty dir is the PLAIN FileNotFoundError, never the subclass
        empty = tmp_path / "fresh"
        empty.mkdir()
        with pytest.raises(FileNotFoundError) as ei2:
            latest_checkpoint(empty)
        assert not isinstance(ei2.value, AllCheckpointsTornError)

    def test_candidate_pruned_during_sort_is_tolerated(self, tmp_path):
        """A file unlinked between iterdir() and the sort key's stat()
        (a concurrent run pruning a shared dir) must not crash
        discovery — the survivor is still found."""
        import unittest.mock as mock

        from apex_tpu.io import checkpoint as ckpt_mod

        keep = self._save(tmp_path / "step_00000004.ckpt")
        gone = self._save(tmp_path / "step_00000002.ckpt")
        real_step = ckpt_mod.checkpoint_step

        def racing_step(p):
            # fires inside the sort key, AFTER iterdir listed the file
            # and BEFORE its mtime stat
            if Path(p).name == gone.name and gone.exists():
                gone.unlink()
            return real_step(p)

        with mock.patch.object(ckpt_mod, "checkpoint_step", racing_step):
            got = latest_checkpoint(tmp_path)
        assert got.endswith(keep.name)


class TestDistributedStepDiscovery:
    """latest_distributed_step: the pod-scale restart side.  Per-step
    dirs mean an interrupted save can only leave an INCOMPLETE newest
    dir; discovery skips those, distinguishes 'nothing saved yet' from
    'everything is torn', and never lets an auto-resuming pod silently
    restart from step 0 over real progress."""

    def _publish(self, d, step, world=2, shards=None):
        import json

        from apex_tpu.io.checkpoint import _shard_name

        sd = d / f"step_{step:08d}"
        sd.mkdir(parents=True)
        (sd / "index.json").write_text(json.dumps({"world_size": world}))
        for i in range(world if shards is None else shards):
            (sd / _shard_name(i, world)).write_bytes(b"x")
        return sd

    def test_newest_complete_dir_wins(self, tmp_path):
        from apex_tpu.io import latest_distributed_step

        self._publish(tmp_path, 4)
        self._publish(tmp_path, 8)
        self._publish(tmp_path, 12, shards=1)  # interrupted newest
        assert latest_distributed_step(tmp_path) == 8

    def test_no_dirs_is_fresh_start(self, tmp_path):
        from apex_tpu.io import latest_distributed_step

        assert latest_distributed_step(tmp_path) == -1
        assert latest_distributed_step(tmp_path / "nope") == -1

    def test_all_incomplete_fails_loudly(self, tmp_path):
        from apex_tpu.io import (AllCheckpointsTornError,
                                 latest_distributed_step)

        self._publish(tmp_path, 4, shards=0)   # no shards yet
        sd = self._publish(tmp_path, 8, shards=1)
        (sd / "index.json").write_text("{garbage")  # unparseable index
        with pytest.raises(AllCheckpointsTornError,
                           match="none is fully published"):
            latest_distributed_step(tmp_path)

    def test_indexed_dir_with_deleted_shard_skipped(self, tmp_path):
        """The crash-between-index-and-shards window (rank 0 publishes
        index.json FIRST): an indexed dir missing any rank's shard is
        torn and must be skipped, not resumed with missing ranks."""
        from apex_tpu.io import latest_distributed_step
        from apex_tpu.io.checkpoint import _shard_name

        self._publish(tmp_path, 4)
        sd = self._publish(tmp_path, 8)
        (sd / _shard_name(1, 2)).unlink()       # rank 1's shard gone
        assert latest_distributed_step(tmp_path) == 4

    def test_stale_other_world_shards_do_not_fake_completeness(
            self, tmp_path):
        """Elastic restarts can re-save one step number at a DIFFERENT
        world size into the same dir: stale shard files from the old
        world must not satisfy the new index by mere COUNT — every
        rank's exactly-named shard is required."""
        from apex_tpu.io import (AllCheckpointsTornError,
                                 latest_distributed_step)
        from apex_tpu.io.checkpoint import _shard_name

        sd = self._publish(tmp_path, 8, world=2, shards=1)  # rank 1 missing
        # leftovers of an interrupted dp=4 save of the same step: three
        # more shard files — five total, >= world_size 2
        for r in range(3):
            (sd / _shard_name(r, 4)).write_bytes(b"stale")
        with pytest.raises(AllCheckpointsTornError):
            latest_distributed_step(tmp_path)


class TestPrefetch:
    def test_yields_all_in_order(self):
        out = list(PrefetchIterator(iter(range(10)), size=3))
        assert out == list(range(10))

    def test_transform_applied(self):
        out = list(PrefetchIterator(iter([1, 2, 3]), transform=lambda x: x * 2))
        assert out == [2, 4, 6]

    def test_error_propagates(self):
        def gen():
            yield 1
            raise RuntimeError("boom")

        it = PrefetchIterator(gen())
        assert next(it) == 1
        with pytest.raises(RuntimeError):
            for _ in it:
                pass


class TestShardedCheckpoint:
    def test_round_trip(self, tmp_path):
        from apex_tpu.io import load_sharded_checkpoint, save_sharded_checkpoint

        d = tmp_path / "ck"
        trees = [{"rank": np.full((3,), float(r)), "x": np.arange(r + 1)} for r in range(4)]
        for r, t in enumerate(trees):
            save_sharded_checkpoint(d, t, r, 4)
        back = load_sharded_checkpoint(d)
        assert len(back) == 4
        for r in range(4):
            np.testing.assert_array_equal(back[r]["rank"], trees[r]["rank"])
        one = load_sharded_checkpoint(d, rank=2)
        np.testing.assert_array_equal(one["x"], trees[2]["x"])

    def test_missing_shard_rejected(self, tmp_path):
        from apex_tpu.io import load_sharded_checkpoint, save_sharded_checkpoint

        d = tmp_path / "ck"
        save_sharded_checkpoint(d, {"a": np.ones(2)}, 0, 3)
        with pytest.raises(FileNotFoundError, match="missing shard"):
            load_sharded_checkpoint(d)

    def test_sync_shard_write_is_atomic(self, tmp_path, monkeypatch):
        """A crash mid-save must leave NEITHER a truncated shard under
        the final name NOR a stray .tmp (the sync path shares the async
        path's tmp+fsync+rename publish)."""
        from apex_tpu.io import checkpoint as ck
        from apex_tpu.io import save_sharded_checkpoint

        d = tmp_path / "ck"

        def boom(path, tree):
            with native.atomic_output(path) as f:
                f.write(b"partial")  # bytes hit the tmp file...
                raise OSError("disk died mid-write")

        monkeypatch.setattr(ck, "save_checkpoint", boom)
        with pytest.raises(OSError, match="disk died"):
            save_sharded_checkpoint(d, {"a": np.ones(2)}, 1, 2)
        assert not (d / "shard_00001-of-00002.ckpt").exists()
        assert not list(d.glob("*.tmp"))
        monkeypatch.undo()
        # a retry after the crash succeeds cleanly
        save_sharded_checkpoint(d, {"a": np.ones(2)}, 1, 2)
        assert (d / "shard_00001-of-00002.ckpt").exists()

    def test_lazy_open_reads_only_requested_leaves(self, tmp_path):
        """open_checkpoint_lazy: header now, bytes on demand — and the
        bytes that do come back are the right ones, leaf by leaf."""
        from apex_tpu.io import save_checkpoint
        from apex_tpu.io.checkpoint import _LazyLeaf, open_checkpoint_lazy

        rng = np.random.RandomState(0)
        tree = {
            "big": rng.randn(64, 8).astype(np.float32),
            "small": np.arange(5, dtype=np.int64),
            "bf16": np.asarray(jnp.arange(6.0, dtype=jnp.bfloat16)),
        }
        p = tmp_path / "lazy.ckpt"
        save_checkpoint(p, tree)
        lazy = open_checkpoint_lazy(p)
        assert all(isinstance(v, _LazyLeaf) for v in lazy.values())
        # PROOF of laziness: zero out "big"'s byte region on disk AFTER
        # the open — an eager reader would have snapshotted the original
        # bytes; the lazy one must see the overwrite, and only for the
        # overwritten leaf
        big = lazy["big"]
        with open(p, "r+b") as f:
            f.seek(big.offset)
            f.write(b"\0" * tree["big"].nbytes)
        np.testing.assert_array_equal(np.asarray(lazy["small"]), tree["small"])
        np.testing.assert_array_equal(
            np.asarray(lazy["big"]), np.zeros_like(tree["big"]))
        np.testing.assert_array_equal(
            np.asarray(lazy["bf16"]).astype(np.float32),
            np.asarray(tree["bf16"]).astype(np.float32))

    def test_distributed_load_never_reads_whole_shard_files(
            self, tmp_path, devices8, monkeypatch):
        """The mesh-aware restore must go through the lazy reader (the
        pod-scale OOM fix): the eager full-file loader must never run,
        and the bytes that do come back must reassemble correctly."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from apex_tpu.io import (
            load_distributed_checkpoint, save_distributed_checkpoint,
        )
        from apex_tpu.io import checkpoint as ck

        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        x = jax.device_put(
            jnp.arange(16.0), NamedSharding(mesh, P("dp")))
        d = tmp_path / "dist"
        save_distributed_checkpoint(d, {"x": x})

        def no_eager(path):
            raise AssertionError(f"eager full-file read of {path}")

        monkeypatch.setattr(ck, "load_checkpoint", no_eager)
        out = load_distributed_checkpoint(
            d, {"x": x}, mesh=mesh, spec_tree={"x": P("dp")})
        np.testing.assert_array_equal(np.asarray(out["x"]), np.arange(16.0))

    @pytest.mark.slow
    def test_zero2_resharding_through_files(self, tmp_path, devices8):
        """End-to-end: ZeRO shard dicts through the sharded-file
        protocol, reloaded at a different dp world."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.io import load_sharded_checkpoint, save_sharded_checkpoint

        params = {"w": jnp.asarray(np.random.RandomState(0).randn(10, 3).astype(np.float32))}
        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        state = opt.init(params, world_size=4)
        sspec = opt.state_partition_spec()
        g = jax.tree.map(jnp.ones_like, params)
        params2, state = jax.shard_map(
            lambda p, s, gg: opt.update(gg, s, p),
            mesh=mesh, in_specs=(P(), sspec, P()), out_specs=(P(), sspec),
            check_vma=False,
        )(params, state, g)

        d = tmp_path / "zero"
        for r in range(4):
            save_sharded_checkpoint(d, opt.sharded_state_dict(state, r, 4), r, 4)
        shards = load_sharded_checkpoint(d)
        state2 = DistributedFusedAdam.load_sharded_state_dicts(shards, world_size=2)
        assert int(state2.step) == 1
        # per-bucket payloads identical across the reshard (totals
        # differ: the dp=2 plan re-pads each bucket for 2 shards)
        np.testing.assert_allclose(
            np.asarray(state2.exp_avg[0][:30]),
            np.asarray(state.exp_avg[0][:30]), rtol=1e-7
        )


class TestAsyncCheckpointer:
    """Non-blocking save: snapshot-at-call-time semantics, ordered
    writes, atomic publish, error propagation."""

    def test_snapshot_semantics_and_roundtrip(self, tmp_path):
        from apex_tpu.io import AsyncCheckpointer, load_checkpoint

        p = str(tmp_path / "a.apex")
        tree = {"w": jnp.arange(4.0), "step": jnp.int32(7)}
        with AsyncCheckpointer() as ckpt:
            ckpt.save(p, tree)
            # mutate AFTER save returns: the file must hold the old values
            tree = {"w": tree["w"] * 100, "step": jnp.int32(8)}
        out = load_checkpoint(p)
        np.testing.assert_array_equal(out["w"], np.arange(4.0))
        assert int(out["step"]) == 7

    def test_many_saves_all_land_in_order(self, tmp_path):
        from apex_tpu.io import AsyncCheckpointer, load_checkpoint

        ckpt = AsyncCheckpointer()
        for i in range(5):
            ckpt.save(str(tmp_path / f"s{i}.apex"), {"i": jnp.int32(i)})
        ckpt.wait_until_finished()
        for i in range(5):
            assert int(load_checkpoint(str(tmp_path / f"s{i}.apex"))["i"]) == i
        # no stray .tmp files (atomic publish)
        assert not list(tmp_path.glob("*.tmp"))

    def test_write_error_reraises(self, tmp_path):
        from apex_tpu.io import AsyncCheckpointer

        ckpt = AsyncCheckpointer()
        bad = str(tmp_path / "no" / "\0bad")  # NUL in path: open() raises
        ckpt.save(bad, {"x": jnp.zeros(1)})
        with pytest.raises(RuntimeError, match="async checkpoint"):
            ckpt.wait_until_finished()
        # checkpointer stays usable after the failure
        ok = str(tmp_path / "ok.apex")
        ckpt.save(ok, {"x": jnp.ones(1)})
        ckpt.wait_until_finished()
        ckpt.close()
        with pytest.raises(RuntimeError, match="closed"):
            ckpt.save(ok, {"x": jnp.ones(1)})

    def test_numpy_leaves_are_copied(self, tmp_path):
        """np.ndarray leaves must be deep-copied at save() time: the
        caller may mutate them in place while the write is queued."""
        from apex_tpu.io import AsyncCheckpointer, load_checkpoint

        arr = np.arange(4.0)
        p = str(tmp_path / "c.apex")
        with AsyncCheckpointer() as ckpt:
            ckpt.save(p, {"w": arr})
            arr *= 100  # in-place mutation after save returned
        np.testing.assert_array_equal(load_checkpoint(p)["w"], np.arange(4.0))

    def test_close_joins_worker(self, tmp_path):
        import threading

        from apex_tpu.io import AsyncCheckpointer

        before = threading.active_count()
        ckpt = AsyncCheckpointer()
        ckpt.save(str(tmp_path / "d.apex"), {"x": jnp.ones(2)})
        ckpt.close()
        assert threading.active_count() == before

    def test_save_distributed_snapshot_and_roundtrip(self, tmp_path, devices8):
        """Async multi-host save: shards snapshot at call time (donation
        safe), the per-process file lands atomically, and the mesh-aware
        load reassembles the saved values."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from apex_tpu.io import AsyncCheckpointer, load_distributed_checkpoint

        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        sh = NamedSharding(mesh, P("dp"))
        x = jax.device_put(jnp.arange(16.0), sh)
        d = tmp_path / "dist"
        with AsyncCheckpointer() as ckpt:
            ckpt.save_distributed(d, {"x": x, "step": jnp.int32(3)})
            # mutate after save returns: the file must hold the old values
            x = jax.device_put(x * 100, sh)
        out = load_distributed_checkpoint(
            d, {"x": x, "step": jnp.int32(0)}, mesh=mesh,
            spec_tree={"x": P("dp"), "step": P()})
        np.testing.assert_array_equal(np.asarray(out["x"]), np.arange(16.0))
        assert int(out["step"]) == 3
        assert not list(d.glob("*.tmp"))

    def test_distributed_payload_copy_does_not_alias_device_buffers(self, devices8):
        """The async snapshot guarantee hinges on copy=True producing
        REAL copies: on the CPU backend np.asarray of a shard is a
        zero-copy view, so a donated buffer would corrupt a queued
        write.  Pin it with shares_memory (the behavior-level 'mutate
        after save' test can't catch a regression — JAX arrays are
        immutable, so rebinding keeps the old buffer alive either way)."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from apex_tpu.io.checkpoint import _distributed_payload

        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        x = jax.device_put(jnp.arange(16.0), NamedSharding(mesh, P("dp")))
        raw_views = [np.asarray(s.data) for s in x.addressable_shards]
        payload, _, _ = _distributed_payload({"x": x}, copy=True)
        for piece in payload["['x']"]:
            assert not any(np.shares_memory(piece["data"], rv) for rv in raw_views)
        # sanity: the zero-copy premise holds (the view path DOES alias),
        # so the assertion above is actually discriminating
        view_payload, _, _ = _distributed_payload({"x": x}, copy=False)
        aliases = [
            np.shares_memory(piece["data"], rv)
            for piece in view_payload["['x']"] for rv in raw_views
        ]
        assert any(aliases)


class TestAtomicOutput:
    """io.native.atomic_output — THE publish primitive (APX104's
    designated helper): clean exits land durable bytes under the final
    name, failures leave nothing at all."""

    def test_publishes_on_clean_exit(self, tmp_path):
        p = tmp_path / "blob.ckpt"
        with native.atomic_output(p) as f:
            f.write(b"hello")
        assert p.read_bytes() == b"hello"
        assert not list(tmp_path.glob("*.tmp"))

    def test_failure_publishes_nothing(self, tmp_path):
        p = tmp_path / "blob.ckpt"
        with pytest.raises(RuntimeError):
            with native.atomic_output(p) as f:
                f.write(b"parti")
                raise RuntimeError("writer died")
        assert not p.exists()
        assert not list(tmp_path.glob("*.tmp"))

    def test_overwrite_is_all_or_nothing(self, tmp_path):
        """A failed re-save must leave the PREVIOUS published bytes
        intact — the whole point of staging through .tmp."""
        p = tmp_path / "blob.ckpt"
        with native.atomic_output(p) as f:
            f.write(b"v1")
        with pytest.raises(RuntimeError):
            with native.atomic_output(p) as f:
                f.write(b"v2-partial")
                raise RuntimeError("boom")
        assert p.read_bytes() == b"v1"

    def test_save_checkpoint_is_atomic_by_itself(self, tmp_path,
                                                 monkeypatch):
        """save_checkpoint routes through atomic_output: a mid-write
        crash (simulated at the native flatten seam) publishes nothing
        and leaves no .tmp."""
        from apex_tpu.io import checkpoint as ck

        def boom(arrays, threads=native.DEFAULT_THREADS):
            raise RuntimeError("flatten died")

        monkeypatch.setattr(ck.native, "flatten", boom)
        with pytest.raises(RuntimeError, match="flatten died"):
            save_checkpoint(tmp_path / "x.ckpt", {"a": np.ones(4)})
        assert not (tmp_path / "x.ckpt").exists()
        assert not list(tmp_path.glob("*.tmp"))


class TestCheckpointIORetry:
    """Bounded retry-with-backoff around shard read/write — tested
    through the chaos slow/failing-I/O seam (ChaosPlan.io_failures
    rides io.checkpoint._with_io_retries)."""

    TREE = {"w": np.arange(6.0), "n": np.int64(2)}

    def _monkey(self, **kw):
        from apex_tpu.resilience import ChaosMonkey, ChaosPlan

        return ChaosMonkey(ChaosPlan.make(**kw))

    def test_transient_write_failures_retried_to_success(self, tmp_path):
        import logging

        from apex_tpu.utils.logging import get_logger

        messages = []
        handler = logging.Handler()
        handler.emit = lambda rec: messages.append(rec.getMessage())
        logger = get_logger("apex_tpu.io")
        logger.addHandler(handler)
        try:
            m = self._monkey(io_failures={"ckpt.write": 2})
            with m.active():
                save_checkpoint(tmp_path / "a.ckpt", self.TREE)
        finally:
            logger.removeHandler(handler)
        assert m.injected["io_fail:ckpt.write"] == 2
        back = load_checkpoint(tmp_path / "a.ckpt")
        np.testing.assert_array_equal(back["w"], self.TREE["w"])
        # the retries are structured-logged with attempt + jittered delay
        retries = [msg for msg in messages if "checkpoint.io_retry" in msg]
        assert len(retries) == 2
        assert "ChaosIOError" in retries[0]
        assert '"attempt": 1' in retries[0] and '"delay_s"' in retries[0]

    def test_transient_read_failures_retried_to_success(self, tmp_path):
        save_checkpoint(tmp_path / "a.ckpt", self.TREE)
        m = self._monkey(io_failures={"ckpt.read": 3})
        with m.active():
            back = load_checkpoint(tmp_path / "a.ckpt")
        assert m.injected["io_fail:ckpt.read"] == 3
        np.testing.assert_array_equal(back["w"], self.TREE["w"])

    def test_persistent_failure_exhausts_budget_and_raises(self, tmp_path):
        from apex_tpu.resilience import ChaosIOError

        m = self._monkey(io_failures={"ckpt.write": 100})
        with m.active(), pytest.raises(ChaosIOError):
            save_checkpoint(tmp_path / "a.ckpt", self.TREE)
        # 1 initial + 3 retries, then the final error propagates
        assert m.injected["io_fail:ckpt.write"] == 4
        assert not (tmp_path / "a.ckpt").exists()
        assert not list(tmp_path.glob("*.tmp"))

    def test_lazy_leaf_reads_retry_too(self, tmp_path):
        from apex_tpu.io.checkpoint import open_checkpoint_lazy

        save_checkpoint(tmp_path / "a.ckpt", self.TREE)
        m = self._monkey(io_failures={"ckpt.read": 1})
        with m.active():
            lazy = open_checkpoint_lazy(tmp_path / "a.ckpt")
        m2 = self._monkey(io_failures={"ckpt.read": 2})
        with m2.active():
            w = np.asarray(lazy["w"])
        np.testing.assert_array_equal(w, self.TREE["w"])
        assert m2.injected["io_fail:ckpt.read"] == 2

    def test_slow_io_delay_injection(self, tmp_path):
        import time as _t

        m = self._monkey(io_delay_seconds={"ckpt.write": 0.15})
        t0 = _t.monotonic()
        with m.active():
            save_checkpoint(tmp_path / "a.ckpt", self.TREE)
        assert _t.monotonic() - t0 >= 0.15
        assert m.injected["io_delay:ckpt.write"] == 1

    def test_index_reads_ride_the_retry_seam(self, tmp_path):
        """index.json is as load-bearing as any shard: a transient EIO
        must not skip the newest COMPLETE step dir (or fail an elastic
        restore) while the shard reads would have retried."""
        from apex_tpu.io import (latest_distributed_step, read_index,
                                 save_sharded_checkpoint)

        save_sharded_checkpoint(tmp_path / "step_00000003",
                                {"a": np.ones(2)}, 0, 1)
        m = self._monkey(io_failures={"ckpt.read": 2})
        with m.active():
            assert latest_distributed_step(tmp_path) == 3
        assert m.injected["io_fail:ckpt.read"] == 2
        m2 = self._monkey(io_failures={"ckpt.read": 1})
        with m2.active():
            assert read_index(tmp_path / "step_00000003")["world_size"] == 1
        assert m2.injected["io_fail:ckpt.read"] == 1

    def test_deterministic_oserrors_are_not_retried(self, tmp_path):
        """A typo'd path (FileNotFoundError) repeats identically —
        retrying would add ~0.35s of sleeps and three spurious
        'transient' warnings in front of the real error."""
        import time as _t

        t0 = _t.monotonic()
        with pytest.raises(FileNotFoundError):
            load_checkpoint(tmp_path / "never_saved.ckpt")
        assert _t.monotonic() - t0 < 0.05  # no backoff sleeps happened

    def test_corrupt_bytes_are_not_retried(self, tmp_path):
        """ValueError (torn header/blob) is NOT a transient error:
        corrupt bytes don't heal, so validation failures surface on
        the first attempt."""
        p = tmp_path / "a.ckpt"
        save_checkpoint(p, self.TREE)
        p.write_bytes(p.read_bytes()[:-8])
        m = self._monkey()   # counts nothing: no injection armed
        with m.active(), pytest.raises(ValueError):
            load_checkpoint(p)
        assert not m.injected
