"""GPT model tests — mirrors the reference's test_gpt_minimal.py: the
tensor-parallel model must match the single-device model exactly, and a
few training steps must reduce the loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.gpt import GPTConfig, gpt_forward, gpt_loss, init_params, param_specs
from apex_tpu.optimizers import FusedAdam

CFG = GPTConfig(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_seq_len=16,
    compute_dtype=jnp.float32,
    checkpoint_layers=False,
)


@pytest.fixture
def batch():
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, CFG.vocab_size, size=(2, 16))
    return jnp.asarray(tokens)


def test_forward_shapes(batch):
    params = init_params(CFG, jax.random.PRNGKey(0))
    logits = gpt_forward(params, batch, CFG)
    assert logits.shape == (16, 2, CFG.vocab_size)


@pytest.mark.slow
def test_tp_matches_single_device(batch, devices8):
    params = init_params(CFG, jax.random.PRNGKey(0))
    ref = gpt_forward(params, batch, CFG)

    mesh = Mesh(np.array(devices8[:4]), ("tp",))
    specs = param_specs(CFG)

    f = jax.shard_map(
        lambda p, t: gpt_forward(p, t, CFG, axis_name="tp"),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=P(None, None, "tp"),
        check_vma=False,
    )
    out = f(params, batch)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_tp_sp_matches_single_device(batch, devices8):
    cfg = GPTConfig(**{**CFG.__dict__, "sequence_parallel": True})
    params = init_params(cfg, jax.random.PRNGKey(0))
    ref = gpt_forward(params, batch, CFG)

    mesh = Mesh(np.array(devices8[:4]), ("tp",))
    specs = param_specs(cfg)
    f = jax.shard_map(
        lambda p, t: gpt_forward(p, t, cfg, axis_name="tp"),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=P(None, None, "tp"),
        check_vma=False,
    )
    out = f(params, batch)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_tp_loss_and_grads_match(batch, devices8):
    params = init_params(CFG, jax.random.PRNGKey(0))
    targets = jnp.roll(batch, -1, axis=1)

    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, batch, targets, CFG)

    mesh = Mesh(np.array(devices8[:4]), ("tp",))
    specs = param_specs(CFG)
    f = jax.shard_map(
        jax.value_and_grad(lambda p, t, y: gpt_loss(p, t, y, CFG, axis_name="tp")),
        mesh=mesh,
        in_specs=(specs, P(), P()),
        out_specs=(P(), specs),
        check_vma=False,
    )
    loss, grads = f(params, batch, targets)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(grads),
        jax.tree_util.tree_leaves_with_path(ref_grads),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
            err_msg=f"{jax.tree_util.keystr(ka)}",
        )


@pytest.mark.slow
def test_tp_sp_grads_match_after_sync(batch, devices8):
    """SP-mode grads (with the sequence-parallel psum) must equal the
    single-device grads — the SP analog of the reference's
    test_layers.py parity."""
    from apex_tpu.models.gpt import sp_grad_sync

    cfg = GPTConfig(**{**CFG.__dict__, "sequence_parallel": True})
    params = init_params(cfg, jax.random.PRNGKey(0))
    targets = jnp.roll(batch, -1, axis=1)
    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, batch, targets, CFG)

    mesh = Mesh(np.array(devices8[:4]), ("tp",))
    specs = param_specs(cfg)

    def local(p, t, y):
        loss, grads = jax.value_and_grad(lambda p: gpt_loss(p, t, y, cfg, axis_name="tp"))(p)
        return loss, sp_grad_sync(grads, "tp")

    f = jax.shard_map(
        local, mesh=mesh, in_specs=(specs, P(), P()), out_specs=(P(), specs), check_vma=False
    )
    loss, grads = f(params, batch, targets)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(grads),
        jax.tree_util.tree_leaves_with_path(ref_grads),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
            err_msg=f"{jax.tree_util.keystr(ka)}",
        )


@pytest.mark.slow
def test_training_reduces_loss(batch):
    params = init_params(CFG, jax.random.PRNGKey(0))
    targets = jnp.roll(batch, -1, axis=1)
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(gpt_loss)(params, batch, targets, CFG)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(10):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
