"""GPT model tests — mirrors the reference's test_gpt_minimal.py: the
tensor-parallel model must match the single-device model exactly, and a
few training steps must reduce the loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.gpt import GPTConfig, gpt_forward, gpt_loss, init_params, param_specs
from apex_tpu.optimizers import FusedAdam, FusedSGD

CFG = GPTConfig(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_seq_len=16,
    compute_dtype=jnp.float32,
    checkpoint_layers=False,
)


@pytest.fixture
def batch():
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, CFG.vocab_size, size=(2, 16))
    return jnp.asarray(tokens)


def test_forward_shapes(batch):
    params = init_params(CFG, jax.random.PRNGKey(0))
    logits = gpt_forward(params, batch, CFG)
    assert logits.shape == (16, 2, CFG.vocab_size)


def test_dense_head_clamps_out_of_range_targets(batch):
    """The dense lm_head_loss fallback must share the fused paths'
    out-of-range semantic: ids are clamped to [0, V-1], never wrapped
    (negative) or NaN-filled (past-V) by bare take_along_axis under jit
    (ADVICE r5 gpt.py:447; analyzer rule APX401)."""
    import dataclasses

    from apex_tpu.models.gpt import lm_head_loss

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, 2, CFG.hidden_size).astype(np.float32))
    embed = jnp.asarray(rng.randn(CFG.vocab_size, CFG.hidden_size)
                        .astype(np.float32))
    targets = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(16, 2)))
    # poison two ids: one negative, one past V
    bad = targets.at[0, 0].set(-3).at[5, 1].set(CFG.vocab_size + 7)
    clamped = jnp.clip(bad, 0, CFG.vocab_size - 1)

    dense_cfg = dataclasses.replace(CFG, fused_ce=False)
    got = jax.jit(lambda t: lm_head_loss(x, embed, t, dense_cfg))(bad)
    want = jax.jit(lambda t: lm_head_loss(x, embed, t, dense_cfg))(clamped)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    assert np.all(np.isfinite(np.asarray(got)))

    # and the fused scan path agrees on the SAME poisoned input
    fused_cfg = dataclasses.replace(CFG, fused_ce=True, fused_ce_chunk=8,
                                    fused_ce_impl="off")
    fused = jax.jit(lambda t: lm_head_loss(x, embed, t, fused_cfg))(bad)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(got), rtol=1e-5)


def test_remat_policies_same_loss_and_grads(batch):
    """Remat must not change math: loss AND grads identical (bitwise up
    to reduction order) across no-remat, full remat, and dots-saveable
    remat."""
    import dataclasses

    results = {}
    for name, kw in {
        "none": {"checkpoint_layers": False},
        "full": {"checkpoint_layers": True, "remat_policy": "full"},
        "dots": {"checkpoint_layers": True, "remat_policy": "dots"},
    }.items():
        cfg = dataclasses.replace(CFG, **kw)
        params = init_params(cfg, jax.random.PRNGKey(0))
        targets = jnp.roll(batch, -1, axis=1)
        loss, grads = jax.value_and_grad(gpt_loss)(params, batch, targets, cfg)
        results[name] = (float(loss), grads)
    for name in ("full", "dots"):
        assert np.isclose(results[name][0], results["none"][0], rtol=1e-6), name
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            results[name][1], results["none"][1],
        )


def test_remat_policy_validated():
    import dataclasses

    with pytest.raises(ValueError, match="remat_policy"):
        dataclasses.replace(CFG, remat_policy="dotz")


@pytest.mark.slow
def test_tp_matches_single_device(batch, devices8):
    params = init_params(CFG, jax.random.PRNGKey(0))
    ref = gpt_forward(params, batch, CFG)

    mesh = Mesh(np.array(devices8[:4]), ("tp",))
    specs = param_specs(CFG)

    f = jax.shard_map(
        lambda p, t: gpt_forward(p, t, CFG, axis_name="tp"),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=P(None, None, "tp"),
        check_vma=False,
    )
    out = f(params, batch)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_tp_sp_matches_single_device(batch, devices8):
    cfg = GPTConfig(**{**CFG.__dict__, "sequence_parallel": True})
    params = init_params(cfg, jax.random.PRNGKey(0))
    ref = gpt_forward(params, batch, CFG)

    mesh = Mesh(np.array(devices8[:4]), ("tp",))
    specs = param_specs(cfg)
    f = jax.shard_map(
        lambda p, t: gpt_forward(p, t, cfg, axis_name="tp"),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=P(None, None, "tp"),
        check_vma=False,
    )
    out = f(params, batch)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_tp_loss_and_grads_match(batch, devices8):
    params = init_params(CFG, jax.random.PRNGKey(0))
    targets = jnp.roll(batch, -1, axis=1)

    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, batch, targets, CFG)

    mesh = Mesh(np.array(devices8[:4]), ("tp",))
    specs = param_specs(CFG)
    f = jax.shard_map(
        jax.value_and_grad(lambda p, t, y: gpt_loss(p, t, y, CFG, axis_name="tp")),
        mesh=mesh,
        in_specs=(specs, P(), P()),
        out_specs=(P(), specs),
        check_vma=False,
    )
    loss, grads = f(params, batch, targets)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(grads),
        jax.tree_util.tree_leaves_with_path(ref_grads),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
            err_msg=f"{jax.tree_util.keystr(ka)}",
        )


@pytest.mark.slow
def test_tp_sp_grads_match_after_sync(batch, devices8):
    """SP-mode grads (with the sequence-parallel psum) must equal the
    single-device grads — the SP analog of the reference's
    test_layers.py parity."""
    from apex_tpu.models.gpt import sp_grad_sync

    cfg = GPTConfig(**{**CFG.__dict__, "sequence_parallel": True})
    params = init_params(cfg, jax.random.PRNGKey(0))
    targets = jnp.roll(batch, -1, axis=1)
    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, batch, targets, CFG)

    mesh = Mesh(np.array(devices8[:4]), ("tp",))
    specs = param_specs(cfg)

    def local(p, t, y):
        loss, grads = jax.value_and_grad(lambda p: gpt_loss(p, t, y, cfg, axis_name="tp"))(p)
        return loss, sp_grad_sync(grads, "tp")

    f = jax.shard_map(
        local, mesh=mesh, in_specs=(specs, P(), P()), out_specs=(P(), specs), check_vma=False
    )
    loss, grads = f(params, batch, targets)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(grads),
        jax.tree_util.tree_leaves_with_path(ref_grads),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
            err_msg=f"{jax.tree_util.keystr(ka)}",
        )


@pytest.mark.slow
def test_training_reduces_loss(batch):
    params = init_params(CFG, jax.random.PRNGKey(0))
    targets = jnp.roll(batch, -1, axis=1)
    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(gpt_loss)(params, batch, targets, CFG)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(10):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


class TestGroupedQueryAttention:
    """GQA through the model: num_query_groups < heads (kv projections
    are narrower), parity between tp-sharded and single-device, and
    training still converges."""

    GQA = GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
        num_query_groups=2, max_seq_len=16, compute_dtype=jnp.float32,
        checkpoint_layers=False,
    )

    def test_kv_projections_are_narrow(self):
        params = init_params(self.GQA, jax.random.PRNGKey(0))
        hd = self.GQA.head_dim
        assert params["layers"]["wk"].shape == (2, 2 * hd, 32)
        assert params["layers"]["wq"].shape == (2, 32, 32)

    @pytest.mark.slow
    def test_tp_gqa_loss_and_grads_match(self, batch, devices8):
        params = init_params(self.GQA, jax.random.PRNGKey(0))
        targets = jnp.roll(batch, -1, axis=1)
        ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(
            params, batch, targets, self.GQA)

        mesh = Mesh(np.array(devices8[:2]), ("tp",))  # kv_heads=2 → tp≤2
        specs = param_specs(self.GQA)
        f = jax.shard_map(
            jax.value_and_grad(lambda p, t, y: gpt_loss(p, t, y, self.GQA, axis_name="tp")),
            mesh=mesh,
            in_specs=(specs, P(), P()),
            out_specs=(P(), specs),
            check_vma=False,
        )
        loss, grads = f(params, batch, targets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(grads),
            jax.tree_util.tree_leaves_with_path(ref_grads),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
                err_msg=f"{jax.tree_util.keystr(ka)}",
            )

    @pytest.mark.slow
    def test_gqa_flash_matches_einsum_path(self, batch):
        import dataclasses

        flash = dataclasses.replace(self.GQA, use_flash_attention=True)
        params = init_params(self.GQA, jax.random.PRNGKey(0))
        out_e = gpt_forward(params, batch, self.GQA)
        out_f = gpt_forward(params, batch, flash)
        np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_f),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa_training_reduces_loss(self, batch):
        from apex_tpu.models.gpt import make_train_step
        from jax.sharding import Mesh as _M

        mesh = _M(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "tp"))
        params = init_params(self.GQA, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        step = make_train_step(self.GQA, opt, mesh)
        targets = jnp.roll(batch, -1, axis=1)
        losses = []
        for _ in range(5):
            params, state, loss = step(params, state, batch, targets)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_tp_larger_than_kv_heads_rejected(self, batch, devices8):
        params = init_params(self.GQA, jax.random.PRNGKey(0))
        mesh = Mesh(np.array(devices8[:4]), ("tp",))  # tp=4 > kv_heads=2
        f = jax.shard_map(
            lambda p, t: gpt_forward(p, t, self.GQA, axis_name="tp"),
            mesh=mesh, in_specs=(param_specs(self.GQA), P()),
            out_specs=P(None, None, "tp"), check_vma=False,
        )
        with pytest.raises(ValueError, match="num_query_groups"):
            f(params, batch)


# ------------------------------------------------- GSPMD step parity
class TestGspmdStepParity:
    """ISSUE 15's numerics acceptance: ``make_train_step(spmd="auto")``
    (jit + NamedSharding, XLA-placed collectives) against the
    shard_map oracle on the dp and dp×tp meshes, fp32.

    What is pinned and why: per-step LOSSES are bitwise-equal at dp=4
    and within one float32 ulp at dp=2×tp=2 (first step only — the
    residual of compiler-chosen fusion order in the tp forward).
    PARAMS track to a few gradient ulps; strict param-bitwise between
    the two programs is not achievable even in principle — the tied
    embedding's two grad contributions (lookup scatter + head dot) are
    all-reduced SEPARATELY by the SPMD partitioner but summed before
    the single pmean in the shard_map program, a summation-association
    difference no source spelling removes (every other leaf matches
    bitwise at dp=4 after normalization.fused_layer_norm's _lead_sum
    fix).  SGD's linear update bounds the drift at gradient scale
    (~4e-9); Adam's rsqrt amplifies it to the measured ~5e-5."""

    STEPS = 5

    def _trajectory(self, mesh, spmd, make_opt, sspec):
        from apex_tpu.models.gpt import make_train_step

        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = make_opt()
        state = opt.init(params)
        step = make_train_step(CFG, opt, mesh, opt_state_spec=sspec,
                               spmd=spmd)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(8, 16)))
        targets = jnp.roll(tokens, -1, axis=1)
        losses = []
        for _ in range(self.STEPS):
            params, state, loss = step(params, state, tokens, targets)
            losses.append(float(loss))
        return losses, params

    @staticmethod
    def _adam_sspec():
        from apex_tpu.optimizers.fused_adam import AdamState

        specs = param_specs(CFG)
        return AdamState(step=P(), exp_avg=specs, exp_avg_sq=specs,
                         master=None)

    @staticmethod
    def _sgd_sspec():
        from apex_tpu.optimizers.fused_sgd import SGDState

        return SGDState(step=P(), momentum_buffer=param_specs(CFG),
                        master=None)

    def _compare(self, mesh, make_opt, sspec, loss_atol, param_atol,
                 bitwise_losses):
        lo, po = self._trajectory(mesh, "shard_map", make_opt, sspec)
        lg, pg = self._trajectory(mesh, "auto", make_opt, sspec)
        if bitwise_losses:
            assert lo == lg, f"losses diverged: {lo} vs {lg}"
        else:
            for i, (a, b) in enumerate(zip(lo, lg)):
                assert abs(a - b) <= loss_atol, \
                    f"step {i}: |{a} - {b}| > {loss_atol}"
        for (ka, a), b in zip(
                jax.tree_util.tree_flatten_with_path(po)[0],
                jax.tree_util.tree_leaves(pg)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=0, atol=param_atol,
                err_msg=f"{jax.tree_util.keystr(ka)}")

    def test_dp4_adam_loss_bitwise(self, devices8):
        """The headline pin: 5 Adam steps, every loss bitwise-equal
        fp32 to the shard_map oracle at dp=4."""
        mesh = Mesh(np.array(devices8[:4]).reshape(4, 1), ("dp", "tp"))
        self._compare(mesh, lambda: FusedAdam(lr=1e-2),
                      self._adam_sspec(), loss_atol=0.0,
                      param_atol=5e-4, bitwise_losses=True)

    def test_dp4_sgd_params_tight(self, devices8):
        """SGD's linear update keeps params at gradient-ulp distance
        (measured 3.7e-9 over 5 steps) — the strongest param pin the
        embed-tie association allows."""
        mesh = Mesh(np.array(devices8[:4]).reshape(4, 1), ("dp", "tp"))
        self._compare(mesh, lambda: FusedSGD(lr=1e-2),
                      self._sgd_sspec(), loss_atol=0.0,
                      param_atol=1e-7, bitwise_losses=True)

    def test_dp2_tp2_sgd(self, devices8):
        """dp=2 × tp=2: losses within one fp32 ulp per step (measured:
        only step 1 differs, by exactly one ulp), params at
        gradient-ulp distance."""
        mesh = Mesh(np.array(devices8[:4]).reshape(2, 2), ("dp", "tp"))
        self._compare(mesh, lambda: FusedSGD(lr=1e-2),
                      self._sgd_sspec(), loss_atol=1.5e-6,
                      param_atol=1e-6, bitwise_losses=False)

    def test_dp2_tp2_adam(self, devices8):
        mesh = Mesh(np.array(devices8[:4]).reshape(2, 2), ("dp", "tp"))
        self._compare(mesh, lambda: FusedAdam(lr=1e-2),
                      self._adam_sspec(), loss_atol=1.5e-6,
                      param_atol=5e-4, bitwise_losses=False)


class TestGspmdStepTails:
    """The loss_scaler / StepGuard / telemetry tails on the
    ``spmd="auto"`` path: on global arrays the finite vote is a plain
    reduction (``sync_axes=()`` makes ``sync_found_inf`` the identity),
    so the SAME ``_apply_*_update`` tails serve both builders — pinned
    here as shard_map-oracle parity at dp=4, the mesh where the plain
    losses are already bitwise."""

    STEPS = 5

    def _mesh(self, devices8):
        return Mesh(np.array(devices8[:4]).reshape(4, 1), ("dp", "tp"))

    def _sspec(self):
        from apex_tpu.optimizers.fused_adam import AdamState

        specs = param_specs(CFG)
        return AdamState(step=P(), exp_avg=specs, exp_avg_sq=specs,
                         master=None)

    def _data(self):
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(8, 16)))
        return tokens, jnp.roll(tokens, -1, axis=1)

    def _run(self, mesh, spmd, **step_kw):
        from apex_tpu.models.gpt import make_train_step

        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        step = make_train_step(CFG, opt, mesh,
                               opt_state_spec=self._sspec(), spmd=spmd,
                               **step_kw)
        tokens, targets = self._data()
        extras = []
        if "loss_scaler" in step_kw:
            extras.append(step_kw["loss_scaler"].init())
        if "step_guard" in step_kw:
            extras.append(step_kw["step_guard"].init())
        if "telemetry" in step_kw:
            extras.append(step_kw["telemetry"].init())
        losses = []
        for _ in range(self.STEPS):
            out = step(params, state, *extras, tokens, targets)
            params, state, *extras, loss = out
            losses.append(float(loss))
        return losses, params, extras

    def _parity(self, devices8, **step_kw):
        mesh = self._mesh(devices8)
        lo, po, eo = self._run(mesh, "shard_map", **step_kw)
        lg, pg, eg = self._run(mesh, "auto", **step_kw)
        assert lo == lg, f"losses diverged: {lo} vs {lg}"
        for (ka, a), b in zip(
                jax.tree_util.tree_flatten_with_path(po)[0],
                jax.tree_util.tree_leaves(pg)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=0, atol=5e-4,
                err_msg=f"{jax.tree_util.keystr(ka)}")
        return eo, eg

    def test_loss_scaler_parity(self, devices8):
        """All-finite fp32 run: identical scaler trajectory (growth
        schedule included) and bitwise losses vs the oracle."""
        from apex_tpu.amp import DynamicLossScaler

        scaler = DynamicLossScaler(init_scale=2.0 ** 6, growth_interval=2)
        eo, eg = self._parity(devices8, loss_scaler=scaler)
        assert float(eo[0].loss_scale) == float(eg[0].loss_scale)
        assert float(eg[0].loss_scale) > 2.0 ** 6  # growth engaged

    def test_step_guard_parity(self, devices8):
        from apex_tpu.resilience import StepGuard

        guard = StepGuard(max_consecutive_bad=3)
        eo, eg = self._parity(devices8, step_guard=guard)
        assert int(eo[0].total_skipped) == int(eg[0].total_skipped) == 0

    def test_telemetry_parity(self, devices8):
        """Telemetry rides the auto path: same losses as the oracle's
        instrumented run, and the window really observed the steps."""
        from apex_tpu.observability import stepstats

        tel = stepstats.StepTelemetry()
        eo, eg = self._parity(devices8, telemetry=tel)
        assert int(eg[0].steps) == self.STEPS
        assert int(eo[0].steps) == self.STEPS
        assert np.isfinite(float(eg[0].loss_last))

    def test_scaled_guarded_parity(self, devices8):
        """The composed tail (scaler + guard) — the full fp16-style
        harness on plain jit + NamedSharding."""
        from apex_tpu.amp import DynamicLossScaler
        from apex_tpu.resilience import StepGuard

        scaler = DynamicLossScaler(init_scale=2.0 ** 6)
        guard = StepGuard(max_consecutive_bad=3)
        eo, eg = self._parity(devices8, loss_scaler=scaler,
                              step_guard=guard)
        assert float(eo[0].loss_scale) == float(eg[0].loss_scale)
        assert int(eo[1].total_skipped) == int(eg[1].total_skipped) == 0
