"""Pallas fused-CE kernels (ops/fused_ce_pallas.py) — interpreter-mode
parity on CPU (the kernels engage for real only on TPU; see
tests/test_layer_norm_pallas.py for the same convention).

The scan path's tests (test_fused_ce.py) re-run on this path too when
APEX_TPU_FUSED_CE_PALLAS=interpret is exported; here we pin the
highest-value cases permanently: raw kernel parity, the dispatch
integration through gpt_loss, and the tp pmax/psum recombination."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.gpt import GPTConfig, gpt_loss, init_params
from apex_tpu.ops.fused_ce_pallas import (
    fused_ce_bwd_pallas,
    fused_ce_fwd_pallas,
)


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    monkeypatch.setenv("APEX_TPU_FUSED_CE_PALLAS", "interpret")
    monkeypatch.setenv("APEX_TPU_FUSED_CE_DOT", "float32")


def _data(N=64, H=32, V=96):
    x = jax.random.normal(jax.random.PRNGKey(0), (N, H), jnp.float32)
    e = jax.random.normal(jax.random.PRNGKey(1), (V, H), jnp.float32)
    t = jax.random.randint(jax.random.PRNGKey(2), (N,), 0, V)
    return x, e, t


def test_fwd_kernel_matches_dense():
    x, e, t = _data()
    logits = x @ e.T
    m, l, tgt = fused_ce_fwd_pallas(x, e, t, block_n=16, block_v=32,
                                    interpret=True)
    np.testing.assert_allclose(
        np.asarray(m + jnp.log(l)),
        np.asarray(jax.scipy.special.logsumexp(logits, -1)), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(tgt),
        np.asarray(jnp.take_along_axis(logits, t[:, None], -1)[:, 0]),
        rtol=1e-6)


@pytest.mark.parametrize("shape", [(90, 32, 393), (24, 8, 100)])
def test_edge_shapes_ceil_grid(shape):
    """Non-lane-aligned N and V (e.g. a tp8 vocab shard 6288 = 2^4·3·131
    has NO aligned divisor): the ceil-grid edge tiles must mask their
    overrun rows/cols — including zeroing garbage operand rows before
    the MXU dots (0 × NaN = NaN inside a contraction)."""
    N, H, V = shape
    x = jax.random.normal(jax.random.PRNGKey(0), (N, H), jnp.float32)
    e = jax.random.normal(jax.random.PRNGKey(1), (V, H), jnp.float32)
    t = jax.random.randint(jax.random.PRNGKey(2), (N,), 0, V)
    g = jax.random.normal(jax.random.PRNGKey(3), (N,)) / N
    logits = x @ e.T
    lse_ref = jax.scipy.special.logsumexp(logits, -1)
    m, l, tgt = fused_ce_fwd_pallas(x, e, t, block_n=64, block_v=128,
                                    interpret=True)
    np.testing.assert_allclose(np.asarray(m + jnp.log(l)),
                               np.asarray(lse_ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(tgt),
        np.asarray(jnp.take_along_axis(logits, t[:, None], -1)[:, 0]),
        rtol=1e-5, atol=1e-5)

    def loss(x, e):
        lg = x @ e.T
        return jnp.sum(g * (jax.scipy.special.logsumexp(lg, -1)
                            - jnp.take_along_axis(lg, t[:, None], -1)[:, 0]))

    dx_ref, de_ref = jax.grad(loss, argnums=(0, 1))(x, e)
    dx, de = fused_ce_bwd_pallas(x, e, t, lse_ref, g, block_n=64,
                                 block_v=128, interpret=True)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(de), np.asarray(de_ref),
                               rtol=1e-4, atol=1e-4)


def test_bwd_kernels_match_autodiff():
    x, e, t = _data()
    g = jax.random.normal(jax.random.PRNGKey(3), (x.shape[0],))

    def loss(x, e):
        lg = x @ e.T
        ls = jax.scipy.special.logsumexp(lg, -1)
        tg = jnp.take_along_axis(lg, t[:, None], -1)[:, 0]
        return jnp.sum(g * (ls - tg))

    dx_ref, de_ref = jax.grad(loss, argnums=(0, 1))(x, e)
    lse = jax.scipy.special.logsumexp(x @ e.T, -1)
    dx, de = fused_ce_bwd_pallas(x, e, t, lse, g, block_n=16, block_v=32,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(de), np.asarray(de_ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("shape", [
    (8192, 768, 50304),   # GPT-124M head, dense
    (8192, 768, 6288),    # tp8 vocab shard (no lane-aligned divisor)
])
def test_kernels_lower_for_tpu_target(shape):
    """Cross-platform lowering (jax.export, platforms=['tpu']) runs the
    full Pallas→Mosaic path without a device: BlockSpec/layout/op
    legality errors surface HERE instead of at the kernels' hardware
    debut inside an audited bench section."""
    from jax import export as jexport

    from apex_tpu.ops import fused_ce_pallas as k

    N, H, V = shape
    x = jax.ShapeDtypeStruct((N, H), jnp.bfloat16)
    e = jax.ShapeDtypeStruct((V, H), jnp.float32)
    t = jax.ShapeDtypeStruct((N,), jnp.int32)
    lse = jax.ShapeDtypeStruct((N,), jnp.float32)
    g = jax.ShapeDtypeStruct((N,), jnp.float32)
    fwd = jexport.export(jax.jit(lambda x, e, t: k.fused_ce_fwd_pallas(x, e, t)),
                         platforms=["tpu"])(x, e, t)
    assert len(fwd.mlir_module_serialized) > 0
    bwd = jexport.export(
        jax.jit(lambda x, e, t, lse, g: k.fused_ce_bwd_pallas(x, e, t, lse, g)),
        platforms=["tpu"])(x, e, t, lse, g)
    assert len(bwd.mlir_module_serialized) > 0


def test_gpt_loss_grad_lowers_for_tpu_with_kernels(monkeypatch):
    """value_and_grad(gpt_loss) with the kernels FORCED on lowers for
    the TPU target — the CE kernels validated inside the real model
    graph (residual threading, float0 cotangent, reshapes), not just
    standalone."""
    from jax import export as jexport

    monkeypatch.setenv("APEX_TPU_FUSED_CE_PALLAS", "1")
    cfg = dataclasses.replace(CFG, compute_dtype=jnp.bfloat16)
    params = jax.eval_shape(
        lambda k: init_params(cfg, k), jax.ShapeDtypeStruct((2,), jnp.uint32))
    tok = jax.ShapeDtypeStruct((2, 16), jnp.int32)

    def step(params, tokens, targets):
        return jax.value_and_grad(gpt_loss)(params, tokens, targets, cfg)

    exp = jexport.export(jax.jit(step), platforms=["tpu"])(params, tok, tok)
    assert len(exp.mlir_module_serialized) > 0


def test_out_of_range_targets_match_scan_path(monkeypatch):
    """Dense-mode ids outside [0, V) must clamp IDENTICALLY on both
    impls (the scan path's take_along_axis clamps; the kernel clamps in
    _local_targets) — platform-dependent losses for the same inputs
    would be a silent correctness trap."""
    from apex_tpu.ops.fused_ce import fused_lm_head_ce

    S, B, H, V = 16, 2, 32, 48
    x = jax.random.normal(jax.random.PRNGKey(0), (S, B, H), jnp.float32)
    e = jax.random.normal(jax.random.PRNGKey(1), (V, H), jnp.float32)
    t = jax.random.randint(jax.random.PRNGKey(2), (S, B), 0, V)
    t = t.at[0, 0].set(-1).at[1, 1].set(V + 7)

    def mean_loss(x, e):
        return jnp.mean(fused_lm_head_ce(x, e, t, 8))

    got = float(mean_loss(x, e))
    got_g = jax.grad(mean_loss, argnums=(0, 1))(x, e)
    monkeypatch.setenv("APEX_TPU_FUSED_CE_PALLAS", "0")
    ref = float(mean_loss(x, e))
    ref_g = jax.grad(mean_loss, argnums=(0, 1))(x, e)
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    for a, b in zip(got_g, ref_g):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


CFG = GPTConfig(
    vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
    max_seq_len=16, compute_dtype=jnp.float32, checkpoint_layers=False,
    fused_ce=True, fused_ce_chunk=8,
)


def test_gpt_loss_via_kernels_matches_dense():
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, size=(2, 16)))
    targets = jnp.roll(tokens, -1, axis=1)
    params = init_params(CFG, jax.random.PRNGKey(0))
    dense = dataclasses.replace(CFG, fused_ce=False)
    ref, ref_g = jax.value_and_grad(gpt_loss)(params, tokens, targets, dense)
    got, got_g = jax.value_and_grad(gpt_loss)(params, tokens, targets, CFG)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        got_g, ref_g)


def test_tp_recombination_matches_dense(devices8):
    """Kernel per shard + pmax/psum outside == global softmax: the
    (m, l, tgt) recombination is the load-bearing tp contract."""
    from apex_tpu.ops.fused_ce import fused_lm_head_ce

    S, B, H, V, tp = 16, 2, 32, 64, 4
    x = jax.random.normal(jax.random.PRNGKey(0), (S, B, H), jnp.float32)
    e = jax.random.normal(jax.random.PRNGKey(1), (V, H), jnp.float32)
    t = jax.random.randint(jax.random.PRNGKey(2), (S, B), 0, V)

    def dense(x, e):
        lg = jnp.matmul(x, e.T)
        ls = jax.scipy.special.logsumexp(lg, -1)
        tg = jnp.take_along_axis(lg, t[..., None], -1)[..., 0]
        return jnp.mean(ls - tg)

    ref = dense(x, e)
    dx_ref, de_ref = jax.grad(dense, argnums=(0, 1))(x, e)

    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))

    def local(x, e_local):
        def f(x, e_local):
            return jnp.mean(fused_lm_head_ce(x, e_local, t, 8, "tp"))

        loss = f(x, e_local)
        dx, de = jax.grad(f, argnums=(0, 1))(x, e_local)
        return loss, jax.lax.psum(dx, "tp"), de

    f = jax.shard_map(local, mesh=mesh,
                      in_specs=(P(), P("tp", None)),
                      out_specs=(P(), P(), P("tp", None)),
                      check_vma=False)
    loss, dx, de = f(x, e)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(de), np.asarray(de_ref),
                               rtol=1e-5, atol=1e-6)
