"""Optimizer parity tests — mirrors tests/L0/run_optimizers of the
reference, which checks fused optimizers against torch.optim references
(``test_adam.py:52-63``, ``test_fused_optimizer.py``, ``test_lamb.py``).
Here torch (CPU) is the oracle for Adam/AdamW/SGD/Adagrad, and a NumPy
reference implements LAMB (as the reference's test_lamb.py does)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.optimizers import (
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedNovoGrad,
    FusedSGD,
)


def make_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "a": rng.randn(7, 5).astype(np.float32),
        "b": {"w": rng.randn(11).astype(np.float32), "s": rng.randn(1).astype(np.float32)},
    }


def tree_to_torch(tree):
    return [torch.nn.Parameter(torch.tensor(x)) for x in jax.tree.leaves(tree)]


def set_torch_grads(tparams, gtree):
    for p, g in zip(tparams, jax.tree.leaves(gtree)):
        p.grad = torch.tensor(np.asarray(g))


def assert_tree_close(jtree, tparams, rtol=1e-5, atol=1e-6):
    for j, t in zip(jax.tree.leaves(jtree), tparams):
        np.testing.assert_allclose(
            np.asarray(j), t.detach().numpy(), rtol=rtol, atol=atol
        )


NSTEPS = 5


class TestFusedAdam:
    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_adamw_parity(self, wd):
        opt = FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=True)
        params, tparams = None, None
        p = jax.tree.map(jnp.asarray, make_tree())
        t = tree_to_torch(p)
        topt = torch.optim.AdamW(t, lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=wd)
        params, tparams = run_pair_with(opt, topt, p, t)
        assert_tree_close(params, tparams, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_adam_l2_parity(self, wd):
        opt = FusedAdam(lr=1e-2, weight_decay=wd, adam_w_mode=False)
        p = jax.tree.map(jnp.asarray, make_tree())
        t = tree_to_torch(p)
        topt = torch.optim.Adam(t, lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=wd)
        params, tparams = run_pair_with(opt, topt, p, t)
        assert_tree_close(params, tparams, rtol=1e-4, atol=1e-5)

    def test_skip_on_overflow(self):
        opt = FusedAdam(lr=1e-2)
        params = jax.tree.map(jnp.asarray, make_tree())
        state = opt.init(params)
        grads = jax.tree.map(lambda x: jnp.full(x.shape, jnp.inf), params)
        new_params, new_state = opt.update(grads, state, params, grads_finite=jnp.bool_(False))
        for a, b in zip(jax.tree.leaves(new_params), jax.tree.leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(new_state.step) == 0

    def test_master_weights_bf16(self):
        opt = FusedAdam(lr=1e-2, master_weights=True)
        params32 = jax.tree.map(jnp.asarray, make_tree())
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params32)
        state = opt.init(params)
        assert state.master is not None
        grads = jax.tree.map(lambda x: jnp.ones(x.shape, jnp.bfloat16), params)
        new_params, new_state = opt.update(grads, state, params)
        # params remain bf16; master stays fp32 and moved
        for p in jax.tree.leaves(new_params):
            assert p.dtype == jnp.bfloat16
        for m in jax.tree.leaves(new_state.master):
            assert m.dtype == jnp.float32

    def test_jit_update(self):
        opt = FusedAdam(lr=1e-2)
        params = jax.tree.map(jnp.asarray, make_tree())
        state = opt.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        step = jax.jit(lambda g, s, p: opt.update(g, s, p))
        p1, s1 = step(grads, state, params)
        p2, s2 = opt.update(grads, state, params)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def run_pair_with(opt, topt, params, tparams, nsteps=NSTEPS, seed=0, **kw):
    state = opt.init(params)
    rng = np.random.RandomState(seed + 100)
    for _ in range(nsteps):
        gnp = jax.tree.map(lambda x: rng.randn(*np.asarray(x).shape).astype(np.float32), params)
        grads = jax.tree.map(jnp.asarray, gnp)
        params, state = opt.update(grads, state, params, **kw)
        set_torch_grads(tparams, gnp)
        topt.step()
    return params, tparams


class TestFusedSGD:
    @pytest.mark.parametrize("momentum,nesterov,wd", [(0.0, False, 0.0), (0.9, False, 0.0), (0.9, True, 0.0), (0.9, False, 0.05)])
    def test_sgd_parity(self, momentum, nesterov, wd):
        opt = FusedSGD(lr=0.1, momentum=momentum, nesterov=nesterov, weight_decay=wd)
        p = jax.tree.map(jnp.asarray, make_tree())
        t = tree_to_torch(p)
        topt = torch.optim.SGD(t, lr=0.1, momentum=momentum, nesterov=nesterov, weight_decay=wd)
        params, tparams = run_pair_with(opt, topt, p, t)
        assert_tree_close(params, tparams, rtol=1e-5, atol=1e-6)


class TestFusedAdagrad:
    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_adagrad_parity(self, wd):
        # torch adagrad: p -= lr * g / (sqrt(h)+eps) with L2 wd folded in —
        # matches ADAGRAD_MODE_0
        opt = FusedAdagrad(lr=0.1, eps=1e-10, weight_decay=wd)
        p = jax.tree.map(jnp.asarray, make_tree())
        t = tree_to_torch(p)
        topt = torch.optim.Adagrad(t, lr=0.1, eps=1e-10, weight_decay=wd)
        params, tparams = run_pair_with(opt, topt, p, t)
        assert_tree_close(params, tparams, rtol=1e-4, atol=1e-5)


def numpy_lamb_reference(params, grads_seq, lr, betas, eps, wd, max_grad_norm=1.0, use_nvlamb=False, grad_averaging=True):
    """Independent NumPy LAMB implementing multi_tensor_lamb.cu semantics."""
    b1, b2 = betas
    b3 = 1 - b1 if grad_averaging else 1.0
    leaves, treedef = jax.tree.flatten(params)
    ms = [np.zeros_like(x) for x in leaves]
    vs = [np.zeros_like(x) for x in leaves]
    ps = [np.array(x) for x in leaves]
    step = 0
    for gtree in grads_seq:
        gs = [np.array(x) for x in jax.tree.leaves(gtree)]
        step += 1
        bc1 = 1 - b1 ** step
        bc2 = 1 - b2 ** step
        gn = np.sqrt(sum((g.astype(np.float64) ** 2).sum() for g in gs))
        clip = gn / max_grad_norm if gn > max_grad_norm else 1.0
        for i in range(len(ps)):
            g = gs[i] / clip
            m = ms[i] = b1 * ms[i] + b3 * g
            v = vs[i] = b2 * vs[i] + (1 - b2) * g * g
            u = (m / bc1) / (np.sqrt(v / bc2) + eps) + wd * ps[i]
            if use_nvlamb or wd != 0:
                pn = np.sqrt((ps[i] ** 2).sum())
                un = np.sqrt((u ** 2).sum())
                ratio = lr * (pn / un) if (pn != 0 and un != 0) else lr
            else:
                ratio = lr
            ps[i] = ps[i] - ratio * u
    return jax.tree.unflatten(treedef, ps)


class TestFusedLAMB:
    @pytest.mark.parametrize("wd,use_nvlamb", [(0.01, False), (0.0, False), (0.0, True)])
    def test_lamb_vs_numpy(self, wd, use_nvlamb):
        lr, betas, eps = 1e-2, (0.9, 0.999), 1e-6
        params = jax.tree.map(jnp.asarray, make_tree())
        opt = FusedLAMB(lr=lr, betas=betas, eps=eps, weight_decay=wd, use_nvlamb=use_nvlamb)
        state = opt.init(params)
        rng = np.random.RandomState(3)
        grads_seq = []
        p = params
        for _ in range(NSTEPS):
            g = jax.tree.map(lambda x: rng.randn(*x.shape).astype(np.float32) * 5, params)
            grads_seq.append(g)
            p, state = opt.update(jax.tree.map(jnp.asarray, g), state, p)
        ref = numpy_lamb_reference(params, grads_seq, lr, betas, eps, wd, use_nvlamb=use_nvlamb)
        for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(ref)):
            np.testing.assert_allclose(np.asarray(a), b, rtol=2e-4, atol=2e-5)


class TestFusedNovoGrad:
    def test_novograd_runs_and_descends(self):
        # quadratic bowl: params should move toward zero
        opt = FusedNovoGrad(lr=0.05, weight_decay=0.0)
        params = {"w": jnp.asarray(np.ones(16, np.float32) * 3)}
        state = opt.init(params)
        for _ in range(50):
            grads = jax.tree.map(lambda p: 2 * p, params)
            params, state = opt.update(grads, state, params)
        assert np.abs(np.asarray(params["w"])).max() < 3.0

    def test_norm_blend_init(self):
        # first step with init from grad norm: v1 = ||g||
        opt = FusedNovoGrad(lr=0.1)
        params = {"w": jnp.asarray(np.ones(4, np.float32))}
        state = opt.init(params)
        g = {"w": jnp.asarray(np.full(4, 2.0, np.float32))}
        _, state = opt.update(g, state, params)
        expected = np.sqrt(4 * 4.0)  # ||g|| = 4
        np.testing.assert_allclose(float(jax.tree.leaves(state.exp_avg_sq)[0]), expected, rtol=1e-5)


class TestParamGroups:
    """Functional param_groups (reference optimizers iterate per-group
    lr/weight_decay): path->group mapping + per-group overrides."""

    def _groups(self, path, leaf):
        return "no_decay" if ("bias" in path or "norm" in path) else "default"

    def test_adam_no_decay_group(self):
        from apex_tpu.optimizers import FusedAdam

        params = {"w": jnp.ones((4, 4)), "bias": jnp.ones((4,)),
                  "norm_scale": jnp.ones((4,))}
        grads = jax.tree.map(jnp.zeros_like, params)  # wd effect only

        grouped = FusedAdam(lr=0.1, weight_decay=0.5,
                            param_group_fn=self._groups,
                            group_hypers={"no_decay": {"weight_decay": 0.0}})
        st = grouped.init(params)
        p2, _ = grouped.update(grads, st, params)
        # zero grad + AdamW: p -= lr*wd*p only where decay applies
        np.testing.assert_allclose(np.asarray(p2["w"]), 0.95, rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(p2["bias"]), 1.0)
        np.testing.assert_array_equal(np.asarray(p2["norm_scale"]), 1.0)

    def test_adam_per_group_lr(self):
        from apex_tpu.optimizers import FusedAdam

        params = {"w": jnp.ones((4,)), "head_w": jnp.ones((4,))}
        grads = {"w": jnp.full((4,), 0.1), "head_w": jnp.full((4,), 0.1)}
        opt = FusedAdam(lr=0.1, weight_decay=0.0,
                        param_group_fn=lambda p, l: "head" if "head" in p else "body",
                        group_hypers={"head": {"lr": 0.0}})
        st = opt.init(params)
        p2, _ = opt.update(grads, st, params)
        assert float(p2["w"][0]) != 1.0
        np.testing.assert_array_equal(np.asarray(p2["head_w"]), 1.0)  # lr=0

    def test_ungrouped_matches_hand_oracle(self):
        """No param_group_fn → exact AdamW numerics (pins the default
        code path against a hand-computed oracle)."""
        from apex_tpu.optimizers import FusedAdam

        params = {"w": jnp.asarray([1.0, 2.0])}
        grads = {"w": jnp.asarray([0.1, -0.2])}
        a = FusedAdam(lr=0.01, weight_decay=0.01)
        pa, _ = a.update(grads, a.init(params), params)

        g = np.array([0.1, -0.2]); p = np.array([1.0, 2.0])
        m = 0.1 * g; v = 0.001 * g * g
        u = (m / (1 - 0.9)) / (np.sqrt(v / (1 - 0.999)) + 1e-8) + 0.01 * p
        np.testing.assert_allclose(np.asarray(pa["w"]), p - 0.01 * u, rtol=1e-6)

    def test_lr_scale_composes_with_schedule(self):
        """lr_scale multiplies the runtime lr (the schedule-friendly
        per-group knob); absolute 'lr' replaces it."""
        from apex_tpu.optimizers import FusedAdam

        params = {"w": jnp.ones((4,)), "head": jnp.ones((4,))}
        grads = {"w": jnp.full((4,), 0.1), "head": jnp.full((4,), 0.1)}
        opt = FusedAdam(lr=999.0, weight_decay=0.0,
                        param_group_fn=lambda p, l: "head" if "head" in p else "body",
                        group_hypers={"head": {"lr_scale": 0.5}})
        st = opt.init(params)
        runtime_lr = 0.01
        p2, _ = opt.update(grads, st, params, lr=runtime_lr)
        dw = 1.0 - float(p2["w"][0])      # stepped at runtime lr
        dh = 1.0 - float(p2["head"][0])   # stepped at 0.5 * runtime lr
        np.testing.assert_allclose(dh, dw * 0.5, rtol=1e-5)

    def test_typod_group_name_raises(self):
        from apex_tpu.optimizers import FusedAdam

        params = {"w": jnp.ones((2,))}
        grads = {"w": jnp.ones((2,))}
        opt = FusedAdam(lr=0.1, param_group_fn=lambda p, l: "body",
                        group_hypers={"no-decay": {"weight_decay": 0.0}})
        with pytest.raises(ValueError, match="no-decay"):
            opt.update(grads, opt.init(params), params)

    def test_typod_override_key_raises(self):
        """A typo'd override key ('weight_dacay') must fail loudly, not
        be silently ignored by the h.get() lookups."""
        from apex_tpu.optimizers import FusedAdam, FusedSGD

        params = {"w": jnp.ones((2,))}
        grads = {"w": jnp.ones((2,))}
        opt = FusedAdam(lr=0.1, param_group_fn=lambda p, l: "body",
                        group_hypers={"body": {"weight_dacay": 0.0}})
        with pytest.raises(ValueError, match="weight_dacay"):
            opt.update(grads, opt.init(params), params)
        # optimizer-specific keys are allowed only where that optimizer
        # reads them: momentum is FusedSGD's, not FusedAdam's
        opt2 = FusedAdam(lr=0.1, param_group_fn=lambda p, l: "body",
                         group_hypers={"body": {"momentum": 0.5}})
        with pytest.raises(ValueError, match="momentum"):
            opt2.update(grads, opt2.init(params), params)
        opt3 = FusedSGD(lr=0.1, momentum=0.9, param_group_fn=lambda p, l: "body",
                        group_hypers={"body": {"momentum": 0.5}})
        opt3.update(grads, opt3.init(params), params)  # valid for SGD

    def test_lamb_trust_ratio_exclusion(self):
        from apex_tpu.optimizers import FusedLAMB

        params = {"w": jnp.full((8,), 2.0), "ln_g": jnp.full((8,), 2.0)}
        grads = {"w": jnp.full((8,), 0.3), "ln_g": jnp.full((8,), 0.3)}
        opt = FusedLAMB(
            lr=0.1, weight_decay=0.1, max_grad_norm=1e9,
            param_group_fn=lambda p, l: "ln" if p.startswith("['ln") else "w",
            group_hypers={"ln": {"use_trust_ratio": False, "weight_decay": 0.0}})
        st = opt.init(params)
        p2, _ = opt.update(grads, st, params)

        # oracle: ln_g takes a plain Adam-style step (no trust ratio, no wd)
        bc1, bc2 = 1 - 0.9, 1 - 0.999
        m = 0.1 * 0.3
        v = 0.001 * 0.3 ** 2
        u = (m / bc1) / (np.sqrt(v / bc2) + 1e-6)
        np.testing.assert_allclose(np.asarray(p2["ln_g"]), 2.0 - 0.1 * u, rtol=1e-5)
        # w uses the trust ratio: ||p||/||u_w|| scaling, so a different step
        assert not np.allclose(np.asarray(p2["w"]), np.asarray(p2["ln_g"]))

    def test_sgd_per_group_momentum_and_decay(self):
        from apex_tpu.optimizers import FusedSGD

        params = {"w": jnp.ones((4,)), "bn_scale": jnp.ones((4,))}
        grads = {"w": jnp.full((4,), 0.1), "bn_scale": jnp.full((4,), 0.1)}
        opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=0.5,
                       param_group_fn=lambda p, l: "bn" if "bn" in p else "w",
                       group_hypers={"bn": {"weight_decay": 0.0, "momentum": 0.0}})
        st = opt.init(params)
        p2, st = opt.update(grads, st, params)
        # bn: plain SGD, no decay: p - lr*g
        np.testing.assert_allclose(np.asarray(p2["bn_scale"]), 1.0 - 0.1 * 0.1, rtol=1e-6)
        # w: wd folded in before momentum; first step buf = g
        np.testing.assert_allclose(np.asarray(p2["w"]), 1.0 - 0.1 * (0.1 + 0.5), rtol=1e-6)
        # second step, exact: buf=0.6 (first step), g2 = 0.1 + 0.5*0.94
        # = 0.57, steady = 0.9*0.6 + 0.57 = 1.11, p3 = 0.94 - 0.1*1.11
        p3, st = opt.update(grads, st, p2)
        np.testing.assert_allclose(np.asarray(p3["w"]), 0.94 - 0.111, rtol=1e-6)
        # bn stays momentum-free: another plain lr*g step
        np.testing.assert_allclose(np.asarray(p3["bn_scale"]), 0.99 - 0.01, rtol=1e-6)

    def test_adagrad_no_decay_group(self):
        from apex_tpu.optimizers import FusedAdagrad

        params = {"w": jnp.ones((4,)), "b": jnp.ones((4,))}
        opt = FusedAdagrad(lr=0.1, weight_decay=0.5,
                           param_group_fn=lambda p, l: "b" if p == "['b']" else "w",
                           group_hypers={"b": {"weight_decay": 0.0}})
        st = opt.init(params)
        g1 = {"w": jnp.full((4,), 0.1), "b": jnp.full((4,), 0.1)}
        p2, st = opt.update(g1, st, params)
        # zero grad: only weight decay moves params — the no-decay group
        # must hold still (first-step adagrad normalizes to sign(g), so
        # the wd difference is only visible from step 2 on)
        g0 = jax.tree.map(jnp.zeros_like, g1)
        p3, st = opt.update(g0, st, p2)
        np.testing.assert_array_equal(np.asarray(p3["b"]), np.asarray(p2["b"]))
        assert not np.allclose(np.asarray(p3["w"]), np.asarray(p2["w"]))
