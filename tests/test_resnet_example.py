"""ResNet + imagenet-example tests — mirrors the reference's L1 tier
(tests/L1/common: run the imagenet trainer, store per-iteration loss,
compare trajectories)."""

import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.resnet import ResNet18ish, ResNet50

# whole-file e2e/parity workloads: >20 s compiled (quick tier skips)
pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[1]


def test_resnet50_builds_and_has_bf16_compute():
    m = ResNet50()
    x = jnp.ones((1, 64, 64, 3))
    variables = m.init(jax.random.PRNGKey(0), x, train=True)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(variables["params"]))
    assert 20_000_000 < n_params < 30_000_000  # ~25.6M
    logits, _ = m.apply(variables, x, train=True, mutable=["batch_stats"])
    assert logits.shape == (1, 1000)
    assert logits.dtype == jnp.float32  # head in fp32


def test_resnet_small_trains():
    m = ResNet18ish(num_classes=10)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 32, 32, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, size=(4,)))
    variables = m.init(jax.random.PRNGKey(0), x, train=True)
    params, bs = variables["params"], variables["batch_stats"]

    from apex_tpu.optimizers import FusedSGD

    opt = FusedSGD(lr=0.05, momentum=0.9)
    state = opt.init(params)

    @jax.jit
    def step(params, state, bs):
        def loss_fn(p, bs):
            logits, upd = m.apply({"params": p, "batch_stats": bs}, x, train=True, mutable=["batch_stats"])
            onehot = jax.nn.one_hot(y, 10)
            return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1)), upd["batch_stats"]

        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, bs)
        params, state = opt.update(grads, state, params)
        return params, state, bs, loss

    losses = []
    for _ in range(8):
        params, state, bs, loss = step(params, state, bs)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_imagenet_example_end_to_end(tmp_path):
    """Run the example script: train → checkpoint → resume (the reference's
    L1 'run it for real' tier)."""
    ck = tmp_path / "ck.pkl"
    cmd = [
        sys.executable, str(REPO / "examples/imagenet/main_amp.py"),
        "--small", "--steps", "2", "--batch-size", "4", "--image-size", "32",
        "--checkpoint", str(ck),
    ]
    env = {"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin:/usr/local/bin", "HOME": "/root"}
    r = subprocess.run(cmd, capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert ck.exists()
    r2 = subprocess.run(
        cmd[:-2] + ["--resume", str(ck)], capture_output=True, text=True, env=env, timeout=600
    )
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "step 2" in r2.stdout  # resumed from step 2
