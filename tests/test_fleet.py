"""Fleet chaos matrix: replica failure must be invisible to callers.

The load-bearing contracts (ISSUE 17 acceptance):

- **Kill (exit-137 shape)**: SIGKILL one replica of a 2-replica fleet
  mid-stream — every submitted request still completes, with greedy
  token streams BITWISE the unkilled single-replica run, zero dropped
  requests, zero duplicate emissions.  The journal is the only replay
  source (a hard kill leaves no manifest).
- **Wedge (exit-75 shape)**: wedge one replica's decode step — the
  ``serve.step_wedged`` manifest path replays (the manifest carries
  tokens the frontend never polled, spliced not regenerated), and the
  request's ONE trace id joins its spans across both replicas.
- **Brownout**: overload sheds best-effort admissions (typed
  ``Overloaded`` with retry-after) BEFORE the interactive lane's TTFT
  is touched, pinned via the per-lane serve histograms.
- **Drain-then-restart**: a planned restart re-routes the drained
  replica's queue, finishes its residents in place, and rejects ZERO
  admissions end to end.
- **Hedge**: an interactive straggler gets exactly one hedged retry;
  first token wins, the loser is cancelled/suppressed — no duplicate
  completion, stream unchanged.
- **Uniformity**: the fleet config registers in the PR 16 seam; a
  fleet whose processes disagree about one replica's scheduler config
  fails ``check_uniform`` loudly with the ``serve.fleet_config`` tag.

Plus the scheduler-seam satellites: ``drain_manifest()`` structure
(emitted tokens included — the splice contract), ``cancel()``, and
``begin_drain()``.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.inference import (
    ContinuousBatchingScheduler, DecodeConfig, KVCacheConfig, Request,
)
from apex_tpu.inference.fleet import (
    FleetFrontend, LocalReplica, Overloaded, Router, RouterConfig,
)
from apex_tpu.models.gpt import GPTConfig, init_params
from apex_tpu.observability import MetricsScope
from apex_tpu.observability import tracing
from apex_tpu.resilience import uniformity as U
from apex_tpu.resilience.chaos import ChaosMonkey, ChaosPlan

VOCAB = 61


@pytest.fixture(autouse=True)
def _isolated_seams():
    """Fleet frontends register a ``serve.fleet_config`` provider in
    the process-global uniformity seam, and some tests install a
    tracer — both must not leak across tests."""
    U.reset_uniformity()
    yield
    U.reset_uniformity()
    tracing.disable()


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, hidden_size=32, num_layers=2,
        num_attention_heads=4, max_seq_len=128,
        position_embedding_type="rope", compute_dtype=jnp.float32,
        checkpoint_layers=False,
    )
    base.update(kw)
    return GPTConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _sched(params, cfg, *, num_pages=40, page_size=4, pages_per_seq=16,
           max_batch=2, max_prompt=16, seed=0, time_fn=None, **dk):
    dcfg = DecodeConfig(
        cache=KVCacheConfig(num_pages=num_pages, page_size=page_size,
                            pages_per_seq=pages_per_seq,
                            dtype=jnp.float32),
        max_batch=max_batch, max_prompt_len=max_prompt,
        temperature=0.0, top_k=0, attn_impl="xla", sample_impl="xla",
        sample_dot_dtype=jnp.float32, base_seed=seed, **dk)
    kw = {} if time_fn is None else {"time_fn": time_fn}
    return ContinuousBatchingScheduler(params, cfg, dcfg, **kw)


def _fleet(params, cfg, *, n=2, config=None, time_fn=None,
           auto_restart=True, **dk):
    """A started n-replica fleet over one shared model (the replicas
    of one deployment serve the same weights — the bitwise-parity
    contract depends on it)."""
    reps = [LocalReplica(
        f"r{i}",
        (lambda params=params, cfg=cfg, dk=dk, tf=time_fn:
         _sched(params, cfg, time_fn=tf, **dk)),
        **({} if time_fn is None else {"time_fn": time_fn}))
        for i in range(n)]
    kw = {} if time_fn is None else {"time_fn": time_fn}
    fe = FleetFrontend(
        reps,
        config=config or RouterConfig(hedge_after_s=0.0,
                                      reject_queue_depth=10_000,
                                      be_shed_queue_depth=10_000),
        auto_restart=auto_restart, **kw)
    return fe.start()


def _requests(rng, n, max_new=6, lane="interactive"):
    return [Request(i, rng.randint(0, VOCAB, size=10).tolist(), max_new,
                    lane=lane) for i in range(n)]


def _baseline(params, cfg, requests):
    """The unkilled single-replica greedy streams — the bitwise bar."""
    sched = _sched(params, cfg, max_batch=max(4, len(requests)),
                   num_pages=120, pages_per_seq=16)
    for r in requests:
        sched.submit(Request(r.rid, list(r.prompt), r.max_new_tokens,
                             eos_id=r.eos_id, lane=r.lane))
    return {c.rid: tuple(c.tokens) for c in sched.run_until_drained()}


class _LogSink(logging.Handler):
    """Collects ``apex_tpu.inference`` records (the module logger
    writes to a stream captured at import, so pytest's capture
    fixtures miss it)."""

    def __init__(self):
        super().__init__()
        self.lines = []

    def emit(self, record):
        self.lines.append(record.getMessage())

    def __enter__(self):
        logging.getLogger("apex_tpu.inference").addHandler(self)
        return self

    def __exit__(self, *exc):
        logging.getLogger("apex_tpu.inference").removeHandler(self)

    @property
    def text(self) -> str:
        return "\n".join(self.lines)


class _Clock:
    """Manually-advanced clock shared by schedulers, replicas, and the
    frontend — hedge deadlines become deterministic."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


# ------------------------------------------------- scheduler-seam units
class TestDrainManifest:
    def test_manifest_includes_emitted_tokens_and_lanes(self, model):
        """The satellite bugfix: the manifest is structured (not just a
        log line) and carries each in-flight request's already-emitted
        tokens, so replay can SPLICE rather than regenerate."""
        cfg, params = model
        rng = np.random.RandomState(0)
        sched = _sched(params, cfg, max_batch=1)
        reqs = [Request(0, rng.randint(0, VOCAB, size=8).tolist(), 6),
                Request(1, rng.randint(0, VOCAB, size=8).tolist(), 6),
                Request(2, rng.randint(0, VOCAB, size=8).tolist(), 6,
                        lane="best_effort")]
        for r in reqs:
            sched.submit(r)
        sched.step()  # admit+prefill rid 0, decode one token
        manifest = {m.rid: m for m in sched.drain_manifest()}
        assert set(manifest) == {0, 1, 2}
        m0 = manifest[0]
        assert m0.phase == "in_flight" and m0.lane == "interactive"
        assert m0.emitted == sched._slots[0].generated
        assert len(m0.emitted) >= 1, "prefill's first token must show"
        assert m0.remaining == 6 - len(m0.emitted)
        assert m0.prompt == list(reqs[0].prompt)
        assert m0.trace_id is not None
        for rid in (1, 2):
            assert manifest[rid].phase == "queued"
            assert manifest[rid].emitted == []
            assert manifest[rid].remaining == 6
        assert manifest[2].lane == "best_effort"

    def test_wedge_log_carries_manifest_and_rid_fields(self, model):
        cfg, params = model
        rng = np.random.RandomState(1)
        sched = _sched(params, cfg, max_batch=1)
        sched.submit(Request(0, rng.randint(0, VOCAB, size=8).tolist(),
                             6))
        sched.submit(Request(1, rng.randint(0, VOCAB, size=8).tolist(),
                             6))
        sched.step()
        with _LogSink() as sink:
            sched._on_wedge({"elapsed_s": 1.5})
        assert "serve.step_wedged" in sink.text
        assert "queued_rids" in sink.text   # watchdog/test-compat field
        assert "manifest" in sink.text
        assert "emitted" in sink.text

    def test_cancel_queued_not_resident(self, model):
        cfg, params = model
        rng = np.random.RandomState(2)
        sched = _sched(params, cfg, max_batch=1)
        sched.submit(Request(0, rng.randint(0, VOCAB, size=8).tolist(),
                             6))
        sched.submit(Request(1, rng.randint(0, VOCAB, size=8).tolist(),
                             6))
        sched.step()
        got = sched.cancel(1)
        assert got is not None and got.rid == 1
        assert not sched.queue
        assert sched.cancel(0) is None, "residents are not cancellable"
        assert sched.cancel(99) is None
        done = sched.run_until_drained()
        assert [c.rid for c in done] == [0]

    def test_begin_drain_stops_admission_finishes_residents(self, model):
        cfg, params = model
        rng = np.random.RandomState(3)
        sched = _sched(params, cfg, max_batch=1)
        for i in range(3):
            sched.submit(Request(
                i, rng.randint(0, VOCAB, size=8).tolist(), 5))
        sched.step()
        handed_back = sched.begin_drain()
        assert sorted(m.rid for m in handed_back) == [1, 2]
        assert all(m.phase == "queued" for m in handed_back)
        assert not sched.queue and not sched.be_queue
        with pytest.raises(RuntimeError, match="draining"):
            sched.submit(Request(
                9, rng.randint(0, VOCAB, size=8).tolist(), 5))
        for _ in range(50):
            if sched.drained():
                break
            sched.step()
        assert sched.drained()
        assert [c.rid for c in sched.completed] == [0]
        assert len(sched.completed[0].tokens) == 5


# --------------------------------------------------- kill (exit-137 shape)
class TestKillReplay:
    def test_kill_one_replica_mid_stream_bitwise_parity(self, model):
        """The headline acceptance: SIGKILL one of two replicas while
        its residents stream — every request completes, greedy streams
        bitwise the unkilled single-replica run, zero drops, zero
        duplicates."""
        cfg, params = model
        rng = np.random.RandomState(7)
        reqs = _requests(rng, 6, max_new=6)
        want = _baseline(params, cfg, reqs)

        monkey = ChaosMonkey(ChaosPlan.make(
            kill_replica_at={"r0": 3}))
        with monkey.active():
            fe = _fleet(params, cfg, n=2)
            for r in reqs:
                fe.submit(Request(r.rid, list(r.prompt),
                                  r.max_new_tokens, lane=r.lane))
            done = fe.run_until_drained()

        assert monkey.injected.get("kill_replica:r0") == 1
        assert fe.stats["replica_deaths"] == 1
        assert fe.stats["replays"] >= 1, \
            "the killed replica held work; replay must have fired"
        rids = [c.rid for c in done]
        assert sorted(rids) == sorted(want), "dropped request(s)"
        assert len(rids) == len(set(rids)), "duplicate completion(s)"
        for c in done:
            assert tuple(c.tokens) == want[c.rid], (
                f"rid {c.rid}: fleet stream diverged from the unkilled "
                f"run (replays={c.replays})")
            assert len(c.token_times) == len(c.tokens)
        assert any(c.replays >= 1 for c in done)
        # the dead replica came back (auto-restart supervisor role)
        assert fe.replicas["r0"].state == "serving"
        assert fe.replicas["r0"].restarts == 1

    def test_direct_kill_api(self, model):
        """`kill()` (no chaos plan) is the test/driver seam: same
        journal-replay path, discovered on the next frontend step."""
        cfg, params = model
        rng = np.random.RandomState(8)
        reqs = _requests(rng, 3, max_new=5)
        want = _baseline(params, cfg, reqs)
        fe = _fleet(params, cfg, n=2, auto_restart=False)
        for r in reqs:
            fe.submit(Request(r.rid, list(r.prompt), r.max_new_tokens),
                      replica_id="r0")
        fe.step()
        fe.replicas["r0"].kill()
        # dead replicas don't raise from step(); the journal holds the
        # orphaned work — reroute it explicitly via the frontend seam
        fe._on_replica_dead(fe.replicas["r0"], None, "kill")
        done = fe.run_until_drained()
        assert {c.rid: tuple(c.tokens) for c in done} == want
        assert all(c.replica_id == "r1" for c in done)


# -------------------------------------------------- wedge (exit-75 shape)
class TestWedgeManifestReplay:
    def test_wedge_replays_manifest_and_trace_ids_join(self, model):
        """The second headline acceptance: a wedged replica's
        ``serve.step_wedged`` manifest drives the replay, and each
        replayed request's ONE trace id joins its spans across both
        replicas (prefill on the wedged one, prefill+request on the
        survivor, the fleet.replay span naming both)."""
        cfg, params = model
        rng = np.random.RandomState(9)
        reqs = _requests(rng, 4, max_new=6)
        want = _baseline(params, cfg, reqs)

        tracer = tracing.configure(capacity=8192)
        monkey = ChaosMonkey(ChaosPlan.make(
            wedge_replica_at={"r0": 3}))
        with MetricsScope() as reg, monkey.active(), _LogSink() as sink:
            fe = _fleet(params, cfg, n=2)
            for r in reqs:
                fe.submit(Request(r.rid, list(r.prompt),
                                  r.max_new_tokens, lane=r.lane))
            done = fe.run_until_drained()

        assert monkey.injected.get("wedge_replica:r0") == 1
        assert "serve.step_wedged" in sink.text
        assert {c.rid: tuple(c.tokens) for c in done} == want
        replays = [m for m in reg.metrics()
                   if m.name == "apex_fleet_replays_total"]
        assert replays and replays[0].value(cause="wedge") >= 1, \
            "the wedge manifest path must drive these replays"

        spans = tracer.spans()
        replay_spans = [s for s in spans if s["name"] == "fleet.replay"]
        assert replay_spans, "no fleet.replay span emitted"
        for rs in replay_spans:
            tid = rs["attrs"]["trace_id"]
            assert rs["attrs"]["cause"] == "wedge"
            assert rs["attrs"]["from_replica"] == "r0"
            assert rs["attrs"]["to_replica"] == "r1"
            joined = [s["name"] for s in spans
                      if s["attrs"].get("trace_id") == tid]
            # leg 1's prefill ran on r0, leg 2's prefill AND the
            # whole-lifetime serve.request on r1 — one id joins them
            assert joined.count("serve.prefill") >= 2, joined
            assert "serve.request" in joined, joined

    def test_wedge_splices_tokens_the_frontend_never_polled(self, model):
        """The manifest is richer than the journal: tokens generated
        between the last poll and the wedge ride the manifest into the
        journal (spliced), so the continuation budget shrinks — replay
        does not regenerate them."""
        cfg, params = model
        rng = np.random.RandomState(10)
        reqs = _requests(rng, 2, max_new=8)
        want = _baseline(params, cfg, reqs)
        monkey = ChaosMonkey(ChaosPlan.make(
            wedge_replica_at={"r0": 4}))
        with monkey.active():
            fe = _fleet(params, cfg, n=2)
            for r in reqs:
                fe.submit(Request(r.rid, list(r.prompt),
                                  r.max_new_tokens),
                          replica_id="r0")
            done = fe.run_until_drained()
        assert {c.rid: tuple(c.tokens) for c in done} == want
        replayed = [c for c in done if c.replays]
        assert replayed, "everything was pinned to the wedged replica"


# ----------------------------------------------------------- brownout
class TestBrownout:
    def test_best_effort_sheds_before_interactive_rejects(self, model):
        cfg, params = model
        rng = np.random.RandomState(11)
        fe = _fleet(params, cfg, n=2, max_batch=1,
                    config=RouterConfig(hedge_after_s=0.0,
                                        be_shed_queue_depth=2,
                                        reject_queue_depth=4,
                                        retry_after_s=0.25,
                                        affinity_min_tokens=10 ** 6))

        def mk(rid, lane="interactive"):
            return Request(rid, rng.randint(0, VOCAB, size=8).tolist(),
                           4, lane=lane)

        with MetricsScope() as reg:
            fe.submit(mk(0))
            fe.submit(mk(1))
            # fleet queued depth is now at the shed rung: best-effort
            # admissions degrade FIRST, typed and with retry-after
            with pytest.raises(Overloaded) as shed:
                fe.submit(mk(100, lane="best_effort"))
            assert shed.value.reason == "brownout_shed"
            assert shed.value.retry_after_s == 0.25
            # the interactive lane still admits at this depth
            fe.submit(mk(2))
            fe.submit(mk(3))
            # ...until the hard rung rejects every lane
            with pytest.raises(Overloaded) as rej:
                fe.submit(mk(4))
            assert rej.value.reason == "overloaded"
            done = fe.run_until_drained()

        assert sorted(c.rid for c in done) == [0, 1, 2, 3]
        # pinned via the per-lane histograms: every interactive TTFT
        # sample landed, and the shed lane never produced one (shed at
        # admission, not after burning prefill on it)
        ttft = [m for m in reg.metrics()
                if m.name == "apex_serve_ttft_seconds"]
        lanes = {l.get("lane"): v for m in ttft
                 for name, l, v in m.samples() if name.endswith("_count")}
        assert lanes.get("interactive") == 4.0, lanes
        assert "best_effort" not in lanes
        rejects = [m for m in reg.metrics()
                   if m.name == "apex_fleet_rejections_total"]
        assert rejects[0].value(reason="brownout_shed",
                                lane="best_effort") == 1.0
        assert rejects[0].value(reason="overloaded",
                                lane="interactive") == 1.0


# ------------------------------------------------- drain-then-restart
class TestDrainRestart:
    def test_drain_reroutes_queue_finishes_residents_zero_rejects(
            self, model):
        cfg, params = model
        rng = np.random.RandomState(12)
        reqs = _requests(rng, 4, max_new=5)
        want = _baseline(params, cfg, reqs)
        fe = _fleet(params, cfg, n=2, max_batch=1, auto_restart=False)
        for i, r in enumerate(reqs):
            fe.submit(Request(r.rid, list(r.prompt), r.max_new_tokens),
                      replica_id=f"r{i % 2}")
        fe.step()  # one resident per replica, one queued behind each
        r0 = fe.replicas["r0"]
        moved = fe.drain_replica("r0")
        assert moved == 1, "r0's queued request must re-route"
        assert r0.state == "draining"
        for _ in range(100):
            if r0.state == "dead":
                break
            fe.step()
        # the frontend retired the drained replica (residents done);
        # with auto_restart off, the relaunch is ours to drive
        assert r0.state == "dead"
        r0.restart()
        r0.step()
        assert r0.state == "serving"
        # post-restart the replica admits again — planned restart done
        extra = Request(50, rng.randint(0, VOCAB, size=10).tolist(), 4)
        fe.submit(extra, replica_id="r0")
        done = fe.run_until_drained()
        assert fe.stats["rejected"] == 0, \
            "a planned drain must reject nothing"
        got = {c.rid: tuple(c.tokens) for c in done}
        for rid, toks in want.items():
            assert got[rid] == toks
        assert 50 in got


# ------------------------------------------------------------- hedging
class TestHedgedRetry:
    def test_straggler_gets_one_hedge_first_token_wins(self, model):
        cfg, params = model
        rng = np.random.RandomState(13)
        clock = _Clock()
        fe = _fleet(params, cfg, n=2, max_batch=1, time_fn=clock,
                    config=RouterConfig(hedge_after_s=0.5,
                                        reject_queue_depth=10 ** 6,
                                        be_shed_queue_depth=10 ** 6,
                                        affinity_min_tokens=10 ** 6))
        blocker = Request(0, rng.randint(0, VOCAB, size=10).tolist(), 12)
        target_prompt = rng.randint(0, VOCAB, size=10).tolist()
        want = _baseline(params, cfg, [Request(1, target_prompt, 4)])
        fe.submit(blocker, replica_id="r0")
        fe.step()  # blocker resident on r0 (max_batch=1)
        # the target starves behind it — queued on r0, no token
        fe.submit(Request(1, list(target_prompt), 4), replica_id="r0")
        clock.t += 1.0  # past the hedge deadline, still token-less
        done = fe.run_until_drained()
        assert fe.stats["hedges"] == 1
        by_rid = {c.rid: c for c in done}
        assert sorted(by_rid) == [0, 1], "zero drops, zero duplicates"
        tgt = by_rid[1]
        assert tgt.hedged and tgt.replica_id == "r1", \
            "the idle replica's hedge leg must win"
        assert tuple(tgt.tokens) == want[1]
        # the loser copy was cancelled out of r0's queue, not served
        assert all(r.sched is None or not any(
            q and any(req.rid == 1 for req in q)
            for q in (r.sched.queue, r.sched.be_queue))
            for r in fe.replicas.values())

    def test_hedge_is_bounded_to_one(self, model):
        cfg, params = model
        rng = np.random.RandomState(14)
        clock = _Clock()
        fe = _fleet(params, cfg, n=3, max_batch=1, time_fn=clock,
                    config=RouterConfig(hedge_after_s=0.5,
                                        reject_queue_depth=10 ** 6,
                                        be_shed_queue_depth=10 ** 6,
                                        affinity_min_tokens=10 ** 6))
        fe.submit(Request(0, rng.randint(0, VOCAB, size=10).tolist(),
                          10), replica_id="r0")
        fe.step()
        fe.submit(Request(1, rng.randint(0, VOCAB, size=10).tolist(),
                          4), replica_id="r0")
        clock.t += 1.0
        fe.step()   # hedge fires once...
        clock.t += 1.0
        fe.step()   # ...and never again, even while still waiting
        assert fe.stats["hedges"] == 1
        entry = fe.journal.get(1)
        assert entry.hedged
        fe.run_until_drained()


# ----------------------------------------------------------- uniformity
class TestFleetUniformity:
    def test_fleet_config_registers_and_uniform_view_checks(self, model):
        cfg, params = model
        fe = _fleet(params, cfg, n=2)
        # same view on every "process": check passes and records the tag
        payload = U.check_uniform(
            gather=lambda p: [dict(p), dict(p)])
        assert "serve.fleet_config" in payload

    def test_one_divergent_replica_config_fails_loudly(self, model):
        """The chaos shape: rank 1's r1 was deployed with a different
        scheduler config (page_size 8 vs 4) — its digest differs, so
        the fleet view diverges and check_uniform names the tag
        instead of letting replay splice onto a different compiled
        program."""
        cfg, params = model
        fe = _fleet(params, cfg, n=2, page_size=4)
        local = fe._uniform_view()
        divergent = LocalReplica(
            "r1", lambda: _sched(params, cfg, page_size=8)).start()
        other = dict(local)
        other["config_digests"] = dict(local["config_digests"])
        other["config_digests"]["r1"] = divergent.config_digest
        assert other != local
        other_digest = U.uniform_digest(other)

        def gather(payload):
            return [dict(payload),
                    {**payload, "serve.fleet_config": other_digest}]

        with pytest.raises(U.UniformityError) as err:
            U.check_uniform(gather=gather)
        assert err.value.tag == "serve.fleet_config"


# ------------------------------------------------------ routing units
class TestRouting:
    def test_prefix_affinity_prefers_warmed_trie(self, model):
        cfg, params = model
        rng = np.random.RandomState(15)
        fe = _fleet(params, cfg, n=2, prefix_sharing=True,
                    config=RouterConfig(hedge_after_s=0.0,
                                        affinity_min_tokens=4,
                                        reject_queue_depth=10 ** 6,
                                        be_shed_queue_depth=10 ** 6))
        prompt = rng.randint(0, VOCAB, size=14).tolist()
        fe.submit(Request(0, list(prompt), 4), replica_id="r0")
        fe.run_until_drained()
        router: Router = fe.router
        reps = list(fe.replicas.values())
        assert fe.replicas["r0"].prefix_affinity(prompt) >= 4
        assert fe.replicas["r1"].prefix_affinity(prompt) == 0
        pick = router.pick(Request(1, list(prompt), 4), reps)
        assert pick.replica_id == "r0", "affinity must beat id order"

    def test_least_loaded_fallback_and_health_gate(self, model):
        cfg, params = model
        rng = np.random.RandomState(16)
        fe = _fleet(params, cfg, n=2, max_batch=1,
                    config=RouterConfig(hedge_after_s=0.0,
                                        affinity_min_tokens=10 ** 6,
                                        reject_queue_depth=10 ** 6,
                                        be_shed_queue_depth=10 ** 6))
        # load r0: a resident plus a queued request
        fe.submit(Request(0, rng.randint(0, VOCAB, size=8).tolist(),
                          8), replica_id="r0")
        fe.step()
        fe.submit(Request(1, rng.randint(0, VOCAB, size=8).tolist(),
                          8), replica_id="r0")
        fresh = Request(2, rng.randint(0, VOCAB, size=8).tolist(), 4)
        pick = fe.router.pick(fresh, list(fe.replicas.values()))
        assert pick.replica_id == "r1", "least-loaded must pick idle r1"
        # health gate: with r1 dead, nothing serving-but-r0 → r0; with
        # both dead, a typed no-capacity rejection
        fe.replicas["r1"].kill()
        pick = fe.router.pick(fresh, list(fe.replicas.values()))
        assert pick.replica_id == "r0"
        fe.replicas["r0"].kill()
        with pytest.raises(Overloaded) as err:
            fe.router.pick(fresh, list(fe.replicas.values()))
        assert err.value.reason == "no_serving_replica"
        fe.run_until_drained.__self__  # fleet left dead deliberately
