"""Encoder-decoder (T5-style) pipeline: the dual-stream 1F1B schedule
must match the single-device model exactly (reference
``ModelType.encoder_and_decoder`` in
``fwd_bwd_pipelining_without_interleaving.py:50-84`` — ranks before the
split carry the encoder stream, ranks after carry decoder stream +
forwarded encoder output — applied at the reference's own
test_pipeline_parallel_fwd_bwd.py parity standard)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from apex_tpu.models.t5 import (
    T5Config,
    init_params,
    make_pp_train_step,
    make_train_step,
    params_to_pp_layout,
    t5_loss,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer.pipeline_parallel.schedules.tick_schedule_encdec import (
    pad_stage_layout_encdec,
    unpad_stage_layout_encdec,
)

CFG = T5Config(
    vocab_size=64,
    hidden_size=32,
    num_encoder_layers=4,
    num_decoder_layers=4,
    num_attention_heads=4,
    max_src_len=16,
    max_tgt_len=12,
    compute_dtype=jnp.float32,
    checkpoint_layers=False,
)


def _data(B=8, s=16, t=12, seed=0):
    rng = np.random.RandomState(seed)
    src = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(B, s)))
    tgt = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(B, t)))
    dec_in = jnp.roll(tgt, 1, axis=1).at[:, 0].set(0)  # shift right, BOS=0
    return src, dec_in, tgt


class TestPadLayout:
    def test_round_trip(self):
        params = init_params(CFG, jax.random.PRNGKey(0))
        enc_p, dec_p = pad_stage_layout_encdec(
            params["enc_layers"], params["dec_layers"], pp=4, split=2)
        # stages 0-1 hold real encoder chunks, 2-3 zeros (mirrored: dec)
        wq = np.asarray(enc_p["wq"])
        assert wq.shape[0] == 4 * 2  # pp * lpc_e
        assert np.all(wq[4:] == 0)
        assert np.any(wq[:4] != 0)
        cw = np.asarray(dec_p["cq"])
        assert np.all(cw[:4] == 0) and np.any(cw[4:] != 0)
        enc_b, dec_b = unpad_stage_layout_encdec(enc_p, dec_p, 4, 2)
        for a, b in zip(jax.tree.leaves(enc_b),
                        jax.tree.leaves(params["enc_layers"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(dec_b),
                        jax.tree.leaves(params["dec_layers"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_bad_split_rejected(self):
        params = init_params(CFG, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="split"):
            pad_stage_layout_encdec(
                params["enc_layers"], params["dec_layers"], pp=4, split=0)


@pytest.mark.slow
class TestEncDecPipelineParity:
    def test_pp4_split2_matches_single_device(self, devices8):
        """pp=4, split=2: encoder on stages 0-1, decoder on 2-3 — one
        optimizer step must match the single-device oracle on loss AND
        every updated parameter (grad parity through the shared-tied
        embedding, both position tables, and both layer stacks)."""
        mesh = Mesh(np.array(devices8[:4]).reshape(4, 1), ("pp", "tp"))
        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        src, dec_in, tgt = _data()

        pp_params = params_to_pp_layout(params, pp=4, split=2)
        state = opt.init(pp_params)
        step = make_pp_train_step(CFG, opt, mesh, num_microbatches=4,
                                  split=2)
        new_params, _, loss = step(pp_params, state, src, dec_in, tgt)

        ref_loss, ref_grads = jax.value_and_grad(t5_loss)(
            params, src, dec_in, tgt, CFG)
        ref_params, _ = opt.update(ref_grads, opt.init(params), params)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)

        enc_u, dec_u = unpad_stage_layout_encdec(
            new_params["enc_layers"], new_params["dec_layers"], 4, 2)
        got = {**{k: v for k, v in new_params.items()
                  if k not in ("enc_layers", "dec_layers")},
               "enc_layers": enc_u, "dec_layers": dec_u}
        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(got),
            jax.tree_util.tree_leaves_with_path(ref_params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-5,
                err_msg=jax.tree_util.keystr(ka),
            )

    def test_pp2_split1_tp2_matches_single_device(self, devices8):
        """The dual-stream schedule composes with tensor parallelism:
        pp=2 (split=1) x tp=2, one step vs the oracle."""
        mesh = Mesh(np.array(devices8[:4]).reshape(2, 2), ("pp", "tp"))
        params = init_params(CFG, jax.random.PRNGKey(1))
        opt = FusedAdam(lr=1e-2)
        src, dec_in, tgt = _data(seed=1)

        pp_params = params_to_pp_layout(params, pp=2, split=1)
        state = opt.init(pp_params)
        step = make_pp_train_step(CFG, opt, mesh, num_microbatches=2,
                                  split=1)
        new_params, _, loss = step(pp_params, state, src, dec_in, tgt)

        ref_loss, ref_grads = jax.value_and_grad(t5_loss)(
            params, src, dec_in, tgt, CFG)
        ref_params, _ = opt.update(ref_grads, opt.init(params), params)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)

        enc_u, dec_u = unpad_stage_layout_encdec(
            new_params["enc_layers"], new_params["dec_layers"], 2, 1)
        np.testing.assert_allclose(
            np.asarray(enc_u["wq"]), np.asarray(ref_params["enc_layers"]["wq"]),
            rtol=5e-3, atol=5e-5)
        np.testing.assert_allclose(
            np.asarray(dec_u["cq"]), np.asarray(ref_params["dec_layers"]["cq"]),
            rtol=5e-3, atol=5e-5)
        np.testing.assert_allclose(
            np.asarray(new_params["embed"]),
            np.asarray(ref_params["embed"]), rtol=5e-3, atol=5e-5)

    def test_uneven_split_pp4_split1(self, devices8):
        """split=1: one encoder stage, three decoder stages (uneven
        split ranks are first-class, reference common.py:90)."""
        cfg = T5Config(**{**CFG.__dict__, "num_encoder_layers": 2,
                          "num_decoder_layers": 6})
        mesh = Mesh(np.array(devices8[:4]).reshape(4, 1), ("pp", "tp"))
        params = init_params(cfg, jax.random.PRNGKey(2))
        opt = FusedAdam(lr=1e-2)
        src, dec_in, tgt = _data(seed=2)

        pp_params = params_to_pp_layout(params, pp=4, split=1)
        state = opt.init(pp_params)
        step = make_pp_train_step(cfg, opt, mesh, num_microbatches=4,
                                  split=1)
        _, _, loss = step(pp_params, state, src, dec_in, tgt)
        ref_loss = t5_loss(params, src, dec_in, tgt, cfg)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)

    def test_fp16_loss_scaling_matches_oracle(self, devices8):
        """make_pp_train_step(loss_scaler=...) through the dual-stream
        pipeline vs a single-device scaled oracle: identical discrete
        scaler decisions (incl. the engineered first-step overflow and
        the held Adam counter), matching losses and params."""
        from apex_tpu.amp import DynamicLossScaler

        scaler = DynamicLossScaler(
            init_scale=2.0 ** 127, backoff_factor=2.0 ** -4,
            growth_factor=2.0, growth_interval=3, hysteresis=1,
        )
        mesh = Mesh(np.array(devices8[:4]).reshape(4, 1), ("pp", "tp"))
        params = init_params(CFG, jax.random.PRNGKey(7))
        opt = FusedAdam(lr=1e-2)
        src, dec_in, tgt = _data(seed=7)
        STEPS = 6

        # single-device scaled oracle
        o_params, o_state, o_sstate = params, opt.init(params), scaler.init()
        o_losses, o_scales = [], []

        @jax.jit
        def oracle_step(p, s, ss):
            def f(p):
                return t5_loss(p, src, dec_in, tgt, CFG) * ss.loss_scale

            sloss, grads = jax.value_and_grad(f)(p)
            grads, finite = scaler.unscale(ss, grads)
            p, s = opt.update(grads, s, p, grads_finite=finite)
            return p, s, scaler.update(ss, finite), sloss / ss.loss_scale

        for _ in range(STEPS):
            o_params, o_state, o_sstate, loss = oracle_step(
                o_params, o_state, o_sstate)
            o_losses.append(float(loss))
            o_scales.append(float(o_sstate.loss_scale))

        pp_params = params_to_pp_layout(params, pp=4, split=2)
        state, sstate = opt.init(pp_params), scaler.init()
        step = make_pp_train_step(CFG, opt, mesh, num_microbatches=4,
                                  split=2, loss_scaler=scaler)
        losses, scales = [], []
        for _ in range(STEPS):
            pp_params, state, sstate, loss = step(
                pp_params, state, sstate, src, dec_in, tgt)
            losses.append(float(loss))
            scales.append(float(sstate.loss_scale))

        np.testing.assert_array_equal(np.asarray(scales),
                                      np.asarray(o_scales))
        assert int(state.step) == int(o_state.step)
        assert np.isinf(losses[0]) and np.isinf(o_losses[0])
        np.testing.assert_allclose(losses[1:], o_losses[1:], rtol=1e-4)
        enc_u, dec_u = unpad_stage_layout_encdec(
            pp_params["enc_layers"], pp_params["dec_layers"], 4, 2)
        np.testing.assert_allclose(
            np.asarray(enc_u["wq"]),
            np.asarray(o_params["enc_layers"]["wq"]), rtol=5e-3, atol=5e-5)
        np.testing.assert_allclose(
            np.asarray(dec_u["co"]),
            np.asarray(o_params["dec_layers"]["co"]), rtol=5e-3, atol=5e-5)
        assert losses[-1] < losses[1]  # trained after the overflow step

    def test_training_reduces_loss(self, devices8):
        mesh = Mesh(np.array(devices8[:4]).reshape(4, 1), ("pp", "tp"))
        params = params_to_pp_layout(
            init_params(CFG, jax.random.PRNGKey(3)), pp=4, split=2)
        opt = FusedAdam(lr=1e-3)
        state = opt.init(params)
        src, dec_in, tgt = _data(seed=3)
        step = make_pp_train_step(CFG, opt, mesh, num_microbatches=4,
                                  split=2)
        losses = []
        for _ in range(6):
            params, state, loss = step(params, state, src, dec_in, tgt)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


@pytest.mark.slow
class TestSplitRankPlumbing:
    def test_split_from_parallel_state(self, devices8):
        """make_pp_train_step reads the split rank from parallel_state
        when not passed — the reference's is_pipeline_stage_before/
        after_split predicates and the schedule must agree."""
        from apex_tpu.transformer import parallel_state as ps

        mesh = ps.initialize_model_parallel(
            tensor_model_parallel_size_=1,
            pipeline_model_parallel_size_=4,
            pipeline_model_parallel_split_rank_=2,
        )
        try:
            assert ps.is_pipeline_stage_before_split(stage=1)
            assert not ps.is_pipeline_stage_before_split(stage=2)
            assert ps.is_pipeline_stage_after_split(stage=2)
            assert ps.is_pipeline_stage_at_split(stage=1)
            params = params_to_pp_layout(
                init_params(CFG, jax.random.PRNGKey(4)), pp=4, split=2)
            opt = FusedAdam(lr=1e-2)
            step = make_pp_train_step(CFG, opt, mesh, num_microbatches=2,
                                      pp_axis="pp", dp_axis=None)
            src, dec_in, tgt = _data(seed=4)
            _, _, loss = step(params, opt.init(params), src, dec_in, tgt)
            assert np.isfinite(float(loss))
        finally:
            ps.destroy_model_parallel()

    def test_missing_split_rejected(self, devices8):
        mesh = Mesh(np.array(devices8[:4]).reshape(4, 1), ("pp", "tp"))
        with pytest.raises(ValueError, match="split"):
            make_pp_train_step(CFG, FusedAdam(lr=1e-2), mesh,
                               num_microbatches=2)


class TestT5Oracle:
    def test_loss_finite_and_causal(self):
        """The oracle itself: future target tokens must not influence
        earlier logits (decoder causality), and cross-attention must
        see the source (changing src changes the loss)."""
        params = init_params(CFG, jax.random.PRNGKey(5))
        src, dec_in, tgt = _data(B=2, seed=5)
        from apex_tpu.models.t5 import t5_forward

        logits = t5_forward(params, src, dec_in, CFG)
        assert np.all(np.isfinite(np.asarray(logits)))
        # causality: perturb the LAST decoder input token; logits at
        # position 0 must not change
        dec_in2 = dec_in.at[:, -1].set((dec_in[:, -1] + 1) % CFG.vocab_size)
        logits2 = t5_forward(params, src, dec_in2, CFG)
        np.testing.assert_allclose(
            np.asarray(logits[0]), np.asarray(logits2[0]), atol=1e-5)
        assert np.max(np.abs(np.asarray(logits[-1]) - np.asarray(logits2[-1]))) > 1e-6
        # cross-attention: a different source must move the loss
        src2 = (src + 1) % CFG.vocab_size
        l1 = float(t5_loss(params, src, dec_in, tgt, CFG))
        l2 = float(t5_loss(params, src2, dec_in, tgt, CFG))
        assert abs(l1 - l2) > 1e-6

    def test_single_device_train_step(self):
        params = init_params(CFG, jax.random.PRNGKey(6))
        opt = FusedAdam(lr=1e-3)
        step = make_train_step(CFG, opt)
        state = opt.init(params)
        src, dec_in, tgt = _data(B=4, seed=6)
        losses = []
        for _ in range(5):
            params, state, loss = step(params, state, src, dec_in, tgt)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
