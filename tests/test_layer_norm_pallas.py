"""Pallas LN kernel tests (interpret mode on CPU; the real-TPU run is
exercised by bench/driver).  Parity vs the jnp specification."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.layer_norm_pallas import (
    _pick_block_r,
    layer_norm_bwd_pallas,
    layer_norm_fwd_pallas,
)


def test_pick_block_r_fits_vmem():
    assert _pick_block_r(8192, 4096, 256) * 4096 * 32 <= 8 * 1024 * 1024
    assert _pick_block_r(1024, 1024, 256) == 128  # VMEM budget caps it
    assert 8192 % _pick_block_r(8192, 4096, 256) == 0


@pytest.mark.parametrize("rms", [False, True])
def test_fwd_interpret_matches_reference(rms):
    R, H = 32, 128
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(R, H).astype(np.float32))
    w = jnp.asarray(rng.rand(H).astype(np.float32) + 0.5)
    b = None if rms else jnp.asarray(rng.randn(H).astype(np.float32))
    y, mean, rstd = layer_norm_fwd_pallas(x, w, b, 1e-5, rms=rms, block_r=16, interpret=True)

    if rms:
        var = jnp.mean(x * x, 1, keepdims=True)
        ref = x * jax.lax.rsqrt(var + 1e-5) * w
    else:
        mu = jnp.mean(x, 1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, 1, keepdims=True)
        ref = (x - mu) * jax.lax.rsqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_bwd_interpret_matches_autodiff():
    R, H = 32, 128
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(R, H).astype(np.float32))
    w = jnp.asarray(rng.rand(H).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(H).astype(np.float32))
    dy = jnp.asarray(rng.randn(R, H).astype(np.float32))

    _, mean, rstd = layer_norm_fwd_pallas(x, w, b, 1e-5, block_r=16, interpret=True)
    dx, dw_acc, db_acc = layer_norm_bwd_pallas(x, w, dy, mean, rstd, block_r=16, interpret=True)

    def f(x, w, b):
        mu = jnp.mean(x, 1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, 1, keepdims=True)
        return jnp.sum(((x - mu) * jax.lax.rsqrt(var + 1e-5) * w + b) * dy)

    gx, gw, gb = jax.grad(f, (0, 1, 2))(x, w, b)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(gx), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dw_acc.sum(0)), np.asarray(gw), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(db_acc.sum(0)), np.asarray(gb), rtol=1e-4, atol=1e-4)
