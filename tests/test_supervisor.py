"""Self-healing supervisor (`apex_tpu.resilience.supervisor`) — the
restart state machine driven deterministically with fake children, a
pinned clock, and the rng seam, plus the checkpoint corruption-probe /
quarantine layer it invokes (`io.probe_checkpoint` / `io
.probe_checkpoint_dir` / `io.quarantine_checkpoint`) on real files.

Everything here is quick-tier: no subprocesses, no jitted steps — the
process-level gauntlet (ONE ``pretrain_gpt.py --supervise`` surviving
kill → wedge → corrupt-checkpoint) lives in tests/test_gpt_example.py.
"""

import json
import random
import subprocess

import numpy as np
import pytest

from apex_tpu import io
from apex_tpu.resilience import (
    EXIT_CRASH_LOOP,
    EXIT_KILLED,
    EXIT_WEDGED,
    Supervisor,
    SupervisorFault,
    SupervisorFaultScript,
    corrupt_newest_checkpoint,
    restart_backoff,
    strip_supervisor_argv,
)


class FakeChild:
    def __init__(self, rc):
        self.rc = rc
        self.terminated = 0
        self.killed = 0

    def wait(self, timeout=None):
        return self.rc

    def terminate(self):
        self.terminated += 1

    def kill(self):
        self.killed += 1


class MaxJitter:
    """rng seam pinning the jitter to its upper bound: delays become
    exactly ``min(cap, base * 2**attempt)``."""

    def uniform(self, a, b):
        return b


def make_sup(codes, *, progress=None, spawned=None, sleeps=None, **kw):
    """Supervisor over a scripted sequence of child exit codes."""
    it = iter(codes)
    spawned = spawned if spawned is not None else []
    sleeps = sleeps if sleeps is not None else []

    def spawn(argv):
        child = FakeChild(next(it))
        spawned.append((list(argv), child))
        return child

    kw.setdefault("rng", MaxJitter())
    kw.setdefault("backoff_base", 1.0)
    kw.setdefault("backoff_cap", 8.0)
    kw.setdefault("progress_fn", progress if progress is not None
                  else lambda: 0)
    return Supervisor(["trainer", "--flag"], spawn_fn=spawn,
                      sleep_fn=sleeps.append, time_fn=lambda: 0.0, **kw)


class TestStateMachine:
    def test_clean_exit_no_restart(self):
        sleeps = []
        sup = make_sup([0], sleeps=sleeps)
        assert sup.run() == 0
        assert sup.restarts == 0 and sleeps == []

    def test_wedged_then_clean_restarts_with_pinned_backoff(self):
        """Exit 75 → ONE restart after exactly restart_backoff(0) (the
        rng seam pins the jitter), then the clean child ends the job."""
        sleeps = []
        sup = make_sup([EXIT_WEDGED, 0], sleeps=sleeps)
        assert sup.run() == 0
        assert sup.restarts == 1
        assert sleeps == [restart_backoff(0, base=1.0, cap=8.0,
                                          rng=MaxJitter())] == [1.0]

    def test_killed_then_clean(self):
        sup = make_sup([EXIT_KILLED, 0])
        assert sup.run() == 0 and sup.restarts == 1

    def test_unknown_nonzero_also_restarts(self):
        """The tentpole table: any nonzero restarts (the breaker, not
        the code, bounds environmental crash damage)."""
        sup = make_sup([3, 0])
        assert sup.run() == 0 and sup.restarts == 1

    def test_crash_loop_trips_breaker_with_pinned_schedule(self):
        """The acceptance contract: K consecutive no-progress failures
        exit EXIT_CRASH_LOOP after a deterministic backoff schedule —
        never an unbounded restart loop.  K=3 → exactly two sleeps
        (restart_backoff(0), restart_backoff(1) at max jitter), then
        the breaker, with no third sleep."""
        sleeps = []
        sup = make_sup([1, 1, 1], sleeps=sleeps, crash_loop_threshold=3)
        assert sup.run() == EXIT_CRASH_LOOP
        assert sup.restarts == 2
        assert sleeps == [1.0, 2.0]  # min(8, 1*2^0), min(8, 1*2^1)

    def test_backoff_respects_cap(self):
        sleeps = []
        sup = make_sup([1] * 6, sleeps=sleeps, crash_loop_threshold=6,
                       backoff_base=1.0, backoff_cap=4.0)
        assert sup.run() == EXIT_CRASH_LOOP
        assert sleeps == [1.0, 2.0, 4.0, 4.0, 4.0]

    def test_progress_resets_the_streak(self):
        """A child that banked new steps before dying is NOT a crash
        loop: the streak resets and the job survives more failures
        than the threshold."""
        state = {"p": 0}

        def progress():
            state["p"] += 1  # every relaunch advanced the run
            return state["p"]

        sup = make_sup([EXIT_KILLED] * 5 + [0], progress=progress,
                       crash_loop_threshold=2)
        assert sup.run() == 0
        assert sup.restarts == 5

    def test_max_restarts_exhaustion_returns_child_code(self):
        sup = make_sup([9, 9], max_restarts=1, crash_loop_threshold=99)
        assert sup.run() == 9
        assert sup.restarts == 1

    def test_repeated_wedge_at_same_progress_lengthens_backoff(self):
        """The goodput-adaptive rule: a second wedge with NO new
        progress doubles the (already longer) backoff and a third
        triples it — hammering a deterministic wedge is how pods
        burn."""
        sleeps = []
        sup = make_sup([EXIT_WEDGED, EXIT_WEDGED, EXIT_WEDGED, 0],
                       sleeps=sleeps, crash_loop_threshold=99)
        assert sup.run() == 0
        # streaks 1,2,3 → base delays 1, 2, 4; wedge repeats 0,1,2 →
        # factors 1, 2, 3
        assert sleeps == [1.0, 4.0, 12.0]

    def test_wedge_at_new_progress_does_not_lengthen(self):
        seen = iter([1, 2, 3])
        sleeps = []
        sup = make_sup([EXIT_WEDGED, EXIT_WEDGED, 0], sleeps=sleeps,
                       progress=lambda: next(seen), crash_loop_threshold=9)
        assert sup.run() == 0
        assert sleeps == [1.0, 1.0]  # streak resets, no repeat factor

    def test_sigterm_forwarded_once_then_grace_kill(self):
        """The drain contract: SIGTERM forwards to the child EXACTLY
        once (resent notices are absorbed), SIGKILL lands only after
        the grace window, and the supervisor never restarts a child it
        was asked to stop — it reports the child's final code."""
        clock = {"t": 0.0}
        holder = {}

        class HangingChild:
            def __init__(self):
                self.terminated = 0
                self.killed = 0

            def wait(self, timeout=None):
                if self.killed:
                    return 137
                sup = holder["sup"]
                sup.request_stop()
                sup.request_stop()  # schedulers resend the notice
                clock["t"] += 1.0   # each poll advances the clock
                raise subprocess.TimeoutExpired(cmd="x", timeout=timeout)

            def terminate(self):
                self.terminated += 1

            def kill(self):
                self.killed += 1

        child = HangingChild()
        sup = Supervisor(["trainer"], grace_sec=2.5,
                         spawn_fn=lambda argv: child,
                         sleep_fn=lambda s: None,
                         time_fn=lambda: clock["t"],
                         progress_fn=lambda: 0)
        holder["sup"] = sup
        assert sup.run() == 137
        assert child.terminated == 1, "SIGTERM must forward exactly once"
        assert child.killed == 1, "grace expiry must SIGKILL"
        assert sup.restarts == 0, "a stopped child is never restarted"

    def test_stop_during_backoff_prevents_respawn(self):
        spawned = []

        def sleep(_):
            sup.request_stop()

        it = iter([EXIT_WEDGED])

        def spawn(argv):
            c = FakeChild(next(it))
            spawned.append(c)
            return c

        sup = Supervisor(["t"], spawn_fn=spawn, sleep_fn=sleep,
                         time_fn=lambda: 0.0, progress_fn=lambda: 0,
                         rng=MaxJitter())
        assert sup.run() == EXIT_WEDGED
        assert len(spawned) == 1
        # no relaunch happened, so none may be counted
        assert sup.restarts == 0

    def test_stop_before_first_spawn_launches_nothing(self):
        """SIGTERM landing before the (first) spawn — e.g. during a
        slow progress read — must not launch a child the scheduler
        already wants dead."""
        spawned = []
        sup = Supervisor(["t"],
                         spawn_fn=lambda argv: spawned.append(argv),
                         sleep_fn=lambda s: None, time_fn=lambda: 0.0,
                         progress_fn=lambda: 0)
        sup.request_stop()
        assert sup.run() == 0
        assert spawned == [] and sup.restarts == 0

    def test_stop_racing_the_spawn_still_forwards_term(self):
        """SIGTERM arriving while _spawn is in flight (the handler saw
        _child=None): the fresh child must still get the TERM + grace
        contract."""
        child = FakeChild(143)

        def spawn(argv):
            # the signal lands "during" the spawn call
            sup._stop_requested = True
            return child

        sup = Supervisor(["t"], spawn_fn=spawn, sleep_fn=lambda s: None,
                         time_fn=lambda: 0.0, progress_fn=lambda: 0)
        assert sup.run() == 143
        assert child.terminated == 1
        assert sup.restarts == 0

    def test_signal_death_returncode_normalized_to_128_plus_sig(self):
        """Popen reports a signal death as -SIGNUM; the supervisor must
        speak the process table's 128+SIGNUM — a raw -9 would garble
        the final exit status (SystemExit(-9) exits 247) and 137 would
        never match a REAL SIGKILL."""
        sup = make_sup([-9, -9], max_restarts=1, crash_loop_threshold=99)
        assert sup.run() == 137  # 128 + SIGKILL, reported as-is

    def test_long_healthy_runtime_counts_as_progress(self):
        """The stateless-child (serving) breaker contract: a child that
        RAN past min_healthy_runtime_sec before failing resets the
        streak even with no step counters — three transient wedges
        days apart must not add up to a circuit-breaker trip."""
        clock = {"t": 0.0}
        children = iter([EXIT_WEDGED] * 5 + [0])

        class LongChild(FakeChild):
            def wait(self, timeout=None):
                clock["t"] += 100.0  # each child "serves" 100s
                return self.rc

        sup = Supervisor(["server"],
                         spawn_fn=lambda argv: LongChild(next(children)),
                         sleep_fn=lambda s: None,
                         time_fn=lambda: clock["t"],
                         progress_fn=lambda: 0,  # stateless: no steps
                         min_healthy_runtime_sec=60.0,
                         crash_loop_threshold=2, rng=MaxJitter())
        assert sup.run() == 0
        assert sup.restarts == 5  # survived 5 wedges, no breaker

    def test_fast_failing_stateless_child_still_trips_breaker(self):
        """...while a child that dies FASTER than the healthy-runtime
        floor, with no progress, is still a crash loop."""
        clock = {"t": 0.0}

        class FastChild(FakeChild):
            def wait(self, timeout=None):
                clock["t"] += 1.0  # dies in 1s, floor is 60s
                return self.rc

        sup = Supervisor(["server"],
                         spawn_fn=lambda argv: FastChild(1),
                         sleep_fn=lambda s: None,
                         time_fn=lambda: clock["t"],
                         progress_fn=lambda: 0,
                         min_healthy_runtime_sec=60.0,
                         crash_loop_threshold=3, rng=MaxJitter())
        assert sup.run() == EXIT_CRASH_LOOP
        assert sup.restarts == 2

    def test_broken_progress_fn_degrades_not_crashes(self):
        def boom():
            raise OSError("metrics volume gone")

        sup = make_sup([1, 1], progress=boom, crash_loop_threshold=2)
        assert sup.run() == EXIT_CRASH_LOOP  # degraded to "no progress"

    def test_validation(self):
        with pytest.raises(ValueError, match="crash_loop_threshold"):
            Supervisor(["x"], crash_loop_threshold=0)
        with pytest.raises(ValueError, match="max_restarts"):
            Supervisor(["x"], max_restarts=-1)


# --------------------------------------------------------- fault scripts
class TestFaultScript:
    def test_per_attempt_args_are_appended_once(self):
        spawned = []
        script = SupervisorFaultScript.from_dict({
            "0": {"args": ["--chaos-kill-at-step", "3"]},
        })
        sup = make_sup([EXIT_KILLED, 0], spawned=spawned,
                       fault_script=script)
        assert sup.run() == 0
        assert spawned[0][0] == ["trainer", "--flag",
                                 "--chaos-kill-at-step", "3"]
        assert spawned[1][0] == ["trainer", "--flag"]  # attempt 1 clean

    def test_from_dict_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown key"):
            SupervisorFaultScript.from_dict({"0": {"argz": []}})

    def test_corrupt_without_checkpoint_dir_refused(self):
        script = SupervisorFaultScript.from_dict(
            {"0": {"corrupt_newest_checkpoint": True}})
        sup = make_sup([0], fault_script=script)
        with pytest.raises(ValueError, match="checkpoint_dir"):
            sup.run()

    def test_from_file_round_trip(self, tmp_path):
        p = tmp_path / "faults.json"
        p.write_text(json.dumps({"2": {"args": ["--x"],
                                       "corrupt_newest_checkpoint": True}}))
        s = SupervisorFaultScript.from_file(p)
        assert s.fault_for(0) is None
        f = s.fault_for(2)
        assert f.extra_args == ("--x",) and f.corrupt_newest_checkpoint


# ------------------------------------------------- corruption + quarantine
def _tree(seed):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(16, 8).astype(np.float32),
            "b": rng.randn(32).astype(np.float32)}


def _publish_step(dir_path, step, world=1):
    for r in range(world):
        io.save_sharded_checkpoint(
            f"{dir_path}/step_{step:08d}", _tree(step * 10 + r), r, world)


class TestCorruptionProbe:
    def test_probe_passes_healthy_and_crc_catches_bit_flips(self, tmp_path):
        p = tmp_path / "step_00000001.ckpt"
        io.save_checkpoint(p, _tree(0))
        io.probe_checkpoint(p)  # healthy: no raise
        size = p.stat().st_size
        corrupt_newest_checkpoint(tmp_path)  # size-preserving flip
        assert p.stat().st_size == size, "the fault must preserve size"
        io.validate_checkpoint(p)  # shallow check CANNOT see it ...
        with pytest.raises(ValueError, match="crc32"):
            io.probe_checkpoint(p)  # ... the deep probe can
        with pytest.raises(ValueError, match="crc32"):
            io.load_checkpoint(p)  # and a restore fails loudly too

    def test_probe_dir_names_newest_complete_step_dir(self, tmp_path):
        _publish_step(tmp_path, 1, world=2)
        _publish_step(tmp_path, 2, world=2)
        assert io.probe_checkpoint_dir(tmp_path) is None
        corrupt_newest_checkpoint(tmp_path)
        bad = io.probe_checkpoint_dir(tmp_path)
        assert bad is not None
        assert bad.path.endswith("step_00000002")
        assert "crc32" in bad.reason

    def test_probe_dir_nothing_to_probe(self, tmp_path):
        assert io.probe_checkpoint_dir(tmp_path / "missing") is None
        assert io.probe_checkpoint_dir(tmp_path) is None  # empty dir

    def test_quarantine_moves_dir_and_writes_reason(self, tmp_path):
        _publish_step(tmp_path, 1)
        _publish_step(tmp_path, 2)
        corrupt_newest_checkpoint(tmp_path)
        bad = io.probe_checkpoint_dir(tmp_path)
        dest = io.quarantine_checkpoint(tmp_path, bad.path, bad.reason)
        assert not (tmp_path / "step_00000002").exists()
        assert (tmp_path / "quarantine" / "step_00000002").exists()
        reason = json.loads(
            (tmp_path / "quarantine"
             / "step_00000002.reason.json").read_text())
        assert "crc32" in reason["reason"] and reason["quarantined_to"] == dest
        # the dir is healthy again: the next restore resumes from step
        # 1 (quarantine/'s contents are not step_* dirs of this root,
        # so they are never restore candidates)
        assert io.probe_checkpoint_dir(tmp_path) is None
        assert io.latest_distributed_step(tmp_path) == 1

    def test_supervisor_quarantines_after_failure(self, tmp_path):
        """The integrated path: child fails, the default probe finds
        the corrupt newest step dir, the supervisor quarantines it and
        the relaunch proceeds."""
        _publish_step(tmp_path, 1)
        _publish_step(tmp_path, 2)
        corrupt_newest_checkpoint(tmp_path)
        sup = make_sup([1, 0], checkpoint_dir=tmp_path,
                       crash_loop_threshold=5)
        assert sup.run() == 0
        assert len(sup.quarantined) == 1
        assert sup.quarantined[0].endswith("step_00000002")
        assert io.latest_distributed_step(tmp_path) == 1

    def test_corrupt_newest_requires_a_checkpoint(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            corrupt_newest_checkpoint(tmp_path)

    def test_incomplete_only_publish_is_quarantined_not_crash_looped(
            self, tmp_path):
        """A hard kill can interrupt the FIRST publish: step dirs exist
        but none is complete, so the resume side refuses loudly by
        design — which under a supervisor would crash-loop forever.
        The probe reports the newest incomplete dir for quarantine;
        the relaunch starts fresh with the bytes preserved."""
        _publish_step(tmp_path, 1, world=2)
        (tmp_path / "step_00000001"
         / "shard_00001-of-00002.ckpt").unlink()  # the un-flushed shard
        with pytest.raises(io.AllCheckpointsTornError):
            io.latest_distributed_step(tmp_path)  # the child's crash
        bad = io.probe_checkpoint_dir(tmp_path)
        assert bad is not None and bad.path.endswith("step_00000001")
        assert "incomplete publish" in bad.reason
        sup = make_sup([1, 0], checkpoint_dir=tmp_path,
                       crash_loop_threshold=5)
        assert sup.run() == 0
        assert (tmp_path / "quarantine" / "step_00000001").exists()
        assert io.latest_distributed_step(tmp_path) == -1  # fresh start

    def test_incomplete_dir_is_not_progress(self, tmp_path):
        """The default progress signal must count only COMPLETE
        checkpoints: a hard kill's half-published newest dir looking
        like progress would skip the quarantine probe and cost an
        extra crash (seen as a bench flake under load: 2 restarts
        where the contract says 1)."""
        sup = make_sup([0], checkpoint_dir=tmp_path,
                       progress_fn=None)  # None -> the real default
        _publish_step(tmp_path, 1, world=2)
        (tmp_path / "step_00000001"
         / "shard_00001-of-00002.ckpt").unlink()
        assert sup._default_progress() == 0  # incomplete: not progress
        _publish_step(tmp_path, 2, world=2)  # a complete dir counts
        assert sup._default_progress() == 2

    def test_kill_into_incomplete_publish_heals_in_one_restart(
            self, tmp_path):
        """The full cycle the bench pins: attempt 0's kill interrupts
        the only publish; the supervisor must see NO progress, probe,
        quarantine, and succeed on attempt 1 — exactly one restart."""
        _publish_step(tmp_path, 1, world=2)
        (tmp_path / "step_00000001"
         / "shard_00001-of-00002.ckpt").unlink()
        sup = make_sup([EXIT_KILLED, 0], checkpoint_dir=tmp_path,
                       progress_fn=None, crash_loop_threshold=3)
        assert sup.run() == 0
        assert sup.restarts == 1
        assert (tmp_path / "quarantine" / "step_00000001").exists()

    def test_incomplete_newest_with_complete_sibling_not_quarantined(
            self, tmp_path):
        """When a COMPLETE dir exists, the resume side already skips
        the incomplete newest one — the probe must leave it alone (it
        may even still be mid-flush from the killed writer's queue)."""
        _publish_step(tmp_path, 1, world=2)
        _publish_step(tmp_path, 2, world=2)
        (tmp_path / "step_00000002"
         / "shard_00001-of-00002.ckpt").unlink()
        assert io.probe_checkpoint_dir(tmp_path) is None
        assert io.latest_distributed_step(tmp_path) == 1


# ----------------------------------------------------------- small seams
class TestSeams:
    def test_restart_backoff_rng_seam_pins_delays(self):
        """The satellite contract: rng= overrides the per-(seed,
        attempt) derivation, existing callers unchanged."""
        a = [restart_backoff(k, base=2.0, cap=30.0,
                             rng=random.Random(123)) for k in range(5)]
        b = [restart_backoff(k, base=2.0, cap=30.0,
                             rng=random.Random(123)) for k in range(5)]
        assert a == b
        for k, v in enumerate(a):
            assert 0.0 <= v <= min(30.0, 2.0 * 2 ** k)
        assert restart_backoff(2, base=4.0, cap=99.0,
                               rng=MaxJitter()) == 16.0
        # the seeded path is byte-for-byte the pre-seam behavior
        assert restart_backoff(3, seed=7) == restart_backoff(3, seed=7)

    def test_strip_supervisor_argv_both_spellings(self):
        argv = ["--supervise", "--steps", "6", "--max-restarts", "4",
                "--backoff-base=0.5", "--zero", "--fault-script",
                "f.json", "--checkpoint", "ck"]
        assert strip_supervisor_argv(argv) == [
            "--steps", "6", "--zero", "--checkpoint", "ck"]

    def test_fault_dataclass_defaults(self):
        f = SupervisorFault()
        assert f.extra_args == () and not f.corrupt_newest_checkpoint
