"""apex_tpu.observability: metrics registry, device-side StepStats
telemetry, async fetch, goodput accounting, serving metrics.

The load-bearing bands:

- **Parity**: telemetry-on vs telemetry-off train steps produce
  BITWISE-identical loss and params in fp32 — stats are observers,
  never participants — including the ZeRO + int8-sync engine and the
  StepGuard/chaos composition (the collective/host-transfer side of
  the same contract is pinned in tests/test_lowered_invariants.py).
- **Goodput closure**: the report's fractions sum to exactly 1 over
  the run's wall clock, with a wedged session's tail and the
  inter-session gap attributed to their causes.
"""

import json
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from apex_tpu.models.gpt import GPTConfig, init_params, make_train_step
from apex_tpu.observability import correlation, goodput, metrics, stepstats
from apex_tpu.optimizers import FusedAdam


# ------------------------------------------------------------------ metrics
class TestMetricsRegistry:
    def test_counter_gauge_histogram_round_trip(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("t_total", "help", ("k",))
        c.inc(k="a")
        c.inc(2.5, k="a")
        c.inc(k="b")
        assert c.value(k="a") == 3.5 and c.value(k="b") == 1.0
        g = reg.gauge("t_gauge")
        g.set(7.0)
        g.set(3.0)
        assert g.value() == 3.0
        h = reg.histogram("t_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        samples = {(n, tuple(sorted(l.items()))): v
                   for n, l, v in h.samples()}
        assert samples[("t_seconds_bucket", (("le", "0.1"),))] == 1
        assert samples[("t_seconds_bucket", (("le", "1"),))] == 2
        assert samples[("t_seconds_bucket", (("le", "+Inf"),))] == 3
        assert samples[("t_seconds_count", ())] == 3
        assert samples[("t_seconds_sum", ())] == pytest.approx(5.55)

    def test_counter_cannot_decrease_and_kind_clash_is_loud(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("x_total")
        with pytest.raises(ValueError, match="cannot decrease"):
            c.inc(-1)
        assert reg.counter("x_total") is c  # get-or-create
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")
        with pytest.raises(ValueError, match="do not match"):
            reg.counter("y_total", labelnames=("a",)).inc(b=1)

    def test_prometheus_text_format(self):
        reg = metrics.MetricsRegistry()
        reg.counter("apex_t_total", "things", ("kind",)).inc(kind="x")
        reg.histogram("apex_l_seconds", buckets=(1.0,)).observe(0.5)
        txt = reg.prometheus_text()
        assert "# HELP apex_t_total things" in txt
        assert "# TYPE apex_t_total counter" in txt
        assert '# TYPE apex_l_seconds histogram' in txt
        assert 'apex_t_total{kind="x",rank="0"} 1' in txt
        assert 'apex_l_seconds_bucket{le="+Inf",rank="0"} 1' in txt
        assert txt.endswith("\n")

    def test_snapshot_jsonl_carries_correlation(self, tmp_path):
        reg = metrics.MetricsRegistry()
        reg.gauge("apex_t_g").set(2.0)
        correlation.set_step_context(run_id="r1", step=17)
        try:
            p = tmp_path / "m.jsonl"
            n = reg.snapshot_jsonl(p, extra_field="x")
            assert n == 1
            rec = json.loads(p.read_text())
            assert rec["metric"] == "apex_t_g" and rec["value"] == 2.0
            assert rec["run_id"] == "r1" and rec["step"] == 17
            assert rec["extra_field"] == "x" and "ts" in rec
        finally:
            correlation.clear_step_context()

    def test_module_helpers_are_best_effort(self):
        """The retrofit helpers must never alter the caller's control
        flow: a registry clash (here: the name is already a gauge) logs
        once and returns instead of raising into the fallback/watchdog/
        drain path that recorded through them."""
        with metrics.MetricsScope() as reg:
            reg.gauge("apex_clash")            # pre-register as gauge
            metrics.inc("apex_clash")          # kind clash: no raise
            metrics.observe("apex_clash", 1.0)  # no raise either
            # direct registry use stays STRICT
            with pytest.raises(ValueError, match="already registered"):
                reg.counter("apex_clash")

    def test_histogram_bucket_clash_is_loud(self):
        reg = metrics.MetricsRegistry()
        reg.histogram("apex_h_seconds", buckets=(1.0, 2.0))
        assert reg.histogram("apex_h_seconds",
                             buckets=(2.0, 1.0)) is not None  # same set
        with pytest.raises(ValueError, match="different bounds"):
            reg.histogram("apex_h_seconds", buckets=(0.5,))

    def test_scope_isolates_module_helpers(self):
        with metrics.MetricsScope() as reg:
            metrics.inc("apex_scoped_total", kind="a")
            assert metrics.get_metrics() is reg
            assert reg.counter(
                "apex_scoped_total", labelnames=("kind",)).value(
                    kind="a") == 1
        # outside the scope, the default registry did not see it
        assert metrics.get_metrics() is not reg

    def test_log_structured_merges_step_context(self):
        import logging

        from apex_tpu.utils.logging import get_logger, log_structured

        logger = get_logger("apex_tpu.t")
        records = []
        handler = logging.Handler()
        handler.emit = records.append  # the apex logger never propagates
        logger.addHandler(handler)
        correlation.set_step_context(run_id="corr", step=5)
        try:
            log_structured(logger, logging.WARNING, "evt", a=1)
        finally:
            correlation.clear_step_context()
            logger.removeHandler(handler)
        payload = json.loads(records[-1].getMessage().split(" ", 1)[1])
        assert payload == {"a": 1, "run_id": "corr", "step": 5}

    def test_scrape_is_a_consistent_snapshot_under_hammer(self):
        """Two-thread hammer for the torn-scrape race: a writer thread
        (the watchdog shape) observes a CONSTANT value into a
        histogram and bumps a counter while the main thread scrapes.
        Every observation lands v=1.0 in the (0.5, 1.5) bucket, so a
        consistent snapshot must satisfy bucket{le=1.5} == count and
        sum == count EXACTLY — the pre-fix lazy expansion (children
        copied under the lock, buckets/sum/count read outside it)
        tears mid-observe and breaks the invariant."""
        import re
        import sys
        import threading

        reg = metrics.MetricsRegistry()
        h = reg.histogram("apex_hammer_seconds", buckets=(0.5, 1.5))
        c = reg.counter("apex_hammer_total")
        stop = threading.Event()
        # shrink the GIL switch interval so the writer interleaves
        # into any unlocked window (the pre-fix tear reproduces in
        # ~20k scrapes at 1µs; at the 5ms default it hides for hours)
        prev_switch = sys.getswitchinterval()
        sys.setswitchinterval(1e-6)

        def writer():
            while not stop.is_set():
                h.observe(1.0)
                c.inc()
                reg.gauge(f"apex_g_{threading.get_ident() % 7}").set(1)

        t = threading.Thread(target=writer, name="hammer-writer")
        t.start()
        try:
            for _ in range(300):
                txt = reg.prometheus_text()

                def val(pat, txt=txt):
                    m = re.search(pat + r"\S* (\S+)", txt)
                    return None if m is None else float(m.group(1))

                count = val(r"apex_hammer_seconds_count")
                if count is None:
                    continue  # scrape ran before the first observe
                # torn scrape: the cumulative buckets, the +Inf
                # bucket, _sum and _count disagree with each other
                assert val(
                    r'apex_hammer_seconds_bucket\{le="1\.5"') == count, txt
                assert val(
                    r'apex_hammer_seconds_bucket\{le="\+Inf"') == count, txt
                assert val(r"apex_hammer_seconds_sum") \
                    == pytest.approx(count), txt
        finally:
            stop.set()
            t.join()
            sys.setswitchinterval(prev_switch)

    def test_nvtx_range_suffix(self):
        from apex_tpu.utils.profiler import nvtx_range

        correlation.set_step_context(run_id="r-2", step=3)
        try:
            assert correlation.span_suffix() == ".run_r-2.s3"
            with nvtx_range("fwd"):   # must not raise with the suffix
                pass
        finally:
            correlation.clear_step_context()
        assert correlation.span_suffix() == ""


# ---------------------------------------------------------------- stepstats
class TestStepStats:
    def test_accumulate_window_math(self):
        tel = stepstats.StepTelemetry(norms=False)
        s = tel.init()
        s = tel.accumulate(s, loss=jnp.float32(2.0),
                           grad_norm=jnp.float32(3.0),
                           finite=jnp.bool_(True),
                           loss_scale=jnp.float32(8.0))
        s = tel.accumulate(s, loss=jnp.float32(4.0),
                           grad_norm=jnp.float32(5.0),
                           finite=jnp.bool_(False),
                           loss_scale=jnp.float32(4.0))
        assert int(s.steps) == 2 and int(s.notfinite) == 1
        assert float(s.loss_sum) == 6.0 and float(s.loss_last) == 4.0
        assert float(s.grad_norm_sum) == 8.0
        assert float(s.grad_norm_last) == 5.0
        assert float(s.loss_scale) == 4.0

    def test_accumulate_absent_optionals(self):
        tel = stepstats.StepTelemetry(norms=False)
        s = tel.accumulate(tel.init(), loss=jnp.float32(1.0))
        assert int(s.steps) == 1 and int(s.notfinite) == 0
        assert math.isnan(float(s.grad_norm_last))
        assert math.isnan(float(s.loss_scale))

    def test_param_update_norms(self):
        tel = stepstats.StepTelemetry(norms=True)
        old = {"a": jnp.asarray([3.0, 4.0])}
        new = {"a": jnp.asarray([3.0, 4.0]) + 1.0}
        s = tel.accumulate(tel.init(), loss=jnp.float32(0.0),
                           new_params=new, old_params=old)
        assert float(s.param_norm) == pytest.approx(
            float(jnp.sqrt(jnp.sum(jnp.square(new["a"])))))
        assert float(s.update_norm) == pytest.approx(np.sqrt(2.0))

    def test_init_buffers_are_distinct(self):
        # shared zero buffers would double-donate through the step
        s = stepstats.StepTelemetry().init()
        leaves = jax.tree.leaves(s)
        f32 = [x for x in leaves if x.dtype == jnp.float32]
        assert len({x.unsafe_buffer_pointer() for x in f32}) == len(f32)

    def test_summary_and_emit(self):
        tel = stepstats.StepTelemetry(norms=False)
        s = tel.accumulate(tel.init(), loss=jnp.float32(2.0),
                           grad_norm=jnp.float32(1.0),
                           finite=jnp.bool_(True))
        tree = jax.tree.map(np.asarray, s._asdict())
        reg = metrics.MetricsRegistry()
        summ = stepstats.StepTelemetry.emit(reg, tree)
        assert summ["loss_mean"] == 2.0 and summ["bad_steps"] == 0
        assert reg.gauge("apex_train_loss").value() == 2.0
        assert reg.counter("apex_train_steps_total").value() == 1

    def test_capture_seam(self):
        assert not stepstats.capturing()
        stepstats.offer("x", 1)  # no-op outside capture
        with stepstats.capture() as cap:
            assert stepstats.capturing()
            stepstats.offer("grad_norm", 7)
            with stepstats.capture() as inner:
                stepstats.offer("grad_norm", 9)
            assert inner == {"grad_norm": 9}
        assert cap == {"grad_norm": 7}
        assert not stepstats.capturing()


class TestAsyncFetcher:
    def test_fifo_harvest_and_flush(self):
        f = stepstats.AsyncFetcher()
        f.put("loss", 0, {"loss": jnp.float32(1.0)})
        f.put("loss", 1, {"loss": jnp.float32(2.0)})
        got = f.ready()
        assert [(k, s) for k, s, _ in got] == [("loss", 0), ("loss", 1)]
        assert isinstance(got[0][2]["loss"], np.ndarray)
        assert float(got[1][2]["loss"]) == 2.0
        f.put("stats", 2, {"v": jnp.int32(3)})
        rest = f.flush()
        assert len(f) == 0 and rest[0][:2] == ("stats", 2)

    def test_non_jax_leaves_pass_through(self):
        f = stepstats.AsyncFetcher()
        f.put("x", 0, {"a": 1.5})
        (_, _, tree), = f.ready()
        assert float(tree["a"]) == 1.5

    def test_concurrent_flush_never_drops_or_doubles(self):
        """The exit-path race (APX114's shape, fixed by the internal
        lock): the loop thread harvests with ready() while an exit
        path (preemption drain, watchdog) calls flush() concurrently.
        Every window must be harvested by EXACTLY ONE caller, and
        each caller's batch must stay FIFO by step."""
        import threading

        for _ in range(20):
            f = stepstats.AsyncFetcher()
            n = 200
            for i in range(n):
                f.put("w", i, {"v": float(i)})
            batches = {}
            barrier = threading.Barrier(2)

            def harvest(name, fn):
                barrier.wait()
                out = []
                for _ in range(50):
                    out.extend(fn())
                batches[name] = out

            t1 = threading.Thread(
                target=harvest, args=("loop", f.ready))
            t2 = threading.Thread(
                target=harvest, args=("exit", f.flush))
            t1.start(); t2.start(); t1.join(); t2.join()
            leftover = f.flush()
            steps_loop = [s for _, s, _ in batches["loop"]]
            steps_exit = [s for _, s, _ in batches["exit"]]
            steps_left = [s for _, s, _ in leftover]
            # exactly-once: the three disjoint batches cover 0..n-1
            all_steps = sorted(steps_loop + steps_exit + steps_left)
            assert all_steps == list(range(n))
            # per-caller FIFO
            assert steps_loop == sorted(steps_loop)
            assert steps_exit == sorted(steps_exit)


# ------------------------------------------------------------------ parity
CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_seq_len=16,
                compute_dtype=jnp.float32, checkpoint_layers=False)


def _data(batch):
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(batch, 16)))
    return tokens, jnp.roll(tokens, -1, axis=1)


def _mesh(devices8, dp):
    return Mesh(np.array(devices8[:dp]).reshape(dp, 1), ("dp", "tp"))


def _assert_bitwise(tree_a, tree_b):
    for a, b in zip(jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestTelemetryParity:
    """Telemetry on/off → bitwise-identical loss/params in fp32."""

    def _run_pair(self, build, run_on, run_off, steps=3):
        losses_on, losses_off = [], []
        st_on = build(True)
        st_off = build(False)
        for i in range(steps):
            losses_on.append(run_on(st_on, i))
            losses_off.append(run_off(st_off, i))
        return st_on, st_off, losses_on, losses_off

    def test_plain_step_with_clip(self, devices8):
        mesh = _mesh(devices8, 2)
        tokens, targets = _data(2)
        tel = stepstats.StepTelemetry()

        def make(with_tel):
            params = init_params(CFG, jax.random.PRNGKey(0))
            opt = FusedAdam(lr=1e-2)
            state = opt.init(params)
            step = make_train_step(
                CFG, opt, mesh, clip_grad_norm=1.0,
                telemetry=tel if with_tel else None)
            return {"p": params, "s": state, "step": step,
                    "stats": tel.init() if with_tel else None}

        a, b = make(True), make(False)
        for i in range(3):
            a["p"], a["s"], a["stats"], loss_a = a["step"](
                a["p"], a["s"], a["stats"], tokens, targets)
            b["p"], b["s"], loss_b = b["step"](
                b["p"], b["s"], tokens, targets)
            assert float(loss_a) == float(loss_b)
        _assert_bitwise(a["p"], b["p"])
        _assert_bitwise(a["s"], b["s"])
        # and the window really observed: 3 steps, clip's global norm
        assert int(a["stats"].steps) == 3
        assert np.isfinite(float(a["stats"].grad_norm_last))

    def test_scaled_guarded_chaos_composition(self, devices8):
        """fp16-style scaler + StepGuard + chaos NaN injection: the
        poisoned step is skipped identically on both sides and the
        telemetry counts it."""
        from apex_tpu.amp import DynamicLossScaler
        from apex_tpu.resilience import ChaosMonkey, ChaosPlan, StepGuard

        mesh = _mesh(devices8, 2)
        tokens, targets = _data(2)
        tel = stepstats.StepTelemetry()
        guard = StepGuard(max_consecutive_bad=5)
        scaler = DynamicLossScaler(init_scale=2.0 ** 4)

        def make(with_tel):
            params = init_params(CFG, jax.random.PRNGKey(0))
            opt = FusedAdam(lr=1e-2)
            state = opt.init(params)
            chaos = ChaosMonkey(ChaosPlan.make(nan_grad_steps=(1,)))
            step = make_train_step(
                CFG, opt, mesh, loss_scaler=scaler, step_guard=guard,
                chaos=chaos, telemetry=tel if with_tel else None)
            return {"p": params, "s": state, "sc": scaler.init(),
                    "g": guard.init(), "step": step,
                    "stats": tel.init() if with_tel else None}

        a, b = make(True), make(False)
        for i in range(3):
            (a["p"], a["s"], a["sc"], a["g"], a["stats"], loss_a) = \
                a["step"](a["p"], a["s"], a["sc"], a["g"], a["stats"],
                          tokens, targets)
            (b["p"], b["s"], b["sc"], b["g"], loss_b) = \
                b["step"](b["p"], b["s"], b["sc"], b["g"], tokens, targets)
        _assert_bitwise(a["p"], b["p"])
        _assert_bitwise([a["sc"].loss_scale, a["g"].total_skipped],
                        [b["sc"].loss_scale, b["g"].total_skipped])
        assert int(a["stats"].notfinite) == 1  # the injected NaN step
        assert float(a["stats"].loss_scale) == float(a["sc"].loss_scale)

    def test_zero_int8_sync(self, devices8):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        mesh = _mesh(devices8, 2)
        tokens, targets = _data(2)
        tel = stepstats.StepTelemetry()

        def make(with_tel):
            params = init_params(CFG, jax.random.PRNGKey(0))
            opt = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                       grad_sync_dtype="int8")
            state = opt.init(params, world_size=2)
            step = make_train_step(CFG, opt, mesh,
                                   telemetry=tel if with_tel else None)
            return {"p": params, "s": state, "step": step,
                    "stats": tel.init() if with_tel else None}

        a, b = make(True), make(False)
        for i in range(3):
            a["p"], a["s"], a["stats"], loss_a = a["step"](
                a["p"], a["s"], a["stats"], tokens, targets)
            b["p"], b["s"], loss_b = b["step"](
                b["p"], b["s"], tokens, targets)
            assert float(loss_a) == float(loss_b)
        _assert_bitwise(a["p"], b["p"])
        _assert_bitwise(a["s"], b["s"])
        assert int(a["stats"].steps) == 3

    def test_window_reset_does_not_retrace(self, devices8):
        """The fetch seam's init_like swap keeps the jit signature —
        compiled-variant count must not grow per fetch."""
        mesh = _mesh(devices8, 2)
        tokens, targets = _data(2)
        tel = stepstats.StepTelemetry()
        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        step = make_train_step(CFG, opt, mesh, telemetry=tel)
        stats = tel.init()
        fetcher = stepstats.AsyncFetcher()
        for i in range(4):
            params, state, stats, _loss = step(params, state, stats,
                                               tokens, targets)
            if i % 2 == 1:  # fetch + reset mid-stream
                fetcher.put("stats", i, stats._asdict())
                stats = tel.init_like(stats)
        baseline = step._cache_size()
        for i in range(2):
            params, state, stats, _loss = step(params, state, stats,
                                               tokens, targets)
            fetcher.put("stats", i, stats._asdict())
            stats = tel.init_like(stats)
        assert step._cache_size() == baseline
        harvested = fetcher.flush()
        assert sum(int(t["steps"]) for _, _, t in harvested) >= 4


# ----------------------------------------------------------------- goodput
class TestGoodput:
    def test_flops_formulas(self):
        assert goodput.model_flops_per_token(10, 2, 4, 8) \
            == 6 * 10 + 12 * 2 * 4 * 8
        assert goodput.model_flops_per_step(10, 2, 4, 8, batch=3) \
            == goodput.model_flops_per_token(10, 2, 4, 8) * 3 * 4
        assert goodput.decode_flops_per_token(10) == 20

    def _clock(self, start=1000.0):
        t = {"now": start}

        def fn():
            return t["now"]

        fn.advance = lambda dt: t.__setitem__("now", t["now"] + dt)
        return fn

    def test_fractions_sum_to_one_with_wedge_and_restart(self, tmp_path):
        clk = self._clock()
        # session 1: 10s productive, 2s checkpoint, then wedges for 4s
        a1 = goodput.GoodputAccountant(tmp_path, run_id="r", time_fn=clk)
        clk.advance(10)
        a1.step_done(steps=10, tokens=1000)
        with a1.attribute("checkpoint"):
            clk.advance(2)
        clk.advance(4)              # the wedged tail (no progress)
        a1.finalize("wedge")        # what the watchdog's on_wedge does
        clk.advance(6)              # supervisor backoff + relaunch gap
        # session 2: 8s productive, 1s restore, clean exit
        a2 = goodput.GoodputAccountant(tmp_path, run_id="r", time_fn=clk)
        with a2.attribute("restore"):
            clk.advance(1)
        clk.advance(8)
        a2.step_done(steps=8, tokens=800)
        a2.finalize("clean")
        rep = goodput.goodput_report(tmp_path)
        assert rep["sessions"] == 2
        assert rep["wall_secs"] == pytest.approx(31.0)
        f = rep["fractions"]
        assert sum(f.values()) == pytest.approx(1.0, abs=1e-9)
        assert rep["seconds"]["wedge"] == pytest.approx(4.0)
        assert rep["seconds"]["restart"] == pytest.approx(6.0)
        assert rep["seconds"]["checkpoint"] == pytest.approx(2.0)
        assert rep["seconds"]["restore"] == pytest.approx(1.0)
        assert rep["seconds"]["productive"] == pytest.approx(18.0)
        assert rep["wedge_events"] == 1
        assert rep["steps"] == 18 and rep["tokens"] == 1800

    def test_hard_killed_session_tail_lands_in_restart(self, tmp_path):
        clk = self._clock()
        a1 = goodput.GoodputAccountant(tmp_path, run_id="r", time_fn=clk)
        clk.advance(5)
        a1.step_done(steps=5)
        a1.heartbeat()          # last persist before the kill
        clk.advance(3)          # unpersisted progress, then SIGKILL
        # (no finalize — the process is gone)
        clk.advance(2)
        a2 = goodput.GoodputAccountant(tmp_path, run_id="r", time_fn=clk)
        clk.advance(4)
        a2.step_done(steps=4)
        a2.finalize("clean")
        rep = goodput.goodput_report(tmp_path)
        # killed session's end IS its last heartbeat; the 3+2s to the
        # relaunch are restart, and the fractions still close to 1
        assert rep["seconds"]["restart"] == pytest.approx(5.0)
        assert sum(rep["fractions"].values()) == pytest.approx(1.0)
        assert rep["exit_causes"] == [None, "clean"]

    def test_mfu_fields(self, tmp_path):
        clk = self._clock()
        a = goodput.GoodputAccountant(tmp_path, time_fn=clk)
        clk.advance(10)
        a.step_done(steps=10, tokens=10_000)
        a.finalize("clean")
        rep = goodput.goodput_report(tmp_path, flops_per_token=1e9,
                                     roofline_tflops=10.0)
        assert rep["tokens_per_sec_productive"] == pytest.approx(1000.0)
        assert rep["model_tflops_productive"] == pytest.approx(1.0)
        assert rep["mfu_vs_measured_roofline"] == pytest.approx(0.1)

    def test_report_tolerates_empty_and_torn(self, tmp_path):
        assert goodput.goodput_report(tmp_path)["sessions"] == 0
        (tmp_path / "goodput_session_torn.json").write_text("{not json")
        assert goodput.goodput_report(tmp_path)["sessions"] == 0

    def test_report_file_in_dir_is_not_a_session(self, tmp_path):
        """The aggregate goodput_report.json lives in the SAME dir and
        carries the same schema tag: a later session's report must
        skip it (the third-resume crash this pins)."""
        clk = self._clock()
        a = goodput.GoodputAccountant(tmp_path, time_fn=clk)
        clk.advance(2)
        a.step_done(steps=2)
        a.finalize("clean")
        rep1 = goodput.goodput_report(tmp_path)
        (tmp_path / "goodput_report.json").write_text(json.dumps(rep1))
        rep2 = goodput.goodput_report(tmp_path)
        assert rep2["sessions"] == 1
        assert abs(sum(rep2["fractions"].values()) - 1.0) < 1e-9


# ---------------------------------------------------------------- serving
class TestServingMetrics:
    def test_scheduler_records_queue_ttft_and_latency(self):
        from apex_tpu.inference import (
            ContinuousBatchingScheduler, DecodeConfig, KVCacheConfig,
            Request,
        )

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_seq_len=64,
                        position_embedding_type="rope",
                        compute_dtype=jnp.float32, checkpoint_layers=False)
        params = init_params(cfg, jax.random.PRNGKey(0))
        dcfg = DecodeConfig(
            cache=KVCacheConfig(num_pages=10, page_size=4,
                                pages_per_seq=4, dtype=jnp.float32),
            max_batch=2, max_prompt_len=8, temperature=0.0,
            attn_impl="xla", sample_impl="xla",
            sample_dot_dtype=jnp.float32)
        with metrics.MetricsScope() as reg:
            sched = ContinuousBatchingScheduler(params, cfg, dcfg)
            for rid in range(3):
                sched.submit(Request(rid=rid, prompt=[1, 2, 3],
                                     max_new_tokens=3))
            done = sched.run_until_drained()
            assert len(done) == 3
            hist = {n: v for m in reg.metrics() for n, l, v in m.samples()}
            assert hist["apex_serve_ttft_seconds_count"] == 3
            assert hist["apex_serve_admission_wait_seconds_count"] == 3
            # inter-token: every decoded token after the first per seq
            decoded = sum(len(c.tokens) - 1 for c in done)
            assert hist["apex_serve_inter_token_seconds_count"] == decoded
            assert reg.counter("apex_serve_completions_total").value() == 3
            assert reg.counter(
                "apex_serve_generated_tokens_total").value() == sum(
                    len(c.tokens) for c in done)
            # drained: gauges read empty
            assert reg.gauge("apex_serve_queue_depth").value() == 0
            assert reg.gauge("apex_serve_active_slots").value() == 0
