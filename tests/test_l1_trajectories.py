"""L1-style trajectory cross-product harness.

Mirrors ``tests/L1/common/run_test.sh:28-50`` + ``compare.py``: train the
same model under the cross product of opt-level × loss-scale ×
half-dtype, record the per-iteration loss trajectory, and assert the
trajectory is identical between two execution modes of the same
numerics.  The reference's two modes are two launch styles of the same
DDP run; the TPU analog is single-device vs dp=4 ``shard_map`` over the
same global batch (sync-BN statistics, pmean'd grads) — numerically the
same training run, so trajectories must agree to reduction-order noise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import FusedSGD

pytestmark = pytest.mark.slow

STEPS = 6
BATCH = 16
IMG = 8


def init_params(rng):
    return {
        "conv": jnp.asarray(rng.randn(3, 3, 3, 8).astype(np.float32) * 0.2),
        "bn_scale": jnp.ones((8,), jnp.float32),
        "bn_bias": jnp.zeros((8,), jnp.float32),
        "dense": jnp.asarray(rng.randn(8, 10).astype(np.float32) * 0.2),
        "dense_b": jnp.zeros((10,), jnp.float32),
    }


def forward(params, x, axis_name=None):
    """Conv → (sync)BN → relu → mean-pool → dense, computed in the dtype
    amp cast the params to."""
    dt = params["conv"].dtype
    h = jax.lax.conv_general_dilated(
        x.astype(dt), params["conv"], (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    hf = h.astype(jnp.float32)
    mean = jnp.mean(hf, axis=(0, 1, 2))
    sq = jnp.mean(hf * hf, axis=(0, 1, 2))
    if axis_name is not None:  # sync-BN statistics over dp
        mean = jax.lax.pmean(mean, axis_name)
        sq = jax.lax.pmean(sq, axis_name)
    var = sq - mean * mean
    hn = (hf - mean) / jnp.sqrt(var + 1e-5)
    hn = hn * params["bn_scale"].astype(jnp.float32) + params["bn_bias"].astype(jnp.float32)
    h = jax.nn.relu(hn).astype(dt)
    pooled = jnp.mean(h.astype(jnp.float32), axis=(1, 2))
    logits = pooled @ params["dense"].astype(jnp.float32) + params["dense_b"].astype(jnp.float32)
    return logits


def make_batches(seed=0):
    """One fixed labeled batch reused every step (so the loss trajectory
    is monotone-ish and the 'it actually trains' assertion is meaningful)."""
    rng = np.random.RandomState(seed)
    x = rng.randn(BATCH, IMG, IMG, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(BATCH,))
    xs = np.broadcast_to(x, (STEPS, *x.shape)).copy()
    ys = np.broadcast_to(y, (STEPS, *y.shape)).copy()
    return jnp.asarray(xs), jnp.asarray(ys)


def run_trajectory(opt_level, loss_scale, half_dtype, dp, devices8=None):
    rng = np.random.RandomState(1)
    params0 = init_params(rng)
    params, amp_obj = amp.initialize(
        params0, opt_level=opt_level, half_dtype=half_dtype, loss_scale=loss_scale
    )
    opt = FusedSGD(lr=0.05, momentum=0.9, master_weights=True)
    opt_state = opt.init(params)
    scaler_state = amp_obj.init_state()
    xs, ys = make_batches()

    def loss_fn(params, x, y, axis_name=None):
        logits = forward(params, x, axis_name)
        onehot = jax.nn.one_hot(y, 10)
        loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
        if axis_name is not None:
            loss = jax.lax.pmean(loss, axis_name)
        return loss

    losses = []
    if dp == 1:
        amp_vg = amp.value_and_grad(amp_obj, loss_fn)

        @jax.jit
        def step(params, opt_state, scaler_state, x, y):
            loss, grads, scaler_state, finite = amp_vg(params, scaler_state, x, y)
            params, opt_state = opt.update(grads, opt_state, params, grads_finite=finite)
            return params, opt_state, scaler_state, loss

        for i in range(STEPS):
            params, opt_state, scaler_state, loss = step(params, opt_state, scaler_state, xs[i], ys[i])
            losses.append(float(loss))
    else:
        mesh = Mesh(np.array(devices8[:dp]), ("dp",))
        amp_vg = amp.value_and_grad(
            amp_obj, lambda p, x, y: loss_fn(p, x, y, axis_name="dp")
        )

        def local(params, opt_state, scaler_state, x, y):
            loss, grads, scaler_state, finite = amp_vg(params, scaler_state, x, y)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            finite = jnp.logical_and(jax.lax.pmin(finite.astype(jnp.int32), "dp"), 1).astype(bool) if finite is not None else None
            params, opt_state = opt.update(grads, opt_state, params, grads_finite=finite)
            return params, opt_state, scaler_state, loss

        step = jax.jit(jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        ))
        for i in range(STEPS):
            params, opt_state, scaler_state, loss = step(params, opt_state, scaler_state, xs[i], ys[i])
            losses.append(float(loss))
    return np.asarray(losses), params


CONFIGS = [
    # (opt_level, loss_scale, half_dtype, rtol)
    ("O0", None, None, 1e-5),
    ("O1", None, jnp.bfloat16, 2e-3),
    ("O1", "dynamic", jnp.float16, 2e-3),
    ("O2", None, jnp.bfloat16, 2e-3),
    ("O2", 128.0, jnp.float16, 2e-3),
    ("O2", "dynamic", jnp.float16, 2e-3),
    ("O3", None, jnp.bfloat16, 4e-3),
    ("O3", 128.0, jnp.float16, 4e-3),
]


class TestL1TrajectoryCrossProduct:
    @pytest.mark.parametrize("opt_level,loss_scale,half_dtype,rtol", CONFIGS)
    def test_single_vs_dp_trajectory(self, opt_level, loss_scale, half_dtype, rtol, devices8):
        """compare.py's assertion: same config, two execution modes,
        same per-iteration loss trajectory."""
        single, _ = run_trajectory(opt_level, loss_scale, half_dtype, dp=1)
        sharded, _ = run_trajectory(opt_level, loss_scale, half_dtype, dp=4, devices8=devices8)
        np.testing.assert_allclose(single, sharded, rtol=rtol, atol=rtol)
        # the run must actually train
        assert single[-1] < single[0], single

    def test_keep_batchnorm_fp32_by_level(self):
        """O2 keeps norm params fp32; O3 casts everything (the
        keep-batchnorm axis of the reference cross product)."""
        params0 = init_params(np.random.RandomState(0))
        p2, _ = amp.initialize(params0, opt_level="O2", half_dtype=jnp.bfloat16)
        p3, _ = amp.initialize(params0, opt_level="O3", half_dtype=jnp.bfloat16)
        assert p2["bn_scale"].dtype == jnp.float32
        assert p2["conv"].dtype == jnp.bfloat16
        assert p3["bn_scale"].dtype == jnp.bfloat16

    def test_o0_matches_plain_fp32_training(self, devices8):
        """O0 is a no-op policy: identical to un-amp'd training."""
        o0, _ = run_trajectory("O0", None, None, dp=1)

        rng = np.random.RandomState(1)
        params = init_params(rng)
        opt = FusedSGD(lr=0.05, momentum=0.9, master_weights=True)
        opt_state = opt.init(params)
        xs, ys = make_batches()

        @jax.jit
        def step(params, opt_state, x, y):
            def lf(p):
                logits = forward(p, x)
                onehot = jax.nn.one_hot(y, 10)
                return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))

            loss, grads = jax.value_and_grad(lf)(params)
            params, opt_state = opt.update(grads, opt_state, params)
            return params, opt_state, loss

        plain = []
        for i in range(STEPS):
            params, opt_state, loss = step(params, opt_state, xs[i], ys[i])
            plain.append(float(loss))
        np.testing.assert_allclose(o0, np.asarray(plain), rtol=1e-6)


class TestAmpMasterParams:
    """tests/distributed/amp_master_params analog: after DDP+amp
    training, the half model params must equal the fp32 master params
    cast to half, on every rank."""

    @pytest.mark.parametrize("half_dtype", [jnp.float16, jnp.bfloat16])
    def test_model_equals_master_cast(self, half_dtype, devices8):
        params0 = init_params(np.random.RandomState(1))
        params, amp_obj = amp.initialize(
            params0, opt_level="O2", half_dtype=half_dtype,
            loss_scale="dynamic" if half_dtype == jnp.float16 else None,
        )
        opt = FusedSGD(lr=0.05, momentum=0.9, master_weights=True)
        opt_state = opt.init(params)
        scaler_state = amp_obj.init_state()
        xs, ys = make_batches()
        mesh = Mesh(np.array(devices8[:4]), ("dp",))
        amp_vg = amp.value_and_grad(
            amp_obj, lambda p, x, y: (lambda logits: -jnp.mean(jnp.sum(
                jax.nn.one_hot(y, 10) * jax.nn.log_softmax(logits), axis=-1
            )))(forward(p, x, "dp")))

        def local(params, opt_state, scaler_state, x, y):
            loss, grads, scaler_state, finite = amp_vg(params, scaler_state, x, y)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            if finite is not None:
                finite = jnp.logical_and(
                    jax.lax.pmin(finite.astype(jnp.int32), "dp"), 1).astype(bool)
            params, opt_state = opt.update(grads, opt_state, params, grads_finite=finite)
            return params, opt_state, scaler_state, loss

        step = jax.jit(jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        ))
        for i in range(STEPS):
            params, opt_state, scaler_state, _ = step(
                params, opt_state, scaler_state, xs[i], ys[i])

        # the reference compare.py contract, leaf by leaf
        for name, p in params.items():
            m = opt_state.master[name]
            if p.dtype == half_dtype:
                assert m.dtype == jnp.float32
                np.testing.assert_array_equal(
                    np.asarray(p, np.float32),
                    np.asarray(m.astype(half_dtype), np.float32),
                    err_msg=f"model/master divergence in {name}")
