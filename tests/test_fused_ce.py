"""Chunked fused LM-head+CE (ops/fused_ce.py) parity tests.

The fused op must be a drop-in for ``logsumexp - target`` on the same
fp32 head matmul: identical loss and identical gradients (dx AND the
tied-embedding dembed), dense and vocab-parallel, op-level and through
``gpt_loss``."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.gpt import GPTConfig, gpt_loss, init_params, param_specs
from apex_tpu.ops.fused_ce import fused_lm_head_ce

S, B, H, V = 32, 3, 16, 48


def _dense_ce(x, embed, targets):
    logits = jnp.matmul(x.astype(jnp.float32), embed.T.astype(jnp.float32))
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return lse - tgt


def _data(dtype):
    k = jax.random.PRNGKey(0)
    kx, ke, kt = jax.random.split(k, 3)
    x = jax.random.normal(kx, (S, B, H), dtype)
    embed = jax.random.normal(ke, (V, H), dtype)
    targets = jax.random.randint(kt, (S, B), 0, V)
    return x, embed, targets


@pytest.mark.parametrize("chunk", [8, 16, 32])
def test_loss_matches_dense(chunk):
    x, embed, targets = _data(jnp.float32)
    ref = _dense_ce(x, embed, targets)
    got = fused_lm_head_ce(x, embed, targets, chunk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_grads_match_dense(dtype):
    x, embed, targets = _data(dtype)

    def mean_ref(x, e):
        return jnp.mean(_dense_ce(x, e, targets))

    def mean_fused(x, e):
        return jnp.mean(fused_lm_head_ce(x, e, targets, 8))

    (dx_r, de_r) = jax.grad(mean_ref, argnums=(0, 1))(x, embed)
    (dx_f, de_f) = jax.grad(mean_fused, argnums=(0, 1))(x, embed)
    # fp32 everything inside both paths; only the final cast differs in
    # accumulation order across chunks
    tol = dict(rtol=1e-5, atol=1e-6) if dtype == jnp.float32 else \
        dict(rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(dx_f, np.float32),
                               np.asarray(dx_r, np.float32), **tol)
    np.testing.assert_allclose(np.asarray(de_f, np.float32),
                               np.asarray(de_r, np.float32), **tol)


def test_vocab_parallel_matches_dense(devices8):
    tp = 4
    x, embed, targets = _data(jnp.float32)

    def mean_ref(x, e):
        return jnp.mean(_dense_ce(x, e, targets))

    ref = mean_ref(x, embed)
    (dx_r, de_r) = jax.grad(mean_ref, argnums=(0, 1))(x, embed)

    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))

    def local(x, e_local, t):
        def mean_fused(x, e):
            return jnp.mean(fused_lm_head_ce(x, e, t, 8, "tp"))

        loss = mean_fused(x, e_local)
        dx, de = jax.grad(mean_fused, argnums=(0, 1))(x, e_local)
        # dx is a shard-local partial (the matmul-like contract); the
        # caller's copy-to-region would psum it — do so here
        return loss, jax.lax.psum(dx, "tp"), de

    f = jax.shard_map(local, mesh=mesh,
                      in_specs=(P(), P("tp", None), P()),
                      out_specs=(P(), P(), P("tp", None)),
                      check_vma=False)
    loss, dx, de = f(x, embed, targets)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_r),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(de), np.asarray(de_r),
                               rtol=1e-5, atol=1e-6)


CFG = GPTConfig(
    vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
    max_seq_len=16, compute_dtype=jnp.float32, checkpoint_layers=False,
    fused_ce=True, fused_ce_chunk=8,
)


def _batch():
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(2, 16)))
    return tokens, jnp.roll(tokens, -1, axis=1)


def test_gpt_loss_fused_matches_dense():
    tokens, targets = _batch()
    params = init_params(CFG, jax.random.PRNGKey(0))
    dense_cfg = dataclasses.replace(CFG, fused_ce=False)
    ref, ref_g = jax.value_and_grad(gpt_loss)(params, tokens, targets, dense_cfg)
    got, got_g = jax.value_and_grad(gpt_loss)(params, tokens, targets, CFG)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        got_g, ref_g)


def test_gpt_loss_fused_falls_back_on_indivisible():
    tokens, targets = _batch()
    params = init_params(CFG, jax.random.PRNGKey(0))
    cfg = dataclasses.replace(CFG, fused_ce_chunk=7)  # 16 % 7 != 0
    dense_cfg = dataclasses.replace(CFG, fused_ce=False)
    ref = gpt_loss(params, tokens, targets, dense_cfg)
    got = gpt_loss(params, tokens, targets, cfg)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-7)


def test_bert_fused_matches_dense():
    from apex_tpu.models.bert import BertConfig, bert_mlm_loss
    from apex_tpu.models.bert import init_params as bert_init

    rng = np.random.RandomState(1)
    cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                     num_attention_heads=4, max_seq_len=16,
                     compute_dtype=jnp.float32, checkpoint_layers=False,
                     fused_ce=True, fused_ce_chunk=8)
    params = bert_init(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(rng.randint(0, 64, size=(2, 16)))
    targets = jnp.asarray(rng.randint(0, 64, size=(2, 16)))
    mask = jnp.asarray(rng.randint(0, 2, size=(2, 16)))
    dense_cfg = dataclasses.replace(cfg, fused_ce=False)
    ref, ref_g = jax.value_and_grad(bert_mlm_loss)(
        params, tokens, targets, mask, dense_cfg)
    got, got_g = jax.value_and_grad(bert_mlm_loss)(
        params, tokens, targets, mask, cfg)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        got_g, ref_g)


def test_t5_fused_matches_dense():
    from apex_tpu.models.t5 import T5Config, t5_loss
    from apex_tpu.models.t5 import init_params as t5_init

    rng = np.random.RandomState(2)
    cfg = T5Config(vocab_size=64, hidden_size=32, num_encoder_layers=2,
                   num_decoder_layers=2, num_attention_heads=4,
                   max_src_len=16, max_tgt_len=16,
                   compute_dtype=jnp.float32, checkpoint_layers=False,
                   fused_ce=True, fused_ce_chunk=8)
    params = t5_init(cfg, jax.random.PRNGKey(0))
    src = jnp.asarray(rng.randint(0, 64, size=(2, 16)))
    dec = jnp.asarray(rng.randint(0, 64, size=(2, 16)))
    targets = jnp.asarray(rng.randint(0, 64, size=(2, 16)))
    dense_cfg = dataclasses.replace(cfg, fused_ce=False)
    ref, ref_g = jax.value_and_grad(t5_loss)(params, src, dec, targets, dense_cfg)
    got, got_g = jax.value_and_grad(t5_loss)(params, src, dec, targets, cfg)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        got_g, ref_g)


@pytest.mark.xfail(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="jax 0.4.x CPU: accumulation-order noise on one fc2 element "
           "exceeds the post-Adam rtol (g/sqrt(v) amplifies tiny-grad "
           "differences); strict on the chip image's newer jax",
    strict=False)
def test_pp_fused_matches_dense_oracle(devices8):
    """The pipeline post-stage head (models/gpt.py post_fn) must produce
    the same loss/params through the fused path as the dense oracle."""
    from apex_tpu.models.gpt import make_pp_train_step
    from apex_tpu.optimizers import FusedAdam

    cfg = dataclasses.replace(CFG, num_layers=4)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("dp", "pp", "tp"))
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(8, 16)))
    targets = jnp.roll(tokens, -1, axis=1)

    step = make_pp_train_step(cfg, opt, mesh, num_microbatches=2)
    new_params, _, loss = step(params, state, tokens, targets)

    dense_cfg = dataclasses.replace(cfg, fused_ce=False)
    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(
        params, tokens, targets, dense_cfg)
    ref_params, _ = opt.update(ref_grads, opt.init(params), params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(new_params),
        jax.tree_util.tree_leaves_with_path(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-5,
            err_msg=jax.tree_util.keystr(ka))


def test_gpt_loss_fused_tp_matches_single_device(devices8):
    tokens, targets = _batch()
    params = init_params(CFG, jax.random.PRNGKey(0))
    dense_cfg = dataclasses.replace(CFG, fused_ce=False)
    ref, ref_g = jax.value_and_grad(gpt_loss)(params, tokens, targets, dense_cfg)

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    specs = param_specs(CFG, "tp")
    f = jax.shard_map(
        jax.value_and_grad(lambda p, t, y: gpt_loss(p, t, y, CFG, axis_name="tp")),
        mesh=mesh, in_specs=(specs, P(), P()), out_specs=(P(), specs),
        check_vma=False)
    loss, grads = f(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        jax.device_get(grads), jax.device_get(ref_g))
