"""Lowered-IR invariants on the REAL train steps, via
``apex_tpu.analysis.lowered`` (the analyzer's jax-importing second
tier).

PR 4 proved these invariants one-off with inline HLO greps pinned to
the ZeRO optimizer's ``update`` in isolation; this band pins the same
contracts on ``gpt.make_train_step`` itself — the seam every refactor
actually goes through — so a step-builder change that silently drops
the per-bucket reduce-scatter plan, reintroduces a whole-tree flatten,
or loses donation coverage fails HERE, in CI, not as a perf regression
three benchmark rounds later.

Everything asserts on the .lower() artifact (trace only, no XLA
compile) except the compiled input_output_alias check, which is the
one fact that only materializes at compile time and rides the slow
tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.analysis import lowered as lw
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.models.gpt import (
    GPTConfig, init_params, make_train_step, param_specs,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.fused_adam import AdamState

DP = 8

CFG = GPTConfig(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_seq_len=16,
    compute_dtype=jnp.float32,
    checkpoint_layers=False,
)

#: splits the tiny fp32 tree into several buckets (clamps at one dtype
#: tile), so "per-bucket" is distinguishable from "whole-tree"
TINY_CAP_MB = 4096 / 2 ** 20


def _mesh(devices8):
    return Mesh(np.array(devices8).reshape(DP, 1), ("dp", "tp"))


def _data():
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(DP, 16)))
    return tokens, jnp.roll(tokens, -1, axis=1)


def _zero_lowering(devices8, **opt_kw):
    params = init_params(CFG, jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                               bucket_cap_mb=TINY_CAP_MB, **opt_kw)
    state = opt.init(params, world_size=DP)
    step = make_train_step(CFG, opt, _mesh(devices8), donate_state=True)
    tokens, targets = _data()
    return step.lower(params, state, tokens, targets), opt, params, state


class TestZeroTrainStep:
    """The bucket plan's collective structure, read off the full
    ``make_train_step`` lowering with a cap that forces >= 2 buckets."""

    def test_grad_sync_is_one_reduce_scatter_per_bucket(self, devices8):
        low, opt, _params, _state = _zero_lowering(devices8)
        n_buckets = len(opt._plan.buckets)
        assert n_buckets >= 2, "cap should split the fp32 bucket"
        txt = low.as_text()
        # exactly one grad reduce-scatter per bucket — a refactor that
        # reroutes grads through pmean (replicated sync) or fuses the
        # buckets back into one collective changes this count
        lw.count_collectives(txt, "reduce_scatter",
                             minimum=n_buckets, maximum=n_buckets)
        lw.assert_collective_dtype(txt, "reduce_scatter", "f32",
                                   mode="all")
        # params come back per bucket too
        lw.count_collectives(txt, "all_gather", minimum=n_buckets)

    def test_no_whole_tree_concat(self, devices8):
        """With >= 2 buckets nothing may concatenate the FULL flat
        param tree — the pre-bucket ``_flatten`` signature (one extra
        whole-model HBM round trip per step)."""
        low, _opt, params, _state = _zero_lowering(devices8)
        total = sum(int(np.prod(p.shape))
                    for p in jax.tree_util.tree_leaves(params))
        lw.assert_no_whole_tree_concat(low.as_text(), total)

    def test_step_donates_params_and_shard_state(self, devices8):
        """``donate_state=True`` must cover every param leaf AND every
        resident ZeRO shard (m/v/master per bucket + step) at the
        lowering level — a dropped donation re-inflates the step's peak
        by the state bytes ZeRO exists to shard away."""
        low, _opt, params, state = _zero_lowering(devices8)
        lw.assert_donation_covers(low, params, state, compiled=False)

    @pytest.mark.slow
    def test_step_donation_survives_compilation(self, devices8):
        """The compiled module's input_output_alias table actually
        aliases the donated buffers (XLA silently DROPS donations it
        cannot use — the declaration alone proves nothing)."""
        low, _opt, params, state = _zero_lowering(devices8)
        lw.assert_donation_covers(low, params, state, compiled=True)


class TestReplicatedTrainStep:
    """The replicated FusedAdam step: dp grad sync stays an all-reduce
    (pmean), never a reduce-scatter, and donation covers params +
    optimizer state."""

    def _lowering(self, devices8):
        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        pspecs = param_specs(CFG)
        sspec = AdamState(step=P(), exp_avg=pspecs, exp_avg_sq=pspecs,
                          master=None)
        step = make_train_step(CFG, opt, _mesh(devices8),
                               donate_state=True, opt_state_spec=sspec)
        tokens, targets = _data()
        return step.lower(params, state, tokens, targets), params, state

    def test_grad_sync_is_all_reduce_not_scatter(self, devices8):
        low, _params, _state = self._lowering(devices8)
        txt = low.as_text()
        lw.count_collectives(txt, "reduce_scatter", maximum=0)
        lw.count_collectives(txt, "all_reduce", minimum=1)

    def test_step_donates_params_and_state(self, devices8):
        low, params, state = self._lowering(devices8)
        lw.assert_donation_covers(low, params, state, compiled=False)


class TestCheckerSelfConsistency:
    """The checkers themselves, against hand-built artifacts — the
    helpers guard real invariants, so their own failure modes (regex
    drift against a jax upgrade's StableHLO spelling) must be loud."""

    def test_counts_and_dtypes_on_a_real_psum_lowering(self, devices8):
        mesh = Mesh(np.array(devices8), ("dp",))
        f = jax.jit(jax.shard_map(
            lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
            in_specs=P("dp"), out_specs=P(), check_vma=False))
        txt = f.lower(jnp.ones((8, 4), jnp.bfloat16)).as_text()
        assert lw.count_collectives(txt, "all_reduce", minimum=1) >= 1
        lw.assert_collective_dtype(txt, "all_reduce", "bf16")
        with pytest.raises(AssertionError):
            lw.count_collectives(txt, "all_reduce", maximum=0)
        with pytest.raises(AssertionError):
            lw.assert_collective_dtype(txt, "all_reduce", "f32",
                                       mode="all")

    def test_whole_tree_concat_detects_a_real_flatten(self):
        f = jax.jit(lambda a, b: jnp.concatenate(
            [a.ravel(), b.ravel()]))
        txt = f.lower(jnp.ones((13, 5)), jnp.ones((31,))).as_text()
        with pytest.raises(AssertionError, match="whole tree"):
            lw.assert_no_whole_tree_concat(txt, 13 * 5 + 31)
        lw.assert_no_whole_tree_concat(txt, 10_000)  # other sizes fine

    def test_donation_checker_flags_uncovered_state(self):
        tree = {"a": jnp.ones((4,)), "b": jnp.ones((2, 2))}
        donated = jax.jit(lambda t: jax.tree.map(lambda x: x + 1, t),
                          donate_argnums=(0,)).lower(tree)
        lw.assert_donation_covers(donated, tree, compiled=False)
        undonated = jax.jit(
            lambda t: jax.tree.map(lambda x: x + 1, t)).lower(tree)
        with pytest.raises(AssertionError, match="donatable"):
            lw.assert_donation_covers(undonated, tree, compiled=False)

    def test_text_passthrough_and_type_errors(self):
        assert lw.hlo_text("module {}") == "module {}"
        with pytest.raises(TypeError):
            lw.hlo_text(42)
