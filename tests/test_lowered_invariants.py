"""Lowered-IR invariants on the REAL train steps, via
``apex_tpu.analysis.lowered`` (the analyzer's jax-importing second
tier).

PR 4 proved these invariants one-off with inline HLO greps pinned to
the ZeRO optimizer's ``update`` in isolation; this band pins the same
contracts on ``gpt.make_train_step`` itself — the seam every refactor
actually goes through — so a step-builder change that silently drops
the per-bucket reduce-scatter plan, reintroduces a whole-tree flatten,
or loses donation coverage fails HERE, in CI, not as a perf regression
three benchmark rounds later.

Everything asserts on the .lower() artifact (trace only, no XLA
compile) except the compiled input_output_alias check, which is the
one fact that only materializes at compile time and rides the slow
tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.analysis import lowered as lw
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.models.gpt import (
    GPTConfig, init_params, make_train_step, param_specs,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.optimizers.fused_adam import AdamState

DP = 8

CFG = GPTConfig(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_seq_len=16,
    compute_dtype=jnp.float32,
    checkpoint_layers=False,
)

#: splits the tiny fp32 tree into several buckets (clamps at one dtype
#: tile), so "per-bucket" is distinguishable from "whole-tree"
TINY_CAP_MB = 4096 / 2 ** 20


def _mesh(devices8):
    return Mesh(np.array(devices8).reshape(DP, 1), ("dp", "tp"))


def _data():
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(DP, 16)))
    return tokens, jnp.roll(tokens, -1, axis=1)


def _zero_lowering(devices8, **opt_kw):
    params = init_params(CFG, jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                               bucket_cap_mb=TINY_CAP_MB, **opt_kw)
    state = opt.init(params, world_size=DP)
    step = make_train_step(CFG, opt, _mesh(devices8), donate_state=True)
    tokens, targets = _data()
    return step.lower(params, state, tokens, targets), opt, params, state


class TestZeroTrainStep:
    """The bucket plan's collective structure, read off the full
    ``make_train_step`` lowering with a cap that forces >= 2 buckets."""

    def test_grad_sync_is_one_reduce_scatter_per_bucket(self, devices8):
        low, opt, _params, _state = _zero_lowering(devices8)
        n_buckets = len(opt._plan.buckets)
        assert n_buckets >= 2, "cap should split the fp32 bucket"
        txt = low.as_text()
        # exactly one grad reduce-scatter per bucket, ON the dp axis —
        # a refactor that reroutes grads through pmean (replicated
        # sync), fuses the buckets back into one collective, or moves
        # the scatter to another axis changes this per-axis count
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp",),
                                  _mesh(devices8), minimum=n_buckets,
                                  maximum=n_buckets, dtype="f32")
        # params come back per bucket too, on the same axis
        lw.assert_collective_axes(txt, "all_gather", ("dp",),
                                  _mesh(devices8), minimum=n_buckets)

    def test_no_whole_tree_concat(self, devices8):
        """With >= 2 buckets nothing may concatenate the FULL flat
        param tree — the pre-bucket ``_flatten`` signature (one extra
        whole-model HBM round trip per step)."""
        low, _opt, params, _state = _zero_lowering(devices8)
        total = sum(int(np.prod(p.shape))
                    for p in jax.tree_util.tree_leaves(params))
        lw.assert_no_whole_tree_concat(low.as_text(), total)

    def test_step_donates_params_and_shard_state(self, devices8):
        """``donate_state=True`` must cover every param leaf AND every
        resident ZeRO shard (m/v/master per bucket + step) at the
        lowering level — a dropped donation re-inflates the step's peak
        by the state bytes ZeRO exists to shard away."""
        low, _opt, params, state = _zero_lowering(devices8)
        lw.assert_donation_covers(low, params, state, compiled=False)

    @pytest.mark.slow
    def test_step_donation_survives_compilation(self, devices8):
        """The compiled module's input_output_alias table actually
        aliases the donated buffers (XLA silently DROPS donations it
        cannot use — the declaration alone proves nothing)."""
        low, _opt, params, state = _zero_lowering(devices8)
        lw.assert_donation_covers(low, params, state, compiled=True)


class TestQuantizedZeroTrainStep:
    """The compressed-sync pins (ISSUE 6): the grad wire really is
    int8/fp8 at the lowering level, no fp32 whole-bucket gradient
    collective survives, and donation still covers every shard buffer
    INCLUDING the error-feedback residuals."""

    def test_int8_wire_one_reduce_scatter_per_bucket(self, devices8):
        low, opt, _params, _state = _zero_lowering(
            devices8, grad_sync_dtype="int8")
        n_buckets = len(opt._plan.buckets)
        assert n_buckets >= 2
        txt = low.as_text()
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp",),
                                  _mesh(devices8), minimum=n_buckets,
                                  maximum=n_buckets, dtype="i8")
        lw.assert_collective_dtype(txt, "reduce_scatter", "f32",
                                   mode="none")
        lw.assert_collective_axes(txt, "all_gather", ("dp",),
                                  _mesh(devices8), minimum=n_buckets)

    def test_fp8_wire_element_types(self, devices8):
        for wire, hlo_dtype in (("float8_e4m3fn", "f8E4M3FN"),
                                ("float8_e5m2", "f8E5M2")):
            low, _opt, _p, _s = _zero_lowering(devices8,
                                               grad_sync_dtype=wire)
            txt = low.as_text()
            lw.assert_collective_dtype(txt, "reduce_scatter", hlo_dtype,
                                       mode="all")
            lw.assert_collective_dtype(txt, "reduce_scatter", "f32",
                                       mode="none")

    def test_no_whole_bucket_fp32_gradient_collective(self, devices8):
        """The scale psums are the ONLY fp32 all-reduces the grad sync
        adds, and they are block-vector sized (total/QBLOCK), never
        bucket-sized: an fp32 collective at any bucket's total would
        mean the narrow wire is being shadowed by a wide one."""
        import re

        from apex_tpu.contrib.optimizers._quantized_sync import QBLOCK

        low, opt, params, _state = _zero_lowering(
            devices8, grad_sync_dtype="int8")
        txt = low.as_text()
        for b in opt._plan.buckets:
            assert not re.search(
                r'(?:stablehlo|mhlo)\.(?:all_reduce|reduce_scatter)'
                r'"?.*?tensor<' + str(b.total) + r'xf32>', txt), (
                f"fp32 collective at whole-bucket size {b.total}")
            # the scale vector for this bucket IS small
            assert b.total // QBLOCK < b.total // 8
        total = sum(int(np.prod(p.shape))
                    for p in jax.tree_util.tree_leaves(params))
        lw.assert_no_whole_tree_concat(txt, total)

    def test_donation_covers_residuals(self, devices8):
        """Every residual bucket is a donated resident buffer like
        m/v/master: the state gains n_buckets leaves and the lowering
        declares them all donatable."""
        low, opt, params, state = _zero_lowering(
            devices8, grad_sync_dtype="int8")
        n_buckets = len(opt._plan.buckets)
        assert len(jax.tree_util.tree_leaves(state)) == 1 + 4 * n_buckets
        lw.assert_donation_covers(low, params, state, compiled=False)

    @pytest.mark.slow
    def test_residual_donation_survives_compilation(self, devices8):
        low, _opt, params, state = _zero_lowering(
            devices8, grad_sync_dtype="int8")
        lw.assert_donation_covers(low, params, state, compiled=True)


# ------------------------------------------------------- hierarchical sync
HIER_AXES = ("dp_out", "dp_in")


def _hier_mesh(devices8):
    return Mesh(np.array(devices8[:4]).reshape(2, 2, 1),
                ("dp_out", "dp_in", "tp"))


def _hier_lowering(devices8, **opt_kw):
    params = init_params(CFG, jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(lr=1e-2, dp_axes=HIER_AXES,
                               bucket_cap_mb=TINY_CAP_MB, **opt_kw)
    state = opt.init(params, world_size=4,
                     axis_sizes={"dp_out": 2, "dp_in": 2, "tp": 1})
    step = make_train_step(CFG, opt, _hier_mesh(devices8),
                           dp_axis=HIER_AXES, donate_state=True)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(4, 16)))
    return (step.lower(params, state, tokens,
                       jnp.roll(tokens, -1, axis=1)), opt, params, state)


class TestHierarchicalZeroTrainStep:
    """The multi-hop sync pins (ISSUE 12): per bucket, EXACTLY one
    reduce-scatter on the fast inner axis and one on the slow outer
    axis — both at the wire dtype (the compressed wire never widens on
    the cross-slice hop) — the param all-gathers mirrored per hop,
    zero new whole-tree concats, and donation still covering every
    shard buffer including the error-feedback residuals.  All read off
    the real ``make_train_step(dp_axis=("dp_out", "dp_in"))`` lowering
    via the per-axis ``replica_groups`` filtering in
    ``analysis.lowered``."""

    def test_wide_wire_one_reduce_scatter_per_bucket_per_hop(self, devices8):
        low, opt, _params, _state = _hier_lowering(devices8)
        n = len(opt._plan.buckets)
        assert n >= 2, "cap should split the fp32 bucket"
        txt = low.as_text()
        mesh = _hier_mesh(devices8)
        # fast hop: the full bucket scatters intra-slice...
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_in",),
                                  mesh, minimum=n, maximum=n, dtype="f32")
        # ...slow hop: the 1/dp_in chunk scatters cross-slice
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_out",),
                                  mesh, minimum=n, maximum=n, dtype="f32")
        # never a single-hop scatter over the combined dp world
        lw.count_collectives(txt, "reduce_scatter", axes=HIER_AXES,
                             mesh=mesh, maximum=0)
        # param sync mirrors: one gather per bucket per hop
        lw.assert_collective_axes(txt, "all_gather", ("dp_out",), mesh,
                                  minimum=n, maximum=n, dtype="f32")
        lw.assert_collective_axes(txt, "all_gather", ("dp_in",), mesh,
                                  minimum=n, maximum=n, dtype="f32")

    def test_int8_wire_stays_compressed_on_both_hops(self, devices8):
        low, opt, params, _state = _hier_lowering(
            devices8, grad_sync_dtype="int8")
        n = len(opt._plan.buckets)
        txt = low.as_text()
        mesh = _hier_mesh(devices8)
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_in",),
                                  mesh, minimum=n, maximum=n, dtype="i8")
        # the headline contract: the SLOW hop still carries int8 — a
        # dequantize-then-reduce regression would show f32 here
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_out",),
                                  mesh, minimum=n, maximum=n, dtype="i8")
        lw.assert_collective_dtype(txt, "reduce_scatter", "f32",
                                   mode="none")
        total = sum(int(np.prod(p.shape))
                    for p in jax.tree_util.tree_leaves(params))
        lw.assert_no_whole_tree_concat(txt, total)

    def test_fp8_wire_element_types_per_hop(self, devices8):
        low, opt, _p, _s = _hier_lowering(devices8,
                                          grad_sync_dtype="float8_e4m3fn")
        n = len(opt._plan.buckets)
        mesh = _hier_mesh(devices8)
        txt = low.as_text()
        for hop in (("dp_in",), ("dp_out",)):
            lw.assert_collective_axes(txt, "reduce_scatter", hop, mesh,
                                      minimum=n, maximum=n,
                                      dtype="f8E4M3FN")

    def test_no_whole_tree_concat_wide(self, devices8):
        low, _opt, params, _state = _hier_lowering(devices8)
        total = sum(int(np.prod(p.shape))
                    for p in jax.tree_util.tree_leaves(params))
        lw.assert_no_whole_tree_concat(low.as_text(), total)

    def test_donation_covers_shards_and_residuals(self, devices8):
        low, opt, params, state = _hier_lowering(devices8,
                                                 grad_sync_dtype="int8")
        n_buckets = len(opt._plan.buckets)
        assert len(jax.tree_util.tree_leaves(state)) == 1 + 4 * n_buckets
        lw.assert_donation_covers(low, params, state, compiled=False)

    @pytest.mark.slow
    def test_donation_survives_compilation(self, devices8):
        low, _opt, params, state = _hier_lowering(devices8,
                                                  grad_sync_dtype="int8")
        lw.assert_donation_covers(low, params, state, compiled=True)


class TestOverlappedInterleaving:
    """The backward-overlap tentpole pin (ISSUE 18): with
    ``overlap_grad_sync=True`` at least one pair of consecutive grad
    reduce-scatters has backward ``dot_general`` compute BETWEEN them
    in program order (bucket k's sync is in flight while a later
    segment's backward still runs — the shape the latency-hiding
    scheduler overlaps), while the knob off keeps the old
    all-at-the-end shape with zero dots between any pair.  The
    per-bucket collective count/dtype pins of PR 12/16 must hold
    UNCHANGED under overlap — only placement moves.

    The config needs final-LN leaves that fill a whole bucket tile
    (hidden 512: bias + scale = 1024 fp32 elements) so a pure
    head-stage bucket exists; with tiny hidden sizes the final-LN
    leaves share a bucket with layer leaves and every bucket becomes
    ready at the same backward stage — nothing to interleave."""

    OVL_CFG = GPTConfig(vocab_size=64, hidden_size=512, num_layers=2,
                        num_attention_heads=4, max_seq_len=16,
                        compute_dtype=jnp.float32,
                        checkpoint_layers=False)

    def _flat(self, devices8, overlap, **opt_kw):
        params = init_params(self.OVL_CFG, jax.random.PRNGKey(0))
        opt = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                   bucket_cap_mb=TINY_CAP_MB, **opt_kw)
        state = opt.init(params, world_size=DP)
        step = make_train_step(self.OVL_CFG, opt, _mesh(devices8),
                               donate_state=True,
                               overlap_grad_sync=overlap)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, self.OVL_CFG.vocab_size,
                                         size=(DP, 16)))
        return (step.lower(params, state, tokens,
                           jnp.roll(tokens, -1, axis=1)), opt)

    def test_flat_overlap_interleaves_scatters_with_backward(
            self, devices8):
        low, opt = self._flat(devices8, True)
        n = len(opt._plan.buckets)
        txt = low.as_text()
        mesh = _mesh(devices8)
        # the PR 12 count pin holds under overlap: still exactly one
        # f32 scatter per bucket on dp — only trace placement moved
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp",), mesh,
                                  minimum=n, maximum=n, dtype="f32")
        gaps = lw.assert_interleaved(txt, "reduce_scatter", axes=("dp",),
                                     mesh=mesh, gaps="any")
        assert len(gaps) == n - 1

    def test_flat_unoverlapped_scatters_all_after_backward(
            self, devices8):
        low, _opt = self._flat(devices8, False)
        lw.assert_interleaved(low.as_text(), "reduce_scatter",
                              axes=("dp",), mesh=_mesh(devices8),
                              gaps="none")

    def test_int8_overlap_interleaves_on_the_compressed_wire(
            self, devices8):
        low, opt = self._flat(devices8, True, grad_sync_dtype="int8")
        n = len(opt._plan.buckets)
        txt = low.as_text()
        mesh = _mesh(devices8)
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp",), mesh,
                                  minimum=n, maximum=n, dtype="i8")
        lw.assert_collective_dtype(txt, "reduce_scatter", "f32",
                                   mode="none")
        lw.assert_interleaved(txt, "reduce_scatter", axes=("dp",),
                              mesh=mesh, dtype="i8", gaps="any")

    def test_hier_overlap_interleaves_per_hop(self, devices8):
        params = init_params(self.OVL_CFG, jax.random.PRNGKey(0))
        opt = DistributedFusedAdam(lr=1e-2, dp_axes=HIER_AXES,
                                   bucket_cap_mb=TINY_CAP_MB)
        state = opt.init(params, world_size=4,
                         axis_sizes={"dp_out": 2, "dp_in": 2, "tp": 1})
        step = make_train_step(self.OVL_CFG, opt, _hier_mesh(devices8),
                               dp_axis=HIER_AXES, donate_state=True,
                               overlap_grad_sync=True)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, self.OVL_CFG.vocab_size,
                                         size=(4, 16)))
        low = step.lower(params, state, tokens,
                         jnp.roll(tokens, -1, axis=1))
        n = len(opt._plan.buckets)
        txt = low.as_text()
        mesh = _hier_mesh(devices8)
        # both hops keep their per-bucket counts under overlap...
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_in",),
                                  mesh, minimum=n, maximum=n, dtype="f32")
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_out",),
                                  mesh, minimum=n, maximum=n, dtype="f32")
        # ...and each hop's scatter stream interleaves with backward
        for hop in (("dp_in",), ("dp_out",)):
            lw.assert_interleaved(txt, "reduce_scatter", axes=hop,
                                  mesh=mesh, gaps="any")

    def test_checker_self_consistency(self):
        with pytest.raises(ValueError, match="at least two"):
            lw.interleave_gaps("module {}")
        with pytest.raises(ValueError, match="gaps"):
            lw.assert_interleaved(
                'x = "stablehlo.reduce_scatter"(a)\n'
                'y = "stablehlo.reduce_scatter"(b)\n', gaps="bogus")


class TestHierarchicalQuantizedReplicatedStep:
    """``make_train_step(grad_sync_dtype=..., dp_axis=(outer, inner))``
    on a NON-ZeRO optimizer: the replicated dp pmean becomes the
    two-hop quantized scatter + mirrored gathers, every payload hop on
    the wire dtype."""

    def test_int8_two_hop_rs_ag(self, devices8):
        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        pspecs = param_specs(CFG)
        sspec = AdamState(step=P(), exp_avg=pspecs, exp_avg_sq=pspecs,
                          master=None)
        mesh = _hier_mesh(devices8)
        step = make_train_step(CFG, opt, mesh, dp_axis=HIER_AXES,
                               opt_state_spec=sspec,
                               grad_sync_dtype="int8")
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(4, 16)))
        txt = step.lower(params, state, tokens,
                         jnp.roll(tokens, -1, axis=1)).as_text()
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_in",),
                                  mesh, minimum=1, dtype="i8")
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_out",),
                                  mesh, minimum=1, dtype="i8")
        lw.assert_collective_axes(txt, "all_gather", ("dp_out",), mesh,
                                  minimum=1, dtype="i8")
        # the inner gather moves the int8 payload + the small fp32
        # hop-2 scale vector (dequantize needs every chunk's scales)
        for s in lw.collective_sites(txt, "all_gather"):
            assert s["dtype"] in ("i8", "f32")


class TestQuantizedReplicatedTrainStep:
    """``make_train_step(grad_sync_dtype=...)`` on a NON-ZeRO
    optimizer: the dp pmean lowers to a reduce-scatter + all-gather
    pair, both on the wire dtype."""

    def test_int8_rs_ag_pair(self, devices8):
        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        pspecs = param_specs(CFG)
        sspec = AdamState(step=P(), exp_avg=pspecs, exp_avg_sq=pspecs,
                          master=None)
        step = make_train_step(CFG, opt, _mesh(devices8),
                               opt_state_spec=sspec,
                               grad_sync_dtype="int8")
        tokens, targets = _data()
        txt = step.lower(params, state, tokens, targets).as_text()
        lw.count_collectives(txt, "reduce_scatter", minimum=1)
        lw.assert_collective_dtype(txt, "reduce_scatter", "i8", mode="all")
        lw.assert_collective_dtype(txt, "all_gather", "i8")

    def test_knob_rejected_on_zero_and_wide_dtypes(self, devices8):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        zopt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
        with pytest.raises(ValueError, match="ZeRO optimizer owns"):
            make_train_step(CFG, zopt, _mesh(devices8),
                            grad_sync_dtype="int8")
        with pytest.raises(ValueError, match="int8"):
            make_train_step(CFG, FusedAdam(lr=1e-2), _mesh(devices8),
                            grad_sync_dtype=jnp.bfloat16)


class TestReplicatedTrainStep:
    """The replicated FusedAdam step: dp grad sync stays an all-reduce
    (pmean), never a reduce-scatter, and donation covers params +
    optimizer state."""

    def _lowering(self, devices8):
        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        pspecs = param_specs(CFG)
        sspec = AdamState(step=P(), exp_avg=pspecs, exp_avg_sq=pspecs,
                          master=None)
        step = make_train_step(CFG, opt, _mesh(devices8),
                               donate_state=True, opt_state_spec=sspec)
        tokens, targets = _data()
        return step.lower(params, state, tokens, targets), params, state

    def test_grad_sync_is_all_reduce_not_scatter(self, devices8):
        low, _params, _state = self._lowering(devices8)
        txt = low.as_text()
        lw.count_collectives(txt, "reduce_scatter", maximum=0)
        lw.count_collectives(txt, "all_reduce", minimum=1)

    def test_step_donates_params_and_state(self, devices8):
        low, params, state = self._lowering(devices8)
        lw.assert_donation_covers(low, params, state, compiled=False)


class TestCheckerSelfConsistency:
    """The checkers themselves, against hand-built artifacts — the
    helpers guard real invariants, so their own failure modes (regex
    drift against a jax upgrade's StableHLO spelling) must be loud."""

    def test_counts_and_dtypes_on_a_real_psum_lowering(self, devices8):
        mesh = Mesh(np.array(devices8), ("dp",))
        f = jax.jit(jax.shard_map(
            lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
            in_specs=P("dp"), out_specs=P(), check_vma=False))
        txt = f.lower(jnp.ones((8, 4), jnp.bfloat16)).as_text()
        assert lw.count_collectives(txt, "all_reduce", minimum=1) >= 1
        lw.assert_collective_dtype(txt, "all_reduce", "bf16")
        with pytest.raises(AssertionError):
            lw.count_collectives(txt, "all_reduce", maximum=0)
        with pytest.raises(AssertionError):
            lw.assert_collective_dtype(txt, "all_reduce", "f32",
                                       mode="all")

    def test_whole_tree_concat_detects_a_real_flatten(self):
        f = jax.jit(lambda a, b: jnp.concatenate(
            [a.ravel(), b.ravel()]))
        txt = f.lower(jnp.ones((13, 5)), jnp.ones((31,))).as_text()
        with pytest.raises(AssertionError, match="whole tree"):
            lw.assert_no_whole_tree_concat(txt, 13 * 5 + 31)
        lw.assert_no_whole_tree_concat(txt, 10_000)  # other sizes fine

    def test_donation_checker_flags_uncovered_state(self):
        tree = {"a": jnp.ones((4,)), "b": jnp.ones((2, 2))}
        donated = jax.jit(lambda t: jax.tree.map(lambda x: x + 1, t),
                          donate_argnums=(0,)).lower(tree)
        lw.assert_donation_covers(donated, tree, compiled=False)
        undonated = jax.jit(
            lambda t: jax.tree.map(lambda x: x + 1, t)).lower(tree)
        with pytest.raises(AssertionError, match="donatable"):
            lw.assert_donation_covers(undonated, tree, compiled=False)

    def test_text_passthrough_and_type_errors(self):
        assert lw.hlo_text("module {}") == "module {}"
        with pytest.raises(TypeError):
            lw.hlo_text(42)

    def test_host_transfer_checker_on_real_lowerings(self):
        clean = jax.jit(lambda x: x * 2.0).lower(jnp.ones((4,)))
        lw.assert_no_host_transfer(clean)

        def dirty(x):
            jax.debug.print("x={x}", x=x)
            return x * 2.0

        low = jax.jit(dirty).lower(jnp.ones((4,)))
        assert lw.host_transfer_sites(low), \
            "a debug.print callback must register as a host transfer"
        with pytest.raises(AssertionError, match="host-transfer"):
            lw.assert_no_host_transfer(low)


# --------------------------------------------------------------- telemetry
class TestTelemetryTrainStep:
    """ISSUE 10's zero-overhead pins: a telemetry-enabled
    ``make_train_step`` lowers with the SAME collective structure as
    the telemetry-off step (the grad-norm stat reuses the clip
    reduction — never a new psum), adds zero host transfers, donates
    the StepStats buffers, and never retraces across window resets."""

    KINDS = ("all_reduce", "reduce_scatter", "all_gather",
             "collective_permute", "all_to_all")

    @staticmethod
    def _telemetry():
        from apex_tpu.observability import StepTelemetry

        return StepTelemetry()

    def _pair(self, devices8, *, zero, clip=None, opt_kw=None):
        """(lowering_on, lowering_off, stats) for one optimizer mode."""
        params = init_params(CFG, jax.random.PRNGKey(0))
        tokens, targets = _data()
        tel = self._telemetry()
        stats = tel.init()

        def build(telemetry):
            if zero:
                opt = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                           bucket_cap_mb=TINY_CAP_MB,
                                           **(opt_kw or {}))
                state = opt.init(params, world_size=DP)
                step = make_train_step(CFG, opt, _mesh(devices8),
                                       donate_state=True,
                                       clip_grad_norm=clip,
                                       telemetry=telemetry)
            else:
                opt = FusedAdam(lr=1e-2)
                state = opt.init(params)
                sspec = AdamState(step=P(), exp_avg=param_specs(CFG),
                                  exp_avg_sq=param_specs(CFG), master=None)
                step = make_train_step(CFG, opt, _mesh(devices8),
                                       donate_state=True,
                                       opt_state_spec=sspec,
                                       clip_grad_norm=clip,
                                       telemetry=telemetry)
            args = (params, state, stats, tokens, targets) \
                if telemetry is not None else (params, state, tokens,
                                               targets)
            return step.lower(*args), state, step

        low_on, state, step_on = build(tel)
        low_off, _, _ = build(None)
        return low_on, low_off, stats, state, step_on

    @pytest.mark.parametrize("zero,clip,opt_kw", [
        (False, None, None),
        (False, 1.0, None),
        (True, 1.0, None),
        (True, None, {"grad_sync_dtype": "int8"}),
    ], ids=["replicated", "replicated_clip", "zero_clip", "zero_int8"])
    def test_same_collective_counts(self, devices8, zero, clip, opt_kw):
        low_on, low_off, *_ = self._pair(devices8, zero=zero, clip=clip,
                                         opt_kw=opt_kw)
        on, off = low_on.as_text(), low_off.as_text()
        for kind in self.KINDS:
            n_on = lw.count_collectives(on, kind, minimum=0)
            n_off = lw.count_collectives(off, kind, minimum=0)
            assert n_on == n_off, (
                f"telemetry changed {kind} count: {n_off} -> {n_on}")

    def test_zero_host_transfers(self, devices8):
        low_on, _, _, _, _ = self._pair(devices8, zero=True, clip=1.0)
        lw.assert_no_host_transfer(low_on)

    def test_pp_step_telemetry_same_collectives_no_host_transfer(
            self, devices8):
        """make_pp_train_step carries the same contract: the StepStats
        observer adds no collectives (the pipeline's ppermutes
        included) and no host transfers to the 3D step."""
        from apex_tpu.models.gpt import make_pp_train_step

        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        mesh = Mesh(np.array(devices8[:4]).reshape(1, 2, 2),
                    ("dp", "pp", "tp"))
        tel = self._telemetry()
        stats = tel.init()
        tokens = jnp.asarray(np.random.RandomState(0).randint(
            0, CFG.vocab_size, size=(2, 16)))
        targets = jnp.roll(tokens, -1, axis=1)

        def build(telemetry):
            step = make_pp_train_step(CFG, opt, mesh, num_microbatches=2,
                                      clip_grad_norm=1.0,
                                      telemetry=telemetry)
            args = (params, state, stats, tokens, targets) \
                if telemetry is not None else (params, state, tokens,
                                               targets)
            return step.lower(*args)

        low_on, low_off = build(tel), build(None)
        on, off = low_on.as_text(), low_off.as_text()
        for kind in self.KINDS:
            assert lw.count_collectives(on, kind, minimum=0) \
                == lw.count_collectives(off, kind, minimum=0), kind
        lw.assert_no_host_transfer(low_on)

    #: StepStats inputs accumulate() READS in this (unscaled) config —
    #: steps, loss_sum, grad_norm_sum, notfinite, loss_scale.  The
    #: write-only last-value fields (loss_last, grad_norm_last,
    #: param_norm, update_norm) are dead inputs the lowering cannot —
    #: and need not — declare donatable.
    LIVE_STATS = 5

    def test_stats_buffers_are_donated(self, devices8):
        low_on, low_off, stats, state, _ = self._pair(
            devices8, zero=True, clip=1.0)
        params = init_params(CFG, jax.random.PRNGKey(0))
        lw.assert_donation_covers(low_on, params, state,
                                  extra=self.LIVE_STATS, compiled=False)
        # and the live-stat donors really are ADDITIONAL to the
        # telemetry-off step's params+state donations
        assert (lw.donated_buffer_count(low_on)
                - lw.donated_buffer_count(low_off)) == self.LIVE_STATS

    @pytest.mark.slow
    def test_stats_donation_survives_compilation(self, devices8):
        low_on, _low_off, stats, state, _ = self._pair(
            devices8, zero=True, clip=1.0)
        params = init_params(CFG, jax.random.PRNGKey(0))
        lw.assert_donation_covers(low_on, params, state,
                                  extra=self.LIVE_STATS, compiled=True)


# ------------------------------------------------------------- decode step
class TestDecodeStep:
    """The serving engine's compiled-step contracts (ROADMAP: 'decode
    step pinned to zero host transfers and zero re-compiles across
    cache lengths'): the one jitted decode step runs entirely on
    device, donates the KV pools, and is reused — one compiled
    executable — across every cache length and batch occupancy."""

    @staticmethod
    def _build():
        from apex_tpu.inference import (
            DecodeConfig, KVCacheConfig, alloc_pools,
        )
        from apex_tpu.inference.decode import make_decode_step, make_prefill
        from apex_tpu.models.gpt import init_params

        cfg = GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=2,
            num_attention_heads=4, max_seq_len=64,
            position_embedding_type="rope",
            compute_dtype=jnp.float32, checkpoint_layers=False)
        dcfg = DecodeConfig(
            cache=KVCacheConfig(num_pages=8, page_size=4, pages_per_seq=4,
                                dtype=jnp.float32),
            max_batch=3, max_prompt_len=8, temperature=0.0,
            attn_impl="xla", sample_impl="xla")
        params = init_params(cfg, jax.random.PRNGKey(0))
        pools = alloc_pools(cfg.num_layers, cfg.kv_heads, cfg.head_dim,
                            dcfg.cache)
        return cfg, dcfg, params, pools, make_decode_step, make_prefill

    def _decode_args(self, dcfg, params, pools):
        B, P = dcfg.max_batch, dcfg.cache.pages_per_seq
        return (params, pools,
                jnp.zeros((B,), jnp.int32),          # tokens
                jnp.zeros((B,), jnp.int32),          # positions
                jnp.zeros((B,), bool),               # active
                jnp.zeros((B, P), jnp.int32),        # page tables
                jnp.zeros((B,), jnp.uint32))         # seeds

    def test_decode_step_has_zero_host_transfers(self):
        cfg, dcfg, params, pools, make_step, _ = self._build()
        step = make_step(cfg, dcfg)
        low = step.lower(*self._decode_args(dcfg, params, pools))
        lw.assert_no_host_transfer(low)

    def test_prefill_has_zero_host_transfers(self):
        cfg, dcfg, params, pools, _, make_prefill = self._build()
        prefill = make_prefill(cfg, dcfg)
        low = prefill.lower(
            params, pools, jnp.zeros((1, dcfg.max_prompt_len), jnp.int32),
            jnp.int32(3), jnp.int32(0),
            jnp.zeros((dcfg.cache.pages_per_seq,), jnp.int32),
            jnp.uint32(0))
        lw.assert_no_host_transfer(low)

    def test_kv_pools_donate_through_decode_step(self):
        """The pools are the resident serving state: both buffers must
        really alias through the compiled step, or every token pays a
        pool-sized copy."""
        cfg, dcfg, params, pools, make_step, _ = self._build()
        step = make_step(cfg, dcfg)
        low = step.lower(*self._decode_args(dcfg, params, pools))
        lw.assert_donation_covers(low, pools, compiled=True)

    def test_decode_step_compiles_once_across_lengths_and_occupancy(self):
        """One executable serves occupancy 0..B and any positions mix:
        shape-identical calls with different occupancy/length DATA must
        not add cache entries — the call-matrix spelling of
        ``analysis.lowered.assert_no_recompile``."""
        from apex_tpu.inference import alloc_pools

        cfg, dcfg, params, _pools, make_step, _ = self._build()
        step = make_step(cfg, dcfg)
        B, P = dcfg.max_batch, dcfg.cache.pages_per_seq
        pt = jnp.arange(B * P, dtype=jnp.int32).reshape(B, P) % 7 + 1
        calls = []
        for active, positions in [
            ((False,) * B, (0,) * B),
            ((True, False, False), (0, 0, 0)),
            ((True, True, True), (3, 9, 14)),
            ((False, True, False), (0, 15, 0)),
        ]:
            # fresh pools per call: the step donates them
            pools = alloc_pools(cfg.num_layers, cfg.kv_heads,
                                cfg.head_dim, dcfg.cache)
            calls.append((params, pools, jnp.zeros((B,), jnp.int32),
                          jnp.asarray(positions, jnp.int32),
                          jnp.asarray(active), pt,
                          jnp.zeros((B,), jnp.uint32)))
        lw.assert_no_recompile(step, calls, label="decode_step")

    def test_verify_and_chunk_steps_zero_host_transfer_and_donate(self):
        """The serving-v2 compiled steps inherit every decode-step
        contract: the speculative verify step and the prefill chunk
        step run entirely on device, donate the KV pools, and compile
        once across draft-hit/occupancy/chunk-phase mixes."""
        from apex_tpu.inference.decode import (
            make_prefill_chunk, make_verify_step,
        )

        cfg, dcfg, params, pools, _, _ = self._build()
        import dataclasses as _dc

        dcfg = _dc.replace(dcfg, draft_len=3, prefill_chunk=4)
        B, P = dcfg.max_batch, dcfg.cache.pages_per_seq
        W = dcfg.draft_len + 1
        verify = make_verify_step(cfg, dcfg)
        vargs = (params, pools, jnp.zeros((B, W), jnp.int32),
                 jnp.zeros((B,), jnp.int32), jnp.zeros((B,), bool),
                 jnp.zeros((B, P), jnp.int32),
                 jnp.zeros((B, W), jnp.uint32))
        low = verify.lower(*vargs)
        lw.assert_no_host_transfer(low)
        lw.assert_donation_covers(low, pools, compiled=True)
        # draft hit/miss and occupancy are DATA: shape-identical calls
        # (fresh pools per call — the step donates them)
        from apex_tpu.inference import alloc_pools

        def fresh():
            return alloc_pools(cfg.num_layers, cfg.kv_heads,
                               cfg.head_dim, dcfg.cache)

        calls = [
            (params, fresh(), jnp.full((B, W), toks, jnp.int32),
             jnp.asarray((2, 9, 0), jnp.int32), jnp.asarray(active),
             jnp.ones((B, P), jnp.int32), jnp.zeros((B, W), jnp.uint32))
            for active, toks in [
                ((True, True, True), 5), ((True, False, False), 0),
                ((False,) * B, 3),
            ]
        ]
        lw.assert_no_recompile(verify, calls, label="verify_step")

        chunk = make_prefill_chunk(cfg, dcfg)
        cargs = (params, fresh(), jnp.zeros((4,), jnp.int32),
                 jnp.int32(0), jnp.int32(4), jnp.int32(0),
                 jnp.zeros((P,), jnp.int32))
        lowc = chunk.lower(*cargs)
        lw.assert_no_host_transfer(lowc)
        lw.assert_donation_covers(lowc, cargs[1], compiled=True)


# ------------------------------------------------------------- GSPMD step
class TestGspmdTrainStep:
    """ISSUE 15's pins on ``make_train_step(spmd="auto")``: the
    annotations really reach the lowering (``assert_sharding``), the
    SPMD partitioner places exactly the sync the shard_map program
    spells by hand (``assert_spmd_collectives`` — the collectives only
    exist in the COMPILED module), donation survives compilation, and
    the optimizer runs its per-leaf path (no whole-tree bucket concat —
    the packed-bucket route was observed MIS-PARTITIONED under GSPMD:
    zeroed pack segments for tp-sharded stacked leaves)."""

    @pytest.fixture(scope="class")
    def gspmd(self, devices8):
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        sspec = AdamState(step=P(), exp_avg=param_specs(CFG),
                          exp_avg_sq=param_specs(CFG), master=None)
        step = make_train_step(CFG, opt, mesh, opt_state_spec=sspec,
                               donate_state=True, spmd="auto")
        tokens, targets = _data()
        low = step.lower(params, state, tokens, targets)
        return mesh, low, low.compile().as_text(), params, state

    def test_param_and_data_annotations_reach_the_lowering(self, gspmd):
        """Column/row/vocab-parallel param layouts and the dp batch
        shard, pinned at the mhlo.sharding attrs via argpath — a spec
        drift (the APX206 class, runtime-side) fails here."""
        mesh, low, _txt, _p, _s = gspmd
        lw.assert_sharding(low, (0, "embed"), mesh, P("tp", None))
        lw.assert_sharding(low, (0, "layers", "wq"), mesh,
                           P(None, "tp", None))
        lw.assert_sharding(low, (0, "layers", "wo"), mesh,
                           P(None, None, "tp"))
        lw.assert_sharding(low, (0, "layers", "ln1_scale"), mesh,
                           P(None, None))
        lw.assert_sharding(low, (2,), mesh, P("dp", None))   # tokens
        # optimizer state mirrors the param sharding (AdamState.exp_avg)
        lw.assert_sharding(low, (1, 1, "layers", "wq"), mesh,
                           P(None, "tp", None))

    def test_partitioner_places_dp_and_tp_sync(self, gspmd):
        """The GSPMD analog of the shard_map program's collective
        structure: a dp-group all-reduce (the grad pmean) and tp-group
        all-reduces (the Megatron f/g collectives) exist; nothing
        lowered to a reduce-scatter (no ZeRO here), and no collective
        spans the WHOLE mesh as one group (dp and tp sync stay
        separate, as in the hand-written program)."""
        mesh, _low, txt, _p, _s = gspmd
        lw.assert_spmd_collectives(txt, "all_reduce", ("dp",), mesh,
                                   minimum=1, dtype="f32")
        lw.assert_spmd_collectives(txt, "all_reduce", ("tp",), mesh,
                                   minimum=1)
        lw.assert_spmd_collectives(txt, "reduce_scatter", maximum=0)

    def test_donation_survives_spmd_compilation(self, gspmd):
        """donate_state=True must alias params AND optimizer state
        through the PARTITIONED executable — the APX208 hazard
        (sharding-mismatched donation) is exactly a silent drop here."""
        _mesh, low, _txt, params, state = gspmd
        lw.assert_donation_covers(low, params, state, compiled=True)

    def test_optimizer_runs_per_leaf_no_whole_tree_concat(self, gspmd):
        """The engine's bucket pack (one flat concat of every leaf)
        must NOT appear: under GSPMD it both forces all-gathers and
        was observed miscompiled (zeroed segments).  The per-leaf
        route's lowering has no tree-sized concatenate."""
        _mesh, low, _txt, params, _state = gspmd
        total = sum(int(np.prod(p.shape))
                    for p in jax.tree_util.tree_leaves(params))
        lw.assert_no_whole_tree_concat(low.as_text(), total)

    def test_rejects_explicit_collective_features(self, devices8):
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
        opt = FusedAdam(lr=1e-2)
        with pytest.raises(NotImplementedError, match="GSPMD"):
            make_train_step(CFG, opt, mesh, spmd="auto",
                            overlap_grad_sync=True)
        with pytest.raises(NotImplementedError, match="ZeRO"):
            make_train_step(CFG, DistributedFusedAdam(lr=1e-2,
                                                      axis_name="dp"),
                            mesh, spmd="auto")
        with pytest.raises(NotImplementedError, match="hierarchical"):
            make_train_step(CFG, opt, mesh, spmd="auto",
                            dp_axis=("dp", "tp"))
        with pytest.raises(ValueError, match="spmd"):
            make_train_step(CFG, opt, mesh, spmd="gspmd")


class TestShardingRuleProof:
    """The live half of APX206's silent-replication claim: the exact
    two-mesh program the analyzer flags COMPILES AND RUNS with zero
    exceptions on real jax — XLA rematerializes and quietly drops the
    intended layout.  If a jax upgrade starts raising here, the rule's
    message (and docs/static_analysis.md) should be re-verified."""

    SRC = """
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh_ci = Mesh(devs, ("dp",))
        mesh_prod = Mesh(devs2, ("dp", "tp"))

        def f(x):
            return jax.lax.with_sharding_constraint(
                x * 2, NamedSharding(mesh_prod, P(None, "tp")))

        step = jax.jit(f, in_shardings=NamedSharding(mesh_ci, P("dp")))
    """

    def test_jit_compiles_and_runs_the_flagged_program(self, devices8):
        from jax.sharding import NamedSharding

        devs = np.array(devices8[:4])
        mesh_ci = Mesh(devs, ("dp",))
        mesh_prod = Mesh(devs.reshape(2, 2), ("dp", "tp"))

        def f(x):
            return jax.lax.with_sharding_constraint(
                x * 2, NamedSharding(mesh_prod, P(None, "tp")))

        step = jax.jit(f, in_shardings=NamedSharding(mesh_ci, P("dp")))
        out = step(jnp.ones((8, 8)))     # no exception: the silent class
        np.testing.assert_array_equal(np.asarray(out), 2.0)

    def test_analyzer_flags_the_same_source(self, tmp_path):
        import textwrap

        from apex_tpu.analysis import analyze_file
        from apex_tpu.analysis.rules_sharding import ShardingSpecAxisUnbound

        p = tmp_path / "silent.py"
        p.write_text(textwrap.dedent(self.SRC))
        got = analyze_file(str(p), [ShardingSpecAxisUnbound()],
                           {"dp", "tp"})
        assert [f.rule for f in got] == ["APX206"]
        assert "silently rematerializes" in got[0].message


# ------------------------------------------------------------------ tracing
class TestTracingTrainStep:
    """ISSUE 14's zero-overhead pins: the ``TracedStep`` dispatch
    wrapper lives entirely OUTSIDE jit, so a traced step's lowering is
    byte-identical to the bare step's — same collective counts/dtypes,
    zero host transfers — with a tracer ACTIVE while lowering (the
    bitwise loss/params side rides tests/test_tracing.py).  A wrapper
    change that sneaks host work (a callback, an id tag) into the
    compiled program fails here."""

    KINDS = ("all_reduce", "reduce_scatter", "all_gather",
             "collective_permute", "all_to_all")

    def _pair(self, build):
        """(lowering under an active tracer via TracedStep, bare
        lowering) for one step builder."""
        from apex_tpu.observability import tracing

        step, args = build()
        with tracing.TracingScope():
            traced = tracing.TracedStep(step, name="train.step.dispatch")
            low_on = traced.lower(*args)
        low_off = step.lower(*args)
        return low_on, low_off

    def _builders(self, devices8):
        def replicated():
            params = init_params(CFG, jax.random.PRNGKey(0))
            opt = FusedAdam(lr=1e-2)
            state = opt.init(params)
            sspec = AdamState(step=P(), exp_avg=param_specs(CFG),
                              exp_avg_sq=param_specs(CFG), master=None)
            step = make_train_step(CFG, opt, _mesh(devices8),
                                   donate_state=True,
                                   opt_state_spec=sspec,
                                   clip_grad_norm=1.0)
            tokens, targets = _data()
            return step, (params, state, tokens, targets)

        def zero_clip():
            params = init_params(CFG, jax.random.PRNGKey(0))
            opt = DistributedFusedAdam(lr=1e-2, axis_name="dp",
                                       bucket_cap_mb=TINY_CAP_MB)
            state = opt.init(params, world_size=DP)
            step = make_train_step(CFG, opt, _mesh(devices8),
                                   donate_state=True, clip_grad_norm=1.0)
            tokens, targets = _data()
            return step, (params, state, tokens, targets)

        def hier_int8():
            params = init_params(CFG, jax.random.PRNGKey(0))
            opt = DistributedFusedAdam(lr=1e-2, dp_axes=HIER_AXES,
                                       bucket_cap_mb=TINY_CAP_MB,
                                       grad_sync_dtype="int8")
            state = opt.init(params, world_size=4,
                             axis_sizes={"dp_out": 2, "dp_in": 2,
                                         "tp": 1})
            step = make_train_step(CFG, opt, _hier_mesh(devices8),
                                   dp_axis=HIER_AXES, donate_state=True)
            rng = np.random.RandomState(0)
            tokens = jnp.asarray(rng.randint(0, CFG.vocab_size,
                                             size=(4, 16)))
            return step, (params, state, tokens,
                          jnp.roll(tokens, -1, axis=1))

        return {"replicated": replicated, "zero_clip": zero_clip,
                "hier_int8": hier_int8}

    @pytest.mark.parametrize("variant",
                             ["replicated", "zero_clip", "hier_int8"])
    def test_lowering_is_byte_identical(self, devices8, variant):
        low_on, low_off = self._pair(self._builders(devices8)[variant])
        assert low_on.as_text() == low_off.as_text()

    @pytest.mark.parametrize("variant",
                             ["replicated", "zero_clip", "hier_int8"])
    def test_same_collective_counts_zero_host_transfers(self, devices8,
                                                        variant):
        low_on, low_off = self._pair(self._builders(devices8)[variant])
        on, off = low_on.as_text(), low_off.as_text()
        for kind in self.KINDS:
            n_on = lw.count_collectives(on, kind, minimum=0)
            assert n_on == lw.count_collectives(off, kind, minimum=0), (
                f"tracing changed {kind} count")
        lw.assert_no_host_transfer(low_on)

    def test_wire_dtype_survives_the_wrapper(self, devices8):
        """The int8 two-hop wire is untouched by tracing: per bucket,
        one i8 reduce-scatter on each hop under the traced lowering."""
        from apex_tpu.observability import tracing

        build = self._builders(devices8)["hier_int8"]
        step, args = build()
        with tracing.TracingScope():
            low = tracing.TracedStep(step).lower(*args)
        mesh = _hier_mesh(devices8)
        txt = low.as_text()
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_in",),
                                  mesh, minimum=1, dtype="i8")
        lw.assert_collective_axes(txt, "reduce_scatter", ("dp_out",),
                                  mesh, minimum=1, dtype="i8")

# ------------------------------------------------------- schedule pins
class TestCollectiveSchedule:
    """``collective_schedule`` / ``assert_same_collective_schedule``
    pins (ISSUE 16): the ORDERED cross-device communication sequence —
    kind, dtype, shape, replica groups, position by position — of
    every production step family, asserted identical across two
    independent builds.  Two processes that lower different schedules
    for the same step wedge a pod device-side; this is the
    single-process, lowering-level spelling of that contract (the
    runtime spelling is ``resilience.uniformity``, the static one
    APX209–211)."""

    def test_flat_zero_schedule_pinned_across_builds(self, devices8):
        low1, opt, _p, _s = _zero_lowering(devices8)
        low2, _opt2, _p2, _s2 = _zero_lowering(devices8)
        scheds = lw.assert_same_collective_schedule(
            low1.as_text(), low2.as_text(), mesh=_mesh(devices8),
            labels=["build 1", "build 2"])
        n = len(opt._plan.buckets)
        kinds = [e["kind"] for e in scheds[0]]
        assert kinds.count("reduce_scatter") == n
        assert kinds.count("all_gather") >= n
        # every grad scatter rides the dp axis at the fp32 wire
        for e in scheds[0]:
            if e["kind"] == "reduce_scatter":
                assert e["axes"] == ("dp",) and e["dtype"] == "f32"

    def test_hierarchical_zero_schedule_pinned(self, devices8):
        low1, opt, _p, _s = _hier_lowering(devices8)
        low2, _opt2, _p2, _s2 = _hier_lowering(devices8)
        scheds = lw.assert_same_collective_schedule(
            low1.as_text(), low2.as_text(), mesh=_hier_mesh(devices8))
        hops = [e["axes"] for e in scheds[0]
                if e["kind"] == "reduce_scatter"]
        # both hops present, in a fixed interleaving across builds
        assert ("dp_in",) in hops and ("dp_out",) in hops

    def test_quantized_zero_schedule_pins_the_i8_wire(self, devices8):
        low1, opt, _p, _s = _zero_lowering(devices8,
                                           grad_sync_dtype="int8")
        low2, _opt2, _p2, _s2 = _zero_lowering(devices8,
                                               grad_sync_dtype="int8")
        scheds = lw.assert_same_collective_schedule(
            low1.as_text(), low2.as_text(), mesh=_mesh(devices8))
        rs_dtypes = {e["dtype"] for e in scheds[0]
                     if e["kind"] == "reduce_scatter"}
        assert "i8" in rs_dtypes, (
            "the compressed wire must appear in the schedule as i8 "
            "reduce-scatters")

    def test_gspmd_auto_schedule_pinned_across_compiles(self, devices8):
        """GSPMD's collectives exist only in the COMPILED module; two
        compiles of the same auto-sharded step must place the identical
        sequence (the partitioner is deterministic — a schedule drift
        here is a jax upgrade changing sync placement under us)."""
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
        params = init_params(CFG, jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        sspec = AdamState(step=P(), exp_avg=param_specs(CFG),
                          exp_avg_sq=param_specs(CFG), master=None)
        step = make_train_step(CFG, opt, mesh, opt_state_spec=sspec,
                               donate_state=True, spmd="auto")
        tokens, targets = _data()
        low = step.lower(params, state, tokens, targets)
        txt1 = low.compile().as_text()
        txt2 = step.lower(params, state, tokens,
                          targets).compile().as_text()
        scheds = lw.assert_same_collective_schedule(txt1, txt2)
        assert any(e["kind"] == "all_reduce" for e in scheds[0]), (
            "the partitioned module must carry the dp/tp all-reduces")

    def test_decode_and_verify_schedules_pinned(self):
        """Single-host serving steps lower a fixed (here: empty)
        collective schedule — a collective appearing in the decode or
        verify lowering is a topology change the scheduler's
        single-process page bookkeeping is not built for."""
        import dataclasses as dc

        cfg, dcfg, params, pools, make_step, _ = TestDecodeStep._build()
        step = make_step(cfg, dcfg)
        B, Pg = dcfg.max_batch, dcfg.cache.pages_per_seq
        dargs = (params, pools, jnp.zeros((B,), jnp.int32),
                 jnp.zeros((B,), jnp.int32), jnp.zeros((B,), bool),
                 jnp.zeros((B, Pg), jnp.int32),
                 jnp.zeros((B,), jnp.uint32))
        low1 = step.lower(*dargs)
        low2 = make_step(cfg, dcfg).lower(*dargs)
        scheds = lw.assert_same_collective_schedule(
            low1.as_text(), low2.as_text(),
            labels=["decode build 1", "decode build 2"])
        assert scheds[0] == []
        from apex_tpu.inference.decode import make_verify_step

        vcfg = dc.replace(dcfg, draft_len=2)
        W = vcfg.draft_len + 1
        vargs = (params, pools, jnp.zeros((B, W), jnp.int32),
                 jnp.zeros((B,), jnp.int32), jnp.zeros((B,), bool),
                 jnp.zeros((B, Pg), jnp.int32),
                 jnp.zeros((B, W), jnp.uint32))
        vlow1 = make_verify_step(cfg, vcfg).lower(*vargs)
        vlow2 = make_verify_step(cfg, vcfg).lower(*vargs)
        vscheds = lw.assert_same_collective_schedule(
            vlow1.as_text(), vlow2.as_text())
        assert vscheds[0] == []


class TestDivergenceRuleProof:
    """The live half of APX209's deadlock claim, provable on one
    process: rank-specialize the SAME step the way the flagged code
    would at runtime (rank 0 takes the branch, rank 1 does not), lower
    both variants, and show their collective schedules diverge — on a
    pod those two programs block in different collectives forever.
    The analyzer flags the source; the lowering mismatch is the
    ground truth it predicts."""

    SRC = """
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def grad_sync(g):
            return jax.lax.psum(g, "dp")

        step = shard_map(grad_sync, mesh=mesh, in_specs=P("dp"),
                         out_specs=P("dp"))

        def maybe_probe(x):
            if jax.process_index() == 0:
                return step(x)
            return x
    """

    def test_analyzer_flags_the_rank_gated_launch(self, tmp_path):
        import textwrap

        from apex_tpu.analysis import analyze_file
        from apex_tpu.analysis.rules_divergence import (
            TaintedPredicateGuardsCollective,
        )

        p = tmp_path / "gated.py"
        p.write_text(textwrap.dedent(self.SRC))
        got = analyze_file(str(p), [TaintedPredicateGuardsCollective()],
                           {"dp"})
        assert [f.rule for f in got] == ["APX209"]
        assert "wedges" in got[0].message

    def test_rank_specialized_variants_lower_divergent_schedules(
            self, devices8):
        """What each process would actually lower under the flagged
        ``if``: rank 0's trace launches the psum, rank 1's skips it.
        ``assert_same_collective_schedule`` names the divergence — the
        proof the static rule's deadlock claim rests on."""
        from jax.experimental.shard_map import shard_map

        mesh = Mesh(np.array(devices8).reshape(DP), ("dp",))
        sync = shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
                         in_specs=P("dp"), out_specs=P(None))

        def as_rank(rank):
            def maybe_probe(x):
                return sync(x) if rank == 0 else x * 1.0
            return jax.jit(maybe_probe).lower(
                jnp.ones((DP, 4), jnp.float32))

        rank0, rank1 = as_rank(0), as_rank(1)
        with pytest.raises(AssertionError, match="diverge"):
            lw.assert_same_collective_schedule(
                rank0.as_text(), rank1.as_text(),
                labels=["process 0", "process 1"])
        # and the uniform spelling passes: both ranks launching is fine
        lw.assert_same_collective_schedule(rank0.as_text(),
                                           as_rank(0).as_text())


class TestRingOverlapLowering:
    """The overlapped ring's lowering shape, pinned at the StableHLO
    tier: ``overlap=True`` unrolls the ring and issues hop r+1's
    ppermute before chunk r's compute, so ``collective_permute`` sites
    interleave with the per-chunk matmuls — the latency-hiding
    scheduler has compute to hide every hop behind.  The serial scan
    traces its two permutes back-to-back at the end of the loop body
    (no dots between any consecutive pair).  ``impl="scan"`` keeps the
    chunk matmuls visible as ``dot_general`` (Pallas kernel bodies are
    opaque to the HLO text)."""

    def _lowering(self, devices8, overlap):
        from apex_tpu.transformer.context_parallel import ring_attention

        cp = 4
        mesh = Mesh(np.array(devices8[:cp]), ("cp",))
        q = jnp.zeros((1, 2, cp * 64, 16), jnp.float32)
        f = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp", causal=False,
                                           impl="scan", overlap=overlap),
            mesh=mesh, in_specs=(P(None, None, "cp", None),) * 3,
            out_specs=P(None, None, "cp", None), check_vma=False)
        return jax.jit(f).lower(q, q, q)

    def test_overlap_permutes_interleave_with_chunk_dots(self, devices8):
        low = self._lowering(devices8, True)
        # unrolled: cp-1 = 3 hops x (k, v), the final rotation elided
        lw.count_collectives(low, "collective_permute", minimum=6,
                             maximum=6)
        gaps = lw.assert_interleaved(low, "collective_permute", gaps="any")
        # hop r+1's pair issues before chunk r's dots, so at least one
        # chunk's matmuls sit between consecutive permute sites
        assert max(gaps) >= 1

    def test_serial_permutes_trace_back_to_back(self, devices8):
        low = self._lowering(devices8, False)
        lw.assert_interleaved(low, "collective_permute", gaps="none")
