"""GPT long-context options: flash attention core parity and ring-
attention context parallelism parity vs the dense single-device model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.gpt import (
    GPTConfig,
    gpt_forward,
    gpt_loss,
    init_params,
    make_train_step,
    param_specs,
)
from apex_tpu.optimizers import FusedAdam

BASE = dict(
    vocab_size=64,
    hidden_size=32,
    num_layers=2,
    num_attention_heads=4,
    max_seq_len=32,
    compute_dtype=jnp.float32,
    checkpoint_layers=False,
)


@pytest.mark.slow
def test_flash_core_matches_einsum_core():
    cfg_e = GPTConfig(**BASE)
    cfg_f = GPTConfig(**BASE, use_flash_attention=True)
    params = init_params(cfg_e, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, size=(2, 32)))
    out_e = gpt_forward(params, tokens, cfg_e)
    out_f = gpt_forward(params, tokens, cfg_f)
    np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_f), rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_cp_forward_matches_single_device(devices8):
    cfg = GPTConfig(**BASE)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 64, size=(2, 32)))
    ref = gpt_forward(params, tokens, cfg)

    mesh = Mesh(np.array(devices8[:4]), ("cp",))
    f = jax.shard_map(
        lambda p, t: gpt_forward(p, t, cfg, cp_axis="cp"),
        mesh=mesh,
        in_specs=(P(), P(None, "cp")),
        out_specs=P("cp", None, None),
        check_vma=False,
    )
    out = f(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("fused_ce", [False, True])
def test_cp_train_step_matches_single_device(devices8, fused_ce):
    """cp=2 × dp=2 × tp=2 full train step == single-device step — with
    and without the chunked fused LM-head+CE (its per-local-chunk loss
    + the cp-mean calculus must agree with the dense head)."""
    cfg = GPTConfig(**BASE, fused_ce=fused_ce, fused_ce_chunk=8)
    mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "cp", "tp"))
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)

    rng = np.random.RandomState(2)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(4, 32)))
    targets = jnp.roll(tokens, -1, axis=1)

    step = make_train_step(cfg, opt, mesh, cp_axis="cp")
    new_params, _, loss = step(params, state, tokens, targets)

    import dataclasses

    dense_cfg = dataclasses.replace(cfg, fused_ce=False)
    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(
        params, tokens, targets, dense_cfg)
    ref_params, _ = opt.update(ref_grads, opt.init(params), params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(new_params),
        jax.tree_util.tree_leaves_with_path(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-5,
            err_msg=jax.tree_util.keystr(ka),
        )


def test_cp_and_sp_together_rejected():
    cfg = GPTConfig(**BASE, sequence_parallel=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError):
        gpt_forward(params, jnp.zeros((1, 4), jnp.int32), cfg, cp_axis="cp")


@pytest.mark.slow
def test_cp_composed_with_pp_matches_single_device(devices8):
    """4D matrix: cp ring attention inside pipeline stages
    (pp=2 x cp=2 x tp=2) vs the single-device oracle."""
    from apex_tpu.models.gpt import make_pp_train_step
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.optimizers.fused_sgd import SGDState
    from jax.sharding import PartitionSpec as P

    cfg = GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=4,
        num_attention_heads=4, max_seq_len=32,
        compute_dtype=jnp.float32, checkpoint_layers=False,
    )
    mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("cp", "pp", "tp"))
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = FusedSGD(lr=1e-2, momentum=0.0)
    state = opt.init(params)

    from apex_tpu.models.gpt import param_specs as gpt_param_specs

    base = gpt_param_specs(cfg)
    specs = dict(base)
    specs["layers"] = jax.tree.map(lambda s: P("pp", *s[1:]), base["layers"],
                                   is_leaf=lambda s: isinstance(s, P))
    sspec = SGDState(step=P(), momentum_buffer=specs, master=None)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(4, 32)))
    targets = jnp.roll(tokens, -1, axis=1)

    step = make_pp_train_step(cfg, opt, mesh, num_microbatches=2,
                              dp_axis=None, cp_axis="cp", opt_state_spec=sspec)
    new_params, _, loss = step(params, state, tokens, targets)

    ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, tokens, targets, cfg)
    ref_params, _ = opt.update(ref_grads, opt.init(params), params)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(new_params),
        jax.tree_util.tree_leaves_with_path(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-5,
            err_msg=jax.tree_util.keystr(ka),
        )
