"""Gradient-accumulation fusion (main_grad contract) — parity vs one
large-batch backward, fp32 accumulation under bf16 params, and the HLO
memory bound (one persistent grad buffer, nothing scaling with M)."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.tensor_parallel import accumulate_gradients, make_grad_accumulator


def loss_fn(params, mb):
    h = jnp.tanh(mb["x"] @ params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred.astype(jnp.float32) - mb["y"]) ** 2)


def make_problem(M=6, MB=4, D=8, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    params = {
        "w1": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3, dtype),
        "w2": jnp.asarray(rng.randn(D, 1).astype(np.float32) * 0.3, dtype),
    }
    mbs = {
        "x": jnp.asarray(rng.randn(M, MB, D).astype(np.float32), dtype),
        "y": jnp.asarray(rng.randn(M, MB, 1).astype(np.float32)),
    }
    return params, mbs


class TestGradAccumulation:
    def test_matches_large_batch_backward(self):
        params, mbs = make_problem()
        loss, grads = accumulate_gradients(loss_fn, params, mbs)

        def big(params):
            flat = {k: v.reshape(-1, *v.shape[2:]) for k, v in mbs.items()}
            return loss_fn(params, flat)

        ref_loss, ref_grads = jax.value_and_grad(big)(params)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        for a, r in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-5, atol=1e-6)

    def test_fp32_accumulation_under_bf16_params(self):
        """The main_grad property: half model, fp32 grad buffer."""
        params, mbs = make_problem(dtype=jnp.bfloat16)
        _, grads = accumulate_gradients(loss_fn, params, mbs)
        assert all(g.dtype == jnp.float32 for g in jax.tree.leaves(grads))

    def test_single_resident_buffer_in_hlo(self):
        """No gradient-sized buffer scales with the microbatch count —
        the property wgrad_gemm_accum_fp32 exists for."""
        D = 16
        for M in (8, 32):
            params, mbs = make_problem(M=M, D=D)
            f = jax.jit(lambda p, m: accumulate_gradients(loss_fn, p, m))
            txt = f.lower(params, mbs).compile().as_text()
            # gradient-shaped buffers: f32[D,D]; count stacked variants
            # f32[M,D,D] (a per-microbatch grad materialization leak)
            leaked = re.findall(rf"f32\[{M},{D},{D}\]", txt)
            assert not leaked, (M, leaked)

    def test_under_shard_map_with_tp(self, devices8):
        """Collectives inside loss_fn run per microbatch (reference
        backward ordering); accumulated grads equal the dense run."""
        from apex_tpu.transformer.tensor_parallel.layers import column_parallel_linear

        D, M, MB = 8, 4, 2
        rng = np.random.RandomState(3)
        w = jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3)
        mbs = {
            "x": jnp.asarray(rng.randn(M, MB, D).astype(np.float32)),
            "y": jnp.asarray(rng.randn(M, MB, D).astype(np.float32)),
        }

        def tp_loss(params, mb):
            y = column_parallel_linear(mb["x"], params["w"], gather_output=True,
                                       axis_name="tp")
            return jnp.mean((y - mb["y"]) ** 2)

        mesh = Mesh(np.array(devices8[:4]), ("tp",))
        accum = make_grad_accumulator(tp_loss)
        loss, grads = jax.shard_map(
            accum, mesh=mesh,
            in_specs=({"w": P("tp", None)}, P()),
            out_specs=(P(), {"w": P("tp", None)}),
            check_vma=False,
        )({"w": w}, mbs)

        def dense_loss(params):
            losses = jax.vmap(lambda x, y: jnp.mean((x @ params["w"].T - y) ** 2))(
                mbs["x"], mbs["y"]
            )
            return jnp.mean(losses)

        ref_loss, ref_g = jax.value_and_grad(dense_loss)({"w": w})
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(grads["w"]), np.asarray(ref_g["w"]),
                                   rtol=1e-5, atol=1e-6)
