"""apex_tpu.resilience: the survivability pillar, proven by chaos.

Every fault these tests inject is one the project has actually suffered
(VERDICT r5): NaN gradients mid-run, Pallas kernels failing at launch on
hardware they were never proven on, preemptions landing between
checkpoint flushes, and sections wedging forever.  The chaos harness
(:mod:`apex_tpu.resilience.chaos`) injects them deterministically into
the virtual 8-device mesh, so the recovery machinery — kernel fallback
registry, step guard, preemption-safe resume — is proven end to end on
CPU today with the same seams real faults will take on TPU.

Rides the quick tier (no ``slow`` marks): every model here is tiny and
every loop is a handful of steps.
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from apex_tpu import resilience
from apex_tpu.amp import DynamicLossScaler
from apex_tpu.io import AsyncCheckpointer, latest_checkpoint, load_checkpoint
from apex_tpu.models.gpt import GPTConfig, init_params, make_train_step
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import (
    BadStepBudgetExceeded,
    ChaosKernelFailure,
    ChaosMonkey,
    ChaosPlan,
    KernelFallbackRegistry,
    PreemptionHandler,
    StepGuard,
    get_registry,
    load_rng_tracker_state_dict,
    rng_tracker_state_dict,
    trip_from_exception,
)
from apex_tpu.resilience.chaos import check_kernel


@pytest.fixture(autouse=True)
def _clean_registry():
    """Each test starts with an untripped process-global registry."""
    get_registry().reset()
    yield
    get_registry().reset()


# ------------------------------------------------------------ step guard
class TestStepGuard:
    def test_counts_consecutive_and_total(self):
        g = StepGuard(max_consecutive_bad=3)
        s = g.init()
        for finite in (True, False, False, True, False):
            s = g.update(s, jnp.bool_(finite))
        assert int(s.step) == 5
        assert int(s.total_skipped) == 3
        assert int(s.consecutive_bad) == 1  # streak reset by the True

    def test_budget_check_raises_with_state(self):
        g = StepGuard(max_consecutive_bad=2)
        s = g.init()
        s = g.update(s, jnp.bool_(False))
        g.check(s)  # 1 < 2: fine
        s = g.update(s, jnp.bool_(False))
        assert bool(g.exhausted(s))
        with pytest.raises(BadStepBudgetExceeded) as ei:
            g.check(s)
        assert "2 consecutive" in str(ei.value)
        assert int(ei.value.guard_state.total_skipped) == 2

    def test_state_dict_roundtrip(self):
        g = StepGuard()
        s = g.update(g.update(g.init(), jnp.bool_(False)), jnp.bool_(True))
        back = g.load_state_dict(g.state_dict(s))
        assert g.state_dict(back) == g.state_dict(s)
        assert g.state_dict(g.load_state_dict(None)) == g.state_dict(g.init())

    def test_budget_validation(self):
        with pytest.raises(ValueError):
            StepGuard(max_consecutive_bad=0)


# --------------------------------------------------- fallback registry
class TestKernelFallbackRegistry:
    def test_kernel_path_counts(self):
        r = KernelFallbackRegistry()
        assert r.call("fused_ce", lambda: "kernel", lambda: "fallback") \
            == "kernel"
        st = r.status()["fused_ce"]
        assert (st["kernel_calls"], st["fallback_calls"]) == (1, 0)
        assert not r.tripped("fused_ce")

    def test_failure_trips_once_and_degrades(self):
        r = KernelFallbackRegistry()
        calls = {"kernel": 0}

        def kernel():
            calls["kernel"] += 1
            raise RuntimeError("Mosaic lowering surprise")

        assert r.call("layer_norm", kernel, lambda: "fallback") == "fallback"
        # degrade ONCE: the tripped kernel is never re-entered
        assert r.call("layer_norm", kernel, lambda: "fallback") == "fallback"
        assert calls["kernel"] == 1
        st = r.status()["layer_norm"]
        assert st["tripped"] and "Mosaic" in st["error"]
        assert st["fallback_calls"] == 2

    def test_reset_rearms(self):
        r = KernelFallbackRegistry()
        r.trip("flash_attention", RuntimeError("boom"))
        r.reset("flash_attention")
        assert not r.tripped("flash_attention")
        assert r.call("flash_attention", lambda: "k", lambda: "f") == "k"

    def test_trip_from_exception_attributes_by_marker(self):
        got = trip_from_exception(
            RuntimeError("error while lowering _dx_kernel for fused_ce"))
        assert got == ["fused_ce"]
        assert get_registry().tripped("fused_ce")
        assert not get_registry().tripped("flash_attention")

    def test_trip_from_exception_shared_marker_trips_every_owner(self):
        """``_fwd_kernel`` is a def in BOTH flash_attention_pallas.py
        and fused_ce_pallas.py: an error naming only it must trip both
        owners (the innocent one pays throughput; tripping the wrong
        one alone would re-lower the broken kernel and crash)."""
        got = trip_from_exception(
            RuntimeError("lowering failed in _fwd_kernel at vmem limit"))
        assert sorted(got) == ["flash_attention", "fused_ce"]
        assert not get_registry().tripped("layer_norm")

    def test_trip_from_exception_generic_mosaic_trips_all(self):
        from apex_tpu.resilience.fallback import KERNELS

        got = trip_from_exception(
            RuntimeError("INTERNAL: Mosaic failed to compile module"))
        # an unattributable Mosaic error must trip EVERY registered
        # kernel (incl. the decode pair) — pin against the registry
        # itself so a new kernel cannot silently escape the net
        assert sorted(got) == sorted(KERNELS)
        assert {"flash_attention", "fused_ce", "layer_norm",
                "decode_attention", "decode_sampling"} <= set(got)

    def test_trip_from_exception_ignores_unrelated(self):
        assert trip_from_exception(ValueError("shape mismatch")) == []
        assert not any(v["tripped"]
                       for v in get_registry().status().values())

    def test_trip_from_exception_ignores_bare_op_names(self):
        """XLA runtime errors embed HLO names derived from the traced
        Python functions: an OOM whose dump mentions `layer_norm` or
        `flash_attention` is NOT a kernel failure and must not be
        attributed — the caller would swallow the real error and burn a
        full recompile per retry with innocent kernels degraded."""
        got = trip_from_exception(RuntimeError(
            "RESOURCE_EXHAUSTED: out of memory while allocating for "
            "fusion.123 (derived from layer_norm and flash_attention)"))
        assert got == []
        assert not any(v["tripped"]
                       for v in get_registry().status().values())

    def test_trip_from_exception_bare_pallas_is_not_generic(self):
        """"pallas" is the API name, not a failure signature: it shows
        up in module paths and buffer names of successfully-compiled
        kernels inside unrelated errors (OOM dumps).  Only "mosaic" —
        the TPU kernel compiler — is a trip-everything trigger."""
        got = trip_from_exception(RuntimeError(
            "RESOURCE_EXHAUSTED: while allocating buffer for "
            "jit(step)/pallas/pallas_call.py custom-call"))
        assert got == []
        assert not any(v["tripped"]
                       for v in get_registry().status().values())

    def test_argument_error_untrips_after_fallback_rejects(self):
        """A validation error raised inside the kernel closure trips the
        kernel — but when the reference impl rejects the SAME call, the
        fault is the arguments, not the kernel: the trip is undone so
        later valid calls still reach the kernel."""
        reg = KernelFallbackRegistry()

        def bad(which):
            def impl():
                raise ValueError(f"H %% Hkv != 0 ({which})")
            return impl

        with pytest.raises(ValueError, match="fallback"):
            reg.call("flash_attention", bad("kernel"), bad("fallback"))
        assert not reg.tripped("flash_attention")
        assert reg.call("flash_attention", lambda: "kernel",
                        lambda: "fallback") == "kernel"

    def test_registry_disengaged_multiprocess(self, monkeypatch):
        """A per-process degrade lowers mismatched collective programs
        across hosts (device-side deadlock with no error): multi-process
        runs never engage the registry, even under chaos."""
        from apex_tpu.resilience import registry_engaged

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        assert not registry_engaged(forced=False)
        with ChaosMonkey(ChaosPlan.make()).active():
            assert not registry_engaged(forced=False)

    def test_trip_from_exception_ignores_oom_with_marker_names(self):
        """An HBM OOM's buffer dump names allocations by op metadata —
        including the ``*_pallas`` entry-point names of kernels that
        compiled fine.  Resource exhaustion is a runtime failure, not a
        lowering failure: nothing trips, the real error surfaces."""
        got = trip_from_exception(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            "1073741824 bytes; largest allocation: custom-call "
            "fused_ce_fwd_pallas from jit(step)"))
        assert got == []
        assert not any(v["tripped"]
                       for v in get_registry().status().values())

    def test_registry_engaged_semantics(self):
        """A forced kernel impl bypasses the registry (fail loudly);
        the chaos harness re-engages it (CPU tests force `interpret`
        to reach the kernel path at all)."""
        from apex_tpu.resilience import registry_engaged

        assert registry_engaged(forced=False)
        assert not registry_engaged(forced=True)
        with ChaosMonkey(ChaosPlan.make()).active():
            assert registry_engaged(forced=True)

    def test_forced_impl_bypasses_tripped_registry(self, monkeypatch):
        """`fused_ce_impl="interpret"` is a demand: run THIS impl or
        fail loudly.  A registry tripped elsewhere in the process must
        not silently swap the kernel for its reference — kernel-vs-
        oracle tests would pass vacuously."""
        from apex_tpu.ops.fused_ce import fused_lm_head_ce

        # fp32 dot accumulation so the two impls compare tightly (the
        # test_fused_ce_pallas.py convention)
        monkeypatch.setenv("APEX_TPU_FUSED_CE_DOT", "float32")
        S, B, H, V = 8, 2, 16, 32
        x = jax.random.normal(jax.random.PRNGKey(0), (S, B, H), jnp.float32)
        e = jax.random.normal(jax.random.PRNGKey(1), (V, H), jnp.float32)
        t = jax.random.randint(jax.random.PRNGKey(2), (S, B), 0, V)

        get_registry().trip("fused_ce", RuntimeError("tripped elsewhere"))
        loss = fused_lm_head_ce(x, e, t, 8, None, "interpret")
        ref = fused_lm_head_ce(x, e, t, 8, None, "off")
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                                   rtol=1e-5)
        st = get_registry().status()["fused_ce"]
        assert st["fallback_calls"] == 0  # bypassed: the kernel ran

    def test_env_forced_impl_bypasses_tripped_registry(self, monkeypatch):
        """APEX_TPU_FUSED_CE_PALLAS=interpret with impl=None is just as
        forced as an explicit impl arg: the env-driven kernel-vs-oracle
        fixtures rely on the kernel actually running, so the registry
        must stay out of the way (a silent degrade would compare the
        scan impl against itself)."""
        from apex_tpu.ops.fused_ce import fused_lm_head_ce

        monkeypatch.setenv("APEX_TPU_FUSED_CE_DOT", "float32")
        monkeypatch.setenv("APEX_TPU_FUSED_CE_PALLAS", "interpret")
        S, B, H, V = 8, 2, 16, 32
        x = jax.random.normal(jax.random.PRNGKey(0), (S, B, H), jnp.float32)
        e = jax.random.normal(jax.random.PRNGKey(1), (V, H), jnp.float32)
        t = jax.random.randint(jax.random.PRNGKey(2), (S, B), 0, V)

        get_registry().trip("fused_ce", RuntimeError("tripped elsewhere"))
        loss = fused_lm_head_ce(x, e, t, 8, None, None)
        ref = fused_lm_head_ce(x, e, t, 8, None, "off")
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                                   rtol=1e-5)
        st = get_registry().status()["fused_ce"]
        assert st["fallback_calls"] == 0  # bypassed: the kernel ran


# ------------------------------------------------------------- chaos
class TestChaosMonkey:
    def test_grad_fault_poisons_exactly_planned_steps(self):
        m = ChaosMonkey(ChaosPlan.make(nan_grad_steps=[1, 3]))
        vals = [float(m.grad_fault(jnp.int32(i))) for i in range(5)]
        assert np.isnan(vals[1]) and np.isnan(vals[3])
        assert vals[0] == vals[2] == vals[4] == 1.0

    def test_grad_fault_unarmed_is_constant_one(self):
        m = ChaosMonkey(ChaosPlan.make())
        assert float(m.grad_fault(jnp.int32(7))) == 1.0

    def test_kernel_failure_budget_burns_down(self):
        m = ChaosMonkey(ChaosPlan.make(kernel_failures={"fused_ce": 2}))
        with m.active():
            with pytest.raises(ChaosKernelFailure):
                check_kernel("fused_ce")
            check_kernel("layer_norm")  # unarmed kernel: no injection
            with pytest.raises(ChaosKernelFailure):
                check_kernel("fused_ce")
            check_kernel("fused_ce")  # budget exhausted: clean
        assert m.injected["kernel:fused_ce"] == 2
        check_kernel("fused_ce")  # monkey deactivated: never fires

    def test_registry_fallback_on_injected_failure(self):
        """The registry seam: an armed plan degrades the kernel call
        exactly like a real launch failure would."""
        r = KernelFallbackRegistry()
        m = ChaosMonkey(ChaosPlan.make(kernel_failures={"layer_norm": 1}))
        with m.active():
            assert r.call("layer_norm", lambda: "k", lambda: "f") == "f"
        assert r.tripped("layer_norm")

    def test_wedge_sleeps_and_counts(self):
        import time

        m = ChaosMonkey(ChaosPlan.make(wedge_seconds={"bench.x": 0.05}))
        with m.active():
            t0 = time.monotonic()
            assert m.maybe_wedge("bench.x") == 0.05
            assert time.monotonic() - t0 >= 0.05
            assert m.maybe_wedge("bench.y") == 0.0
        assert m.injected["wedge:bench.x"] == 1

    def test_preemption_delivered_at_planned_step(self):
        m = ChaosMonkey(ChaosPlan.make(preempt_at_step=3))
        pre = PreemptionHandler()
        assert not m.maybe_preempt(2, pre) and not pre.preempted
        assert m.maybe_preempt(3, pre)
        assert pre.preempted and "chaos" in pre.reason


# -------------------------------------------------------- preemption
class TestPreemptionHandler:
    def test_sigterm_sets_flag_and_restores_handler(self):
        prev = signal.getsignal(signal.SIGTERM)
        with PreemptionHandler() as pre:
            assert not pre.preempted
            os.kill(os.getpid(), signal.SIGTERM)
            assert pre.preempted
            assert "SIGTERM" in pre.reason
        assert signal.getsignal(signal.SIGTERM) is prev

    def test_deadline_counts_as_preemption(self):
        pre = PreemptionHandler(deadline_sec=10.0, grace_sec=30.0)
        assert pre.preempted  # inside the grace window already
        assert "deadline" in pre.reason

    def test_drain_makes_async_saves_durable(self, tmp_path):
        ck = AsyncCheckpointer()
        try:
            pre = PreemptionHandler()
            pre.simulate()
            ck.save(tmp_path / "step_00000001.ckpt", {"x": jnp.arange(4.0)})
            pre.drain(ck)
            got = load_checkpoint(tmp_path / "step_00000001.ckpt")
            np.testing.assert_array_equal(got["x"], np.arange(4.0))
        finally:
            ck.close()

    def test_drain_is_not_reentrant_but_waits_for_completion(self):
        """A second drain landing while one is in flight (watchdog
        thread firing mid-preemption-drain, schedulers resending
        SIGTERM) never re-enters the flush — but it WAITS for the
        in-flight one: returning early would let a watchdog report
        'drained' and exit while the first flush is still writing."""
        import threading
        import time

        entered = []
        release = threading.Event()
        started = threading.Event()

        class SlowCkpt:
            def wait_until_finished(self):
                entered.append(1)
                started.set()
                release.wait(5.0)

        pre = PreemptionHandler()
        ck = SlowCkpt()
        t = threading.Thread(target=pre.drain, args=(ck,))
        t.start()
        assert started.wait(5.0)
        t0 = time.monotonic()
        reentrant_done = threading.Event()

        def second():
            pre.drain(ck)  # must block until the first flush lands
            reentrant_done.set()

        threading.Thread(target=second).start()
        time.sleep(0.2)
        assert not reentrant_done.is_set()  # still waiting on flush #1
        release.set()
        t.join(5.0)
        assert reentrant_done.wait(5.0)
        assert time.monotonic() - t0 >= 0.2
        assert len(entered) == 1            # ONE flush served both
        # after the in-flight drain completes, a NEW drain runs again
        pre.drain(ck)
        assert len(entered) == 2

    def test_reentrant_drain_sees_inflight_failure(self):
        """A caller that piggybacks on an in-flight drain must NOT
        report success when that flush failed — a watchdog would log
        'drained' and exit over an unflushed save."""
        import threading

        release = threading.Event()
        started = threading.Event()

        class FailingCkpt:
            def wait_until_finished(self):
                started.set()
                release.wait(5.0)
                raise RuntimeError("disk full mid-flush")

        pre = PreemptionHandler()
        ck = FailingCkpt()
        first_err = []

        def first():
            try:
                pre.drain(ck)
            except RuntimeError as e:
                first_err.append(e)

        t = threading.Thread(target=first)
        t.start()
        assert started.wait(5.0)
        waiter_err = []

        def second():
            try:
                pre.drain(ck)
            except RuntimeError as e:
                waiter_err.append(e)

        t2 = threading.Thread(target=second)
        t2.start()
        release.set()
        t.join(5.0)
        t2.join(5.0)
        assert first_err and "disk full" in str(first_err[0])
        assert waiter_err and "in-flight drain failed" in str(waiter_err[0])

    def test_sigterm_during_drain_only_sets_flag(self):
        """SIGTERM arriving DURING the drain: the handler sets the flag
        and chains — it never calls drain itself, so the in-flight
        flush completes exactly once and the process can still exit 0
        (the process-level twin lives in test_gpt_example.py)."""
        import threading

        entered = []
        release = threading.Event()

        class SlowCkpt:
            def wait_until_finished(self):
                entered.append(1)
                # SIGTERM lands while the main thread is INSIDE drain
                os.kill(os.getpid(), signal.SIGTERM)
                release.wait(2.0)

        with PreemptionHandler() as pre:
            pre.simulate("first notice")
            release.set()
            pre.drain(SlowCkpt())
            assert pre.preempted  # the mid-drain signal registered
        assert len(entered) == 1

    def test_rng_tracker_roundtrip_continues_streams(self):
        """A resume that reset the fork counter would replay dropout
        masks; the snapshot must continue the stream exactly."""
        from apex_tpu.transformer.tensor_parallel.random import (
            RNGStatesTracker,
        )

        tracker = RNGStatesTracker()
        tracker.add("model-parallel-rng", 17)
        tracker.fork("model-parallel-rng")  # burn one: counter now 1
        snap = rng_tracker_state_dict(tracker)

        fresh = RNGStatesTracker()
        load_rng_tracker_state_dict(snap, fresh)
        a = tracker.fork("model-parallel-rng")
        b = fresh.fork("model-parallel-rng")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert fresh.counts_ == tracker.counts_ == {
            "model-parallel-rng": 2}


# ------------------------------------------------- bench.py fault paths
class TestBenchHarness:
    """The wedge/timeout seams in bench.py, driven by chaos — the
    subprocess section runner is what lets a ResNet-50 compile wedge
    bank its partials without killing the later sections."""

    @pytest.fixture(autouse=True)
    def _bench(self, tmp_path, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "_SECTIONS_PATH",
                            str(tmp_path / "sections.jsonl"))
        monkeypatch.setattr(bench, "_DEVICE_WEDGED", False)
        import time

        monkeypatch.setattr(bench, "_DEADLINE", time.monotonic() + 120)
        self.bench = bench

    def test_try_watchdog_catches_injected_wedge(self):
        m = ChaosMonkey(ChaosPlan.make(wedge_seconds={"bench.stuck": 5.0}))
        with m.active():
            r = self.bench._try("stuck", lambda: {"v": 1},
                                section_budget=0.2)
        assert "timeout" in r["error"]
        assert self.bench._DEVICE_WEDGED  # in-process: thread unkillable

    def test_subprocess_section_timeout_does_not_wedge_device(self):
        import sys

        r = self.bench._try_subprocess(
            "resnet50_b64", section_budget=1.0,
            cmd=[sys.executable, "-c", "import time; time.sleep(30)"])
        assert "timeout" in r["error"]
        assert not self.bench._DEVICE_WEDGED  # the wedge died with the child

    def test_subprocess_section_result_round_trip(self):
        import sys

        child = ("import json; print('noise'); print(json.dumps("
                 "{'section': 'resnet50_b64', "
                 "'result': {'images_per_sec': 9.0}}))")
        r = self.bench._try_subprocess("resnet50_b64", section_budget=30.0,
                                       cmd=[sys.executable, "-c", child])
        assert r == {"images_per_sec": 9.0}

    def test_subprocess_device_acquisition_failure_retries_in_process(
            self, monkeypatch):
        """Exclusive local TPU: the parent process owns the chip, so no
        child can ever acquire it — the section retries in-process (the
        only way to get a number there) instead of failing every round."""
        import sys

        monkeypatch.setitem(self.bench._SUBPROCESS_SECTIONS,
                            "resnet50_b64",
                            lambda: {"images_per_sec": 7.0})
        r = self.bench._try_subprocess(
            "resnet50_b64", section_budget=30.0,
            cmd=[sys.executable, "-c",
                 "import sys; print('The TPU is already in use by another "
                 "process', file=sys.stderr); sys.exit(1)"])
        assert r == {"images_per_sec": 7.0}
        assert not self.bench._DEVICE_WEDGED

    def test_subprocess_child_crash_is_recorded_not_raised(self):
        import sys

        r = self.bench._try_subprocess(
            "resnet50_b64", section_budget=30.0,
            cmd=[sys.executable, "-c",
                 "import sys; print('dying', file=sys.stderr); sys.exit(3)"])
        assert "rc=3" in r["error"]


# --------------------------------------------------- end-to-end survival
CFG = GPTConfig(
    vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
    max_seq_len=16, compute_dtype=jnp.float32, checkpoint_layers=False,
)


def _data(seed=0, batch=8, seq=16):
    rng = np.random.RandomState(seed)
    tok = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(batch, seq)))
    return tok, jnp.roll(tok, -1, axis=1)


def _mesh(devices8):
    return Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))


def _leaves_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


class TestEndToEndSurvival:
    def test_nan_step_skipped_scaler_backs_off_then_training_resumes(
            self, devices8):
        """Injected NaN grads at step 1: the update is skipped
        device-side (params bitwise unchanged, Adam step counter held),
        the scaler backs off, the guard counts it — and step 2 trains
        normally from the pre-fault params."""
        scaler = DynamicLossScaler(init_scale=2.0 ** 8, hysteresis=1)
        guard = StepGuard(max_consecutive_bad=3)
        chaos = ChaosMonkey(ChaosPlan.make(nan_grad_steps=[1]))
        opt = FusedAdam(lr=1e-2)
        params = init_params(CFG, jax.random.PRNGKey(0))
        state = opt.init(params)
        sstate, gstate = scaler.init(), guard.init()
        step = make_train_step(CFG, opt, _mesh(devices8),
                               loss_scaler=scaler, step_guard=guard,
                               chaos=chaos)
        tok, tgt = _data()

        params, state, sstate, gstate, loss0 = step(
            params, state, sstate, gstate, tok, tgt)
        assert np.isfinite(float(loss0))
        before = jax.tree.map(np.asarray, params)
        before_opt_step = int(state.step)
        scale_before = float(sstate.loss_scale)

        params, state, sstate, gstate, loss1 = step(
            params, state, sstate, gstate, tok, tgt)
        assert not np.isfinite(float(loss1))          # the poisoned step
        assert _leaves_equal(params, before)          # update skipped
        assert int(state.step) == before_opt_step     # Adam counter held
        assert float(sstate.loss_scale) < scale_before  # backoff
        assert int(gstate.total_skipped) == 1
        assert int(gstate.consecutive_bad) == 1
        guard.check(gstate)  # within budget: no raise

        params, state, sstate, gstate, loss2 = step(
            params, state, sstate, gstate, tok, tgt)
        assert np.isfinite(float(loss2))
        assert int(gstate.consecutive_bad) == 0       # streak reset
        assert not _leaves_equal(params, before)      # trained again

    def test_bad_step_budget_aborts_unscaled_loop(self, devices8):
        """No loss scaler: the guard brings its own all_finite vote, and
        a NaN storm exhausts the budget into a clean abort signal."""
        guard = StepGuard(max_consecutive_bad=2)
        chaos = ChaosMonkey(ChaosPlan.make(nan_grad_steps=[0, 1, 2, 3]))
        opt = FusedAdam(lr=1e-2)
        params = init_params(CFG, jax.random.PRNGKey(0))
        state = opt.init(params)
        gstate = guard.init()
        step = make_train_step(CFG, opt, _mesh(devices8), step_guard=guard,
                               chaos=chaos)
        tok, tgt = _data()

        with pytest.raises(BadStepBudgetExceeded) as ei:
            for _ in range(4):
                params, state, gstate, _ = step(params, state, gstate,
                                                tok, tgt)
                guard.check(gstate)
        assert int(ei.value.guard_state.consecutive_bad) == 2

    def test_kernel_failure_falls_back_and_matches_reference(
            self, devices8):
        """Injected fused-CE kernel-launch failure: the registry
        degrades to the scan impl with the run alive, and the loss
        trajectory MATCHES the reference impl's exactly (the fallback
        IS the numerics specification)."""
        import dataclasses

        cfg = dataclasses.replace(CFG, fused_ce=True, fused_ce_chunk=8,
                                  fused_ce_impl="interpret")
        ref_cfg = dataclasses.replace(CFG, fused_ce=True, fused_ce_chunk=8,
                                      fused_ce_impl="off")
        tok, tgt = _data()

        def run(config, chaos_plan=None):
            get_registry().reset()
            opt = FusedAdam(lr=1e-2)
            params = init_params(config, jax.random.PRNGKey(0))
            state = opt.init(params)
            guard = StepGuard()
            gstate = guard.init()
            chaos = ChaosMonkey(chaos_plan or ChaosPlan.make())
            with chaos.active():
                step = make_train_step(config, opt, _mesh(devices8),
                                       step_guard=guard, chaos=chaos)
                losses = []
                for _ in range(3):
                    params, state, gstate, loss = step(params, state,
                                                       gstate, tok, tgt)
                    losses.append(float(loss))
            return params, losses

        # huge budget: every call fails until the registry trips
        plan = ChaosPlan.make(kernel_failures={"fused_ce": 10 ** 6})
        surv_params, surv_losses = run(cfg, plan)
        assert get_registry().tripped("fused_ce")
        assert all(np.isfinite(surv_losses))

        ref_params, ref_losses = run(ref_cfg)
        np.testing.assert_allclose(surv_losses, ref_losses, rtol=1e-6)
        for a, b in zip(jax.tree.leaves(surv_params),
                        jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    def test_preemption_resume_bitwise_identical(self, devices8, tmp_path):
        """Injected preemption at step 2: the loop saves, drains the
        async queue, and exits; a fresh 'process' discovers the
        checkpoint via latest_checkpoint and resumes at the same step
        with bitwise-identical params, guard, and scaler state."""
        scaler = DynamicLossScaler(init_scale=2.0 ** 8)
        guard = StepGuard()
        chaos = ChaosMonkey(ChaosPlan.make(preempt_at_step=2))
        opt = FusedAdam(lr=1e-2)
        params = init_params(CFG, jax.random.PRNGKey(0))
        state = opt.init(params)
        sstate, gstate = scaler.init(), guard.init()
        step = make_train_step(CFG, opt, _mesh(devices8),
                               loss_scaler=scaler, step_guard=guard)
        tok, tgt = _data()
        pre = PreemptionHandler()  # no install: chaos delivers it

        stopped_at = None
        with AsyncCheckpointer() as ck:
            for i in range(5):
                params, state, sstate, gstate, _ = step(
                    params, state, sstate, gstate, tok, tgt)
                chaos.maybe_preempt(i, pre)
                if pre.preempted:
                    ck.save(tmp_path / f"step_{i + 1:08d}.ckpt", {
                        "params": params, "state": state,
                        "scaler": scaler.state_dict(sstate),
                        "guard": guard.state_dict(gstate),
                        "step": np.int64(i + 1),
                    })
                    pre.drain(ck)
                    stopped_at = i + 1
                    break
        assert stopped_at == 3  # preempt delivered AFTER loop step 2

        # ---- fresh process: discover, validate, resume
        path = latest_checkpoint(tmp_path)
        assert path.endswith("step_00000003.ckpt")
        ck2 = load_checkpoint(path)
        assert int(ck2["step"]) == stopped_at
        assert _leaves_equal(ck2["params"], params)   # bitwise
        assert _leaves_equal(ck2["state"], state)
        r_sstate = scaler.load_state_dict(ck2["scaler"])
        r_gstate = guard.load_state_dict(ck2["guard"])
        assert float(r_sstate.loss_scale) == float(sstate.loss_scale)
        assert guard.state_dict(r_gstate) == guard.state_dict(gstate)

        # the resumed step must run and train
        r_params = jax.tree.map(jnp.asarray, ck2["params"])
        r_state = jax.tree.map(jnp.asarray, ck2["state"])
        r_params, r_state, r_sstate, r_gstate, loss = step(
            r_params, r_state, r_sstate, r_gstate, tok, tgt)
        assert np.isfinite(float(loss))
        assert not _leaves_equal(r_params, ck2["params"])

    def test_full_survival_story(self, devices8, tmp_path):
        """The acceptance scenario in one loop: a NaN step (skipped,
        scaler backs off), a kernel-launch failure (falls back, loss
        matches the reference trajectory), and a preemption (resumes
        from the flushed checkpoint at the same step, params bitwise
        identical)."""
        import dataclasses

        cfg = dataclasses.replace(CFG, fused_ce=True, fused_ce_chunk=8,
                                  fused_ce_impl="interpret")
        ref_cfg = dataclasses.replace(cfg, fused_ce_impl="off")
        tok, tgt = _data()
        plan = ChaosPlan.make(nan_grad_steps=[1],
                              kernel_failures={"fused_ce": 10 ** 6},
                              preempt_at_step=3)

        def loop(config, chaos_plan, ckpt_dir=None, steps=5):
            get_registry().reset()
            scaler = DynamicLossScaler(init_scale=2.0 ** 8, hysteresis=1)
            guard = StepGuard(max_consecutive_bad=3)
            chaos = ChaosMonkey(chaos_plan)
            opt = FusedAdam(lr=1e-2)
            params = init_params(config, jax.random.PRNGKey(0))
            state = opt.init(params)
            sstate, gstate = scaler.init(), guard.init()
            pre = PreemptionHandler()
            losses = []
            with chaos.active():
                step = make_train_step(config, opt, _mesh(devices8),
                                       loss_scaler=scaler,
                                       step_guard=guard, chaos=chaos)
                with AsyncCheckpointer() as ck:
                    for i in range(steps):
                        params, state, sstate, gstate, loss = step(
                            params, state, sstate, gstate, tok, tgt)
                        losses.append(float(loss))
                        guard.check(gstate)
                        chaos.maybe_preempt(i, pre)
                        if ckpt_dir and pre.preempted:
                            ck.save(
                                ckpt_dir / f"step_{i + 1:08d}.ckpt",
                                {"params": params,
                                 "step": np.int64(i + 1)})
                            pre.drain(ck)
                            break
            return params, gstate, losses

        params, gstate, losses = loop(cfg, plan, ckpt_dir=tmp_path)
        # kernel failure absorbed
        assert get_registry().tripped("fused_ce")
        # NaN step absorbed and counted
        assert not np.isfinite(losses[1])
        assert int(gstate.total_skipped) == 1
        # preempted after loop step 3 (4 losses recorded), durable save
        assert len(losses) == 4
        ck = load_checkpoint(latest_checkpoint(tmp_path))
        assert int(ck["step"]) == 4
        assert _leaves_equal(ck["params"], params)  # bitwise at resume

        # the degraded run's trajectory == the reference impl's, fault
        # for fault (same chaos plan, no kernel failures needed: "off"
        # IS the fallback impl the degraded run used)
        ref_plan = ChaosPlan.make(nan_grad_steps=[1], preempt_at_step=3)
        _, _, ref_losses = loop(ref_cfg, ref_plan, ckpt_dir=None)
        np.testing.assert_allclose(losses[0:1] + losses[2:],
                                   ref_losses[0:1] + ref_losses[2:4],
                                   rtol=1e-6)
