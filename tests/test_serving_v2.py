"""Serving v2 tests: speculative decode, prefix sharing + COW, chunked
prefill, and SLO lanes.

The load-bearing contracts:

- **Speculative parity**: the spec engine's token streams are BITWISE
  the non-speculative engine's (greedy and sampled — every emission
  spends the same (slot, draw) seed), with accepted-tokens/step > 1 on
  repetitive text, and the verify step compiles once across draft
  hit/miss/occupancy mixes.
- **Prefix sharing accounting**: N sequences sharing a system prompt
  hold exactly ONE refcounted copy of its full pages (pool accounting
  pinned), COW on the first divergent write preserves per-sequence
  tokens bitwise vs unshared, and shared-prefix oversubscription
  admits strictly more concurrent sequences than worst-case
  reservation.
- **Chunked prefill**: prompts beyond the padded prefill limit admit
  as fixed-size chunks, produce the same greedy stream as a one-shot
  prefill engine, and interleave with resident decode streams.
- **Lanes**: best-effort residents are preempted through the
  evict→recycle path to admit the interactive head, survivors are
  uncorrupted, preempted generations complete via continuation, and
  the serve histograms split by lane.
- **Refcounted allocator**: property-band — random
  allocate/share/free sequences never leak, never double-free, and the
  garbage page's refcount never moves.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.analysis import lowered as lw
from apex_tpu.inference import (
    ContinuousBatchingScheduler, DecodeConfig, GARBAGE_PAGE, KVCacheConfig,
    NGramProposer, PageAllocator, PrefixCache, Request, accepted_tokens,
)
from apex_tpu.models.gpt import GPTConfig, gpt_forward, init_params
from apex_tpu.observability import MetricsScope
from apex_tpu.ops.decode_attention_pallas import (
    decode_attention_xla, paged_decode_attention_pallas,
)


def tiny_cfg(**kw):
    base = dict(
        vocab_size=61, hidden_size=32, num_layers=2,
        num_attention_heads=4, max_seq_len=128,
        position_embedding_type="rope", compute_dtype=jnp.float32,
        checkpoint_layers=False,
    )
    base.update(kw)
    return GPTConfig(**base)


def _sched(params, cfg, *, num_pages=40, page_size=4, pages_per_seq=16,
           max_batch=3, temperature=0.0, top_k=0, max_prompt=16, seed=0,
           **dk):
    dcfg = DecodeConfig(
        cache=KVCacheConfig(num_pages=num_pages, page_size=page_size,
                            pages_per_seq=pages_per_seq,
                            dtype=jnp.float32),
        max_batch=max_batch, max_prompt_len=max_prompt,
        temperature=temperature, top_k=top_k,
        attn_impl="xla", sample_impl="xla",
        sample_dot_dtype=jnp.float32, base_seed=seed, **dk)
    return ContinuousBatchingScheduler(params, cfg, dcfg)


def _repetitive_prompt(rng, vocab, period=4, length=14):
    pat = rng.randint(0, vocab, size=period).tolist()
    return (pat * (length // period + 1))[:length]


def _tokens_by_rid(completions):
    return {c.rid: tuple(c.tokens) for c in completions}


# ------------------------------------------------- verify-width attention
class TestVerifyWidthAttention:
    def _case(self, rng, B=2, W=3, H=4, KVH=2, D=16, num_pages=9, page=8,
              P=4):
        q = jnp.asarray(rng.randn(B * W, H, D), jnp.float32)
        kp = jnp.asarray(rng.randn(num_pages, page, KVH, D), jnp.float32)
        vp = jnp.asarray(rng.randn(num_pages, page, KVH, D), jnp.float32)
        pt = jnp.asarray(rng.randint(1, num_pages, size=(B, P)), jnp.int32)
        lengths = jnp.asarray(
            rng.randint(0, page * P, size=(B * W,)), jnp.int32)
        return q, kp, vp, pt, lengths

    def test_width_matches_repeated_tables(self):
        """The width layout is pure bookkeeping: scoring W consecutive
        rows against one shared table row must equal width=1 with the
        table explicitly repeated."""
        rng = np.random.RandomState(0)
        q, kp, vp, pt, lengths = self._case(rng)
        wide = decode_attention_xla(q, kp, vp, pt, lengths, width=3)
        flat = decode_attention_xla(q, kp, vp, jnp.repeat(pt, 3, axis=0),
                                    lengths, width=1)
        np.testing.assert_allclose(np.asarray(wide), np.asarray(flat),
                                   rtol=0, atol=1e-6)

    def test_kernel_width_matches_reference(self):
        rng = np.random.RandomState(1)
        q, kp, vp, pt, lengths = self._case(rng)
        ref = decode_attention_xla(q, kp, vp, pt, lengths, width=3)
        out = paged_decode_attention_pallas(q, kp, vp, pt, lengths,
                                            width=3, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=0, atol=1e-5)

    def test_width_shape_mismatch_refuses(self):
        rng = np.random.RandomState(2)
        q, kp, vp, pt, lengths = self._case(rng)
        with pytest.raises(ValueError, match="width"):
            decode_attention_xla(q, kp, vp, pt, lengths, width=2)


# ----------------------------------------------------------- speculation
class TestSpeculative:
    @pytest.fixture(scope="class")
    def model(self):
        cfg = tiny_cfg()
        return cfg, init_params(cfg, jax.random.PRNGKey(0))

    def _requests(self, rng, n, vocab, max_new=8):
        return [Request(i, _repetitive_prompt(rng, vocab), max_new)
                for i in range(n)]

    @pytest.mark.parametrize("pet,gqa", [
        ("rope", None), ("learned", None), ("rope", 2)])
    def test_greedy_spec_stream_bitwise_vs_plain(self, pet, gqa):
        """The acceptance pin, across the gpt config zoo: greedy
        speculative serving emits BITWISE the non-speculative engine's
        token streams, and beats one token/step on repetitive text."""
        cfg = tiny_cfg(position_embedding_type=pet, num_query_groups=gqa,
                       max_seq_len=64)
        params = init_params(cfg, jax.random.PRNGKey(1))
        rng = np.random.RandomState(3)
        reqs = self._requests(rng, 4, cfg.vocab_size)

        plain = _sched(params, cfg)
        for r in reqs:
            plain.submit(Request(r.rid, list(r.prompt), r.max_new_tokens))
        want = _tokens_by_rid(plain.run_until_drained())

        spec = _sched(params, cfg, draft_len=3)
        for r in reqs:
            spec.submit(Request(r.rid, list(r.prompt), r.max_new_tokens))
        got = _tokens_by_rid(spec.run_until_drained())
        assert got == want, (
            "speculative greedy streams diverged from the plain engine")
        rate = spec.stats["spec_emitted"] / max(spec.stats["spec_steps"], 1)
        assert rate > 1.0, (
            f"accepted-tokens/step {rate:.2f} <= 1 on repetitive text — "
            f"drafts never land")
        assert spec.stats["decode_steps"] < plain.stats["decode_steps"], (
            "speculation saved no decode steps")

    def test_sampled_spec_stream_bitwise_vs_plain(self, model):
        """Temperature sampling too: each emission spends the same
        (slot, draw) seed the plain engine would, so even the SAMPLED
        stream is reproduced exactly.  (Requests <= max_batch: with a
        queue, speculation finishes residents at different STEPS, so a
        queued request can land in a different slot — a different seed
        lineage.  Greedy parity, which ignores seeds, holds regardless
        — the zoo test above queues 4 into 3 slots.)"""
        cfg, params = model
        rng = np.random.RandomState(4)
        reqs = self._requests(rng, 3, cfg.vocab_size)

        def run(draft):
            s = _sched(params, cfg, temperature=0.8, top_k=7, seed=5,
                       draft_len=draft)
            for r in reqs:
                s.submit(Request(r.rid, list(r.prompt), r.max_new_tokens))
            return _tokens_by_rid(s.run_until_drained())

        assert run(0) == run(4)

    def test_eos_respected_mid_acceptance(self, model):
        """An accepted burst that crosses eos truncates exactly where
        the plain engine stops."""
        cfg, params = model
        rng = np.random.RandomState(5)
        prompt = _repetitive_prompt(rng, cfg.vocab_size)
        plain = _sched(params, cfg)
        plain.submit(Request(0, list(prompt), 10))
        toks = plain.run_until_drained()[0].tokens
        eos = toks[len(toks) // 2]
        cut = toks.index(eos) + 1

        spec = _sched(params, cfg, draft_len=3)
        spec.submit(Request(0, list(prompt), 10, eos_id=eos))
        assert spec.run_until_drained()[0].tokens == toks[:cut]

    def test_verify_step_compiles_once_across_mixes(self, model):
        """assert_no_recompile on the verify step across occupancy x
        draft-hit/miss mixes (repetitive AND incompressible prompts,
        admissions and evictions in flight)."""
        cfg, params = model
        sched = _sched(params, cfg, draft_len=3)
        rng = np.random.RandomState(6)
        for i in range(5):
            prompt = (_repetitive_prompt(rng, cfg.vocab_size) if i % 2
                      else rng.randint(0, 61, size=7).tolist())
            sched.submit(Request(i, prompt, int(rng.randint(2, 9))))
        sched.run_until_drained()
        assert sched.stats["spec_steps"] > 0
        lw.assert_no_recompile(sched._verify, label="verify_step")

    def test_ngram_proposer_prompt_lookup(self):
        p = NGramProposer(draft_len=3, ngram_max=2, ngram_min=1)
        p.extend([5, 1, 2, 3, 9, 1, 2])
        # trailing bigram (1, 2) last occurred at positions 1..2 —
        # the continuation there is [3, 9, 1]
        assert p.propose() == [3, 9, 1]
        q = NGramProposer(draft_len=2)
        q.extend([1, 2, 3, 4])
        assert q.propose() == []  # nothing repeats

    def test_accepted_tokens_rule(self):
        # drafts all hit -> every emission consumed (incl. the bonus)
        assert accepted_tokens([7, 4, 5], [4, 5, 6]) == [4, 5, 6]
        # first draft misses -> only the standard-path token
        assert accepted_tokens([7, 9, 5], [4, 5, 6]) == [4]
        # partial
        assert accepted_tokens([7, 4, 9], [4, 5, 6]) == [4, 5]


# --------------------------------------------------- refcounted allocator
class TestRefcountAllocator:
    def test_share_and_deferred_recycle(self):
        a = PageAllocator(num_pages=6)
        pages = a.allocate(2)
        a.share(pages)  # second reference
        a.free(pages)   # drops to 1 — still live
        assert a.free_pages == 3 and a.refcount(pages[0]) == 1
        a.free(pages)   # last reference — recycles
        assert a.free_pages == 5 and a.refcount(pages[0]) == 0

    def test_share_guards(self):
        a = PageAllocator(num_pages=4)
        with pytest.raises(ValueError, match="never shared"):
            a.share([GARBAGE_PAGE])
        with pytest.raises(ValueError, match="free page"):
            a.share([2])  # never allocated

    def test_property_random_ops_never_leak_or_double_free(self):
        """The satellite band: random allocate/share/free sequences
        against a model of the refcounts — the pool never leaks, a
        stale free always raises, the garbage page never moves."""
        rng = np.random.RandomState(7)
        N = 17
        a = PageAllocator(num_pages=N)
        model = {}  # page -> refcount
        for _ in range(600):
            op = rng.randint(3)
            if op == 0:
                n = int(rng.randint(1, 4))
                got = a.allocate(n)
                if n > N - 1 - len(model):
                    assert got is None, "allocated past the pool"
                else:
                    assert got is not None and len(got) == n
                if got is not None:
                    for p in got:
                        assert p != GARBAGE_PAGE and p not in model
                        model[p] = 1
            elif op == 1 and model:
                p = int(rng.choice(sorted(model)))
                a.share([p])
                model[p] += 1
            elif op == 2 and model:
                p = int(rng.choice(sorted(model)))
                a.free([p])
                model[p] -= 1
                if model[p] == 0:
                    del model[p]
            # invariants, every step
            assert a.refcount(GARBAGE_PAGE) == 0
            assert a.free_pages == N - 1 - len(model), "page leak"
            for p, r in model.items():
                assert a.refcount(p) == r
        dead = [p for p in range(1, N) if p not in model]
        if dead:
            with pytest.raises(ValueError, match="double free"):
                a.free([dead[0]])
        for p, r in list(model.items()):
            a.free([p] * r)
        assert a.free_pages == N - 1, "pages leaked at drain"

    def test_release_skips_resident_held_chains(self):
        """Pressure relief must count pages actually RECYCLED, not
        trie refs dropped: a chain whose every page is still
        resident-held frees nothing — wiping it would only destroy the
        sharing while the admission stays blocked (release returns 0
        and the scheduler escalates to preemption instead)."""
        alloc = PageAllocator(num_pages=8)
        cache = PrefixCache(alloc, page_size=4)
        pages = alloc.allocate(2)
        prompt = list(range(8))
        cache.register(prompt, pages)  # trie ref on top: refcounts 2
        assert cache.release(10) == 0, "resident-held chain was wiped"
        assert cache.indexed_pages == 2
        assert cache.match(prompt).num_full == 2, (
            "sharing destroyed by a release that freed nothing")
        assert alloc.free_pages == 5
        alloc.free(pages)  # the resident evicts — trie is last holder
        assert cache.release(10) == 2  # now the drop actually recycles
        assert alloc.free_pages == 7
        assert cache.match(prompt).num_full == 0


# -------------------------------------------------------- prefix sharing
class TestPrefixSharing:
    @pytest.fixture(scope="class")
    def model(self):
        cfg = tiny_cfg()
        return cfg, init_params(cfg, jax.random.PRNGKey(0))

    def test_full_pages_deduped_pool_accounting_pinned(self, model):
        """N resident sequences sharing a system prompt hold exactly
        ONE refcounted copy of its full pages."""
        cfg, params = model
        rng = np.random.RandomState(8)
        sysp = rng.randint(0, 61, size=8).tolist()  # exactly 2 full pages
        n, max_new = 3, 12  # long enough that all 3 stay resident
        sched = _sched(params, cfg, prefix_sharing=True, max_batch=n)
        for i in range(n):
            sched.submit(Request(i, sysp + [i], max_new))
        sched.step()  # one admission sweep
        assert sched.num_active == n
        per_seq = 6   # ceil((9 + 12) / 4)
        expect_live = per_seq + (n - 1) * (per_seq - 2)
        assert sched.allocator.live_pages == expect_live, (
            "pool accounting: shared full pages were not deduped")
        assert sched.stats["shared_full_pages"] == 2 * (n - 1)
        shared = [int(p) for p in sched._page_tables[0, :2]]
        for i in range(1, n):
            assert [int(p) for p in sched._page_tables[i, :2]] == shared
        # n sequences + the trie each hold a reference
        assert all(sched.allocator.refcount(p) == n + 1 for p in shared)
        sched.run_until_drained()
        sched.prefix.release(10 ** 6)
        assert sched.allocator.free_pages == 39, "pages leaked"

    def test_cow_preserves_tokens_bitwise_vs_unshared(self, model):
        """Owner evicts -> tail page enters the trie; a same-prompt
        matcher shares it and COWs on its first divergent write — its
        stream must equal the unshared engine's bitwise."""
        cfg, params = model
        rng = np.random.RandomState(9)
        prompt = rng.randint(0, 61, size=10).tolist()  # 2 pages + tail
        sched = _sched(params, cfg, prefix_sharing=True)
        sched.submit(Request(0, list(prompt), 5))
        sched.run_until_drained()
        sched.submit(Request(1, list(prompt), 5))
        got = sched.run_until_drained()[-1]
        assert sched.stats["shared_tail_pages"] == 1
        assert sched.stats["cow_copies"] == 1

        plain = _sched(params, cfg)
        plain.submit(Request(1, list(prompt), 5))
        want = plain.run_until_drained()[0]
        assert got.tokens == want.tokens, (
            "COW changed the shared-tail sequence's stream")

    def test_oversubscription_admits_strictly_more(self, model):
        """The capacity win: a pool that fits ONE worst-case sequence
        unshared fits TWO with a shared prefix."""
        cfg, params = model
        rng = np.random.RandomState(10)
        sysp = rng.randint(0, 61, size=8).tolist()  # 2 full pages
        kw = dict(num_pages=6, page_size=4, pages_per_seq=3, max_batch=2)

        def max_resident(sharing):
            sched = _sched(params, cfg, prefix_sharing=sharing, **kw)
            for i in range(3):
                sched.submit(Request(i, sysp + [i], 3))  # 3 pages each
            peak = 0
            for _ in range(200):
                if sched.idle():
                    break
                sched.step()
                peak = max(peak, sched.num_active)
            assert sched.idle() and len(sched.completed) == 3
            return peak

        assert max_resident(False) == 1
        assert max_resident(True) == 2, (
            "shared prefixes must admit strictly more than worst-case "
            "reservation")

    def test_trie_release_under_pressure_keeps_serving(self, model):
        """A full trie must not wedge admission: the allocator runs
        dry, LRU chains release, the queue drains."""
        cfg, params = model
        rng = np.random.RandomState(11)
        sched = _sched(params, cfg, prefix_sharing=True, num_pages=8,
                       page_size=4, pages_per_seq=4, max_batch=1)
        for i in range(4):  # distinct prompts: the trie only grows
            sched.submit(Request(i, rng.randint(0, 61, size=8).tolist(), 4))
        done = sched.run_until_drained()
        assert len(done) == 4
        assert sched.prefix.stats["released_pages"] > 0, (
            "pool pressure never released trie chains — the test is "
            "not exercising the release path")

    def test_random_share_trace_never_leaks(self, model):
        """End-to-end chaos band: random prompts (some shared), random
        budgets, interleaved submits/drains — afterwards every page is
        accounted for and the garbage page never moved."""
        cfg, params = model
        rng = np.random.RandomState(12)
        sched = _sched(params, cfg, prefix_sharing=True, draft_len=2,
                       num_pages=24, pages_per_seq=10, max_batch=2)
        sysp = rng.randint(0, 61, size=9).tolist()
        rid = 0
        for _ in range(6):
            for _ in range(int(rng.randint(1, 4))):
                if rng.rand() < 0.6:
                    prompt = sysp + rng.randint(
                        0, 61, size=rng.randint(1, 4)).tolist()
                else:
                    prompt = rng.randint(
                        0, 61, size=rng.randint(2, 10)).tolist()
                sched.submit(Request(rid, prompt,
                                     int(rng.randint(1, 6))))
                rid += 1
            sched.run_until_drained()
            assert sched.allocator.refcount(GARBAGE_PAGE) == 0
        assert (sched.allocator.free_pages
                + sched.prefix.indexed_pages) == 23, "pages leaked"
        sched.prefix.release(10 ** 6)
        assert sched.allocator.free_pages == 23


# -------------------------------------------------------- chunked prefill
class TestChunkedPrefill:
    @pytest.fixture(scope="class")
    def model(self):
        cfg = tiny_cfg()
        return cfg, init_params(cfg, jax.random.PRNGKey(0))

    def test_long_prompt_beyond_padded_limit_matches_oneshot(self, model):
        """A prompt LONGER than max_prompt_len admits via chunks and
        reproduces the one-shot-prefill engine's greedy stream."""
        cfg, params = model
        rng = np.random.RandomState(13)
        prompt = rng.randint(0, 61, size=23).tolist()
        chunked = _sched(params, cfg, prefill_chunk=4, max_prompt=8)
        chunked.submit(Request(0, list(prompt), 6))
        got = chunked.run_until_drained()[0]
        assert chunked.stats["chunk_steps"] == 6  # ceil(23 / 4)

        oneshot = _sched(params, cfg, max_prompt=32)
        oneshot.submit(Request(0, list(prompt), 6))
        want = oneshot.run_until_drained()[0]
        assert got.tokens == want.tokens

        with pytest.raises(ValueError, match="max_prompt_len"):
            oneshot.submit(Request(1, rng.randint(0, 61, size=40).tolist(),
                                   2))

    def test_chunks_interleave_with_decode(self, model):
        """Resident streams keep emitting WHILE a long prompt
        chunk-prefills — the TTFT-spike fix."""
        cfg, params = model
        rng = np.random.RandomState(14)
        sched = _sched(params, cfg, prefill_chunk=4, max_prompt=8,
                       max_batch=2)
        sched.submit(Request(0, rng.randint(0, 61, size=5).tolist(), 30))
        sched.step()  # rid 0 resident and decoding
        sched.submit(Request(1, rng.randint(0, 61, size=20).tolist(), 3))
        sched.step()  # rid 1 admitted; its first chunk lands
        resident = sched._slots[0]
        emitted_during_chunking = []
        while any(s is not None and s.chunk_next is not None
                  for s in sched._slots):
            sched.step()
            emitted_during_chunking.append(len(resident.generated))
        assert len(emitted_during_chunking) >= 2
        assert emitted_during_chunking[-1] > emitted_during_chunking[0], (
            "the resident stream stalled while the long prompt "
            "chunk-prefilled")
        assert len(sched.run_until_drained()) == 2

    def test_shared_prefix_skips_chunk_compute(self, model):
        """Chunked prefill over a fully-cached prompt collapses to ONE
        recompute chunk (the last position), and the stream matches."""
        cfg, params = model
        rng = np.random.RandomState(15)
        prompt = rng.randint(0, 61, size=12).tolist()  # 3 full pages
        sched = _sched(params, cfg, prefill_chunk=4, max_prompt=8,
                       prefix_sharing=True)
        sched.submit(Request(0, list(prompt), 4))
        sched.run_until_drained()
        chunks_before = sched.stats["chunk_steps"]
        sched.submit(Request(1, list(prompt), 4))
        done = sched.run_until_drained()
        assert sched.stats["chunk_steps"] == chunks_before + 1, (
            "a fully-shared prompt must cost one recompute chunk, not "
            "a full prefill")
        assert done[0].tokens == done[1].tokens  # greedy, same prompt

    def test_chunk_step_compiles_once(self, model):
        cfg, params = model
        sched = _sched(params, cfg, prefill_chunk=4, max_prompt=8)
        rng = np.random.RandomState(16)
        for i, plen in enumerate((3, 9, 23, 17)):
            sched.submit(Request(i, rng.randint(0, 61, size=plen).tolist(),
                                 3))
        sched.run_until_drained()
        lw.assert_no_recompile(sched._chunk, label="prefill_chunk")
        lw.assert_no_recompile(sched._sample_head, label="sample_head")


# ---------------------------------------------------------------- lanes
class TestLanes:
    @pytest.fixture(scope="class")
    def model(self):
        cfg = tiny_cfg()
        return cfg, init_params(cfg, jax.random.PRNGKey(0))

    def test_preemption_frees_pages_for_interactive(self, model):
        """The SLO contract: a full pool of best-effort work yields to
        the interactive head via the evict→recycle path; survivors'
        streams stay bitwise correct; preempted work completes via
        continuation."""
        cfg, params = model
        rng = np.random.RandomState(17)
        kw = dict(num_pages=9, page_size=4, pages_per_seq=8, max_batch=2)
        prompts = [rng.randint(0, 61, size=6).tolist() for _ in range(3)]

        sched = _sched(params, cfg, **kw)
        sched.submit(Request(0, list(prompts[0]), 8, lane="best_effort"))
        sched.submit(Request(1, list(prompts[1]), 8, lane="best_effort"))
        sched.step()
        assert sched.num_active == 2 and sched.allocator.free_pages == 0
        sched.submit(Request(2, list(prompts[2]), 8, lane="interactive"))
        done = {c.rid: c for c in sched.run_until_drained()}
        assert sched.stats["preemptions"] >= 1
        assert set(done) == {0, 1, 2}
        assert all(len(c.tokens) == 8 for c in done.values()), (
            "a preempted generation lost tokens — continuation broke")
        preempted = [c for c in done.values() if c.preemptions]
        assert preempted and all(c.lane == "best_effort"
                                 for c in preempted)

        # bitwise correctness for every stream, preempted included:
        # greedy serving must equal the training forward's argmax walk
        for c in done.values():
            seq = list(c.prompt)
            for tok in c.tokens:
                logits = gpt_forward(params, jnp.asarray([seq]), cfg)
                assert int(jnp.argmax(logits[len(seq) - 1, 0])) == tok, (
                    f"rid={c.rid}: corrupted after preemption chaos")
                seq.append(tok)

    def test_best_effort_waits_for_interactive_queue(self, model):
        """Lane priority: while an interactive request waits, no
        best-effort request is admitted."""
        cfg, params = model
        rng = np.random.RandomState(18)
        sched = _sched(params, cfg, max_batch=1)
        sched.submit(Request(0, rng.randint(0, 61, size=4).tolist(), 3))
        sched.step()  # rid 0 occupies the only slot
        sched.submit(Request(1, rng.randint(0, 61, size=4).tolist(), 2,
                             lane="best_effort"))
        sched.submit(Request(2, rng.randint(0, 61, size=4).tolist(), 2,
                             lane="interactive"))
        order = []
        orig = sched._admit_into

        def record(slot, req, *plan):
            order.append(req.rid)
            return orig(slot, req, *plan)

        sched._admit_into = record
        sched.run_until_drained()
        assert order == [2, 1], (
            f"admission order {order}: best-effort overtook a waiting "
            f"interactive request")

    def test_histograms_split_by_lane(self, model):
        cfg, params = model
        rng = np.random.RandomState(19)
        with MetricsScope() as reg:
            sched = _sched(params, cfg)
            sched.submit(Request(0, rng.randint(0, 61, size=4).tolist(),
                                 3))
            sched.submit(Request(1, rng.randint(0, 61, size=4).tolist(),
                                 3, lane="best_effort"))
            sched.run_until_drained()
            lanes = {l.get("lane") for m in reg.metrics()
                     if m.name == "apex_serve_ttft_seconds"
                     for _, l, _ in m.samples()}
            assert {"interactive", "best_effort"} <= lanes, (
                f"TTFT histogram lanes {lanes}: the per-lane SLO "
                f"evidence is missing")

    def test_unknown_lane_refused(self, model):
        cfg, params = model
        sched = _sched(params, cfg)
        with pytest.raises(ValueError, match="lane"):
            sched.submit(Request(0, [1, 2], 2, lane="bulk"))


# ------------------------------------------------- seeds & recompile pins
class TestSeedDeterminism:
    @pytest.fixture(scope="class")
    def model(self):
        cfg = tiny_cfg()
        return cfg, init_params(cfg, jax.random.PRNGKey(0))

    def test_two_generations_one_slot_never_replay_a_seed(self, model):
        """The satellite regression: submit -> drain -> submit again
        lands in the SAME slot; its draw counter must advance
        monotonically across generations — a reset would replay
        generation 1's seeds (and, under temperature, its tokens)."""
        cfg, params = model
        sched = _sched(params, cfg, max_batch=1, temperature=0.9, top_k=5,
                       seed=11)
        used = []
        orig = sched._seed_at

        def spy(slot, draw):
            used.append((slot, draw))
            return orig(slot, draw)

        sched._seed_at = spy
        sched.submit(Request(0, [3, 4, 5], 4))
        g1 = sched.run_until_drained()[-1].tokens
        draws_after_g1 = int(sched._draws[0])
        sched.submit(Request(1, [3, 4, 5], 4))
        g2 = sched.run_until_drained()[-1].tokens
        assert len(g1) == len(g2) == 4
        assert int(sched._draws[0]) == draws_after_g1 + 4, (
            "slot draw counter reset between generations")
        assert len(used) == len(set(used)), (
            f"(slot, draw) seed replayed across generations: {used}")

    def test_preemption_readmission_stays_deterministic(self, model):
        """Same seeded trace with preemption in it, twice — bitwise the
        same served tokens (draw counters never reset on the preempt →
        re-admit path either)."""
        cfg, params = model

        def run():
            sched = _sched(params, cfg, num_pages=9, page_size=4,
                           pages_per_seq=8, max_batch=2, temperature=0.9,
                           top_k=6, seed=13)
            rng = np.random.RandomState(20)
            sched.submit(Request(0, rng.randint(0, 61, size=6).tolist(),
                                 8, lane="best_effort"))
            sched.submit(Request(1, rng.randint(0, 61, size=6).tolist(),
                                 8, lane="best_effort"))
            sched.step()
            sched.submit(Request(2, rng.randint(0, 61, size=6).tolist(),
                                 8))
            done = sched.run_until_drained()
            assert sched.stats["preemptions"] >= 1
            return _tokens_by_rid(done)

        assert run() == run()


class TestAssertNoRecompile:
    def test_passes_on_stable_shapes_and_reports_results(self):
        f = jax.jit(lambda x: x * 2)
        out = lw.assert_no_recompile(
            f, [(jnp.ones((3,)),), (jnp.zeros((3,)),)])
        assert len(out) == 2 and float(out[0][0]) == 2.0

    def test_fails_naming_the_offending_call(self):
        f = jax.jit(lambda x: x + 1)
        with pytest.raises(AssertionError, match="call 1"):
            lw.assert_no_recompile(
                f, [(jnp.ones((3,)),), (jnp.ones((4,)),)])

    def test_rejects_unjitted_and_uncalled(self):
        with pytest.raises(TypeError, match="_cache_size"):
            lw.assert_no_recompile(lambda x: x)
        with pytest.raises(AssertionError, match="never called"):
            lw.assert_no_recompile(jax.jit(lambda x: x))
