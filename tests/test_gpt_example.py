"""GPT pretraining example: the canonical-trainer role of the
reference's ``examples/imagenet/main_amp.py``, exercised as a CLI —
including the memmapped-token data path through the native
``gather_rows`` batch assembly + prefetch."""

import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[1]


def _run(args, extra_env=None):
    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": str(REPO),
        **(extra_env or {}),
    }
    r = subprocess.run(
        [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"), *args],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert r.returncode == 0, f"stderr:\n{r.stderr[-2000:]}"
    return r.stdout


def test_memmap_data_path(tmp_path):
    """--data: a uint16 token bin drives training through the native
    gather_rows assembly; losses print and are finite."""
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 512, size=40 * 65, dtype=np.uint16)
    data = tmp_path / "tokens.bin"
    tokens.tofile(data)
    out = _run(["--tp", "2", "--steps", "3", "--data", str(data),
                "--seq", "64", "--global-batch", "8"])
    losses = [float(l.split("loss=")[1].split()[0])
              for l in out.splitlines() if l.startswith("step ")]
    assert len(losses) == 3
    assert all(np.isfinite(losses))


def test_data_validation(tmp_path):
    """Token ids beyond --vocab and too-small files fail loudly."""
    bad = tmp_path / "bad.bin"
    np.full(20 * 65, 60000, dtype=np.uint16).tofile(bad)
    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": str(REPO),
    }
    r = subprocess.run(
        [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"),
         "--steps", "1", "--data", str(bad), "--seq", "64"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode != 0
    assert "vocab" in r.stderr


def test_synthetic_resume_round_trip(tmp_path):
    """No --data: synthetic corpus rides the same gather_rows+prefetch
    pipeline; checkpoint then resume continues at the right step."""
    ck = tmp_path / "ck"
    _run(["--tp", "2", "--steps", "4", "--checkpoint", str(ck)])
    out = _run(["--tp", "2", "--steps", "2", "--resume", str(ck)])
    assert "resumed at step 4" in out
    assert "step 5:" in out
