"""GPT pretraining example: the canonical-trainer role of the
reference's ``examples/imagenet/main_amp.py``, exercised as a CLI —
including the memmapped-token data path through the native
``gather_rows`` batch assembly + prefetch."""

import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[1]


def _env(extra=None):
    return {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": str(REPO),
        **(extra or {}),
    }


def _run(args, extra_env=None, expect_fail=False):
    """Run the trainer CLI; returns stdout on success.  With
    ``expect_fail`` asserts a nonzero exit and returns stderr."""
    r = subprocess.run(
        [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"), *args],
        capture_output=True, text=True, timeout=600, env=_env(extra_env),
    )
    if expect_fail:
        assert r.returncode != 0, f"expected failure; stdout:\n{r.stdout[-2000:]}"
        return r.stderr
    assert r.returncode == 0, f"stderr:\n{r.stderr[-2000:]}"
    return r.stdout


def test_memmap_data_path(tmp_path):
    """--data: a uint16 token bin drives training through the native
    gather_rows assembly; losses print and are finite."""
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 512, size=40 * 65, dtype=np.uint16)
    data = tmp_path / "tokens.bin"
    tokens.tofile(data)
    out = _run(["--tp", "2", "--steps", "3", "--data", str(data),
                "--seq", "64", "--global-batch", "8"])
    losses = [float(l.split("loss=")[1].split()[0])
              for l in out.splitlines() if l.startswith("step ")]
    assert len(losses) == 3
    assert all(np.isfinite(losses))


def test_data_validation(tmp_path):
    """Token ids beyond --vocab and too-small files fail loudly."""
    bad = tmp_path / "bad.bin"
    np.full(20 * 65, 60000, dtype=np.uint16).tofile(bad)
    err = _run(["--steps", "1", "--data", str(bad), "--seq", "64"],
               expect_fail=True)
    assert "vocab" in err


def test_synthetic_resume_round_trip(tmp_path):
    """No --data: synthetic corpus rides the same gather_rows+prefetch
    pipeline; checkpoint then resume continues at the right step."""
    ck = tmp_path / "ck"
    _run(["--tp", "2", "--steps", "4", "--checkpoint", str(ck)])
    out = _run(["--tp", "2", "--steps", "2", "--resume", str(ck)])
    assert "resumed at step 4" in out
    assert "step 5:" in out


def test_auto_resume_skips_torn_newest(tmp_path):
    """--auto-resume (apex_tpu.resilience): the same command line does
    first launch and restart, and a torn newest checkpoint — the
    leftovers of a writer killed mid-save — costs one save interval,
    not the run."""
    ck = tmp_path / "ck"
    args = ["--tp", "2", "--steps", "4", "--checkpoint", str(ck),
            "--auto-resume"]
    out = _run(args)          # first launch: no checkpoint, fresh start
    assert "resumed" not in out
    # a torn write from a killed process: valid prefix, truncated blob
    good = ck / "step_00000004.ckpt"
    (ck / "step_00000099.ckpt").write_bytes(good.read_bytes()[:-16])
    out = _run(args)          # identical command line: resumes
    assert "resumed at step 4" in out
    assert "step 5:" in out


def test_auto_resume_all_torn_fails_loudly(tmp_path):
    """--auto-resume starts fresh on an EMPTY dir, but when checkpoints
    existed and every one is torn, silently restarting from step 0
    would discard the run's progress: fail loudly instead."""
    ck = tmp_path / "ck"
    args = ["--tp", "2", "--steps", "4", "--checkpoint", str(ck),
            "--auto-resume"]
    _run(args)
    saved = list(ck.glob("step_*.ckpt"))
    assert saved
    for f in saved:
        f.write_bytes(f.read_bytes()[:-16])
    assert "torn/corrupt" in _run(args, expect_fail=True)


def test_zero_quantized_auto_resume(tmp_path):
    """--zero --grad-sync-dtype int8: the compressed wire trains end to
    end, the error-feedback residuals checkpoint with the sharded state
    (format v3), the same command line resumes — and resuming WITHOUT
    the flag fails loudly at the residual field instead of silently
    dropping the carried error."""
    ck = tmp_path / "ck"
    args = ["--tp", "2", "--zero", "--grad-sync-dtype", "int8",
            "--steps", "4", "--save-every", "2",
            "--checkpoint", str(ck), "--auto-resume"]
    out = _run(args)
    assert "resumed" not in out
    losses = [float(l.split("loss=")[1].split()[0])
              for l in out.splitlines() if l.startswith("step ")]
    assert len(losses) == 4 and all(np.isfinite(losses))
    out2 = _run(["--tp", "2", "--zero", "--grad-sync-dtype", "int8",
                 "--steps", "2", "--checkpoint", str(ck), "--auto-resume"])
    assert "resumed at step 4" in out2
    err = _run(["--tp", "2", "--zero", "--steps", "1",
                "--checkpoint", str(ck), "--auto-resume"], expect_fail=True)
    assert "residual" in err
    # and without --zero the flag itself is refused with the reason
    err2 = _run(["--tp", "2", "--grad-sync-dtype", "int8", "--steps", "1"],
                expect_fail=True)
    assert "--zero" in err2


def _devs(n):
    return {"XLA_FLAGS": f"--xla_force_host_platform_device_count={n}"}


def test_elastic_zero_resume_across_world_sizes(tmp_path):
    """The one-command elastic contract: `--zero --auto-resume` saved at
    dp=4 resumes at dp=2 (shrink) and then back at dp=4 (grow), the
    full sharded state resharding through the bucket plan's pad
    formula; losses stay finite and the step counter continues."""
    ck = tmp_path / "ck"
    base = ["--tp", "2", "--zero", "--save-every", "2",
            "--checkpoint", str(ck), "--auto-resume"]
    out = _run([*base, "--steps", "4"], extra_env=_devs(8))   # dp=4
    assert "resumed" not in out
    assert (ck / "step_00000004" / "index.json").exists()
    out2 = _run([*base, "--steps", "2"], extra_env=_devs(4))  # dp=2
    assert "resumed at step 4 (elastic reshard: dp=4 -> dp=2)" in out2
    assert "step 5:" in out2
    out3 = _run([*base, "--steps", "2"], extra_env=_devs(8))  # dp=4 again
    assert "resumed at step 6 (elastic reshard: dp=2 -> dp=4)" in out3
    losses = [float(l.split("loss=")[1].split()[0])
              for l in out3.splitlines() if l.startswith("step ")]
    assert len(losses) == 2 and all(np.isfinite(losses))
    # and a zero checkpoint refuses to silently restart when --zero is
    # dropped from the resume command
    err = _run(["--tp", "2", "--steps", "1", "--checkpoint", str(ck),
                "--auto-resume"], extra_env=_devs(8), expect_fail=True)
    assert "--zero" in err


def test_chaos_kill_one_host_then_elastic_resume(tmp_path):
    """Pod chaos at process level: the run dies HARD at step 3 (exit
    137 — no save, no drain), then the same command at a smaller world
    resumes elastically from the last COMPLETE step dir."""
    import subprocess as sp

    ck = tmp_path / "ck"
    r = sp.run(
        [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"),
         "--tp", "2", "--zero", "--steps", "6", "--save-every", "2",
         "--checkpoint", str(ck), "--auto-resume",
         "--chaos-kill-at-step", "3"],
        capture_output=True, text=True, timeout=600, env=_env(_devs(8)),
    )
    assert r.returncode == 137, f"rc={r.returncode}\n{r.stderr[-1500:]}"
    assert "chaos.host_killed" in r.stderr
    out = _run(["--tp", "2", "--zero", "--steps", "2", "--save-every", "2",
                "--checkpoint", str(ck), "--auto-resume"],
               extra_env=_devs(4))
    assert "resumed at step 2 (elastic reshard: dp=4 -> dp=2)" in out
    assert "step 3:" in out


def test_watchdog_drains_and_exits_75_on_wedged_step(tmp_path):
    """Wedged-step watchdog at process level: step 2's dispatch hangs
    (chaos), the watchdog logs, drains the async queue, and exits with
    the documented 75 — leaving the accepted saves durable so the same
    command resumes."""
    import subprocess as sp

    ck = tmp_path / "ck"
    r = sp.run(
        [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"),
         "--tp", "2", "--zero", "--steps", "6", "--save-every", "2",
         "--checkpoint", str(ck), "--auto-resume",
         "--watchdog-secs", "3", "--chaos-wedge-step", "3",
         "--chaos-wedge-secs", "300"],
        capture_output=True, text=True, timeout=600, env=_env(_devs(4)),
    )
    assert r.returncode == 75, f"rc={r.returncode}\n{r.stderr[-1500:]}"
    assert "watchdog.step_wedged" in r.stderr
    assert '"drain": "drained"' in r.stderr
    assert (ck / "step_00000002" / "index.json").exists()
    out = _run(["--tp", "2", "--zero", "--steps", "1",
                "--checkpoint", str(ck), "--auto-resume"],
               extra_env=_devs(4))
    assert "resumed at step 2" in out


def test_fp16_resume_from_fp32_checkpoint_fails_loudly(tmp_path):
    """Resuming --fp16 from a checkpoint saved without a loss scaler
    (e.g. a dir mixing runs with different precision flags) names the
    mismatch instead of crashing inside load_state_dict."""
    ck = tmp_path / "ck"
    _run(["--tp", "2", "--steps", "4", "--checkpoint", str(ck)])
    err = _run(["--tp", "2", "--steps", "2", "--fp16",
                "--resume", str(ck)], expect_fail=True)
    assert "no loss-scaler state" in err


def test_sigterm_preempts_saves_and_resumes(tmp_path):
    """The preemption path end to end as a real process: SIGTERM (the
    Cloud TPU reclaim notice) makes the loop save, drain the async
    queue, and exit 0; rerunning the same command resumes."""
    import select
    import signal
    import time

    ck = tmp_path / "ck"
    args = ["--tp", "2", "--steps", "200", "--checkpoint", str(ck),
            "--auto-resume", "--save-every", "1000"]
    # stderr goes to a file, not a pipe: nobody reads it until the end,
    # and a pipe the child fills past 64KB of JAX warnings would wedge
    # it (and this test) forever
    err_path = tmp_path / "stderr.log"
    with open(err_path, "w") as err_f:
        proc = subprocess.Popen(
            [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"),
             *args],
            stdout=subprocess.PIPE, stderr=err_f, text=True, env=_env(),
        )
        try:
            deadline = time.monotonic() + 300
            lines = []
            saw_step = False
            while time.monotonic() < deadline:
                # select before readline: a child wedged pre-output must
                # fail this test at the deadline, not hang the suite
                ready, _, _ = select.select(
                    [proc.stdout], [], [],
                    max(0.0, deadline - time.monotonic()))
                if not ready:
                    break
                line = proc.stdout.readline()
                if not line:          # EOF: child exited early
                    break
                lines.append(line)
                if line.startswith("step 1:"):
                    proc.send_signal(signal.SIGTERM)
                    saw_step = True
                    break
            if not saw_step:
                pytest.fail("never saw step 1:\n" + "".join(lines))
            out, _ = proc.communicate(timeout=120)
        finally:
            proc.kill()
    err = err_path.read_text()
    assert proc.returncode == 0, err[-2000:]
    assert "preempted (signal SIGTERM)" in out
    assert list(ck.glob("step_*.ckpt")), "no durable checkpoint"
    out2 = _run(["--tp", "2", "--steps", "1", "--checkpoint", str(ck),
                 "--auto-resume"])
    assert "resumed at step" in out2


def test_second_sigterm_during_drain_still_exits_clean(tmp_path):
    """SIGTERM arriving DURING the save+drain window (schedulers resend
    the reclaim notice): the handler only sets the flag — drain is
    re-entrancy-guarded — so the process still exits 0 with a VALID
    (non-torn) newest checkpoint and the same command resumes."""
    import select
    import signal
    import time

    ck = tmp_path / "ck"
    args = ["--tp", "2", "--steps", "200", "--checkpoint", str(ck),
            "--auto-resume", "--save-every", "1000"]
    err_path = tmp_path / "stderr.log"
    with open(err_path, "w") as err_f:
        proc = subprocess.Popen(
            [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"),
             *args],
            stdout=subprocess.PIPE, stderr=err_f, text=True, env=_env(),
        )
        try:
            deadline = time.monotonic() + 300
            saw_step = False
            lines = []
            while time.monotonic() < deadline:
                ready, _, _ = select.select(
                    [proc.stdout], [], [],
                    max(0.0, deadline - time.monotonic()))
                if not ready:
                    break
                line = proc.stdout.readline()
                if not line:
                    break
                lines.append(line)
                if line.startswith("step 1:"):
                    # the reclaim notice, then an immediate resend: it
                    # lands while the loop is still stepping/saving/
                    # draining (any later and it can hit interpreter
                    # teardown, where restored default handlers would
                    # kill the child -15 — the exact-mid-drain timing
                    # is pinned by the in-process unit test)
                    proc.send_signal(signal.SIGTERM)
                    proc.send_signal(signal.SIGTERM)
                    saw_step = True
                    break
            if not saw_step:
                pytest.fail("never saw step 1:\n" + "".join(lines))
            out, _ = proc.communicate(timeout=120)
        finally:
            proc.kill()
    err = err_path.read_text()
    assert proc.returncode == 0, err[-2000:]
    assert out.count("preempted (") == 1
    from apex_tpu.io import latest_checkpoint, validate_checkpoint

    newest = latest_checkpoint(ck)  # torn files would be skipped: require
    validate_checkpoint(newest)     # the NEWEST to be the valid one
    assert sorted(ck.glob("step_*.ckpt"))[-1] == Path(newest)
    out2 = _run(["--tp", "2", "--steps", "1", "--checkpoint", str(ck),
                 "--auto-resume"])
    assert "resumed at step" in out2


def test_metrics_dir_telemetry(tmp_path):
    """--metrics-dir end to end: per-step loss lines still print (now
    through the async fetch seam), the StepStats windows land in
    metrics.jsonl with the (run_id, step) correlation, a final
    Prometheus snapshot exists, and the goodput report's fractions sum
    to 1 with productive time dominating an uninterrupted run."""
    import json

    md = tmp_path / "metrics"
    out = _run(["--tp", "2", "--steps", "4", "--metrics-dir", str(md),
                "--telemetry-every", "2", "--run-id", "mtest"])
    losses = [float(l.split("loss=")[1].split()[0])
              for l in out.splitlines() if l.startswith("step ")]
    assert len(losses) == 4 and all(np.isfinite(losses))
    assert "telemetry[" in out
    recs = [json.loads(l) for l in (md / "metrics.jsonl").read_text()
            .splitlines()]
    by_metric = {}
    for r in recs:
        by_metric.setdefault(r["metric"], []).append(r)
    assert "apex_train_loss" in by_metric
    assert "apex_train_grad_norm_last" in by_metric
    assert all(r["run_id"] == "mtest" for r in recs)
    # counters accumulate across windows: the last steps_total sample
    # covers every step
    assert by_metric["apex_train_steps_total"][-1]["value"] == 4
    prom = (md / "metrics.prom").read_text()
    assert "# TYPE apex_train_loss gauge" in prom
    report = json.loads((md / "goodput_report.json").read_text())
    f = report["fractions"]
    assert abs(sum(f.values()) - 1.0) < 1e-9
    assert f["productive"] > 0.5
    assert report["tokens"] == 4 * 8 * 64  # steps x batch x seq
    assert "goodput:" in out


def test_goodput_attributes_wedge(tmp_path):
    """The ISSUE 10 acceptance run: a chaos-interrupted `--zero
    --auto-resume --metrics-dir` run (wedged step -> watchdog exit 75
    -> elastic resume) yields a goodput report whose fractions sum to
    1 AND attribute the injected fault: wedge > 0 (the watchdog's
    on_wedge hook stamped the dying session), restart > 0 (the gap to
    the relaunch), checkpoint time accounted."""
    import json
    import subprocess as sp

    ck, md = tmp_path / "ck", tmp_path / "metrics"
    base = ["--tp", "2", "--zero", "--save-every", "2",
            "--checkpoint", str(ck), "--auto-resume",
            "--metrics-dir", str(md), "--telemetry-every", "2"]
    r = sp.run(
        [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"),
         *base, "--steps", "6", "--watchdog-secs", "3",
         "--chaos-wedge-step", "3", "--chaos-wedge-secs", "300"],
        capture_output=True, text=True, timeout=600, env=_env(_devs(4)),
    )
    assert r.returncode == 75, f"rc={r.returncode}\n{r.stderr[-1500:]}"
    sessions = list(md.glob("goodput_session_*.json"))
    assert len(sessions) == 1
    assert json.loads(sessions[0].read_text())["exit_cause"] == "wedge"
    out = _run([*base, "--steps", "2"], extra_env=_devs(4))
    assert "resumed at step 2" in out
    report = json.loads((md / "goodput_report.json").read_text())
    assert report["sessions"] == 2
    assert report["wedge_events"] == 1
    assert report["exit_causes"] == ["wedge", "clean"]
    f = report["fractions"]
    assert abs(sum(f.values()) - 1.0) < 1e-9, f
    assert f.get("wedge", 0) > 0, f
    assert f.get("restart", 0) > 0, f
    assert f.get("productive", 0) > 0, f
    assert "checkpoint" in report["seconds"]


def test_serve_metrics_dir(tmp_path):
    """serve_gpt.py --metrics-dir: the scheduler's queue/occupancy
    gauges and admission/TTFT/inter-token histograms land in both
    export formats."""
    import json

    md = tmp_path / "smetrics"
    r = subprocess.run(
        [sys.executable, str(REPO / "examples/gpt/serve_gpt.py"),
         "--smoke", "--metrics-dir", str(md)],
        capture_output=True, text=True, timeout=600, env=_env(),
    )
    assert r.returncode == 0, f"stderr:\n{r.stderr[-2000:]}"
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metrics_dir"] == str(md)
    prom = (md / "metrics.prom").read_text()
    for name in ("apex_serve_queue_depth", "apex_serve_active_slots",
                 "apex_serve_free_pages", "apex_serve_ttft_seconds",
                 "apex_serve_inter_token_seconds",
                 "apex_serve_admission_wait_seconds",
                 "apex_serve_completions_total"):
        assert name in prom, name
    recs = [json.loads(l)
            for l in (md / "metrics.jsonl").read_text().splitlines()]
    counts = {r_["metric"]: r_["value"] for r_ in recs}
    assert counts["apex_serve_ttft_seconds_count"] == rec["stats"]["admitted"]
    assert counts["apex_serve_completions_total"] == rec["stats"]["evicted"]


def test_serve_replica_id_suffixes_artifacts(tmp_path):
    """serve_gpt.py --replica-id: N replica processes can share one
    sink dir — metrics land in metrics_<id>.jsonl/.prom and the
    replica id is folded into the run id (trace file names derive from
    it), so a fleet's artifacts never clobber each other."""
    import json

    md, td = tmp_path / "smetrics", tmp_path / "straces"
    r = subprocess.run(
        [sys.executable, str(REPO / "examples/gpt/serve_gpt.py"),
         "--smoke", "--metrics-dir", str(md), "--trace-dir", str(td),
         "--replica-id", "r0"],
        capture_output=True, text=True, timeout=600, env=_env(),
    )
    assert r.returncode == 0, f"stderr:\n{r.stderr[-2000:]}"
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert (md / "metrics_r0.prom").exists()
    assert (md / "metrics_r0.jsonl").exists()
    assert not (md / "metrics.prom").exists(), \
        "--replica-id must suffix, not also write the shared name"
    assert "serve_r0" in rec["trace_file"]
    recs = [json.loads(l)
            for l in (md / "metrics_r0.jsonl").read_text().splitlines()]
    assert all(r_["run_id"] == "serve_r0" for r_ in recs)


def test_supervised_gauntlet_one_invocation_survives_all(tmp_path):
    """The ISSUE 11 acceptance run: ONE `pretrain_gpt.py --supervise
    --zero --auto-resume` invocation survives the scripted fault
    gauntlet — attempt 0 hard-killed (rc 137), attempt 1's step wedged
    until the watchdog fires (rc 75), attempt 2's newest checkpoint
    corrupted (size-preserving bit flips the completeness/torn-size
    seams cannot see) so its restore crashes — and the supervisor
    quarantines exactly the bad step dir, attempt 3 resumes from the
    prior step, reaches the target, and the whole job exits 0 with
    goodput fractions summing to exactly 1 and the restart/wedge
    downtime attributed."""
    import json
    import subprocess as sp

    ck, md = tmp_path / "ck", tmp_path / "metrics"
    script = tmp_path / "faults.json"
    script.write_text(json.dumps({
        "0": {"args": ["--chaos-kill-at-step", "3"]},
        "1": {"args": ["--watchdog-secs", "3", "--chaos-wedge-step", "4",
                       "--chaos-wedge-secs", "300"]},
        "2": {"corrupt_newest_checkpoint": True},
    }))
    r = sp.run(
        [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"),
         "--supervise", "--tp", "2", "--zero", "--auto-resume",
         "--steps", "6", "--save-every", "2", "--checkpoint", str(ck),
         "--metrics-dir", str(md), "--fault-script", str(script),
         "--max-restarts", "8", "--backoff-base", "0.05",
         "--backoff-cap", "0.2"],
        capture_output=True, text=True, timeout=600, env=_env(_devs(4)),
    )
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    # every fault fired, in order, and each was survived
    assert "chaos.host_killed" in r.stderr          # attempt 0: rc 137
    assert "watchdog.step_wedged" in r.stderr       # attempt 1: rc 75
    assert "checkpoint.quarantined" in r.stderr     # attempt 2: corrupt
    assert "supervisor.quarantined" in r.stderr
    assert r.stderr.count("supervisor.restarting") == 3
    # quarantine semantics: EXACTLY the bad step dir moved aside, with
    # its reason file, and the run resumed from the PRIOR step
    q = ck / "quarantine"
    assert [p.name for p in sorted(q.glob("step_*")) if p.is_dir()] \
        == ["step_00000004"]
    reason = json.loads((q / "step_00000004.reason.json").read_text())
    assert "crc32" in reason["reason"]
    assert "resumed at step 2" in r.stdout          # fell back one step
    assert "step 7:" in r.stdout                    # reached the target
    assert "supervisor goodput:" in r.stdout        # one job summary
    # goodput: 4 sessions, the wedge stamped, fractions closed over the
    # whole supervised job (restart gaps = backoff + relaunch)
    report = json.loads((md / "goodput_report.json").read_text())
    assert report["sessions"] == 4
    assert report["wedge_events"] == 1
    f = report["fractions"]
    assert abs(sum(f.values()) - 1.0) < 1e-9, f
    assert f.get("wedge", 0) > 0, f
    assert f.get("restart", 0) > 0, f
    assert f.get("productive", 0) > 0, f


def test_supervised_crash_loop_trips_breaker(tmp_path):
    """The crash-loop acceptance contract at process level: a fault
    script that kills EVERY attempt at step 0 (no checkpoint ever
    published, no goodput steps — zero progress) trips the circuit
    breaker after exactly K=3 consecutive failures and the supervisor
    exits the documented breaker code 76 — never an unbounded restart
    loop.  (The pinned-backoff-schedule half of the contract rides the
    rng seam in tests/test_supervisor.py.)"""
    import json
    import subprocess as sp

    ck = tmp_path / "ck"
    script = tmp_path / "faults.json"
    kill = {"args": ["--chaos-kill-at-step", "0"]}
    script.write_text(json.dumps({"0": kill, "1": kill, "2": kill}))
    r = sp.run(
        [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"),
         "--supervise", "--zero", "--auto-resume", "--steps", "4",
         "--save-every", "100", "--checkpoint", str(ck),
         "--fault-script", str(script), "--crash-loop-threshold", "3",
         "--backoff-base", "0.05", "--backoff-cap", "0.1"],
        capture_output=True, text=True, timeout=600, env=_env(),
    )
    assert r.returncode == 76, f"rc={r.returncode}\n{r.stderr[-2000:]}"
    assert "supervisor.circuit_breaker_tripped" in r.stderr
    assert '"no_progress_failures": 3' in r.stderr
    # two backoff sleeps, then the breaker — no fourth launch
    assert r.stderr.count("supervisor.restarting") == 2
    assert r.stderr.count("chaos.host_killed") == 3


def test_serve_supervised_recovers_from_wedge(tmp_path):
    """Serving rides the same machinery: attempt 0's decode step 3
    wedges, the serving watchdog logs the queued/in-flight request ids
    (the requeue manifest) and exits 75, the supervisor restarts the
    engine WITHOUT the fault, and the job finishes 0."""
    import json
    import subprocess as sp

    script = tmp_path / "faults.json"
    script.write_text(json.dumps({
        "0": {"args": ["--watchdog-secs", "2",
                       "--chaos-wedge-decode-step", "3",
                       "--chaos-wedge-secs", "300"]},
    }))
    r = sp.run(
        [sys.executable, str(REPO / "examples/gpt/serve_gpt.py"),
         "--smoke", "--supervise", "--fault-script", str(script),
         "--max-restarts", "3", "--backoff-base", "0.05",
         "--backoff-cap", "0.2"],
        capture_output=True, text=True, timeout=600, env=_env(),
    )
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-2000:]}"
    assert "serve.step_wedged" in r.stderr
    assert '"queued_rids"' in r.stderr
    assert r.stderr.count("supervisor.restarting") == 1
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["smoke"] is True  # attempt 1 met the full smoke contract


def test_serve_gpt_smoke_contract():
    """The serving driver's acceptance contract end-to-end:
    ``serve_gpt.py --smoke`` must admit/evict >= 3 generations through
    recycled pages, reproduce the training forward's greedy
    continuation for every served token, and compile the decode step
    exactly once (the script asserts all three; rc 0 == contract)."""
    import json

    r = subprocess.run(
        [sys.executable, str(REPO / "examples/gpt/serve_gpt.py"), "--smoke"],
        capture_output=True, text=True, timeout=600, env=_env(),
    )
    assert r.returncode == 0, f"stderr:\n{r.stderr[-2000:]}"
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["smoke"] is True and rec["decode_compiles"] == 1
    assert rec["stats"]["evicted"] >= 3


def test_forensics_wedge_leaves_correlated_artifacts(tmp_path):
    """The ISSUE 14 acceptance run: ONE supervised `--zero
    --auto-resume --trace-dir` invocation under a scripted chaos
    gauntlet (attempt 0's step wedges -> watchdog rc 75; attempt 1
    hard-killed rc 137; attempt 2 finishes) leaves the full forensics
    chain, all correlated by (run_id, step):

    (a) a flight-recorder dump whose `wedged_step` names the wedged
        step and whose span ring ends at exactly its predecessor (the
        chaos wedge stalls inside the top-of-iteration hook, so the
        last completed dispatch is step wedged-1; the stuck-OPEN-span
        shape of a wedged dispatch is pinned in-process by
        tests/test_tracing.py::TestDumpTriggers),
    (b) an `apex_anomaly_step_time_total` increment (the watchdog's
        forced step-time alert) persisted in the anomaly summary and
        the metrics JSONL,
    (c) a Perfetto-loadable Chrome trace carrying the same
        (run_id, step)-stamped spans,
    and the supervisor's restart records attach the newest dump path —
    the hard-kill attempt included (nothing ran at ITS death; the
    attached artifact is the latest on disk)."""
    import json
    import subprocess as sp

    ck, md, td = tmp_path / "ck", tmp_path / "metrics", tmp_path / "trace"
    script = tmp_path / "faults.json"
    script.write_text(json.dumps({
        "0": {"args": ["--watchdog-secs", "10", "--chaos-wedge-step", "3",
                       "--chaos-wedge-secs", "300"]},
        "1": {"args": ["--chaos-kill-at-step", "5"]},
    }))
    r = sp.run(
        [sys.executable, str(REPO / "examples/gpt/pretrain_gpt.py"),
         "--supervise", "--tp", "2", "--zero", "--auto-resume",
         "--steps", "6", "--save-every", "2", "--checkpoint", str(ck),
         "--metrics-dir", str(md), "--trace-dir", str(td),
         "--telemetry-every", "2", "--run-id", "fr1",
         "--fault-script", str(script), "--max-restarts", "8",
         "--backoff-base", "0.05", "--backoff-cap", "0.2"],
        capture_output=True, text=True, timeout=600, env=_env(_devs(4)),
    )
    assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr[-3000:]}"
    assert "watchdog.step_wedged" in r.stderr
    assert "chaos.host_killed" in r.stderr

    # (a) the flight-recorder dump names the wedged step...
    from apex_tpu.observability import flightrec

    dumps = sorted(td.glob("flightrec_dump_*.json"))
    assert len(dumps) == 1, [p.name for p in dumps]
    dump = flightrec.load_dump(dumps[0])
    assert dump["reason"] == "wedge"
    assert dump["run_id"] == "fr1"
    wedged_step = dump["wedged_step"]
    assert wedged_step == 3  # the chaos plan's step, by name
    # the wedge stalls the top-of-iteration hook BEFORE the step
    # context advances: the dump's correlation and its last completed
    # dispatch span both sit at exactly wedged_step - 1 — the ring
    # SHOWS where the run stopped
    assert dump["step"] == wedged_step - 1
    dispatch_steps = [s["attrs"].get("step") for s in dump["spans"]
                      if s["name"] == "train.step.dispatch"]
    assert dispatch_steps and dispatch_steps[-1] == wedged_step - 1
    assert all(s["attrs"].get("run_id") == "fr1"
               for s in dump["spans"])
    assert any(s["name"] == "train.data_wait" for s in dump["spans"])
    assert any(e["event"] == "watchdog.step_wedged"
               for e in dump["events"])

    # (b) the anomaly counter incremented and survived the os._exit
    # (every attempt persists a pid-suffixed summary at exit; exactly
    # one — the wedged attempt's — carries the forced wedge alert)
    summaries = [json.loads(p.read_text())
                 for p in md.glob("anomaly_*.json")]
    wedged = [s for s in summaries
              if any(a.get("wedge") for a in s["alerts"])]
    assert len(wedged) == 1, [s["counts"] for s in summaries]
    summary = wedged[0]
    assert summary["counts"].get("step_time", 0) >= 1
    assert summary["run_id"] == "fr1"
    wedge_alerts = [a for a in summary["alerts"] if a.get("wedge")]
    assert wedge_alerts and wedge_alerts[0]["step"] == wedged_step
    metrics_pts = [json.loads(l)
                   for l in (md / "metrics.jsonl").read_text().splitlines()]
    counter = [p for p in metrics_pts
               if p["metric"] == "apex_anomaly_step_time_total"]
    assert counter and counter[-1]["value"] >= 1

    # (c) a Perfetto-loadable trace from the wedged attempt, same join:
    # its dispatch track also ends at the wedge boundary
    traces = sorted(td.glob("trace_fr1_*.json"))
    assert traces, "no trace files exported"
    boundary_hits = []
    for p in traces:
        doc = json.loads(p.read_text())
        assert doc["schema"] == "apex_tpu_trace_v1"
        assert {"name", "ph", "ts", "pid", "tid"} <= set(
            doc["traceEvents"][0])
        steps = [e["args"]["step"] for e in doc["traceEvents"]
                 if e["name"] == "train.step.dispatch"
                 and e["args"].get("run_id") == "fr1"]
        if steps and max(steps) == wedged_step - 1:
            boundary_hits.append(p.name)
    assert boundary_hits, "no trace ends at the wedge boundary"

    # the supervisor attached a dump path to EVERY restart record
    # (wedge AND hard kill), and the job still reached the target
    restarting = [l for l in r.stderr.splitlines()
                  if "supervisor.restarting" in l]
    assert len(restarting) == 2
    for line in restarting:
        assert '"flight_dump": "' in line and "flightrec" in line, line
    assert "step 6:" in r.stdout or "6 steps" in r.stdout


def test_trace_dir_only_run_keeps_the_forensics_loop_alive(tmp_path):
    """`--trace-dir` WITHOUT `--metrics-dir` still drives the full
    forensics loop: telemetry windows are harvested (they are the
    flight recorder's republish cadence and the anomaly detectors'
    feed, not just the metrics files' source), so the rolling
    flightrec_<pid>.json — the hard-kill (137) dump — exists, the
    anomaly summary persists, and the Perfetto trace exports."""
    import json

    td = tmp_path / "t"
    out = _run(["--tp", "2", "--steps", "4", "--trace-dir", str(td),
                "--telemetry-every", "2", "--run-id", "tonly"],
               extra_env=_devs(4))
    assert "telemetry[" in out  # windows really harvested
    rolling = list(td.glob("flightrec_[0-9]*.json"))
    assert len(rolling) == 1, sorted(p.name for p in td.iterdir())
    rec = json.loads(rolling[0].read_text())
    assert rec["schema"] == "apex_tpu_flightrec_v1"
    assert rec["run_id"] == "tonly"
    assert any(s["name"] == "train.step.dispatch" for s in rec["spans"])
    assert list(td.glob("anomaly_*.json"))
    assert list(td.glob("trace_tonly_*.json"))
