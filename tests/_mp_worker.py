"""Worker for the real multi-process distributed tests.

Launched by ``tests/test_multiprocess.py`` as 2 OS processes × 4 virtual
CPU devices each (the reference's test shape:
``apex/transformer/testing/distributed_test_base.py:22-94`` spawns
``MultiProcessTestCase`` workers with file-store rendezvous; here the
rendezvous is ``jax.distributed.initialize``'s coordinator).

Phases:
1. **dp×tp train parity** — build the mesh across processes via
   ``parallel_state.initialize_model_parallel``, run 3 GPT train steps
   on global arrays, emit the loss trajectory (the pytest side compares
   it against a single-process oracle).
2. **ZeRO distributed checkpoint/resume** — train 2 steps with
   ``DistributedFusedAdam`` (state sharded over (tp, dp) across both
   processes), write a per-process checkpoint of exactly the shards
   each process addresses (``io.save_distributed_checkpoint``),
   "restart" by reassembling global arrays from the shard files, run
   one more step, and verify bit-identical params vs the uninterrupted
   run.
"""

import argparse
import json
import os
import sys
from pathlib import Path

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--out", required=True)
    args = ap.parse_args()
    out = Path(args.out)

    import jax

    jax.distributed.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    assert jax.process_count() == args.num_processes
    assert jax.local_device_count() == 4, jax.local_devices()
    assert jax.device_count() == 8, jax.devices()

    from apex_tpu import io
    from apex_tpu.models.gpt import (
        GPTConfig,
        init_params,
        make_train_step,
        param_specs,
    )
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.fused_adam import AdamState
    from apex_tpu.transformer import parallel_state as ps
    from jax.sharding import PartitionSpec as P

    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size_=2, devices=jax.devices()
    )
    config = GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
        max_seq_len=16, compute_dtype=jnp.float32, checkpoint_layers=True,
    )
    specs = param_specs(config)
    rng = np.random.RandomState(0)
    tokens_np = rng.randint(0, 64, size=(8, 16))
    targets_np = np.roll(tokens_np, -1, axis=1)

    def to_global(tree, spec_tree):
        return io.make_global_array_tree(tree, mesh, spec_tree)

    # ---------------------------------------------- phase 1: dp×tp parity
    params = to_global(init_params(config, jax.random.PRNGKey(0)), specs)
    opt = FusedAdam(lr=1e-2)
    sspec = AdamState(step=P(), exp_avg=specs, exp_avg_sq=specs, master=None)
    state = to_global(opt.init(jax.tree.map(np.asarray, params)), sspec)
    # ^ init on host values: every process builds the same zero state
    data_spec = P("dp", None)
    tokens = to_global(tokens_np, data_spec)
    targets = to_global(targets_np, data_spec)

    step = make_train_step(config, opt, mesh)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, tokens, targets)
        losses.append(float(loss))
    if args.process_id == 0:
        (out / "losses.json").write_text(json.dumps(losses))
    print(f"[worker {args.process_id}] phase1 losses: {losses}", flush=True)

    # --- phase 1b: pp=2 × tp=4 (dp=1) — the pipeline ppermutes CROSS the
    # process boundary.  The mesh is dp-outermost, so with dp=1 stage 0
    # is devices 0-3 (all of process 0) and stage 1 is devices 4-7 (all
    # of process 1): every cross-stage send is a cross-process transfer.
    from apex_tpu.models.gpt import make_pp_train_step

    ps.destroy_model_parallel()
    pp_mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size_=4, pipeline_model_parallel_size_=2,
        devices=jax.devices(),
    )
    assert pp_mesh.shape["dp"] == 1
    stage0 = {d.process_index for d in pp_mesh.devices[0, 0].ravel()}
    stage1 = {d.process_index for d in pp_mesh.devices[0, 1].ravel()}
    assert stage0 == {0} and stage1 == {1}, (
        f"stages must live on different processes (got {stage0} vs "
        f"{stage1}) for this test to exercise cross-process ppermutes")
    pp_cfg = GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=4, num_attention_heads=4,
        max_seq_len=16, compute_dtype=jnp.float32, checkpoint_layers=True,
    )
    pp_base = param_specs(pp_cfg)
    pp_specs = dict(pp_base)
    pp_specs["layers"] = {k: P("pp", *s[1:]) for k, s in pp_base["layers"].items()}
    pp_params_host = init_params(pp_cfg, jax.random.PRNGKey(2))
    pp_opt = FusedAdam(lr=1e-2)
    pp_state_host = pp_opt.init(pp_params_host)
    pp_sspec = AdamState(step=P(), exp_avg=pp_specs, exp_avg_sq=pp_specs,
                         master=None)
    pp_params = io.make_global_array_tree(pp_params_host, pp_mesh, pp_specs)
    pp_state = io.make_global_array_tree(pp_state_host, pp_mesh, pp_sspec)
    pp_tok = io.make_global_array_tree(tokens_np, pp_mesh, P("dp", None))
    pp_tgt = io.make_global_array_tree(targets_np, pp_mesh, P("dp", None))
    pp_step = make_pp_train_step(pp_cfg, pp_opt, pp_mesh, num_microbatches=2)
    pp_losses = []
    for _ in range(2):
        pp_params, pp_state, pp_loss = pp_step(pp_params, pp_state, pp_tok, pp_tgt)
        pp_losses.append(float(pp_loss))
    if args.process_id == 0:
        (out / "pp_losses.json").write_text(json.dumps(pp_losses))
    print(f"[worker {args.process_id}] phase1b pp losses: {pp_losses}", flush=True)
    ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size_=2, devices=jax.devices()
    )

    # ------------------------------- phase 2: ZeRO distributed ckpt/resume
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    zparams_host = init_params(config, jax.random.PRNGKey(1))
    zopt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
    zstate_host = zopt.init(
        zparams_host, world_size=mesh.shape["dp"], param_specs=specs,
        axis_sizes={"tp": mesh.shape["tp"]},
    )
    zsspec = zopt.state_partition_spec()
    zparams = to_global(zparams_host, specs)
    zstate = to_global(zstate_host, zsspec)
    zstep = make_train_step(config, zopt, mesh)

    for _ in range(2):
        zparams, zstate, zloss = zstep(zparams, zstate, tokens, targets)

    ckpt_dir = out / "zero_ckpt"
    io.save_distributed_checkpoint(ckpt_dir, {"params": zparams, "state": zstate})
    multihost_utils.sync_global_devices("zero ckpt written")

    # uninterrupted continuation
    p3, s3, _ = zstep(zparams, zstate, tokens, targets)

    # restart: reassemble from the per-process shard files
    template = {
        "params": jax.tree.map(np.asarray, zparams_host),
        "state": jax.tree.map(
            lambda x: np.zeros(x.shape, x.dtype), zstate_host
        ),
    }
    # mesh-aware load: each process assembles only the slices its own
    # devices need, straight into global arrays
    restored = io.load_distributed_checkpoint(
        ckpt_dir, template, mesh=mesh,
        spec_tree={"params": specs, "state": zsspec},
    )
    rparams, rstate = restored["params"], restored["state"]
    p3r, s3r, _ = zstep(rparams, rstate, tokens, targets)

    # bit-identical resume, checked shard-by-shard on THIS process
    def assert_shards_equal(a, b, what):
        for leaf_a, leaf_b in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            for sa, sb in zip(leaf_a.addressable_shards, leaf_b.addressable_shards):
                assert sa.index == sb.index
                if not np.array_equal(np.asarray(sa.data), np.asarray(sb.data)):
                    raise AssertionError(
                        f"[worker {args.process_id}] {what} diverged after resume"
                    )

    assert_shards_equal(p3, p3r, "params")
    assert_shards_equal(s3, s3r, "optimizer state")
    (out / f"zero_ok_{args.process_id}").write_text("ok")
    print(f"[worker {args.process_id}] phase2 zero resume: bit-identical", flush=True)
    multihost_utils.sync_global_devices("done")


if __name__ == "__main__":
    main()
