"""Inference-engine tests: prefill↔decode parity, the paged KV cache,
the fused sampling head, and the continuous-batching scheduler.

The parity band is the load-bearing contract: token-by-token decode
over the paged cache must reproduce the full-sequence TRAINING forward
(same weights, causal) — in fp32 to reduction-reorder ulps (XLA CPU
picks different matmul microkernels for an (S, S) score block and a
single-query row, so literally-bitwise equality across shapes does not
exist on this backend; the single-token case, where the shapes agree,
IS pinned bitwise), with GQA and tp=2 shard_map variants.  The
scheduler band pins the admission/eviction/recycling semantics and the
chaos seam (a decode-kernel failure degrades once, the server keeps
serving the SAME tokens).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.inference import (
    ContinuousBatchingScheduler, DecodeConfig, GARBAGE_PAGE, KVCacheConfig,
    PageAllocator, Request, alloc_pools, pages_needed, write_decode_kv,
    write_prompt_kv,
)
from apex_tpu.inference.decode import make_decode_step, make_prefill
from apex_tpu.models.gpt import (
    GPTConfig, forward_decode, gpt_forward, init_params, param_specs,
)
from apex_tpu.ops.decode_attention_pallas import (
    decode_attention_xla, paged_decode_attention_pallas,
)
from apex_tpu.ops.decode_sampling_pallas import (
    fused_sample_pallas, fused_sample_xla, gumbel_from_seed,
)
from apex_tpu.resilience.chaos import ChaosMonkey, ChaosPlan
from apex_tpu.resilience.fallback import get_registry


def tiny_cfg(**kw):
    base = dict(
        vocab_size=61, hidden_size=32, num_layers=2,
        num_attention_heads=4, max_seq_len=64,
        position_embedding_type="rope", compute_dtype=jnp.float32,
        checkpoint_layers=False,
    )
    base.update(kw)
    return GPTConfig(**base)


def _decode_logits_tokenwise(params, cfg, tokens, prefix, kcfg, pt_row,
                             attn_impl="xla"):
    """Prefill ``tokens[:prefix]`` through the training forward, then
    decode positions ``prefix..S-1`` one token at a time, returning the
    per-position fp32 logits."""
    S = tokens.shape[1]
    _, kv = gpt_forward(params, tokens[:, :S], cfg, return_kv=True)
    ks = kv[0][:, 0].transpose(0, 2, 1, 3)[:, :prefix]
    vs = kv[1][:, 0].transpose(0, 2, 1, 3)[:, :prefix]
    pools = alloc_pools(cfg.num_layers, cfg.kv_heads, cfg.head_dim, kcfg)
    kp, vp = write_prompt_kv(pools["k"], pools["v"], ks, vs, pt_row,
                             jnp.int32(prefix))
    pools = {"k": kp, "v": vp}
    out = []
    for pos in range(prefix, S):
        hidden, pools = forward_decode(
            params, tokens[:, pos], jnp.asarray([pos], jnp.int32),
            jnp.asarray([True]), pools, pt_row[None], cfg,
            attn_impl=attn_impl)
        out.append(jnp.matmul(hidden.astype(jnp.float32),
                              params["embed"].T.astype(jnp.float32))[0])
    return jnp.stack(out)  # (S - prefix, V)


# ------------------------------------------------------ prefill <-> decode
class TestDecodeParity:
    @pytest.mark.parametrize("pet,gqa", [
        ("learned", None), ("rope", None), ("rope", 2)])
    def test_decode_logits_match_training_fp32(self, pet, gqa):
        cfg = tiny_cfg(position_embedding_type=pet, num_query_groups=gqa,
                       num_layers=3)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(1)
        S, prefix = 12, 5
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(1, S)))
        ref = gpt_forward(params, tokens, cfg)  # (S, 1, V)
        kcfg = KVCacheConfig(num_pages=8, page_size=4, pages_per_seq=5,
                             dtype=jnp.float32)
        pt_row = jnp.asarray([1, 2, 3, 4, 5], jnp.int32)
        dec = _decode_logits_tokenwise(params, cfg, tokens, prefix, kcfg,
                                       pt_row)
        np.testing.assert_allclose(
            np.asarray(dec), np.asarray(ref[prefix:, 0]),
            rtol=0, atol=5e-6,
            err_msg="token-by-token decode logits diverged from the "
                    "training forward beyond fp32 reduction-reorder ulps")

    def test_first_token_decode_is_bitwise(self):
        """At matching contraction shapes (a length-1 sequence) the
        decode expression IS the training expression: bitwise fp32.
        This pins that every per-op formula (LN, projections, RoPE,
        softmax fill, head) is shared, so the general-case tolerance
        above covers ONLY shape-dependent reduction reordering."""
        cfg = tiny_cfg(num_layers=2)
        params = init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.asarray([[7]])
        ref = gpt_forward(params, tokens, cfg)[0, 0]
        kcfg = KVCacheConfig(num_pages=3, page_size=1, pages_per_seq=1,
                             dtype=jnp.float32)
        pools = alloc_pools(cfg.num_layers, cfg.kv_heads, cfg.head_dim, kcfg)
        hidden, _ = forward_decode(
            params, tokens[:, 0], jnp.asarray([0], jnp.int32),
            jnp.asarray([True]), pools, jnp.asarray([[1]], jnp.int32), cfg,
            attn_impl="xla")
        dec = jnp.matmul(hidden.astype(jnp.float32),
                         params["embed"].T.astype(jnp.float32))[0]
        assert bool(jnp.all(dec == ref)), (
            "single-token decode is no longer bitwise against the "
            "training forward — a shared-expression seam drifted")

    def test_decode_matches_training_bf16(self):
        """bf16 compute + bf16 KV storage: parity within bf16
        tolerance (the cache round-trips k/v through the storage dtype
        once; activations already round at every op)."""
        cfg = tiny_cfg(compute_dtype=jnp.bfloat16)
        params = init_params(cfg, jax.random.PRNGKey(2))
        rng = np.random.RandomState(3)
        S, prefix = 8, 3
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(1, S)))
        ref = gpt_forward(params, tokens, cfg)
        kcfg = KVCacheConfig(num_pages=6, page_size=4, pages_per_seq=2,
                             dtype=jnp.bfloat16)
        dec = _decode_logits_tokenwise(
            params, cfg, tokens, prefix, kcfg,
            jnp.asarray([1, 2], jnp.int32))
        np.testing.assert_allclose(
            np.asarray(dec), np.asarray(ref[prefix:, 0]),
            rtol=0.05, atol=0.1)

    def test_tp2_decode_matches_dense_training(self, devices8):
        """forward_decode inside a tp=2 shard_map (column/row-parallel
        projections, kv heads sharded over tp, vocab-parallel head)
        matches the DENSE training forward."""
        cfg = tiny_cfg(vocab_size=64)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(1)
        S = 8
        tokens = jnp.asarray(rng.randint(0, 64, size=(1, S)))
        nxt = jnp.asarray([[5]], jnp.int32)
        full = jnp.concatenate([tokens, nxt], axis=1)
        ref = gpt_forward(params, full, cfg)[S, 0]

        kcfg = KVCacheConfig(num_pages=6, page_size=4, pages_per_seq=3,
                             dtype=jnp.float32)
        mesh = Mesh(np.array(devices8[:2]).reshape(2, 1), ("tp", "dp"))
        pool_spec = P(None, None, None, "tp", None)
        pools = alloc_pools(cfg.num_layers, cfg.kv_heads, cfg.head_dim, kcfg)
        pt_row = jnp.asarray([[1, 2, 3]], jnp.int32)

        def local(params, kpool, vpool, toks, pos, active, pt):
            _, kv = gpt_forward(params, toks[:, :S], cfg, axis_name="tp",
                                return_hidden=True, return_kv=True)
            ks = kv[0][:, 0].transpose(0, 2, 1, 3)
            vs = kv[1][:, 0].transpose(0, 2, 1, 3)
            kpool, vpool = write_prompt_kv(kpool, vpool, ks, vs, pt[0],
                                           jnp.int32(S))
            h, _ = forward_decode(params, toks[:, S], pos, active,
                                  {"k": kpool, "v": vpool}, pt, cfg,
                                  axis_name="tp", attn_impl="xla")
            return jnp.matmul(h.astype(jnp.float32),
                              params["embed"].T.astype(jnp.float32))

        fn = jax.shard_map(
            local, mesh=mesh,
            in_specs=(param_specs(cfg), pool_spec, pool_spec,
                      P(), P(), P(), P()),
            out_specs=P(None, "tp"), check_vma=False)
        got = fn(params, pools["k"], pools["v"], full,
                 jnp.asarray([S], jnp.int32), jnp.asarray([True]), pt_row)
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref),
                                   rtol=0, atol=5e-6)


# -------------------------------------------------- decode attention kernel
class TestDecodeAttentionKernel:
    def _case(self, rng, B=3, H=4, KVH=2, D=16, num_pages=9, page=8, P=4):
        q = jnp.asarray(rng.randn(B, H, D), jnp.float32)
        kp = jnp.asarray(rng.randn(num_pages, page, KVH, D), jnp.float32)
        vp = jnp.asarray(rng.randn(num_pages, page, KVH, D), jnp.float32)
        pt = jnp.asarray(rng.randint(1, num_pages, size=(B, P)), jnp.int32)
        return q, kp, vp, pt

    def test_kernel_matches_reference_gqa_partial_inactive(self):
        rng = np.random.RandomState(0)
        q, kp, vp, pt = self._case(rng)
        lengths = jnp.asarray([0, 5, 25], jnp.int32)  # inactive/tail/full
        ref = decode_attention_xla(q, kp, vp, pt, lengths)
        out = paged_decode_attention_pallas(q, kp, vp, pt, lengths,
                                            interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=0, atol=1e-5)
        assert float(jnp.max(jnp.abs(out[0]))) == 0.0, (
            "inactive (length 0) row must attend to nothing")

    def test_bf16_storage_widens_at_read(self):
        rng = np.random.RandomState(1)
        q, kp, vp, pt = self._case(rng)
        lengths = jnp.asarray([8, 16, 32], jnp.int32)
        ref = decode_attention_xla(q, kp.astype(jnp.bfloat16),
                                   vp.astype(jnp.bfloat16), pt, lengths)
        out = paged_decode_attention_pallas(
            q, kp.astype(jnp.bfloat16), vp.astype(jnp.bfloat16), pt,
            lengths, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=0.05, atol=0.05)

    def test_out_of_range_page_ids_clamp_not_wrap(self):
        """A corrupt page table (negative / past-pool ids) must behave
        exactly like its clamped self — in BOTH implementations (the
        APX107 contract at runtime)."""
        rng = np.random.RandomState(2)
        q, kp, vp, _ = self._case(rng, B=2, P=3)
        pt_bad = jnp.asarray([[-3, 2, 99], [1, -1, 1000]], jnp.int32)
        pt_ok = jnp.clip(pt_bad, 0, kp.shape[0] - 1)
        lengths = jnp.asarray([20, 24], jnp.int32)
        for impl in (decode_attention_xla,
                     lambda *a: paged_decode_attention_pallas(
                         *a, interpret=True)):
            a = impl(q, kp, vp, pt_bad, lengths)
            b = impl(q, kp, vp, pt_ok, lengths)
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------- fused sampling
class TestFusedSampling:
    def _case(self, rng, N=5, H=32, V=307):
        x2 = jnp.asarray(rng.randn(N, H), jnp.float32)
        emb = jnp.asarray(rng.randn(V, H), jnp.float32)
        seeds = jnp.asarray(rng.randint(0, 2 ** 31, size=(N,)), jnp.uint32)
        return x2, emb, seeds

    @pytest.mark.parametrize("temperature,top_k", [
        (0.0, 0), (1.0, 0), (0.7, 13), (1.3, 1), (0.9, 400)])
    def test_kernel_matches_reference_bitwise(self, temperature, top_k):
        """Same counter-based Gumbel stream, same threshold semantics:
        the kernel and the reference draw the IDENTICAL token (fp32
        dots pin the logits bitwise on CPU)."""
        rng = np.random.RandomState(0)
        x2, emb, seeds = self._case(rng)
        a = fused_sample_xla(x2, emb, seeds, temperature, top_k)
        b = fused_sample_pallas(x2, emb, seeds, temperature, top_k,
                                dot_dtype=jnp.float32, interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_greedy_is_argmax(self):
        rng = np.random.RandomState(1)
        x2, emb, seeds = self._case(rng)
        logits = x2 @ emb.T
        np.testing.assert_array_equal(
            np.asarray(fused_sample_xla(x2, emb, seeds, 0.0, 0)),
            np.asarray(jnp.argmax(logits, axis=-1)))

    def test_top_k_restricts_support(self):
        """Over many seeds, every draw lands inside the top-k set."""
        rng = np.random.RandomState(2)
        x2, emb, _ = self._case(rng, N=1)
        k = 7
        logits = x2 @ emb.T
        topset = set(np.asarray(jax.lax.top_k(logits, k)[1][0]).tolist())
        xs = jnp.broadcast_to(x2, (256, x2.shape[1]))
        seeds = jnp.arange(256, dtype=jnp.uint32)
        toks = np.asarray(fused_sample_xla(xs, emb, seeds, 0.8, k))
        assert set(toks.tolist()) <= topset
        assert len(set(toks.tolist())) > 1, "top-k sampling degenerated " \
            "to greedy (no variety across seeds)"

    @pytest.mark.slow
    def test_temperature_sampling_tracks_softmax(self):
        """Empirical distribution over 4000 seeds vs the true softmax:
        total-variation distance at the sampling-noise scale."""
        rng = np.random.RandomState(3)
        x2, emb, _ = self._case(rng, N=1, V=101)
        n = 4000
        xs = jnp.broadcast_to(x2, (n, x2.shape[1]))
        toks = np.asarray(fused_sample_xla(
            xs, emb, jnp.arange(n, dtype=jnp.uint32), 1.0, 0))
        p_emp = np.bincount(toks, minlength=101) / n
        p_true = np.asarray(jax.nn.softmax(x2[0] @ emb.T))
        assert 0.5 * np.abs(p_emp - p_true).sum() < 0.05

    def test_gumbel_stream_is_open_interval(self):
        g = gumbel_from_seed(jnp.arange(4096, dtype=jnp.uint32)[:, None],
                             jnp.arange(64, dtype=jnp.int32)[None, :])
        assert bool(jnp.all(jnp.isfinite(g)))


# -------------------------------------------------------------- KV cache
class TestKVCache:
    def test_allocator_reserves_garbage_page(self):
        a = PageAllocator(num_pages=5)
        got = a.allocate(4)
        assert got == [1, 2, 3, 4] and GARBAGE_PAGE not in got
        assert a.allocate(1) is None, "over-allocation must refuse, " \
            "never hand out the garbage page"

    def test_allocator_recycles_and_guards(self):
        a = PageAllocator(num_pages=4)
        pages = a.allocate(3)
        a.free(pages)
        assert a.free_pages == 3
        with pytest.raises(ValueError, match="double free"):
            a.free([pages[0]])  # already back in the free list
        with pytest.raises(ValueError, match="reserved"):
            a.free([GARBAGE_PAGE])
        with pytest.raises(ValueError, match="outside"):
            a.free([99])

    def test_pages_needed(self):
        assert pages_needed(1, 4) == 1
        assert pages_needed(4, 4) == 1
        assert pages_needed(5, 4) == 2

    def test_inactive_decode_write_hits_garbage_page_only(self):
        rng = np.random.RandomState(0)
        kp = jnp.asarray(rng.randn(4, 2, 1, 8), jnp.float32)
        vp = kp + 1
        k_new = jnp.ones((2, 1, 8))
        pt = jnp.asarray([[2], [3]], jnp.int32)
        pos = jnp.asarray([0, 1], jnp.int32)
        active = jnp.asarray([False, False])
        nk, nv = write_decode_kv(kp, vp, k_new, k_new, pt, pos, active)
        np.testing.assert_array_equal(np.asarray(nk[1:]), np.asarray(kp[1:]))
        np.testing.assert_array_equal(np.asarray(nv[1:]), np.asarray(vp[1:]))

    def test_prompt_pad_tail_hits_garbage_page_only(self):
        kp = jnp.zeros((2, 5, 4, 1, 8))
        ks = jnp.ones((2, 6, 1, 8))
        row = jnp.asarray([2, 3], jnp.int32)
        nk, _ = write_prompt_kv(kp, kp, ks, ks, row, jnp.int32(5))
        # positions 0..4 land in pages 2 (0..3) and 3 (slot 0); the
        # padded position 5 must NOT touch page 3 slot 1
        assert float(jnp.sum(jnp.abs(nk[:, 3, 1:]))) == 0.0
        assert float(jnp.sum(nk[:, 2])) == 4 * 8 * 2
        assert float(jnp.sum(nk[:, 3, 0])) == 8 * 2


# -------------------------------------------------------------- scheduler
def _sched(params, cfg, *, num_pages=10, page_size=4, pages_per_seq=6,
           max_batch=3, temperature=0.0, top_k=0, attn="xla", sample="xla",
           max_prompt=8, seed=0, watchdog=None):
    dcfg = DecodeConfig(
        cache=KVCacheConfig(num_pages=num_pages, page_size=page_size,
                            pages_per_seq=pages_per_seq, dtype=jnp.float32),
        max_batch=max_batch, max_prompt_len=max_prompt,
        temperature=temperature, top_k=top_k,
        attn_impl=attn, sample_impl=sample,
        sample_dot_dtype=jnp.float32, base_seed=seed)
    return ContinuousBatchingScheduler(params, cfg, dcfg,
                                       watchdog=watchdog)


def _requests(rng, n, vocab, plen=(2, 7), max_new=(2, 6)):
    return [Request(rid=i,
                    prompt=list(rng.randint(0, vocab,
                                            size=rng.randint(*plen))),
                    max_new_tokens=int(rng.randint(*max_new)))
            for i in range(n)]


class TestScheduler:
    @pytest.fixture(scope="class")
    def model(self):
        cfg = tiny_cfg()
        return cfg, init_params(cfg, jax.random.PRNGKey(0))

    def test_greedy_serving_matches_training_forward(self, model):
        """Every served token is the training forward's argmax
        continuation — end-to-end decode parity through admission,
        page recycling, and eviction."""
        cfg, params = model
        sched = _sched(params, cfg)
        rng = np.random.RandomState(7)
        for r in _requests(rng, 6, cfg.vocab_size):
            sched.submit(r)
        done = sched.run_until_drained()
        assert len(done) == 6
        assert sched.stats["admitted"] == 6 and sched.stats["evicted"] == 6
        for c in done[:3]:
            seq = list(c.prompt)
            for tok in c.tokens:
                logits = gpt_forward(params, jnp.asarray([seq]), cfg)
                assert int(jnp.argmax(logits[len(seq) - 1, 0])) == tok
                seq.append(tok)

    def test_admission_only_when_pages_free(self, model):
        """Pool of 3 allocatable pages, requests needing 2 each: at
        most one resident at a time, queued requests wait."""
        cfg, params = model
        sched = _sched(params, cfg, num_pages=4, page_size=4,
                       pages_per_seq=2, max_batch=3)
        rng = np.random.RandomState(1)
        for i in range(3):
            sched.submit(Request(rid=i,
                                 prompt=list(rng.randint(0, 61, size=4)),
                                 max_new_tokens=3))
        max_resident = 0
        for _ in range(100):
            if sched.idle():
                break
            sched.step()
            max_resident = max(max_resident, sched.num_active)
        assert sched.idle() and len(sched.completed) == 3
        assert max_resident == 1, (
            f"pages for one 2-page request were free, yet "
            f"{max_resident} sequences were resident")

    def test_fifo_order_pinned_no_starvation(self, model):
        """A page-hungry queue head must NOT be overtaken by small
        requests behind it (FIFO admission, starvation-free)."""
        cfg, params = model
        sched = _sched(params, cfg, num_pages=7, page_size=4,
                       pages_per_seq=6, max_batch=3)
        admitted_order = []
        orig = sched._admit_into

        def record(slot, req, *plan):
            admitted_order.append(req.rid)
            return orig(slot, req, *plan)

        sched._admit_into = record
        rng = np.random.RandomState(2)
        # rid 0 small (occupies pages), rid 1 HUGE (blocks), rid 2 small
        sched.submit(Request(0, list(rng.randint(0, 61, size=4)), 8))
        sched.step()  # rid 0 resident, holds 3 of 6 pages
        sched.submit(Request(1, list(rng.randint(0, 61, size=8)), 16))
        sched.submit(Request(2, list(rng.randint(0, 61, size=2)), 2))
        done = sched.run_until_drained()
        assert admitted_order == [0, 1, 2], (
            f"admission order {admitted_order} broke FIFO — a small "
            f"request overtook the blocked head")
        assert len(done) == 3

    def test_page_recycling_serves_more_than_pool(self, model):
        """Total page demand across the run exceeds the pool several
        times over; eviction must recycle pages back to admission."""
        cfg, params = model
        sched = _sched(params, cfg, num_pages=5, page_size=4,
                       pages_per_seq=2, max_batch=2)
        rng = np.random.RandomState(3)
        n = 8
        for i in range(n):
            sched.submit(Request(i, list(rng.randint(0, 61, size=3)), 4))
        done = sched.run_until_drained()
        assert len(done) == n
        total_pages = n * pages_needed(3 + 4, 4)
        assert total_pages > 4, "test must oversubscribe the pool"
        assert sched.allocator.free_pages == 4, "pages leaked"

    def test_deterministic_under_seeded_trace(self, model):
        """Same seeded arrival trace + temperature sampling: bitwise
        the same served tokens, twice."""
        cfg, params = model

        def run():
            sched = _sched(params, cfg, temperature=0.9, top_k=5, seed=11)
            rng = np.random.RandomState(5)
            for r in _requests(rng, 5, cfg.vocab_size):
                sched.submit(r)
            return [(c.rid, tuple(c.tokens))
                    for c in sched.run_until_drained()]

        assert run() == run()

    def test_eos_stops_generation_early(self, model):
        cfg, params = model
        sched = _sched(params, cfg)
        sched.submit(Request(0, [5, 9, 12], max_new_tokens=20, eos_id=None))
        done = sched.run_until_drained()
        toks = done[0].tokens
        # re-serve with eos = some generated token: generation must cut
        # at its FIRST occurrence (greedy is deterministic, so the
        # prefix is reproduced exactly)
        eos = toks[-1]
        cut = toks.index(eos) + 1
        sched2 = _sched(params, cfg)
        sched2.submit(Request(0, [5, 9, 12], max_new_tokens=20, eos_id=eos))
        done2 = sched2.run_until_drained()
        assert done2[0].tokens == toks[:cut]

    def test_submit_validation(self, model):
        cfg, params = model
        sched = _sched(params, cfg)
        with pytest.raises(ValueError, match="max_prompt_len"):
            sched.submit(Request(0, list(range(9)), 2))
        with pytest.raises(ValueError, match="pages_per_seq"):
            sched.submit(Request(1, [1, 2], 1000))
        with pytest.raises(ValueError, match="empty"):
            sched.submit(Request(2, [], 2))

    def test_chaos_decode_kernel_failure_degrades_once_keeps_serving(
            self, model):
        """An injected decode-attention launch failure (the Mosaic
        stand-in) trips the registry ONCE; the serve loop degrades to
        the XLA reference and produces the SAME tokens."""
        cfg, params = model

        def serve():
            sched = _sched(params, cfg, attn="interpret",
                           sample="interpret", temperature=0.8, top_k=6,
                           seed=4)
            rng = np.random.RandomState(6)
            for r in _requests(rng, 4, cfg.vocab_size):
                sched.submit(r)
            return [(c.rid, tuple(c.tokens))
                    for c in sched.run_until_drained()]

        get_registry().reset()
        try:
            baseline = serve()
            get_registry().reset()
            monkey = ChaosMonkey(ChaosPlan.make(
                kernel_failures={"decode_attention": 1}))
            with monkey.active():
                served = serve()
            status = get_registry().status()["decode_attention"]
            assert status["tripped"] and status["fallback_calls"] >= 1
            assert served == baseline, (
                "the degraded (XLA) serve produced different tokens")
            assert monkey.injected.get("kernel:decode_attention") == 1
        finally:
            get_registry().reset()

    def test_decode_step_compiles_once_across_occupancy(self, model):
        """The compile-once contract at the scheduler level: varying
        occupancy (1..3 active), cache lengths, admissions and
        evictions all reuse ONE compiled decode step (pinned through
        the generalized ``analysis.lowered.assert_no_recompile``
        guard-rail, post-hoc spelling)."""
        from apex_tpu.analysis import lowered as lw

        cfg, params = model
        sched = _sched(params, cfg)
        rng = np.random.RandomState(8)
        for r in _requests(rng, 7, cfg.vocab_size, plen=(2, 8),
                           max_new=(2, 8)):
            sched.submit(r)
        sched.run_until_drained()
        lw.assert_no_recompile(sched._decode, label="decode_step")
        assert sched.decode_cache_size() == 1

    def test_chaos_wedged_decode_step_fires_serving_watchdog(self, model):
        """The serving-side watchdog contract: one decode step stalls
        (chaos ``wedge_step_at`` keyed on the decode-step counter), the
        per-step heartbeat stops, and the watchdog fires WHILE the step
        is hung — the scheduler's on_wedge hook logs every queued and
        in-flight request id (the requeue manifest for the layer above)
        and records ``apex_serve_wedges_total`` — instead of the server
        hanging forever.  ``on_fire`` captures the firing in place of
        the real exit-75 (which ``serve_gpt.py --watchdog-secs`` takes
        and the supervisor restarts on)."""
        import time

        from apex_tpu.observability import MetricsScope
        from apex_tpu.resilience import StepWatchdog

        cfg, params = model
        fired = []
        wd = StepWatchdog(0.5, poll_sec=0.05, first_deadline_sec=120.0,
                          on_fire=fired.append)
        with MetricsScope() as reg:
            sched = _sched(params, cfg, watchdog=wd)
            rng = np.random.RandomState(9)
            # warmup WITHOUT the watchdog thread: compiles prefill +
            # decode so the armed phase's step times are real step
            # times, not jit compiles tripping a spurious fire
            sched.submit(Request(100, list(rng.randint(0, 61, size=3)), 2))
            sched.run_until_drained()
            wedge_at = sched.stats["decode_steps"] + 1
            monkey = ChaosMonkey(ChaosPlan.make(
                wedge_step_at=wedge_at, wedge_step_seconds=2.0))
            for r in _requests(rng, 4, cfg.vocab_size):
                sched.submit(r)
            with wd, monkey.active():
                t0 = time.monotonic()
                done = sched.run_until_drained()
                hung = time.monotonic() - t0
            assert len(done) == 5  # warmup + 4: the wedge cost time, not work
            assert hung >= 1.5, "the injected wedge did not hold the step"
            assert monkey.injected.get("wedge_step") == 1
            assert fired and fired[0]["exit_code"] == 75
            assert reg.counter("apex_serve_wedges_total").value() == 1
            # the wedge fired while requests were still queued/in
            # flight: the manifest hook had rids to report (admitted 5
            # total, only the warmup was complete before the wedge)
            assert sched.stats["evicted"] == 5
