"""Distributed tracing, flight recorder, and anomaly detection
(apex_tpu.observability v2, ISSUE 14).

The load-bearing bands:

- **Observer, never participant**: tracing on vs off produces
  BITWISE-identical loss/params on the real ``make_train_step``
  (replicated+clip, ZeRO+clip, hierarchical int8 sync) — the
  :class:`~apex_tpu.observability.tracing.TracedStep` wrapper lives
  entirely outside jit (the lowering side of the same contract is
  pinned in tests/test_lowered_invariants.py::TestTracingTrainStep).
- **Forensics chaos matrix**: the dump triggers really fire — a
  watchdog wedge dumps a recording whose OPEN span is the wedged
  dispatch with the right ``(run_id, step)``, a StepGuard budget abort
  and a preemption notice each leave a reason-stamped dump, and
  torn/partial dump files are skipped LOUDLY on read.
- **Exporters**: the Chrome-trace export is Perfetto-loadable JSON
  (phase/ts/dur/args shape, thread_name metadata), the JSONL export
  carries the sidecar contract fields, and both carry the
  ``(run_id, step)`` correlation captured at span START.
- **Anomaly detection**: rolling median/MAD robust z-scores alarm on
  genuine spikes/drops in the watched direction only, stay quiet on a
  near-constant series and during cold start, vote stragglers
  cross-sectionally, and fan out to ``apex_anomaly_*`` counters with
  labels preserved.
"""

import json
import logging
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from apex_tpu.models.gpt import GPTConfig, init_params, make_train_step
from apex_tpu.observability import (
    anomaly as anomaly_mod,
    correlation,
    flightrec,
    metrics,
    tracing,
)
from apex_tpu.optimizers import FusedAdam

CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_seq_len=16,
                compute_dtype=jnp.float32, checkpoint_layers=False)


@pytest.fixture(autouse=True)
def _clean_observability_state():
    """Every test starts with no tracer, no recorder, no correlation
    context, and leaves none behind."""
    tracing.disable()
    flightrec.uninstall()
    correlation.clear_step_context()
    yield
    tracing.disable()
    flightrec.uninstall()
    correlation.clear_step_context()


def _data(batch):
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(batch, 16)))
    return tokens, jnp.roll(tokens, -1, axis=1)


def _mesh(devices8, dp):
    return Mesh(np.array(devices8[:dp]).reshape(dp, 1), ("dp", "tp"))


def _assert_bitwise(tree_a, tree_b):
    for a, b in zip(jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------- tracer core
class TestTracerCore:
    def test_span_records_name_duration_attrs_thread(self):
        tr = tracing.Tracer()
        with tr.span("train.data_wait", batch=3):
            pass
        (rec,) = tr.spans()
        assert rec["name"] == "train.data_wait"
        assert rec["ph"] == "X"
        assert rec["dur_us"] >= 0
        assert rec["attrs"]["batch"] == 3
        assert rec["tid"] == threading.current_thread().ident
        assert rec["thread"] == threading.current_thread().name

    def test_handle_spelling_and_mid_span_attrs(self):
        tr = tracing.Tracer()
        s = tr.span("serve.verify_step", draft_len=3)
        s.set(emitted=7)
        s.end(accepted=2)
        (rec,) = tr.spans()
        assert rec["attrs"] == {"draft_len": 3, "emitted": 7,
                                "accepted": 2}
        # double-end is a no-op, not a duplicate record
        s.end()
        assert len(tr.spans()) == 1

    def test_exception_exits_span_with_error_attr(self):
        tr = tracing.Tracer()
        with pytest.raises(RuntimeError):
            with tr.span("train.step.dispatch"):
                raise RuntimeError("wedged")
        (rec,) = tr.spans()
        assert rec["attrs"]["error"] == "RuntimeError"
        assert not tr.open_spans()

    def test_ring_bounds_memory_and_counts_drops(self):
        tr = tracing.Tracer(capacity=4)
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
        spans = tr.spans()
        assert len(spans) == 4
        assert [s["name"] for s in spans] == ["s6", "s7", "s8", "s9"]
        assert tr.dropped == 6
        assert tr.started == tr.finished == 10

    def test_open_span_tracked_with_elapsed(self):
        tr = tracing.Tracer()
        s = tr.span("train.step.dispatch", step=7)
        time.sleep(0.01)
        (rec,) = tr.open_spans()
        assert rec["open"] is True
        assert rec["name"] == "train.step.dispatch"
        assert rec["dur_us"] >= 10_000 * 0.5  # monotonic, scheduler slack
        assert not tr.spans()
        s.end()
        assert not tr.open_spans()
        assert len(tr.spans()) == 1

    def test_spans_record_their_thread(self):
        tr = tracing.Tracer()

        def work():
            with tr.span("watchdog.probe"):
                pass

        t = threading.Thread(target=work, name="apex-test-watchdog")
        t.start()
        t.join()
        (rec,) = tr.spans()
        assert rec["thread"] == "apex-test-watchdog"
        assert rec["tid"] != threading.current_thread().ident

    def test_instant_and_retro_emit(self):
        tr = tracing.Tracer()
        tr.instant("zero_sync.bucket0.hop_dp", payload_bytes=1024)
        t0 = time.time() - 0.5
        tr.emit("serve.admission_wait", t0, 0.25, rid=3)
        marker, emitted = tr.spans()
        assert marker["ph"] == "i" and marker["dur_us"] == 0
        assert emitted["ph"] == "X"
        assert emitted["ts"] == pytest.approx(t0)
        assert emitted["dur_us"] == 250_000

    def test_listener_feed_and_listener_errors_swallowed(self):
        tr = tracing.Tracer()
        seen = []
        tr.add_listener(seen.append)
        tr.add_listener(lambda rec: 1 / 0)  # broken observer
        with tr.span("a"):
            pass
        assert [r["name"] for r in seen] == ["a"]

    def test_capacity_validated(self):
        with pytest.raises(ValueError, match="capacity"):
            tracing.Tracer(capacity=0)


class TestModuleApi:
    def test_span_without_tracer_is_the_noop_singleton(self):
        a = tracing.span("x", attr=1)
        b = tracing.span("y")
        assert a is b  # no allocation on the disabled path
        with a:
            a.set(z=2)
        assert a.elapsed() == 0.0
        assert not tracing.enabled()

    def test_configure_routes_module_span(self):
        tr = tracing.configure(capacity=16)
        assert tracing.get_tracer() is tr
        with tracing.span("train.data_wait"):
            pass
        tracing.instant("marker")
        assert [s["name"] for s in tr.spans()] == ["train.data_wait",
                                                   "marker"]

    def test_scope_restores_previous_tracer(self):
        outer = tracing.configure()
        with tracing.TracingScope() as inner:
            assert tracing.get_tracer() is inner
            with tracing.span("inner_only"):
                pass
        assert tracing.get_tracer() is outer
        assert not outer.spans()
        assert [s["name"] for s in inner.spans()] == ["inner_only"]

    def test_trace_ids_are_process_unique(self):
        ids = {tracing.new_trace_id() for _ in range(64)}
        assert len(ids) == 64
        assert all(i.startswith(f"{os.getpid():x}-") for i in ids)

    def test_correlation_captured_at_span_start(self):
        tr = tracing.configure()
        correlation.set_step_context(run_id="r1", step=7)
        s = tracing.span("train.step.dispatch")
        correlation.set_step_context(step=8)  # the loop moved on
        s.end()
        (rec,) = tr.spans()
        assert rec["attrs"]["run_id"] == "r1"
        assert rec["attrs"]["step"] == 7


# -------------------------------------------------------------- exporters
class TestExporters:
    def _traced(self, tmp_path):
        tr = tracing.configure()
        correlation.set_step_context(run_id="exp", step=3)
        with tr.span("train.step.dispatch", dispatch=True):
            pass
        tr.span("train.data_wait")  # left OPEN deliberately
        return tr

    def test_chrome_export_is_perfetto_loadable(self, tmp_path):
        tr = self._traced(tmp_path)
        path = tmp_path / "trace.json"
        n = tr.export_chrome(path)
        doc = json.loads(path.read_text())
        assert doc["schema"] == "apex_tpu_trace_v1"
        events = doc["traceEvents"]
        assert len(events) == n
        by_name = {e["name"]: e for e in events}
        d = by_name["train.step.dispatch"]
        # the Chrome trace-event contract: phase X, µs timestamps,
        # pid/tid ints, attrs under args
        assert d["ph"] == "X" and d["dur"] >= 0
        assert isinstance(d["ts"], int) and d["ts"] > 1e15  # epoch µs
        assert d["args"]["run_id"] == "exp" and d["args"]["step"] == 3
        assert by_name["train.data_wait"]["args"]["open"] is True
        meta = [e for e in events if e["ph"] == "M"]
        assert meta and all(e["name"] == "thread_name" for e in meta)
        # atomic publish: no staging files left behind
        assert [p.name for p in tmp_path.iterdir()] == ["trace.json"]

    def test_jsonl_export_carries_sidecar_contract(self, tmp_path):
        tr = self._traced(tmp_path)
        path = tmp_path / "spans.jsonl"
        n = tr.export_jsonl(path)
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert len(lines) == n == 2
        done, open_ = lines
        assert done["span"] == "train.step.dispatch"
        assert done["run_id"] == "exp" and done["step"] == 3
        assert {"ts", "dur_us", "tid", "thread", "rank"} <= set(done)
        assert done["open"] is False and open_["open"] is True


# ----------------------------------------------------------- TracedStep
class TestTracedStep:
    def test_wraps_dispatch_in_a_span_only_when_tracing(self):
        calls = []

        def fn(x, y):
            calls.append((x, y))
            return x + y

        wrapped = tracing.TracedStep(fn, name="train.step.dispatch")
        assert wrapped(1, 2) == 3  # tracing off: plain delegation
        with tracing.TracingScope() as tr:
            assert wrapped(3, 4) == 7
        assert calls == [(1, 2), (3, 4)]
        (rec,) = tr.spans()
        assert rec["name"] == "train.step.dispatch"
        assert rec["attrs"]["dispatch"] is True

    def test_delegates_attributes_to_the_wrapped_callable(self):
        class FakeStep:
            def __call__(self, x):
                return x

            def lower(self, *a):
                return "lowering"

            def _cache_size(self):
                return 1

        w = tracing.TracedStep(FakeStep())
        assert w.lower() == "lowering"
        assert w._cache_size() == 1

    def test_emit_sync_plan_markers(self):
        class FakeOpt:
            def sync_plan_hops(self):
                return [
                    {"bucket": 0, "hop": "dp_in", "payload_bytes": 10},
                    {"bucket": 0, "hop": "dp_out", "payload_bytes": 5},
                    {"bucket": 1, "hop": "dp_in", "payload_bytes": 8},
                ]

        # tracing off
        assert tracing.emit_sync_plan(FakeOpt()) == \
            {"markers": 0, "overlap_fraction": 0.0}
        with tracing.TracingScope() as tr:
            out = tracing.emit_sync_plan(FakeOpt())
            assert out["markers"] == 3
            # markers emitted outside any dispatch span: no concurrency
            assert out["overlap_fraction"] == 0.0
            assert tracing.emit_sync_plan(object()) == \
                {"markers": 0, "overlap_fraction": 0.0}  # no plan
        names = [s["name"] for s in tr.spans()]
        assert names == ["zero_sync.bucket0.hop_dp_in",
                         "zero_sync.bucket0.hop_dp_out",
                         "zero_sync.bucket1.hop_dp_in"]
        assert tr.spans()[1]["attrs"]["payload_bytes"] == 5

    def test_overlap_fraction_counts_markers_inside_dispatch(self):
        class FakeOpt:
            def sync_plan_hops(self):
                return [{"bucket": 0, "hop": "dp"},
                        {"bucket": 1, "hop": "dp"}]

        assert tracing.overlap_fraction() == 0.0  # tracing off
        with tracing.TracingScope() as tr:
            # two markers inside a live dispatch span...
            wrapped = tracing.TracedStep(
                lambda: tracing.emit_sync_plan(FakeOpt()),
                name="train.step.dispatch")
            inside = wrapped()
            assert inside["markers"] == 2
            assert inside["overlap_fraction"] == 1.0
            # ...then two more outside any dispatch window
            out = tracing.emit_sync_plan(FakeOpt())
            assert out["markers"] == 2
            assert out["overlap_fraction"] == pytest.approx(0.5)
            assert tracing.overlap_fraction(tr) == pytest.approx(0.5)


# ------------------------------------------------------------ parity band
class TestTracingParity:
    """Tracing on (TracedStep under an active tracer) vs off: bitwise
    loss/params on the real train step.  The variants of the ISSUE 14
    acceptance: replicated+clip, ZeRO+clip, hierarchical int8."""

    def _run(self, make_step, n=3):
        params = init_params(CFG, jax.random.PRNGKey(0))
        step, state, (tokens, targets) = make_step(params)
        losses = []
        for _ in range(n):
            params, state, loss = step(params, state, tokens, targets)
            losses.append(float(loss))
        return params, state, losses

    def _pair(self, make_step):
        with tracing.TracingScope() as tr:
            traced = self._run(
                lambda p: self._with_traced_wrapper(make_step, p))
        plain = self._run(make_step)
        _assert_bitwise(traced[0], plain[0])
        _assert_bitwise(traced[1], plain[1])
        assert traced[2] == plain[2]
        dispatch = [s for s in tr.spans()
                    if s["name"] == "train.step.dispatch"]
        assert len(dispatch) == 3  # the spans really recorded
        return tr

    @staticmethod
    def _with_traced_wrapper(make_step, params):
        step, state, data = make_step(params)
        return tracing.TracedStep(step, name="train.step.dispatch"), \
            state, data

    def test_replicated_clip(self, devices8):
        def make(params):
            opt = FusedAdam(lr=1e-2)
            step = make_train_step(CFG, opt, _mesh(devices8, 2),
                                   clip_grad_norm=1.0)
            return step, opt.init(params), _data(2)

        self._pair(make)

    def test_zero_clip(self, devices8):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        def make(params):
            opt = DistributedFusedAdam(lr=1e-2, axis_name="dp")
            state = opt.init(params, world_size=2)
            step = make_train_step(CFG, opt, _mesh(devices8, 2),
                                   clip_grad_norm=1.0)
            return step, state, _data(2)

        self._pair(make)

    def test_hier_int8(self, devices8):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        mesh = Mesh(np.array(devices8[:4]).reshape(2, 2, 1),
                    ("dp_out", "dp_in", "tp"))

        def make(params):
            opt = DistributedFusedAdam(lr=1e-2,
                                       dp_axes=("dp_out", "dp_in"),
                                       grad_sync_dtype="int8")
            state = opt.init(params, world_size=4,
                             axis_sizes={"dp_out": 2, "dp_in": 2,
                                         "tp": 1})
            step = make_train_step(CFG, opt, mesh,
                                   dp_axis=("dp_out", "dp_in"))
            return step, state, _data(4)

        self._pair(make)


# -------------------------------------------------------- flight recorder
class TestFlightRecorder:
    def test_rings_are_bounded(self):
        rec = flightrec.FlightRecorder(capacity=3, events_capacity=2,
                                       stats_capacity=2)
        for i in range(6):
            rec.record_span({"name": f"s{i}", "ph": "X", "dur_us": 1})
            rec.record_event(f"e{i}", {"i": i})
            rec.record_stats(i, {"loss_mean": float(i)})
        snap = rec.snapshot()
        assert [s["name"] for s in snap["spans"]] == ["s3", "s4", "s5"]
        assert [e["event"] for e in snap["events"]] == ["e4", "e5"]
        assert [s["step"] for s in snap["stats_windows"]] == [4, 5]

    def test_dump_and_load_roundtrip(self, tmp_path):
        correlation.set_step_context(run_id="fr", step=9)
        rec = flightrec.FlightRecorder(tmp_path, run_id="fr")
        rec.record_span({"name": "train.step.dispatch", "ph": "X",
                         "dur_us": 5})
        path = rec.dump("wedge", wedged_step=9)
        assert path is not None and rec.dumped == [path]
        loaded = flightrec.load_dump(path)
        assert loaded["reason"] == "wedge"
        assert loaded["wedged_step"] == 9
        assert loaded["run_id"] == "fr" and loaded["step"] == 9
        assert loaded["spans"][0]["name"] == "train.step.dispatch"

    def test_dump_includes_tracers_open_span(self, tmp_path):
        """The wedged dispatch never finishes — the dump must name it
        anyway (the forensics headline)."""
        tr = tracing.configure()
        rec = flightrec.FlightRecorder(tmp_path).attach(tr)
        correlation.set_step_context(run_id="w", step=4)
        wedged = tracing.span("train.step.dispatch", dispatch=True)
        path = rec.dump("wedge", wedged_step=4)
        loaded = flightrec.load_dump(path)
        (open_span,) = loaded["open_spans"]
        assert open_span["name"] == "train.step.dispatch"
        assert open_span["open"] is True
        assert open_span["attrs"]["step"] == 4
        wedged.end()

    def test_attach_feeds_finished_spans(self):
        tr = tracing.configure()
        rec = flightrec.FlightRecorder().attach(tr)
        with tracing.span("serve.decode_step"):
            pass
        assert [s["name"] for s in rec.snapshot()["spans"]] \
            == ["serve.decode_step"]

    def test_checkpoint_republishes_atomically(self, tmp_path):
        rec = flightrec.FlightRecorder(tmp_path)
        rec.record_event("a", {})
        p1 = rec.checkpoint()
        rec.record_event("b", {})
        p2 = rec.checkpoint()
        assert p1 == p2  # one rolling file, republished
        events = [e["event"]
                  for e in flightrec.load_dump(p1)["events"]]
        assert events == ["a", "b"]
        assert flightrec.FlightRecorder().checkpoint() is None

    def test_log_structured_feeds_installed_recorder(self):
        from apex_tpu.utils.logging import get_logger, log_structured

        rec = flightrec.install(flightrec.FlightRecorder())
        correlation.set_step_context(run_id="lg", step=2)
        log_structured(get_logger("apex_tpu.test"), logging.INFO,
                       "checkpoint.saved", step_dir="/x/step_2")
        (ev,) = rec.snapshot()["events"]
        assert ev["event"] == "checkpoint.saved"
        assert ev["step_dir"] == "/x/step_2"
        assert ev["run_id"] == "lg" and ev["step"] == 2
        flightrec.uninstall()
        log_structured(get_logger("apex_tpu.test"), logging.INFO,
                       "after.uninstall")
        assert len(rec.snapshot()["events"]) == 1

    def test_dump_active_is_a_noop_without_a_recorder(self):
        assert flightrec.dump_active("wedge") is None

    def test_dump_never_raises(self, tmp_path, monkeypatch):
        rec = flightrec.FlightRecorder(tmp_path)
        monkeypatch.setattr(rec, "snapshot",
                            lambda *a, **k: 1 / 0)
        assert rec.dump("wedge") is None  # reported, not raised


class TestDumpReadSide:
    def _good_dump(self, tmp_path, **extra):
        rec = flightrec.FlightRecorder(tmp_path)
        return rec.dump("wedge", **extra)

    def test_load_dump_rejects_torn_bytes(self, tmp_path):
        p = tmp_path / "flightrec_dump_1_1.json"
        p.write_text('{"schema": "apex_tpu_flightrec_v1", "spans": [')
        with pytest.raises(ValueError, match="torn/partial"):
            flightrec.load_dump(p)

    def test_load_dump_rejects_wrong_schema(self, tmp_path):
        p = tmp_path / "flightrec_dump_1_1.json"
        p.write_text('{"schema": "something_else"}')
        with pytest.raises(ValueError, match="schema"):
            flightrec.load_dump(p)

    def test_latest_dump_skips_torn_files_loudly(self, tmp_path):
        good = self._good_dump(tmp_path, wedged_step=5)
        torn = tmp_path / "flightrec_dump_9999999999999_1.json"
        torn.write_text('{"schema": "apex_tpu_flightrec_v1", "ev')
        os.utime(torn, (time.time() + 60, time.time() + 60))  # newest

        from apex_tpu.utils.logging import get_logger

        records = []
        handler = logging.Handler()
        handler.emit = records.append
        logger = get_logger("apex_tpu.observability")
        logger.addHandler(handler)
        try:
            path, rec = flightrec.latest_dump(tmp_path)
        finally:
            logger.removeHandler(handler)
        assert path == good and rec["wedged_step"] == 5
        loud = [r.getMessage() for r in records
                if "torn_dump_skipped" in r.getMessage()]
        assert loud and torn.name in loud[0]  # loud, and names the file

    def test_latest_dump_none_cases(self, tmp_path):
        assert flightrec.latest_dump(tmp_path) is None
        assert flightrec.latest_dump_path(tmp_path / "missing") is None
        assert flightrec.latest_dump_path(None) is None

    def test_latest_dump_path_finds_newest(self, tmp_path):
        clock = iter(np.arange(1.0, 10.0, 0.5))
        rec = flightrec.FlightRecorder(tmp_path,
                                       time_fn=lambda: float(next(clock)))
        first = rec.dump("wedge")
        second = rec.dump("preemption")
        os.utime(first, (1, 1))
        os.utime(second, (2, 2))
        assert flightrec.latest_dump_path(tmp_path) == second


# ----------------------------------------------------------- dump triggers
class TestDumpTriggers:
    """The chaos matrix: every library exit path leaves a dump."""

    def test_step_guard_abort_dumps_before_the_raise(self, tmp_path):
        from apex_tpu.resilience import BadStepBudgetExceeded, StepGuard
        from apex_tpu.resilience.step_guard import GuardState

        flightrec.install(flightrec.FlightRecorder(tmp_path))
        guard = StepGuard(max_consecutive_bad=2)
        bad = GuardState(step=jnp.int32(10), consecutive_bad=jnp.int32(2),
                         total_skipped=jnp.int32(3))
        with pytest.raises(BadStepBudgetExceeded):
            guard.check(bad)
        path, rec = flightrec.latest_dump(tmp_path)
        assert rec["reason"] == "step_guard_abort"
        assert rec["consecutive_bad"] == 2
        assert rec["guard_step"] == 10

    def test_preemption_notice_dumps(self, tmp_path):
        from apex_tpu.resilience import PreemptionHandler

        flightrec.install(flightrec.FlightRecorder(tmp_path))
        h = PreemptionHandler(signals=())
        h.simulate("chaos preemption")
        _, rec = flightrec.latest_dump(tmp_path)
        assert rec["reason"] == "preemption"
        assert rec["preempt_reason"] == "chaos preemption"
        # the notice dumps ONCE (the flag is latched)
        h.simulate("again")
        assert len([p for p in os.listdir(tmp_path)
                    if p.startswith("flightrec_dump_")]) == 1

    def test_watchdog_wedge_dumps_with_the_wedged_step(self, tmp_path):
        """rc-75 forensics in-process: the watchdog fire path (via the
        on_fire test seam, which replaces only the final os._exit)
        dumps a recording whose OPEN span is the wedged dispatch with
        the right (run_id, step)."""
        from apex_tpu.resilience import StepWatchdog

        tr = tracing.configure()
        flightrec.install(
            flightrec.FlightRecorder(tmp_path, run_id="wdg").attach(tr))
        correlation.set_step_context(run_id="wdg", step=6)
        fired = []
        wedged = tracing.span("train.step.dispatch", dispatch=True)
        with StepWatchdog(0.15, poll_sec=0.02,
                          on_fire=fired.append) as wd:
            wd.beat(6)
            deadline = time.monotonic() + 5.0
            while not fired and time.monotonic() < deadline:
                time.sleep(0.02)
        wedged.end()
        assert fired, "watchdog never fired"
        info = fired[0]
        assert info["step"] == 6
        assert info["flight_dump"] is not None
        rec = flightrec.load_dump(info["flight_dump"])
        assert rec["reason"] == "wedge"
        assert rec["wedged_step"] == 6
        assert rec["run_id"] == "wdg" and rec["step"] == 6
        (open_span,) = rec["open_spans"]
        assert open_span["name"] == "train.step.dispatch"
        assert open_span["attrs"]["step"] == 6


# ---------------------------------------------------------------- anomaly
class TestRobustZscore:
    def test_median_mad_math(self):
        z, med, mad = anomaly_mod.robust_zscore(
            10.0, [1.0, 2.0, 3.0, 4.0, 100.0], min_rel_spread=0.0)
        assert med == 3.0 and mad == 1.0
        assert z == pytest.approx((10.0 - 3.0)
                                  / (anomaly_mod.MAD_TO_SIGMA * 1.0))

    def test_rel_spread_floor_quiets_constant_series(self):
        # microsecond jitter on a ~1.0s series: the floor dominates
        z, _, _ = anomaly_mod.robust_zscore(
            1.000004, [1.000001, 1.000002, 1.000001, 1.000003])
        assert abs(z) < 1.0


class TestRollingMadDetector:
    def test_spike_alarms_high_direction(self):
        det = anomaly_mod.RollingMadDetector(window=32, threshold=4.0,
                                             min_points=8)
        rng = np.random.RandomState(0)
        for v in 1.0 + 0.01 * rng.randn(20):
            assert det.update(v) is None
        hit = det.update(3.0)
        assert hit is not None and hit["zscore"] > 4.0
        assert det.alerts == 1

    def test_cold_start_is_quiet(self):
        det = anomaly_mod.RollingMadDetector(min_points=16)
        for _ in range(15):
            assert det.update(1.0) is None
        assert det.update(100.0) is None  # still < min_points history

    def test_direction_low_alarms_on_drops_only(self):
        det = anomaly_mod.RollingMadDetector(window=32, min_points=8,
                                             direction="low")
        rng = np.random.RandomState(1)
        for v in 100.0 + rng.randn(20):
            det.update(v)
        assert det.update(300.0) is None   # spike: not watched
        assert det.update(10.0) is not None  # drop: alarm

    def test_outlier_does_not_mask_itself(self):
        """The candidate is scored against the window EXCLUDING it."""
        det = anomaly_mod.RollingMadDetector(window=8, min_points=4,
                                             threshold=4.0)
        for v in (1.0, 1.01, 0.99, 1.02, 1.0):
            det.update(v)
        assert det.update(50.0) is not None

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="window"):
            anomaly_mod.RollingMadDetector(window=1)
        with pytest.raises(ValueError, match="direction"):
            anomaly_mod.RollingMadDetector(direction="sideways")
        with pytest.raises(ValueError, match="min_points"):
            anomaly_mod.RollingMadDetector(min_points=1)


class TestAnomalyMonitor:
    def _ramp(self, mon, kind, n=24, base=1.0, **labels):
        rng = np.random.RandomState(7)
        for v in base + 0.01 * base * rng.randn(n):
            mon.observe(kind, v, **labels)

    def test_detection_increments_counter_with_labels(self):
        with metrics.MetricsScope() as reg:
            mon = anomaly_mod.AnomalyMonitor(min_points=8)
            self._ramp(mon, "ttft", lane="interactive")
            alert = mon.observe("ttft", 30.0, lane="interactive")
            assert alert is not None and alert["lane"] == "interactive"
            c = reg.counter("apex_anomaly_ttft_total",
                            labelnames=("lane",))
            assert c.value(lane="interactive") == 1.0

    def test_series_keyed_per_label_set(self):
        """A best-effort-lane regression must not poison the
        interactive lane's window (and vice versa)."""
        mon = anomaly_mod.AnomalyMonitor(min_points=8)
        self._ramp(mon, "ttft", base=1.0, lane="interactive")
        self._ramp(mon, "ttft", base=60.0, lane="best_effort")
        # 50s is normal for best_effort, anomalous for interactive
        assert mon.observe("ttft", 50.0, lane="best_effort") is None
        assert mon.observe("ttft", 50.0, lane="interactive") is not None

    def test_goodput_kind_watches_drops(self):
        mon = anomaly_mod.AnomalyMonitor(min_points=8)
        self._ramp(mon, "tokens_per_sec", base=1000.0)
        assert mon.observe("tokens_per_sec", 1500.0) is None
        assert mon.observe("tokens_per_sec", 100.0) is not None

    def test_wedge_is_an_unconditional_alert(self):
        with metrics.MetricsScope() as reg:
            mon = anomaly_mod.AnomalyMonitor()
            rec = mon.wedge(300.0, step=17)
            assert rec["wedge"] is True and rec["step"] == 17
            assert reg.counter("apex_anomaly_step_time_total").value() \
                == 1.0
        assert mon.counts() == {"step_time": 1}

    def test_straggler_vote(self):
        mon = anomaly_mod.AnomalyMonitor(threshold=4.0)
        per_rank = {0: 1.0, 1: 1.01, 2: 0.99, 3: 1.02, 4: 5.0}
        alerts = mon.check_stragglers(per_rank)
        assert [a["rank"] for a in alerts] == ["4"]
        assert alerts[0]["series"] == "rank_step_time"
        # two ranks: no majority to deviate from
        assert mon.check_stragglers({0: 1.0, 1: 9.0}) == []

    def test_span_listener_routes_durations(self):
        mon = anomaly_mod.AnomalyMonitor(min_points=8)
        tr = tracing.Tracer()
        tr.add_listener(mon.span_listener({
            "serve.decode_step": "inter_token",
            "zero_sync.*": "hop_sync_time",
        }))
        for _ in range(12):
            tr.emit("serve.decode_step", time.time(), 0.01)
            tr.emit("zero_sync.bucket0.hop_dp", time.time(), 0.02)
        tr.emit("serve.decode_step", time.time(), 5.0)     # spike
        tr.emit("zero_sync.bucket0.hop_dp", time.time(), 9.0)
        tr.emit("unmapped.span", time.time(), 99.0)        # ignored
        counts = mon.counts()
        assert counts == {"inter_token": 1, "hop_sync_time": 1}
        (hop_alert,) = [a for a in mon.alerts
                        if a["kind"] == "hop_sync_time"]
        assert hop_alert["span"] == "zero_sync.bucket0.hop_dp"

    def test_mixed_label_shapes_still_count_in_the_registry(self):
        """A kind fed alerts with two label shapes must not lose the
        second shape's counter increments: the registry pins labelnames
        at first use and the best-effort helper swallows the clash, so
        _alert conforms later shapes to the first-seen schema (and the
        span_listener feed emits ONE stable shape to begin with)."""
        with metrics.MetricsScope() as reg:
            mon = anomaly_mod.AnomalyMonitor(min_points=8)
            tr = tracing.Tracer()
            tr.add_listener(mon.span_listener({"serve.*": "inter_token"}))
            for _ in range(12):  # laneless spans build the baseline
                tr.emit("serve.decode_step", time.time(), 0.01)
                tr.emit("serve.prefill", time.time(), 0.01,
                        lane="interactive")
            tr.emit("serve.decode_step", time.time(), 5.0)   # laneless
            tr.emit("serve.prefill", time.time(), 9.0,       # laned
                    lane="interactive")
            assert mon.counts() == {"inter_token": 2}
            ctr = reg.counter("apex_anomaly_inter_token_total",
                              labelnames=("lane", "span"))
            total = sum(v for _, _, v in ctr.samples())
            assert total == 2  # neither increment swallowed
            # direct misuse conforms too instead of losing the count
            mon._alert("custom", {"a": "1"}, {"value": 1.0})
            mon._alert("custom", {"b": "2"}, {"value": 1.0})
            c2 = reg.counter("apex_anomaly_custom_total",
                             labelnames=("a",))
            assert sum(v for _, _, v in c2.samples()) == 2

    def test_alert_lands_in_flight_recorder(self):
        rec = flightrec.install(flightrec.FlightRecorder())
        mon = anomaly_mod.AnomalyMonitor(min_points=8)
        self._ramp(mon, "step_time")
        mon.observe("step_time", 50.0)
        events = [e for e in rec.snapshot()["events"]
                  if e["event"] == "anomaly.detected"]
        assert len(events) == 1 and events[0]["kind"] == "step_time"

    def test_counts_by_lane(self):
        mon = anomaly_mod.AnomalyMonitor(min_points=8)
        self._ramp(mon, "ttft", lane="interactive")
        mon.observe("ttft", 40.0, lane="interactive")
        assert mon.counts_by("lane") == {"ttft": {"interactive": 1}}


class TestAnomalyPersistence:
    def _persisted(self, tmp_path):
        mon = anomaly_mod.AnomalyMonitor(min_points=8)
        rng = np.random.RandomState(3)
        for v in 1.0 + 0.01 * rng.randn(16):
            mon.observe("step_time", v)
        mon.observe("step_time", 99.0)
        return mon.persist(tmp_path)

    def test_persist_and_recent_alert_count(self, tmp_path):
        path = self._persisted(tmp_path)
        doc = json.loads(open(path).read())
        assert doc["schema"] == "apex_tpu_anomaly_v1"
        assert doc["counts"] == {"step_time": 1}
        assert anomaly_mod.recent_alert_count(tmp_path) == 1
        assert anomaly_mod.recent_alert_count(None) == 0
        assert anomaly_mod.recent_alert_count(tmp_path / "missing") == 0

    def test_recent_alert_count_age_gate_and_torn_files(self, tmp_path):
        self._persisted(tmp_path)
        (tmp_path / "anomaly_torn.json").write_text('{"schema": "apex')
        assert anomaly_mod.recent_alert_count(tmp_path) == 1
        assert anomaly_mod.recent_alert_count(
            tmp_path, max_age_sec=10.0,
            now=time.time() + 3600.0) == 0


# ------------------------------------------------- supervisor consumption
class TestSupervisorForensics:
    """The supervisor attaches the newest dump to restart/quarantine
    records and lengthens backoff on fresh anomaly alerts."""

    class _MaxJitter:
        def uniform(self, a, b):
            return b

    class _FakeChild:
        def __init__(self, rc):
            self.rc = rc

        def wait(self, timeout=None):
            return self.rc

        def terminate(self):
            pass

        def kill(self):
            pass

    def _supervisor(self, tmp_path, rcs, **kw):
        from apex_tpu.resilience.supervisor import Supervisor

        it = iter(rcs)
        return Supervisor(
            ["prog"], max_restarts=8, metrics_dir=str(tmp_path),
            spawn_fn=lambda argv: self._FakeChild(next(it)),
            sleep_fn=lambda s: None, time_fn=lambda: 0.0,
            rng=self._MaxJitter(), backoff_base=1.0, backoff_cap=64.0,
            progress_fn=lambda: 0, **kw)

    def test_restart_record_attaches_dump_path(self, tmp_path):
        dump = flightrec.FlightRecorder(
            os.path.join(tmp_path, "flightrec")).dump(
                "wedge", wedged_step=3)
        sup = self._supervisor(tmp_path, [75, 0])
        assert sup.run() == 0
        assert sup.flight_dumps == [dump]

    def test_restart_record_none_without_dumps(self, tmp_path):
        sup = self._supervisor(tmp_path, [137, 0])
        assert sup.run() == 0
        assert sup.flight_dumps == [None]

    def test_anomaly_alerts_lengthen_backoff_once_per_batch(self,
                                                           tmp_path):
        """FRESH alerts (appearing after run start) double the next
        backoff exactly once; the second failure with no new alerts
        backs off normally."""
        counts = iter([0, 2, 2])  # baseline read, then per-failure
        plain = self._supervisor(tmp_path, [75, 75, 0],
                                 anomaly_fn=lambda: 0)
        assert plain.run() == 0
        loud = self._supervisor(tmp_path, [75, 75, 0],
                                anomaly_fn=lambda: next(counts))
        assert loud.run() == 0
        assert loud.backoffs[0] == pytest.approx(2 * plain.backoffs[0])
        assert loud.backoffs[1] == pytest.approx(plain.backoffs[1])

    def test_anomaly_watermark_tracks_aged_out_summaries_down(
            self, tmp_path):
        """`recent_alert_count` DROPS as summary files age out of its
        window; the watermark must follow it down, or a high-alert
        attempt more than an hour ago would silently eat the next batch
        of fresh alerts (the healthy-for-an-hour server case)."""
        counts = iter([0, 5, 0, 3])  # baseline; ramp; aged out; fresh
        sup = self._supervisor(tmp_path, [75, 75, 75, 0],
                               crash_loop_threshold=8,
                               anomaly_fn=lambda: next(counts))
        assert sup.run() == 0
        plain = self._supervisor(tmp_path, [75, 75, 75, 0],
                                 crash_loop_threshold=8,
                                 anomaly_fn=lambda: 0)
        assert plain.run() == 0
        assert sup.backoffs[0] == pytest.approx(2 * plain.backoffs[0])
        assert sup.backoffs[1] == pytest.approx(plain.backoffs[1])
        # 3 fresh alerts AFTER the old summary aged out (count fell
        # 5 -> 0 -> 3): still "new regressions", still lengthened
        assert sup.backoffs[2] == pytest.approx(2 * plain.backoffs[2])

    def test_stale_anomaly_summaries_do_not_lengthen(self, tmp_path):
        """Summaries a PREVIOUS run left under the same metrics dir are
        the baseline, not fresh evidence: a new supervisor's first
        backoff stays plain."""
        mon = anomaly_mod.AnomalyMonitor(min_points=8)
        rng = np.random.RandomState(5)
        for v in 1.0 + 0.01 * rng.randn(16):
            mon.observe("step_time", v)
        mon.observe("step_time", 77.0)
        mon.persist(tmp_path)  # run A's leftovers
        plain = self._supervisor(tmp_path, [75, 0],
                                 anomaly_fn=lambda: 0)
        assert plain.run() == 0
        stale = self._supervisor(tmp_path, [75, 0])  # default reader
        assert stale.run() == 0
        assert stale.backoffs == plain.backoffs


# ------------------------------------------------ scheduler trace joining
class TestServeTraceJoin:
    """The ISSUE 14 scheduler fix: a TTFT histogram outlier joins to
    its request's spans through the shared trace_id exemplar."""

    def _completions(self, tr):
        from apex_tpu.inference import (
            ContinuousBatchingScheduler, DecodeConfig, KVCacheConfig,
            Request,
        )

        cfg = GPTConfig(vocab_size=61, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_seq_len=128,
                        position_embedding_type="rope",
                        compute_dtype=jnp.float32,
                        checkpoint_layers=False)
        params = init_params(cfg, jax.random.PRNGKey(0))
        dcfg = DecodeConfig(
            cache=KVCacheConfig(num_pages=40, page_size=4,
                                pages_per_seq=16, dtype=jnp.float32),
            max_batch=2, max_prompt_len=16, temperature=0.0,
            attn_impl="xla", sample_impl="xla",
            sample_dot_dtype=jnp.float32)
        sched = ContinuousBatchingScheduler(params, cfg, dcfg)
        rng = np.random.RandomState(0)
        for rid in range(2):
            sched.submit(Request(
                rid=rid, prompt=rng.randint(0, 61, size=6).tolist(),
                max_new_tokens=3))
        return sched.run_until_drained()

    def test_trace_id_joins_exemplar_to_spans(self):
        with metrics.MetricsScope() as reg, \
                tracing.TracingScope() as tr:
            completions = self._completions(tr)
        assert len(completions) == 2
        ids = {c.rid: c.trace_id for c in completions}
        assert all(ids.values()) and len(set(ids.values())) == 2
        # the histogram sample is no longer anonymous: its exemplar
        # carries the trace id...
        hist = reg.histogram("apex_serve_ttft_seconds",
                             labelnames=("lane",))
        exemplars = hist.drain_exemplars()
        assert {ex["trace_id"] for _, ex in exemplars} \
            == set(ids.values())
        # ...and the same id is on the request's spans
        by_id = {}
        for s in tr.spans():
            tid = s.get("attrs", {}).get("trace_id")
            if tid is not None:
                by_id.setdefault(tid, set()).add(s["name"])
        for tid in ids.values():
            assert {"serve.admission_wait", "serve.prefill",
                    "serve.request"} <= by_id[tid]
        # ...and the batch-level decode/verify spans name every
        # resident request, so the exemplar also joins to the EXACT
        # steps that served it, not just the whole-lifetime span
        decode = [s for s in tr.spans()
                  if s["name"] in ("serve.decode_step",
                                   "serve.verify_step")
                  and s["attrs"].get("active", 0) > 0]
        assert decode
        for s in decode:
            carried = s["attrs"].get("trace_ids")
            assert carried and len(carried) == s["attrs"]["active"]
            assert set(carried) <= set(ids.values())
        for tid in ids.values():  # every request decoded at least once
            assert any(tid in s["attrs"]["trace_ids"] for s in decode)

    def test_window_max_exemplar_survives_ring_eviction(self):
        """serve_gpt.py drains exemplars exactly once, at the end of
        the run: a mid-run p99 outlier must still be present after
        hundreds of ordinary samples, or the join the exemplar exists
        for is lost to recency eviction."""
        with metrics.MetricsScope() as reg:
            hist = reg.histogram("apex_serve_ttft_seconds",
                                 labelnames=("lane",))
            hist.observe(9.9, exemplar={"trace_id": "outlier"},
                         lane="interactive")
            for i in range(200):  # ordinary traffic after the spike
                hist.observe(0.01, exemplar={"trace_id": f"t{i}"},
                             lane="interactive")
            drained = hist.drain_exemplars()
            assert len(drained) == metrics.Histogram.MAX_EXEMPLARS
            by_id = {ex["trace_id"]: ex for _, ex in drained}
            assert by_id["outlier"]["value"] == 9.9
            # recency is otherwise preserved (the most recent samples)
            assert f"t199" in by_id and f"t198" in by_id

    def test_exemplars_ride_the_jsonl_snapshot_once(self, tmp_path):
        with metrics.MetricsScope() as reg:
            reg.histogram("apex_serve_ttft_seconds",
                          labelnames=("lane",)).observe(
                0.5, exemplar={"trace_id": "t-1", "rid": 7},
                lane="interactive")
            path = tmp_path / "metrics.jsonl"
            reg.snapshot_jsonl(path)
            reg.snapshot_jsonl(path)  # drained: not re-emitted
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        ex = [l for l in lines if l["type"] == "exemplar"]
        assert len(ex) == 1
        assert ex[0]["metric"] == "apex_serve_ttft_seconds_exemplar"
        assert ex[0]["trace_id"] == "t-1" and ex[0]["rid"] == 7
        assert ex[0]["labels"] == {"lane": "interactive"}
